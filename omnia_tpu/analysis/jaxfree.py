"""Jax-free-by-contract package checker (rule ``jaxfree``).

Some packages are jax-free BY CONTRACT: ``engine/grammar`` must be
importable with grammar=off allocating zero device arrays, which is
only provable if nothing in the package can ever touch jax (PR 3;
``tests/test_grammar.py`` asserts the import-time half in a
subprocess). This rule is the source-level half, absorbed from
``tests/test_guards.py``: no ``import jax`` / ``from jax ...`` at ANY
position (module top, function body, conditional) in a contracted
package. AST-based, so an import hidden inside a function no longer
slips past the old line-regex.
"""

from __future__ import annotations

import ast

from omnia_tpu.analysis.core import Finding, SourceFile

#: Repo-relative path prefixes (packages or single modules) that must
#: never import jax.
JAX_FREE_PACKAGES: tuple[str, ...] = (
    "omnia_tpu/engine/grammar/",
    "omnia_tpu/analysis/",
    # Cold-start tracker + warmup manifest: jax-free by contract so the
    # mock parity layer and the CI poisoned-jax subset can run it.
    "omnia_tpu/engine/coldstart.py",
    # Traffic simulator: the generator/report path and the mock-fleet
    # CLI must run in jax-less containers (the duplex driver's runtime
    # import is lazy and degrades to a recorded skip).
    "omnia_tpu/evals/trafficsim/",
    # Fleet scaler: queue-depth → replica-count decisions are host-side
    # arithmetic by contract — the operator's pod path runs it in
    # jax-less controller processes, and the CI poisoned-jax subset
    # proves the whole control loop without a device stack.
    "omnia_tpu/engine/fleet.py",
    # Role policy + handoff orchestration are host-side by contract:
    # the DisaggRouter must run in jax-less controller processes and
    # the CI poisoned-jax subset proves the routing/handoff plane
    # without a device stack.
    "omnia_tpu/engine/disagg.py",
    # Device-resident decode loop host half: the chunk drainer, ring
    # self-gate, and deadline-step state are host-side by contract —
    # the CI poisoned-jax subset proves the drain/gate plane without a
    # device stack (the readback's numpy import is lazy for the same
    # reason).
    "omnia_tpu/engine/devloop.py",
)


def jaxfree_files(all_files: list[str]) -> list[str]:
    return [
        f for f in all_files
        if any(f.startswith(p) for p in JAX_FREE_PACKAGES)
    ]


def check_jaxfree(sources: dict[str, SourceFile]) -> list[Finding]:
    findings: list[Finding] = []
    for src in sources.values():
        if not any(src.rel.startswith(p) for p in JAX_FREE_PACKAGES):
            continue
        if src.tree is None:
            continue
        for node in ast.walk(src.tree):
            bad = None
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name == "jax" or alias.name.startswith("jax."):
                        bad = alias.name
            elif isinstance(node, ast.ImportFrom):
                mod = node.module or ""
                if node.level == 0 and (mod == "jax" or mod.startswith("jax.")):
                    bad = mod
            if bad is not None:
                findings.append(Finding(
                    "jaxfree", src.rel, node.lineno,
                    f"imports {bad!r} inside a jax-free-by-contract "
                    f"package — the package must stay importable with "
                    f"zero device-array allocation",
                ))
    return findings
