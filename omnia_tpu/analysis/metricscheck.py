"""Metrics-conformance checker (rule ``metrics``).

The metrics key names are a stable interface — the dashboard, doctor,
and operators' alerts read them — so every key WRITTEN anywhere in the
engine package must be registered in the stability registries
(``tests/test_prefix_cache.py`` ``TestMetricsKeyStability``) and
documented in the ``docs/serving.md`` metrics tables:

- engine-family files (``engine.py`` + mixins)  → ``EXPECTED``
- ``mock.py``            → ``EXPECTED`` ∪ ``MOCK_ONLY`` (the mock
  mirrors engine keys; its private keys get their own registry)
- ``coordinator.py``     → ``COORDINATOR``

Write sites recognized (all by AST): ``self.metrics["k"] op ...``,
``self.metrics.get("k", ...)``, and the coordinator's
``self._count("k")``/``self._count("k", n)`` helper — plus the keys of
the ``self.metrics = {...}`` dict literal itself.

A key written but unregistered, a key written but undocumented, or a
registry row no code writes anymore each produce a finding. This is the
machine check behind the PR rule "every new metric rides with its
EXPECTED row and its docs row".
"""

from __future__ import annotations

import ast
import os
from typing import Optional

from omnia_tpu.analysis.core import Finding, SourceFile

REGISTRY_FILE = "tests/test_prefix_cache.py"
DOCS_FILE = "docs/serving.md"

#: File → registry set(s) its metric keys must belong to.
ENGINE_FAMILY = (
    "omnia_tpu/engine/engine.py",
    "omnia_tpu/engine/scheduler.py",
    "omnia_tpu/engine/lifecycle.py",
    "omnia_tpu/engine/interleave.py",
    "omnia_tpu/engine/placement.py",
    "omnia_tpu/engine/sessions.py",
    "omnia_tpu/engine/prefix_cache.py",
    "omnia_tpu/engine/spec_decode.py",
    "omnia_tpu/engine/paged.py",
    "omnia_tpu/engine/warmup.py",
    "omnia_tpu/engine/multihost.py",
)
#: Mock-engine family: mock.py plus its session-migration mixin — a
#: mixin method's ``self`` IS the MockEngine, so its metric writes are
#: mock writes and must name registered mock keys.
MOCK_FILES = (
    "omnia_tpu/engine/mock.py",
    "omnia_tpu/engine/mock_sessions.py",
    "omnia_tpu/engine/mock_mirrors.py",
)
#: Coordinator family: coordinator.py plus the membership/relay splits.
#: membership.py holds the actual increment sites for the fleet ledger
#: (`fleet_workers`/`scale_events`/`sessions_migrated`/
#: `migration_fallbacks`); relay.py books through its owner today but
#: any direct ``self.metrics`` write it ever grows must be registered.
COORDINATOR_FILES = (
    "omnia_tpu/engine/coordinator.py",
    "omnia_tpu/engine/membership.py",
    "omnia_tpu/engine/relay.py",
    # Disaggregated-serving split: handoff books through its coord
    # argument today, but any direct ``self.metrics`` write it ever
    # grows must be registered.
    "omnia_tpu/engine/disagg.py",
)
#: Traffic-simulator files: the simulator reports through its own JSON
#: report schema, not `self.metrics` — any `self.metrics` write that
#: ever appears here must name a registered engine key (it would be
#: mirroring the engine ledger) or it is a finding.
TRAFFICSIM_FILES = (
    "omnia_tpu/evals/trafficsim/simulator.py",
    "omnia_tpu/evals/trafficsim/report.py",
    "omnia_tpu/evals/trafficsim/generator.py",
    "omnia_tpu/evals/trafficsim/arrivals.py",
    "omnia_tpu/evals/trafficsim/scenarios.py",
)
#: Fleet scaler: reports through ScaleEvent/stats() dicts, not
#: `self.metrics` — any `self.metrics` write that ever appears here
#: must name a registered coordinator key (it would be mirroring the
#: fleet ledger) or it is a finding.
FLEET_FILE = "omnia_tpu/engine/fleet.py"


def metric_keys_in(src: SourceFile) -> list[tuple[str, int]]:
    """(key, line) for every metrics-key write site in a module."""
    out: list[tuple[str, int]] = []
    if src.tree is None:
        return out

    def is_self_metrics(node: ast.expr) -> bool:
        return (
            isinstance(node, ast.Attribute)
            and node.attr == "metrics"
            and isinstance(node.value, ast.Name)
            and node.value.id == "self"
        )

    for node in ast.walk(src.tree):
        if isinstance(node, ast.Subscript) and is_self_metrics(node.value):
            if isinstance(node.slice, ast.Constant) and isinstance(
                node.slice.value, str
            ):
                out.append((node.slice.value, node.lineno))
        elif isinstance(node, ast.Call):
            func = node.func
            if (
                isinstance(func, ast.Attribute)
                and func.attr in ("get", "setdefault")
                and is_self_metrics(func.value)
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
            ):
                out.append((node.args[0].value, node.lineno))
            elif (
                isinstance(func, ast.Attribute)
                and func.attr == "_count"
                and isinstance(func.value, ast.Name)
                and func.value.id == "self"
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)
            ):
                out.append((node.args[0].value, node.lineno))
        elif isinstance(node, ast.Assign) and isinstance(node.value, ast.Dict):
            if any(
                is_self_metrics(t) for t in node.targets
            ):
                for k in node.value.keys:
                    if isinstance(k, ast.Constant) and isinstance(k.value, str):
                        out.append((k.value, k.lineno))
    return out


def load_registry_sets(src: Optional[SourceFile]) -> dict[str, set[str]]:
    """``TestMetricsKeyStability``'s class-level set literals by name."""
    out: dict[str, set[str]] = {}
    if src is None or src.tree is None:
        return out
    for node in ast.walk(src.tree):
        if isinstance(node, ast.ClassDef) and node.name == "TestMetricsKeyStability":
            for stmt in node.body:
                if isinstance(stmt, ast.Assign) and isinstance(
                    stmt.value, ast.Set
                ):
                    for t in stmt.targets:
                        if isinstance(t, ast.Name):
                            out[t.id] = {
                                e.value for e in stmt.value.elts
                                if isinstance(e, ast.Constant)
                                and isinstance(e.value, str)
                            }
    return out


def check_metrics(root: str, sources: dict[str, SourceFile]) -> list[Finding]:
    findings: list[Finding] = []
    reg_src = sources.get(REGISTRY_FILE)
    if reg_src is None:
        reg_path = os.path.join(root, REGISTRY_FILE)
        if os.path.isfile(reg_path):
            reg_src = SourceFile(root, REGISTRY_FILE)
    registries = load_registry_sets(reg_src)
    expected = registries.get("EXPECTED")
    mock_only = registries.get("MOCK_ONLY", set())
    coordinator = registries.get("COORDINATOR", set())
    if expected is None:
        return [Finding(
            "metrics", REGISTRY_FILE, 1,
            "TestMetricsKeyStability.EXPECTED set not found — the "
            "stable engine metric key registry is the conformance anchor",
        )]
    docs_path = os.path.join(root, DOCS_FILE)
    docs_text = ""
    if os.path.isfile(docs_path):
        with open(docs_path, encoding="utf-8") as f:
            docs_text = f.read()
    else:
        findings.append(Finding(
            "metrics", DOCS_FILE, 1, "docs/serving.md missing",
        ))

    plans: list[tuple[str, set[str], str]] = []
    for f in ENGINE_FAMILY:
        plans.append((f, expected, "TestMetricsKeyStability.EXPECTED"))
    for f in TRAFFICSIM_FILES:
        plans.append((f, expected, "TestMetricsKeyStability.EXPECTED"))
    for f in MOCK_FILES:
        plans.append((
            f, expected | mock_only,
            "TestMetricsKeyStability.EXPECTED ∪ MOCK_ONLY",
        ))
    for f in COORDINATOR_FILES:
        plans.append((
            f, coordinator, "TestMetricsKeyStability.COORDINATOR",
        ))
    plans.append((
        FLEET_FILE, coordinator, "TestMetricsKeyStability.COORDINATOR",
    ))

    written: dict[str, set[str]] = {"engine": set(), "mock": set(), "coord": set()}
    seen: set[tuple[str, int, str, str]] = set()
    for rel, allowed, registry_name in plans:
        src = sources.get(rel)
        if src is None:
            continue
        for key, line in metric_keys_in(src):
            if (rel, line, key, registry_name) in seen:
                continue  # .get + subscript on one line report once
            seen.add((rel, line, key, registry_name))
            if rel in COORDINATOR_FILES:
                written["coord"].add(key)
            elif rel in MOCK_FILES:
                written["mock"].add(key)
            else:
                written["engine"].add(key)
            if key not in allowed:
                findings.append(Finding(
                    "metrics", rel, line,
                    f"metrics key {key!r} is not registered in "
                    f"{registry_name} — metric names are a stable "
                    f"interface; add the registry row (and the docs row)",
                ))
            if docs_text and f"`{key}`" not in docs_text:
                findings.append(Finding(
                    "metrics", rel, line,
                    f"metrics key {key!r} is not documented in "
                    f"{DOCS_FILE} — add a row to the metrics table",
                ))

    # Stale registry rows: a registered key nothing writes anymore.
    reg_line = 1
    if reg_src is not None and reg_src.tree is not None:
        for node in ast.walk(reg_src.tree):
            if isinstance(node, ast.ClassDef) and (
                node.name == "TestMetricsKeyStability"
            ):
                reg_line = node.lineno
    all_written = written["engine"] | written["mock"] | written["coord"]
    for name, keys in (("EXPECTED", expected), ("MOCK_ONLY", mock_only),
                       ("COORDINATOR", coordinator)):
        for key in sorted(keys - all_written):
            findings.append(Finding(
                "metrics", REGISTRY_FILE, reg_line,
                f"stale registry row: TestMetricsKeyStability.{name} "
                f"contains {key!r} but no engine/mock/coordinator code "
                f"writes it",
            ))
    return findings
