"""Trace-purity checker (rule ``purity``).

A function traced by ``jax.jit`` / ``lax.scan`` / ``shard_map`` /
``pl.pallas_call`` runs ONCE at trace time; host side effects inside it
silently happen never (or once, at compile) instead of per step — the
classic "time.time() in a scan body" bug. This checker finds the traced
bodies in a module and flags host effects inside them:

- ``print(...)`` / ``open(...)``
- ``time.*`` (host clock inside a traced body)
- bare ``random.*`` and ``np.random.*`` (host RNG; ``jax.random`` is
  the device-side API and is fine)
- ``.item()`` and ``np.asarray(...)`` (implicit device→host syncs)
- ``.block_until_ready()``
- ``global`` / ``nonlocal`` declarations and ``self.<attr>`` writes
  (Python-state mutation from a traced body)

Traced-body discovery is module-local and transitive: a function is
traced if it is decorated with / passed to a tracer entry point, if it
is defined inside a traced function, or if a traced function calls it
by name. Cross-module calls are not followed — each listed module is
checked against its own tracer call sites.
"""

from __future__ import annotations

import ast

from omnia_tpu.analysis.core import Finding, SourceFile

#: Files whose traced bodies are checked (the compiled-program surface —
#: plus the flight-recorder layer and the scheduler/placement seams it
#: instruments: all flight timing must be captured strictly host-side,
#: so a host clock slipping into a traced body there is exactly this
#: rule's bug class).
PURITY_FILES_PREFIXES: tuple[str, ...] = (
    "omnia_tpu/engine/programs.py",
    "omnia_tpu/engine/interleave.py",
    "omnia_tpu/engine/spec_decode.py",
    "omnia_tpu/engine/flight.py",
    "omnia_tpu/engine/scheduler.py",
    "omnia_tpu/engine/placement.py",
    "omnia_tpu/engine/paged.py",
    "omnia_tpu/engine/warmup.py",
    "omnia_tpu/ops/",
    "omnia_tpu/models/",
    "omnia_tpu/parallel/",
    # The traffic simulator is host-side by contract; listing it makes
    # any future traced body inside it subject to the same rule.
    "omnia_tpu/evals/trafficsim/",
    # The fleet scaler is host-side by contract (scale decisions are
    # stats arithmetic); a traced body here would be the same bug class.
    "omnia_tpu/engine/fleet.py",
    # Role routing and the handoff plane are stats arithmetic + worker
    # RPCs; a traced body here would be the same bug class.
    "omnia_tpu/engine/disagg.py",
    # The decode-ring host half is host-side by contract (drainer
    # threads + gate arithmetic); a traced body here would be the same
    # bug class.
    "omnia_tpu/engine/devloop.py",
)

#: Call heads that trace their function argument(s).
_TRACER_ATTRS = frozenset({"jit", "scan", "shard_map", "pallas_call",
                           "while_loop", "fori_loop", "cond", "vmap",
                           "checkpoint", "remat", "grad", "value_and_grad"})

_HOST_MODULES = frozenset({"time", "random"})
_NP_ALIASES = frozenset({"np", "numpy"})


def purity_files(all_files: list[str]) -> list[str]:
    return [
        f for f in all_files
        if any(
            f == p or (p.endswith("/") and f.startswith(p))
            for p in PURITY_FILES_PREFIXES
        )
    ]


def _call_attr_name(func: ast.expr) -> str:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return ""


class _TracedIndex:
    """All function defs in a module + which are (transitively) traced.
    ``traced`` holds FunctionDef/AsyncFunctionDef AND Lambda nodes —
    a lambda handed to a tracer entry point is a traced body too."""

    def __init__(self, tree: ast.AST):
        self.defs: dict[str, list[ast.FunctionDef]] = {}
        self.parents: dict[ast.FunctionDef, ast.FunctionDef | None] = {}
        self.traced: set[ast.AST] = set()
        self._collect(tree, None)
        self._seed(tree)
        self._closure()

    def _collect(self, node: ast.AST, parent) -> None:
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if not isinstance(node, ast.ClassDef):
                    # Methods are reachable only via attribute access,
                    # never a bare Name — indexing them by name would
                    # falsely trace any method sharing a name with a
                    # jitted function. (Decorator seeding still covers
                    # @jit methods directly, by node.)
                    self.defs.setdefault(child.name, []).append(child)
                self.parents[child] = parent
                self._collect(child, child)
            else:
                self._collect(child, parent)

    def _seed_arg(self, arg: ast.expr) -> None:
        """Mark one tracer argument: a named def, an in-place lambda, or
        either wrapped in ``functools.partial(...)`` (the idiom both
        ``@partial(jax.jit, ...)`` bodies and
        ``pallas_call(partial(kernel, ...))`` kernels use)."""
        if isinstance(arg, ast.Name) and arg.id in self.defs:
            self.traced.update(self.defs[arg.id])
        elif isinstance(arg, ast.Lambda):
            self.traced.add(arg)  # traced in place
        elif isinstance(arg, ast.Call) and _call_attr_name(arg.func) == "partial":
            for sub in list(arg.args) + [kw.value for kw in arg.keywords]:
                self._seed_arg(sub)

    @staticmethod
    def _decorator_traces(deco: ast.expr) -> bool:
        """True when a decorator traces the function it decorates:
        ``@jax.jit``, ``@jit(...)``, or ``@functools.partial(jax.jit,
        ...)`` (the partial head itself is not a tracer — its FIRST
        argument is)."""
        if isinstance(deco, ast.Call):
            head = _call_attr_name(deco.func)
            if head in _TRACER_ATTRS:
                return True
            if head == "partial" and deco.args:
                return _call_attr_name(deco.args[0]) in _TRACER_ATTRS
            return False
        return _call_attr_name(deco) in _TRACER_ATTRS

    def _seed(self, tree: ast.AST) -> None:
        for node in ast.walk(tree):
            if isinstance(node, ast.Call):
                head = _call_attr_name(node.func)
                if head not in _TRACER_ATTRS:
                    continue
                for arg in list(node.args) + [kw.value for kw in node.keywords]:
                    self._seed_arg(arg)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if any(self._decorator_traces(d) for d in node.decorator_list):
                    self.traced.add(node)

    def _closure(self) -> None:
        # (a) defs nested inside a traced def are traced; (b) module-
        # local functions CALLED from a traced body are traced. Iterate
        # to fixpoint (the sets are tiny).
        changed = True
        while changed:
            changed = False
            for fns in self.defs.values():
                for fn in fns:
                    if fn in self.traced:
                        continue
                    parent = self.parents.get(fn)
                    if parent is not None and parent in self.traced:
                        self.traced.add(fn)
                        changed = True
            for fn in list(self.traced):
                for node in ast.walk(fn):
                    if isinstance(node, ast.Call) and isinstance(
                        node.func, ast.Name
                    ):
                        for callee in self.defs.get(node.func.id, ()):
                            if callee not in self.traced:
                                self.traced.add(callee)
                                changed = True


def _iter_body(fn: ast.AST, traced: set[ast.AST]):
    """Walk one traced body WITHOUT descending into nested nodes that
    are traced roots themselves — every nested def of a traced function
    (closure rule) and every directly-seeded lambda is walked as its own
    root, so each violation is attributed to exactly one body."""
    stack = list(ast.iter_child_nodes(fn))
    while stack:
        node = stack.pop()
        if node in traced:
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


def _check_body(src: SourceFile, fn: ast.AST, traced: set[ast.AST],
                findings: list[Finding]) -> None:
    where = f"traced body {getattr(fn, 'name', '<lambda>')!r}"
    for node in _iter_body(fn, traced):
        if isinstance(node, (ast.Global, ast.Nonlocal)):
            findings.append(Finding(
                "purity", src.rel, node.lineno,
                f"{where} declares {'global' if isinstance(node, ast.Global) else 'nonlocal'}"
                f" — Python-state mutation inside a traced body",
            ))
        elif isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (
                node.targets if isinstance(node, ast.Assign) else [node.target]
            )
            for t in targets:
                if (
                    isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                ):
                    findings.append(Finding(
                        "purity", src.rel, node.lineno,
                        f"{where} writes self.{t.attr} — object mutation "
                        f"inside a traced body happens at TRACE time, "
                        f"not per step",
                    ))
        elif isinstance(node, ast.Call):
            _check_call(src, where, node, findings)
        elif isinstance(node, ast.Attribute):
            if isinstance(node.value, ast.Name) and node.value.id in _HOST_MODULES:
                findings.append(Finding(
                    "purity", src.rel, node.lineno,
                    f"{where} uses {node.value.id}.{node.attr} — host "
                    f"{'clock' if node.value.id == 'time' else 'RNG'} "
                    f"inside a traced body runs once at trace time",
                ))
            elif (
                node.attr == "random"
                and isinstance(node.value, ast.Name)
                and node.value.id in _NP_ALIASES
            ):
                findings.append(Finding(
                    "purity", src.rel, node.lineno,
                    f"{where} uses {node.value.id}.random — host RNG "
                    f"inside a traced body",
                ))


def _check_call(src: SourceFile, where: str, node: ast.Call,
                findings: list[Finding]) -> None:
    func = node.func
    if isinstance(func, ast.Name) and func.id in ("print", "open"):
        findings.append(Finding(
            "purity", src.rel, node.lineno,
            f"{where} calls {func.id}() — host side effect inside a "
            f"traced body",
        ))
        return
    if not isinstance(func, ast.Attribute):
        return
    if func.attr == "item":
        findings.append(Finding(
            "purity", src.rel, node.lineno,
            f"{where} calls .item() — implicit device→host sync inside "
            f"a traced body",
        ))
    elif func.attr == "block_until_ready":
        findings.append(Finding(
            "purity", src.rel, node.lineno,
            f"{where} calls .block_until_ready() inside a traced body",
        ))
    elif func.attr == "asarray" and isinstance(func.value, ast.Name) and (
        func.value.id in _NP_ALIASES
    ):
        findings.append(Finding(
            "purity", src.rel, node.lineno,
            f"{where} calls {func.value.id}.asarray() — implicit "
            f"device→host sync inside a traced body (use jnp.asarray)",
        ))


def check_purity(sources: dict[str, SourceFile]) -> list[Finding]:
    findings: list[Finding] = []
    in_scope = set(purity_files(sorted(sources)))
    for src in sources.values():
        # Self-scoped (like jaxfree/locks): a full run shares one source
        # map across rules, so files loaded for OTHER rules must not
        # widen this one — `--rule purity` and the full suite agree.
        if src.rel not in in_scope or src.tree is None:
            continue
        index = _TracedIndex(src.tree)
        for fn in index.traced:
            _check_body(src, fn, index.traced, findings)
    return findings
