"""Operator REST APIs: content, authz, tool-test, mgmt-plane tokens,
deploy translate, license.

Reference parity: internal/api/content (workspace content CRUD),
internal/api/authz (workspace role checks), internal/tooltest/server.go
(dashboard "test this tool" backend), internal/mgmtplane/fetcher.go
(dashboard-minted mgmt JWTs for in-cluster callers), internal/api/deploy
(DeployIntent), ee license activation. One framework-free handler so the
operator process mounts it next to the dashboard.
"""

from __future__ import annotations

import json
import logging
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from omnia_tpu.facade.auth import HmacValidator
from omnia_tpu.license import CommunityLicenseManager, LicenseError
from omnia_tpu.operator.deploy import DeployIntentError, deploy as apply_intent
from omnia_tpu.operator.validation import ValidationError

logger = logging.getLogger(__name__)

# Workspace roles → allowed verbs (reference internal/api/authz).
ROLE_VERBS = {
    "viewer": {"get", "list"},
    "editor": {"get", "list", "create", "update"},
    "admin": {"get", "list", "create", "update", "delete", "grant"},
}


class ContentStore:
    """Versioned workspace content (reference internal/api/content →
    workspace PVC): path → ordered versions, latest wins."""

    def __init__(self) -> None:
        self._items: dict[tuple[str, str], list[dict]] = {}
        self._lock = threading.Lock()

    def put(self, workspace: str, path: str, content: str, author: str = "") -> dict:
        with self._lock:
            versions = self._items.setdefault((workspace, path), [])
            doc = {
                "workspace": workspace, "path": path, "content": content,
                "version": len(versions) + 1, "author": author,
                "updated_at": time.time(),
            }
            versions.append(doc)
            return dict(doc)

    def get(self, workspace: str, path: str, version: Optional[int] = None) -> Optional[dict]:
        with self._lock:
            versions = self._items.get((workspace, path))
            if not versions:
                return None
            if version is None:
                return dict(versions[-1])
            if 1 <= version <= len(versions):
                return dict(versions[version - 1])
            return None

    def list(self, workspace: str) -> list[dict]:
        with self._lock:
            return [
                {"path": p, "version": len(v), "updated_at": v[-1]["updated_at"]}
                for (ws, p), v in sorted(self._items.items())
                if ws == workspace
            ]

    def delete(self, workspace: str, path: str) -> bool:
        with self._lock:
            return self._items.pop((workspace, path), None) is not None


class OperatorAPI:
    # Routes that change state or mint credentials; read-only routes stay
    # open for the dashboard (which fronts its own auth).
    # tooltest is protected because an mcp/python handler config is code
    # execution on the operator host — never exposable unauthenticated.
    _PROTECTED = ("/api/v1/mgmt-token", "/api/v1/deploy",
                  "/api/v1/license/activate", "/api/v1/content/",
                  "/api/v1/tooltest")

    def __init__(
        self,
        store,                       # operator resource store
        mgmt_secret: Optional[bytes] = None,
        license_manager=None,
        tool_executor=None,          # retained for wiring symmetry; tool
        # tests always run on an ephemeral executor
        content: Optional[ContentStore] = None,
        service_token: Optional[str] = None,
    ) -> None:
        self.store = store
        self.mgmt_secret = mgmt_secret
        self.license = license_manager or CommunityLicenseManager()
        self.content = content or ContentStore()
        self.tool_executor = tool_executor
        # Service-to-service auth (reference internal/serviceauth): when a
        # token is configured, privileged routes require it. Minting mgmt
        # tokens is privileged ALWAYS — an open minting endpoint would let
        # any caller escalate to an authenticated principal, so with no
        # service token configured it is disabled rather than open.
        self.service_token = service_token
        self._httpd: Optional[ThreadingHTTPServer] = None
        self.port: Optional[int] = None

    # -- authz ---------------------------------------------------------

    def _workspace_roles(self, workspace: str) -> list[dict]:
        res = self.store.get("default", "Workspace", workspace)
        if res is None:
            return []
        return res.spec.get("roleBindings", [])

    def check_access(self, workspace: str, user: str, verb: str) -> dict:
        for binding in self._workspace_roles(workspace):
            if user in binding.get("users", []):
                role = binding.get("role", "viewer")
                if verb in ROLE_VERBS.get(role, set()):
                    return {"allowed": True, "role": role}
        return {"allowed": False, "role": None}

    # -- tool-test -----------------------------------------------------

    def tool_test(self, body: dict) -> tuple[int, dict]:
        """Execute one tool handler config against its backend and report
        the outcome (reference internal/tooltest/server.go:33). The
        execution + hardening live in tools/tooltest.py, shared with the
        console's /api/tooltest route."""
        from omnia_tpu.tools.tooltest import run_tool_test

        return run_tool_test(body or {})

    # -- mgmt tokens ---------------------------------------------------

    MAX_MGMT_TTL_S = 3600.0

    def mint_mgmt_token(self, subject: str, ttl_s: float = 300.0) -> tuple[int, dict]:
        """Short-lived HS256 mgmt-plane token (reference
        internal/mgmtplane/fetcher.go consumes the dashboard's equivalent;
        here the operator mints for in-cluster callers like doctor). TTL
        is capped: an uncapped client-supplied ttl would let a service-
        token holder mint effectively permanent principals that survive
        service-token rotation."""
        if not self.mgmt_secret:
            return 503, {"error": "management plane secret not configured"}
        ttl_s = min(max(ttl_s, 1.0), self.MAX_MGMT_TTL_S)
        token = HmacValidator.mint(
            self.mgmt_secret, subject=subject, audience="mgmt", ttl_s=ttl_s
        )
        return 200, {"token": token, "expires_in_s": ttl_s}

    # -- routing -------------------------------------------------------

    def _authorized(self, path: str, headers: Optional[dict]) -> bool:
        if not any(path.startswith(p) for p in self._PROTECTED):
            return True
        if path == "/api/v1/mgmt-token" and self.service_token is None:
            return False  # never open: minting escalates privileges
        if self.service_token is None:
            return True
        # Header names are case-insensitive (RFC 7230; HTTP/2 lowercases).
        auth = ""
        for k, v in (headers or {}).items():
            if str(k).lower() == "authorization":
                auth = str(v)
                break
        token = auth[7:] if auth.startswith("Bearer ") else ""
        import hashlib
        import hmac as hmac_mod

        return hmac_mod.compare_digest(
            hashlib.sha256(token.encode()).digest(),
            hashlib.sha256(self.service_token.encode()).digest(),
        )

    def handle(self, method: str, path: str, body: Optional[dict],
               query: Optional[dict] = None,
               headers: Optional[dict] = None) -> tuple[int, dict]:
        query = query or {}
        if not self._authorized(path, headers):
            return 401, {"error": "service token required"}
        try:
            return self._route(method, path, body or {}, query)
        except (ValidationError, DeployIntentError) as e:
            return 400, {"error": str(e)}
        except LicenseError as e:
            return 402, {"error": str(e)}
        except (KeyError, TypeError, ValueError) as e:
            return 400, {"error": str(e)}
        except Exception as e:  # pragma: no cover - defensive
            logger.exception("operator api error")
            return 500, {"error": str(e)}

    def _route(self, method, path, body, query):
        if path == "/api/v1/deploy" and method == "POST":
            result = apply_intent(self.store, body)
            return 200, result.to_dict()
        if path == "/api/v1/tooltest" and method == "POST":
            return self.tool_test(body)
        if path == "/api/v1/mgmt-token" and method == "POST":
            subject = body.get("subject", "")
            if not subject:
                return 400, {"error": "subject required"}
            return self.mint_mgmt_token(subject, float(body.get("ttl_s", 300)))
        if path == "/api/v1/authz/check" and method == "POST":
            for field in ("workspace", "user", "verb"):
                if not body.get(field):
                    return 400, {"error": f"{field} required"}
            return 200, self.check_access(
                body["workspace"], body["user"], body["verb"])
        if path == "/api/v1/license" and method == "GET":
            return 200, self.license.heartbeat()
        if path == "/api/v1/license/activate" and method == "POST":
            lic = self.license.activate(body.get("key", ""))
            return 200, {"activated": True, "license_id": lic.license_id,
                         "features": sorted(lic.features)}
        # content CRUD
        if path.startswith("/api/v1/content/"):
            rest = path[len("/api/v1/content/"):]
            ws, _, cpath = rest.partition("/")
            if not ws:
                return 400, {"error": "workspace required"}
            if method == "GET" and not cpath:
                return 200, {"items": self.content.list(ws)}
            if not cpath:
                return 400, {"error": "content path required"}
            if method == "GET":
                version = query.get("version")
                doc = self.content.get(
                    ws, cpath, int(version[0]) if version else None)
                return (200, doc) if doc else (404, {"error": "not found"})
            if method in ("PUT", "POST"):
                if "content" not in body:
                    return 400, {"error": "content required"}
                return 200, self.content.put(
                    ws, cpath, body["content"], body.get("author", ""))
            if method == "DELETE":
                return (200, {"deleted": True}) if self.content.delete(ws, cpath) \
                    else (404, {"error": "not found"})
        return 404, {"error": f"no route {method} {path}"}

    # -- http ----------------------------------------------------------

    def serve(self, host: str = "localhost", port: int = 0) -> int:
        api = self

        class Handler(BaseHTTPRequestHandler):
            def _dispatch(self, method):
                split = urllib.parse.urlsplit(self.path)
                body = None
                length = int(self.headers.get("Content-Length") or 0)
                if length:
                    try:
                        body = json.loads(self.rfile.read(length))
                    except json.JSONDecodeError:
                        self._reply(400, {"error": "bad json"})
                        return
                status, doc = api.handle(
                    method, split.path, body,
                    urllib.parse.parse_qs(split.query),
                    headers=dict(self.headers),
                )
                self._reply(status, doc)

            def _reply(self, status, doc):
                payload = json.dumps(doc).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

            def do_GET(self):
                self._dispatch("GET")

            def do_POST(self):
                self._dispatch("POST")

            def do_PUT(self):
                self._dispatch("PUT")

            def do_DELETE(self):
                self._dispatch("DELETE")

            def log_message(self, *a):  # pragma: no cover
                pass

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.port = self._httpd.server_address[1]
        threading.Thread(
            target=self._httpd.serve_forever, name="omnia-operator-api",
            daemon=True,
        ).start()
        return self.port

    def shutdown(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
