"""Declarative resource model: the framework's CRD-equivalent surface.

Kinds mirror the reference's core CRDs (reference api/v1alpha1/ — see
SURVEY.md §2.1): AgentRuntime (agentruntime_types.go:1355-1504),
Provider (provider_types.go:322-412, plus the NEW `type: tpu`),
PromptPack, ToolRegistry, Workspace, AgentPolicy, MemoryPolicy,
SessionRetentionPolicy, SkillSource. The envelope is K8s-shaped
(apiVersion/kind/metadata/spec/status) so manifests translate 1:1, but
resources here are plain dicts validated by validation.py — the control
plane is cluster-optional (file-backed store = the reference's
OMNIA_CONFIG_DIR clusterless mode, pkg/k8s/filebacked.go:36-42)."""

from __future__ import annotations

import copy
import enum
import time
from dataclasses import dataclass, field
from typing import Any, Optional

API_VERSION = "omnia.tpu/v1alpha1"


class ResourceKind(str, enum.Enum):
    AGENT_RUNTIME = "AgentRuntime"
    PROVIDER = "Provider"
    PROMPT_PACK = "PromptPack"
    TOOL_REGISTRY = "ToolRegistry"
    WORKSPACE = "Workspace"
    AGENT_POLICY = "AgentPolicy"
    MEMORY_POLICY = "MemoryPolicy"
    SESSION_RETENTION_POLICY = "SessionRetentionPolicy"
    SKILL_SOURCE = "SkillSource"
    # Enterprise kinds (reference ee/api/v1alpha1): store-resident like
    # everything else, reconciled only when the feature is licensed.
    ARENA_JOB = "ArenaJob"
    TOOL_POLICY = "ToolPolicy"
    SESSION_PRIVACY_POLICY = "SessionPrivacyPolicy"
    ROLLOUT_ANALYSIS = "RolloutAnalysis"
    # Source-sync kinds (reference ee promptpacksource_controller.go,
    # arenasource/arenatemplatesource/arenadevsession controllers).
    PROMPT_PACK_SOURCE = "PromptPackSource"
    ARENA_SOURCE = "ArenaSource"
    ARENA_TEMPLATE_SOURCE = "ArenaTemplateSource"
    ARENA_DEV_SESSION = "ArenaDevSession"


EE_KINDS = frozenset({
    ResourceKind.ARENA_JOB.value,
    ResourceKind.TOOL_POLICY.value,
    ResourceKind.SESSION_PRIVACY_POLICY.value,
    ResourceKind.ROLLOUT_ANALYSIS.value,
    ResourceKind.PROMPT_PACK_SOURCE.value,
    ResourceKind.ARENA_SOURCE.value,
    ResourceKind.ARENA_TEMPLATE_SOURCE.value,
    ResourceKind.ARENA_DEV_SESSION.value,
})

# Source spec type vocabulary (SkillSource/PromptPackSource/Arena*Source;
# reference sourcesync_types.go:56-58 git|oci|configmap + in-tree local).
SOURCE_TYPES = ("git", "oci", "configmap", "local")


# Enum vocabularies shared with validation (reference anchors cited).
FACADE_TYPES = ("websocket", "a2a", "rest", "mcp")  # agentruntime_types.go:1408-1417
AGENT_MODES = ("agent", "function")  # agentruntime_types.go:1356-1394
# Reference enum :382-414 + the new tpu type; "tone" is the in-tree
# model-free pcm16 speech test codec; cartesia/elevenlabs/openai are the
# real HTTP speech vendors (provider_types.go:407-414,
# runtime/speech_http.py) for tts/stt roles.
# "procedural" is the in-tree model-free image generator
# (runtime/images.py — the image analog of the tone speech codec).
PROVIDER_TYPES = ("tpu", "mock", "tone", "cartesia", "elevenlabs", "openai",
                  "procedural")
# provider_types.go:40-63; image/inference validated for parity, served
# when an on-device image/inference family lands.
PROVIDER_ROLES = ("llm", "embedding", "tts", "stt", "image", "inference")
TOOL_HANDLER_TYPES = ("http", "openapi", "grpc", "mcp", "client")  # toolregistry :26-51


@dataclass
class Resource:
    kind: str
    name: str
    namespace: str = "default"
    labels: dict = field(default_factory=dict)
    spec: dict = field(default_factory=dict)
    status: dict = field(default_factory=dict)
    generation: int = 1
    created_at: float = field(default_factory=time.time)

    @property
    def key(self) -> str:
        return f"{self.namespace}/{self.kind}/{self.name}"

    def to_manifest(self) -> dict:
        return {
            "apiVersion": API_VERSION,
            "kind": self.kind,
            "metadata": {
                "name": self.name,
                "namespace": self.namespace,
                "labels": dict(self.labels),
                "generation": self.generation,
            },
            "spec": copy.deepcopy(self.spec),
            "status": copy.deepcopy(self.status),
        }

    @classmethod
    def from_manifest(cls, doc: dict) -> "Resource":
        if "kind" not in doc:
            raise ValueError("manifest missing kind")
        md = doc.get("metadata") or {}
        if not md.get("name"):
            raise ValueError("manifest missing metadata.name")
        return cls(
            kind=doc["kind"],
            name=md["name"],
            namespace=md.get("namespace", "default"),
            labels=md.get("labels") or {},
            spec=copy.deepcopy(doc.get("spec") or {}),
            status=copy.deepcopy(doc.get("status") or {}),
            generation=md.get("generation", 1),
        )


def ref_key(namespace: str, kind: str, name: str) -> str:
    return f"{namespace}/{kind}/{name}"


def resolve_ref(
    store, namespace: str, kind: ResourceKind, ref: Any
) -> Optional[Resource]:
    """Resolve a spec reference ({'name': ...} or plain string) within the
    same namespace, the reference's ref convention."""
    if ref is None:
        return None
    name = ref.get("name") if isinstance(ref, dict) else str(ref)
    if not name:
        return None
    return store.get(namespace, kind.value, name)
