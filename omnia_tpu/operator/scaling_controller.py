"""Queue-depth autoscaling seam of the controller.

The pod-backend half of the elastic-fleet story: the resync loop samples
each deployment's runtime /healthz (queue depth PLUS the prompt-token
prefill backlog) and drives the SAME `FleetScaler` control loop the
in-process coordinator fleets run (engine/fleet.py), applying scale
decisions through `backend.scale`. Split from controller.py so the
scaling seam reads as one unit; mixed into :class:`ControllerManager`.
"""

from __future__ import annotations

import logging

from omnia_tpu.operator.autoscaling import AutoscalingPolicy
from omnia_tpu.operator.deployment import AgentDeployment

logger = logging.getLogger(__name__)


class _AutoscaleMixin:
    """Autoscaling methods of :class:`ControllerManager` (uses its pod
    backend, deployments map, and per-deployment scaler registry)."""

    def _apply_scale(self, dep: AgentDeployment, want: int) -> int:
        """The pod-backend half of the FleetScaler provisioner seam."""
        self.backend.scale(dep, want, wait_ready=self.wait_ready)
        return len(dep.pods)

    def _autoscale(self, key: str, dep: AgentDeployment) -> None:
        # Lazy import: engine/fleet.py imports this package's
        # autoscaling policy, so a module-top import here would be
        # circular through omnia_tpu.operator.__init__.
        from omnia_tpu.engine.fleet import FleetScaler

        policy = AutoscalingPolicy.from_spec(
            dep.resource.spec.get("autoscaling"),
            fallback_replicas=dep.resource.spec.get("replicas", 1),
        )
        scaler = self._autoscalers.get(key)
        if scaler is None or scaler.policy != policy:
            scaler = FleetScaler(
                policy, provisioner=lambda want: self._apply_scale(dep, want),
            )
            self._autoscalers[key] = scaler
        # The resync loop samples its own pods (the deployment record is
        # resync-local state) and supplies current + the sample to the
        # shared control loop; the bare-callable provisioner applies
        # through backend.scale.
        scaler.provisioner = lambda want: self._apply_scale(dep, want)
        depth, conns = self._load_signals(dep)
        ev = scaler.tick(current=len(dep.pods), depth=depth, conns=conns)
        if ev is not None:
            logger.info(
                "autoscale %s: %d -> %d (queue=%.2f conns=%s)",
                dep.name, ev.from_workers, ev.to_workers, depth, conns,
            )

    def _load_signals(self, dep: AgentDeployment) -> tuple[float, int]:
        from omnia_tpu.engine.fleet import PENDING_TOKENS_NORM
        from omnia_tpu.runtime.client import RuntimeClient

        # Disaggregated tier (engine/disagg.py): a deployment declaring
        # `disagg: {role: decode}` scales on decode-slot occupancy —
        # the tier's own backlog — instead of the prefill-side signal;
        # prefill/pooled deployments keep the queue+token trigger.
        role = (dep.resource.spec.get("disagg") or {}).get("role", "pooled")
        depth = 0.0
        conns = 0
        for pod in dep.pods + dep.candidate_pods:
            try:
                client = RuntimeClient(f"localhost:{pod.runtime_port}")
                try:
                    h = client.health()
                    if role == "decode":
                        # Occupied decode slots are the decode tier's
                        # work units; queue depth still counts so a
                        # backed-up decode worker registers too.
                        depth += h.queue_depth
                        depth += getattr(h, "decode_slots_active", 0)
                    else:
                        # Queue depth PLUS the prompt-token prefill
                        # backlog in request-equivalents — the SURVEY
                        # §5.8 trigger: four queued 8k-token prompts
                        # scale like real work, not like four idle
                        # connections.
                        depth += h.queue_depth
                        depth += (
                            getattr(h, "pending_prefill_tokens", 0)
                            / PENDING_TOKENS_NORM
                        )
                finally:
                    client.close()
            except Exception:
                pass  # scrape is advisory; autoscaler tolerates gaps
            try:
                conns += int(pod.facade.metrics.gauge("connections_active").value())
            except Exception:
                pass  # in-process pod without facade metrics
        return depth, conns
