"""Deployment builder: AgentRuntime resource → running agent "pods".

The reference operator builds a two-container pod (facade + runtime) per
agent (reference internal/controller/deployment_builder.go:124,
deployment_builder_containers.go:27/:187) and applies podOverrides for
node placement — the hook the TPU build uses for
`cloud.google.com/gke-tpu-accelerator` node pools (reference
internal/podoverrides/podoverrides.go:44).

Two backends over one Deployment abstraction:
- InProcessPodBackend — actually runs the pair (RuntimeServer gRPC +
  FacadeServer WebSocket) on localhost ports: the framework's
  single-node/dev data plane, and what integration tests drive.
- K8sManifestBackend — renders Deployment/Service manifests (two
  containers, config projection, TPU nodeSelector/tolerations from
  podOverrides) for a cluster to run; rendering is pure so it needs no
  cluster to test.
"""

from __future__ import annotations

import copy
import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Optional

from omnia_tpu.operator.resources import Resource

logger = logging.getLogger(__name__)


@dataclass
class PodHandle:
    name: str
    runtime: object  # RuntimeServer
    facade: object   # FacadeServer
    runtime_port: int
    facade_port: int
    started_at: float = field(default_factory=time.time)
    version: str = ""  # config hash / rollout track

    @property
    def endpoint(self) -> str:
        return f"ws://localhost:{self.facade_port}"

    def stop(self) -> None:
        try:
            self.facade.shutdown()
        finally:
            self.runtime.shutdown()


@dataclass
class AgentDeployment:
    """Desired state resolved from an AgentRuntime + its refs."""

    resource: Resource
    pack_doc: dict
    provider_specs: list[dict]
    default_provider: str
    tool_configs: list[dict] = field(default_factory=list)
    session_api_url: Optional[str] = None
    required_capabilities: list[str] = field(default_factory=list)
    replicas: int = 1
    pods: list[PodHandle] = field(default_factory=list)
    # Rollout bookkeeping: stable config hash + candidate pods.
    stable_hash: str = ""
    candidate_pods: list[PodHandle] = field(default_factory=list)
    candidate_weight: float = 0.0
    # Capability-gate latch: config hash that was probed and found
    # missing capabilities; stays scaled-to-zero until the config (or
    # required capability set) changes.
    gate_blocked_hash: str = ""

    @property
    def name(self) -> str:
        return self.resource.name

    @property
    def namespace(self) -> str:
        return self.resource.namespace

    def config_hash(self) -> str:
        """Hash of everything that requires a pod restart when changed
        (the reference's config-hash restart trigger,
        deployment_builder_confighash.go). Scaling and delivery policy
        (replicas / autoscaling / rollout) are deliberately EXCLUDED — a
        replica-count edit must not restart pods or trigger a canary."""
        import hashlib
        import json

        restart_spec = {
            k: v
            for k, v in self.resource.spec.items()
            if k not in ("replicas", "autoscaling", "rollout")
        }
        doc = {
            "spec": restart_spec,
            "pack": self.pack_doc,
            "providers": self.provider_specs,
            "tools": self.tool_configs,
        }
        return hashlib.sha256(
            json.dumps(doc, sort_keys=True, default=str).encode()
        ).hexdigest()[:16]

    def endpoints(self) -> list[tuple[str, float]]:
        """(endpoint, weight) pairs for traffic routing. Stable pods share
        (100 - candidate_weight); candidates share candidate_weight."""
        out: list[tuple[str, float]] = []
        stable_w = 100.0 - self.candidate_weight
        if self.pods:
            w = stable_w / len(self.pods)
            out.extend((p.endpoint, w) for p in self.pods)
        if self.candidate_pods and self.candidate_weight > 0:
            w = self.candidate_weight / len(self.candidate_pods)
            out.extend((p.endpoint, w) for p in self.candidate_pods)
        return out


def _build_tool_handlers(tool_configs: list[dict]):
    """CRD tools[] entries → executor handlers. All five handler types
    route (reference internal/runtime/tools/config.go:131-169 HandlerEntry
    carries per-type config blocks; same shape here in camelCase)."""
    from omnia_tpu.tools.executor import ToolHandler

    handlers = []
    for t in tool_configs:
        h = t.get("handler", {})
        htype = h.get("type", "http")
        if htype not in ("http", "openapi", "grpc", "mcp", "client"):
            htype = "http"
        grpc_cfg = h.get("grpcConfig", {})
        openapi_cfg = h.get("openAPIConfig", {})
        handlers.append(
            ToolHandler(
                name=t["name"],
                type=htype,
                description=t.get("description", ""),
                input_schema=t.get("inputSchema", t.get("input_schema")),
                url=h.get("url", ""),
                method=h.get("method", "POST"),
                headers=h.get("headers", openapi_cfg.get("headers", {})),
                timeout_s=h.get("timeoutSeconds", t.get("timeout_s", 30.0)),
                endpoint=h.get("endpoint", grpc_cfg.get("endpoint", "")),
                tls=bool(grpc_cfg.get("tls", h.get("tls", False))),
                auth_token=grpc_cfg.get("authToken", h.get("authToken", "")),
                mcp=h.get("mcpConfig") or h.get("mcp"),
                spec=h.get("spec"),
                spec_url=h.get("specURL", openapi_cfg.get("specURL", "")),
                base_url=h.get("baseURL", openapi_cfg.get("baseURL", "")),
                operation=h.get("operation", ""),
                remote_name=h.get("remoteName", ""),
            )
        )
    return handlers


class InProcessPodBackend:
    """Runs facade+runtime pairs in this process (threads + localhost
    ports) — the reference's integration-test topology (test/integration/
    facade_runtime_test.go:190-202) promoted to a first-class dev
    backend."""

    def __init__(self) -> None:
        import os

        self._counter = 0
        self._lock = threading.Lock()
        self._media = None
        # Cluster analog: every facade pod gets OMNIA_MGMT_SECRET via
        # secretKeyRef (K8sManifestBackend); in-process pods read it from
        # the operator's own env so console-minted mgmt JWTs validate at
        # the facade the same way in both topologies.
        self._mgmt_secret = (os.environ.get("OMNIA_MGMT_SECRET") or "").encode() or None

    def _tracer(self):
        """OTLP tracer for in-process pods when the operator env carries
        OMNIA_OTLP_ENDPOINT (observability bundle); cluster pods get the
        same env stamped by K8sManifestBackend."""
        import os

        endpoint = os.environ.get("OMNIA_OTLP_ENDPOINT")
        if not endpoint:
            return None
        from omnia_tpu.utils.tracing import OTLPExporter, Tracer

        return Tracer(
            "omnia-runtime",
            sample_rate=float(os.environ.get("OMNIA_TRACE_SAMPLE_RATE", "1.0")),
            otlp=OTLPExporter(endpoint),
        )

    def _auth_chain(self):
        """Facade auth for in-process pods: audience-pinned HMAC when a
        mgmt secret is configured (matching cli.py facade assembly), else
        None (open dev pods, same as before)."""
        if self._mgmt_secret is None:
            return None
        from omnia_tpu.facade.auth import AuthChain, HmacValidator

        return AuthChain([HmacValidator(self._mgmt_secret, audience="mgmt")])

    def _media_store(self):
        """One shared LocalMediaStore per backend: all in-process pods see
        the same media (the cluster analog is a shared bucket + shared
        OMNIA_MEDIA_SECRET); facade and runtime must share the instance so
        grant tokens verify across the pair."""
        with self._lock:
            if self._media is None:
                import tempfile

                from omnia_tpu.media import LocalMediaStore

                self._media = LocalMediaStore(
                    tempfile.mkdtemp(prefix="omnia-media-")
                )
            return self._media

    def start_pod(
        self,
        dep: AgentDeployment,
        *,
        version: str = "",
        wait_ready: bool = True,
        track: str = "stable",
    ) -> PodHandle:
        from omnia_tpu.facade.recording import RecordingInterceptor
        from omnia_tpu.facade.server import FacadeServer
        from omnia_tpu.runtime.packs import load_pack
        from omnia_tpu.runtime.providers import ProviderRegistry, ProviderSpec
        from omnia_tpu.runtime.server import RuntimeServer
        from omnia_tpu.tools.executor import ToolExecutor

        with self._lock:
            self._counter += 1
            pod_name = f"{dep.name}-{self._counter}"

        registry = ProviderRegistry()
        for ps in dep.provider_specs:
            registry.register(ProviderSpec.from_dict(ps))
        runtime = RuntimeServer(
            pack=load_pack(copy.deepcopy(dep.pack_doc)),
            providers=registry,
            provider_name=dep.default_provider,
            tool_executor=ToolExecutor(handlers=_build_tool_handlers(dep.tool_configs)),
            media_store=self._media_store(),
            workspace=dep.namespace,
            tracer=self._tracer(),
        )
        runtime_port = runtime.serve(wait_ready=wait_ready)
        facade = FacadeServer(
            runtime_target=f"localhost:{runtime_port}",
            agent_name=dep.name,
            recording=RecordingInterceptor(
                dep.session_api_url,
                agent=dep.name,
                # Track/version attribution: rollout analysis scopes its
                # eval verdict to candidate-track sessions of the hash
                # under analysis (reference rollout_analysis.go gates on
                # candidate metrics, not whole-agent metrics).
                attrs={"track": track, "version": version or dep.config_hash()},
            ),
            media_store=self._media_store(),
            workspace=dep.namespace,
            auth_chain=self._auth_chain(),
        )
        facade_port = facade.serve()
        handle = PodHandle(
            name=pod_name,
            runtime=runtime,
            facade=facade,
            runtime_port=runtime_port,
            facade_port=facade_port,
            version=version or dep.config_hash(),
        )
        logger.info("pod %s up: facade :%d runtime :%d", pod_name, facade_port, runtime_port)
        return handle

    def stop_pod(self, handle: PodHandle) -> None:
        logger.info("pod %s stopping", handle.name)
        handle.stop()

    def scale(self, dep: AgentDeployment, replicas: int, *, wait_ready: bool = True) -> None:
        """Reconcile the stable pod set to `replicas`."""
        while len(dep.pods) > replicas:
            self.stop_pod(dep.pods.pop())
        while len(dep.pods) < replicas:
            dep.pods.append(
                self.start_pod(dep, version=dep.stable_hash, wait_ready=wait_ready)
            )


class K8sManifestBackend:
    """Pure manifest rendering for cluster deployment; mirrors the
    reference's Deployment shape (two containers, env projection,
    config-hash annotation, podOverrides merge for TPU placement)."""

    def render(self, dep: AgentDeployment) -> dict:
        import os

        spec = dep.resource.spec
        overrides = spec.get("podOverrides", {})
        cfg_hash = dep.config_hash()
        env = [
            {"name": "OMNIA_AGENT", "value": dep.name},
            {"name": "OMNIA_PROVIDER", "value": dep.default_provider},
            {"name": "OMNIA_SESSION_API_URL", "value": dep.session_api_url or ""},
            # Trace export propagates operator → agent pods: agents are
            # where turn spans originate (install.py points the operator
            # at the bundled Tempo; cli._tracer reads this in the pod).
            *([{"name": "OMNIA_OTLP_ENDPOINT",
                "value": os.environ["OMNIA_OTLP_ENDPOINT"]}]
              if os.environ.get("OMNIA_OTLP_ENDPOINT") else []),
            # Facades validate mgmt-plane JWTs (console WS, in-cluster
            # callers) with the shared secret; optional so clusters
            # without the omnia-mgmt Secret still schedule (open facade,
            # dev posture).
            {"name": "OMNIA_MGMT_SECRET", "valueFrom": {"secretKeyRef": {
                "name": "omnia-mgmt", "key": "secret", "optional": True,
            }}},
        ]
        pod_spec = {
            "nodeSelector": overrides.get("nodeSelector", {}),
            "tolerations": overrides.get("tolerations", []),
            "serviceAccountName": overrides.get("serviceAccountName", "default"),
            "volumes": overrides.get("volumes", []),
            "containers": [
                {
                    "name": "facade",
                    "image": spec.get("facadeImage", "omnia-tpu/facade:latest"),
                    "ports": [
                        {"name": "ws", "containerPort": 8080},
                        {"name": "metrics", "containerPort": 8081},
                    ],
                    "env": env,
                },
                {
                    "name": "runtime",
                    "image": spec.get("runtimeImage", "omnia-tpu/runtime:latest"),
                    # Port names must be unique pod-wide in K8s; the
                    # facade owns the plain "metrics" name.
                    "ports": [
                        {"name": "grpc", "containerPort": 9000},
                        {"name": "metrics-rt", "containerPort": 9001},
                    ],
                    "env": env,
                    "resources": overrides.get(
                        "runtimeResources",
                        {"limits": {"google.com/tpu": spec.get("tpuChips", 8)}},
                    ),
                },
            ],
        }
        deployment = {
            "apiVersion": "apps/v1",
            "kind": "Deployment",
            "metadata": {
                "name": f"agent-{dep.name}",
                "namespace": dep.namespace,
                "labels": {"omnia/agent": dep.name, "omnia/track": "stable"},
                "annotations": {"omnia/config-hash": cfg_hash},
            },
            "spec": {
                "replicas": dep.replicas,
                # Selector labels are IMMUTABLE after creation (the
                # reference carves out exactly this subset,
                # deployment_builder.go:134-145): agent identity + track,
                # nothing that can evolve. track in the selector keeps the
                # stable and canary Deployments' pod ownership DISJOINT.
                "selector": {"matchLabels": {
                    "omnia/agent": dep.name, "omnia/track": "stable"}},
                "template": {
                    "metadata": {
                        # app.kubernetes.io labels make the observability
                        # bundle's PodMonitor (component: agent) and the
                        # Prometheus pod-label keep rule match agent pods.
                        "labels": {"omnia/agent": dep.name,
                                   "omnia/track": "stable",
                                   "app.kubernetes.io/name": "omnia",
                                   "app.kubernetes.io/component": "agent"},
                        "annotations": {"omnia/config-hash": cfg_hash},
                    },
                    "spec": pod_spec,
                },
            },
        }
        service = {
            "apiVersion": "v1",
            "kind": "Service",
            "metadata": {"name": f"agent-{dep.name}", "namespace": dep.namespace},
            "spec": {
                "selector": {"omnia/agent": dep.name},
                "ports": [
                    {"name": "ws", "port": 80, "targetPort": "ws"},
                    {"name": "metrics", "port": 8081, "targetPort": "metrics"},
                ],
            },
        }
        out = {"deployment": deployment, "service": service}
        hosts = int(spec.get("tpuHosts", 1))
        if hosts > 1:
            # Multi-host engine (one pjit program spanning pods): the
            # runtime replicas become a StatefulSet so each pod gets a
            # stable ordinal (= jax process_id, inferred from the
            # hostname by parallel/distributed.py), a headless service
            # names process 0 as the coordinator, and the engine's mesh
            # covers hosts × chips global devices.
            coord = (
                f"agent-{dep.name}-0.agent-{dep.name}-hosts."
                f"{dep.namespace}.svc:8476"
            )
            for c in pod_spec["containers"]:
                if c["name"] == "runtime":
                    c["env"] = env + [
                        {"name": "OMNIA_COORDINATOR_ADDR", "value": coord},
                        {"name": "OMNIA_NUM_PROCESSES", "value": str(hosts)},
                    ]
            deployment["kind"] = "StatefulSet"
            deployment["spec"]["serviceName"] = f"agent-{dep.name}-hosts"
            deployment["spec"]["replicas"] = hosts
            # Only the LEADER (ordinal 0) serves clients — followers
            # replicate its step stream (engine/multihost.py) and run no
            # facade surface. Route the client Service to pod-0 alone via
            # the per-pod StatefulSet label.
            service["spec"]["selector"] = {
                "statefulset.kubernetes.io/pod-name": f"agent-{dep.name}-0",
            }
            out["headless_service"] = {
                "apiVersion": "v1",
                "kind": "Service",
                "metadata": {"name": f"agent-{dep.name}-hosts",
                             "namespace": dep.namespace},
                "spec": {
                    "clusterIP": "None",
                    "selector": {"omnia/agent": dep.name},
                    "ports": [{"name": "coordinator", "port": 8476}],
                },
            }
        scaler = self.render_autoscaling(dep)
        if scaler is not None and hosts <= 1:
            out["autoscaling"] = scaler  # HPA cannot scale a multi-host set
        max_replicas = dep.replicas
        if scaler is not None:
            # An autoscaled replicas:1 agent still runs multiple pods at
            # peak — read the ceiling from the RENDERED scaler (HPA or
            # KEDA) so this never drifts from render_autoscaling's
            # defaulting rules.
            sspec = scaler.get("spec", {})
            max_replicas = max(
                max_replicas,
                int(sspec.get("maxReplicas")
                    or sspec.get("maxReplicaCount") or 1),
                int(sspec.get("minReplicas")
                    or sspec.get("minReplicaCount") or 1),
            )
        if max_replicas > 1 and hosts <= 1:
            # Voluntary-disruption floor (reference internal/controller/
            # pdb.go): node drains must leave at least one serving pod.
            # Multi-host sets get none — evicting ANY host breaks the
            # lockstep engine, so disruptions are all-or-nothing there.
            out["pdb"] = {
                "apiVersion": "policy/v1",
                "kind": "PodDisruptionBudget",
                "metadata": {"name": f"agent-{dep.name}",
                             "namespace": dep.namespace},
                "spec": {
                    "minAvailable": 1,
                    # track-scoped: a lone canary pod must not satisfy the
                    # floor while every stable pod is evicted.
                    "selector": {"matchLabels": {
                        "omnia/agent": dep.name, "omnia/track": "stable"}},
                },
            }
        return out

    def render_candidate(self, dep: AgentDeployment, candidate_hash: str,
                         weight: float) -> dict:
        """Cluster-side progressive delivery artifacts (reference
        rollout_candidate.go + rollout_istio.go): a candidate Deployment
        (track-labeled, 1 replica), a track-scoped Service, and an Istio
        VirtualService splitting traffic stable/candidate by the current
        step weight. The in-process backend does the same split with
        weighted endpoints; this is its kubectl-visible equivalent."""
        if int(dep.resource.spec.get("tpuHosts", 1)) > 1:
            raise ValueError(
                "progressive rollout is not supported for multi-host sets: "
                "a 1-replica candidate cannot join (or must not poison) the "
                "stable lockstep coordinator — roll multi-host models by "
                "deploying a second AgentRuntime"
            )
        base = self.render(dep)
        cand = copy.deepcopy(base["deployment"])
        cand["metadata"]["name"] = f"agent-{dep.name}-canary"
        cand["metadata"]["annotations"]["omnia/config-hash"] = candidate_hash
        for meta in (cand["metadata"],
                     cand["spec"]["template"]["metadata"]):
            meta.setdefault("labels", {})["omnia/track"] = "candidate"
        cand["spec"]["replicas"] = 1
        cand["spec"]["selector"]["matchLabels"]["omnia/track"] = "candidate"
        cand["spec"]["template"]["metadata"]["annotations"][
            "omnia/config-hash"] = candidate_hash
        stable_svc = copy.deepcopy(base["service"])
        stable_svc["metadata"]["name"] = f"agent-{dep.name}-stable"
        stable_svc["spec"]["selector"] = {
            "omnia/agent": dep.name, "omnia/track": "stable",
        }
        cand_svc = copy.deepcopy(base["service"])
        cand_svc["metadata"]["name"] = f"agent-{dep.name}-canary"
        cand_svc["spec"]["selector"] = {
            "omnia/agent": dep.name, "omnia/track": "candidate",
        }
        w = max(0, min(100, int(round(weight))))
        vs = {
            "apiVersion": "networking.istio.io/v1beta1",
            "kind": "VirtualService",
            "metadata": {"name": f"agent-{dep.name}",
                         "namespace": dep.namespace},
            "spec": {
                "hosts": [f"agent-{dep.name}"],
                "http": [{
                    "route": [
                        {"destination": {
                            "host": f"agent-{dep.name}-stable"},
                         "weight": 100 - w},
                        {"destination": {
                            "host": f"agent-{dep.name}-canary"},
                         "weight": w},
                    ],
                }],
            },
        }
        return {
            "candidate_deployment": cand,
            "stable_service": stable_svc,
            "candidate_service": cand_svc,
            "virtual_service": vs,
        }

    @staticmethod
    def render_autoscaling(dep: AgentDeployment):
        """HPA or KEDA ScaledObject from spec.autoscaling (reference
        autoscaling.go:74/:204). The north-star trigger is inference
        QUEUE DEPTH (the engine's backlog signal), not active connections:
        KEDA when scale-to-zero is requested (HPA cannot reach 0),
        plain HPA otherwise."""
        spec = dep.resource.spec.get("autoscaling")
        if not spec:
            return None
        min_r = int(spec.get("minReplicas", 1))
        max_r = int(spec.get("maxReplicas", max(min_r, 1)))
        target_depth = int(spec.get("queueDepthTarget", 8))
        if spec.get("scaleToZero"):
            return {
                "apiVersion": "keda.sh/v1alpha1",
                "kind": "ScaledObject",
                "metadata": {
                    "name": f"agent-{dep.name}",
                    "namespace": dep.namespace,
                },
                "spec": {
                    "scaleTargetRef": {"name": f"agent-{dep.name}"},
                    "minReplicaCount": 0,
                    "maxReplicaCount": max_r,
                    "triggers": [{
                        "type": "prometheus",
                        "metadata": {
                            "serverAddress": spec.get(
                                "prometheusAddress",
                                "http://prometheus.omnia-system.svc:9090",
                            ),
                            "query": (
                                "sum(omnia_runtime_queue_depth"
                                f'{{agent="{dep.name}"}})'
                            ),
                            "threshold": str(target_depth),
                        },
                    }],
                },
            }
        return {
            "apiVersion": "autoscaling/v2",
            "kind": "HorizontalPodAutoscaler",
            "metadata": {
                "name": f"agent-{dep.name}", "namespace": dep.namespace,
            },
            "spec": {
                "scaleTargetRef": {
                    "apiVersion": "apps/v1", "kind": "Deployment",
                    "name": f"agent-{dep.name}",
                },
                "minReplicas": max(min_r, 1),
                "maxReplicas": max_r,
                "metrics": [{
                    "type": "Pods",
                    "pods": {
                        "metric": {"name": "omnia_runtime_queue_depth"},
                        "target": {"type": "AverageValue",
                                   "averageValue": str(target_depth)},
                    },
                }],
            },
        }
