"""Resource store: the control plane's K8s-API equivalent.

Two backends, same watchable interface:
- MemoryResourceStore — in-process (tests, embedded control plane).
- FileResourceStore — a directory of YAML/JSON manifests, the
  reference's clusterless devroot mode (reference
  pkg/k8s/filebacked.go:36-42, examples/custom-runtime: any binary runs
  against a YAML devroot). `sync()` re-reads the tree so external edits
  (kubectl-apply-equivalent) are picked up.

Apply runs admission validation (validation.py) before committing —
fail-closed, like the reference's webhook chain. Watchers receive
(event, resource) callbacks: ADDED | MODIFIED | DELETED."""

from __future__ import annotations

import json
import os
import threading
from typing import Callable, Iterable, Optional

from omnia_tpu.operator.resources import Resource
from omnia_tpu.operator.validation import validate

Watcher = Callable[[str, Resource], None]


class ResourceStore:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._watchers: list[Watcher] = []

    # -- watch ---------------------------------------------------------

    def watch(self, fn: Watcher) -> None:
        with self._lock:
            self._watchers.append(fn)

    def _notify(self, event: str, res: Resource) -> None:
        with self._lock:
            watchers = list(self._watchers)
        for w in watchers:
            try:
                w(event, res)
            except Exception:  # watcher bugs must not break the store
                import logging

                logging.getLogger(__name__).exception("watcher failed")

    # -- CRUD (subclass provides storage) ------------------------------

    def apply(self, res: Resource) -> Resource:
        validate(res)
        prev = self.get(res.namespace, res.kind, res.name)
        if prev is not None:
            res.generation = prev.generation + 1
            res.created_at = prev.created_at
        self._put(res)
        self._notify("MODIFIED" if prev is not None else "ADDED", res)
        return res

    def update_status(self, res: Resource, status: dict) -> Resource:
        """Status-subresource write: no generation bump, no admission."""
        cur = self.get(res.namespace, res.kind, res.name)
        if cur is None:
            raise KeyError(res.key)
        cur.status = dict(status)
        self._put(cur)
        return cur

    def delete(self, namespace: str, kind: str, name: str) -> bool:
        res = self.get(namespace, kind, name)
        if res is None:
            return False
        self._remove(res)
        self._notify("DELETED", res)
        return True

    # storage primitives -------------------------------------------------

    def _put(self, res: Resource) -> None:
        raise NotImplementedError

    def _remove(self, res: Resource) -> None:
        raise NotImplementedError

    def get(self, namespace: str, kind: str, name: str) -> Optional[Resource]:
        raise NotImplementedError

    def list(
        self, kind: Optional[str] = None, namespace: Optional[str] = None
    ) -> list[Resource]:
        raise NotImplementedError


class MemoryResourceStore(ResourceStore):
    def __init__(self) -> None:
        super().__init__()
        self._items: dict[str, Resource] = {}

    def _put(self, res: Resource) -> None:
        with self._lock:
            self._items[res.key] = res

    def _remove(self, res: Resource) -> None:
        with self._lock:
            self._items.pop(res.key, None)

    def get(self, namespace: str, kind: str, name: str) -> Optional[Resource]:
        with self._lock:
            return self._items.get(f"{namespace}/{kind}/{name}")

    def list(
        self, kind: Optional[str] = None, namespace: Optional[str] = None
    ) -> list[Resource]:
        with self._lock:
            out = [
                r
                for r in self._items.values()
                if (kind is None or r.kind == kind)
                and (namespace is None or r.namespace == namespace)
            ]
        return sorted(out, key=lambda r: r.key)


def _load_manifest_file(path: str) -> Iterable[dict]:
    with open(path, encoding="utf-8") as f:
        raw = f.read()
    if path.endswith((".yaml", ".yml")):
        import yaml

        for doc in yaml.safe_load_all(raw):
            if doc:
                yield doc
    else:
        doc = json.loads(raw)
        yield from doc if isinstance(doc, list) else [doc]


class FileResourceStore(MemoryResourceStore):
    """Manifests under root/<namespace>/<Kind>/<name>.json (writes) plus
    any *.yaml|*.json dropped in the tree (reads via sync)."""

    def __init__(self, root: str) -> None:
        super().__init__()
        self.root = root
        os.makedirs(root, exist_ok=True)
        self.sync()

    def sync(self) -> int:
        """Re-read the manifest tree; returns how many resources loaded.
        External edits surface as ADDED/MODIFIED events."""
        n = 0
        for dirpath, _, files in os.walk(self.root):
            for fn in sorted(files):
                if not fn.endswith((".yaml", ".yml", ".json")):
                    continue
                path = os.path.join(dirpath, fn)
                try:
                    for doc in _load_manifest_file(path):
                        res = Resource.from_manifest(doc)
                        cur = self.get(res.namespace, res.kind, res.name)
                        if cur is None or cur.spec != res.spec:
                            # Route through admission + watch like apply,
                            # but keep file writes out (we just read it).
                            validate(res)
                            if cur is not None:
                                res.generation = cur.generation + 1
                                res.status = cur.status
                            MemoryResourceStore._put(self, res)
                            self._notify("MODIFIED" if cur else "ADDED", res)
                        n += 1
                except Exception:
                    import logging

                    logging.getLogger(__name__).exception("bad manifest %s", path)
        return n

    def _path(self, res: Resource) -> str:
        return os.path.join(self.root, res.namespace, res.kind, res.name + ".json")

    def _put(self, res: Resource) -> None:
        super()._put(res)
        path = self._path(res)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(res.to_manifest(), f, indent=2)
        os.replace(tmp, path)

    def _remove(self, res: Resource) -> None:
        super()._remove(res)
        try:
            os.remove(self._path(res))
        except FileNotFoundError:
            pass
