"""Rollout analysis: metric-gated promotion for canary candidates.

Reference internal/controller/rollout_analysis.go + the EE
RolloutAnalysis CRD: during a progressive rollout, the candidate track's
metrics are evaluated against declared thresholds; a violation rolls the
candidate back instead of promoting it. Metrics come straight from the
candidate pods' own registries (in-process pods expose them directly;
a cluster backend would scrape the same names over /metrics):

- `error-rate`     : turn errors / messages       (max: maxErrorRate)
- `p95-latency`    : facade turn_seconds p95      (max: maxP95LatencyS)
- `eval-pass-rate` : realtime eval results for the agent from session-api
                     (min: threshold)

`minSamples` (default 1) guards against deciding on no traffic: until
the candidate has served that many turns, analysis reports healthy
(the time-boxed rollout step is the traffic-accumulation window).
"""

from __future__ import annotations

import concurrent.futures
import json
import logging
import urllib.parse
import urllib.request
from typing import Optional

from omnia_tpu.operator.resources import Resource, ResourceKind, resolve_ref

logger = logging.getLogger(__name__)


class AnalysisFetchError(Exception):
    """session-api unreachable/errored — distinct from 'no eval data yet',
    so a declared eval gate fails closed instead of silently passing."""


class AnalysisRunner:
    def __init__(self, store, session_api_url: Optional[str] = None):
        self.store = store
        self.session_api_url = (session_api_url or "").rstrip("/")
        # Exposed for observability/tests: last evaluation per agent key.
        self.last_results: dict[str, list[dict]] = {}

    # -- metric collection --------------------------------------------

    @staticmethod
    def _candidate_counts(dep) -> tuple[float, float, float]:
        """(messages, errors, p95_latency_s) summed over candidate pods."""
        messages = errors = 0.0
        p95 = 0.0
        for pod in dep.candidate_pods:
            m = pod.facade.metrics
            messages += m.counter("messages_total").value()
            errors += m.counter("turn_errors_total").value()
            hist = m.histogram("turn_seconds")
            if hist.count:  # property, not method
                p95 = max(p95, hist.quantile(0.95))
        return messages, errors, p95

    # Bounded work per analysis tick: this runs on the controller's
    # reconcile thread, so total wall time must stay small even against a
    # slow session-api.
    _SESSION_SAMPLE = 20
    _FETCH_TIMEOUT_S = 3.0
    _FETCH_WORKERS = 8

    def _eval_pass_rate(
        self, agent: str, version: Optional[str]
    ) -> Optional[float]:
        """Pass rate over the candidate track's recent sessions.

        Scoped server-side to the agent and client-side to sessions the
        candidate pods served (attrs.track == "candidate", and the hash
        under analysis when known) — stable-track sessions must not
        dilute the canary verdict. Returns None only for the legitimate
        'no candidate eval data yet' case; infrastructure failures raise
        AnalysisFetchError (fail closed)."""
        if not self.session_api_url:
            return None
        # Track/version filtering happens SERVER-SIDE (attrs.* query
        # params): heavy stable-track traffic can push every candidate
        # session out of a recency-limited page, which would make this
        # return None ('no data yet') and silently pass a gate that DOES
        # have candidate data (ADVICE r2).
        query = (
            f"limit={self._SESSION_SAMPLE}"
            f"&agent={urllib.parse.quote(agent, safe='')}"
            "&attrs.track=candidate"
        )
        if version is not None:
            query += f"&attrs.version={urllib.parse.quote(str(version), safe='')}"
        try:
            with urllib.request.urlopen(
                f"{self.session_api_url}/api/v1/sessions?{query}",
                timeout=self._FETCH_TIMEOUT_S,
            ) as r:
                candidates = json.loads(r.read())["sessions"]
        except Exception as e:
            raise AnalysisFetchError(f"session listing failed: {e}") from e
        if not candidates:
            return None

        def fetch(sid: str) -> list[dict]:
            with urllib.request.urlopen(
                f"{self.session_api_url}/api/v1/sessions/"
                f"{urllib.parse.quote(sid, safe='')}/eval-results",
                timeout=self._FETCH_TIMEOUT_S,
            ) as r:
                return json.loads(r.read())["eval_results"]

        total = passed = 0
        with concurrent.futures.ThreadPoolExecutor(self._FETCH_WORKERS) as ex:
            futs = [ex.submit(fetch, s["session_id"]) for s in candidates]
            # Aggregate wait sized from the wave count with one wave of
            # slack: a healthy-but-slow session-api near the per-request
            # timeout must not trip fail-closed with zero headroom
            # (ADVICE r2: 3s*3 exactly equaled the worst legitimate case).
            waves = -(-len(futs) // self._FETCH_WORKERS)  # ceil
            done, not_done = concurrent.futures.wait(
                futs, timeout=self._FETCH_TIMEOUT_S * (waves + 1)
            )
            for f in not_done:
                f.cancel()
            if not_done:
                raise AnalysisFetchError(
                    f"{len(not_done)} eval-result fetches timed out"
                )
            for f in done:
                try:
                    results = f.result()
                except Exception as e:
                    raise AnalysisFetchError(f"eval-result fetch failed: {e}") from e
                for res in results:
                    total += 1
                    passed += bool(res.get("passed"))
        return (passed / total) if total else None

    # -- the analyzer hook --------------------------------------------

    def analyze(self, dep) -> bool:
        """Analyzer signature for RolloutEngine: True = candidate healthy.
        Falls back to the health-probe analyzer when the spec references
        no analysis."""
        from omnia_tpu.operator.rollout import _default_analyzer

        if not _default_analyzer(dep):
            return False  # a dead candidate fails regardless of metrics
        ref = (dep.resource.spec.get("rollout") or {}).get("analysis")
        if not ref:
            return True
        res = resolve_ref(
            self.store, dep.resource.namespace, ResourceKind.ROLLOUT_ANALYSIS, ref
        )
        if res is None:
            logger.warning("rollout analysis ref %r not found; failing closed", ref)
            return False  # declared analysis that can't run must not promote
        if res.status.get("phase") == "Blocked":
            # License-gated: a Blocked analysis must not silently grant the
            # EE feature (nor promote an unanalyzed candidate).
            logger.warning("rollout analysis %s is Blocked (unlicensed)", res.name)
            return False
        return self.evaluate(dep, res)

    def evaluate(self, dep, analysis: Resource) -> bool:
        spec = analysis.spec
        min_samples = int(spec.get("minSamples", 1))
        messages, errors, p95 = self._candidate_counts(dep)
        results: list[dict] = []
        healthy = True
        for metric in spec.get("metrics", []):
            name = metric.get("name", "")
            verdict: Optional[bool] = None
            observed: Optional[float] = None
            if name == "error-rate":
                if messages >= min_samples:
                    observed = errors / messages if messages else 0.0
                    verdict = observed <= float(metric.get("maxErrorRate", 1.0))
            elif name == "p95-latency":
                if messages >= min_samples:
                    observed = p95
                    verdict = observed <= float(metric.get("maxP95LatencyS", 1e9))
            elif name == "eval-pass-rate":
                version = (
                    dep.candidate_pods[0].version if dep.candidate_pods else None
                )
                try:
                    observed = self._eval_pass_rate(dep.resource.name, version)
                except AnalysisFetchError:
                    # A declared eval gate with an unreachable metrics
                    # source must not promote (same stance as a missing
                    # analysis ref).
                    logger.warning(
                        "eval pass-rate unavailable; failing closed",
                        exc_info=True,
                    )
                    verdict = False
                else:
                    if observed is not None:
                        verdict = observed >= float(metric.get("threshold", 0.0))
            else:
                # A misspelled metric must not promote ungated — same
                # fail-closed stance as a missing analysis ref.
                logger.warning("unknown analysis metric %r fails closed", name)
                verdict = False
            results.append({"name": name, "observed": observed,
                            "passed": verdict})
            if verdict is False:
                healthy = False
        self.last_results[dep.resource.key] = results
        return healthy

