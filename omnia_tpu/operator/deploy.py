"""Deploy API: versioned DeployIntent → applied resource set.

Reference internal/api/deploy/translate.go (cmd/SERVICE.md:17-21): a
single DeployIntent document (the dashboard's "deploy this agent"
payload) translates into PromptPack + ToolRegistry + AgentPolicy +
AgentRuntime resources applied atomically to the resource store. The
translation is versioned so older dashboards keep working."""

from __future__ import annotations

import dataclasses
import logging
from typing import Optional

from omnia_tpu.operator.resources import Resource
from omnia_tpu.operator.validation import ValidationError, validate

logger = logging.getLogger(__name__)

SUPPORTED_VERSIONS = ("v1",)


class DeployIntentError(ValueError):
    pass


@dataclasses.dataclass
class DeployResult:
    applied: list  # [Resource]
    agent: str
    namespace: str

    def to_dict(self) -> dict:
        return {
            "agent": self.agent,
            "namespace": self.namespace,
            "applied": [f"{r.kind}/{r.name}" for r in self.applied],
        }


def translate(intent: dict) -> list[Resource]:
    """DeployIntent → resources (not yet applied). Raises
    DeployIntentError on malformed intents."""
    version = intent.get("version", "v1")
    if version not in SUPPORTED_VERSIONS:
        raise DeployIntentError(f"unsupported intent version {version!r}")
    name = intent.get("name")
    if not name:
        raise DeployIntentError("intent.name required")
    namespace = intent.get("namespace", "default")
    pack_content = intent.get("pack")
    if not pack_content:
        raise DeployIntentError("intent.pack required")

    out: list[Resource] = []
    pack_name = f"{name}-pack"
    out.append(
        Resource(kind="PromptPack", name=pack_name, namespace=namespace,
                 spec={"content": pack_content})
    )

    registry_ref = None
    if intent.get("tools"):
        registry_ref = f"{name}-tools"
        out.append(
            Resource(kind="ToolRegistry", name=registry_ref, namespace=namespace,
                     spec={"tools": [_normalize_tool(t) for t in intent["tools"]]})
        )

    if intent.get("policy"):
        out.append(
            Resource(kind="AgentPolicy", name=f"{name}-policy", namespace=namespace,
                     spec=dict(intent["policy"]))
        )

    providers = intent.get("providers")
    if not providers:
        if not intent.get("provider"):
            raise DeployIntentError("intent.provider (or providers[]) required")
        providers = [{"name": "main", "providerRef": intent["provider"]}]
    agent_spec = {
        "mode": intent.get("mode", "agent"),
        "promptPackRef": pack_name,
        "providers": providers,
        "facades": intent.get("facades", [{"type": "websocket"}]),
    }
    if registry_ref:
        agent_spec["toolRegistryRef"] = registry_ref
    for key in ("replicas", "autoscaling", "rollout", "memory", "podOverrides", "context"):
        if key in intent:
            agent_spec[key] = intent[key]
    out.append(
        Resource(kind="AgentRuntime", name=name, namespace=namespace, spec=agent_spec)
    )
    return out


def _normalize_tool(t: dict) -> dict:
    """Accept both the canonical shape ({name, handler: {type, ...}}) and
    the dashboard's flat shape ({name, type, url, ...})."""
    if "handler" in t:
        return dict(t)
    out = {"name": t.get("name"), "description": t.get("description", "")}
    handler = {k: v for k, v in t.items() if k not in ("name", "description")}
    out["handler"] = handler
    return out


def deploy(store, intent: dict) -> DeployResult:
    """Translate + validate ALL resources, then apply (all-or-nothing on
    validation — the store apply itself is last so a bad intent never
    half-lands)."""
    resources = translate(intent)
    for res in resources:
        try:
            validate(res)
        except ValidationError as e:
            raise DeployIntentError(f"{res.kind}/{res.name}: {e}") from e
    applied = [store.apply(res) for res in resources]
    agent = next(r for r in applied if r.kind == "AgentRuntime")
    return DeployResult(applied=applied, agent=agent.name, namespace=agent.namespace)
