"""Controller manager: reconcilers over the resource store.

The reconcile flow mirrors the reference's AgentRuntimeReconciler
(reference internal/controller/agentruntime_controller.go:479 →
:523 reconcileReferences → :539 reconcileResources → :548
enforceCapabilities → :551 reconcileRollout → :566 reconcileAutoscaling
→ :630 status update), plus Provider and PromptPack reconcilers. Watch
events enqueue keys into a work queue drained by `reconcile_once` /
`run` — level-triggered like controller-runtime: each pass recomputes
from current state."""

from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Optional

from omnia_tpu.operator.deployment import AgentDeployment, InProcessPodBackend
from omnia_tpu.operator.resources import EE_KINDS, Resource, ResourceKind, resolve_ref
from omnia_tpu.operator.rollout import RolloutEngine
from omnia_tpu.operator.scaling_controller import _AutoscaleMixin
from omnia_tpu.operator.sources_controller import _SourceReconcilersMixin
from omnia_tpu.operator.store import ResourceStore

logger = logging.getLogger(__name__)


def warmup_progress_message(warmup: dict) -> str:
    """Render a Health.warmup snapshot (engine/coldstart.py) into the
    one-line staged-readiness condition message the operator writes —
    e.g. ``phase=warmup_compile, programs 12/40, weights 1.2/16.1 GB``.
    Tolerates partial/empty dicts (legacy runtimes send no warmup)."""
    if not warmup:
        return "phase=unknown (runtime reports no warmup progress)"
    parts = [f"phase={warmup.get('phase', 'unknown')}"]
    total = int(warmup.get("programs_total") or 0)
    if total:
        parts.append(f"programs {int(warmup.get('programs_done') or 0)}/{total}")
    wtotal = int(warmup.get("weights_bytes_total") or 0)
    if wtotal:
        loaded = int(warmup.get("weights_bytes_loaded") or 0)
        parts.append(f"weights {loaded / 1e9:.1f}/{wtotal / 1e9:.1f} GB")
    return ", ".join(parts)


class ControllerManager(_AutoscaleMixin, _SourceReconcilersMixin):
    def __init__(
        self,
        store: ResourceStore,
        backend: Optional[InProcessPodBackend] = None,
        session_api_url: Optional[str] = None,
        capability_probe_timeout_s: float = 600.0,
        wait_ready: bool = True,
        license_manager=None,
        arena: Optional["object"] = None,
    ) -> None:
        from omnia_tpu.license import CommunityLicenseManager

        self.store = store
        self.backend = backend or InProcessPodBackend()
        self.session_api_url = session_api_url
        self.capability_probe_timeout_s = capability_probe_timeout_s
        self.wait_ready = wait_ready
        # Metric-gated canary analysis (RolloutAnalysis resources) wraps
        # the default health-probe analyzer.
        from omnia_tpu.operator.analysis import AnalysisRunner

        self.analysis = AnalysisRunner(store, session_api_url=session_api_url)
        self.rollouts = RolloutEngine(self.backend, analyzer=self.analysis.analyze)
        self.deployments: dict[str, AgentDeployment] = {}
        # Per-deployment FleetScaler (engine/fleet.py, imported lazily —
        # the fleet module imports this package's autoscaling policy):
        # the SAME queue-depth control loop the in-process coordinator
        # fleets run, applied here through the pod backend's
        # current()/scale_to() provisioner callback.
        self._autoscalers: dict[str, object] = {}
        # EE plane: license gates reconciliation of enterprise kinds
        # (reference ee/pkg/setup registration behind --enterprise +
        # license activation); the shared policy evaluator is rebuilt from
        # ToolPolicy resources and consumed by policy brokers.
        self.license = license_manager or CommunityLicenseManager()
        self.arena = arena  # evals.arena.ArenaJobController (lazy default)
        self.policy_evaluator = None  # policy.broker.PolicyEvaluator
        from omnia_tpu.operator.workspace import InProcessWorkspaceBackend

        self.workspaces = InProcessWorkspaceBackend()
        self._queue: "queue.Queue[tuple[str, str, str]]" = queue.Queue()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # In-flight ToolRegistry probes (network dials run off-thread).
        self._probe_threads: dict[str, threading.Thread] = {}
        self._probe_lock = threading.Lock()
        store.watch(self._on_event)

    # -- watch fan-in ---------------------------------------------------

    def _on_event(self, event: str, res: Resource) -> None:
        if res.kind == ResourceKind.AGENT_RUNTIME.value:
            self._queue.put((res.namespace, res.kind, res.name))
        elif res.kind in (
            ResourceKind.PROVIDER.value,
            ResourceKind.PROMPT_PACK.value,
            ResourceKind.TOOL_REGISTRY.value,
            ResourceKind.SKILL_SOURCE.value,
        ):
            # Cross-resource fan-in: requeue every AgentRuntime that might
            # reference this (reference agentruntime_watches.go).
            self._queue.put((res.namespace, res.kind, res.name))
            for ar in self.store.list(ResourceKind.AGENT_RUNTIME.value, res.namespace):
                self._queue.put((ar.namespace, ar.kind, ar.name))
        elif res.kind == "HTTPRoute":
            # Route observation (reference facade_route.go watch): a
            # route appearing/changing re-derives every agent's public
            # endpoints in the namespace; the route itself has no
            # reconcile of its own.
            for ar in self.store.list(ResourceKind.AGENT_RUNTIME.value, res.namespace):
                self._queue.put((ar.namespace, ar.kind, ar.name))
        elif res.kind in EE_KINDS or res.kind == ResourceKind.WORKSPACE.value:
            self._queue.put((res.namespace, res.kind, res.name))

    # -- run loop -------------------------------------------------------

    def run(self, resync_s: float = 5.0) -> None:
        self._thread = threading.Thread(
            target=self._loop, args=(resync_s,), daemon=True
        )
        self._thread.start()

    def _loop(self, resync_s: float) -> None:
        last_resync = 0.0
        while not self._stop.is_set():
            try:
                key = self._queue.get(timeout=0.25)
                self.reconcile_key(*key)
            except queue.Empty:
                pass
            if time.monotonic() - last_resync >= resync_s:
                last_resync = time.monotonic()
                try:
                    self.resync()
                except Exception:  # the reconcile thread must never die
                    logger.exception("resync failed; retrying next tick")

    def shutdown(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
        for dep in self.deployments.values():
            for p in dep.pods + dep.candidate_pods:
                try:
                    p.stop()
                except Exception:
                    pass  # best-effort pod teardown
        self.deployments.clear()
        self.workspaces.shutdown()

    def drain_queue(self) -> None:
        """Process every queued key (tests / single-step operation)."""
        while True:
            try:
                key = self._queue.get_nowait()
            except queue.Empty:
                self.join_probes()
                return
            self.reconcile_key(*key)

    def resync(self) -> None:
        """Periodic level-trigger: autoscale + rollout ticks + status."""
        # Devroot mode: re-read the manifest tree so external edits are
        # the kubectl-apply equivalent (FileResourceStore.sync fires
        # ADDED/MODIFIED events into the work queue).
        sync = getattr(self.store, "sync", None)
        if callable(sync):
            try:
                sync()
            except Exception:
                logger.exception("store sync failed")
        for ar in self.store.list(ResourceKind.AGENT_RUNTIME.value):
            self.reconcile_agent_runtime(ar)
        # Running arena jobs fold queue results on the same tick; Blocked
        # EE resources re-check the gate — a license activated at runtime
        # (POST /api/v1/license/activate) fires no store event, so the
        # level-trigger is what unblocks them.
        for aj in self.store.list(ResourceKind.ARENA_JOB.value):
            if aj.status.get("phase") in ("", "Pending", "Running", "Blocked", None):
                self.reconcile_arena_job(aj)
        for kind in (
            ResourceKind.TOOL_POLICY.value,
            ResourceKind.SESSION_PRIVACY_POLICY.value,
            ResourceKind.ROLLOUT_ANALYSIS.value,
        ):
            for res in self.store.list(kind):
                if res.status.get("phase") in ("Blocked", "", None):
                    self.reconcile_key(res.namespace, res.kind, res.name)
        # Workspaces recover from transient reconcile failures on the same
        # level-trigger as everything else.
        for ws in self.store.list(ResourceKind.WORKSPACE.value):
            if ws.status.get("phase") in ("Error", "", None):
                self.reconcile_workspace(ws)
        # Source kinds re-sync on their declared interval (reference
        # promptpacksource_controller.go requeue-after) and retry errors.
        for kind, fn in (
            (ResourceKind.PROMPT_PACK_SOURCE.value, self.reconcile_prompt_pack_source),
            (ResourceKind.ARENA_SOURCE.value, self.reconcile_arena_source),
            (ResourceKind.ARENA_TEMPLATE_SOURCE.value, self.reconcile_arena_source),
            (ResourceKind.SKILL_SOURCE.value, self.reconcile_skill_source),
        ):
            for src in self.store.list(kind):
                synced = float(src.status.get("syncedAt") or 0.0)
                interval = float(src.spec.get("interval_s", 60.0))
                if (
                    src.status.get("phase") != "Ready"
                    or time.time() - synced >= interval
                ):
                    fn(src)
        for ads in self.store.list(ResourceKind.ARENA_DEV_SESSION.value):
            if ads.status.get("phase") in ("Ready", "Blocked", "", None):
                self.reconcile_arena_dev_session(ads)
        # ToolRegistry reachability is a LIVE property: re-probe on the
        # declared interval (reference toolregistry_probe.go requeue-
        # after), so a backend that dies after apply flips the phase.
        for tr in self.store.list(ResourceKind.TOOL_REGISTRY.value):
            probe_cfg = tr.spec.get("probe", {}) or {}
            if not probe_cfg.get("enabled", True):
                # Probe-disabled registries still need their declared-only
                # status ONCE — a devroot manifest synced before the
                # watcher subscribed otherwise never gets a phase at all.
                if not tr.status.get("phase"):
                    self.reconcile_tool_registry(tr)
                continue
            interval = float(probe_cfg.get("intervalSeconds", 60.0))
            last = float(tr.status.get("lastProbeAt") or 0.0)
            if time.time() - last >= interval:
                self.reconcile_tool_registry(tr)

    # -- reconcilers ----------------------------------------------------

    def reconcile_key(self, namespace: str, kind: str, name: str) -> None:
        res = self.store.get(namespace, kind, name)
        if res is None:
            if kind == ResourceKind.AGENT_RUNTIME.value:
                self._teardown(f"{namespace}/{kind}/{name}")
            elif kind == ResourceKind.TOOL_POLICY.value:
                # A deleted policy's rules must stop being enforced NOW —
                # a stale allow-override lingering in the evaluator is a
                # security hole.
                self._rebuild_policy_evaluator()
            elif kind == ResourceKind.WORKSPACE.value:
                self.workspaces.teardown(f"{namespace}/{kind}/{name}")
            return
        if kind == ResourceKind.AGENT_RUNTIME.value:
            self.reconcile_agent_runtime(res)
        elif kind == ResourceKind.PROVIDER.value:
            self.reconcile_provider(res)
        elif kind == ResourceKind.PROMPT_PACK.value:
            self.reconcile_prompt_pack(res)
        elif kind == ResourceKind.ARENA_JOB.value:
            self.reconcile_arena_job(res)
        elif kind == ResourceKind.TOOL_POLICY.value:
            self.reconcile_tool_policies(res)
        elif kind == ResourceKind.WORKSPACE.value:
            self.reconcile_workspace(res)
        elif kind == ResourceKind.TOOL_REGISTRY.value:
            self.reconcile_tool_registry(res)
        elif kind == ResourceKind.SKILL_SOURCE.value:
            self.reconcile_skill_source(res)
        elif kind == ResourceKind.PROMPT_PACK_SOURCE.value:
            self.reconcile_prompt_pack_source(res)
        elif kind in (
            ResourceKind.ARENA_SOURCE.value,
            ResourceKind.ARENA_TEMPLATE_SOURCE.value,
        ):
            self.reconcile_arena_source(res)
        elif kind == ResourceKind.ARENA_DEV_SESSION.value:
            self.reconcile_arena_dev_session(res)
        elif kind in (
            ResourceKind.SESSION_PRIVACY_POLICY.value,
            ResourceKind.ROLLOUT_ANALYSIS.value,
        ):
            self.reconcile_ee_passive(res)

    def reconcile_provider(self, res: Resource) -> None:
        """Credential/model validation → phase (reference
        provider_controller.go → phase Ready/Error)."""
        spec = res.spec
        phase, msg = "Ready", ""
        if spec.get("type") == "tpu":
            from omnia_tpu.models import PRESETS

            if spec.get("model") not in PRESETS:
                phase, msg = "Error", f"unknown model preset {spec.get('model')!r}"
        self.store.update_status(res, {"phase": phase, "message": msg})

    def reconcile_prompt_pack(self, res: Resource) -> None:
        from omnia_tpu.runtime.packs import validate_pack

        errs = validate_pack(res.spec.get("content") or {})
        self.store.update_status(
            res,
            {
                "phase": "Error" if errs else "Ready",
                "message": "; ".join(errs),
                "version": (res.spec.get("content") or {}).get("version", ""),
            },
        )

    def reconcile_workspace(self, res: Resource) -> None:
        """Per-service-group data planes (reference
        workspace_services.go:72-365): real in-process session/memory-api
        instances per group; endpoints land in status."""
        try:
            endpoints = self.workspaces.reconcile(res)
        except Exception as e:
            self.store.update_status(res, {"phase": "Error", "message": str(e)})
            return
        self.store.update_status(res, {
            "phase": "Ready",
            "environment": res.spec.get("environment", ""),
            "serviceGroups": endpoints,
        })

    # -- EE reconcilers -------------------------------------------------

    def _license_gate(self, res: Resource, feature: str) -> bool:
        if self.license.licensed(feature):
            return True
        self.store.update_status(res, {
            "phase": "Blocked",
            "message": f"feature {feature!r} requires an enterprise license",
        })
        return False

    def reconcile_arena_job(self, res: Resource) -> None:
        """ArenaJob → partition matrix → work queue → poll results
        (reference ee/internal/controller/arenajob_controller.go:199)."""
        if not self._license_gate(res, "arena"):
            return
        from omnia_tpu.evals.arena import ArenaJobController
        from omnia_tpu.evals.defs import ArenaJobSpec

        if self.arena is None:
            self.arena = ArenaJobController()
        name = f"{res.namespace}/{res.name}"
        try:
            if not self.arena.has(name):
                spec_doc = dict(res.spec)
                spec_doc["name"] = name
                sf = spec_doc.pop("scenariosFrom", None)
                if sf and not spec_doc.get("scenarios"):
                    # Scenarios from a synced ArenaSource (reference arena
                    # content sync → worker PVC; here the shared sync root).
                    import json as _json

                    key = (
                        f"{ResourceKind.ARENA_SOURCE.value.lower()}-"
                        f"{res.namespace}-{sf['name']}"
                    )
                    raw = self._syncer().read(key, sf.get("path", "scenarios.json"))
                    spec_doc["scenarios"] = _json.loads(raw)
                self.arena.submit(ArenaJobSpec.from_dict(spec_doc))
            status = self.arena.reconcile(name)
        except Exception as e:
            self.store.update_status(res, {"phase": "Error", "message": str(e)})
            return
        self.store.update_status(res, status.to_dict())

    def _rebuild_policy_evaluator(self) -> list[str]:
        from omnia_tpu.policy.broker import PolicyEvaluator, ToolPolicy

        policies = []
        errs = []
        for tp in self.store.list(kind=ResourceKind.TOOL_POLICY.value):
            try:
                policies.append(ToolPolicy.from_dict(
                    {"name": tp.name, **tp.spec}))
            except Exception as e:
                errs.append(f"{tp.name}: {e}")
        self.policy_evaluator = PolicyEvaluator(policies)
        return errs

    def reconcile_tool_registry(self, res: Resource) -> None:
        """Probe each tool handler's endpoint and surface per-tool status
        + a registry phase (reference toolregistry_probe.go:53 +
        toolregistry_types.go:661-673). The probe dials real sockets, so
        it runs OFF the reconcile thread — network timeouts must not
        stall every other kind's reconcile behind a ToolRegistry event.
        drain_queue() joins in-flight probes so tests stay synchronous.
        spec.probe.enabled=False skips probing (tools report Unknown,
        phase Ready — declared-only)."""
        key = f"{res.namespace}/{res.name}"
        with self._probe_lock:
            existing = self._probe_threads.get(key)
            if existing is not None and existing.is_alive():
                return  # a probe for this registry is already in flight
            t = threading.Thread(
                target=self._probe_tool_registry, args=(res,),
                name=f"toolprobe-{key}", daemon=True,
            )
            self._probe_threads[key] = t
        t.start()

    def _probe_tool_registry(self, res: Resource) -> None:
        from omnia_tpu.operator import toolprobe

        tools = res.spec.get("tools", [])
        probe_cfg = res.spec.get("probe", {}) or {}
        if probe_cfg.get("enabled", True):
            statuses = toolprobe.probe_tools(
                tools, timeout_s=float(probe_cfg.get("timeoutSeconds", 2.0))
            )
            phase = toolprobe.phase_of(statuses)
        else:
            statuses = [{
                "name": t.get("name", ""),
                "handlerType": (t.get("handler") or {}).get("type", "http"),
                "status": toolprobe.STATUS_UNKNOWN,
            } for t in tools]
            phase = toolprobe.PHASE_READY if tools else toolprobe.PHASE_PENDING
        down = [t["name"] for t in statuses
                if t["status"] == toolprobe.STATUS_UNAVAILABLE]
        try:
            self.store.update_status(res, {
                "phase": phase,
                "discoveredToolsCount": len(tools),
                "tools": statuses,
                "lastProbeAt": time.time(),
                "message": f"unreachable: {', '.join(down)}" if down else "",
            })
        except KeyError:
            # The registry was deleted while its probe was in flight —
            # nothing to report against.
            pass
        finally:
            with self._probe_lock:
                key = f"{res.namespace}/{res.name}"
                if self._probe_threads.get(key) is threading.current_thread():
                    self._probe_threads.pop(key, None)

    def join_probes(self, timeout_s: float = 30.0) -> None:
        """Wait for in-flight ToolRegistry probes (tests/drain)."""
        with self._probe_lock:
            threads = list(self._probe_threads.values())
        deadline = time.monotonic() + timeout_s
        for t in threads:
            t.join(timeout=max(0.1, deadline - time.monotonic()))

    def reconcile_tool_policies(self, res: Resource) -> None:
        """Rebuild the shared evaluator from ALL ToolPolicy resources (the
        reference policy broker's list-and-poll watcher,
        ee/pkg/policy/watcher.go:26-108)."""
        if not self._license_gate(res, "policy-broker"):
            return
        errs = self._rebuild_policy_evaluator()
        self.store.update_status(res, {
            "phase": "Error" if errs else "Ready",
            "message": "; ".join(errs),
            "policiesLoaded": len(self.policy_evaluator.policies),
        })

    def reconcile_ee_passive(self, res: Resource) -> None:
        """SessionPrivacyPolicy / RolloutAnalysis: admission already
        validated the spec; consumers resolve them by ref (recording
        interceptor, rollout analysis runs) — reconcile just marks Ready
        under license."""
        feature = (
            "privacy-api"
            if res.kind == ResourceKind.SESSION_PRIVACY_POLICY.value
            else "arena"
        )
        if not self._license_gate(res, feature):
            return
        status = {"phase": "Ready", "message": ""}
        if res.kind == ResourceKind.SESSION_PRIVACY_POLICY.value:
            # Compliance presets expand server-side (reference
            # ee/pkg/compliance/presets.go): consumers read the effective
            # policy from status, never re-derive regime rules.
            from omnia_tpu.privacy.compliance import expand_preset

            try:
                status["effective"] = expand_preset(res.spec)
            except ValueError as e:
                status = {"phase": "Error", "message": str(e)}
        self.store.update_status(res, status)

    def reconcile_agent_runtime(self, res: Resource) -> None:
        key = res.key
        refs = self._resolve_refs(res)
        if refs is None:
            return  # status already written by _resolve_refs
        pack_doc, provider_specs, default_provider, tool_configs = refs

        dep = self.deployments.get(key)
        if dep is None:
            dep = AgentDeployment(
                resource=res,
                pack_doc=pack_doc,
                provider_specs=provider_specs,
                default_provider=default_provider,
                tool_configs=tool_configs,
                session_api_url=self.session_api_url,
                required_capabilities=self._required_capabilities(res, tool_configs),
                replicas=res.spec.get("replicas", 1),
            )
            dep.stable_hash = dep.config_hash()
            self.deployments[key] = dep
            self.backend.scale(dep, dep.replicas, wait_ready=self.wait_ready)
        else:
            dep.resource = res
            dep.pack_doc = pack_doc
            dep.provider_specs = provider_specs
            dep.default_provider = default_provider
            dep.tool_configs = tool_configs
            dep.required_capabilities = self._required_capabilities(res, tool_configs)
            dep.replicas = res.spec.get("replicas", 1)

        # Capability gate (reference capability_gate.go:125): scale to 0
        # until a running runtime advertises what the spec requires. The
        # gate LATCHES on the probed config hash — otherwise the next
        # resync would see zero pods, un-gate, scale up, and flap.
        gate_key = dep.config_hash() + "|" + ",".join(sorted(dep.required_capabilities))
        if dep.gate_blocked_hash == gate_key:
            self._write_blocked(res, dep, "latched: config unchanged since probe")
            return
        if dep.gate_blocked_hash:
            dep.gate_blocked_hash = ""  # config changed: re-admit and re-probe
            if not dep.pods and not dep.candidate_pods:
                self.backend.scale(dep, max(1, dep.replicas), wait_ready=self.wait_ready)
        gated, missing, warming = self._capability_gate(dep)
        if warming is not None:
            # Staged readiness (engine/coldstart.py → Health.warmup): the
            # runtime is still warming — surface WHICH phase and how far
            # instead of silently re-probing until a 600 s timeout, and
            # don't gate on capabilities it cannot advertise yet. The
            # next resync re-probes; progress updates in place.
            self._write_status(
                res, dep, phase="Starting",
                conditions=[{
                    "type": "CapabilitiesSatisfied", "status": "Unknown",
                    "message": f"runtime warming up: {warming}",
                }],
            )
            return
        if gated:
            dep.gate_blocked_hash = gate_key
            self.backend.scale(dep, 0)
            self._write_blocked(
                res, dep, f"runtime missing capabilities: {missing}"
            )
            return

        # Rollout on config change.
        self.rollouts.tick(dep)

        # Autoscaling on queue depth + connections.
        self._autoscale(key, dep)

        self._write_status(
            res,
            dep,
            phase="Running" if dep.pods or dep.candidate_pods else "Idle",
            conditions=[
                {"type": "CapabilitiesSatisfied", "status": "True", "message": ""}
            ],
        )

    # -- pieces ---------------------------------------------------------

    def _resolve_refs(self, res: Resource):
        ns = res.namespace
        pack = resolve_ref(self.store, ns, ResourceKind.PROMPT_PACK, res.spec.get("promptPackRef"))
        if pack is None:
            self._write_ref_error(res, "promptPackRef not found")
            return None
        provider_specs: list[dict] = []
        default_provider = ""
        for entry in res.spec.get("providers", []):
            pres = resolve_ref(self.store, ns, ResourceKind.PROVIDER, entry.get("providerRef"))
            if pres is None:
                self._write_ref_error(
                    res, f"providerRef {entry.get('providerRef')} not found"
                )
                return None
            spec = {
                "name": entry["name"],
                "type": pres.spec.get("type", "tpu"),
                "role": pres.spec.get("role", "llm"),
                "model": pres.spec.get("model", ""),
                "options": pres.spec.get("options", {}),
                "input_cost_per_mtok": pres.spec.get("pricing", {}).get("inputPerMTok", 0.0),
                "output_cost_per_mtok": pres.spec.get("pricing", {}).get("outputPerMTok", 0.0),
            }
            if not spec["model"]:
                spec.pop("model")
            provider_specs.append(spec)
            if entry.get("default") or not default_provider:
                default_provider = entry["name"]
        tool_configs: list[dict] = []
        treg = resolve_ref(self.store, ns, ResourceKind.TOOL_REGISTRY, res.spec.get("toolRegistryRef"))
        if res.spec.get("toolRegistryRef") and treg is None:
            self._write_ref_error(res, "toolRegistryRef not found")
            return None
        if treg is not None:
            tool_configs = treg.spec.get("tools", [])
        content, skill_err = self._merge_pack_skills(ns, pack.spec["content"])
        if skill_err is not None:
            self._write_ref_error(res, skill_err)
            return None
        return content, provider_specs, default_provider, tool_configs

    def _required_capabilities(self, res: Resource, tool_configs: list[dict]) -> list[str]:
        from omnia_tpu.runtime.contract import Capability as C

        req = [C.TEXT.value, C.STREAMING.value, C.RESUME.value]
        if res.spec.get("mode", "agent") == "function":
            req.append(C.FUNCTIONS.value)
        if tool_configs:
            req.append(C.TOOLS.value)
            if any(t.get("handler", {}).get("type") == "client" for t in tool_configs):
                req.append(C.CLIENT_TOOLS.value)
        return req

    def _capability_gate(self, dep: AgentDeployment):
        """Probe the first live runtime's Health; returns
        ``(gated, missing, warming)``. Gate if advertised capabilities
        miss anything required; ``warming`` (a progress string) is
        non-None while the runtime reports "initializing" — the staged
        cold-start signal, during which capability absence means
        "not ready yet", never "missing". No pods yet → not gated
        (nothing to probe; scale-up proceeds and the next resync probes)."""
        pods = dep.pods + dep.candidate_pods
        if not pods:
            return False, [], None
        from omnia_tpu.runtime.client import RuntimeClient

        try:
            client = RuntimeClient(f"localhost:{pods[0].runtime_port}")
            try:
                h = client.health(timeout=self.capability_probe_timeout_s)
            finally:
                client.close()
        except Exception as e:
            logger.warning("capability probe failed for %s: %s", dep.name, e)
            return False, [], None  # unreachable ≠ missing; retry next resync
        if h.status == "initializing":
            return False, [], warmup_progress_message(
                getattr(h, "warmup", None) or {}
            )
        missing = sorted(set(dep.required_capabilities) - set(h.capabilities))
        return bool(missing), missing, None

    def _write_blocked(self, res: Resource, dep, msg: str) -> None:
        self._write_status(
            res,
            dep,
            phase="Blocked",
            conditions=[
                {
                    "type": "CapabilitiesSatisfied",
                    "status": "False",
                    "message": msg,
                }
            ],
        )

    def _write_ref_error(self, res: Resource, msg: str) -> None:
        self.store.update_status(
            res,
            {
                "phase": "Pending",
                "conditions": [
                    {"type": "ReferencesResolved", "status": "False", "message": msg}
                ],
            },
        )

    def _route_endpoints(self, res) -> list[dict]:
        """Public endpoints observed from Gateway-API HTTPRoutes whose
        backendRefs target this agent's Service (reference
        internal/controller/facade_endpoints.go + facade_route.go): each
        route hostname × matching rule path becomes a public URL in
        status.facade.endpoints."""
        svc = f"agent-{res.name}"
        out: list[dict] = []
        for route in self.store.list("HTTPRoute", res.namespace):
            for rule in route.spec.get("rules", []) or []:
                # Admission validates shape, but a reconcile crash here
                # would kill the controller loop — stay defensive against
                # resources that predate (or bypass) validation.
                if not isinstance(rule, dict):
                    continue
                refs = rule.get("backendRefs") or []
                if not isinstance(refs, list):
                    continue
                if not any(isinstance(r, dict) and r.get("name") == svc
                           for r in refs):
                    continue
                # EVERY match path contributes an endpoint (hostname ×
                # path); non-dict path shapes are skipped, not crashed on.
                paths = []
                for m in (rule.get("matches") or []):
                    if isinstance(m, dict) and isinstance(m.get("path"), dict):
                        paths.append(m["path"].get("value", "") or "")
                if not paths:
                    paths = [""]
                for host in route.spec.get("hostnames") or []:
                    if host == "*":
                        continue  # wildcard hosts carry no usable URL
                    for path in paths:
                        out.append({
                            "url": f"https://{host}{path}",
                            "source": "httproute",
                            "route": route.name,
                        })
        # Deterministic + deduped (two rules can repeat a hostname).
        seen: set[str] = set()
        uniq = []
        for e in sorted(out, key=lambda e: (e["url"], e["route"])):
            if e["url"] not in seen:
                seen.add(e["url"])
                uniq.append(e)
        return uniq

    def _write_status(self, res, dep, phase: str, conditions: list[dict]) -> None:
        pod_endpoints = [
            {"url": url, "weight": w} for url, w in dep.endpoints()
        ]
        st = {
            "phase": phase,
            "replicas": len(dep.pods),
            "candidateReplicas": len(dep.candidate_pods),
            "endpoints": pod_endpoints,
            # Reference status.facade.endpoints: the PUBLIC addresses —
            # HTTPRoute-derived URLs first, direct pod endpoints as the
            # fallback when no route fronts the agent.
            "facade": {
                "endpoints": (self._route_endpoints(res) or pod_endpoints),
            },
            "configHash": dep.stable_hash,
            "conditions": conditions,
            "rollout": self.rollouts.state(dep).to_status(),
        }
        try:
            self.store.update_status(res, st)
        except KeyError:
            pass  # deleted mid-reconcile

    def _teardown(self, key: str) -> None:
        dep = self.deployments.pop(key, None)
        if dep is None:
            return
        for p in dep.pods + dep.candidate_pods:
            try:
                p.stop()
            except Exception:
                logger.exception("pod stop failed during teardown")
        self._autoscalers.pop(key, None)
        logger.info("deployment %s torn down", key)
