"""Progressive delivery: candidate pods, stepped traffic, analysis,
auto-rollback.

Reference shape: internal/controller/rollout*.go — a config change spawns
a candidate Deployment; traffic shifts through spec.rollout.steps[]
weights; each step runs metric analysis; failure rolls back, completion
promotes the candidate to stable. Version-triggered rollouts fire when
the PromptPack resolves to a new version (rollout_version_trigger.go).

Here the state machine is explicit and tick-driven so it is testable
without a cluster: the controller calls `tick()` on its resync loop, the
analyzer is injectable (default: facade error-rate + eval pass-rate from
the session store)."""

from __future__ import annotations

import enum
import logging
import time
from dataclasses import dataclass, field
from typing import Callable, Optional

from omnia_tpu.operator.deployment import AgentDeployment

logger = logging.getLogger(__name__)


class RolloutPhase(str, enum.Enum):
    IDLE = "Idle"
    PROGRESSING = "Progressing"
    PROMOTED = "Promoted"
    ROLLED_BACK = "RolledBack"


@dataclass
class RolloutStep:
    weight: float
    hold_s: float = 0.0  # dwell before analysis+advance

    @classmethod
    def from_spec(cls, d: dict) -> "RolloutStep":
        # `pause_s` is the published CRD key (operator/crds.py); the
        # holdSeconds spelling is accepted for compatibility.
        return cls(
            weight=float(d["weight"]),
            hold_s=float(d.get("pause_s", d.get("holdSeconds", 0.0))),
        )


@dataclass
class RolloutState:
    phase: RolloutPhase = RolloutPhase.IDLE
    candidate_hash: str = ""
    step_index: int = -1
    step_entered_at: float = 0.0
    message: str = ""
    # Rollback latch: the config hash that failed analysis. A rolled-back
    # hash is never auto-retried — only a *new* config restarts a rollout
    # (otherwise a persistently unhealthy candidate would be spawned and
    # killed on every controller resync).
    failed_hash: str = ""

    def to_status(self) -> dict:
        return {
            "phase": self.phase.value,
            "candidateHash": self.candidate_hash,
            "stepIndex": self.step_index,
            "message": self.message,
        }


# Analyzer returns True (healthy), False (unhealthy → rollback).
Analyzer = Callable[[AgentDeployment], bool]


def _default_analyzer(dep: AgentDeployment) -> bool:
    """Healthy iff every candidate pod's runtime still answers Health
    ready. Metric-based analysis (error rate, eval pass-rate) plugs in
    here via the controller."""
    from omnia_tpu.runtime.client import RuntimeClient

    for pod in dep.candidate_pods:
        try:
            client = RuntimeClient(f"localhost:{pod.runtime_port}")
            try:
                h = client.health()
                if h.status != "ok":
                    return False
            finally:
                client.close()
        except Exception:
            return False
    return True


class RolloutEngine:
    def __init__(self, backend, analyzer: Optional[Analyzer] = None):
        self.backend = backend
        self.analyzer = analyzer or _default_analyzer
        self._states: dict[str, RolloutState] = {}

    def state(self, dep: AgentDeployment) -> RolloutState:
        return self._states.setdefault(dep.resource.key, RolloutState())

    def tick(self, dep: AgentDeployment, now: Optional[float] = None) -> RolloutState:
        """Advance the rollout machine one step. No-op (direct replace)
        when the spec has no rollout steps."""
        now = time.time() if now is None else now
        st = self.state(dep)
        steps = [
            RolloutStep.from_spec(s)
            for s in (dep.resource.spec.get("rollout") or {}).get("steps", [])
        ]
        new_hash = dep.config_hash()

        if st.phase in (RolloutPhase.IDLE, RolloutPhase.PROMOTED, RolloutPhase.ROLLED_BACK):
            if new_hash != dep.stable_hash and new_hash != st.failed_hash:
                if not steps:
                    self._direct_replace(dep, new_hash)
                    st.phase = RolloutPhase.PROMOTED
                    st.candidate_hash = new_hash
                    st.message = "replaced without steps"
                else:
                    self._start_candidate(dep, new_hash, steps[0], st, now)
            return st

        # PROGRESSING -------------------------------------------------
        if new_hash != st.candidate_hash:
            # Spec changed mid-rollout: abort current candidate, restart.
            self._teardown_candidate(dep)
            st.phase = RolloutPhase.IDLE
            st.message = "superseded by newer config"
            return self.tick(dep, now)

        step = steps[st.step_index] if st.step_index < len(steps) else None
        if step is not None and now - st.step_entered_at < step.hold_s:
            return st  # dwell

        if not self.analyzer(dep):
            self._teardown_candidate(dep)
            st.phase = RolloutPhase.ROLLED_BACK
            st.failed_hash = st.candidate_hash
            st.message = f"analysis failed at step {st.step_index}"
            logger.warning("rollout %s rolled back: %s", dep.name, st.message)
            return st

        next_index = st.step_index + 1
        if next_index < len(steps):
            st.step_index = next_index
            st.step_entered_at = now
            dep.candidate_weight = steps[next_index].weight
            st.message = f"step {next_index}: weight {dep.candidate_weight}"
        else:
            self._promote(dep, st)
        return st

    # -- transitions ----------------------------------------------------

    def _start_candidate(self, dep, new_hash, first_step, st, now) -> None:
        n = max(1, len(dep.pods))
        for _ in range(n):
            dep.candidate_pods.append(
                self.backend.start_pod(dep, version=new_hash, track="candidate")
            )
        dep.candidate_weight = first_step.weight
        st.phase = RolloutPhase.PROGRESSING
        st.candidate_hash = new_hash
        st.step_index = 0
        st.step_entered_at = now
        st.message = f"step 0: weight {first_step.weight}"
        logger.info("rollout %s started: candidate %s", dep.name, new_hash)

    def _teardown_candidate(self, dep: AgentDeployment) -> None:
        for p in dep.candidate_pods:
            try:
                self.backend.stop_pod(p)
            except Exception:
                logger.exception("candidate pod stop failed")
        dep.candidate_pods = []
        dep.candidate_weight = 0.0

    def _promote(self, dep: AgentDeployment, st: RolloutState) -> None:
        old = dep.pods
        dep.pods = dep.candidate_pods
        dep.candidate_pods = []
        dep.candidate_weight = 0.0
        dep.stable_hash = st.candidate_hash
        for p in old:
            try:
                self.backend.stop_pod(p)
            except Exception:
                logger.exception("old stable pod stop failed")
        st.phase = RolloutPhase.PROMOTED
        st.message = "promoted"
        logger.info("rollout %s promoted %s", dep.name, st.candidate_hash)

    def _direct_replace(self, dep: AgentDeployment, new_hash: str) -> None:
        old = dep.pods
        dep.pods = [
            self.backend.start_pod(dep, version=new_hash) for _ in range(max(1, len(old)))
        ]
        dep.stable_hash = new_hash
        for p in old:
            try:
                self.backend.stop_pod(p)
            except Exception:
                logger.exception("old pod stop failed")
