"""Admission validation: the CEL-rules + webhook equivalent.

The reference validates CRDs with CEL expressions compiled into the CRD
schema plus validating webhooks (reference internal/webhook/*_webhook.go,
api/v1alpha1/agentruntime_facades_cel_envtest_test.go). Here each kind
gets a Python validator invoked by the store on every apply — same
fail-closed admission posture, no cluster required."""

from __future__ import annotations

from typing import Callable

from omnia_tpu.operator.resources import (
    AGENT_MODES,
    FACADE_TYPES,
    PROVIDER_ROLES,
    PROVIDER_TYPES,
    TOOL_HANDLER_TYPES,
    Resource,
    ResourceKind,
)


class ValidationError(ValueError):
    def __init__(self, resource: Resource, errors: list[str]):
        self.errors = errors
        super().__init__(f"{resource.key}: " + "; ".join(errors))


def _validate_agent_runtime(spec: dict, errs: list[str]) -> None:
    mode = spec.get("mode", "agent")
    if mode not in AGENT_MODES:
        errs.append(f"mode must be one of {AGENT_MODES}, got {mode!r}")
    facades = spec.get("facades", [{"type": "websocket"}])
    if not isinstance(facades, list) or not facades:
        errs.append("facades must be a non-empty list")
        facades = []
    for f in facades:
        t = f.get("type") if isinstance(f, dict) else None
        if t not in FACADE_TYPES:
            errs.append(f"facade type must be one of {FACADE_TYPES}, got {t!r}")
    # mcp facade requires function mode (reference CEL rule on facades).
    if mode != "function" and any(
        isinstance(f, dict) and f.get("type") == "mcp" for f in facades
    ):
        errs.append("mcp facade requires mode: function")
    if not spec.get("promptPackRef"):
        errs.append("promptPackRef is required")
    providers = spec.get("providers", [])
    if not providers:
        errs.append("at least one providers[] entry is required")
    names = [p.get("name") for p in providers if isinstance(p, dict)]
    if len(names) != len(set(names)):
        errs.append("providers[].name must be unique")
    for p in providers:
        if not isinstance(p, dict) or not p.get("name") or not p.get("providerRef"):
            errs.append("each providers[] entry needs name and providerRef")
    replicas = spec.get("replicas", 1)
    if not isinstance(replicas, int) or replicas < 0:
        errs.append("replicas must be a non-negative integer")
    auto = spec.get("autoscaling")
    if auto:
        # Defaults must match AutoscalingPolicy.from_spec (min 0, max 4)
        # or a spec the scaler accepts gets rejected at admission.
        lo, hi = auto.get("minReplicas", 0), auto.get("maxReplicas", 4)
        if lo > hi:
            errs.append("autoscaling.minReplicas must be <= maxReplicas")
    rollout = spec.get("rollout")
    if rollout:
        steps = rollout.get("steps", [])
        if not steps:
            errs.append("rollout.steps must be non-empty when rollout is set")
        for s in steps:
            w = s.get("weight") if isinstance(s, dict) else None
            if not isinstance(w, (int, float)) or not (0 <= w <= 100):
                errs.append("rollout step weight must be in [0, 100]")
    hosts = spec.get("tpuHosts", 1)
    if not isinstance(hosts, int) or isinstance(hosts, bool) or hosts < 1:
        errs.append(f"tpuHosts must be an integer >= 1, got {hosts!r}")
    elif hosts > 1:
        # One multi-host set IS one model instance: a replica count or an
        # autoscaler on top would silently be discarded by the renderer —
        # reject instead (scale multi-host models with more AgentRuntimes
        # or a fleet coordinator, not HPA).
        if spec.get("replicas", 1) != 1:
            errs.append("tpuHosts > 1 requires replicas == 1 "
                        "(the StatefulSet's replicas are HOSTS of one model)")
        if spec.get("autoscaling"):
            errs.append("tpuHosts > 1 cannot be autoscaled (HPA would "
                        "resize the host set, not add model replicas)")


def _validate_provider(spec: dict, errs: list[str]) -> None:
    t = spec.get("type")
    if t not in PROVIDER_TYPES:
        errs.append(f"type must be one of {PROVIDER_TYPES}, got {t!r}")
    role = spec.get("role", "llm")
    if role not in PROVIDER_ROLES:
        errs.append(f"role must be one of {PROVIDER_ROLES}, got {role!r}")
    # Role↔type compatibility, mirroring the reference's per-type role
    # restrictions (provider_types.go:399-409: mock is LLM-role only,
    # speech types are TTS/STT-role only).
    role_types = {
        "llm": ("tpu", "mock"),
        "embedding": ("tpu", "mock"),
        "tts": ("tone", "mock", "cartesia", "elevenlabs", "openai"),
        "stt": ("tone", "mock", "cartesia", "elevenlabs", "openai"),
        "image": ("procedural", "openai"),
        "inference": ("tpu",),
    }
    if role in role_types and t in PROVIDER_TYPES and t not in role_types[role]:
        errs.append(
            f"type {t!r} does not serve role {role!r} "
            f"(valid types: {role_types[role] or '(none yet)'})"
        )
    if t == "tpu" and role in ("llm", "inference") and not spec.get("model"):
        errs.append("tpu provider requires spec.model (a model preset name)")
    pricing = spec.get("pricing", {})
    for k in ("inputPerMTok", "outputPerMTok"):
        v = pricing.get(k, 0)
        if not isinstance(v, (int, float)) or v < 0:
            errs.append(f"pricing.{k} must be a non-negative number")


def _validate_prompt_pack(spec: dict, errs: list[str]) -> None:
    content = spec.get("content")
    if content is None:
        errs.append("spec.content (compiled pack JSON) is required")
        return
    from omnia_tpu.runtime.packs import validate_pack

    errs.extend(validate_pack(content))


def _validate_tool_registry(spec: dict, errs: list[str]) -> None:
    tools = spec.get("tools", [])
    seen = set()
    for t in tools:
        if not isinstance(t, dict) or not t.get("name"):
            errs.append("each tools[] entry needs a name")
            continue
        if t["name"] in seen:
            errs.append(f"duplicate tool name {t['name']!r}")
        seen.add(t["name"])
        h = t.get("handler", {})
        ht = h.get("type")
        if ht not in TOOL_HANDLER_TYPES:
            errs.append(
                f"tool {t['name']}: handler.type must be one of {TOOL_HANDLER_TYPES}"
            )
            continue
        # Per-type required config (reference HandlerEntry carries a
        # matching config block per type, config.go:131-169).
        if ht == "http" and not h.get("url"):
            errs.append(f"tool {t['name']}: http handler needs url")
        elif ht == "grpc" and not (h.get("endpoint") or h.get("grpcConfig", {}).get("endpoint")):
            errs.append(f"tool {t['name']}: grpc handler needs endpoint")
        elif ht == "mcp":
            mcp = h.get("mcpConfig") or h.get("mcp") or {}
            if not (mcp.get("command") or mcp.get("endpoint")):
                errs.append(
                    f"tool {t['name']}: mcp handler needs mcpConfig.command "
                    "(stdio) or mcpConfig.endpoint (streamable-http)"
                )
        elif ht == "openapi":
            oa = h.get("openAPIConfig", {})
            if not (h.get("spec") or h.get("specURL") or oa.get("specURL")
                    or h.get("url")):
                errs.append(
                    f"tool {t['name']}: openapi handler needs spec/specURL"
                )


def _validate_workspace(spec: dict, errs: list[str]) -> None:
    if not spec.get("environment"):
        errs.append("spec.environment is required (e.g. dev|staging|prod)")
    for g in spec.get("services", []):
        if not isinstance(g, dict) or not g.get("name"):
            errs.append("each services[] group needs a name")


def _validate_retention(spec: dict, errs: list[str]) -> None:
    hot = spec.get("hotIdleSeconds", 3600)
    warm = spec.get("warmWindowSeconds", 7 * 86400)
    cold = spec.get("coldWindowSeconds", 90 * 86400)
    if not (0 < hot <= warm <= cold):
        errs.append("windows must satisfy 0 < hot <= warm <= cold")


def _validate_memory_policy(spec: dict, errs: list[str]) -> None:
    for tier in spec.get("tiers", []):
        if tier.get("ttlSeconds", 1) <= 0:
            errs.append("tier ttlSeconds must be positive")
        hl = tier.get("halfLifeSeconds")
        if hl is not None and hl <= 0:
            errs.append("tier halfLifeSeconds must be positive")


def _validate_agent_policy(spec: dict, errs: list[str]) -> None:
    allow, deny = spec.get("allowTools"), spec.get("denyTools")
    if allow is not None and deny is not None:
        overlap = set(allow) & set(deny)
        if overlap:
            errs.append(f"tools both allowed and denied: {sorted(overlap)}")


def _validate_skill_source(spec: dict, errs: list[str]) -> None:
    src = spec.get("source", {})
    if src.get("type") not in ("git", "oci", "configmap", "local"):
        errs.append("source.type must be git|oci|configmap|local")


def _validate_arena_job(spec: dict, errs: list[str]) -> None:
    if not spec.get("scenarios") and not spec.get("scenariosFrom"):
        errs.append("scenarios[] or scenariosFrom is required")
    sf = spec.get("scenariosFrom")
    if sf is not None and (not isinstance(sf, dict) or not sf.get("name")):
        errs.append("scenariosFrom.name (an ArenaSource) is required")
    if not spec.get("providers"):
        errs.append("providers[] is required")
    mode = spec.get("mode", "direct")
    if mode not in ("direct", "fleet"):
        errs.append(f"mode must be direct|fleet, got {mode!r}")
    repeats = spec.get("repeats", 1)
    if not isinstance(repeats, int) or isinstance(repeats, bool) or repeats < 1:
        errs.append(f"repeats must be an integer >= 1, got {repeats!r}")
    for i, s in enumerate(spec.get("scenarios") or []):
        if not isinstance(s, dict) or not s.get("name"):
            errs.append(f"scenarios[{i}].name is required")


def _validate_tool_policy(spec: dict, errs: list[str]) -> None:
    rules = spec.get("rules")
    if not isinstance(rules, list) or not rules:
        errs.append("rules[] is required")
        return
    for i, r in enumerate(rules):
        if not isinstance(r, dict):
            errs.append(f"rules[{i}] must be an object")
            continue
        # Same vocabulary the policy broker enforces (PolicyRule.action).
        if r.get("action") not in ("allow", "deny"):
            errs.append(f"rules[{i}].action must be allow|deny")
    if spec.get("default_action", "deny") not in ("allow", "deny"):
        errs.append("default_action must be allow|deny")


def _validate_session_privacy_policy(spec: dict, errs: list[str]) -> None:
    preset = spec.get("preset")
    if preset is not None:
        from omnia_tpu.privacy.compliance import PRESETS

        if preset not in PRESETS:
            errs.append(f"preset must be one of {PRESETS}, got {preset!r}")
    if "recording" in spec and not isinstance(spec["recording"], bool):
        errs.append("recording must be a bool")
    for field in ("redactFields", "consentCategories"):
        v = spec.get(field)
        if v is not None and (
            not isinstance(v, list) or not all(isinstance(x, str) for x in v)
        ):
            errs.append(f"{field} must be a list of strings")


def _validate_rollout_analysis(spec: dict, errs: list[str]) -> None:
    metrics = spec.get("metrics")
    if not isinstance(metrics, list) or not metrics:
        errs.append("metrics[] is required")
        return
    for i, m in enumerate(metrics):
        if not isinstance(m, dict) or not m.get("name"):
            errs.append(f"metrics[{i}].name is required")
        elif "maxErrorRate" not in m and "maxP95LatencyS" not in m \
                and "threshold" not in m:
            errs.append(f"metrics[{i}] needs a threshold field")


def _validate_sync_source(spec: dict, errs: list[str]) -> None:
    src = spec.get("source")
    if not isinstance(src, dict):
        errs.append("spec.source is required")
        return
    from omnia_tpu.operator.resources import SOURCE_TYPES

    stype = src.get("type")
    if stype not in SOURCE_TYPES:
        errs.append(f"source.type must be one of {SOURCE_TYPES}, got {stype!r}")
    if stype == "git" and not (src.get("repo") or src.get("url")):
        errs.append("git source requires repo url")
    if stype == "oci" and not (src.get("ref") or src.get("url")):
        errs.append("oci source requires ref (host/repo:tag)")
    if stype == "configmap" and not isinstance(src.get("data"), dict):
        errs.append("configmap source requires data {filename: content}")
    if stype == "local" and not src.get("path"):
        errs.append("local source requires path")


def _validate_arena_dev_session(spec: dict, errs: list[str]) -> None:
    ref = spec.get("agentRef")
    if not isinstance(ref, dict) or not ref.get("name"):
        errs.append("agentRef.name is required")
    ttl = spec.get("ttl_s")
    if ttl is not None and (not isinstance(ttl, (int, float)) or ttl <= 0):
        errs.append("ttl_s must be a positive number")


def _validate_httproute(spec: dict, errs: list[str]) -> None:
    """Minimal Gateway-API HTTPRoute shape (gateway.networking.k8s.io):
    enough structure for the controller's endpoint observation
    (reference internal/controller/facade_route.go). Not one of omnia's
    own CRDs — accepted so a devroot/store can carry the routes the
    reference watches from the cluster."""
    hostnames = spec.get("hostnames", [])
    if not isinstance(hostnames, list) or not all(
        isinstance(h, str) and h for h in hostnames
    ):
        errs.append("hostnames must be a list of non-empty strings")
    rules = spec.get("rules", [])
    if not isinstance(rules, list):
        errs.append("rules must be a list")
        return
    for i, rule in enumerate(rules):
        if not isinstance(rule, dict):
            errs.append(f"rules[{i}] must be an object")
            continue
        matches = rule.get("matches", []) or []
        if not isinstance(matches, list) or not all(
            isinstance(m, dict) for m in matches
        ):
            errs.append(f"rules[{i}].matches must be a list of objects")
        refs = rule.get("backendRefs", []) or []
        if not isinstance(refs, list):
            errs.append(f"rules[{i}].backendRefs must be a list")
            continue
        for j, ref in enumerate(refs):
            if not isinstance(ref, dict) or not ref.get("name"):
                errs.append(f"rules[{i}].backendRefs[{j}] needs a name")


_VALIDATORS: dict[str, Callable[[dict, list[str]], None]] = {
    "HTTPRoute": _validate_httproute,
    ResourceKind.PROMPT_PACK_SOURCE.value: _validate_sync_source,
    ResourceKind.ARENA_SOURCE.value: _validate_sync_source,
    ResourceKind.ARENA_TEMPLATE_SOURCE.value: _validate_sync_source,
    ResourceKind.ARENA_DEV_SESSION.value: _validate_arena_dev_session,
    ResourceKind.ARENA_JOB.value: _validate_arena_job,
    ResourceKind.TOOL_POLICY.value: _validate_tool_policy,
    ResourceKind.SESSION_PRIVACY_POLICY.value: _validate_session_privacy_policy,
    ResourceKind.ROLLOUT_ANALYSIS.value: _validate_rollout_analysis,
    ResourceKind.AGENT_RUNTIME.value: _validate_agent_runtime,
    ResourceKind.PROVIDER.value: _validate_provider,
    ResourceKind.PROMPT_PACK.value: _validate_prompt_pack,
    ResourceKind.TOOL_REGISTRY.value: _validate_tool_registry,
    ResourceKind.WORKSPACE.value: _validate_workspace,
    ResourceKind.SESSION_RETENTION_POLICY.value: _validate_retention,
    ResourceKind.MEMORY_POLICY.value: _validate_memory_policy,
    ResourceKind.AGENT_POLICY.value: _validate_agent_policy,
    ResourceKind.SKILL_SOURCE.value: _validate_skill_source,
}


def validate(resource: Resource) -> None:
    """Raise ValidationError when the resource fails admission. Unknown
    kinds are rejected (fail closed, like an unregistered CRD)."""
    v = _VALIDATORS.get(resource.kind)
    if v is None:
        raise ValidationError(resource, [f"unknown kind {resource.kind!r}"])
    errs: list[str] = []
    v(resource.spec, errs)
    if errs:
        raise ValidationError(resource, errs)
