"""ToolRegistry reachability probes → per-tool status + registry phase.

Counterpart of the reference's ToolRegistry probe pass (reference
internal/controller/toolregistry_probe.go:53 fans probes out under a
small semaphore, :79 TCP-dials each network endpoint within a timeout
and marks Available/Unavailable, :113 leaves client://, stdio:// and
empty endpoints unprobed; phases in api/v1alpha1/toolregistry_types.go:
661-673 — Pending/Ready/Degraded/Failed, tools Available/Unavailable/
Unknown).
"""

from __future__ import annotations

import socket
import threading
import time
import urllib.parse
from typing import Optional

PHASE_PENDING = "Pending"
PHASE_READY = "Ready"
PHASE_DEGRADED = "Degraded"
PHASE_FAILED = "Failed"

STATUS_AVAILABLE = "Available"
STATUS_UNAVAILABLE = "Unavailable"
STATUS_UNKNOWN = "Unknown"

DEFAULT_TIMEOUT_S = 2.0
MAX_CONCURRENT_PROBES = 8


def endpoint_of(tool: dict) -> str:
    """The probeable endpoint a tools[] CRD entry resolves to.
    client tools → client:// (unprobed), stdio MCP → stdio:// (a
    subprocess has no address), everything else → its network target."""
    h = tool.get("handler", {}) or {}
    htype = h.get("type", "http")
    if htype == "client":
        return "client://"
    if htype == "http":
        return h.get("url", "")
    if htype == "grpc":
        return h.get("endpoint") or h.get("grpcConfig", {}).get("endpoint", "")
    if htype == "mcp":
        mcp = h.get("mcpConfig") or h.get("mcp") or {}
        if mcp.get("command") or (mcp.get("transport") or "").lower() == "stdio":
            return "stdio://"
        return mcp.get("endpoint", "")
    if htype == "openapi":
        oa = h.get("openAPIConfig", {})
        return (h.get("baseURL") or oa.get("baseURL")
                or h.get("specURL") or oa.get("specURL") or h.get("url", ""))
    return ""


def probe_address(endpoint: str) -> Optional[tuple[str, int]]:
    """(host, port) to dial, or None when the endpoint can't be parsed
    (a network endpoint we can't parse is a misconfiguration — the
    caller surfaces it rather than leaving the tool unprobed)."""
    u = urllib.parse.urlsplit(endpoint)
    if u.scheme and u.hostname:
        port = u.port or (443 if u.scheme in ("https", "wss") else 80)
        return u.hostname, port
    # bare host:port (gRPC endpoints), incl. bracketed IPv6 [::1]:50051
    host, _, port = endpoint.rpartition(":")
    if host and port.isdigit():
        if host.startswith("[") and host.endswith("]"):
            host = host[1:-1]  # getaddrinfo wants the bare address
        return host, int(port)
    return None


def is_network_endpoint(endpoint: str) -> bool:
    return bool(endpoint) and not endpoint.startswith(("client://", "stdio://"))


def probe_one(endpoint: str, timeout_s: float = DEFAULT_TIMEOUT_S) -> tuple[str, str]:
    """→ (status, error). TCP reachability, not protocol health: the
    reference deliberately dials rather than speaking each protocol."""
    if not is_network_endpoint(endpoint):
        return STATUS_UNKNOWN, ""
    addr = probe_address(endpoint)
    if addr is None:
        return STATUS_UNAVAILABLE, f"unrecognized endpoint address {endpoint!r}"
    try:
        with socket.create_connection(addr, timeout=timeout_s):
            return STATUS_AVAILABLE, ""
    except OSError as e:
        return STATUS_UNAVAILABLE, f"probe failed: {e}"


def probe_tools(
    tools: list[dict],
    timeout_s: float = DEFAULT_TIMEOUT_S,
    max_concurrent: int = MAX_CONCURRENT_PROBES,
) -> list[dict]:
    """Probe every tool concurrently (bounded). Returns per-tool status
    entries in input order."""
    sem = threading.Semaphore(max_concurrent)
    out: list[Optional[dict]] = [None] * len(tools)

    def worker(i: int, tool: dict) -> None:
        with sem:
            endpoint = endpoint_of(tool)
            status, err = probe_one(endpoint, timeout_s)
            entry = {
                "name": tool.get("name", ""),
                "handlerType": (tool.get("handler") or {}).get("type", "http"),
                "endpoint": endpoint,
                "status": status,
                "lastChecked": time.time(),
            }
            if err:
                entry["error"] = err
            out[i] = entry

    threads = [
        threading.Thread(target=worker, args=(i, t), daemon=True)
        for i, t in enumerate(tools)
    ]
    for t in threads:
        t.start()
    # The connect timeout does not bound DNS resolution (getaddrinfo has
    # no per-call deadline), so the join is the hard backstop: a probe
    # hung on a blackholed name reports Unknown with its IDENTITY kept —
    # the tool must not vanish from status while it is unprobeable.
    deadline = time.time() + timeout_s * 4 + 5
    for t in threads:
        t.join(timeout=max(0.1, deadline - time.time()))
    return [
        e if e is not None else {
            "name": tools[i].get("name", ""),
            "handlerType": (tools[i].get("handler") or {}).get("type", "http"),
            "endpoint": endpoint_of(tools[i]),
            "status": STATUS_UNKNOWN,
            "error": "probe timed out (DNS or dial hang)",
            "lastChecked": time.time(),
        }
        for i, e in enumerate(out)
    ]


def phase_of(tool_statuses: list[dict]) -> str:
    """Registry phase from per-tool statuses (toolregistry_types.go:
    661-667): Ready when nothing is Unavailable, Degraded when some are,
    Failed when ALL network tools are down, Pending when empty."""
    if not tool_statuses:
        return PHASE_PENDING
    down = [t for t in tool_statuses if t["status"] == STATUS_UNAVAILABLE]
    if not down:
        return PHASE_READY
    probed = [t for t in tool_statuses if t["status"] != STATUS_UNKNOWN]
    if len(down) == len(probed):
        return PHASE_FAILED
    return PHASE_DEGRADED
