"""Source sync: pack/arena content → versioned filesystem layout.

Reference internal/sourcesync (git.go, oci.go, configmap.go,
syncer.go:92 SyncToFilesystem): content from a git repo, a configmap
payload, or a local directory lands in a versioned directory tree

    <root>/<source>/<version>/...files...
    <root>/<source>/HEAD            ← current version name

with atomic HEAD flips and garbage collection of old versions
(syncer.go:216-236 keeps the most recent N). Consumers (pack resolve,
arena workers) always read through HEAD, so a half-synced version is
never visible."""

from __future__ import annotations

import hashlib
import json
import logging
import os
import shutil
import subprocess
from typing import Optional

logger = logging.getLogger(__name__)

DEFAULT_KEEP_VERSIONS = 3


class SyncError(RuntimeError):
    pass


class Syncer:
    def __init__(self, root: str, keep_versions: int = DEFAULT_KEEP_VERSIONS):
        self.root = root
        self.keep_versions = keep_versions
        os.makedirs(root, exist_ok=True)

    # -- public ------------------------------------------------------------

    def sync(self, name: str, source: dict) -> str:
        """Sync one source spec (SkillSource/PromptPackSource shape:
        {type: git|configmap|local, ...}) → version id now at HEAD."""
        stype = source.get("type")
        if stype == "git":
            return self._sync_git(name, source)
        if stype == "configmap":
            return self._sync_payload(name, source.get("data") or {})
        if stype == "local":
            return self._sync_local(name, source["path"])
        if stype == "oci":
            return self._sync_oci(name, source)
        raise SyncError(f"unsupported source type {stype!r}")

    def head(self, name: str) -> Optional[str]:
        path = os.path.join(self.root, name, "HEAD")
        if not os.path.exists(path):
            return None
        with open(path) as f:
            return f.read().strip()

    def head_dir(self, name: str) -> Optional[str]:
        version = self.head(name)
        if version is None:
            return None
        return os.path.join(self.root, name, version)

    def read(self, name: str, rel_path: str) -> bytes:
        d = self.head_dir(name)
        if d is None:
            raise SyncError(f"source {name!r} never synced")
        full = os.path.realpath(os.path.join(d, rel_path))
        if not full.startswith(os.path.realpath(d) + os.sep):
            raise SyncError("path escapes source root")
        with open(full, "rb") as f:
            return f.read()

    def versions(self, name: str) -> list[str]:
        base = os.path.join(self.root, name)
        if not os.path.isdir(base):
            return []
        return sorted(
            v for v in os.listdir(base)
            if v != "HEAD" and os.path.isdir(os.path.join(base, v))
        )

    # -- backends ----------------------------------------------------------

    def _sync_git(self, name: str, source: dict) -> str:
        url = source.get("repo") or source.get("url")
        if not url:
            raise SyncError("git source requires repo url")
        ref = source.get("ref", "HEAD")
        tmp = os.path.join(self.root, name, ".clone.tmp")
        shutil.rmtree(tmp, ignore_errors=True)
        try:
            subprocess.run(
                ["git", "clone", "--quiet", "--depth", "1",
                 *(["--branch", ref] if ref != "HEAD" else []), url, tmp],
                check=True, capture_output=True, timeout=120,
            )
            rev = subprocess.run(
                ["git", "-C", tmp, "rev-parse", "--short=12", "HEAD"],
                check=True, capture_output=True, text=True, timeout=30,
            ).stdout.strip()
            shutil.rmtree(os.path.join(tmp, ".git"), ignore_errors=True)
            subdir = source.get("path", "")
            src_dir = os.path.join(tmp, subdir) if subdir else tmp
            if not os.path.isdir(src_dir):
                raise SyncError(f"git source path {subdir!r} not found")
            return self._install(name, f"git-{rev}", src_dir)
        except subprocess.CalledProcessError as e:
            raise SyncError(f"git sync failed: {e.stderr}") from e
        finally:
            shutil.rmtree(tmp, ignore_errors=True)

    def _sync_payload(self, name: str, data: dict) -> str:
        """Configmap-style payload: {filename: text-or-json}."""
        digest = hashlib.sha256(
            json.dumps(data, sort_keys=True).encode()
        ).hexdigest()[:12]
        version = f"cm-{digest}"
        if self.head(name) == version:
            return version  # idempotent re-sync
        staging = os.path.join(self.root, name, f".{version}.tmp")
        shutil.rmtree(staging, ignore_errors=True)
        os.makedirs(staging)
        for fname, content in data.items():
            if "/" in fname or fname.startswith("."):
                raise SyncError(f"bad payload filename {fname!r}")
            text = content if isinstance(content, str) else json.dumps(content)
            with open(os.path.join(staging, fname), "w") as f:
                f.write(text)
        return self._install(name, version, staging, move=True)

    def _sync_oci(self, name: str, source: dict) -> str:
        """OCI artifact source (reference internal/sourcesync/oci.go):
        pull 'host:port/repo:tag[@digest]' from a v2 registry (the
        in-tree omnia_tpu.oci registry, or any plain-HTTP in-cluster
        registry) and install the layer files as a version. Version id =
        manifest digest, so re-syncing an unchanged tag is idempotent
        and a moved tag lands as a NEW version (tag-move = pack update)."""
        ref = source.get("ref") or source.get("url")
        if not ref:
            raise SyncError("oci source requires ref (host/repo:tag)")
        from omnia_tpu.oci import OCIError, pull_artifact

        try:
            digest, files = pull_artifact(ref, token=source.get("token"))
        except OCIError as e:
            raise SyncError(f"oci sync failed: {e}") from e
        except Exception as e:  # network/registry errors
            raise SyncError(f"oci sync failed: {e}") from e
        version = f"oci-{digest.split(':', 1)[1][:12]}"
        if self.head(name) == version:
            return version
        staging = os.path.join(self.root, name, f".{version}.tmp")
        shutil.rmtree(staging, ignore_errors=True)
        os.makedirs(staging)
        for rel, data in files.items():
            dest = os.path.join(staging, rel)
            os.makedirs(os.path.dirname(dest) or staging, exist_ok=True)
            with open(dest, "wb") as f:
                f.write(data)
        return self._install(name, version, staging, move=True)

    def _sync_local(self, name: str, path: str) -> str:
        if not os.path.isdir(path):
            raise SyncError(f"local source {path!r} not a directory")
        h = hashlib.sha256()
        for dirpath, _dirs, files in sorted(os.walk(path)):
            for fname in sorted(files):
                fp = os.path.join(dirpath, fname)
                h.update(fname.encode())
                with open(fp, "rb") as f:
                    h.update(f.read())
        return self._install(name, f"local-{h.hexdigest()[:12]}", path)

    # -- install / GC ------------------------------------------------------

    def _install(self, name: str, version: str, src_dir: str, move: bool = False) -> str:
        base = os.path.join(self.root, name)
        os.makedirs(base, exist_ok=True)
        dest = os.path.join(base, version)
        if not os.path.isdir(dest):
            staging = dest + ".installing"
            shutil.rmtree(staging, ignore_errors=True)
            if move:
                os.rename(src_dir, staging)
            else:
                shutil.copytree(src_dir, staging)
            os.rename(staging, dest)  # version dirs appear atomically
        head_tmp = os.path.join(base, "HEAD.tmp")
        with open(head_tmp, "w") as f:
            f.write(version)
        os.replace(head_tmp, os.path.join(base, "HEAD"))  # atomic flip
        self._gc(name, keep=version)
        logger.info("synced %s → %s", name, version)
        return version

    def _gc(self, name: str, keep: str) -> None:
        base = os.path.join(self.root, name)
        versions = [
            (os.path.getmtime(os.path.join(base, v)), v)
            for v in self.versions(name)
            if v != keep
        ]
        versions.sort(reverse=True)
        for _mtime, v in versions[self.keep_versions - 1:]:
            shutil.rmtree(os.path.join(base, v), ignore_errors=True)
