"""Installable deployment bundle — the chart.

Reference parity target: charts/omnia (operator + dashboard + redis +
agents RBAC + observability). Rendered in Python instead of Go
templates: `render_install(values)` returns the full manifest list and
`python -m omnia_tpu.operator.install [values.yaml] > install.yaml`
emits it as multi-doc YAML for `kubectl apply -f -`. Everything rendered
here must pass `manifest_lint.lint` — the repo's dry-run gate (tests
enforce it), and deploy/values.yaml documents every knob.

The agent pods themselves are NOT rendered here — the operator builds
those at runtime from AgentRuntime resources (deployment.K8sManifestBackend),
exactly like the reference's deployment builder.
"""

from __future__ import annotations

import sys
from typing import Optional

from omnia_tpu.operator.crds import GROUP, render_crds

DEFAULT_VALUES: dict = {
    "namespace": "omnia-system",
    "images": {
        "operator": "omnia-tpu/operator:latest",
        "sessionApi": "omnia-tpu/session-api:latest",
        "memoryApi": "omnia-tpu/memory-api:latest",
        "redis": "omnia-tpu/redisd:latest",
    },
    "operator": {"replicas": 1, "dashboard": True},
    "sessionApi": {"replicas": 1},
    "memoryApi": {"replicas": 1},
    "redis": {"enabled": True},
    "serviceAccount": "omnia-operator",
    # Bundled observability (reference charts/omnia/templates/observability:
    # Prometheus + Grafana dashboards + podmonitors; Loki/Tempo are left to
    # a cluster's own logging/tracing stack — OTLP export is wired via
    # OMNIA_OTLP_ENDPOINT on the services).
    "observability": {
        "enabled": False,
        "prometheus": {"image": "prom/prometheus:v2.53.0", "retention": "24h"},
        "grafana": {"image": "grafana/grafana:11.1.0"},
        "podMonitors": True,
    },
}


def _merge(base: dict, over: Optional[dict]) -> dict:
    out = dict(base)
    for k, v in (over or {}).items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = _merge(out[k], v)
        else:
            out[k] = v
    return out


def _labels(comp: str) -> dict:
    return {"app.kubernetes.io/name": "omnia", "app.kubernetes.io/component": comp}


def _deployment(ns: str, name: str, comp: str, image: str, replicas: int,
                ports: list[dict], env: list[dict]) -> dict:
    labels = _labels(comp)
    return {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": {"name": name, "namespace": ns, "labels": labels},
        "spec": {
            "replicas": replicas,
            "selector": {"matchLabels": labels},
            "template": {
                "metadata": {"labels": labels},
                "spec": {
                    "containers": [{
                        "name": comp,
                        "image": image,
                        "ports": ports,
                        "env": env,
                    }],
                },
            },
        },
    }


def _service(ns: str, name: str, comp: str, ports: list[dict]) -> dict:
    return {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {"name": name, "namespace": ns, "labels": _labels(comp)},
        "spec": {"selector": _labels(comp), "ports": ports},
    }


def render_install(values: Optional[dict] = None) -> list[dict]:
    v = _merge(DEFAULT_VALUES, values)
    ns = v["namespace"]
    sa = v["serviceAccount"]
    out: list[dict] = [
        {"apiVersion": "v1", "kind": "Namespace", "metadata": {"name": ns}},
    ]
    out += render_crds()
    # RBAC: the operator watches its CRDs cluster-wide and manages agent
    # Deployments/Services/ConfigMaps in workspace namespaces.
    out += [
        {
            "apiVersion": "v1",
            "kind": "ServiceAccount",
            "metadata": {"name": sa, "namespace": ns},
        },
        {
            "apiVersion": "rbac.authorization.k8s.io/v1",
            "kind": "ClusterRole",
            "metadata": {"name": "omnia-operator"},
            "rules": [
                {"apiGroups": [GROUP],
                 "resources": ["*"],
                 "verbs": ["get", "list", "watch", "update", "patch"]},
                {"apiGroups": [GROUP],
                 "resources": ["*/status"],
                 "verbs": ["get", "update", "patch"]},
                {"apiGroups": ["apps"],
                 "resources": ["deployments"],
                 "verbs": ["get", "list", "watch", "create", "update", "patch", "delete"]},
                {"apiGroups": [""],
                 "resources": ["services", "configmaps", "secrets"],
                 "verbs": ["get", "list", "watch", "create", "update", "patch", "delete"]},
                {"apiGroups": ["autoscaling"],
                 "resources": ["horizontalpodautoscalers"],
                 "verbs": ["get", "list", "watch", "create", "update", "patch", "delete"]},
                {"apiGroups": ["policy"],
                 "resources": ["poddisruptionbudgets"],
                 "verbs": ["get", "list", "watch", "create", "update", "patch", "delete"]},
            ],
        },
        {
            "apiVersion": "rbac.authorization.k8s.io/v1",
            "kind": "ClusterRoleBinding",
            "metadata": {"name": "omnia-operator"},
            "roleRef": {
                "apiGroup": "rbac.authorization.k8s.io",
                "kind": "ClusterRole",
                "name": "omnia-operator",
            },
            "subjects": [{"kind": "ServiceAccount", "name": sa, "namespace": ns}],
        },
    ]
    redis_env = []
    if v["redis"]["enabled"]:
        out += [
            _deployment(ns, "omnia-redis", "redis", v["images"]["redis"], 1,
                        [{"name": "redis", "containerPort": 6379}], []),
            _service(ns, "omnia-redis", "redis",
                     [{"name": "redis", "port": 6379}]),
        ]
        redis_env = [{"name": "OMNIA_REDIS_ADDR",
                      "value": f"omnia-redis.{ns}.svc:6379"}]
    common_env = redis_env + [
        {"name": "OMNIA_NAMESPACE", "value": ns},
    ]
    out += [
        _deployment(
            ns, "omnia-operator", "operator", v["images"]["operator"],
            v["operator"]["replicas"],
            [{"name": "http", "containerPort": 8090},
             {"name": "metrics", "containerPort": 8091}],
            common_env + [
                {"name": "OMNIA_DASHBOARD",
                 "value": "1" if v["operator"]["dashboard"] else "0"},
            ],
        ),
        _service(ns, "omnia-operator", "operator",
                 [{"name": "http", "port": 8090}]),
        _deployment(
            ns, "omnia-session-api", "session-api", v["images"]["sessionApi"],
            v["sessionApi"]["replicas"],
            [{"name": "http", "containerPort": 8300},
             {"name": "metrics", "containerPort": 8301}],
            common_env,
        ),
        _service(ns, "omnia-session-api", "session-api",
                 [{"name": "http", "port": 8300}]),
        _deployment(
            ns, "omnia-memory-api", "memory-api", v["images"]["memoryApi"],
            v["memoryApi"]["replicas"],
            [{"name": "http", "containerPort": 8400},
             {"name": "metrics", "containerPort": 8401}],
            common_env + [
                {"name": "OMNIA_SESSION_API_URL",
                 "value": f"http://omnia-session-api.{ns}.svc:8300"},
            ],
        ),
        _service(ns, "omnia-memory-api", "memory-api",
                 [{"name": "http", "port": 8400}]),
    ]
    if v["observability"]["enabled"]:
        out += _render_observability(ns, v["observability"])
    return out


# -- observability bundle ---------------------------------------------------
# Reference charts/omnia/templates/observability: in-cluster Prometheus
# scraping every omnia pod's `metrics` port, a Grafana instance provisioned
# with the serving dashboard, and PodMonitor objects for clusters running
# prometheus-operator (the reference's agent-podmonitor.yaml shape).

GRAFANA_DASHBOARD = {
    "title": "Omnia TPU Serving",
    "uid": "omnia-serving",
    "panels": [
        {"title": "TTFT p50 (s)", "type": "timeseries", "targets": [
            {"expr": "histogram_quantile(0.5, sum(rate("
                     "omnia_facade_turn_seconds_bucket[5m])) by (le))"}]},
        {"title": "Decode tokens/sec", "type": "timeseries", "targets": [
            {"expr": "sum(rate(omnia_engine_tokens_generated_total[1m]))"}]},
        {"title": "Inference queue depth", "type": "timeseries", "targets": [
            {"expr": "sum(omnia_engine_queue_depth) by (pod)"}]},
        {"title": "Active connections", "type": "timeseries", "targets": [
            {"expr": "sum(omnia_facade_connections_active)"}]},
        {"title": "Turn errors/min", "type": "timeseries", "targets": [
            {"expr": "sum(rate(omnia_facade_turn_errors_total[1m])) * 60"}]},
        {"title": "Session writes/min", "type": "timeseries", "targets": [
            {"expr": "sum(rate(omnia_session_writes_total[1m])) * 60"}]},
    ],
}


def _render_observability(ns: str, cfg: dict) -> list[dict]:
    import json as _json

    prom_cfg = {
        "global": {"scrape_interval": "15s"},
        "scrape_configs": [{
            "job_name": "omnia",
            "kubernetes_sd_configs": [{"role": "pod"}],
            "relabel_configs": [
                # Scrape any pod exposing a port NAMED `metrics` with the
                # omnia app label — agents and core services alike (the
                # reference discovers by port name too).
                {"source_labels": ["__meta_kubernetes_pod_label_app_kubernetes_io_name"],
                 "regex": "omnia", "action": "keep"},
                {"source_labels": ["__meta_kubernetes_pod_container_port_name"],
                 "regex": "metrics", "action": "keep"},
                {"source_labels": ["__meta_kubernetes_pod_name"],
                 "target_label": "pod"},
            ],
        }],
    }
    out: list[dict] = [
        {
            "apiVersion": "v1",
            "kind": "ConfigMap",
            "metadata": {"name": "omnia-prometheus-config", "namespace": ns,
                         "labels": _labels("prometheus")},
            "data": {"prometheus.yml": _to_inline_yaml(prom_cfg)},
        },
        _deployment(ns, "omnia-prometheus", "prometheus",
                    cfg["prometheus"]["image"], 1,
                    [{"name": "http", "containerPort": 9090}], []),
        _service(ns, "omnia-prometheus", "prometheus",
                 [{"name": "http", "port": 9090}]),
        {
            "apiVersion": "v1",
            "kind": "ConfigMap",
            "metadata": {"name": "omnia-grafana-dashboards", "namespace": ns,
                         "labels": _labels("grafana")},
            "data": {"omnia-serving.json": _json.dumps(GRAFANA_DASHBOARD)},
        },
        _deployment(ns, "omnia-grafana", "grafana", cfg["grafana"]["image"], 1,
                    [{"name": "http", "containerPort": 3000}],
                    [{"name": "GF_AUTH_ANONYMOUS_ENABLED", "value": "true"}]),
        _service(ns, "omnia-grafana", "grafana",
                 [{"name": "http", "port": 3000}]),
    ]
    # Mount prometheus config + grafana dashboards into their pods.
    prom = out[1]["spec"]["template"]["spec"]
    prom["volumes"] = [{"name": "config",
                        "configMap": {"name": "omnia-prometheus-config"}}]
    prom["containers"][0]["args"] = [
        "--config.file=/etc/prometheus/prometheus.yml",
        f"--storage.tsdb.retention.time={cfg['prometheus']['retention']}",
    ]
    prom["containers"][0]["volumeMounts"] = [
        {"name": "config", "mountPath": "/etc/prometheus"}]
    graf = out[4]["spec"]["template"]["spec"]
    graf["volumes"] = [{"name": "dashboards",
                        "configMap": {"name": "omnia-grafana-dashboards"}}]
    graf["containers"][0]["volumeMounts"] = [
        {"name": "dashboards",
         "mountPath": "/var/lib/grafana/dashboards"}]
    if cfg.get("podMonitors", True):
        # prometheus-operator clusters (reference agent-podmonitor.yaml).
        for comp, selector in (
            ("agents", {"app.kubernetes.io/name": "omnia",
                        "app.kubernetes.io/component": "agent"}),
            ("services", {"app.kubernetes.io/name": "omnia"}),
        ):
            out.append({
                "apiVersion": "monitoring.coreos.com/v1",
                "kind": "PodMonitor",
                "metadata": {"name": f"omnia-{comp}", "namespace": ns,
                             "labels": _labels("monitoring")},
                "spec": {
                    "selector": {"matchLabels": selector},
                    "podMetricsEndpoints": [{"port": "metrics"}],
                },
            })
    return out


def _to_inline_yaml(doc: dict) -> str:
    import yaml

    return yaml.safe_dump(doc, sort_keys=False)


def to_yaml(manifests: list[dict]) -> str:
    import yaml

    return "---\n".join(
        yaml.safe_dump(m, sort_keys=False, default_flow_style=False)
        for m in manifests
    )


def main(argv: Optional[list[str]] = None) -> int:
    import yaml

    argv = sys.argv[1:] if argv is None else argv
    values = None
    if argv:
        with open(argv[0]) as f:
            values = yaml.safe_load(f) or {}
    manifests = render_install(values)
    from omnia_tpu.operator.manifest_lint import lint

    errs = lint(manifests)
    if errs:
        for e in errs:
            print(f"lint: {e}", file=sys.stderr)
        return 1
    print(to_yaml(manifests))
    return 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
