"""Installable deployment bundle — the chart.

Reference parity target: charts/omnia (operator + dashboard + redis +
agents RBAC + observability). Rendered in Python instead of Go
templates: `render_install(values)` returns the full manifest list and
`python -m omnia_tpu.operator.install [values.yaml] > install.yaml`
emits it as multi-doc YAML for `kubectl apply -f -`. Everything rendered
here must pass `manifest_lint.lint` — the repo's dry-run gate (tests
enforce it), and deploy/values.yaml documents every knob.

The agent pods themselves are NOT rendered here — the operator builds
those at runtime from AgentRuntime resources (deployment.K8sManifestBackend),
exactly like the reference's deployment builder.
"""

from __future__ import annotations

import sys
from typing import Optional

from omnia_tpu.operator.crds import GROUP, render_crds

DEFAULT_VALUES: dict = {
    "namespace": "omnia-system",
    "images": {
        "operator": "omnia-tpu/operator:latest",
        "sessionApi": "omnia-tpu/session-api:latest",
        "memoryApi": "omnia-tpu/memory-api:latest",
        "redis": "omnia-tpu/redisd:latest",
    },
    "operator": {"replicas": 1, "dashboard": True},
    "sessionApi": {"replicas": 1},
    "memoryApi": {"replicas": 1},
    "redis": {"enabled": True},
    # At-rest envelope encryption for session/memory storage (reference
    # cmd/session-api/main.go:210 resolver). enabled=True stamps
    # OMNIA_ENCRYPTION=local on session-api/memory-api with the KEK
    # pulled from `secretName[secretKey]` via secretKeyRef — the key
    # itself never appears in the rendered manifests.
    "encryption": {"enabled": False, "secretName": "omnia-kek",
                   "secretKey": "kek"},
    "serviceAccount": "omnia-operator",
    # Bundled observability (reference charts/omnia/templates/observability:
    # Prometheus + Grafana + Loki + Tempo + an Alloy collector). Services
    # get OMNIA_OTLP_ENDPOINT pointed at Tempo automatically; the Alloy
    # DaemonSet tails pod logs into Loki and relays any pod OTLP to Tempo.
    "observability": {
        "enabled": False,
        "prometheus": {"image": "prom/prometheus:v2.53.0", "retention": "24h"},
        "grafana": {"image": "grafana/grafana:11.1.0"},
        "loki": {"image": "grafana/loki:3.1.0", "retention": "168h"},
        "tempo": {"image": "grafana/tempo:2.5.0"},
        "collector": {"image": "grafana/alloy:v1.3.0"},
        "podMonitors": True,
    },
}

# Schema for install values (reference charts/omnia/values.schema.json):
# typo'd keys and wrong types fail at render time, not at kubectl-apply
# time. additionalProperties: false at every level is the point.
_IMAGE = {"type": "string", "minLength": 1}
_REPLICAS = {"type": "integer", "minimum": 0}
VALUES_SCHEMA = {
    "type": "object",
    "additionalProperties": False,
    "properties": {
        "namespace": {"type": "string", "minLength": 1},
        "serviceAccount": {"type": "string", "minLength": 1},
        "images": {
            "type": "object", "additionalProperties": False,
            "properties": {k: _IMAGE for k in
                           ("operator", "sessionApi", "memoryApi", "redis")},
        },
        "operator": {
            "type": "object", "additionalProperties": False,
            "properties": {"replicas": _REPLICAS,
                           "dashboard": {"type": "boolean"}},
        },
        "sessionApi": {
            "type": "object", "additionalProperties": False,
            "properties": {"replicas": _REPLICAS},
        },
        "memoryApi": {
            "type": "object", "additionalProperties": False,
            "properties": {"replicas": _REPLICAS},
        },
        "redis": {
            "type": "object", "additionalProperties": False,
            "properties": {"enabled": {"type": "boolean"}},
        },
        "encryption": {
            "type": "object", "additionalProperties": False,
            "properties": {
                "enabled": {"type": "boolean"},
                "secretName": {"type": "string", "minLength": 1},
                "secretKey": {"type": "string", "minLength": 1},
            },
        },
        "observability": {
            "type": "object", "additionalProperties": False,
            "properties": {
                "enabled": {"type": "boolean"},
                "podMonitors": {"type": "boolean"},
                "prometheus": {
                    "type": "object", "additionalProperties": False,
                    "properties": {"image": _IMAGE,
                                   "retention": {"type": "string"}},
                },
                "grafana": {
                    "type": "object", "additionalProperties": False,
                    "properties": {"image": _IMAGE},
                },
                "loki": {
                    "type": "object", "additionalProperties": False,
                    "properties": {"image": _IMAGE,
                                   "retention": {"type": "string"}},
                },
                "tempo": {
                    "type": "object", "additionalProperties": False,
                    "properties": {"image": _IMAGE,
                                   "retention": {"type": "string"}},
                },
                "collector": {
                    "type": "object", "additionalProperties": False,
                    "properties": {"image": _IMAGE},
                },
            },
        },
    },
}


class ValuesError(ValueError):
    """values.yaml failed schema validation."""


def _merge(base: dict, over: Optional[dict]) -> dict:
    out = dict(base)
    for k, v in (over or {}).items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = _merge(out[k], v)
        else:
            out[k] = v
    return out


def _labels(comp: str) -> dict:
    return {"app.kubernetes.io/name": "omnia", "app.kubernetes.io/component": comp}


def _deployment(ns: str, name: str, comp: str, image: str, replicas: int,
                ports: list[dict], env: list[dict]) -> dict:
    labels = _labels(comp)
    return {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": {"name": name, "namespace": ns, "labels": labels},
        "spec": {
            "replicas": replicas,
            "selector": {"matchLabels": labels},
            "template": {
                "metadata": {"labels": labels},
                "spec": {
                    "containers": [{
                        "name": comp,
                        "image": image,
                        "ports": ports,
                        "env": env,
                    }],
                },
            },
        },
    }


def _service(ns: str, name: str, comp: str, ports: list[dict]) -> dict:
    return {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {"name": name, "namespace": ns, "labels": _labels(comp)},
        "spec": {"selector": _labels(comp), "ports": ports},
    }


def validate_values(values: Optional[dict]) -> None:
    """Schema-gate user values (reference values.schema.json)."""
    if values is None:
        return
    import jsonschema

    try:
        jsonschema.validate(values, VALUES_SCHEMA)
    except jsonschema.ValidationError as e:
        path = ".".join(str(p) for p in e.absolute_path) or "(root)"
        raise ValuesError(f"values.{path}: {e.message}") from e


def render_install(values: Optional[dict] = None) -> list[dict]:
    validate_values(values)
    v = _merge(DEFAULT_VALUES, values)
    ns = v["namespace"]
    sa = v["serviceAccount"]
    out: list[dict] = [
        {"apiVersion": "v1", "kind": "Namespace", "metadata": {"name": ns}},
    ]
    out += render_crds()
    # RBAC: the operator watches its CRDs cluster-wide and manages agent
    # Deployments/Services/ConfigMaps in workspace namespaces.
    out += [
        {
            "apiVersion": "v1",
            "kind": "ServiceAccount",
            "metadata": {"name": sa, "namespace": ns},
        },
        {
            "apiVersion": "rbac.authorization.k8s.io/v1",
            "kind": "ClusterRole",
            "metadata": {"name": "omnia-operator"},
            "rules": [
                {"apiGroups": [GROUP],
                 "resources": ["*"],
                 "verbs": ["get", "list", "watch", "update", "patch"]},
                {"apiGroups": [GROUP],
                 "resources": ["*/status"],
                 "verbs": ["get", "update", "patch"]},
                {"apiGroups": ["apps"],
                 "resources": ["deployments"],
                 "verbs": ["get", "list", "watch", "create", "update", "patch", "delete"]},
                {"apiGroups": [""],
                 "resources": ["services", "configmaps", "secrets"],
                 "verbs": ["get", "list", "watch", "create", "update", "patch", "delete"]},
                {"apiGroups": ["autoscaling"],
                 "resources": ["horizontalpodautoscalers"],
                 "verbs": ["get", "list", "watch", "create", "update", "patch", "delete"]},
                {"apiGroups": ["policy"],
                 "resources": ["poddisruptionbudgets"],
                 "verbs": ["get", "list", "watch", "create", "update", "patch", "delete"]},
                # Leader election: cluster mode holds a Lease by default —
                # without this grant the elector 403s forever and the
                # operator blocks waiting for a lease it can never take.
                {"apiGroups": ["coordination.k8s.io"],
                 "resources": ["leases"],
                 "verbs": ["get", "list", "watch", "create", "update", "patch", "delete"]},
            ],
        },
        {
            "apiVersion": "rbac.authorization.k8s.io/v1",
            "kind": "ClusterRoleBinding",
            "metadata": {"name": "omnia-operator"},
            "roleRef": {
                "apiGroup": "rbac.authorization.k8s.io",
                "kind": "ClusterRole",
                "name": "omnia-operator",
            },
            "subjects": [{"kind": "ServiceAccount", "name": sa, "namespace": ns}],
        },
    ]
    redis_env = []
    if v["redis"]["enabled"]:
        out += [
            _deployment(ns, "omnia-redis", "redis", v["images"]["redis"], 1,
                        [{"name": "redis", "containerPort": 6379}], []),
            _service(ns, "omnia-redis", "redis",
                     [{"name": "redis", "port": 6379}]),
        ]
        redis_env = [{"name": "OMNIA_REDIS_ADDR",
                      "value": f"omnia-redis.{ns}.svc:6379"}]
    common_env = redis_env + [
        {"name": "OMNIA_NAMESPACE", "value": ns},
    ]
    enc_env = []
    if v["encryption"]["enabled"]:
        enc_env = [
            {"name": "OMNIA_ENCRYPTION", "value": "local"},
            {"name": "OMNIA_KEK_B64",
             "valueFrom": {"secretKeyRef": {
                 "name": v["encryption"]["secretName"],
                 "key": v["encryption"]["secretKey"]}}},
        ]
    if v["observability"]["enabled"]:
        # Trace export address (cli._tracer). The OPERATOR's copy is the
        # load-bearing one: it propagates to every agent pod it renders
        # (deployment.K8sManifestBackend), and agent runtimes are where
        # turn spans originate.
        common_env.append({
            "name": "OMNIA_OTLP_ENDPOINT",
            "value": f"http://omnia-tempo.{ns}.svc:4318",
        })
    out += [
        _deployment(
            ns, "omnia-operator", "operator", v["images"]["operator"],
            v["operator"]["replicas"],
            [{"name": "http", "containerPort": 8090},
             {"name": "metrics", "containerPort": 8091}],
            common_env + [
                {"name": "OMNIA_DASHBOARD",
                 "value": "1" if v["operator"]["dashboard"] else "0"},
            ],
        ),
        _service(ns, "omnia-operator", "operator",
                 [{"name": "http", "port": 8090}]),
        _deployment(
            ns, "omnia-session-api", "session-api", v["images"]["sessionApi"],
            v["sessionApi"]["replicas"],
            [{"name": "http", "containerPort": 8300},
             {"name": "metrics", "containerPort": 8301}],
            common_env + enc_env,
        ),
        _service(ns, "omnia-session-api", "session-api",
                 [{"name": "http", "port": 8300}]),
        _deployment(
            ns, "omnia-memory-api", "memory-api", v["images"]["memoryApi"],
            v["memoryApi"]["replicas"],
            [{"name": "http", "containerPort": 8400},
             {"name": "metrics", "containerPort": 8401}],
            common_env + enc_env + [
                {"name": "OMNIA_SESSION_API_URL",
                 "value": f"http://omnia-session-api.{ns}.svc:8300"},
            ],
        ),
        _service(ns, "omnia-memory-api", "memory-api",
                 [{"name": "http", "port": 8400}]),
    ]
    if v["observability"]["enabled"]:
        out += _render_observability(ns, v["observability"])
    return out


# -- observability bundle ---------------------------------------------------
# Reference charts/omnia/templates/observability: in-cluster Prometheus
# scraping every omnia pod's `metrics` port, a Grafana instance provisioned
# with the serving dashboard, and PodMonitor objects for clusters running
# prometheus-operator (the reference's agent-podmonitor.yaml shape).

GRAFANA_DASHBOARD = {
    "title": "Omnia TPU Serving",
    "uid": "omnia-serving",
    "panels": [
        {"title": "TTFT p50 (s)", "type": "timeseries", "targets": [
            {"expr": "histogram_quantile(0.5, sum(rate("
                     "omnia_facade_turn_seconds_bucket[5m])) by (le))"}]},
        {"title": "Decode tokens/sec", "type": "timeseries", "targets": [
            {"expr": "sum(rate(omnia_engine_tokens_generated_total[1m]))"}]},
        {"title": "Inference queue depth", "type": "timeseries", "targets": [
            {"expr": "sum(omnia_engine_queue_depth) by (pod)"}]},
        {"title": "Active connections", "type": "timeseries", "targets": [
            {"expr": "sum(omnia_facade_connections_active)"}]},
        {"title": "Turn errors/min", "type": "timeseries", "targets": [
            {"expr": "sum(rate(omnia_facade_turn_errors_total[1m])) * 60"}]},
        {"title": "Session writes/min", "type": "timeseries", "targets": [
            {"expr": "sum(rate(omnia_session_writes_total[1m])) * 60"}]},
    ],
}


def _render_observability(ns: str, cfg: dict) -> list[dict]:
    import json as _json

    prom_cfg = {
        "global": {"scrape_interval": "15s"},
        "scrape_configs": [{
            "job_name": "omnia",
            "kubernetes_sd_configs": [{"role": "pod"}],
            "relabel_configs": [
                # Scrape any pod exposing a port NAMED `metrics` with the
                # omnia app label — agents and core services alike (the
                # reference discovers by port name too).
                {"source_labels": ["__meta_kubernetes_pod_label_app_kubernetes_io_name"],
                 "regex": "omnia", "action": "keep"},
                {"source_labels": ["__meta_kubernetes_pod_container_port_name"],
                 "regex": "metrics", "action": "keep"},
                {"source_labels": ["__meta_kubernetes_pod_name"],
                 "target_label": "pod"},
            ],
        }],
    }
    out: list[dict] = [
        {
            "apiVersion": "v1",
            "kind": "ConfigMap",
            "metadata": {"name": "omnia-prometheus-config", "namespace": ns,
                         "labels": _labels("prometheus")},
            "data": {"prometheus.yml": _to_inline_yaml(prom_cfg)},
        },
        _deployment(ns, "omnia-prometheus", "prometheus",
                    cfg["prometheus"]["image"], 1,
                    [{"name": "http", "containerPort": 9090}], []),
        _service(ns, "omnia-prometheus", "prometheus",
                 [{"name": "http", "port": 9090}]),
        {
            "apiVersion": "v1",
            "kind": "ConfigMap",
            "metadata": {"name": "omnia-grafana-dashboards", "namespace": ns,
                         "labels": _labels("grafana")},
            "data": {"omnia-serving.json": _json.dumps(GRAFANA_DASHBOARD)},
        },
        _deployment(ns, "omnia-grafana", "grafana", cfg["grafana"]["image"], 1,
                    [{"name": "http", "containerPort": 3000}],
                    [{"name": "GF_AUTH_ANONYMOUS_ENABLED", "value": "true"}]),
        _service(ns, "omnia-grafana", "grafana",
                 [{"name": "http", "port": 3000}]),
    ]
    # Mount prometheus config + grafana dashboards into their pods.
    prom = out[1]["spec"]["template"]["spec"]
    prom["volumes"] = [{"name": "config",
                        "configMap": {"name": "omnia-prometheus-config"}}]
    prom["containers"][0]["args"] = [
        "--config.file=/etc/prometheus/prometheus.yml",
        f"--storage.tsdb.retention.time={cfg['prometheus']['retention']}",
    ]
    prom["containers"][0]["volumeMounts"] = [
        {"name": "config", "mountPath": "/etc/prometheus"}]
    graf = out[4]["spec"]["template"]["spec"]
    graf["volumes"] = [
        {"name": "dashboards",
         "configMap": {"name": "omnia-grafana-dashboards"}},
        {"name": "datasources",
         "configMap": {"name": "omnia-grafana-datasources"}},
    ]
    graf["containers"][0]["volumeMounts"] = [
        {"name": "dashboards",
         "mountPath": "/var/lib/grafana/dashboards"},
        {"name": "datasources",
         "mountPath": "/etc/grafana/provisioning/datasources"},
    ]
    # Metrics + logs + traces provisioned as one Grafana view.
    out.append({
        "apiVersion": "v1",
        "kind": "ConfigMap",
        "metadata": {"name": "omnia-grafana-datasources", "namespace": ns,
                     "labels": _labels("grafana")},
        "data": {"datasources.yaml": _to_inline_yaml({
            "apiVersion": 1,
            "datasources": [
                {"name": "Prometheus", "type": "prometheus",
                 "url": f"http://omnia-prometheus.{ns}.svc:9090",
                 "isDefault": True},
                {"name": "Loki", "type": "loki",
                 "url": f"http://omnia-loki.{ns}.svc:3100"},
                {"name": "Tempo", "type": "tempo",
                 "url": f"http://omnia-tempo.{ns}.svc:3200"},
            ],
        })},
    })
    out += _render_logs_traces(ns, cfg)
    if cfg.get("podMonitors", True):
        # prometheus-operator clusters (reference agent-podmonitor.yaml).
        for comp, selector in (
            ("agents", {"app.kubernetes.io/name": "omnia",
                        "app.kubernetes.io/component": "agent"}),
            ("services", {"app.kubernetes.io/name": "omnia"}),
        ):
            out.append({
                "apiVersion": "monitoring.coreos.com/v1",
                "kind": "PodMonitor",
                "metadata": {"name": f"omnia-{comp}", "namespace": ns,
                             "labels": _labels("monitoring")},
                "spec": {
                    "selector": {"matchLabels": selector},
                    "podMetricsEndpoints": [{"port": "metrics"}],
                },
            })
    return out


def _render_logs_traces(ns: str, cfg: dict) -> list[dict]:
    """Loki (logs) + Tempo (traces) + an Alloy collector DaemonSet
    (reference charts/omnia/templates/observability bundles the same
    trio). Single-binary filesystem-backed configs: the in-cluster dev/
    eval posture; production clusters swap object-storage backends via
    values images/config."""
    loki_cfg = {
        "auth_enabled": False,
        "server": {"http_listen_port": 3100},
        "common": {
            "replication_factor": 1,
            "ring": {"kvstore": {"store": "inmemory"}},
            "path_prefix": "/loki",
        },
        "schema_config": {"configs": [{
            "from": "2024-01-01", "store": "tsdb",
            "object_store": "filesystem", "schema": "v13",
            "index": {"prefix": "index_", "period": "24h"},
        }]},
        "limits_config": {
            "retention_period": cfg["loki"]["retention"],
        },
        # retention_period is a no-op without the compactor actively
        # enforcing it (Loki 3.x) — without this the emptyDir fills until
        # the node evicts the pod.
        "compactor": {
            "working_directory": "/loki/compactor",
            "retention_enabled": True,
            "delete_request_store": "filesystem",
        },
    }
    tempo_cfg = {
        "server": {"http_listen_port": 3200},
        "distributor": {"receivers": {"otlp": {"protocols": {
            "grpc": {"endpoint": "0.0.0.0:4317"},
            "http": {"endpoint": "0.0.0.0:4318"},
        }}}},
        "storage": {"trace": {"backend": "local",
                              "local": {"path": "/var/tempo"}}},
        # Same fill-until-eviction failure mode as Loki: traces land on
        # an emptyDir, so the compactor must actively expire blocks
        # (mirrors the loki retention value rather than Tempo's 14d
        # default).
        "compactor": {"compaction": {
            "block_retention": cfg["tempo"].get(
                "retention", cfg["loki"]["retention"]
            ),
        }},
    }
    # Alloy config: tail every omnia pod's logs into Loki, and relay any
    # pod-local OTLP (agents that can't reach Tempo's Service directly)
    # onward — the reference's Alloy role.
    alloy_cfg = "\n".join([
        # Node-scoped discovery: each DaemonSet pod tails ONLY its own
        # node's pods (NODE_NAME via fieldRef below) — without the field
        # selector every node would push every pod's logs, duplicating
        # them by the node count.
        'discovery.kubernetes "pods" {',
        '  role = "pod"',
        '  selectors {',
        '    role  = "pod"',
        '    field = "spec.nodeName=" + sys.env("NODE_NAME")',
        '  }',
        '}',
        '',
        'discovery.relabel "omnia_pods" {',
        '  targets = discovery.kubernetes.pods.targets',
        '  rule {',
        '    source_labels = ["__meta_kubernetes_pod_label_app_kubernetes_io_name"]',
        '    regex         = "omnia"',
        '    action        = "keep"',
        '  }',
        '}',
        '',
        'loki.source.kubernetes "pod_logs" {',
        '  targets    = discovery.relabel.omnia_pods.output',
        '  forward_to = [loki.write.default.receiver]',
        '}',
        '',
        'loki.write "default" {',
        f'  endpoint {{ url = "http://omnia-loki.{ns}.svc:3100/loki/api/v1/push" }}',
        '}',
        '',
        'otelcol.receiver.otlp "relay" {',
        '  grpc { endpoint = "0.0.0.0:4317" }',
        '  http { endpoint = "0.0.0.0:4318" }',
        '  output { traces = [otelcol.exporter.otlphttp.tempo.input] }',
        '}',
        '',
        'otelcol.exporter.otlphttp "tempo" {',
        f'  client {{ endpoint = "http://omnia-tempo.{ns}.svc:4318" }}',
        '}',
    ])
    out: list[dict] = [
        {
            "apiVersion": "v1",
            "kind": "ConfigMap",
            "metadata": {"name": "omnia-loki-config", "namespace": ns,
                         "labels": _labels("loki")},
            "data": {"loki.yaml": _to_inline_yaml(loki_cfg)},
        },
        _deployment(ns, "omnia-loki", "loki", cfg["loki"]["image"], 1,
                    [{"name": "http", "containerPort": 3100}], []),
        _service(ns, "omnia-loki", "loki", [{"name": "http", "port": 3100}]),
        {
            "apiVersion": "v1",
            "kind": "ConfigMap",
            "metadata": {"name": "omnia-tempo-config", "namespace": ns,
                         "labels": _labels("tempo")},
            "data": {"tempo.yaml": _to_inline_yaml(tempo_cfg)},
        },
        _deployment(ns, "omnia-tempo", "tempo", cfg["tempo"]["image"], 1,
                    [{"name": "http", "containerPort": 3200},
                     {"name": "otlp-grpc", "containerPort": 4317},
                     {"name": "otlp-http", "containerPort": 4318}], []),
        _service(ns, "omnia-tempo", "tempo",
                 [{"name": "http", "port": 3200},
                  {"name": "otlp-grpc", "port": 4317},
                  {"name": "otlp-http", "port": 4318}]),
        {
            "apiVersion": "v1",
            "kind": "ConfigMap",
            "metadata": {"name": "omnia-collector-config", "namespace": ns,
                         "labels": _labels("collector")},
            "data": {"config.alloy": alloy_cfg},
        },
    ]
    loki = out[1]["spec"]["template"]["spec"]
    loki["volumes"] = [{"name": "config",
                        "configMap": {"name": "omnia-loki-config"}},
                       {"name": "data", "emptyDir": {}}]
    loki["containers"][0]["args"] = ["-config.file=/etc/loki/loki.yaml"]
    loki["containers"][0]["volumeMounts"] = [
        {"name": "config", "mountPath": "/etc/loki"},
        {"name": "data", "mountPath": "/loki"}]
    tempo = out[4]["spec"]["template"]["spec"]
    tempo["volumes"] = [{"name": "config",
                         "configMap": {"name": "omnia-tempo-config"}},
                        {"name": "data", "emptyDir": {}}]
    tempo["containers"][0]["args"] = ["-config.file=/etc/tempo/tempo.yaml"]
    tempo["containers"][0]["volumeMounts"] = [
        {"name": "config", "mountPath": "/etc/tempo"},
        {"name": "data", "mountPath": "/var/tempo"}]
    labels = _labels("collector")
    # The collector gets its OWN ServiceAccount with the minimal log-
    # tailing grant: attaching the cluster-wide pods/log read to the
    # operator's ClusterRole would hand the operator broader privilege
    # than either component needs.
    out.append({
        "apiVersion": "v1",
        "kind": "ServiceAccount",
        "metadata": {"name": "omnia-collector", "namespace": ns},
    })
    out.append({
        "apiVersion": "rbac.authorization.k8s.io/v1",
        "kind": "ClusterRole",
        "metadata": {"name": "omnia-collector"},
        "rules": [
            {"apiGroups": [""],
             "resources": ["pods"],
             "verbs": ["get", "list", "watch"]},
            {"apiGroups": [""],
             "resources": ["pods/log"],
             "verbs": ["get"]},
        ],
    })
    out.append({
        "apiVersion": "rbac.authorization.k8s.io/v1",
        "kind": "ClusterRoleBinding",
        "metadata": {"name": "omnia-collector"},
        "roleRef": {
            "apiGroup": "rbac.authorization.k8s.io",
            "kind": "ClusterRole",
            "name": "omnia-collector",
        },
        "subjects": [{"kind": "ServiceAccount", "name": "omnia-collector",
                      "namespace": ns}],
    })
    out.append({
        "apiVersion": "apps/v1",
        "kind": "DaemonSet",
        "metadata": {"name": "omnia-collector", "namespace": ns,
                     "labels": labels},
        "spec": {
            "selector": {"matchLabels": labels},
            "template": {
                "metadata": {"labels": labels},
                "spec": {
                    "serviceAccountName": "omnia-collector",
                    "containers": [{
                        "name": "collector",
                        "image": cfg["collector"]["image"],
                        "args": ["run", "/etc/alloy/config.alloy"],
                        "env": [{
                            "name": "NODE_NAME",
                            "valueFrom": {"fieldRef": {
                                "fieldPath": "spec.nodeName"}},
                        }],
                        "ports": [
                            {"name": "otlp-grpc", "containerPort": 4317},
                            {"name": "otlp-http", "containerPort": 4318},
                        ],
                        "volumeMounts": [
                            {"name": "config", "mountPath": "/etc/alloy"},
                        ],
                    }],
                    "volumes": [{
                        "name": "config",
                        "configMap": {"name": "omnia-collector-config"},
                    }],
                },
            },
        },
    })
    # Stable in-cluster address for the OTLP relay (pods that prefer the
    # collector hop over Tempo's Service directly).
    out.append(_service(ns, "omnia-collector", "collector",
                        [{"name": "otlp-grpc", "port": 4317},
                         {"name": "otlp-http", "port": 4318}]))
    return out


def _to_inline_yaml(doc: dict) -> str:
    import yaml

    return yaml.safe_dump(doc, sort_keys=False)


def to_yaml(manifests: list[dict]) -> str:
    import yaml

    return "---\n".join(
        yaml.safe_dump(m, sort_keys=False, default_flow_style=False)
        for m in manifests
    )


def main(argv: Optional[list[str]] = None) -> int:
    import yaml

    argv = sys.argv[1:] if argv is None else argv
    values = None
    if argv:
        with open(argv[0]) as f:
            values = yaml.safe_load(f) or {}
    manifests = render_install(values)
    from omnia_tpu.operator.manifest_lint import lint

    errs = lint(manifests)
    if errs:
        for e in errs:
            print(f"lint: {e}", file=sys.stderr)
        return 1
    print(to_yaml(manifests))
    return 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
