"""Installable deployment bundle — the chart.

Reference parity target: charts/omnia (operator + dashboard + redis +
agents RBAC + observability). Rendered in Python instead of Go
templates: `render_install(values)` returns the full manifest list and
`python -m omnia_tpu.operator.install [values.yaml] > install.yaml`
emits it as multi-doc YAML for `kubectl apply -f -`. Everything rendered
here must pass `manifest_lint.lint` — the repo's dry-run gate (tests
enforce it), and deploy/values.yaml documents every knob.

The agent pods themselves are NOT rendered here — the operator builds
those at runtime from AgentRuntime resources (deployment.K8sManifestBackend),
exactly like the reference's deployment builder.
"""

from __future__ import annotations

import sys
from typing import Optional

from omnia_tpu.operator.crds import GROUP, render_crds

DEFAULT_VALUES: dict = {
    "namespace": "omnia-system",
    "images": {
        "operator": "omnia-tpu/operator:latest",
        "sessionApi": "omnia-tpu/session-api:latest",
        "memoryApi": "omnia-tpu/memory-api:latest",
        "redis": "omnia-tpu/redisd:latest",
    },
    "operator": {"replicas": 1, "dashboard": True},
    "sessionApi": {"replicas": 1},
    "memoryApi": {"replicas": 1},
    "redis": {"enabled": True},
    "serviceAccount": "omnia-operator",
}


def _merge(base: dict, over: Optional[dict]) -> dict:
    out = dict(base)
    for k, v in (over or {}).items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = _merge(out[k], v)
        else:
            out[k] = v
    return out


def _labels(comp: str) -> dict:
    return {"app.kubernetes.io/name": "omnia", "app.kubernetes.io/component": comp}


def _deployment(ns: str, name: str, comp: str, image: str, replicas: int,
                ports: list[dict], env: list[dict]) -> dict:
    labels = _labels(comp)
    return {
        "apiVersion": "apps/v1",
        "kind": "Deployment",
        "metadata": {"name": name, "namespace": ns, "labels": labels},
        "spec": {
            "replicas": replicas,
            "selector": {"matchLabels": labels},
            "template": {
                "metadata": {"labels": labels},
                "spec": {
                    "containers": [{
                        "name": comp,
                        "image": image,
                        "ports": ports,
                        "env": env,
                    }],
                },
            },
        },
    }


def _service(ns: str, name: str, comp: str, ports: list[dict]) -> dict:
    return {
        "apiVersion": "v1",
        "kind": "Service",
        "metadata": {"name": name, "namespace": ns, "labels": _labels(comp)},
        "spec": {"selector": _labels(comp), "ports": ports},
    }


def render_install(values: Optional[dict] = None) -> list[dict]:
    v = _merge(DEFAULT_VALUES, values)
    ns = v["namespace"]
    sa = v["serviceAccount"]
    out: list[dict] = [
        {"apiVersion": "v1", "kind": "Namespace", "metadata": {"name": ns}},
    ]
    out += render_crds()
    # RBAC: the operator watches its CRDs cluster-wide and manages agent
    # Deployments/Services/ConfigMaps in workspace namespaces.
    out += [
        {
            "apiVersion": "v1",
            "kind": "ServiceAccount",
            "metadata": {"name": sa, "namespace": ns},
        },
        {
            "apiVersion": "rbac.authorization.k8s.io/v1",
            "kind": "ClusterRole",
            "metadata": {"name": "omnia-operator"},
            "rules": [
                {"apiGroups": [GROUP],
                 "resources": ["*"],
                 "verbs": ["get", "list", "watch", "update", "patch"]},
                {"apiGroups": [GROUP],
                 "resources": ["*/status"],
                 "verbs": ["get", "update", "patch"]},
                {"apiGroups": ["apps"],
                 "resources": ["deployments"],
                 "verbs": ["get", "list", "watch", "create", "update", "patch", "delete"]},
                {"apiGroups": [""],
                 "resources": ["services", "configmaps", "secrets"],
                 "verbs": ["get", "list", "watch", "create", "update", "patch", "delete"]},
                {"apiGroups": ["autoscaling"],
                 "resources": ["horizontalpodautoscalers"],
                 "verbs": ["get", "list", "watch", "create", "update", "patch", "delete"]},
            ],
        },
        {
            "apiVersion": "rbac.authorization.k8s.io/v1",
            "kind": "ClusterRoleBinding",
            "metadata": {"name": "omnia-operator"},
            "roleRef": {
                "apiGroup": "rbac.authorization.k8s.io",
                "kind": "ClusterRole",
                "name": "omnia-operator",
            },
            "subjects": [{"kind": "ServiceAccount", "name": sa, "namespace": ns}],
        },
    ]
    redis_env = []
    if v["redis"]["enabled"]:
        out += [
            _deployment(ns, "omnia-redis", "redis", v["images"]["redis"], 1,
                        [{"name": "redis", "containerPort": 6379}], []),
            _service(ns, "omnia-redis", "redis",
                     [{"name": "redis", "port": 6379}]),
        ]
        redis_env = [{"name": "OMNIA_REDIS_ADDR",
                      "value": f"omnia-redis.{ns}.svc:6379"}]
    common_env = redis_env + [
        {"name": "OMNIA_NAMESPACE", "value": ns},
    ]
    out += [
        _deployment(
            ns, "omnia-operator", "operator", v["images"]["operator"],
            v["operator"]["replicas"],
            [{"name": "http", "containerPort": 8090},
             {"name": "metrics", "containerPort": 8091}],
            common_env + [
                {"name": "OMNIA_DASHBOARD",
                 "value": "1" if v["operator"]["dashboard"] else "0"},
            ],
        ),
        _service(ns, "omnia-operator", "operator",
                 [{"name": "http", "port": 8090}]),
        _deployment(
            ns, "omnia-session-api", "session-api", v["images"]["sessionApi"],
            v["sessionApi"]["replicas"],
            [{"name": "http", "containerPort": 8300},
             {"name": "metrics", "containerPort": 8301}],
            common_env,
        ),
        _service(ns, "omnia-session-api", "session-api",
                 [{"name": "http", "port": 8300}]),
        _deployment(
            ns, "omnia-memory-api", "memory-api", v["images"]["memoryApi"],
            v["memoryApi"]["replicas"],
            [{"name": "http", "containerPort": 8400},
             {"name": "metrics", "containerPort": 8401}],
            common_env + [
                {"name": "OMNIA_SESSION_API_URL",
                 "value": f"http://omnia-session-api.{ns}.svc:8300"},
            ],
        ),
        _service(ns, "omnia-memory-api", "memory-api",
                 [{"name": "http", "port": 8400}]),
    ]
    return out


def to_yaml(manifests: list[dict]) -> str:
    import yaml

    return "---\n".join(
        yaml.safe_dump(m, sort_keys=False, default_flow_style=False)
        for m in manifests
    )


def main(argv: Optional[list[str]] = None) -> int:
    import yaml

    argv = sys.argv[1:] if argv is None else argv
    values = None
    if argv:
        with open(argv[0]) as f:
            values = yaml.safe_load(f) or {}
    manifests = render_install(values)
    from omnia_tpu.operator.manifest_lint import lint

    errs = lint(manifests)
    if errs:
        for e in errs:
            print(f"lint: {e}", file=sys.stderr)
        return 1
    print(to_yaml(manifests))
    return 0


if __name__ == "__main__":  # pragma: no cover
    sys.exit(main())
