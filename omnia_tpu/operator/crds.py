"""CustomResourceDefinition manifests for the 9 declarative kinds.

The cluster-facing twin of `operator/resources.py` (reference
config/crd/bases/*.yaml, generated there by controller-gen from
api/v1alpha1 types). Here the CRDs are generated from the same enum
vocabularies the in-process admission validation uses
(`operator/validation.py`), so the schema the cluster enforces and the
schema the operator enforces cannot drift apart. `deploy/crds/*.yaml` is
the committed output; tests assert the files match this generator
(the controller-gen make-manifests discipline).

Structural-schema rules honored: every object schema either types its
properties or carries x-kubernetes-preserve-unknown-fields for
deliberately open maps (pack params, tool args, annotations).
"""

from __future__ import annotations

from omnia_tpu.operator.resources import (
    AGENT_MODES,
    API_VERSION,
    FACADE_TYPES,
    PROVIDER_ROLES,
    PROVIDER_TYPES,
    TOOL_HANDLER_TYPES,
)

GROUP = API_VERSION.split("/")[0]
VERSION = API_VERSION.split("/")[1]


def _str(enum=None, **kw):
    s = {"type": "string", **kw}
    if enum:
        s["enum"] = list(enum)
    return s


def _obj(props=None, required=None, open_=False, desc=None):
    s: dict = {"type": "object"}
    if props:
        s["properties"] = props
    if required:
        s["required"] = list(required)
    if open_:
        s["x-kubernetes-preserve-unknown-fields"] = True
    if desc:
        s["description"] = desc
    return s


def _arr(items):
    return {"type": "array", "items": items}


_INT = {"type": "integer"}
_NUM = {"type": "number"}
_BOOL = {"type": "boolean"}
_REF = _obj({"name": _str()}, required=["name"])


def _agent_runtime_schema() -> dict:
    facade = _obj(
        {
            "type": _str(enum=FACADE_TYPES),
            "path": _str(),
            "auth": _obj(open_=True),
        },
        required=["type"],
    )
    autoscaling = _obj({
        "minReplicas": _INT,
        "maxReplicas": _INT,
        "scaleToZero": _BOOL,
        "queueDepthTarget": _INT,
    })
    rollout = _obj({
        "steps": _arr(_obj({"weight": _INT, "pause_s": _NUM})),
        "analysis": _obj(open_=True),
        "autoPromote": _BOOL,
    })
    return _obj(
        {
            "mode": _str(enum=AGENT_MODES),
            "promptPackRef": _REF,
            "toolRegistryRef": _REF,
            "providers": _arr(_obj({
                "name": _str(),
                "providerRef": _REF,
                "role": _str(enum=PROVIDER_ROLES),
            }, required=["providerRef"])),
            "facades": _arr(facade),
            "context": _obj({"ttl_s": _NUM, "store": _str()}),
            "memoryRef": _REF,
            "privacyPolicyRef": _REF,
            "replicas": _INT,
            "autoscaling": autoscaling,
            "rollout": rollout,
            "duplex": _obj({"enabled": _BOOL, "format": _obj(open_=True)}),
            "evals": _arr(_obj(open_=True)),
            "externalAuth": _obj(open_=True),
            "serviceGroup": _str(),
            "facadeImage": _str(),
            "runtimeImage": _str(),
            "tpuChips": _INT,
            # Multi-host engine: pods per model replica (StatefulSet +
            # jax.distributed; parallel/distributed.py env contract).
            "tpuHosts": _INT,
            "podOverrides": _obj(open_=True),
        },
        required=["promptPackRef", "providers"],
    )


def _provider_schema() -> dict:
    return _obj(
        {
            "type": _str(enum=PROVIDER_TYPES),
            "role": _str(enum=PROVIDER_ROLES),
            "model": _str(),
            "options": _obj(open_=True),
            # Key names match the admission/controller vocabulary
            # (validation.py pricing checks, controller._resolve_refs) —
            # the apiserver-shim schema gate caught the earlier *MTokUSD
            # drift.
            "pricing": _obj({
                "inputPerMTok": _NUM,
                "outputPerMTok": _NUM,
            }),
            "engine": _obj({
                "numSlots": _INT,
                "maxSeq": _INT,
                "dtype": _str(),
                "dp": _INT,
                "tp": _INT,
                "decodeChunk": _INT,
                "maxSessions": _INT,
                # Cross-session shared-prefix KV pool (docs/serving.md).
                "prefixCacheSlots": _INT,
                "prefixCacheRows": _INT,
            }),
        },
        required=["type"],
    )


def _prompt_pack_schema() -> dict:
    return _obj(
        {
            "content": _obj(open_=True, desc="compiled pack JSON"),
            "sourceRef": _REF,
            "version": _str(),
        },
        required=["content"],
    )


def _tool_registry_schema() -> dict:
    # handler carries per-type config blocks, mirroring the reference's
    # HandlerEntry (reference internal/runtime/tools/config.go:131-169:
    # grpcConfig/mcpConfig/openAPIConfig alongside the plain http fields).
    handler = _obj({
        "type": _str(enum=TOOL_HANDLER_TYPES),
        "url": _str(),
        "method": _str(),
        "headers": _obj(open_=True),
        "timeoutSeconds": _NUM,
        "endpoint": _str(),
        "remoteName": _str(),
        "operation": _str(),
        "spec": _obj(open_=True),
        "specURL": _str(),
        "baseURL": _str(),
        "grpcConfig": _obj({
            "endpoint": _str(),
            "tls": _BOOL,
            "authToken": _str(),
        }, open_=True),
        "mcpConfig": _obj({
            "transport": _str(enum=("stdio", "http", "streamable-http")),
            "command": _str(),
            "args": _arr(_str()),
            "env": _obj(open_=True),
            "workDir": _str(),
            "endpoint": _str(),
            "headers": _obj(open_=True),
            "toolFilter": _obj({
                "allowlist": _arr(_str()),
                "blocklist": _arr(_str()),
            }),
        }),
        "openAPIConfig": _obj({
            "specURL": _str(),
            "baseURL": _str(),
            "headers": _obj(open_=True),
        }, open_=True),
    }, required=["type"])
    return _obj({
        # Reachability probing (reference toolregistry_types.go
        # ProbeConfig): the controller TCP-dials each network handler and
        # surfaces per-tool Available/Unavailable + a registry phase.
        "probe": _obj({
            "enabled": _BOOL,
            "timeoutSeconds": _NUM,
            "intervalSeconds": _NUM,
        }),
        "tools": _arr(_obj({
            "name": _str(),
            "description": _str(),
            "handler": handler,
            "inputSchema": _obj(open_=True),
            "input_schema": _obj(open_=True),  # legacy spelling, examples/
            "auth": _obj(open_=True),
            "timeout_s": _NUM,
        }, required=["name"])),
    }, required=["tools"])


def _workspace_schema() -> dict:
    return _obj({
        "environment": _str(),
        "services": _arr(_obj({
            "name": _str(),
            "sessionApi": _BOOL,
            "memoryApi": _BOOL,
        }, required=["name"])),
        "roleBindings": _arr(_obj(open_=True)),
        "storage": _obj(open_=True),
    }, required=["environment"])


def _agent_policy_schema() -> dict:
    return _obj({
        "rules": _arr(_obj({
            "tools": _arr(_str()),
            "effect": _str(enum=("allow", "deny")),
            "when": _str(),
        }, required=["effect"])),
    }, required=["rules"])


def _memory_policy_schema() -> dict:
    return _obj({
        "tiers": _arr(_str()),
        "ttl_s": _NUM,
        "halfLife_s": _NUM,
        "consentCategories": _arr(_str()),
        "ingestion": _obj(open_=True),
    })


def _session_retention_schema() -> dict:
    return _obj({
        "hot_ttl_s": _NUM,
        "warm_ttl_s": _NUM,
        "cold_ttl_s": _NUM,
        "purgeDeleted": _BOOL,
    })


def _arena_job_schema() -> dict:
    # scenarios/scenariosFrom are an either-or (admission enforces it);
    # requiring scenarios here would reject every source-fed job.
    return _obj({
        "scenarios": _arr(_obj({
            "name": _str(),
            "turns": _arr(_obj(open_=True)),
            "checks": _arr(_obj(open_=True)),
        }, required=["name"], open_=True)),
        "scenariosFrom": _obj({
            "name": _str(),
            "path": _str(),
        }, required=["name"]),
        "providers": _arr(_str()),
        "repeats": _INT,
        "mode": _str(enum=("direct", "fleet")),
        "threshold": _obj({
            "min_pass_rate": _NUM,
            "max_error_rate": _NUM,
            "max_p95_latency_s": _NUM,
            # Simulator SLO gates (evals/trafficsim → Aggregator
            # add_slo_cells): attainment + flight-recorder percentiles.
            "min_slo_attainment": _NUM,
            "max_p95_ttft_ms": _NUM,
            "max_p95_itl_ms": _NUM,
            # Decode-ring bench gate (bench aux.devloop → Aggregator
            # add_devloop): tok/s ratio floor on non-self-disabled runs.
            "min_devloop_ratio": _NUM,
        }),
    }, required=["providers"])


def _tool_policy_schema() -> dict:
    return _obj({
        "tools": _arr(_str()),
        "agents": _arr(_str()),
        "rules": _arr(_obj({
            "action": _str(enum=("allow", "deny")),
            "when": _str(),
            "reason": _str(),
        }, required=["action"])),
        "default_action": _str(enum=("allow", "deny")),
        "priority": _INT,
    }, required=["rules"])


def _session_privacy_policy_schema() -> dict:
    return _obj({
        # Compliance preset expanded server-side (ee/pkg/compliance).
        "preset": _str(enum=("gdpr", "hipaa", "ccpa")),
        "recording": _BOOL,
        "redactFields": _arr(_str()),
        "consentCategories": _arr(_str()),
        "retention": _obj(open_=True),
        "userOptOut": _obj(open_=True),
        "encryption": _obj(open_=True),
    })


def _rollout_analysis_schema() -> dict:
    return _obj({
        "metrics": _arr(_obj({
            "name": _str(),
            "threshold": _NUM,
            "maxErrorRate": _NUM,
            "maxP95LatencyS": _NUM,
        }, required=["name"])),
        "interval_s": _NUM,
    }, required=["metrics"])


def _skill_source_schema() -> dict:
    return _obj({
        "source": _obj({
            "type": _str(enum=("dir", "configmap", "git", "oci")),
            "path": _str(),
            "ref": _str(),
        }, required=["type"]),
        "interval_s": _NUM,
    }, required=["source"])


# Shared source shape for PromptPackSource / Arena*Source (reference
# sourcesync_types.go:56-58: git | oci | configmap; local for devroots).
def _sync_source() -> dict:
    return _obj({
        "type": _str(enum=("git", "oci", "configmap", "local")),
        "repo": _str(desc="git clone url"),
        "ref": _str(desc="git branch/tag, or OCI host/repo:tag[@digest]"),
        "path": _str(),
        "data": _obj(open_=True, desc="configmap payload {filename: text}"),
        "token": _str(desc="OCI bearer token"),
    }, required=["type"])


def _prompt_pack_source_schema() -> dict:
    return _obj({
        "source": _sync_source(),
        "packName": _str(desc="target PromptPack name (default: source name)"),
        "packFile": _str(desc="pack JSON filename in the source (default pack.json)"),
        "interval_s": _NUM,
    }, required=["source"])


def _arena_source_schema() -> dict:
    return _obj({
        "source": _sync_source(),
        "interval_s": _NUM,
    }, required=["source"])


def _arena_dev_session_schema() -> dict:
    return _obj({
        "agentRef": _REF,
        "ttl_s": _NUM,
        "packOverride": _obj(open_=True),
    }, required=["agentRef"])


# kind → (plural, schema builder, short names)
KINDS: dict[str, tuple[str, object, list[str]]] = {
    "AgentRuntime": ("agentruntimes", _agent_runtime_schema, ["ar"]),
    "Provider": ("providers", _provider_schema, ["prov"]),
    "PromptPack": ("promptpacks", _prompt_pack_schema, ["pack"]),
    "ToolRegistry": ("toolregistries", _tool_registry_schema, ["tools"]),
    "Workspace": ("workspaces", _workspace_schema, ["ws"]),
    "AgentPolicy": ("agentpolicies", _agent_policy_schema, []),
    "MemoryPolicy": ("memorypolicies", _memory_policy_schema, []),
    "SessionRetentionPolicy": (
        "sessionretentionpolicies", _session_retention_schema, ["srp"],
    ),
    "SkillSource": ("skillsources", _skill_source_schema, []),
    # EE kinds (reference ee/api/v1alpha1).
    "ArenaJob": ("arenajobs", _arena_job_schema, ["aj"]),
    "ToolPolicy": ("toolpolicies", _tool_policy_schema, []),
    "SessionPrivacyPolicy": (
        "sessionprivacypolicies", _session_privacy_policy_schema, ["spp"],
    ),
    "RolloutAnalysis": ("rolloutanalyses", _rollout_analysis_schema, []),
    "PromptPackSource": ("promptpacksources", _prompt_pack_source_schema, ["pps"]),
    "ArenaSource": ("arenasources", _arena_source_schema, []),
    "ArenaTemplateSource": ("arenatemplatesources", _arena_source_schema, []),
    "ArenaDevSession": ("arenadevsessions", _arena_dev_session_schema, ["ads"]),
}


def render_crd(kind: str) -> dict:
    plural, schema_fn, short = KINDS[kind]
    return {
        "apiVersion": "apiextensions.k8s.io/v1",
        "kind": "CustomResourceDefinition",
        "metadata": {"name": f"{plural}.{GROUP}"},
        "spec": {
            "group": GROUP,
            "names": {
                "kind": kind,
                "listKind": f"{kind}List",
                "plural": plural,
                "singular": kind.lower(),
                **({"shortNames": short} if short else {}),
            },
            "scope": "Namespaced",
            "versions": [
                {
                    "name": VERSION,
                    "served": True,
                    "storage": True,
                    "subresources": {"status": {}},
                    "schema": {
                        "openAPIV3Schema": _obj({
                            "apiVersion": _str(),
                            "kind": _str(),
                            "metadata": {"type": "object"},
                            "spec": schema_fn(),
                            "status": _obj(open_=True),
                        }),
                    },
                }
            ],
        },
    }


def render_crds() -> list[dict]:
    return [render_crd(kind) for kind in KINDS]
