"""Content-source reconcilers of the controller.

PromptPackSource / SkillSource / ArenaSource / ArenaTemplateSource /
ArenaDevSession sync flows (reference promptpacksource_controller.go,
skillsource_controller.go, ee arena source controllers): fetch from
git/oci/configmap/local through the shared syncer, version-stamp status,
and fan content changes out to consuming packs/agents. Split from
controller.py so the sync pipeline reads as one unit; mixed into
:class:`ControllerManager`.
"""

from __future__ import annotations

import logging
import time

from omnia_tpu.operator.resources import Resource, ResourceKind

logger = logging.getLogger(__name__)


class _SourceReconcilersMixin:
    """Source-sync methods of :class:`ControllerManager` (uses its store,
    queue, license manager, and deployments map)."""

    def _syncer(self):
        """Lazy shared source syncer (OMNIA_SYNC_ROOT or a temp dir — the
        reference syncs to a workspace PVC, sourcesync/syncer.go:92)."""
        if getattr(self, "_syncer_inst", None) is None:
            import os
            import tempfile

            from omnia_tpu.operator.sourcesync import Syncer

            root = os.environ.get("OMNIA_SYNC_ROOT") or tempfile.mkdtemp(
                prefix="omnia-sync-"
            )
            self._syncer_inst = Syncer(root)
        return self._syncer_inst

    def _source_key(self, res: Resource) -> str:
        return f"{res.kind.lower()}-{res.namespace}-{res.name}"

    def reconcile_prompt_pack_source(self, res: Resource) -> None:
        """Sync the source and project its pack JSON into a PromptPack
        resource (reference ee promptpacksource_controller.go): a version
        change lands as a PromptPack update, which the existing
        version-trigger rollout machinery picks up — pack-source push =
        progressive rollout."""
        if not self._license_gate(res, "sources"):
            return
        import json as _json

        syncer = self._syncer()
        key = self._source_key(res)
        pack_name = res.spec.get("packName") or res.name
        try:
            version = syncer.sync(key, res.spec.get("source") or {})
            raw = syncer.read(key, res.spec.get("packFile", "pack.json"))
            content = _json.loads(raw)
            existing = self.store.get(
                res.namespace, ResourceKind.PROMPT_PACK.value, pack_name
            )
            if existing is None or existing.spec.get("content") != content:
                pack = existing or Resource(
                    kind=ResourceKind.PROMPT_PACK.value,
                    name=pack_name,
                    namespace=res.namespace,
                )
                pack.spec = dict(pack.spec)
                pack.spec["content"] = content
                pack.spec["sourceRef"] = {"name": res.name}
                # Admission (ValidationError) must land as source status,
                # not escape resync() and kill the reconcile thread: a bad
                # pack in a synced repo is routine operator input.
                self.store.apply(pack)
        except Exception as e:  # noqa: BLE001 - any failure = source Error
            self.store.update_status(res, {"phase": "Error", "message": str(e)})
            return
        self.store.update_status(res, {
            "phase": "Ready",
            "version": version,
            "packName": pack_name,
            "packVersion": content.get("version", ""),
            "syncedAt": time.time(),
        })

    def reconcile_skill_source(self, res: Resource) -> None:
        """Skill bundle sync (reference skillsource_controller.go): skill
        content lands in the shared sync root; packs that declare
        `skills: [name]` get it merged into their system prompt at
        resolution (_merge_pack_skills — the promptpack_skills.go analog).
        Core kind: no license gate."""
        source = dict(res.spec.get("source") or {})
        if source.get("type") == "dir":
            source["type"] = "local"  # SkillSource vocabulary → syncer's
        try:
            version = self._syncer().sync(self._source_key(res), source)
        except Exception as e:  # noqa: BLE001 - status, not crash
            self.store.update_status(res, {"phase": "Error", "message": str(e)})
            return
        changed = res.status.get("version") != version
        self.store.update_status(res, {
            "phase": "Ready", "version": version, "syncedAt": time.time(),
        })
        if changed:
            # Status writes fire no watch events: fan the new skill
            # content out to the agents serving it ourselves (a skill
            # push must restart/re-resolve its consumers the way a pack
            # push does — the reference's version-trigger discipline).
            for ar in self.store.list(
                ResourceKind.AGENT_RUNTIME.value, res.namespace
            ):
                self._queue.put((ar.namespace, ar.kind, ar.name))

    def _merge_pack_skills(self, ns: str, content: dict):
        """Pack content with `skills: [names]` → content whose system
        prompt carries each SkillSource's synced markdown (reference
        promptpack_skills.go merge). Returns (content, error)."""
        skills = content.get("skills") or []
        if not skills:
            return content, None
        import os as _os

        blocks = []
        for sname in skills:
            src = self.store.get(ns, ResourceKind.SKILL_SOURCE.value, sname)
            if src is None:
                return content, f"skill source {sname!r} not found"
            if src.status.get("phase") != "Ready":
                self.reconcile_skill_source(src)
                src = self.store.get(ns, ResourceKind.SKILL_SOURCE.value, sname)
                if src.status.get("phase") != "Ready":
                    return content, (
                        f"skill source {sname!r}: {src.status.get('message')}"
                    )
            head = self._syncer().head_dir(self._source_key(src))
            if head is None:
                # Ready status but no synced content on THIS sync root
                # (pruned PVC / fresh temp dir): os.listdir(None) would
                # read the process cwd into the prompt — fail instead.
                return content, (
                    f"skill source {sname!r} has no synced content here; "
                    "re-sync pending"
                )
            texts = []
            for fn in sorted(_os.listdir(head)):
                if fn.endswith(".md"):
                    with open(_os.path.join(head, fn)) as f:
                        texts.append(f.read().strip())
            if not texts:
                return content, f"skill source {sname!r} has no .md content"
            blocks.append(f"[SKILL {sname}]\n" + "\n".join(texts) + "\n[/SKILL]")
        out = dict(content)
        out["prompts"] = dict(content.get("prompts") or {})
        out["prompts"]["system"] = (
            out["prompts"].get("system", "") + "\n" + "\n".join(blocks)
        ).strip()
        return out, None

    def reconcile_arena_source(self, res: Resource) -> None:
        """Arena scenario/template content sync (reference
        arenasource_controller.go / arenatemplatesource_controller.go):
        content lands in the shared sync root; ArenaJobs reference it via
        scenariosFrom."""
        if not self._license_gate(res, "sources"):
            return
        try:
            version = self._syncer().sync(
                self._source_key(res), res.spec.get("source") or {}
            )
        except Exception as e:  # noqa: BLE001 - any failure = source Error
            self.store.update_status(res, {"phase": "Error", "message": str(e)})
            return
        self.store.update_status(res, {
            "phase": "Ready", "version": version, "syncedAt": time.time(),
        })

    def reconcile_arena_dev_session(self, res: Resource) -> None:
        """Interactive arena dev session record (reference
        arenadevsession_controller.go): validates the agent ref, stamps an
        expiry, and expires on the level-trigger."""
        if not self._license_gate(res, "arena"):
            return
        exp = res.status.get("expiresAt")
        if exp and time.time() > float(exp):
            self.store.update_status(res, {"phase": "Expired"})
            return
        ref = (res.spec.get("agentRef") or {}).get("name", "")
        agent = self.store.get(
            res.namespace, ResourceKind.AGENT_RUNTIME.value, ref
        )
        if agent is None:
            self.store.update_status(
                res, {"phase": "Error", "message": f"agentRef {ref!r} not found"}
            )
            return
        endpoint = (agent.status.get("serviceEndpoint") or "")
        self.store.update_status(res, {
            "phase": "Ready",
            "agentEndpoint": endpoint,
            "expiresAt": exp or time.time() + float(res.spec.get("ttl_s", 3600.0)),
        })
