from omnia_tpu.operator.resources import Resource, ResourceKind
from omnia_tpu.operator.store import FileResourceStore, MemoryResourceStore, ResourceStore
from omnia_tpu.operator.validation import ValidationError, validate
from omnia_tpu.operator.deployment import (
    AgentDeployment,
    InProcessPodBackend,
    K8sManifestBackend,
)
from omnia_tpu.operator.autoscaling import Autoscaler, AutoscalingPolicy
from omnia_tpu.operator.rollout import RolloutEngine, RolloutState
from omnia_tpu.operator.controller import ControllerManager

__all__ = [
    "AgentDeployment",
    "Autoscaler",
    "AutoscalingPolicy",
    "ControllerManager",
    "FileResourceStore",
    "InProcessPodBackend",
    "K8sManifestBackend",
    "MemoryResourceStore",
    "Resource",
    "ResourceKind",
    "ResourceStore",
    "RolloutEngine",
    "RolloutState",
    "ValidationError",
    "validate",
]
