"""Structural manifest validation — the in-tree stand-in for
`kubectl apply --dry-run=client` / kubeconform.

The reference validates its charts against a live envtest apiserver; this
environment has no cluster, so the deploy artifacts are gated by this
linter instead: every rendered manifest must pass before it lands in
deploy/. Checks the invariants that actually break installs — identity
fields, DNS-1123 names, unique resource identities, Deployment
selector⇄template-label agreement, container port-name uniqueness and
length, env var names, CRD structural-schema rules, and RBAC shape.
"""

from __future__ import annotations

import re
from typing import Iterable

_DNS1123 = re.compile(r"^[a-z0-9]([-a-z0-9]*[a-z0-9])?$")
_ENV_NAME = re.compile(r"^[A-Za-z_][A-Za-z0-9_]*$")
_PORT_NAME = re.compile(r"^[a-z0-9]([-a-z0-9]*[a-z0-9])?$")

# Kinds that are cluster-scoped (no namespace expected).
_CLUSTER_SCOPED = {
    "CustomResourceDefinition", "ClusterRole", "ClusterRoleBinding",
    "Namespace", "PriorityClass",
}


def lint(manifests: Iterable[dict]) -> list[str]:
    errs: list[str] = []
    seen: set[tuple] = set()
    for i, m in enumerate(manifests):
        where = f"manifest[{i}]"
        if not isinstance(m, dict):
            errs.append(f"{where}: not a mapping")
            continue
        kind = m.get("kind")
        api = m.get("apiVersion")
        md = m.get("metadata") or {}
        name = md.get("name", "")
        where = f"{kind or '?'}/{name or '?'}"
        if not api:
            errs.append(f"{where}: missing apiVersion")
        if not kind:
            errs.append(f"{where}: missing kind")
        if not name:
            errs.append(f"{where}: missing metadata.name")
        elif kind != "CustomResourceDefinition" and not _DNS1123.match(name):
            errs.append(f"{where}: name {name!r} is not DNS-1123")
        elif len(name) > 253:
            errs.append(f"{where}: name too long")
        ns = md.get("namespace")
        if kind in _CLUSTER_SCOPED and ns:
            errs.append(f"{where}: cluster-scoped kind must not set namespace")
        ident = (api, kind, ns or "", name)
        if ident in seen:
            errs.append(f"{where}: duplicate resource identity")
        seen.add(ident)

        if kind == "Deployment":
            errs += _lint_deployment(where, m)
        elif kind == "CustomResourceDefinition":
            errs += _lint_crd(where, m)
        elif kind == "Service":
            errs += _lint_service(where, m)
        elif kind in ("ClusterRole", "Role"):
            for r, rule in enumerate(m.get("rules") or []):
                if not rule.get("verbs"):
                    errs.append(f"{where}: rules[{r}] missing verbs")
        elif kind in ("ClusterRoleBinding", "RoleBinding"):
            if not m.get("roleRef", {}).get("name"):
                errs.append(f"{where}: roleRef.name missing")
            if not m.get("subjects"):
                errs.append(f"{where}: subjects missing")
    return errs


def _lint_deployment(where: str, m: dict) -> list[str]:
    errs = []
    spec = m.get("spec") or {}
    sel = (spec.get("selector") or {}).get("matchLabels") or {}
    tmpl = spec.get("template") or {}
    labels = (tmpl.get("metadata") or {}).get("labels") or {}
    if not sel:
        errs.append(f"{where}: selector.matchLabels empty")
    for k, v in sel.items():
        if labels.get(k) != v:
            errs.append(
                f"{where}: selector {k}={v} not matched by template labels"
            )
    pod = tmpl.get("spec") or {}
    containers = pod.get("containers") or []
    if not containers:
        errs.append(f"{where}: no containers")
    port_names: set[str] = set()
    cnames: set[str] = set()
    for c in containers:
        cn = c.get("name", "")
        if not _DNS1123.match(cn):
            errs.append(f"{where}: container name {cn!r} invalid")
        if cn in cnames:
            errs.append(f"{where}: duplicate container name {cn!r}")
        cnames.add(cn)
        if not c.get("image"):
            errs.append(f"{where}/{cn}: missing image")
        for p in c.get("ports") or []:
            pn = p.get("name")
            if pn:
                if len(pn) > 15 or not _PORT_NAME.match(pn):
                    errs.append(f"{where}/{cn}: bad port name {pn!r}")
                if pn in port_names:
                    errs.append(f"{where}/{cn}: duplicate port name {pn!r} in pod")
                port_names.add(pn)
            cp = p.get("containerPort")
            if not isinstance(cp, int) or not (0 < cp < 65536):
                errs.append(f"{where}/{cn}: bad containerPort {cp!r}")
        for e in c.get("env") or []:
            if not _ENV_NAME.match(e.get("name", "")):
                errs.append(f"{where}/{cn}: bad env name {e.get('name')!r}")
            if "value" in e and not isinstance(e["value"], str):
                errs.append(
                    f"{where}/{cn}: env {e['name']} value must be a string"
                )
    return errs


def _lint_service(where: str, m: dict) -> list[str]:
    errs = []
    spec = m.get("spec") or {}
    if not spec.get("selector"):
        errs.append(f"{where}: service selector empty")
    for p in spec.get("ports") or []:
        if not isinstance(p.get("port"), int):
            errs.append(f"{where}: service port missing/bad")
    return errs


def _lint_crd(where: str, m: dict) -> list[str]:
    errs = []
    spec = m.get("spec") or {}
    names = spec.get("names") or {}
    group = spec.get("group", "")
    if m.get("metadata", {}).get("name") != f"{names.get('plural')}.{group}":
        errs.append(f"{where}: CRD name must be <plural>.<group>")
    for field in ("kind", "plural", "singular"):
        if not names.get(field):
            errs.append(f"{where}: names.{field} missing")
    versions = spec.get("versions") or []
    if not versions:
        errs.append(f"{where}: no versions")
    if sum(1 for v in versions if v.get("storage")) != 1:
        errs.append(f"{where}: exactly one storage version required")
    for v in versions:
        schema = (v.get("schema") or {}).get("openAPIV3Schema")
        if not schema:
            errs.append(f"{where}: version {v.get('name')} missing schema")
            continue
        errs += _lint_schema(f"{where}@{v.get('name')}", schema, "")
    return errs


def _lint_schema(where: str, s: dict, path: str) -> list[str]:
    """Structural-schema subset: every object either types its properties
    or preserves unknown fields; arrays carry items; enums are lists."""
    errs = []
    t = s.get("type")
    if t == "object":
        if path == ".metadata":
            # Structural-schema special case: root metadata MUST be a bare
            # `type: object` — the apiserver owns its schema.
            return errs
        if "properties" not in s and not s.get("x-kubernetes-preserve-unknown-fields"):
            errs.append(
                f"{where}: object at {path or '/'} has neither properties "
                "nor preserve-unknown-fields"
            )
        for k, sub in (s.get("properties") or {}).items():
            errs += _lint_schema(where, sub, f"{path}.{k}")
        for req in s.get("required") or []:
            if req not in (s.get("properties") or {}):
                errs.append(f"{where}: required {path}.{req} not in properties")
    elif t == "array":
        items = s.get("items")
        if not items:
            errs.append(f"{where}: array at {path} missing items")
        else:
            errs += _lint_schema(where, items, path + "[]")
    elif t in ("string", "integer", "number", "boolean"):
        enum = s.get("enum")
        if enum is not None and not isinstance(enum, list):
            errs.append(f"{where}: enum at {path} not a list")
    elif t is None and s.get("x-kubernetes-preserve-unknown-fields"):
        pass
    elif t is None:
        errs.append(f"{where}: schema at {path or '/'} missing type")
    return errs
