"""Workspace reconciliation: per-service-group data planes.

Reference internal/controller/workspace_services.go:72-365 (+ the
netpol/RBAC/storage builders): a Workspace's `services[]` groups each
get their OWN session-api/memory-api deployments so tenants' data planes
are isolated. Two backends, same shape as agent pods:

- In-process (dev/tests): real SessionAPI/MemoryAPI instances per group,
  endpoints written into Workspace status.
- Manifests (clusters): Deployments + Services + a default-deny
  NetworkPolicy scoped to the workspace + a namespaced Role/RoleBinding —
  rendered pure and linted like every other deploy artifact.
"""

from __future__ import annotations

import logging
import threading
from typing import Optional

from omnia_tpu.operator.resources import Resource

logger = logging.getLogger(__name__)


class ServiceGroup:
    __slots__ = ("name", "session_api", "memory_api", "session_port",
                 "memory_port", "shape")

    def __init__(self, name: str) -> None:
        self.name = name
        self.session_api = None
        self.memory_api = None
        self.session_port: Optional[int] = None
        self.memory_port: Optional[int] = None
        self.shape: tuple = (False, False)  # (sessionApi, memoryApi)

    def endpoints(self) -> dict:
        out: dict = {"group": self.name}
        if self.session_port is not None:
            out["sessionApi"] = f"http://localhost:{self.session_port}"
        if self.memory_port is not None:
            out["memoryApi"] = f"http://localhost:{self.memory_port}"
        return out

    def stop(self) -> None:
        for svc in (self.session_api, self.memory_api):
            if svc is not None:
                try:
                    svc.shutdown()
                except Exception:
                    logger.exception("service group %s shutdown failed", self.name)


class InProcessWorkspaceBackend:
    """Real per-group services in this process (the devroot analog of the
    reference's per-group Deployments)."""

    def __init__(self) -> None:
        self._groups: dict[str, dict[str, ServiceGroup]] = {}
        self._lock = threading.Lock()

    def reconcile(self, res: Resource) -> list[dict]:
        """Converge running groups to the spec; returns endpoint docs."""
        from omnia_tpu.memory.api import MemoryAPI
        from omnia_tpu.session.api import SessionAPI

        want = {
            g["name"]: g for g in res.spec.get("services", [])
            if isinstance(g, dict) and g.get("name")
        }
        key = res.key
        with self._lock:
            groups = self._groups.setdefault(key, {})
            for name in list(groups):
                if name not in want:
                    groups.pop(name).stop()
            for name, spec in want.items():
                shape = (bool(spec.get("sessionApi", True)),
                         bool(spec.get("memoryApi", False)))
                existing = groups.get(name)
                if existing is not None:
                    if existing.shape == shape:
                        continue
                    # Spec changed: converge by recreate (these are
                    # stateless-by-default dev services).
                    groups.pop(name).stop()
                group = ServiceGroup(name)
                group.shape = shape
                try:
                    if shape[0]:
                        group.session_api = SessionAPI()
                        group.session_port = group.session_api.serve(
                            host="localhost", port=0)
                    if shape[1]:
                        group.memory_api = MemoryAPI()
                        group.memory_port = group.memory_api.serve(
                            host="localhost", port=0)
                except BaseException:
                    group.stop()  # never leak a half-started group
                    raise
                groups[name] = group
            return [g.endpoints() for g in groups.values()]

    def teardown(self, key: str) -> None:
        with self._lock:
            groups = self._groups.pop(key, {})
        for g in groups.values():
            g.stop()

    def group(self, key: str, name: str) -> Optional[ServiceGroup]:
        with self._lock:
            return self._groups.get(key, {}).get(name)

    def shutdown(self) -> None:
        with self._lock:
            all_groups, self._groups = list(self._groups.values()), {}
        for groups in all_groups:
            for g in groups.values():
                g.stop()


def render_workspace_manifests(res: Resource, images: Optional[dict] = None) -> list[dict]:
    """Cluster manifests for a Workspace: per-group session/memory-api
    Deployments+Services, default-deny-ingress NetworkPolicy (workspace
    traffic only), and a namespaced admin Role/RoleBinding from
    roleBindings (reference workspace_controller _networkpolicy/_rbac)."""
    images = images or {
        "sessionApi": "omnia-tpu/session-api:latest",
        "memoryApi": "omnia-tpu/memory-api:latest",
    }
    ns = res.spec.get("namespace", res.name)
    out: list[dict] = [
        {"apiVersion": "v1", "kind": "Namespace", "metadata": {"name": ns}},
        {
            "apiVersion": "networking.k8s.io/v1",
            "kind": "NetworkPolicy",
            "metadata": {"name": "omnia-workspace-default", "namespace": ns},
            "spec": {
                "podSelector": {},
                "policyTypes": ["Ingress"],
                "ingress": [{
                    "from": [
                        {"podSelector": {}},  # same-namespace traffic
                        {"namespaceSelector": {"matchLabels": {
                            "kubernetes.io/metadata.name": "omnia-system"}}},
                    ],
                }],
            },
        },
    ]
    for i, binding in enumerate(res.spec.get("roleBindings", [])):
        role = binding.get("role", "viewer")
        users = binding.get("users", [])
        if not users:
            continue
        out.append({
            "apiVersion": "rbac.authorization.k8s.io/v1",
            "kind": "RoleBinding",
            # Indexed: two bindings with the same role must not collide.
            "metadata": {"name": f"omnia-{role}-{i}", "namespace": ns},
            "roleRef": {
                "apiGroup": "rbac.authorization.k8s.io",
                "kind": "ClusterRole",
                # Map workspace roles onto the stock cluster roles.
                "name": {"viewer": "view", "editor": "edit",
                         "admin": "admin"}.get(role, "view"),
            },
            "subjects": [
                {"kind": "User", "name": u,
                 "apiGroup": "rbac.authorization.k8s.io"}
                for u in users
            ],
        })
    for group in res.spec.get("services", []):
        name = group.get("name")
        if not name:
            continue
        for svc_key, enabled_default, image_key, port in (
            ("sessionApi", True, "sessionApi", 8300),
            ("memoryApi", False, "memoryApi", 8400),
        ):
            if not group.get(svc_key, enabled_default):
                continue
            comp = f"{name}-{'session-api' if svc_key == 'sessionApi' else 'memory-api'}"
            labels = {"app.kubernetes.io/name": "omnia",
                      "app.kubernetes.io/component": comp}
            out.append({
                "apiVersion": "apps/v1",
                "kind": "Deployment",
                "metadata": {"name": comp, "namespace": ns, "labels": labels},
                "spec": {
                    "replicas": int(group.get("replicas", 1)),
                    "selector": {"matchLabels": labels},
                    "template": {
                        "metadata": {"labels": labels},
                        "spec": {"containers": [{
                            "name": "api",
                            "image": images[image_key],
                            "ports": [{"name": "http", "containerPort": port}],
                            "env": [{"name": "OMNIA_HTTP_PORT",
                                     "value": str(port)}],
                        }]},
                    },
                },
            })
            out.append({
                "apiVersion": "v1",
                "kind": "Service",
                "metadata": {"name": comp, "namespace": ns, "labels": labels},
                "spec": {"selector": labels,
                         "ports": [{"name": "http", "port": port}]},
            })
    return out
