"""Autoscaling: inference-queue-depth driven replica control.

The reference scales agents with HPA or KEDA on the Prometheus metric
`omnia_agent_connections_active`, including scale-to-zero (reference
internal/controller/autoscaling.go:74/:204/:306-319). The TPU build's
north star rewires the trigger to **inference queue depth** — the
engine's continuous-batching backlog is the true load signal on a TPU
slice (SURVEY.md §2.4). This scaler consumes per-pod queue depth +
active connections and returns a desired replica count; the controller
applies it through the pod backend.
"""

from __future__ import annotations

import math
import time
from dataclasses import dataclass
from typing import Callable, Optional


@dataclass(frozen=True)
class AutoscalingPolicy:
    min_replicas: int = 0               # 0 => scale-to-zero allowed
    max_replicas: int = 4
    target_queue_depth: float = 8.0     # per-replica backlog target
    scale_to_zero_after_idle_s: float = 300.0
    stabilization_s: float = 30.0       # min seconds between scale-downs

    @classmethod
    def from_spec(
        cls, spec: Optional[dict], fallback_replicas: int = 1
    ) -> "AutoscalingPolicy":
        if not spec:
            # No autoscaling block: pin to spec.replicas.
            return cls(min_replicas=fallback_replicas, max_replicas=fallback_replicas)
        return cls(
            min_replicas=spec.get("minReplicas", 0),
            max_replicas=spec.get("maxReplicas", 4),
            target_queue_depth=spec.get("targetQueueDepth", 8.0),
            scale_to_zero_after_idle_s=spec.get("scaleToZeroAfterIdleSeconds", 300.0),
            stabilization_s=spec.get("stabilizationSeconds", 30.0),
        )


class Autoscaler:
    def __init__(self, policy: AutoscalingPolicy,
                 clock: Callable[[], float] = time.monotonic):
        # Injectable clock (same idiom as the engine's deadline/LRU
        # clock): scale-to-zero idle windows and scale-down
        # stabilization become deterministic under test — a scripted
        # clock walks the boundary exactly instead of sleeping at it.
        self.policy = policy
        self._clock = clock
        self._last_active_at = clock()
        self._last_change = 0.0
        self._prev_change = 0.0

    def desired_replicas(
        self,
        current: int,
        total_queue_depth: float,
        active_connections: int,
        now: Optional[float] = None,
    ) -> int:
        """KEDA/HPA-style: ceil(load / per-replica target), clamped, with
        scale-to-zero only after a sustained idle window and scale-down
        stabilization to avoid flapping."""
        p = self.policy
        now = self._clock() if now is None else now
        busy = total_queue_depth > 0 or active_connections > 0
        if busy:
            self._last_active_at = now

        if total_queue_depth > 0:
            want = math.ceil(total_queue_depth / p.target_queue_depth)
        elif active_connections > 0:
            want = max(1, current)
        else:
            want = 0 if self._idle_long_enough(now) else max(1, min(current, p.max_replicas))

        want = max(p.min_replicas, min(p.max_replicas, want))
        # Cold-start from zero on any load (KEDA activation semantics).
        if current == 0 and busy:
            want = max(want, 1)
        # Scale-downs hold for stabilization_s after the last replica
        # change (HPA stabilization: don't thrash on a transient dip).
        if want < current and now - self._last_change < p.stabilization_s:
            return current
        if want != current:
            self._prev_change = self._last_change
            self._last_change = now
        return want

    def note_unapplied(self) -> None:
        """The caller could not apply the last non-hold decision (the
        provisioner raised, or its floor/ceiling clamp made the apply a
        no-op): restore the pre-decision stabilization stamp, so a
        phantom "change" does not suppress the next real scale-down for
        a full stabilization window."""
        self._last_change = self._prev_change

    def _idle_long_enough(self, now: float) -> bool:
        return (
            self.policy.min_replicas == 0
            and now - self._last_active_at >= self.policy.scale_to_zero_after_idle_s
        )
