"""Restricted boolean expression language over dict contexts.

The framework's CEL stand-in (the reference evaluates CEL in its policy
broker, ee/pkg/policy/evaluator.go, and in memory deny-filters): a tiny
total language — no calls, no loops, no attribute access beyond dotted
dict paths — so policy evaluation is safe on untrusted input and always
terminates. Parse errors raise ExprError; callers fail closed.

Grammar:
  expr     := or
  or       := and ("||" and)*
  and      := unary ("&&" unary)*
  unary    := "!" unary | "(" expr ")" | cmp
  cmp      := operand (op operand)?        op ∈ == != < <= > >= in contains
  operand  := string | number | true|false | path
  path     := ident ("." ident)*           resolved against the context dict
"""

from __future__ import annotations

import re

_TOKEN = re.compile(
    r"\s*(?:(?P<op>\(|\)|==|!=|<=|>=|<|>|&&|\|\||!)|(?P<kw>in|contains|true|false)\b"
    r"|(?P<str>\"[^\"]*\"|'[^']*')|(?P<num>-?\d+(?:\.\d+)?)"
    r"|(?P<path>[A-Za-z_][A-Za-z0-9_]*(?:\.[A-Za-z_][A-Za-z0-9_]*)*))"
)


class ExprError(ValueError):
    pass


def _lex(expr: str) -> list[tuple[str, str]]:
    out, pos = [], 0
    while pos < len(expr):
        m = _TOKEN.match(expr, pos)
        if not m or m.end() == pos:
            raise ExprError(f"bad token at {pos!r} in {expr!r}")
        pos = m.end()
        for kind in ("op", "kw", "str", "num", "path"):
            if m.group(kind) is not None:
                out.append((kind, m.group(kind)))
                break
    return out


def _resolve(d: dict, path: str):
    cur = d
    for part in path.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return None
        cur = cur[part]
    return cur


def compile_expr(expr: str):
    """→ predicate(context_dict) -> bool. Raises ExprError on malformed
    input; comparisons against missing paths / mismatched types are False
    (never an exception at evaluation time)."""
    toks = _lex(expr)
    pos = 0

    def peek():
        return toks[pos] if pos < len(toks) else (None, None)

    def eat(kind=None, val=None):
        nonlocal pos
        k, v = peek()
        if k is None or (kind and k != kind) or (val and v != val):
            raise ExprError(f"unexpected {v!r} at token {pos} in {expr!r}")
        pos += 1
        return v

    def operand():
        k, v = peek()
        if k == "str":
            eat()
            return lambda d, s=v[1:-1]: s
        if k == "num":
            eat()
            return lambda d, n=float(v): n
        if k == "kw" and v in ("true", "false"):
            eat()
            return lambda d, b=(v == "true"): b
        if k == "path":
            eat()
            return lambda d, p=v: _resolve(d, p)
        raise ExprError(f"expected operand, got {v!r}")

    def _cmp_vals(a, b, op):
        if op == "==":
            return a == b
        if op == "!=":
            return a != b
        if op == "in":
            try:
                return b is not None and a in b
            except TypeError:
                return False
        if op == "contains":
            try:
                return a is not None and b in a
            except TypeError:
                return False
        # Numeric-ish ordering: both sides must be comparable.
        try:
            if op == "<":
                return a < b
            if op == "<=":
                return a <= b
            if op == ">":
                return a > b
            if op == ">=":
                return a >= b
        except TypeError:
            return False
        raise ExprError(f"unknown operator {op!r}")

    def cmp_expr():
        k, v = peek()
        if k == "op" and v == "(":
            eat()
            inner = or_expr()
            eat("op", ")")
            return inner
        if k == "op" and v == "!":
            eat()
            inner = cmp_expr()
            return lambda d: not inner(d)
        lhs = operand()
        k2, v2 = peek()
        if (k2 == "op" and v2 in ("==", "!=", "<", "<=", ">", ">=")) or (
            k2 == "kw" and v2 in ("in", "contains")
        ):
            eat()
            rhs = operand()
            return lambda d, op=v2: _cmp_vals(lhs(d), rhs(d), op)
        return lambda d: bool(lhs(d))

    def and_expr():
        terms = [cmp_expr()]
        while peek() == ("op", "&&"):
            eat()
            terms.append(cmp_expr())
        return lambda d: all(t(d) for t in terms)

    def or_expr():
        terms = [and_expr()]
        while peek() == ("op", "||"):
            eat()
            terms.append(and_expr())
        return lambda d: any(t(d) for t in terms)

    result = or_expr()
    if pos != len(toks):
        raise ExprError(f"trailing tokens in {expr!r}")
    return result


def lint(expr: str) -> list[str]:
    """Parse-only check (the reference's cel_lint analog): [] when valid."""
    try:
        compile_expr(expr)
        return []
    except ExprError as e:
        return [str(e)]
