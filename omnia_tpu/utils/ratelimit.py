"""Keyed token-bucket rate limiting (facade connections, API clients).

Same role as the reference's pkg/ratelimit KeyedLimiter: per-key buckets
with lazy refill, O(1) per check, periodic garbage collection of idle keys.
"""

from __future__ import annotations

import threading
import time


class TokenBucket:
    __slots__ = ("rate", "burst", "tokens", "last")

    def __init__(self, rate: float, burst: float):
        self.rate = rate
        self.burst = burst
        self.tokens = burst
        self.last = time.monotonic()

    def allow(self, cost: float = 1.0) -> bool:
        now = time.monotonic()
        self.tokens = min(self.burst, self.tokens + (now - self.last) * self.rate)
        self.last = now
        if self.tokens >= cost:
            self.tokens -= cost
            return True
        return False


class KeyedLimiter:
    """Per-key token buckets (key = connection id, client IP, ...)."""

    def __init__(self, rate: float, burst: float, gc_after_s: float = 300.0):
        self.rate = rate
        self.burst = burst
        self.gc_after_s = gc_after_s
        self._buckets: dict[str, TokenBucket] = {}
        self._lock = threading.Lock()
        self._last_gc = time.monotonic()

    def allow(self, key: str, cost: float = 1.0) -> bool:
        with self._lock:
            self._maybe_gc()
            bucket = self._buckets.get(key)
            if bucket is None:
                bucket = self._buckets[key] = TokenBucket(self.rate, self.burst)
            return bucket.allow(cost)

    def forget(self, key: str) -> None:
        with self._lock:
            self._buckets.pop(key, None)

    def _maybe_gc(self) -> None:
        now = time.monotonic()
        if now - self._last_gc < self.gc_after_s:
            return
        dead = [k for k, b in self._buckets.items() if now - b.last > self.gc_after_s]
        for k in dead:
            del self._buckets[k]
        self._last_gc = now
