"""Service discovery: resolve workspace data-plane endpoints.

Reference pkg/servicediscovery: the facade/runtime resolve their
session-api and memory-api endpoints from the Workspace resource's
service groups (workspace_types.go services[]), falling back to
install-wide defaults. An agent names its group via
spec.serviceGroup."""

from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass(frozen=True)
class Endpoints:
    session_api: str = ""
    memory_api: str = ""
    privacy_api: str = ""

    def merged_over(self, base: "Endpoints") -> "Endpoints":
        return Endpoints(
            session_api=self.session_api or base.session_api,
            memory_api=self.memory_api or base.memory_api,
            privacy_api=self.privacy_api or base.privacy_api,
        )


class ServiceDiscovery:
    def __init__(self, store, defaults: Optional[Endpoints] = None):
        self.store = store
        self.defaults = defaults or Endpoints()

    def resolve(self, namespace: str, workspace: str,
                service_group: str = "") -> Endpoints:
        """Workspace service-group endpoints merged over defaults. An
        unknown workspace or group resolves to the defaults (an agent
        without data services still runs; recording just no-ops)."""
        res = self.store.get(namespace, "Workspace", workspace)
        if res is None:
            return self.defaults
        groups = res.spec.get("services") or []
        chosen = None
        for g in groups:
            if g.get("name") == service_group:
                chosen = g
                break
        if chosen is None and groups and not service_group:
            chosen = groups[0]  # unnamed → the workspace's default group
        if chosen is None:
            return self.defaults
        return Endpoints(
            session_api=chosen.get("sessionApi", ""),
            memory_api=chosen.get("memoryApi", ""),
            privacy_api=chosen.get("privacyApi", ""),
        ).merged_over(self.defaults)
