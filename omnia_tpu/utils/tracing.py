"""Tracing: OTel-shaped spans, propagation, exporters.

Reference internal/tracing/tracing.go: an OTLP tracer provider with a
fixed span vocabulary — conversation (turn-indexed), invocation, llm,
tool — plus helpers stamping LLM metrics (token counts, TTFT, finish
reason) onto spans, gRPC metadata propagation between facade and
runtime, and trace ids enriched into logs (pkg/logctx). Here the tracer
is dependency-free: spans collect into an in-memory ring and/or a jsonl
exporter (OTLP-compatible field names, so an adapter can forward to a
real collector); propagation uses the same W3C-style traceparent string
the reference's otelgrpc interceptors produce."""

from __future__ import annotations

import contextvars
import json
import logging
import os
import random
import threading
import time
from collections import deque
from typing import Optional

# Span kinds (the reference's vocabulary, internal/tracing/tracing.go
# :214/:244/:270/:296) — plus the engine-request span the serving layer
# adds: the in-tree TPU engine's child of the runtime's llm span, so one
# trace id covers facade → runtime → engine dispatch (engine/flight.py).
SPAN_CONVERSATION = "omnia.conversation"
SPAN_INVOCATION = "omnia.invocation"
SPAN_LLM = "omnia.llm"
SPAN_TOOL = "omnia.tool"
SPAN_ENGINE = "omnia.engine.request"

MD_TRACEPARENT = "traceparent"

_current_span: "contextvars.ContextVar[Optional[Span]]" = contextvars.ContextVar(
    "omnia_current_span", default=None
)


def _rand_hex(nbytes: int) -> str:
    return "".join(f"{random.getrandbits(8):02x}" for _ in range(nbytes))


class Span:
    def __init__(self, tracer: "Tracer", name: str, trace_id: str, span_id: str,
                 parent_id: str = "", attrs: Optional[dict] = None):
        self.tracer = tracer
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.attrs = dict(attrs or {})
        self.events: list[dict] = []
        self.status = "ok"
        # Wall clock for the exported timestamps (cross-process trace
        # correlation needs unix time), but the DURATION is computed
        # from the monotonic clock: an NTP step between start and end
        # would otherwise yield negative/garbage span durations.
        self.start_ns = time.time_ns()
        self._start_monotonic_ns = time.monotonic_ns()
        self.end_ns: Optional[int] = None
        self._token = None

    # -- lifecycle ---------------------------------------------------------

    def __enter__(self) -> "Span":
        self._token = _current_span.set(self)
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if exc is not None:
            self.record_error(exc)
        self.end()

    def end(self) -> None:
        if self.end_ns is not None:
            return
        # end = wall start + monotonic elapsed: the exported duration is
        # immune to wall-clock steps (keeps end_ns >= start_ns always).
        self.end_ns = self.start_ns + self.duration_ns()
        if self._token is not None:
            _current_span.reset(self._token)
            self._token = None
        self.tracer._export(self)

    def duration_ns(self) -> int:
        """Monotonic elapsed time since the span started (or the final
        duration once ended). Never negative, whatever NTP did."""
        if self.end_ns is not None:
            return self.end_ns - self.start_ns
        return max(time.monotonic_ns() - self._start_monotonic_ns, 0)

    # -- data --------------------------------------------------------------

    def set_attr(self, key: str, value) -> None:
        self.attrs[key] = value

    def add_event(self, name: str, attrs: Optional[dict] = None) -> None:
        self.events.append({"name": name, "ts_ns": time.time_ns(), "attrs": attrs or {}})

    def record_error(self, exc: BaseException) -> None:
        self.status = "error"
        self.attrs["error.type"] = type(exc).__name__
        self.attrs["error.message"] = str(exc)

    # -- LLM helpers (reference AddLLMMetrics/AddFinishReason/AddToolResult)

    def add_llm_metrics(self, prompt_tokens: int, completion_tokens: int,
                        ttft_s: Optional[float] = None, cost_usd: float = 0.0) -> None:
        self.attrs["llm.prompt_tokens"] = prompt_tokens
        self.attrs["llm.completion_tokens"] = completion_tokens
        if ttft_s is not None:
            self.attrs["llm.ttft_s"] = round(ttft_s, 6)
        self.attrs["llm.cost_usd"] = cost_usd

    def add_finish_reason(self, reason: str) -> None:
        self.attrs["llm.finish_reason"] = reason

    def add_tool_result(self, tool: str, is_error: bool) -> None:
        self.attrs["tool.name"] = tool
        self.attrs["tool.is_error"] = is_error

    # -- propagation -------------------------------------------------------

    def traceparent(self) -> str:
        """W3C traceparent for cross-process propagation (gRPC metadata /
        HTTP header)."""
        return f"00-{self.trace_id}-{self.span_id}-01"

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_span_id": self.parent_id,
            "start_ns": self.start_ns,
            "end_ns": self.end_ns,
            "status": self.status,
            "attributes": self.attrs,
            "events": self.events,
        }


def parse_traceparent(header: str) -> Optional[tuple[str, str, bool]]:
    """→ (trace_id, parent_span_id, sampled) or None."""
    try:
        version, trace_id, span_id, flags = header.split("-")
        if len(trace_id) == 32 and len(span_id) == 16 and version == "00":
            sampled = bool(int(flags, 16) & 0x01)
            return trace_id, span_id, sampled
    except ValueError:
        pass
    return None


class OTLPExporter:
    """OTLP/HTTP trace exporter (reference internal/tracing/tracing.go:102
    NewProvider → OTLP → Tempo). Spans batch in a bounded queue drained by
    one background thread POSTing ExportTraceServiceRequest JSON to
    `{endpoint}/v1/traces`; a dead collector drops batches (fail-open,
    counted) — tracing must never stall serving."""

    def __init__(self, endpoint: str, flush_interval_s: float = 2.0,
                 max_batch: int = 512, timeout_s: float = 10.0):
        self.endpoint = endpoint.rstrip("/")
        self.flush_interval_s = flush_interval_s
        self.max_batch = max_batch
        self.timeout_s = timeout_s
        self.dropped = 0
        self.exported = 0
        self._queue: "deque[tuple[str, dict]]" = deque(maxlen=8192)
        self._lock = threading.Lock()
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="otlp-exporter", daemon=True
        )
        self._thread.start()

    def offer(self, service: str, span_dict: dict) -> None:
        with self._lock:
            if len(self._queue) == self._queue.maxlen:
                self.dropped += 1
            self._queue.append((service, span_dict))
        if len(self._queue) >= self.max_batch:
            self._wake.set()

    @staticmethod
    def _otlp_value(v):
        if isinstance(v, bool):
            return {"boolValue": v}
        if isinstance(v, int):
            return {"intValue": str(v)}
        if isinstance(v, float):
            return {"doubleValue": v}
        return {"stringValue": str(v)}

    @classmethod
    def _otlp_span(cls, d: dict) -> dict:
        return {
            "traceId": d["trace_id"],
            "spanId": d["span_id"],
            **({"parentSpanId": d["parent_span_id"]} if d["parent_span_id"] else {}),
            "name": d["name"],
            "kind": 1,  # SPAN_KIND_INTERNAL
            "startTimeUnixNano": str(d["start_ns"]),
            "endTimeUnixNano": str(d["end_ns"] or d["start_ns"]),
            "attributes": [
                {"key": k, "value": cls._otlp_value(v)}
                for k, v in d["attributes"].items()
            ],
            "events": [
                {
                    "timeUnixNano": str(e["ts_ns"]),
                    "name": e["name"],
                    "attributes": [
                        {"key": k, "value": cls._otlp_value(v)}
                        for k, v in e["attrs"].items()
                    ],
                }
                for e in d["events"]
            ],
            "status": {"code": 2 if d["status"] == "error" else 1},
        }

    def _loop(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(timeout=self.flush_interval_s)
            self._wake.clear()
            self.flush()

    def flush(self) -> None:
        with self._lock:
            batch = list(self._queue)
            self._queue.clear()
        if not batch:
            return
        by_service: dict[str, list[dict]] = {}
        for service, d in batch:
            by_service.setdefault(service, []).append(self._otlp_span(d))
        body = json.dumps({
            "resourceSpans": [
                {
                    "resource": {"attributes": [
                        {"key": "service.name",
                         "value": {"stringValue": svc}},
                    ]},
                    "scopeSpans": [{
                        "scope": {"name": "omnia_tpu"},
                        "spans": spans,
                    }],
                }
                for svc, spans in by_service.items()
            ]
        }).encode()
        import urllib.request

        req = urllib.request.Request(
            self.endpoint + "/v1/traces", data=body,
            headers={"Content-Type": "application/json"},
        )
        try:
            with urllib.request.urlopen(req, timeout=self.timeout_s):
                self.exported += len(batch)
        except Exception:
            self.dropped += len(batch)

    def shutdown(self) -> None:
        self._stop.set()
        self._wake.set()
        self._thread.join(timeout=5)
        self.flush()


class Tracer:
    """Process tracer: sampling + ring buffer + optional jsonl and/or
    OTLP/HTTP export."""

    def __init__(self, service: str, sample_rate: float = 1.0,
                 export_path: Optional[str] = None, ring_size: int = 2048,
                 seed: Optional[int] = None,
                 otlp: Optional[OTLPExporter] = None):
        self.service = service
        self.sample_rate = sample_rate
        self.export_path = export_path
        self.otlp = otlp
        self.finished: "deque[Span]" = deque(maxlen=ring_size)
        self._rng = random.Random(seed)
        self._lock = threading.Lock()
        # Separate I/O lock + persistent handle: span-ending threads must
        # never serialize on per-span open/write/close of the export file.
        self._io_lock = threading.Lock()
        self._export_file = None

    def start_span(self, name: str, parent: Optional[Span] = None,
                   traceparent: Optional[str] = None,
                   attrs: Optional[dict] = None) -> Span:
        """Parent precedence: explicit parent > traceparent header >
        current-context span > new root. Sampling decides at the root;
        children always follow their root's decision (parent-based).

        A parseable ``traceparent`` really does beat the ambient
        context-var span: a caller handing over a remote context (the
        engine's request span parenting under the runtime's llm span)
        must get THAT parent even when some enclosing span is active on
        the thread — the old code silently parented under the ambient
        span and orphaned the handed-over context."""
        trace_id = parent_id = None
        parsed = parse_traceparent(traceparent) if traceparent else None
        if parent is None and parsed is None:
            parent = _current_span.get()
        if isinstance(parent, _NoopSpan):
            # Parent-based sampling: children of an unsampled root must be
            # dropped too, not exported as orphans under the zero trace id.
            return _NoopSpan(self)
        if parent is not None:
            trace_id, parent_id = parent.trace_id, parent.span_id
        elif parsed:
            trace_id, parent_id, sampled = parsed
            if not sampled:
                # Parent-based sampling: honor the remote decision —
                # an explicitly-unsampled parent (flags 00) must not
                # be resurrected here.
                return _NoopSpan(self)
        if trace_id is None:
            if self._rng.random() >= self.sample_rate:
                return _NoopSpan(self)
            trace_id, parent_id = _rand_hex(16), ""
        span = Span(self, name, trace_id, _rand_hex(8), parent_id, attrs)
        span.attrs.setdefault("service.name", self.service)
        return span

    def _export(self, span: Span) -> None:
        with self._lock:
            self.finished.append(span)
        if self.otlp is not None:
            self.otlp.offer(self.service, span.to_dict())
        if self.export_path:
            line = json.dumps(span.to_dict()) + "\n"
            try:
                with self._io_lock:
                    if self._export_file is None:
                        self._export_file = open(self.export_path, "a")
                    self._export_file.write(line)
                    self._export_file.flush()
            except OSError:  # pragma: no cover — tracing never breaks serving
                pass

    def spans(self, name: Optional[str] = None) -> list[Span]:
        with self._lock:
            return [s for s in self.finished if name is None or s.name == name]


class _NoopSpan(Span):
    """Unsampled span: context-manager compatible, exports nothing."""

    def __init__(self, tracer: Tracer):
        super().__init__(tracer, "noop", "0" * 32, "0" * 16)

    def traceparent(self) -> str:
        # flags 00: a downstream layer (the engine's request span)
        # honoring parent-based sampling must not resurrect children
        # under the zero trace id.
        return f"00-{self.trace_id}-{self.span_id}-00"

    def end(self) -> None:
        if self._token is not None:
            _current_span.reset(self._token)
            self._token = None
        self.end_ns = self.start_ns + self.duration_ns()  # no export


def current_span() -> Optional[Span]:
    return _current_span.get()


class TraceContextFilter(logging.Filter):
    """logctx analog: stamps trace_id/span_id onto every log record so
    logs correlate with traces (blank when no span is active)."""

    def filter(self, record: logging.LogRecord) -> bool:
        span = _current_span.get()
        record.trace_id = span.trace_id if span else ""
        record.span_id = span.span_id if span else ""
        return True


def noop_tracer() -> Tracer:
    return Tracer("noop", sample_rate=0.0)
