"""Persistent XLA compilation cache.

The engine AOT-compiles every serving shape before readiness (the TTFT
discipline — no compile on the request path), which makes *cold start* pay
the full compile bill. The reference's serving stack has no compile step at
all (it relays HTTPS SSE), so its pods are warm in seconds; a TPU pod that
recompiles ~100 s of XLA programs on every start would make the platform's
scale-to-zero autoscaling (reference internal/controller/autoscaling.go:204)
useless. Persisting compiled executables across process starts turns every
restart after the first into a cache hit: warmup becomes deserialize +
load, not compile.

One call, idempotent, safe before or after backend init. Used by the
engine itself (so every serving path benefits), bench, and the dryrun
entry.
"""

from __future__ import annotations

import logging
import os

logger = logging.getLogger(__name__)

_enabled = False


def default_cache_dir() -> str:
    """OMNIA_JAX_CACHE_DIR wins; otherwise a dot-dir next to the package
    (the repo root in dev, the install prefix in a pod image — both are
    writable in their respective environments)."""
    env = os.environ.get("OMNIA_JAX_CACHE_DIR")
    if env:
        return env
    pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    return os.path.join(pkg_root, ".jax_cache")


def enable_compilation_cache(cache_dir: str | None = None) -> str | None:
    """Point JAX's persistent compilation cache at `cache_dir` and drop the
    entry-size/compile-time floors so *every* serving program is cached
    (the defaults skip fast compiles — but through a remote-device tunnel
    even a 1 s compile is worth skipping). Returns the dir, or None if the
    cache could not be enabled (old jax) — serving still works, cold starts
    just stay slow."""
    global _enabled
    if _enabled:
        return default_cache_dir() if cache_dir is None else cache_dir
    explicit = cache_dir is not None or "OMNIA_JAX_CACHE_DIR" in os.environ
    cache_dir = cache_dir or default_cache_dir()
    try:
        import jax

        if not explicit and jax.default_backend() == "cpu":
            # CPU runs (tests, dev) don't pay a meaningful compile bill,
            # and XLA:CPU AOT cache entries are machine-feature-pinned —
            # reloading them across feature-detection differences risks
            # SIGILL. Opt in explicitly to cache on CPU.
            return None
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        _enabled = True
        return cache_dir
    except Exception:  # pragma: no cover - depends on jax version
        logger.exception("persistent compilation cache unavailable")
        return None
