"""Persistent XLA compilation cache.

The engine AOT-compiles every serving shape before readiness (the TTFT
discipline — no compile on the request path), which makes *cold start* pay
the full compile bill. The reference's serving stack has no compile step at
all (it relays HTTPS SSE), so its pods are warm in seconds; a TPU pod that
recompiles ~100 s of XLA programs on every start would make the platform's
scale-to-zero autoscaling (reference internal/controller/autoscaling.go:204)
useless. Persisting compiled executables across process starts turns every
restart after the first into a cache hit: warmup becomes deserialize +
load, not compile.

One call, idempotent, safe before or after backend init. Used by the
engine itself (so every serving path benefits), bench, and the dryrun
entry.
"""

from __future__ import annotations

import logging
import os
import tempfile

logger = logging.getLogger(__name__)

_enabled = False
_enabled_dir: str | None = None


def _writable_dir(path: str) -> bool:
    """True when `path` exists (or can be created) and accepts writes —
    the probe actually creates and removes a file, because os.access
    lies under containers' overlayfs/read-only mounts."""
    try:
        os.makedirs(path, exist_ok=True)
        probe = os.path.join(path, f".write_probe_{os.getpid()}")
        with open(probe, "w") as f:
            f.write("")
        os.remove(probe)
        return True
    except OSError:
        return False


def default_cache_dir() -> str:
    """OMNIA_JAX_CACHE_DIR wins; otherwise a dot-dir next to the package
    (the repo root in dev, the install prefix in a pod image) — and when
    THAT is unwritable (read-only container images mount the install
    prefix ro), a per-user tmpdir with a logged warning. A tmpdir cache
    only survives the pod, not the node — but a silent failure used to
    disable caching entirely, which is strictly worse."""
    env = os.environ.get("OMNIA_JAX_CACHE_DIR")
    if env:
        return env
    pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    preferred = os.path.join(pkg_root, ".jax_cache")
    if _writable_dir(preferred):
        return preferred
    fallback = os.path.join(
        tempfile.gettempdir(), f"omnia_jax_cache_{os.getuid()}"
    )
    logger.warning(
        "compile cache dir %s is unwritable (read-only image?); falling "
        "back to %s — set OMNIA_JAX_CACHE_DIR to a persistent volume so "
        "restarts keep their compile cache",
        preferred, fallback,
    )
    return fallback


def enabled_dir() -> str | None:
    """The directory the persistent compile cache was enabled with, or
    None while disabled. Jax-free to call (module state only) — the
    warmup manifest and the metrics mirror read it."""
    return _enabled_dir


def enable_compilation_cache(cache_dir: str | None = None) -> str | None:
    """Point JAX's persistent compilation cache at `cache_dir` and drop the
    entry-size/compile-time floors so *every* serving program is cached
    (the defaults skip fast compiles — but through a remote-device tunnel
    even a 1 s compile is worth skipping). Returns the dir, or None if the
    cache could not be enabled (old jax) — serving still works, cold starts
    just stay slow."""
    global _enabled, _enabled_dir
    if _enabled:
        return _enabled_dir
    explicit = cache_dir is not None or "OMNIA_JAX_CACHE_DIR" in os.environ
    try:
        import jax

        if not explicit and jax.default_backend() == "cpu":
            # CPU runs (tests, dev) don't pay a meaningful compile bill,
            # and XLA:CPU AOT cache entries are machine-feature-pinned —
            # reloading them across feature-detection differences risks
            # SIGILL. Opt in explicitly to cache on CPU. Decided BEFORE
            # resolving the default dir: the resolution write-probes the
            # filesystem and may log the read-only-image fallback
            # warning, which would be noise for a cache never enabled.
            return None
        cache_dir = cache_dir or default_cache_dir()
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        _enabled = True
        _enabled_dir = cache_dir
        return cache_dir
    except Exception:  # pragma: no cover - depends on jax version
        logger.exception("persistent compilation cache unavailable")
        return None
