"""Mgmt-plane token fetcher.

Reference internal/mgmtplane/fetcher.go: in-cluster callers (doctor,
conformance probes) fetch short-lived management-plane JWTs from the
token-minting endpoint instead of holding long-lived secrets. Tokens are
cached and refreshed shortly before expiry.
"""

from __future__ import annotations

import json
import threading
import time
import urllib.request
from typing import Optional


class MgmtTokenFetcher:
    def __init__(self, operator_url: str, subject: str,
                 service_token: Optional[str] = None,
                 refresh_margin_s: float = 30.0, timeout_s: float = 10.0):
        self.url = operator_url.rstrip("/") + "/api/v1/mgmt-token"
        self.subject = subject
        # The minting endpoint requires service-to-service auth; the
        # service token is the pod-mounted credential that proves this
        # caller may obtain mgmt principals.
        self.service_token = service_token
        self.refresh_margin_s = refresh_margin_s
        self.timeout_s = timeout_s
        self._token: Optional[str] = None
        self._expires_at = 0.0
        self._lock = threading.Lock()

    def token(self) -> str:
        """Cached token, refreshed when within the margin of expiry."""
        with self._lock:
            if self._token and time.time() < self._expires_at - self.refresh_margin_s:
                return self._token
            headers = {"Content-Type": "application/json"}
            if self.service_token:
                headers["Authorization"] = f"Bearer {self.service_token}"
            req = urllib.request.Request(
                self.url,
                data=json.dumps({"subject": self.subject}).encode(),
                headers=headers,
            )
            with urllib.request.urlopen(req, timeout=self.timeout_s) as r:
                doc = json.loads(r.read())
            self._token = doc["token"]
            self._expires_at = time.time() + float(doc.get("expires_in_s", 300))
            return self._token

    def auth_header(self) -> dict:
        return {"Authorization": f"Bearer {self.token()}"}
