"""Prometheus-style metrics registry with text exposition.

Naming convention matches the reference platform: `omnia_<service>_*`
(reference pkg/metrics + per-service metrics files; discovery by a port
named "metrics"). Implemented fresh and dependency-free: counters, gauges,
histograms with the classic exposition format served from each service's
health endpoint.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional, Sequence

_DEFAULT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)


def _fmt_labels(labels: dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
    return "{" + inner + "}"


class Counter:
    def __init__(self, name: str, help_: str = ""):
        self.name = name
        self.help = help_
        self._values: dict[tuple, float] = {}
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels: str) -> float:
        return self._values.get(tuple(sorted(labels.items())), 0.0)

    def expose(self) -> list[str]:
        lines = [f"# TYPE {self.name} counter"]
        for key, v in sorted(self._values.items()):
            lines.append(f"{self.name}{_fmt_labels(dict(key))} {v}")
        return lines


class Gauge:
    def __init__(self, name: str, help_: str = "", fn: Optional[Callable[[], float]] = None):
        self.name = name
        self.help = help_
        self._fn = fn
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self._value = v

    def add(self, v: float) -> None:
        with self._lock:
            self._value += v

    def value(self) -> float:
        return self._fn() if self._fn is not None else self._value

    def expose(self) -> list[str]:
        return [f"# TYPE {self.name} gauge", f"{self.name} {self.value()}"]


class Histogram:
    def __init__(self, name: str, help_: str = "", buckets: Sequence[float] = _DEFAULT_BUCKETS):
        self.name = name
        self.help = help_
        self.buckets = tuple(sorted(buckets))
        self._counts = [0] * (len(self.buckets) + 1)
        self._sum = 0.0
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        with self._lock:
            self._sum += v
            for i, b in enumerate(self.buckets):
                if v <= b:
                    self._counts[i] += 1
                    return
            self._counts[-1] += 1

    @property
    def count(self) -> int:
        return sum(self._counts)

    def quantile(self, q: float) -> float:
        """Approximate quantile from bucket counts (upper bound)."""
        total = self.count
        if total == 0:
            return 0.0
        target = q * total
        cum = 0
        for i, b in enumerate(self.buckets):
            cum += self._counts[i]
            if cum >= target:
                return b
        return float("inf")

    def expose(self) -> list[str]:
        lines = [f"# TYPE {self.name} histogram"]
        cum = 0
        for i, b in enumerate(self.buckets):
            cum += self._counts[i]
            lines.append(f'{self.name}_bucket{{le="{b}"}} {cum}')
        cum += self._counts[-1]
        lines.append(f'{self.name}_bucket{{le="+Inf"}} {cum}')
        lines.append(f"{self.name}_sum {self._sum}")
        lines.append(f"{self.name}_count {cum}")
        return lines


class Registry:
    def __init__(self, prefix: str = "omnia"):
        self.prefix = prefix
        self._metrics: dict[str, object] = {}
        self._lock = threading.Lock()

    def counter(self, name: str, help_: str = "") -> Counter:
        return self._get_or_make(name, lambda n: Counter(n, help_))

    def gauge(self, name: str, help_: str = "", fn=None) -> Gauge:
        return self._get_or_make(name, lambda n: Gauge(n, help_, fn))

    def histogram(self, name: str, help_: str = "", buckets=_DEFAULT_BUCKETS) -> Histogram:
        return self._get_or_make(name, lambda n: Histogram(n, help_, buckets))

    def _get_or_make(self, name: str, make):
        full = f"{self.prefix}_{name}"
        with self._lock:
            m = self._metrics.get(full)
            if m is None:
                m = self._metrics[full] = make(full)
            return m

    def expose(self) -> str:
        lines: list[str] = []
        for m in self._metrics.values():
            lines.extend(m.expose())
        return "\n".join(lines) + "\n"
