"""Prometheus-style metrics registry with text exposition.

Naming convention matches the reference platform: `omnia_<service>_*`
(reference pkg/metrics + per-service metrics files; discovery by a port
named "metrics"). Implemented fresh and dependency-free: counters, gauges,
histograms with the classic exposition format served from each service's
health endpoint.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional, Sequence

_DEFAULT_BUCKETS = (0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)


def _fmt_labels(labels: dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
    return "{" + inner + "}"


class Counter:
    def __init__(self, name: str, help_: str = ""):
        self.name = name
        self.help = help_
        self._values: dict[tuple, float] = {}
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0, **labels: str) -> None:
        key = tuple(sorted(labels.items()))
        with self._lock:
            self._values[key] = self._values.get(key, 0.0) + amount

    def value(self, **labels: str) -> float:
        return self._values.get(tuple(sorted(labels.items())), 0.0)

    def expose(self) -> list[str]:
        lines = [f"# TYPE {self.name} counter"]
        for key, v in sorted(self._values.items()):
            lines.append(f"{self.name}{_fmt_labels(dict(key))} {v}")
        return lines


class Gauge:
    def __init__(self, name: str, help_: str = "", fn: Optional[Callable[[], float]] = None):
        self.name = name
        self.help = help_
        self._fn = fn
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self._value = v

    def add(self, v: float) -> None:
        with self._lock:
            self._value += v

    def value(self) -> float:
        return self._fn() if self._fn is not None else self._value

    def expose(self) -> list[str]:
        return [f"# TYPE {self.name} gauge", f"{self.name} {self.value()}"]


class Histogram:
    def __init__(self, name: str, help_: str = "", buckets: Sequence[float] = _DEFAULT_BUCKETS):
        self.name = name
        self.help = help_
        self.buckets = tuple(sorted(buckets))
        self._counts = [0] * (len(self.buckets) + 1)
        self._sum = 0.0
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        with self._lock:
            self._sum += v
            for i, b in enumerate(self.buckets):
                if v <= b:
                    self._counts[i] += 1
                    return
            self._counts[-1] += 1

    @property
    def count(self) -> int:
        return sum(self._counts)

    def quantile(self, q: float) -> float:
        """Approximate quantile from bucket counts (upper bound)."""
        total = self.count
        if total == 0:
            return 0.0
        target = q * total
        cum = 0
        for i, b in enumerate(self.buckets):
            cum += self._counts[i]
            if cum >= target:
                return b
        return float("inf")

    def expose(self) -> list[str]:
        lines = [f"# TYPE {self.name} histogram"]
        cum = 0
        for i, b in enumerate(self.buckets):
            cum += self._counts[i]
            lines.append(f'{self.name}_bucket{{le="{b}"}} {cum}')
        cum += self._counts[-1]
        lines.append(f'{self.name}_bucket{{le="+Inf"}} {cum}')
        lines.append(f"{self.name}_sum {self._sum}")
        lines.append(f"{self.name}_count {cum}")
        return lines


class DictCollector:
    """Live exposition of a plain metrics dict (e.g. ``engine.metrics``).

    ONE collector, no copied bookkeeping: the dict is read at scrape
    time through ``fn``, so the exposition can never go stale behind the
    source counters. Values are read without the source's lock — ints
    and floats read atomically in CPython; at worst a scrape sees two
    keys from adjacent instants, which is the normal Prometheus
    contract. Non-numeric values are skipped. A ``<prefix>_scrape_unixtime``
    line stamps each scrape so a monitor (and the doctor's engine-metrics
    check) can prove the family is computed live, not cached."""

    def __init__(self, prefix: str, fn: Callable[[], dict], help_: str = ""):
        self.name = prefix
        self.prefix = prefix
        self.help = help_
        self._fn = fn

    def expose(self) -> list[str]:
        lines: list[str] = []
        d = self._fn() or {}
        for k in sorted(d):
            v = d[k]
            if isinstance(v, bool) or not isinstance(v, (int, float)):
                continue
            lines.append(f"# TYPE {self.prefix}_{k} gauge")
            lines.append(f"{self.prefix}_{k} {float(v)}")
        lines.append(f"# TYPE {self.prefix}_scrape_unixtime gauge")
        lines.append(f"{self.prefix}_scrape_unixtime {time.time()}")
        return lines


class Registry:
    def __init__(self, prefix: str = "omnia"):
        self.prefix = prefix
        self._metrics: dict[str, object] = {}
        self._lock = threading.Lock()

    def counter(self, name: str, help_: str = "") -> Counter:
        return self._get_or_make(name, lambda n: Counter(n, help_))

    def gauge(self, name: str, help_: str = "", fn=None) -> Gauge:
        return self._get_or_make(name, lambda n: Gauge(n, help_, fn))

    def histogram(self, name: str, help_: str = "", buckets=_DEFAULT_BUCKETS) -> Histogram:
        return self._get_or_make(name, lambda n: Histogram(n, help_, buckets))

    def register(self, metric, replace: bool = False) -> object:
        """Adopt an externally-created metric (its ``name`` is used
        verbatim — no registry prefix). By default first registration
        wins (re-registering the same series is idempotent);
        ``replace=True`` swaps the series in — the rebind path for a
        replaced backing object (a reloaded engine must not leave the
        exposition pointing at its dead predecessor)."""
        with self._lock:
            if replace:
                self._metrics[metric.name] = metric
                return metric
            return self._metrics.setdefault(metric.name, metric)

    def unregister_prefix(self, prefix: str) -> int:
        """Drop every registered metric whose full name starts with
        ``prefix``; returns how many were removed. The rebind broom:
        series owned by a replaced backing object must not survive it
        frozen (see :func:`bind_engine_metrics`)."""
        with self._lock:
            doomed = [n for n in self._metrics if n.startswith(prefix)]
            for n in doomed:
                del self._metrics[n]
            return len(doomed)

    def _get_or_make(self, name: str, make):
        full = f"{self.prefix}_{name}"
        with self._lock:
            m = self._metrics.get(full)
            if m is None:
                m = self._metrics[full] = make(full)
            return m

    def expose(self) -> str:
        lines: list[str] = []
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            lines.extend(m.expose())
        return "\n".join(lines) + "\n"


def bind_engine_metrics(registry: Registry, engine) -> DictCollector:
    """Bridge an engine-like object (InferenceEngine / MockEngine /
    EngineCoordinator) into a Prometheus registry: its ``metrics`` dict
    is exposed live as the ``omnia_engine_*`` gauge family (one
    collector, no double bookkeeping), and — when the engine carries a
    flight recorder (``EngineConfig.flight_events > 0``) — the
    recorder's step-timing histograms (ttft, inter-token, queue wait,
    per-chunk dispatch/sync µs) register alongside it. The facade/doctor
    ``/metrics`` endpoint then answers engine-health queries directly.

    One registry exposes ONE engine family: rebinding (a provider
    reload replacing the engine) first sweeps every ``omnia_engine_*``
    series, then registers the new collector and histograms — so the
    exposition can never keep reading a dead engine's frozen counters
    (not even its old flight histograms when the replacement has no
    recorder), which would pass the doctor's freshness stamp while
    serving stale data."""
    if not hasattr(engine, "metrics") or isinstance(engine, dict):
        # Loud rejection beats a silently-empty family: passing the
        # metrics DICT instead of the engine object would expose zero
        # engine series while the freshness stamp keeps ticking.
        raise TypeError(
            "bind_engine_metrics wants the engine OBJECT (anything with "
            f"a .metrics dict), got {type(engine).__name__}"
        )
    registry.unregister_prefix("omnia_engine_")
    coll = DictCollector(
        "omnia_engine", lambda: getattr(engine, "metrics", {}) or {},
        help_="live view of engine.metrics",
    )
    registry.register(coll, replace=True)
    rec = getattr(engine, "_flight", None)
    if rec is not None:
        for h in rec.hist.values():
            registry.register(h, replace=True)
    return coll
