"""Session service tests: tiers, read-through, compaction, REST API.

Mirrors the reference's session-api coverage (tiered providers,
partitioned usage, compaction engine warm→cold, event publishing)."""

import json
import time
import urllib.request

import pytest

from omnia_tpu.session import (
    ColdArchive,
    CompactionEngine,
    HotStore,
    MessageRecord,
    ProviderCallRecord,
    RetentionPolicy,
    SessionAPI,
    SessionRecord,
    TieredStore,
    ToolCallRecord,
    WarmStore,
    LocalBlobStore,
)


def _seed(store, sid="s1", ws="default"):
    store.ensure_session(SessionRecord(session_id=sid, workspace=ws, agent="a1"))
    store.append_message(MessageRecord(session_id=sid, role="user", content="hi"))
    store.append_message(MessageRecord(session_id=sid, role="assistant", content="yo"))
    store.append_tool_call(
        ToolCallRecord(session_id=sid, tool="search", arguments="{}", result="ok")
    )
    store.append_provider_call(
        ProviderCallRecord(
            session_id=sid,
            provider="tpu",
            model="llama3-8b",
            input_tokens=10,
            output_tokens=20,
            cost_usd=0.001,
        )
    )


# -- hot ---------------------------------------------------------------


def test_hot_store_roundtrip():
    hot = HotStore()
    _seed(hot)
    assert hot.get_session("s1").agent == "a1"
    assert [m.content for m in hot.messages("s1")] == ["hi", "yo"]
    assert hot.usage()["input_tokens"] == 10
    assert hot.delete_session("s1")
    assert hot.get_session("s1") is None


def test_hot_pop_idle():
    hot = HotStore()
    _seed(hot, "old")
    _seed(hot, "fresh")
    # Make "old" idle.
    with hot._lock:
        hot._bundles["old"].session.updated_at = time.time() - 7200
    popped = hot.pop_idle(idle_s=3600)
    assert [b.session.session_id for b in popped] == ["old"]
    assert hot.get_session("old") is None
    assert hot.get_session("fresh") is not None


# -- warm --------------------------------------------------------------


def test_warm_store_roundtrip(tmp_path):
    warm = WarmStore(str(tmp_path / "warm.db"))
    _seed(warm, ws="acme")
    s = warm.get_session("s1")
    assert s.workspace == "acme" and s.tier == "warm"
    assert len(warm.messages("s1")) == 2
    assert warm.tool_calls("s1")[0].tool == "search"
    u = warm.usage("acme")
    assert u["input_tokens"] == 10 and u["calls"] == 1
    assert warm.usage("other")["calls"] == 0
    warm.close()


def test_warm_sessions_older_than():
    warm = WarmStore()
    old = SessionRecord(session_id="old")
    old.updated_at = time.time() - 100
    warm.ensure_session(old)
    warm.ensure_session(SessionRecord(session_id="new"))
    got = warm.sessions_older_than(time.time() - 50)
    assert [s.session_id for s in got] == ["old"]


# -- cold --------------------------------------------------------------


def test_cold_archive_roundtrip(tmp_path):
    cold = ColdArchive(LocalBlobStore(str(tmp_path)))
    warm = WarmStore()
    _seed(warm)
    sess = warm.get_session("s1")
    key = cold.archive_session(sess, warm.all_records("s1"))
    assert key.endswith("s1.parquet")
    got = cold.get_session("s1")
    assert got.archived and got.tier == "cold"
    msgs = cold.records("s1", "message")
    assert [m.content for m in msgs] == ["hi", "yo"]
    assert len(cold.records("s1")) == 4  # all kinds
    assert cold.delete_session("s1")
    assert cold.get_session("s1") is None


def test_cold_purge():
    cold = ColdArchive()
    sess = SessionRecord(session_id="ancient")
    sess.updated_at = time.time() - 1000
    cold.archive_session(sess, {"message": []})
    assert cold.purge_older_than(time.time() - 500) == 1
    assert len(cold) == 0


# -- tiered read-through ----------------------------------------------


def test_tiered_read_through_falls_to_warm_and_cold():
    store = TieredStore()
    _seed(store.warm, "warm-only")
    assert store.get_session("warm-only").tier == "warm"
    assert len(store.messages("warm-only")) == 2

    sess = SessionRecord(session_id="cold-only")
    store.cold.archive_session(
        sess,
        {"message": [MessageRecord(session_id="cold-only", role="user", content="x").__dict__]},
    )
    assert store.get_session("cold-only").tier == "cold"
    assert store.messages("cold-only")[0].content == "x"


# -- compaction --------------------------------------------------------


def test_compaction_full_lifecycle():
    policy = RetentionPolicy(hot_idle_s=10, warm_window_s=100, cold_window_s=1000)
    store = TieredStore()
    engine = CompactionEngine(store, policy)
    _seed(store, "live")
    _seed(store, "idle")
    now = time.time()
    with store.hot._lock:
        store.hot._bundles["idle"].session.updated_at = now - 50

    r1 = engine.run_once(now)
    assert r1.demoted_hot_to_warm == 1 and not r1.errors
    assert store.warm.get_session("idle") is not None
    assert store.hot.get_session("live") is not None
    # Read-through still serves the demoted session's records.
    assert len(store.messages("idle")) == 2

    # Age past the warm window → cold. On the single shared clock,
    # "live" (idle since `now`) demotes hot→warm AND warm→cold in the
    # same pass alongside "idle".
    r2 = engine.run_once(now + 200)
    assert r2.demoted_hot_to_warm == 1  # "live"
    assert r2.demoted_warm_to_cold == 2
    assert store.warm.get_session("idle") is None
    assert store.cold.get_session("idle").archived
    assert [m.content for m in store.messages("idle")] == ["hi", "yo"]

    # Past cold window → purged.
    r3 = engine.run_once(now + 5000)
    assert r3.purged_cold == 2
    assert store.get_session("idle") is None


def test_retention_policy_validation():
    with pytest.raises(ValueError):
        RetentionPolicy(hot_idle_s=100, warm_window_s=10).validate()


# -- REST API ----------------------------------------------------------


def test_api_append_and_read_and_events():
    api = SessionAPI()
    code, _ = api.handle(
        "POST",
        "/api/v1/messages",
        {"kind": "message", "session_id": "s9", "role": "user", "content": "hello"},
    )
    assert code == 200
    code, resp = api.handle("GET", "/api/v1/sessions/s9/messages", None)
    assert code == 200 and resp["messages"][0]["content"] == "hello"
    # Session auto-ensured; events published for ensure+append.
    code, resp = api.handle("GET", "/api/v1/sessions/s9", None)
    assert code == 200
    evs = api.events.read_group("test", "c", count=10)
    types = [e.data["type"] for e in evs]
    assert "message" in types


def test_api_usage_and_not_found():
    api = SessionAPI()
    code, resp = api.handle(
        "POST",
        "/api/v1/provider-calls",
        {
            "session_id": "u1",
            "provider": "tpu",
            "model": "m",
            "input_tokens": 5,
            "output_tokens": 7,
        },
    )
    assert code == 200
    code, usage = api.handle("GET", "/api/v1/usage", None)
    assert code == 200 and usage["input_tokens"] == 5
    code, _ = api.handle("GET", "/api/v1/sessions/nope", None)
    assert code == 404
    code, _ = api.handle("GET", "/api/v1/bogus", None)
    assert code == 404


def test_api_list_sessions_attrs_filter_server_side():
    """?attrs.<k>=<v> scopes the listing server-side: candidate-track
    sessions must be findable even when stable traffic dominates recency
    (ADVICE r2 — rollout analysis relies on this)."""
    api = SessionAPI()
    for i in range(30):
        api.handle("POST", "/api/v1/sessions", {
            "session_id": f"stable-{i}", "agent": "a",
            "attrs": {"track": "stable"},
        })
    api.handle("POST", "/api/v1/sessions", {
        "session_id": "cand-1", "agent": "a",
        "attrs": {"track": "candidate", "version": "v2"},
    })
    for i in range(30, 60):
        api.handle("POST", "/api/v1/sessions", {
            "session_id": f"stable-{i}", "agent": "a",
            "attrs": {"track": "stable"},
        })
    # A recency-limited unfiltered page misses the candidate...
    code, resp = api.handle(
        "GET", "/api/v1/sessions", {"limit": "20", "agent": "a"}
    )
    assert code == 200
    assert all(s["session_id"] != "cand-1" for s in resp["sessions"])
    # ...the server-side attrs filter finds it.
    code, resp = api.handle(
        "GET", "/api/v1/sessions",
        {"limit": "20", "agent": "a", "attrs.track": "candidate",
         "attrs.version": "v2"},
    )
    assert code == 200
    assert [s["session_id"] for s in resp["sessions"]] == ["cand-1"]


def test_api_bad_append_is_400():
    api = SessionAPI()
    code, resp = api.handle("POST", "/api/v1/messages", {"role": "user", "content": "x"})
    assert code == 400


def test_api_http_server_end_to_end():
    api = SessionAPI()
    port = api.serve(port=0)
    base = f"http://localhost:{port}"
    try:
        body = json.dumps(
            {"session_id": "httpsess", "role": "user", "content": "over http"}
        ).encode()
        req = urllib.request.Request(
            base + "/api/v1/messages",
            data=body,
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        with urllib.request.urlopen(req, timeout=5) as r:
            assert r.status == 200
        with urllib.request.urlopen(
            base + "/api/v1/sessions/httpsess/messages", timeout=5
        ) as r:
            got = json.loads(r.read())
        assert got["messages"][0]["content"] == "over http"
        with urllib.request.urlopen(base + "/metrics", timeout=5) as r:
            text = r.read().decode()
        assert "omnia_session_records_written_total" in text
        with urllib.request.urlopen(base + "/healthz", timeout=5) as r:
            assert r.status == 200
    finally:
        api.shutdown()


def test_api_delete_session():
    api = SessionAPI()
    api.handle("POST", "/api/v1/sessions", {"session_id": "d1", "workspace": "w"})
    code, _ = api.handle("DELETE", "/api/v1/sessions/d1", None)
    assert code == 200
    code, _ = api.handle("DELETE", "/api/v1/sessions/d1", None)
    assert code == 404


# -- regression: code-review findings ---------------------------------


def test_resumed_session_merges_history_across_tiers():
    """A session demoted to warm then resumed must show old + new turns."""
    store = TieredStore()
    _seed(store, "r1")
    with store.hot._lock:
        store.hot._bundles["r1"].session.updated_at = time.time() - 7200
    CompactionEngine(store, RetentionPolicy(hot_idle_s=3600)).run_once()
    assert store.hot.get_session("r1") is None
    # Resume: new message lands in hot.
    store.append_message(MessageRecord(session_id="r1", role="user", content="again"))
    contents = [m.content for m in store.messages("r1")]
    assert contents == ["hi", "yo", "again"]


def test_hot_capacity_eviction_demotes_to_warm():
    store = TieredStore(hot=HotStore(max_sessions=2))
    _seed(store, "a")
    _seed(store, "b")
    _seed(store, "c")  # evicts oldest ("a") into warm
    assert store.warm.get_session("a") is not None
    assert [m.content for m in store.messages("a")] == ["hi", "yo"]


def test_explicit_ensure_after_auto_ensure_updates_identity():
    store = TieredStore()
    store.append_message(MessageRecord(session_id="x", role="user", content="early"))
    store.ensure_session(
        SessionRecord(session_id="x", workspace="team-x", user_id="u1", agent="ag")
    )
    s = store.get_session("x")
    assert (s.workspace, s.user_id, s.agent) == ("team-x", "u1", "ag")


def test_usage_does_not_double_count_resumed_sessions():
    store = TieredStore()
    _seed(store, "u")
    with store.hot._lock:
        store.hot._bundles["u"].session.updated_at = time.time() - 7200
    CompactionEngine(store, RetentionPolicy(hot_idle_s=3600)).run_once()
    store.append_message(MessageRecord(session_id="u", role="user", content="back"))
    assert store.usage()["sessions"] == 1


def test_compaction_restores_bundle_on_warm_failure(monkeypatch):
    store = TieredStore()
    _seed(store, "f1")
    with store.hot._lock:
        store.hot._bundles["f1"].session.updated_at = time.time() - 7200

    def boom(*a, **k):
        raise RuntimeError("disk full")

    monkeypatch.setattr(store.warm, "append_message", boom)
    eng = CompactionEngine(store, RetentionPolicy(hot_idle_s=3600))
    r = eng.run_once()
    assert r.errors and r.demoted_hot_to_warm == 0
    # Records survived: bundle restored to hot.
    assert [m.content for m in store.hot.messages("f1")] == ["hi", "yo"]
    monkeypatch.undo()
    # Next pass succeeds without double-counting usage.
    r2 = eng.run_once()
    assert r2.demoted_hot_to_warm == 1
    assert store.warm.usage()["calls"] == 1


def test_rearchive_merges_cold_history():
    """Resumed-after-archive sessions must keep their full cold history."""
    store = TieredStore()
    policy = RetentionPolicy(hot_idle_s=10, warm_window_s=100, cold_window_s=10**9)
    eng = CompactionEngine(store, policy)
    _seed(store, "m1")
    now = time.time()
    with store.hot._lock:
        store.hot._bundles["m1"].session.updated_at = now - 50
    eng.run_once(now)            # hot -> warm
    eng.run_once(now + 200)      # warm -> cold
    assert store.cold.get_session("m1") is not None
    old_keys = set(store.cold.blobs.list("archive/"))

    # Resume: new turn, demote again, re-archive.
    store.append_message(MessageRecord(session_id="m1", role="user", content="resumed"))
    with store.hot._lock:
        store.hot._bundles["m1"].session.updated_at = now + 300
    eng.run_once(now + 400)      # hot -> warm
    eng.run_once(now + 600)      # warm -> cold (re-archive, merge)
    contents = [m.content for m in store.cold.records("m1", "message")]
    assert contents == ["hi", "yo", "resumed"]
    # Superseded blob deleted (no orphan leak).
    keys = set(store.cold.blobs.list("archive/"))
    assert len(keys) == 1 and (keys == old_keys or not (old_keys & keys))


def test_otlp_trace_ingest_end_to_end():
    """Session-api ingests OTLP/HTTP traces (reference
    internal/session/otlp): the platform's own Tracer exports a turn span
    over real HTTP, and it lands as a runtime event on the session."""
    from omnia_tpu.utils.tracing import OTLPExporter, Tracer

    api = SessionAPI()
    port = api.serve(host="127.0.0.1", port=0)
    try:
        otlp = OTLPExporter(f"http://127.0.0.1:{port}", flush_interval_s=60)
        tracer = Tracer("runtime", otlp=otlp)
        span = tracer.start_span("conversation.turn",
                                 attrs={"session.id": "otlp-sess",
                                        "turn.index": 1})
        span.set_attr("llm.completion_tokens", 42)
        span.end()
        # A span with NO session attribute is accepted and dropped.
        tracer.start_span("orphan").end()
        otlp.shutdown()  # flush over the wire
        assert otlp.exported == 2 and otlp.dropped == 0

        code, resp = api.handle(
            "GET", "/api/v1/sessions/otlp-sess/events", None)
        assert code == 200
        events = resp["events"]
        assert len(events) == 1
        ev = events[0]
        assert ev["event_type"] == "otlp_span"
        assert ev["data"]["name"] == "conversation.turn"
        assert ev["data"]["service"] == "runtime"
        assert ev["data"]["attrs"]["llm.completion_tokens"] in (42, "42", 42.0)
        assert ev["data"]["duration_ms"] >= 0
    finally:
        api.shutdown()
