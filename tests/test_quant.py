"""int8 quantization: qdot accuracy, full-forward fidelity, engine + loader
integration, sharded specs (models/quant.py — the single-chip capacity
path for the Llama-3-8B north star; see BASELINE.md) — and the int8 KV
cache (models/kv_quant.py, EngineConfig.kv_quant): round-trip bounds,
greedy golden-equivalence real-vs-mock and quantized-vs-fp32 drift
bounds across ≥256 decoded tokens, and the spec-decode verify path."""

import dataclasses
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from omnia_tpu.engine import EngineConfig, InferenceEngine, MockEngine, SamplingParams
from omnia_tpu.models import checkpoint as ckpt_io
from omnia_tpu.models import get_config, kv_quant as kvq, llama, quant
from omnia_tpu.parallel import make_mesh, shard_pytree


@pytest.fixture(scope="module")
def tiny():
    cfg = get_config("test-tiny")
    params = llama.init_params(cfg, jax.random.key(0), dtype=jnp.float32)
    return cfg, params


# ---------------------------------------------------------------------------
# qdot
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("mode", quant.QUANT_MODES)
def test_qdot_matches_dense(mode):
    k1, k2 = jax.random.split(jax.random.key(0))
    h = jax.random.normal(k1, (4, 64), dtype=jnp.float32)
    w = jax.random.normal(k2, (64, 32), dtype=jnp.float32) * 0.05
    ref = jnp.dot(h, w)
    out = quant.qdot(h, quant.quantize_weight(w, mode))
    # int8 per-channel round-trip: ~0.5% weight error (w8a16), plus the
    # same again on activations for w8a8.
    tol = 0.02 if mode == "int8" else 0.05
    err = jnp.linalg.norm(out - ref) / jnp.linalg.norm(ref)
    assert err < tol, f"{mode}: relative error {err}"


def test_qdot_passthrough_dense_weight():
    h = jnp.ones((2, 8))
    w = jnp.ones((8, 4))
    np.testing.assert_allclose(quant.qdot(h, w), jnp.dot(h, w))


def test_scale_commutes_with_contraction():
    """The w8a16 identity the design rests on: per-output-channel scale
    applied to the output equals dequantizing the weight first."""
    k1, k2 = jax.random.split(jax.random.key(1))
    h = jax.random.normal(k1, (3, 16), dtype=jnp.float32)
    w = jax.random.normal(k2, (16, 8), dtype=jnp.float32)
    d = quant.quantize_weight(w, "int8")
    dequant = d["w8"].astype(jnp.float32) * d["s"][None, :]
    np.testing.assert_allclose(
        np.asarray(quant.qdot(h, d)),
        np.asarray(jnp.dot(h, dequant)),
        rtol=1e-5, atol=1e-5,
    )


# ---------------------------------------------------------------------------
# Full-forward fidelity
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("mode", quant.QUANT_MODES)
def test_forward_close_to_dense(tiny, mode):
    cfg, params = tiny
    qparams = quant.quantize_params(params, cfg, mode)
    toks = jax.random.randint(jax.random.key(2), (2, 12), 0, cfg.vocab_size)
    ref = llama.forward_train(params, cfg, toks)
    out = llama.forward_train(qparams, cfg, toks)
    # Logits drift but ranking must hold almost everywhere: top-1 token
    # agreement is the serving-relevant fidelity metric.
    agree = jnp.mean(
        (jnp.argmax(ref, axis=-1) == jnp.argmax(out, axis=-1)).astype(jnp.float32)
    )
    assert agree > 0.9, f"{mode}: top-1 agreement {agree}"


def test_quantized_structure(tiny):
    cfg, params = tiny
    qparams = quant.quantize_params(params, cfg, "int8")
    assert quant.params_quantized(qparams)
    assert not quant.params_quantized(params)
    wq = qparams["layers"]["attn"]["wq"]
    assert wq["w8"].dtype == jnp.int8
    assert wq["s"].shape == (cfg.num_layers, cfg.q_dim)
    # Norms/embed untouched.
    assert qparams["layers"]["ln1"].dtype == params["layers"]["ln1"].dtype
    assert qparams["embed"].dtype == params["embed"].dtype


def test_moe_init_quantized_rejected():
    cfg = get_config("test-tiny-moe")
    with pytest.raises(ValueError, match="MoE"):
        quant.init_params_quantized(cfg, jax.random.key(0))


# ---------------------------------------------------------------------------
# Sharding
# ---------------------------------------------------------------------------


def test_quantized_specs_shard_on_mesh(tiny):
    cfg, params = tiny
    qparams = quant.quantize_params(params, cfg, "int8")
    specs = quant.quantize_param_specs(llama.param_specs(cfg), cfg, "int8")
    mesh = make_mesh(dp=2, tp=4)
    sharded = shard_pytree(qparams, specs, mesh)
    toks = jax.random.randint(jax.random.key(3), (2, 8), 0, cfg.vocab_size)
    ref = llama.forward_train(qparams, cfg, toks)
    out = llama.forward_train(sharded, cfg, toks)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), rtol=2e-2, atol=2e-2)


# ---------------------------------------------------------------------------
# Engine integration
# ---------------------------------------------------------------------------


def _greedy_turn(engine, prompt, n=8):
    h = engine.submit(prompt, SamplingParams(temperature=0.0, max_tokens=n))
    toks, final = h.collect_tokens(timeout=120)
    assert final.error is None
    return toks


@pytest.mark.parametrize("mode", quant.QUANT_MODES)
def test_engine_serves_quantized(mode):
    cfg = get_config("test-tiny")
    eng = InferenceEngine(
        cfg,
        EngineConfig(
            num_slots=2, max_seq=64, prefill_buckets=(16,), dtype="float32",
            quant=mode, max_sessions=0,
        ),
        seed=0,
    )
    eng.start()
    try:
        a = _greedy_turn(eng, [1, 2, 3, 4])
        b = _greedy_turn(eng, [1, 2, 3, 4])
        assert a == b and len(a) == 8  # deterministic greedy decode
    finally:
        eng.stop()


def test_engine_quantizes_supplied_params(tiny):
    cfg, params = tiny
    eng = InferenceEngine(
        cfg,
        EngineConfig(
            num_slots=2, max_seq=64, prefill_buckets=(16,), dtype="float32",
            quant="int8", max_sessions=0,
        ),
        params=params,
    )
    assert quant.params_quantized(eng.params)
    eng.start()
    try:
        ref_eng = InferenceEngine(
            cfg,
            EngineConfig(
                num_slots=2, max_seq=64, prefill_buckets=(16,), dtype="float32",
                max_sessions=0,
            ),
            params=params,
        )
        ref_eng.start()
        try:
            a = _greedy_turn(eng, [5, 6, 7])
            b = _greedy_turn(ref_eng, [5, 6, 7])
            # Same weights, int8 vs dense: greedy paths usually agree on
            # the first tokens; require a common prefix, not equality.
            assert a[:2] == b[:2]
        finally:
            ref_eng.stop()
    finally:
        eng.stop()


def test_engine_on_mesh_quantized():
    cfg = get_config("test-tiny-gqa8")  # 8 kv heads: tp=4 divides them
    params = llama.init_params(cfg, jax.random.key(0), dtype=jnp.float32)
    eng = InferenceEngine(
        cfg,
        EngineConfig(
            num_slots=2, max_seq=64, prefill_buckets=(16,), dtype="float32",
            quant="int8", dp=2, tp=4, max_sessions=0,
        ),
        params=params,
    )
    eng.start()
    try:
        toks = _greedy_turn(eng, [1, 2, 3])
        assert len(toks) == 8
    finally:
        eng.stop()


# ---------------------------------------------------------------------------
# Checkpoint loader
# ---------------------------------------------------------------------------


def test_load_params_quantized(tiny, tmp_path):
    cfg, params = tiny
    path = str(tmp_path / "ckpt")
    ckpt_io.save_params(params, cfg, path)
    qparams = ckpt_io.load_params(path, cfg, dtype=jnp.float32, quant="int8")
    assert quant.params_quantized(qparams)
    toks = jax.random.randint(jax.random.key(4), (1, 10), 0, cfg.vocab_size)
    ref = llama.forward_train(params, cfg, toks)
    out = llama.forward_train(qparams, cfg, toks)
    agree = jnp.mean(
        (jnp.argmax(ref, axis=-1) == jnp.argmax(out, axis=-1)).astype(jnp.float32)
    )
    assert agree > 0.9


def test_engine_adopts_and_validates_prequantized_mode(tiny):
    cfg, params = tiny
    qparams = quant.quantize_params(params, cfg, "int8-dynamic")
    # quant unset → adopted from the tree (specs must match leaf layout).
    eng = InferenceEngine(
        cfg,
        EngineConfig(num_slots=2, max_seq=64, prefill_buckets=(16,),
                     dtype="float32", max_sessions=0),
        params=qparams,
    )
    assert quant.detect_mode(eng.params) == "int8-dynamic"
    # Contradictory config → hard error, not silent wrong arithmetic.
    with pytest.raises(ValueError, match="int8"):
        InferenceEngine(
            cfg,
            EngineConfig(num_slots=2, max_seq=64, prefill_buckets=(16,),
                         dtype="float32", quant="int8", max_sessions=0),
            params=qparams,
        )


def test_save_params_rejects_quantized(tiny, tmp_path):
    cfg, params = tiny
    qparams = quant.quantize_params(params, cfg, "int8")
    with pytest.raises(ckpt_io.CheckpointError, match="int8"):
        ckpt_io.save_params(qparams, cfg, str(tmp_path / "q"))


# ---------------------------------------------------------------------------
# int8 KV cache (models/kv_quant.py — EngineConfig.kv_quant)
# ---------------------------------------------------------------------------


def test_kv_quant_roundtrip_error_bound():
    """The documented per-row bound: dequantized error ≤ half a
    quantization step = row_absmax / 254, per element."""
    x = jax.random.normal(jax.random.key(5), (4, 32, 2, 16), jnp.float32)
    kv = kvq.quantize_rows(x)
    assert kv.q.dtype == jnp.int8
    assert kv.s.shape == (4, 32, 2) and kv.s.dtype == jnp.float32
    back = kvq.dequantize_rows(kv)
    bound = jnp.max(jnp.abs(x), axis=-1, keepdims=True) / 254.0 + 1e-6
    assert bool(jnp.all(jnp.abs(back - x) <= bound))


def test_kv_quant_np_twins_bit_identical():
    """The mock's host-side mirror must quantize EXACTLY like the
    compiled path (identical-numerics contract)."""
    rng = np.random.default_rng(0)
    x = rng.standard_normal((3, 17, 2, 16)).astype(np.float32)
    a = kvq.quantize_rows(jnp.asarray(x))
    b = kvq.quantize_rows_np(x)
    np.testing.assert_array_equal(np.asarray(a.q), b.q)
    np.testing.assert_array_equal(np.asarray(a.s), b.s)


def test_kv_quant_mode_validation():
    with pytest.raises(ValueError, match="kv_quant"):
        kvq.validate_kv_quant("int4")
    with pytest.raises(ValueError, match="kv_quant"):
        InferenceEngine(
            get_config("test-tiny"),
            EngineConfig(num_slots=2, max_seq=64, prefill_buckets=(16,),
                         dtype="float32", kv_quant="int4", max_sessions=0),
        )


def _kv_cfg(max_seq_len=384):
    return dataclasses.replace(get_config("test-tiny"), max_seq_len=max_seq_len)


def _kv_engine(kv_quant, cfg=None, **kw):
    kw.setdefault("num_slots", 2)
    kw.setdefault("max_seq", 384)
    kw.setdefault("prefill_buckets", (16,))
    kw.setdefault("max_sessions", 0)
    return InferenceEngine(
        cfg or _kv_cfg(),
        EngineConfig(dtype="float32", kv_quant=kv_quant, **kw),
        seed=0,
    )


def test_kv_quant_greedy_drift_bound_256_tokens():
    """The acceptance bar, decision-level: across >=256 teacher-forced
    decode steps (identical context fed to both cache precisions, so one
    near-tie flip cannot cascade), the int8-KV argmax agrees with the
    fp32-KV argmax on >=95% of steps and the logits drift stays under
    the documented 2% median (measured: 99.6% / 0.08%)."""
    cfg = _kv_cfg()
    params = llama.init_params(cfg, jax.random.key(0), dtype=jnp.float32)
    S, n_steps = 384, 264
    prompt = list(range(1, 9))
    step = jax.jit(
        lambda p, t, pos, ck, cv, ws: llama.forward(p, cfg, t, pos, ck, cv, ws)
    )

    def rollout(kv_quant, stream=None):
        ck, cv = llama.init_kv_cache(cfg, 1, S, dtype=jnp.float32,
                                     kv_quant=kv_quant)
        pos = jnp.arange(len(prompt), dtype=jnp.int32)[None]
        logits, ck, cv = step(
            params, jnp.asarray([prompt], jnp.int32), pos, ck, cv,
            jnp.zeros((1,), jnp.int32),
        )
        all_logits = [logits[0, -1]]
        choices = [int(jnp.argmax(logits[0, -1]))]
        cur = choices[0] if stream is None else stream[0]
        for i in range(1, n_steps):
            p = len(prompt) + i - 1
            logits, ck, cv = step(
                params, jnp.asarray([[cur]], jnp.int32),
                jnp.asarray([[p]], jnp.int32), ck, cv,
                jnp.asarray([p], jnp.int32),
            )
            all_logits.append(logits[0, 0])
            choices.append(int(jnp.argmax(logits[0, 0])))
            cur = choices[-1] if stream is None else stream[i]
        return choices, jnp.stack(all_logits)

    fp_toks, fp_logits = rollout(None)
    q8_choice, q8_logits = rollout("int8", stream=fp_toks)
    agree = np.mean([a == b for a, b in zip(fp_toks, q8_choice)])
    rel = np.linalg.norm(
        np.asarray(q8_logits - fp_logits), axis=-1
    ) / np.maximum(np.linalg.norm(np.asarray(fp_logits), axis=-1), 1e-9)
    assert len(fp_toks) >= 256
    assert agree >= 0.95, f"per-step argmax agreement {agree}"
    assert float(np.median(rel)) < 0.02, f"median logits drift {np.median(rel)}"


def test_kv_quant_engine_exact_prefix_and_bytes():
    """Free-running engines (the serving path: prefill_insert + decode
    scan): int8 KV emits an identical greedy prefix for >=24 tokens
    (measured: 75 before the first near-tie flip), and the measured
    device allocation (rows + scales) is <=0.55x the fp32 cache."""
    sp = SamplingParams(temperature=0.0, max_tokens=300)
    fp = _kv_engine(None)
    q8 = _kv_engine("int8")
    a, _ = fp.generate(list(range(1, 9)), sp)
    b, _ = q8.generate(list(range(1, 9)), sp)
    assert len(a) == len(b) == 300
    div = next((i for i, (x, y) in enumerate(zip(a, b)) if x != y), len(a))
    assert div >= 24, f"greedy diverged at token {div}"
    assert q8.metrics["kv_quant_enabled"] == 1
    ratio = (
        q8.metrics["kv_quant_device_bytes"] / fp.metrics["kv_quant_device_bytes"]
    )
    assert ratio <= 0.55, f"kv bytes ratio {ratio}"


def test_kv_quant_spec_decode_verify_path():
    """The verify program writes its [B, K+1] KV window through the same
    quantizer: greedy spec decoding over int8 KV matches the fp32-KV
    spec engine token-for-token on a short repeat-heavy prompt (well
    inside the exact-prefix regime) and the verify path engages."""
    cfg = _kv_cfg(max_seq_len=128)
    kw = dict(cfg=cfg, max_seq=64, prefill_buckets=(8, 16), spec_decode=3)
    sp = SamplingParams(temperature=0.0, max_tokens=12)
    prompt = [1, 2, 3, 1, 2, 3, 1, 2]
    q8 = _kv_engine("int8", **kw)
    fp = _kv_engine(None, **kw)
    b, _ = q8.generate(prompt, sp)
    a, _ = fp.generate(prompt, sp)
    assert q8.metrics["spec_steps"] > 0
    assert a == b


def test_kv_quant_mock_round_trip_exact():
    """The mock mirrors the quantize/dequant round-trip host-side with
    EXACTLY unchanged output, and its observed drift respects the same
    documented bound the real scheme carries."""
    a, _ = MockEngine().generate([72, 105])
    m8 = MockEngine(kv_quant="int8")
    b, _ = m8.generate([72, 105])
    assert a == b  # scripted playback is exact under kv_quant
    assert m8.metrics["kv_quant_enabled"] == 1
    assert m8.metrics["kv_quant_rows_written"] == 2 + len(b)
    assert 0.0 < m8.metrics["kv_quant_roundtrip_rel_err"] < 0.01
    with pytest.raises(ValueError, match="kv_quant"):
        MockEngine(kv_quant="int4")


def test_kv_quant_session_and_restore_round_trip():
    """Session offload/restore pages int8 rows + scales verbatim (the
    page itself adds zero drift). The fresh-engine comparison is bounded
    rather than structural: the restored arm extends against int8 prefix
    rows while the fresh arm's single-bucket prefill attends the
    original float rows — a near-tie argmax flip between the arms is
    legal, though 4-token turns sit deep inside the measured exact
    regime (free-running divergence starts ~token 75)."""
    cfg = _kv_cfg(max_seq_len=128)
    kw = dict(cfg=cfg, max_seq=128, prefill_buckets=(8, 16), num_slots=2,
              max_sessions=8)
    sp = SamplingParams(temperature=0.0, max_tokens=4)
    q8 = _kv_engine("int8", **kw)
    p1 = [1, 2, 3, 4, 5, 6, 7, 8]
    h = q8.submit(p1, sp, session_id="s")
    while q8.step():
        pass
    toks1, _ = h.collect_tokens(timeout=60)
    sess = q8._sessions["s"]
    q8._offload_session(sess)                  # force the page-out
    assert q8.metrics["session_offloads"] == 1
    p2 = p1 + toks1[:-1] + [9, 9]
    h2 = q8.submit(p2, sp, session_id="s")
    while q8.step():
        pass
    toks2, _ = h2.collect_tokens(timeout=60)
    assert q8.metrics["session_restores"] == 1
    fresh = _kv_engine("int8", **kw)
    want, _ = fresh.generate(p2, sp)
    assert len(toks2) == len(want) and toks2[:2] == want[:2]
    assert sum(int(x == y) for x, y in zip(toks2, want)) >= len(want) - 1
