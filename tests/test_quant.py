"""int8 quantization: qdot accuracy, full-forward fidelity, engine + loader
integration, sharded specs. (models/quant.py — the single-chip capacity
path for the Llama-3-8B north star; see BASELINE.md.)"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from omnia_tpu.engine import EngineConfig, InferenceEngine, SamplingParams
from omnia_tpu.models import checkpoint as ckpt_io
from omnia_tpu.models import get_config, llama, quant
from omnia_tpu.parallel import make_mesh, shard_pytree


@pytest.fixture(scope="module")
def tiny():
    cfg = get_config("test-tiny")
    params = llama.init_params(cfg, jax.random.key(0), dtype=jnp.float32)
    return cfg, params


# ---------------------------------------------------------------------------
# qdot
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("mode", quant.QUANT_MODES)
def test_qdot_matches_dense(mode):
    k1, k2 = jax.random.split(jax.random.key(0))
    h = jax.random.normal(k1, (4, 64), dtype=jnp.float32)
    w = jax.random.normal(k2, (64, 32), dtype=jnp.float32) * 0.05
    ref = jnp.dot(h, w)
    out = quant.qdot(h, quant.quantize_weight(w, mode))
    # int8 per-channel round-trip: ~0.5% weight error (w8a16), plus the
    # same again on activations for w8a8.
    tol = 0.02 if mode == "int8" else 0.05
    err = jnp.linalg.norm(out - ref) / jnp.linalg.norm(ref)
    assert err < tol, f"{mode}: relative error {err}"


def test_qdot_passthrough_dense_weight():
    h = jnp.ones((2, 8))
    w = jnp.ones((8, 4))
    np.testing.assert_allclose(quant.qdot(h, w), jnp.dot(h, w))


def test_scale_commutes_with_contraction():
    """The w8a16 identity the design rests on: per-output-channel scale
    applied to the output equals dequantizing the weight first."""
    k1, k2 = jax.random.split(jax.random.key(1))
    h = jax.random.normal(k1, (3, 16), dtype=jnp.float32)
    w = jax.random.normal(k2, (16, 8), dtype=jnp.float32)
    d = quant.quantize_weight(w, "int8")
    dequant = d["w8"].astype(jnp.float32) * d["s"][None, :]
    np.testing.assert_allclose(
        np.asarray(quant.qdot(h, d)),
        np.asarray(jnp.dot(h, dequant)),
        rtol=1e-5, atol=1e-5,
    )


# ---------------------------------------------------------------------------
# Full-forward fidelity
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("mode", quant.QUANT_MODES)
def test_forward_close_to_dense(tiny, mode):
    cfg, params = tiny
    qparams = quant.quantize_params(params, cfg, mode)
    toks = jax.random.randint(jax.random.key(2), (2, 12), 0, cfg.vocab_size)
    ref = llama.forward_train(params, cfg, toks)
    out = llama.forward_train(qparams, cfg, toks)
    # Logits drift but ranking must hold almost everywhere: top-1 token
    # agreement is the serving-relevant fidelity metric.
    agree = jnp.mean(
        (jnp.argmax(ref, axis=-1) == jnp.argmax(out, axis=-1)).astype(jnp.float32)
    )
    assert agree > 0.9, f"{mode}: top-1 agreement {agree}"


def test_quantized_structure(tiny):
    cfg, params = tiny
    qparams = quant.quantize_params(params, cfg, "int8")
    assert quant.params_quantized(qparams)
    assert not quant.params_quantized(params)
    wq = qparams["layers"]["attn"]["wq"]
    assert wq["w8"].dtype == jnp.int8
    assert wq["s"].shape == (cfg.num_layers, cfg.q_dim)
    # Norms/embed untouched.
    assert qparams["layers"]["ln1"].dtype == params["layers"]["ln1"].dtype
    assert qparams["embed"].dtype == params["embed"].dtype


def test_moe_init_quantized_rejected():
    cfg = get_config("test-tiny-moe")
    with pytest.raises(ValueError, match="MoE"):
        quant.init_params_quantized(cfg, jax.random.key(0))


# ---------------------------------------------------------------------------
# Sharding
# ---------------------------------------------------------------------------


def test_quantized_specs_shard_on_mesh(tiny):
    cfg, params = tiny
    qparams = quant.quantize_params(params, cfg, "int8")
    specs = quant.quantize_param_specs(llama.param_specs(cfg), cfg, "int8")
    mesh = make_mesh(dp=2, tp=4)
    sharded = shard_pytree(qparams, specs, mesh)
    toks = jax.random.randint(jax.random.key(3), (2, 8), 0, cfg.vocab_size)
    ref = llama.forward_train(qparams, cfg, toks)
    out = llama.forward_train(sharded, cfg, toks)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(out), rtol=2e-2, atol=2e-2)


# ---------------------------------------------------------------------------
# Engine integration
# ---------------------------------------------------------------------------


def _greedy_turn(engine, prompt, n=8):
    h = engine.submit(prompt, SamplingParams(temperature=0.0, max_tokens=n))
    toks, final = h.collect_tokens(timeout=120)
    assert final.error is None
    return toks


@pytest.mark.parametrize("mode", quant.QUANT_MODES)
def test_engine_serves_quantized(mode):
    cfg = get_config("test-tiny")
    eng = InferenceEngine(
        cfg,
        EngineConfig(
            num_slots=2, max_seq=64, prefill_buckets=(16,), dtype="float32",
            quant=mode, max_sessions=0,
        ),
        seed=0,
    )
    eng.start()
    try:
        a = _greedy_turn(eng, [1, 2, 3, 4])
        b = _greedy_turn(eng, [1, 2, 3, 4])
        assert a == b and len(a) == 8  # deterministic greedy decode
    finally:
        eng.stop()


def test_engine_quantizes_supplied_params(tiny):
    cfg, params = tiny
    eng = InferenceEngine(
        cfg,
        EngineConfig(
            num_slots=2, max_seq=64, prefill_buckets=(16,), dtype="float32",
            quant="int8", max_sessions=0,
        ),
        params=params,
    )
    assert quant.params_quantized(eng.params)
    eng.start()
    try:
        ref_eng = InferenceEngine(
            cfg,
            EngineConfig(
                num_slots=2, max_seq=64, prefill_buckets=(16,), dtype="float32",
                max_sessions=0,
            ),
            params=params,
        )
        ref_eng.start()
        try:
            a = _greedy_turn(eng, [5, 6, 7])
            b = _greedy_turn(ref_eng, [5, 6, 7])
            # Same weights, int8 vs dense: greedy paths usually agree on
            # the first tokens; require a common prefix, not equality.
            assert a[:2] == b[:2]
        finally:
            ref_eng.stop()
    finally:
        eng.stop()


def test_engine_on_mesh_quantized():
    cfg = get_config("test-tiny-gqa8")  # 8 kv heads: tp=4 divides them
    params = llama.init_params(cfg, jax.random.key(0), dtype=jnp.float32)
    eng = InferenceEngine(
        cfg,
        EngineConfig(
            num_slots=2, max_seq=64, prefill_buckets=(16,), dtype="float32",
            quant="int8", dp=2, tp=4, max_sessions=0,
        ),
        params=params,
    )
    eng.start()
    try:
        toks = _greedy_turn(eng, [1, 2, 3])
        assert len(toks) == 8
    finally:
        eng.stop()


# ---------------------------------------------------------------------------
# Checkpoint loader
# ---------------------------------------------------------------------------


def test_load_params_quantized(tiny, tmp_path):
    cfg, params = tiny
    path = str(tmp_path / "ckpt")
    ckpt_io.save_params(params, cfg, path)
    qparams = ckpt_io.load_params(path, cfg, dtype=jnp.float32, quant="int8")
    assert quant.params_quantized(qparams)
    toks = jax.random.randint(jax.random.key(4), (1, 10), 0, cfg.vocab_size)
    ref = llama.forward_train(params, cfg, toks)
    out = llama.forward_train(qparams, cfg, toks)
    agree = jnp.mean(
        (jnp.argmax(ref, axis=-1) == jnp.argmax(out, axis=-1)).astype(jnp.float32)
    )
    assert agree > 0.9


def test_engine_adopts_and_validates_prequantized_mode(tiny):
    cfg, params = tiny
    qparams = quant.quantize_params(params, cfg, "int8-dynamic")
    # quant unset → adopted from the tree (specs must match leaf layout).
    eng = InferenceEngine(
        cfg,
        EngineConfig(num_slots=2, max_seq=64, prefill_buckets=(16,),
                     dtype="float32", max_sessions=0),
        params=qparams,
    )
    assert quant.detect_mode(eng.params) == "int8-dynamic"
    # Contradictory config → hard error, not silent wrong arithmetic.
    with pytest.raises(ValueError, match="int8"):
        InferenceEngine(
            cfg,
            EngineConfig(num_slots=2, max_seq=64, prefill_buckets=(16,),
                         dtype="float32", quant="int8", max_sessions=0),
            params=qparams,
        )


def test_save_params_rejects_quantized(tiny, tmp_path):
    cfg, params = tiny
    qparams = quant.quantize_params(params, cfg, "int8")
    with pytest.raises(ckpt_io.CheckpointError, match="int8"):
        ckpt_io.save_params(qparams, cfg, str(tmp_path / "q"))
