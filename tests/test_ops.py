"""Numerics tests for core ops against straightforward NumPy references."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from omnia_tpu.ops.attention import gqa_attention
from omnia_tpu.ops.norms import rms_norm
from omnia_tpu.ops.rope import apply_rope, rope_cos_sin
from omnia_tpu.ops.sampling import sample_tokens


def test_rms_norm_matches_reference():
    rng = np.random.default_rng(0)
    x = rng.standard_normal((2, 5, 16)).astype(np.float32)
    w = rng.standard_normal(16).astype(np.float32)
    eps = 1e-5
    expected = x / np.sqrt((x**2).mean(-1, keepdims=True) + eps) * w
    got = rms_norm(jnp.asarray(x), jnp.asarray(w), eps)
    np.testing.assert_allclose(np.asarray(got), expected, rtol=1e-5, atol=1e-5)


def test_rms_norm_preserves_dtype():
    x = jnp.ones((2, 8), dtype=jnp.bfloat16)
    w = jnp.ones(8, dtype=jnp.bfloat16)
    assert rms_norm(x, w).dtype == jnp.bfloat16


def test_rope_preserves_norm():
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.standard_normal((1, 6, 4, 32)).astype(np.float32))
    pos = jnp.arange(6, dtype=jnp.int32)[None, :]
    cos, sin = rope_cos_sin(pos, 32, 10000.0)
    out = apply_rope(x, cos, sin)
    np.testing.assert_allclose(
        np.linalg.norm(np.asarray(out), axis=-1),
        np.linalg.norm(np.asarray(x), axis=-1),
        rtol=1e-4,
    )


def test_rope_relative_property():
    """<rope(q,m), rope(k,n)> depends only on m-n."""
    rng = np.random.default_rng(2)
    q = jnp.asarray(rng.standard_normal((1, 1, 1, 16)).astype(np.float32))
    k = jnp.asarray(rng.standard_normal((1, 1, 1, 16)).astype(np.float32))

    def dot_at(m, n):
        pos_q = jnp.full((1, 1), m, dtype=jnp.int32)
        pos_k = jnp.full((1, 1), n, dtype=jnp.int32)
        cq, sq = rope_cos_sin(pos_q, 16, 10000.0)
        ck, sk = rope_cos_sin(pos_k, 16, 10000.0)
        return float(jnp.sum(apply_rope(q, cq, sq) * apply_rope(k, ck, sk)))

    assert abs(dot_at(5, 3) - dot_at(12, 10)) < 1e-4
    assert abs(dot_at(0, 0) - dot_at(7, 7)) < 1e-4


def _naive_attention(q, k, v, q_pos):
    """NumPy GQA reference. q [B,T,H,D]; k,v [B,S,Hkv,D]; q_pos [B,T]."""
    B, T, H, D = q.shape
    S, Hkv = k.shape[1], k.shape[2]
    G = H // Hkv
    out = np.zeros_like(q, dtype=np.float32)
    for b in range(B):
        for h in range(H):
            kv_h = h // G
            scores = q[b, :, h] @ k[b, :, kv_h].T / np.sqrt(D)  # [T,S]
            mask = np.arange(S)[None, :] <= q_pos[b][:, None]
            scores = np.where(mask, scores, -1e30)
            e = np.exp(scores - scores.max(-1, keepdims=True))
            p = e / e.sum(-1, keepdims=True)
            out[b, :, h] = p @ v[b, :, kv_h]
    return out


def test_gqa_attention_matches_naive():
    rng = np.random.default_rng(3)
    B, T, S, H, Hkv, D = 2, 4, 8, 4, 2, 16
    q = rng.standard_normal((B, T, H, D)).astype(np.float32)
    k = rng.standard_normal((B, S, Hkv, D)).astype(np.float32)
    v = rng.standard_normal((B, S, Hkv, D)).astype(np.float32)
    q_pos = np.array([[0, 1, 2, 3], [2, 3, 4, 5]], dtype=np.int32)
    got = gqa_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), jnp.asarray(q_pos))
    expected = _naive_attention(q, k, v, q_pos)
    np.testing.assert_allclose(np.asarray(got), expected, rtol=1e-4, atol=1e-4)


def test_gqa_attention_mha_case():
    """H == Hkv (no grouping) still works."""
    rng = np.random.default_rng(4)
    B, T, S, H, D = 1, 2, 4, 2, 8
    q = rng.standard_normal((B, T, H, D)).astype(np.float32)
    k = rng.standard_normal((B, S, H, D)).astype(np.float32)
    v = rng.standard_normal((B, S, H, D)).astype(np.float32)
    q_pos = np.array([[1, 2]], dtype=np.int32)
    got = gqa_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), jnp.asarray(q_pos))
    expected = _naive_attention(q, k, v, q_pos)
    np.testing.assert_allclose(np.asarray(got), expected, rtol=1e-4, atol=1e-4)


class TestSampling:
    def test_greedy_when_temperature_zero(self):
        logits = jnp.asarray([[0.1, 5.0, 0.2], [3.0, 0.0, -1.0]])
        toks = sample_tokens(
            logits,
            jax.random.key(0),
            temperature=jnp.zeros(2),
            top_p=jnp.ones(2),
        )
        assert toks.tolist() == [1, 0]

    def test_top_k_restricts_support(self):
        logits = jnp.asarray([[0.0, 1.0, 2.0, 3.0]] * 64, dtype=jnp.float32)
        toks = sample_tokens(
            logits,
            jax.random.key(1),
            temperature=jnp.full(64, 10.0),  # near-uniform over survivors
            top_p=jnp.ones(64),
            top_k=2,
        )
        assert set(np.asarray(toks).tolist()) <= {2, 3}

    def test_top_p_restricts_support(self):
        # softmax([0,0,10,10]) ≈ [~0, ~0, .5, .5]; top_p=0.9 keeps {2,3}.
        logits = jnp.asarray([[0.0, 0.0, 10.0, 10.0]] * 64, dtype=jnp.float32)
        toks = sample_tokens(
            logits,
            jax.random.key(2),
            temperature=jnp.ones(64),
            top_p=jnp.full(64, 0.9),
        )
        assert set(np.asarray(toks).tolist()) <= {2, 3}

    def test_mixed_batch_greedy_and_sampled(self):
        logits = jnp.asarray([[0.0, 4.0], [4.0, 0.0]])
        toks = sample_tokens(
            logits,
            jax.random.key(3),
            temperature=jnp.asarray([0.0, 1.0]),
            top_p=jnp.ones(2),
        )
        assert int(toks[0]) == 1

    def test_jittable(self):
        f = jax.jit(lambda l, k, t, p: sample_tokens(l, k, t, p, top_k=4))
        out = f(
            jnp.zeros((2, 16)),
            jax.random.key(0),
            jnp.ones(2),
            jnp.full(2, 0.9),
        )
        assert out.shape == (2,)


def test_top_k_top_p_sequential_semantics():
    """top_p nucleus must be computed over the RENORMALIZED top-k survivors
    (HF/vLLM sequential filtering), not the full distribution."""
    # probs: [0.3, 0.2, 0.05 x 10] -> top_k=2 survivors renormalize to
    # [0.6, 0.4]; top_p=0.5 then admits only token 0.
    probs = np.array([[0.3, 0.2] + [0.05] * 10], dtype=np.float32)
    logits = jnp.asarray(np.log(probs))
    logits64 = jnp.tile(logits, (64, 1))
    toks = sample_tokens(
        logits64,
        jax.random.key(7),
        temperature=jnp.ones(64),
        top_p=jnp.full(64, 0.5),
        top_k=jnp.full(64, 2, dtype=jnp.int32),
    )
    assert set(np.asarray(toks).tolist()) == {0}
