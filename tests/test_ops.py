"""Numerics tests for core ops against straightforward NumPy references."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from omnia_tpu.ops.attention import gqa_attention
from omnia_tpu.ops.norms import rms_norm
from omnia_tpu.ops.rope import apply_rope, rope_cos_sin
from omnia_tpu.ops.sampling import sample_tokens


def test_rms_norm_matches_reference():
    rng = np.random.default_rng(0)
    x = rng.standard_normal((2, 5, 16)).astype(np.float32)
    w = rng.standard_normal(16).astype(np.float32)
    eps = 1e-5
    expected = x / np.sqrt((x**2).mean(-1, keepdims=True) + eps) * w
    got = rms_norm(jnp.asarray(x), jnp.asarray(w), eps)
    np.testing.assert_allclose(np.asarray(got), expected, rtol=1e-5, atol=1e-5)


def test_rms_norm_preserves_dtype():
    x = jnp.ones((2, 8), dtype=jnp.bfloat16)
    w = jnp.ones(8, dtype=jnp.bfloat16)
    assert rms_norm(x, w).dtype == jnp.bfloat16


def test_rope_preserves_norm():
    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.standard_normal((1, 6, 4, 32)).astype(np.float32))
    pos = jnp.arange(6, dtype=jnp.int32)[None, :]
    cos, sin = rope_cos_sin(pos, 32, 10000.0)
    out = apply_rope(x, cos, sin)
    np.testing.assert_allclose(
        np.linalg.norm(np.asarray(out), axis=-1),
        np.linalg.norm(np.asarray(x), axis=-1),
        rtol=1e-4,
    )


def test_rope_relative_property():
    """<rope(q,m), rope(k,n)> depends only on m-n."""
    rng = np.random.default_rng(2)
    q = jnp.asarray(rng.standard_normal((1, 1, 1, 16)).astype(np.float32))
    k = jnp.asarray(rng.standard_normal((1, 1, 1, 16)).astype(np.float32))

    def dot_at(m, n):
        pos_q = jnp.full((1, 1), m, dtype=jnp.int32)
        pos_k = jnp.full((1, 1), n, dtype=jnp.int32)
        cq, sq = rope_cos_sin(pos_q, 16, 10000.0)
        ck, sk = rope_cos_sin(pos_k, 16, 10000.0)
        return float(jnp.sum(apply_rope(q, cq, sq) * apply_rope(k, ck, sk)))

    assert abs(dot_at(5, 3) - dot_at(12, 10)) < 1e-4
    assert abs(dot_at(0, 0) - dot_at(7, 7)) < 1e-4


def _naive_attention(q, k, v, q_pos):
    """NumPy GQA reference. q [B,T,H,D]; k,v [B,S,Hkv,D]; q_pos [B,T]."""
    B, T, H, D = q.shape
    S, Hkv = k.shape[1], k.shape[2]
    G = H // Hkv
    out = np.zeros_like(q, dtype=np.float32)
    for b in range(B):
        for h in range(H):
            kv_h = h // G
            scores = q[b, :, h] @ k[b, :, kv_h].T / np.sqrt(D)  # [T,S]
            mask = np.arange(S)[None, :] <= q_pos[b][:, None]
            scores = np.where(mask, scores, -1e30)
            e = np.exp(scores - scores.max(-1, keepdims=True))
            p = e / e.sum(-1, keepdims=True)
            out[b, :, h] = p @ v[b, :, kv_h]
    return out


def test_gqa_attention_matches_naive():
    rng = np.random.default_rng(3)
    B, T, S, H, Hkv, D = 2, 4, 8, 4, 2, 16
    q = rng.standard_normal((B, T, H, D)).astype(np.float32)
    k = rng.standard_normal((B, S, Hkv, D)).astype(np.float32)
    v = rng.standard_normal((B, S, Hkv, D)).astype(np.float32)
    q_pos = np.array([[0, 1, 2, 3], [2, 3, 4, 5]], dtype=np.int32)
    got = gqa_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), jnp.asarray(q_pos))
    expected = _naive_attention(q, k, v, q_pos)
    np.testing.assert_allclose(np.asarray(got), expected, rtol=1e-4, atol=1e-4)


def test_gqa_attention_mha_case():
    """H == Hkv (no grouping) still works."""
    rng = np.random.default_rng(4)
    B, T, S, H, D = 1, 2, 4, 2, 8
    q = rng.standard_normal((B, T, H, D)).astype(np.float32)
    k = rng.standard_normal((B, S, H, D)).astype(np.float32)
    v = rng.standard_normal((B, S, H, D)).astype(np.float32)
    q_pos = np.array([[1, 2]], dtype=np.int32)
    got = gqa_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), jnp.asarray(q_pos))
    expected = _naive_attention(q, k, v, q_pos)
    np.testing.assert_allclose(np.asarray(got), expected, rtol=1e-4, atol=1e-4)


class TestSampling:
    def test_greedy_when_temperature_zero(self):
        logits = jnp.asarray([[0.1, 5.0, 0.2], [3.0, 0.0, -1.0]])
        toks = sample_tokens(
            logits,
            jax.random.key(0),
            temperature=jnp.zeros(2),
            top_p=jnp.ones(2),
        )
        assert toks.tolist() == [1, 0]

    def test_top_k_restricts_support(self):
        logits = jnp.asarray([[0.0, 1.0, 2.0, 3.0]] * 64, dtype=jnp.float32)
        toks = sample_tokens(
            logits,
            jax.random.key(1),
            temperature=jnp.full(64, 10.0),  # near-uniform over survivors
            top_p=jnp.ones(64),
            top_k=2,
        )
        assert set(np.asarray(toks).tolist()) <= {2, 3}

    def test_top_p_restricts_support(self):
        # softmax([0,0,10,10]) ≈ [~0, ~0, .5, .5]; top_p=0.9 keeps {2,3}.
        logits = jnp.asarray([[0.0, 0.0, 10.0, 10.0]] * 64, dtype=jnp.float32)
        toks = sample_tokens(
            logits,
            jax.random.key(2),
            temperature=jnp.ones(64),
            top_p=jnp.full(64, 0.9),
        )
        assert set(np.asarray(toks).tolist()) <= {2, 3}

    def test_mixed_batch_greedy_and_sampled(self):
        logits = jnp.asarray([[0.0, 4.0], [4.0, 0.0]])
        toks = sample_tokens(
            logits,
            jax.random.key(3),
            temperature=jnp.asarray([0.0, 1.0]),
            top_p=jnp.ones(2),
        )
        assert int(toks[0]) == 1

    def test_jittable(self):
        f = jax.jit(lambda l, k, t, p: sample_tokens(l, k, t, p, top_k=4))
        out = f(
            jnp.zeros((2, 16)),
            jax.random.key(0),
            jnp.ones(2),
            jnp.full(2, 0.9),
        )
        assert out.shape == (2,)


def test_top_k_top_p_sequential_semantics():
    """top_p nucleus must be computed over the RENORMALIZED top-k survivors
    (HF/vLLM sequential filtering), not the full distribution."""
    # probs: [0.3, 0.2, 0.05 x 10] -> top_k=2 survivors renormalize to
    # [0.6, 0.4]; top_p=0.5 then admits only token 0.
    probs = np.array([[0.3, 0.2] + [0.05] * 10], dtype=np.float32)
    logits = jnp.asarray(np.log(probs))
    logits64 = jnp.tile(logits, (64, 1))
    toks = sample_tokens(
        logits64,
        jax.random.key(7),
        temperature=jnp.ones(64),
        top_p=jnp.full(64, 0.5),
        top_k=jnp.full(64, 2, dtype=jnp.int32),
    )
    assert set(np.asarray(toks).tolist()) == {0}


def test_fast_prefix_threshold_matches_full_sort():
    """The top_k-prefix fast path must be semantics-identical to the
    full-sort path across regimes: peaked rows (fast path engages), flat
    rows (nucleus past the prefix → fallback), and top_k beyond the
    prefix (fallback)."""
    import numpy as np

    from omnia_tpu.ops import sampling as S

    rng = np.random.default_rng(0)
    V = 4096  # > _FAST_PREFIX_K so the prefix is a strict subset

    def full_sort_reference(scaled, top_p, top_k):
        # Full-sort formulation: smallest descending prefix of the top-k
        # survivors whose mass reaches top_p * survivor mass.
        scaled = jnp.asarray(scaled, jnp.float32)
        sorted_desc = jnp.sort(scaled, axis=-1)[..., ::-1]
        k = jnp.clip(jnp.asarray(top_k, jnp.int32), 0, V)
        kth = jnp.take_along_axis(
            sorted_desc, jnp.clip(k - 1, 0, V - 1)[:, None], axis=-1)
        k_thresh = jnp.where((k > 0)[:, None], kth, -1e30)
        in_topk = jnp.arange(V)[None, :] < jnp.where(k > 0, k, V)[:, None]
        m = sorted_desc[:, :1]
        e = jnp.where(in_topk, jnp.exp(sorted_desc - m), 0.0)
        cum = jnp.cumsum(e, axis=-1)
        denom = jnp.where(
            k > 0,
            jnp.take_along_axis(
                cum, jnp.clip(k - 1, 0, V - 1)[:, None], axis=-1)[:, 0],
            cum[:, -1],
        )
        keep = in_topk & (
            (cum - e) < jnp.asarray(top_p)[:, None] * denom[:, None])
        p_thresh = jnp.min(
            jnp.where(keep, sorted_desc, jnp.inf), axis=-1, keepdims=True)
        # Disabled knobs (top_p>=1, k=0) mean NO filtering: express that
        # as an open threshold rather than the row minimum — at f32 the
        # cumsum boundary is ulp-noisy there, and "admit everything" is
        # the defined semantics.
        no_filter = (jnp.asarray(top_p) >= 1.0) & (k <= 0)
        p_thresh = jnp.where(no_filter[:, None], -1e30, p_thresh)
        return jnp.maximum(k_thresh, p_thresh)

    cases = [
        # peaked logits, typical serving knobs (incl. a default-params
        # row: top_p=1/k=0 is exempt, not a fallback trigger) → FAST
        (rng.normal(0, 4, (4, V)), [0.9, 0.95, 0.5, 1.0], [0, 40, 8, 0], True),
        # near-flat logits: top-256 mass << top_p → full-sort fallback
        (rng.normal(0, 0.01, (3, V)), [0.99, 0.9, 0.999], [0, 0, 0], False),
        # top_k beyond the prefix → fallback
        (rng.normal(0, 2, (2, V)), [0.9, 1.0], [1000, 2000], False),
        # mixed batch: one row would be fast, one forces fallback
        (rng.normal(0, 2, (2, V)) * np.array([[4.0], [0.01]]),
         [0.9, 0.99], [0, 0], False),
        # all-defaults batch (the common serving case) must be FAST
        (rng.normal(0, 2, (4, V)), [1.0] * 4, [0] * 4, True),
    ]
    fast_seen = slow_seen = False
    for logits, top_p, top_k, want_fast in cases:
        # Guard the guard: assert each case exercises the intended branch.
        assert S.fast_path_feasible(logits, top_p, top_k) is want_fast, (
            "case no longer hits its intended path", top_p, top_k)
        fast_seen |= want_fast
        slow_seen |= not want_fast
        scaled = jnp.asarray(logits, jnp.float32)
        got = S._filter_thresholds(
            scaled,
            jnp.asarray(top_p, jnp.float32),
            jnp.asarray(top_k, jnp.int32),
        )
        want = full_sort_reference(logits, np.asarray(top_p, np.float32), top_k)
        # Compare ADMITTED SETS, not raw thresholds: an unfiltered row's
        # threshold may be -inf on one path and the row minimum on the
        # other — same admitted vocabulary either way.
        np.testing.assert_array_equal(
            np.asarray(scaled >= got), np.asarray(scaled >= want))
    assert fast_seen and slow_seen
