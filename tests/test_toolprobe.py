"""ToolRegistry reachability probes (VERDICT r4 #7): endpoint
derivation, TCP probing, phase computation, controller status
projection, and the doctor check — an unreachable tool shows up in CRD
status AND doctor output.
"""

import time
import socket
import threading

import pytest

from omnia_tpu.operator import toolprobe
from omnia_tpu.operator.controller import ControllerManager
from omnia_tpu.operator.resources import Resource
from omnia_tpu.operator.store import MemoryResourceStore


@pytest.fixture
def live_port():
    """A listening TCP socket (reachable endpoint)."""
    srv = socket.socket()
    srv.bind(("127.0.0.1", 0))
    srv.listen(8)
    t = threading.Thread(target=lambda: [c[0].close() for c in
                                         iter(lambda: _accept(srv), None)],
                         daemon=True)
    t.start()
    yield srv.getsockname()[1]
    srv.close()


def _accept(srv):
    try:
        return srv.accept()
    except OSError:
        return None


class TestEndpointDerivation:
    def test_per_handler_type(self):
        assert toolprobe.endpoint_of(
            {"handler": {"type": "http", "url": "http://kb:8080/s"}}
        ) == "http://kb:8080/s"
        assert toolprobe.endpoint_of(
            {"handler": {"type": "grpc",
                         "grpcConfig": {"endpoint": "billing:50051"}}}
        ) == "billing:50051"
        assert toolprobe.endpoint_of(
            {"handler": {"type": "mcp",
                         "mcpConfig": {"transport": "stdio", "command": "x"}}}
        ) == "stdio://"
        assert toolprobe.endpoint_of(
            {"handler": {"type": "mcp",
                         "mcpConfig": {"endpoint": "http://mcp:9000/mcp"}}}
        ) == "http://mcp:9000/mcp"
        assert toolprobe.endpoint_of({"handler": {"type": "client"}}) == "client://"
        assert toolprobe.endpoint_of(
            {"handler": {"type": "openapi",
                         "openAPIConfig": {"specURL": "https://api.x/spec"}}}
        ) == "https://api.x/spec"

    def test_probe_address_forms(self):
        assert toolprobe.probe_address("http://h:81/x") == ("h", 81)
        assert toolprobe.probe_address("https://h/x") == ("h", 443)
        assert toolprobe.probe_address("grpc-host:50051") == ("grpc-host", 50051)
        assert toolprobe.probe_address("not an endpoint") is None


class TestProbe:
    def test_reachable_and_unreachable(self, live_port):
        status, err = toolprobe.probe_one(f"http://127.0.0.1:{live_port}/x",
                                          timeout_s=2.0)
        assert status == "Available" and not err
        status, err = toolprobe.probe_one("http://127.0.0.1:1/x", timeout_s=0.5)
        assert status == "Unavailable" and "probe failed" in err

    def test_unprobeable_endpoints_stay_unknown(self):
        assert toolprobe.probe_one("stdio://")[0] == "Unknown"
        assert toolprobe.probe_one("client://")[0] == "Unknown"
        assert toolprobe.probe_one("")[0] == "Unknown"

    def test_bad_address_is_misconfiguration(self):
        status, err = toolprobe.probe_one("no-port-here")
        assert status == "Unavailable" and "unrecognized" in err

    def test_phases(self):
        A, U, K = "Available", "Unavailable", "Unknown"

        def mk(*sts):
            return [{"status": s} for s in sts]

        assert toolprobe.phase_of([]) == "Pending"
        assert toolprobe.phase_of(mk(A, A, K)) == "Ready"
        assert toolprobe.phase_of(mk(A, U)) == "Degraded"
        assert toolprobe.phase_of(mk(U, U, K)) == "Failed"


class TestControllerIntegration:
    def test_unreachable_tool_surfaces_in_status_and_doctor(self, live_port):
        store = MemoryResourceStore()
        cm = ControllerManager(store)
        try:
            store.apply(Resource(kind="ToolRegistry", name="tr", spec={
                "probe": {"timeoutSeconds": 0.5},
                "tools": [
                    {"name": "up", "handler": {
                        "type": "http",
                        "url": f"http://127.0.0.1:{live_port}/hook"}},
                    {"name": "down", "handler": {
                        "type": "grpc", "endpoint": "127.0.0.1:1"}},
                    {"name": "browser", "handler": {"type": "client"}},
                ],
            }))
            cm.drain_queue()
            res = store.get("default", "ToolRegistry", "tr")
            status = res.status
            assert status["phase"] == "Degraded"
            assert status["discoveredToolsCount"] == 3
            by_name = {t["name"]: t for t in status["tools"]}
            assert by_name["up"]["status"] == "Available"
            assert by_name["down"]["status"] == "Unavailable"
            assert "probe failed" in by_name["down"]["error"]
            assert by_name["browser"]["status"] == "Unknown"
            assert "down" in status["message"]

            # doctor reads the same status
            from omnia_tpu.doctor import Doctor

            doc = Doctor()
            doc.add_tool_registry_check(store)
            report = doc.run()
            assert report["status"] == "warn"
            tr_check = next(c for c in report["checks"]
                            if c["name"] == "tool-registries")
            assert "down" in tr_check["detail"]
        finally:
            cm.shutdown()

    def test_backend_death_flips_phase_on_resync(self):
        """Reachability is a LIVE property: a backend that dies after
        apply must flip Ready→Degraded on the next interval re-probe —
        not stay green forever."""
        srv = socket.socket()
        srv.bind(("127.0.0.1", 0))
        srv.listen(4)
        port = srv.getsockname()[1]
        t = threading.Thread(target=lambda: [c[0].close() for c in
                                             iter(lambda: _accept(srv), None)],
                             daemon=True)
        t.start()
        store = MemoryResourceStore()
        cm = ControllerManager(store)
        try:
            store.apply(Resource(kind="ToolRegistry", name="tr", spec={
                "probe": {"timeoutSeconds": 0.5, "intervalSeconds": 0.0},
                "tools": [{"name": "t", "handler": {
                    "type": "grpc", "endpoint": f"127.0.0.1:{port}"}}],
            }))
            cm.drain_queue()
            assert store.get("default", "ToolRegistry", "tr").status["phase"] == "Ready"
            srv.close()  # backend dies
            # intervalSeconds=0 → due immediately; the controller's own
            # background resync may have a pre-death probe in flight, so
            # re-probe until the dead backend is observed (bounded).
            deadline = time.time() + 10.0
            status = {}
            while time.time() < deadline:
                cm.resync()
                cm.join_probes()
                status = store.get("default", "ToolRegistry", "tr").status
                if status.get("phase") == "Failed":
                    break
                time.sleep(0.05)
            assert status["phase"] == "Failed", status
            assert status["tools"][0]["status"] == "Unavailable"
        finally:
            cm.shutdown()
            srv.close()

    def test_probe_disabled_reports_declared_only(self):
        store = MemoryResourceStore()
        cm = ControllerManager(store)
        try:
            store.apply(Resource(kind="ToolRegistry", name="tr", spec={
                "probe": {"enabled": False},
                "tools": [{"name": "t", "handler": {
                    "type": "grpc", "endpoint": "127.0.0.1:1"}}],
            }))
            cm.drain_queue()
            status = store.get("default", "ToolRegistry", "tr").status
            assert status["phase"] == "Ready"
            assert status["tools"][0]["status"] == "Unknown"
        finally:
            cm.shutdown()
