"""Stall-free batching: mixed prefill+decode equivalence battery.

The token-budget scheduler (``EngineConfig.prefill_chunk_tokens``,
engine/interleave.py) must be a pure LATENCY optimization: interleaved
prefill produces bit-identical greedy tokens AND resident KV rows to
monolithic prefill-first serving — under int8 KV, with grammar slots in
the batch, from a shared-prefix pool seed, and across mid-prefill
deadline/cancel aborts (partial books stay exact). Everything here is
hermetic (test-tiny model, CPU, single-threaded stepping).
"""

import numpy as np
import pytest

from omnia_tpu.engine import (
    EngineConfig,
    FinishReason,
    InferenceEngine,
    SamplingParams,
)
from omnia_tpu.models import get_config
from omnia_tpu.models.kv_quant import is_quant_kv

pytestmark = pytest.mark.interleave

CFG = get_config("test-tiny")
BASE = dict(
    num_slots=4, max_seq=128, prefill_buckets=(8, 16, 32), dtype="float32",
    max_sessions=4,
)


def _engine(chunk=0, **kw):
    merged = {**BASE, **kw}
    return InferenceEngine(
        CFG, EngineConfig(**merged, prefill_chunk_tokens=chunk), seed=0
    )


def _kv_rows(eng, slot, n):
    """Host copies of one slot's leading KV rows (QuantKV-aware)."""
    out = []
    for c in (eng._ck, eng._cv):
        if is_quant_kv(c):
            out.append(np.asarray(c.q)[:, slot, :n])
            out.append(np.asarray(c.s)[:, slot, :n])
        else:
            out.append(np.asarray(c)[:, slot, :n])
    return out


def _run_pair(eng, prompt_b, sp_b, warm_steps=3, **submit_b):
    """One long-running greedy decode (slot 0) + one arrival mid-stream:
    the arrival's prefill is the work under test. Returns both streams."""
    sp_a = SamplingParams(temperature=0.0, max_tokens=60)
    ha = eng.submit([1, 2, 3, 4], sp_a)
    for _ in range(warm_steps):
        eng.step()
    assert eng._slots[0].active  # decode live when the arrival lands
    hb = eng.submit(prompt_b, sp_b, **submit_b)
    while eng.step():
        pass
    return ha.collect_tokens(timeout=30), hb.collect_tokens(timeout=30)


PROMPT_B = list(range(5, 35))  # 30 tokens -> several 4-token pieces


class TestBitExactEquivalence:
    def test_tokens_and_kv_match_monolithic(self):
        base = _engine(0)
        mix = _engine(4)
        (ta0, _), (tb0, fb0) = _run_pair(
            base, PROMPT_B, SamplingParams(temperature=0.0, max_tokens=8)
        )
        (ta1, _), (tb1, fb1) = _run_pair(
            mix, PROMPT_B, SamplingParams(temperature=0.0, max_tokens=8)
        )
        # The interleaved arm actually interleaved...
        assert mix.metrics["mixed_steps"] >= 8  # ceil(30 / 4) pieces
        assert mix.metrics["interleaved_prefill_tokens"] == len(PROMPT_B)
        # ...and never stalled decode, while prefill-first did.
        assert mix.metrics["decode_stall_steps"] == 0
        assert base.metrics["decode_stall_steps"] > 0
        assert base.metrics["mixed_steps"] == 0
        # Bit-identical streams AND resident KV (prompt + decoded rows).
        assert ta0 == ta1 and tb0 == tb1
        assert fb0.finish_reason == fb1.finish_reason
        rows = len(PROMPT_B) + fb0.num_generated_tokens - 1
        for x, y in zip(_kv_rows(base, 1, rows), _kv_rows(mix, 1, rows)):
            np.testing.assert_array_equal(x, y)
        # prefill_tokens metered per piece sums to the monolithic count.
        assert (
            mix.metrics["prefill_tokens"] == base.metrics["prefill_tokens"]
        )

    def test_tokens_and_kv_match_under_int8_kv(self):
        # Prompt LONGER than the largest bucket so the monolithic arm
        # takes the chunked-extend path too: under int8 KV the extend
        # seam attends already-quantized resident rows, while a fresh
        # self-contained prefill attends its own FLOAT chunk — a
        # documented pre-existing ±1-LSB asymmetry (docs/serving.md "KV
        # cache precision", pinned since the int8 PR). Extend-vs-extend
        # is exactly chunk-size invariant, so interleaving stays
        # bit-identical to what monolithic serving stores.
        long_b = list(range(5, 45))  # 40 tokens > max bucket 32
        base = _engine(0, kv_quant="int8")
        mix = _engine(4, kv_quant="int8")
        (ta0, _), (tb0, _) = _run_pair(
            base, long_b, SamplingParams(temperature=0.0, max_tokens=8)
        )
        (ta1, _), (tb1, _) = _run_pair(
            mix, long_b, SamplingParams(temperature=0.0, max_tokens=8)
        )
        assert mix.metrics["mixed_steps"] >= 10
        assert ta0 == ta1 and tb0 == tb1
        # int8 rows AND their f32 scales bit-identical: the mixed
        # program quantizes at the same _write_kv seam.
        for x, y in zip(
            _kv_rows(base, 1, len(long_b)), _kv_rows(mix, 1, len(long_b))
        ):
            np.testing.assert_array_equal(x, y)
        # Short fresh prompts (monolithic takes the float-attending
        # fresh-prefill program) still emit identical greedy TOKENS.
        (_, _), (ts0, _) = _run_pair(
            _engine(0, kv_quant="int8"), PROMPT_B,
            SamplingParams(temperature=0.0, max_tokens=8),
        )
        (_, _), (ts1, _) = _run_pair(
            _engine(4, kv_quant="int8"), PROMPT_B,
            SamplingParams(temperature=0.0, max_tokens=8),
        )
        assert ts0 == ts1

    def test_multi_turn_session_reuse_matches_monolithic(self):
        """Turn 2 of a session extends from the turn-1 rows on both
        policies; the interleaved extend pieces must reproduce the
        monolithic suffix exactly."""
        turn1 = list(range(40, 60))
        results = []
        for chunk in (0, 4):
            eng = _engine(chunk)
            ha = eng.submit(
                [1, 2, 3, 4], SamplingParams(temperature=0.0, max_tokens=90)
            )
            for _ in range(3):
                eng.step()
            h1 = eng.submit(
                turn1, SamplingParams(temperature=0.0, max_tokens=4),
                session_id="s",
            )
            while eng.step():
                pass
            t1, _ = h1.collect_tokens(timeout=30)
            # Turn 2: same session, prompt = turn1 + reply + new tokens.
            ha2 = eng.submit(
                [1, 2, 3, 4], SamplingParams(temperature=0.0, max_tokens=60)
            )
            for _ in range(3):
                eng.step()
            turn2 = turn1 + t1 + [7, 8, 9]
            h2 = eng.submit(
                turn2, SamplingParams(temperature=0.0, max_tokens=4),
                session_id="s",
            )
            while eng.step():
                pass
            t2, _ = h2.collect_tokens(timeout=30)
            results.append((t1, t2, eng.metrics["prefix_reuse_tokens"]))
            ha.collect_tokens(timeout=30)
            ha2.collect_tokens(timeout=30)
        assert results[0] == results[1]
        assert results[0][2] > 0  # turn 2 really reused resident rows


class TestGrammarInterleave:
    @pytest.fixture(scope="class")
    def engines(self):
        kw = dict(
            num_slots=4, max_seq=128, prefill_buckets=(8, 16, 32),
            dtype="float32", max_sessions=0, grammar=True,
            grammar_max_states=512,
        )
        return (
            InferenceEngine(
                CFG, EngineConfig(**kw, prefill_chunk_tokens=0), seed=0
            ),
            InferenceEngine(
                CFG, EngineConfig(**kw, prefill_chunk_tokens=4), seed=0
            ),
        )

    def _grammar(self):
        from omnia_tpu.engine.grammar import compile_json_schema
        from omnia_tpu.engine.tokenizer import ByteTokenizer

        schema = {
            "type": "object",
            "properties": {"a": {"type": "integer"}},
            "required": ["a"],
        }
        return compile_json_schema(schema, ByteTokenizer())

    def test_active_grammar_slot_and_constrained_arrival(self, engines):
        """A grammar-constrained slot keeps decoding through mixed steps
        (FSM state rides the fused program), and an arriving request WITH
        a grammar gets its first-token start-state bias inside the final
        mixed piece — both bit-identical to prefill-first."""
        g = self._grammar()
        sp_g = SamplingParams(
            temperature=0.0, max_tokens=40, stop_token_ids=(0,)
        )
        streams = []
        for eng in engines:
            ha = eng.submit(list(b"make json"), sp_g, grammar=g)
            for _ in range(3):
                eng.step()
            assert eng._slots[0].active
            hb = eng.submit(PROMPT_B, SamplingParams(
                temperature=0.0, max_tokens=6))
            hc = eng.submit(list(b"second json goes here, a long prompt"),
                            sp_g, grammar=g)
            while eng.step():
                pass
            streams.append((
                ha.collect_tokens(timeout=30)[0],
                hb.collect_tokens(timeout=30)[0],
                hc.collect_tokens(timeout=30)[0],
            ))
        assert streams[0] == streams[1]
        mix = engines[1]
        assert mix.metrics["mixed_steps"] > 0
        assert mix.metrics["decode_stall_steps"] == 0
        # The constrained streams really walked the grammar.
        v = g.view(CFG.vocab_size, (0,))
        for toks in (streams[0][0], streams[0][2]):
            s = v.start
            for t in toks:
                assert v.allowed(s)[t]
                s = v.advance(s, t)


class TestPrefixSeededInterleave:
    SYS = list(range(1, 25))  # 24 tokens >= prefix_cache_min_tokens

    def _run(self, chunk):
        eng = _engine(chunk, prefix_cache_slots=2, max_sessions=0)
        eng.register_prefix(self.SYS)
        # Publish the registered prefix from an idle first placement
        # (monolithic on both arms — nothing to stall).
        h0 = eng.submit(
            self.SYS + [30], SamplingParams(temperature=0.0, max_tokens=2)
        )
        while eng.step():
            pass
        h0.collect_tokens(timeout=30)
        # A live decoder + a fresh seeded arrival: only the suffix
        # should prefill, interleaved.
        ha = eng.submit(
            [9, 9, 9], SamplingParams(temperature=0.0, max_tokens=40)
        )
        for _ in range(3):
            eng.step()
        hb = eng.submit(
            self.SYS + [31, 32, 33],
            SamplingParams(temperature=0.0, max_tokens=6),
        )
        while eng.step():
            pass
        ha.collect_tokens(timeout=30)
        return eng, hb.collect_tokens(timeout=30)

    def test_seeded_placement_matches_monolithic(self):
        base, (tb0, _) = self._run(0)
        mix, (tb1, _) = self._run(4)
        assert tb0 == tb1
        hit = base.metrics["prefix_cache_hit_tokens"]
        assert hit > 0  # the pool really served the head
        assert mix.metrics["prefix_cache_hit_tokens"] == hit
        # Seeded head + interleaved suffix: only the suffix rode mixed
        # steps, and decode never stalled for it.
        assert 0 < mix.metrics["interleaved_prefill_tokens"] < len(self.SYS) + 3
        assert mix.metrics["decode_stall_steps"] == 0


class TestMidPrefillAborts:
    def test_deadline_mid_prefill_partial_counts_stay_exact(self):
        eng = _engine(4)
        clock = [0.0]
        eng.clock = lambda: clock[0]
        ha = eng.submit(
            [1, 2, 3, 4], SamplingParams(temperature=0.0, max_tokens=60)
        )
        for _ in range(3):
            eng.step()
        pb = list(range(10, 40))
        prefill0 = eng.metrics["prefill_tokens"]  # A's own prefill
        hb = eng.submit(
            pb, SamplingParams(temperature=0.0, max_tokens=4),
            session_id="s1", deadline_s=5.0,
        )
        eng.step()  # begins the interleave + consumes the first piece
        assert eng._prefilling is not None
        consumed = eng.metrics["interleaved_prefill_tokens"]
        assert 0 < consumed < len(pb)
        clock[0] = 6.0  # TTL expires mid-prefill
        eng.step()
        assert eng._prefilling is None
        toks, fin = hb.collect_tokens(timeout=30)
        assert fin.finish_reason is FinishReason.DEADLINE and toks == []
        assert fin.num_prompt_tokens == len(pb)
        assert eng.metrics["deadline_exceeded"] == 1
        # Partial books exact: only consumed pieces were ever counted.
        assert eng.metrics["prefill_tokens"] - prefill0 == consumed
        assert eng.metrics["interleaved_prefill_tokens"] == consumed
        # The consumed rows stay genuinely valid: the retry on the same
        # session reuses exactly the consumed frontier and still emits
        # the fresh-prefill greedy tokens.
        hb2 = eng.submit(
            pb, SamplingParams(temperature=0.0, max_tokens=4),
            session_id="s1",
        )
        while eng.step():
            pass
        t2, fin2 = hb2.collect_tokens(timeout=30)
        assert fin2.finish_reason is FinishReason.LENGTH
        assert eng.metrics["prefix_reuse_tokens"] == consumed
        ha.collect_tokens(timeout=30)
        ref = _engine(0)
        rt, _ = ref.generate(pb, SamplingParams(temperature=0.0, max_tokens=4))
        assert t2 == rt

    def test_cancel_mid_prefill_frees_the_slot(self):
        eng = _engine(4)
        ha = eng.submit(
            [1, 2, 3, 4], SamplingParams(temperature=0.0, max_tokens=60)
        )
        for _ in range(3):
            eng.step()
        hb = eng.submit(
            list(range(10, 40)), SamplingParams(temperature=0.0, max_tokens=4)
        )
        eng.step()
        assert eng._prefilling is not None
        hb.cancel()
        eng.step()
        assert eng._prefilling is None
        _toks, fin = hb.collect_tokens(timeout=30)
        assert fin.finish_reason is FinishReason.CANCELLED
        # The slot is immediately reusable.
        hc = eng.submit(
            list(range(50, 70)), SamplingParams(temperature=0.0, max_tokens=4)
        )
        while eng.step():
            pass
        _t, fin_c = hc.collect_tokens(timeout=30)
        assert fin_c.finish_reason is FinishReason.LENGTH
        ha.collect_tokens(timeout=30)
        # Books balance: every submit reached exactly one terminal.
        assert (
            eng.metrics["requests_finished"]
            == eng.metrics["requests_submitted"] == 3
        )

    def test_drain_completes_half_prefilled_request(self):
        eng = _engine(4)
        ha = eng.submit(
            [1, 2, 3, 4], SamplingParams(temperature=0.0, max_tokens=30)
        )
        for _ in range(3):
            eng.step()
        hb = eng.submit(
            list(range(10, 40)), SamplingParams(temperature=0.0, max_tokens=4)
        )
        eng.step()
        assert eng._prefilling is not None
        eng.stop(drain=True)  # threadless drain steps the engine inline
        _toks, fin = hb.collect_tokens(timeout=30)
        assert fin.finish_reason is FinishReason.LENGTH
        ha.collect_tokens(timeout=30)


class TestWarmupCoversMixedPrograms:
    def test_no_compiles_during_interleaved_placement(self):
        """The mixed family is AOT-compiled by warmup (TTFT discipline):
        an interleaved placement on a warm engine must trigger zero
        compiles."""
        import io
        import logging as _logging

        import jax as _jax

        eng = _engine(4)
        eng.warmup()
        with _jax.log_compiles():
            stream = io.StringIO()
            handler = _logging.StreamHandler(stream)
            logger = _logging.getLogger("jax._src.dispatch")
            logger.addHandler(handler)
            try:
                ha = eng.submit(
                    [1, 2, 3], SamplingParams(temperature=0.0, max_tokens=40)
                )
                for _ in range(3):
                    eng.step()
                hb = eng.submit(
                    PROMPT_B, SamplingParams(temperature=0.0, max_tokens=4)
                )
                while eng.step():
                    pass
                ha.collect_tokens(timeout=30)
                hb.collect_tokens(timeout=30)
            finally:
                logger.removeHandler(handler)
            logged = stream.getvalue()
        assert eng.metrics["mixed_steps"] > 0
        assert "Compiling" not in logged, logged


class TestLoadSignal:
    def test_engine_reports_prompt_token_backlog(self):
        eng = _engine(4)
        ha = eng.submit(
            [1, 2, 3, 4], SamplingParams(temperature=0.0, max_tokens=60)
        )
        for _ in range(3):
            eng.step()
        pb = list(range(10, 40))
        eng.submit(pb, SamplingParams(temperature=0.0, max_tokens=4))
        assert eng.pending_prefill_tokens() == len(pb)  # still queued
        eng.step()  # interleave begins; some pieces consumed
        pf = eng._prefilling
        assert pf is not None
        assert (
            eng.pending_prefill_tokens() == len(pb) - pf.frontier > 0
        )
        while eng.step():
            pass
        assert eng.pending_prefill_tokens() == 0
        ha.collect_tokens(timeout=30)

    def test_coordinator_load_counts_token_backlog(self):
        """Four 8k-prompt requests must not route like four 10-token
        ones: the load signal folds the prompt-token backlog in."""
        from omnia_tpu.engine.coordinator import EngineCoordinator
        from omnia_tpu.engine.mock import MockEngine

        a, b = MockEngine(), MockEngine()
        coord = EngineCoordinator([a, b])
        with a._lock:
            a._live_prompt_tokens = 4 * 8192  # queued prefill WORK
        assert coord._load(0) > coord._load(1) + 1.0
        # A fresh short request routes to the token-idle worker.
        assert coord._pick(None, [1, 2, 3]) == 1

    def test_coordinator_load_tolerates_legacy_workers(self):
        from omnia_tpu.engine.coordinator import EngineCoordinator

        class Legacy:
            def queue_depth(self):
                return 2

            def active_slots(self):
                return 1

            def healthy(self):
                return True

            def start(self):
                pass

            def stop(self, drain=False):
                pass

        coord = EngineCoordinator([Legacy()])
        assert coord._load(0) == 3.0  # count-only load, no raise


class TestMockParity:
    def test_mock_mirrors_interleave_metrics(self):
        from omnia_tpu.engine import MockEngine

        mock = MockEngine(prefill_chunk_tokens=8)
        prompt = list(b"hello mock interleave")  # 21 tokens -> 3 pieces
        _toks, fin = mock.generate(prompt)
        assert fin.finish_reason is not None
        assert mock.metrics["mixed_steps"] == 3
        assert mock.metrics["interleaved_prefill_tokens"] == len(prompt)
        assert mock.metrics["decode_stall_steps"] == 0
        assert mock.pending_prefill_tokens() == 0

    def test_mock_counts_stalls_without_budget(self):
        import time as _time

        from omnia_tpu.engine import MockEngine
        from omnia_tpu.engine.mock import Scenario

        mock = MockEngine([Scenario(".*", reply="x" * 30,
                                    delay_per_token_s=0.005)])
        h1 = mock.submit(list(b"one"), SamplingParams(max_tokens=30))
        _time.sleep(0.02)  # first playback live when the second prefills
        h2 = mock.submit(list(b"two"), SamplingParams(max_tokens=30))
        h1.collect_tokens(timeout=10)
        h2.collect_tokens(timeout=10)
        assert mock.metrics["decode_stall_steps"] >= 1
        assert mock.metrics["mixed_steps"] == 0
