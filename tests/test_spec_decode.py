"""Prompt-lookup speculative decoding (engine/spec_decode.py): the
verify path must be TOKEN-IDENTICAL to vanilla greedy decode while
spending measurably fewer weight streams on repetitive context, and it
must disengage cleanly for sampled/mixed traffic and near cache limits."""

from __future__ import annotations

import pytest

from omnia_tpu.engine import EngineConfig, InferenceEngine, SamplingParams
from omnia_tpu.models import get_config


def _engine(spec: int, **over):
    kw = dict(num_slots=2, max_seq=128, prefill_buckets=(16,),
              dtype="float32", decode_chunk=4, max_sessions=4,
              spec_decode=spec)
    kw.update(over)
    eng = InferenceEngine(get_config("test-tiny"), EngineConfig(**kw), seed=0)
    eng.warmup()
    return eng


GREEDY = SamplingParams(temperature=0.0, max_tokens=24)
# A prompt with strong n-gram repetition (the prompt-lookup sweet spot).
REPETITIVE = [5, 6, 7, 8, 5, 6, 7, 8, 5, 6, 7, 8, 5, 6]
PLAIN = [9, 3, 14, 2, 7]


@pytest.mark.parametrize("prompt", [REPETITIVE, PLAIN])
def test_spec_greedy_identical_to_vanilla(prompt):
    """Same model, same prompt, greedy: spec decode must emit exactly
    the tokens vanilla decode emits (acceptance is lossless)."""
    vanilla = _engine(0)
    toks_ref, fin_ref = vanilla.generate(prompt, GREEDY)
    spec = _engine(4)
    toks, fin = spec.generate(prompt, GREEDY)
    assert toks == toks_ref, (toks, toks_ref)
    assert fin.finish_reason == fin_ref.finish_reason
    assert spec.metrics["spec_steps"] > 0, "spec path never engaged"


def test_spec_spends_fewer_weight_streams_on_repetition():
    """The roofline claim: tokens per weight stream must clearly beat 1
    once generation turns repetitive (greedy decode of the tiny model
    settles into a loop the n-gram lookup predicts)."""
    eng = _engine(4)
    toks, _fin = eng.generate(
        REPETITIVE, SamplingParams(temperature=0.0, max_tokens=100))
    steps = eng.metrics["spec_steps"] + eng.metrics["decode_steps"]
    assert len(toks) == 100
    assert eng.metrics["spec_accepted"] > 0
    tokens_per_stream = len(toks) / steps
    assert tokens_per_stream > 1.4, (
        f"{tokens_per_stream:.2f} tok/stream — speculation isn't paying")


def test_spec_disengages_for_sampled_traffic():
    """A sampled request in the batch forces the exact chunked path —
    and sampled outputs stay seed-reproducible with spec configured."""
    eng = _engine(4)
    eng.start()
    try:
        sampled = SamplingParams(temperature=0.8, top_p=0.9, max_tokens=10,
                                 seed=7)
        h1 = eng.submit(PLAIN, sampled)
        h2 = eng.submit(REPETITIVE, GREEDY)
        t1, _ = h1.collect_tokens(timeout=120)
        t2, _ = h2.collect_tokens(timeout=120)
        assert len(t1) == 10 and len(t2) == 24
    finally:
        eng.stop()
    ref = _engine(0)
    t1_ref, _ = ref.generate(PLAIN, sampled)
    assert t1 == t1_ref, "sampled reproducibility broken by spec config"


def test_spec_respects_stop_tokens_and_budget():
    """A stop id inside an accepted run must end the stream AT the stop
    token — speculation can't overshoot the contract."""
    eng = _engine(4)
    toks_ref, fin_ref = _engine(0).generate(
        REPETITIVE, SamplingParams(temperature=0.0, max_tokens=24,
                                   stop_token_ids=(6,)))
    toks, fin = eng.generate(
        REPETITIVE, SamplingParams(temperature=0.0, max_tokens=24,
                                   stop_token_ids=(6,)))
    assert toks == toks_ref and fin.finish_reason == fin_ref.finish_reason


def test_spec_sessions_reuse_stays_correct():
    """Cross-turn prefix reuse on top of spec decode: turn 2 reuses
    rows written by verify steps, so its output must match a fresh
    engine's answer for the same conversation."""
    eng = _engine(4)
    h1 = eng.submit(REPETITIVE, GREEDY, session_id="sess")
    eng_drive(eng, h1)
    t1, _ = h1.collect_tokens(timeout=1)
    follow = REPETITIVE + t1 + [9]
    h2 = eng.submit(follow, GREEDY, session_id="sess")
    eng_drive(eng, h2)
    t2, _ = h2.collect_tokens(timeout=1)
    assert eng.metrics["prefix_reuse_tokens"] > 0
    ref = _engine(0)
    t2_ref, _ = ref.generate(follow, GREEDY)
    assert t2 == t2_ref


def eng_drive(eng, handle, max_steps=3000):
    """Drive steps inline until the handle has its final event queued."""
    for _ in range(max_steps):
        eng.step()
        if handle._queue.qsize() and any(
            ev.is_final for ev in list(handle._queue.queue)
        ):
            return
    raise AssertionError("request did not finish")


def test_spec_coexists_with_grammar_slot():
    """A grammar-constrained greedy slot no longer disables spec for the
    whole batch: verify steps still run, the constrained output is
    token-identical to the non-spec masked path (spec only ever emits
    tokens whose unmasked argmax the grammar admits — where masked and
    unmasked greedy coincide), and every emitted token is admissible
    under the host FSM walk."""
    import json

    import jsonschema

    from omnia_tpu.engine.grammar import compile_json_schema
    from omnia_tpu.engine.tokenizer import ByteTokenizer

    tok = ByteTokenizer()
    schema = {"type": "object",
              "properties": {"a": {"type": "integer"},
                             "ok": {"type": "boolean"}},
              "required": ["a", "ok"]}
    g = compile_json_schema(schema, tok)
    over = dict(num_slots=2, grammar=True, grammar_max_states=512)
    sp_g = SamplingParams(temperature=0.0, max_tokens=100,
                          stop_token_ids=(0,))

    ref = _engine(0, **over)
    hg = ref.submit(tok.encode("make json"), sp_g, grammar=g)
    eng_drive(ref, hg)
    toks_ref, _ = hg.collect_tokens(timeout=1)

    eng = _engine(4, **over)
    hg = eng.submit(tok.encode("make json"), sp_g, grammar=g)
    hf = eng.submit(REPETITIVE, SamplingParams(temperature=0.0,
                                               max_tokens=60))
    eng_drive(eng, hf)
    eng_drive(eng, hg)
    toks_f, _ = hf.collect_tokens(timeout=1)
    toks_g, fin_g = hg.collect_tokens(timeout=1)

    assert eng.metrics["spec_steps"] > 0, "grammar slot suspended spec"
    assert toks_g == toks_ref, "spec changed constrained greedy output"
    payload = [t for t in toks_g if t != 0]
    jsonschema.validate(json.loads(tok.decode(payload)), schema)
    view = g.view(eng.model_cfg.vocab_size, (0,))
    s = view.start
    for t in toks_g:
        assert view.allowed(s)[t], (s, t)
        s = view.advance(s, t)
    toks_f_ref, _ = _engine(0).generate(
        REPETITIVE, SamplingParams(temperature=0.0, max_tokens=60))
    assert toks_f == toks_f_ref, "unconstrained slot diverged"


def test_spec_config_validation():
    with pytest.raises(ValueError, match="spec_decode"):
        InferenceEngine(
            get_config("test-tiny"),
            EngineConfig(num_slots=2, max_seq=64, prefill_buckets=(4,),
                         dtype="float32", spec_decode=8),
        )
