"""Speculative-decoding suite (EngineConfig.spec_decode).

Two halves, one marker (``spec``, tier-1):

- **Controllers** (jax-free): the bounded ``_NgramIndex``, the shared
  per-slot depth policy (``spec_depth_update``), the ``_SpecGate``
  duty-cycle self-gate, and the MockEngine mirror — this subset runs in
  the CI analysis job with no jax installed (module-level imports stay
  jax-free; engine-backed cases importorskip jax).
- **Equivalence battery**: the verify path must be TOKEN-IDENTICAL to
  vanilla (masked) greedy decode while spending measurably fewer weight
  streams on repetitive context — across sampled co-tenants, grammar
  constraints, int8 KV, token-budget interleaving, and mid-stream
  deadline/cancel with exact partial ledgers.
"""

from __future__ import annotations

import pytest

import omnia_tpu.engine.spec_decode as sd
from omnia_tpu.engine.spec_decode import (
    _NgramIndex,
    _SpecGate,
    spec_depth_update,
    validate_spec_config,
)

pytestmark = pytest.mark.spec


# ---------------------------------------------------------------------------
# Bounded n-gram index (jax-free)
# ---------------------------------------------------------------------------


class TestNgramIndex:
    def test_proposes_most_recent_continuation(self):
        idx = _NgramIndex()
        prop, real = idx.propose([5, 6, 7, 8, 5, 6], 3)
        assert (prop, real) == ([7, 8, 5], 3)

    def test_miss_returns_zero_real(self):
        idx = _NgramIndex()
        prop, real = idx.propose([1, 2, 3, 4, 5], 4)
        assert real == 0 and prop == [0, 0, 0, 0]

    def test_incremental_appends_only(self):
        idx = _NgramIndex()
        ctx = [1, 2, 3]
        idx.propose(ctx, 2)
        built = dict(idx.built)
        ctx += [1, 2]
        prop, real = idx.propose(ctx, 2)
        assert real == 2 and prop == [3, 1]
        assert all(idx.built[n] >= built[n] for n in idx.built)

    def test_cap_bounds_entries_with_fifo_eviction(self, monkeypatch):
        monkeypatch.setattr(sd, "_NGRAM_CAP", 8)
        idx = _NgramIndex()
        ctx = list(range(100))  # all-distinct grams: every insert is new
        idx.propose(ctx, 4)
        assert all(len(m) <= 8 for m in idx.maps.values())
        assert idx.entries() <= 8 * sd._NGRAM_MAX
        # The RECENT context stays indexed (eviction drops the oldest;
        # the tail gram itself is the query and is never inserted).
        assert (98,) in idx.maps[1]
        assert (0,) not in idx.maps[1]

    def test_entries_counts_all_orders(self):
        idx = _NgramIndex()
        idx.propose([1, 2, 1, 2, 1], 2)
        assert idx.entries() == sum(len(m) for m in idx.maps.values())

    def test_recurring_grams_survive_eviction(self, monkeypatch):
        """Eviction is least-recently-INGESTED: a gram that keeps
        recurring re-inserts at the back of the order and outlives
        cold grams — the hot prompt grams are exactly the hits."""
        monkeypatch.setattr(sd, "_NGRAM_CAP", 8)
        idx = _NgramIndex()
        ctx = [42] + list(range(100)) + [42, 43]
        idx.propose(ctx, 4)
        assert (42,) in idx.maps[1]      # re-seen late: survived
        assert (0,) not in idx.maps[1]   # seen once, early: evicted
        assert idx.maps[1][(42,)] == 101  # and points at the LATEST spot


# ---------------------------------------------------------------------------
# Per-slot depth policy (jax-free)
# ---------------------------------------------------------------------------


class TestDepthPolicy:
    def test_full_accepts_grow_to_kmax(self):
        ema, k = 0.5, 4
        for _ in range(20):
            ema, k = spec_depth_update(ema, k or 1, k or 1, kmax=8)
        assert k == 8 and ema > 0.95

    def test_rejects_collapse_to_zero(self):
        ema, k = 1.0, 8
        seen = [k]
        for _ in range(30):
            ema, k = spec_depth_update(ema, max(k, 1), 0, kmax=8)
            seen.append(k)
        assert k == 0 and seen[0] > seen[len(seen) // 4] >= k

    def test_fixed_mode_tracks_ema_only(self):
        ema, k = spec_depth_update(0.0, 4, 4, kmax=0)
        assert k == 0 and ema > 0.0  # caller pins depth in fixed mode

    def test_config_validation(self):
        from omnia_tpu.engine.types import EngineConfig

        validate_spec_config(EngineConfig())  # off: dead knobs unvalidated
        with pytest.raises(ValueError, match="spec_decode_max"):
            validate_spec_config(EngineConfig(
                prefill_buckets=(32,), spec_decode=4, spec_decode_max=2))
        with pytest.raises(ValueError, match="spec window"):
            validate_spec_config(EngineConfig(
                prefill_buckets=(8,), spec_decode=4, spec_decode_max=16))
        with pytest.raises(ValueError, match="spec_gate_window"):
            validate_spec_config(EngineConfig(
                prefill_buckets=(32,), spec_decode=4, spec_gate_window=-1))


# ---------------------------------------------------------------------------
# Online self-gate (jax-free)
# ---------------------------------------------------------------------------


def _drive_gate(gate, phases):
    """Feed (rate tokens/s per tick-second) per phase; returns the
    permitted-flag history. One tick per simulated second."""
    t, toks, out = 0.0, 0, []
    for rate, ticks in phases:
        for _ in range(ticks):
            t += 1.0
            toks += rate
            out.append(gate.tick(t, toks))
    return out


class TestSpecGate:
    def test_window_zero_always_allows(self):
        g = _SpecGate(0)
        assert all(_drive_gate(g, [(1, 50)])) and g.state_code() == 0

    def test_slow_spec_disables_and_reports(self):
        g = _SpecGate(10)
        # Spec probe realizes 10 tok/s, plain probe 30 → disable.
        _drive_gate(g, [(10, 10), (30, 10)])
        assert g.state == _SpecGate.HOLD_OFF and not g.allows_spec()
        assert g.state_code() == 2 and g.disables == 1
        rep = g.report()
        assert rep["state"] == "off"
        assert rep["rate_plain_tok_s"] > rep["rate_spec_tok_s"]

    def test_fast_spec_stays_on(self):
        g = _SpecGate(10)
        _drive_gate(g, [(30, 10), (10, 10)])
        assert g.state == _SpecGate.HOLD_ON and g.allows_spec()
        assert g.state_code() == 1 and g.disables == 0

    def test_hold_expires_into_reprobe(self):
        g = _SpecGate(4, hold_factor=2)
        _drive_gate(g, [(1, 4), (9, 4)])   # decide: off
        assert g.state == _SpecGate.HOLD_OFF
        _drive_gate(g, [(9, 8)])           # hold (2×4 ticks) expires
        assert g.state == _SpecGate.PROBE_SPEC  # re-probing: spec allowed
        assert g.allows_spec() and g.decisions == 1


# ---------------------------------------------------------------------------
# MockEngine mirror (jax-free)
# ---------------------------------------------------------------------------


class TestMockMirror:
    def test_greedy_playback_books_spec_ledger(self):
        from omnia_tpu.engine.mock import MockEngine, Scenario
        from omnia_tpu.engine.types import SamplingParams

        # Gate off for the ledger assertions: probe phases are wall-
        # clock driven, so a gated mirror could legitimately spend the
        # whole short script in a suppressed window.
        m = MockEngine(
            [Scenario("hi", "ab ab ab ab ab ab ab ab")],
            spec_decode=3, spec_decode_max=6,
        )
        toks, fin = m.generate(
            m.tokenizer.encode("hi"), SamplingParams(temperature=0.0,
                                                     max_tokens=64)
        )
        # Scripted output EXACTLY unchanged by the mirror.
        assert m.tokenizer.decode(toks) == "ab ab ab ab ab ab ab ab"
        assert m.metrics["spec_steps"] > 0
        assert m.metrics["spec_accepted"] > 0
        assert 0.0 < m.metrics["spec_accept_ema"] <= 1.0
        assert m.metrics["spec_index_bytes"] > 0
        assert m.metrics["spec_gate_state"] in (0, 1, 2)

    def test_sampled_playback_never_engages_mirror(self):
        from omnia_tpu.engine.mock import MockEngine, Scenario
        from omnia_tpu.engine.types import SamplingParams

        m = MockEngine([Scenario("hi", "ab ab ab ab")], spec_decode=3)
        m.generate(m.tokenizer.encode("hi"),
                   SamplingParams(temperature=0.7, max_tokens=64))
        assert m.metrics["spec_steps"] == 0


# ---------------------------------------------------------------------------
# Engine-backed equivalence battery (importorskips jax)
# ---------------------------------------------------------------------------


def _engine(spec: int, **over):
    pytest.importorskip("jax")
    from omnia_tpu.engine import EngineConfig, InferenceEngine
    from omnia_tpu.models import get_config

    kw = dict(num_slots=2, max_seq=128, prefill_buckets=(16,),
              dtype="float32", decode_chunk=4, max_sessions=4,
              spec_decode=spec)
    kw.update(over)
    eng = InferenceEngine(get_config("test-tiny"), EngineConfig(**kw), seed=0)
    eng.warmup()
    return eng


def _sp(**kw):
    from omnia_tpu.engine import SamplingParams

    return SamplingParams(**kw)


GREEDY = dict(temperature=0.0, max_tokens=24)
# A prompt with strong n-gram repetition (the prompt-lookup sweet spot).
REPETITIVE = [5, 6, 7, 8, 5, 6, 7, 8, 5, 6, 7, 8, 5, 6]
PLAIN = [9, 3, 14, 2, 7]


def eng_drive(eng, handle, max_steps=3000):
    """Drive steps inline until the handle has its final event queued."""
    for _ in range(max_steps):
        eng.step()
        if handle._queue.qsize() and any(
            ev.is_final for ev in list(handle._queue.queue)
        ):
            return
    raise AssertionError("request did not finish")


@pytest.mark.parametrize("prompt", [REPETITIVE, PLAIN])
def test_spec_greedy_identical_to_vanilla(prompt):
    """Same model, same prompt, greedy: spec decode must emit exactly
    the tokens vanilla decode emits (acceptance is lossless)."""
    vanilla = _engine(0)
    toks_ref, fin_ref = vanilla.generate(prompt, _sp(**GREEDY))
    spec = _engine(4)
    toks, fin = spec.generate(prompt, _sp(**GREEDY))
    assert toks == toks_ref, (toks, toks_ref)
    assert fin.finish_reason == fin_ref.finish_reason
    assert spec.metrics["spec_steps"] > 0, "spec path never engaged"


def test_spec_spends_fewer_weight_streams_on_repetition():
    """The roofline claim: tokens per weight stream must clearly beat 1
    once generation turns repetitive (greedy decode of the tiny model
    settles into a loop the n-gram lookup predicts)."""
    eng = _engine(4)
    toks, _fin = eng.generate(
        REPETITIVE, _sp(temperature=0.0, max_tokens=100))
    steps = eng.metrics["spec_steps"] + eng.metrics["decode_steps"]
    assert len(toks) == 100
    assert eng.metrics["spec_accepted"] > 0
    tokens_per_stream = len(toks) / steps
    assert tokens_per_stream > 1.4, (
        f"{tokens_per_stream:.2f} tok/stream — speculation isn't paying")


def test_adaptive_depth_stays_identical_and_accepts():
    """spec_decode_max lets depth follow the accept EMA; output must
    stay token-identical to vanilla while the ledger shows adaptation
    (accepts observed, engine-wide EMA moved, index bounded)."""
    ref, _ = _engine(0).generate(REPETITIVE, _sp(temperature=0.0,
                                                 max_tokens=100))
    eng = _engine(2, spec_decode_max=8)
    toks, _ = eng.generate(REPETITIVE, _sp(temperature=0.0, max_tokens=100))
    assert toks == ref
    assert eng.metrics["spec_accepted"] > 0
    assert eng.metrics["spec_accept_ema"] > 0.0
    assert eng.metrics["spec_index_bytes"] > 0
    # Deep windows engaged: some step accepted more than the base depth
    # would ever allow (depth grew past spec_decode=2).
    assert eng.metrics["spec_proposed"] > 2 * eng.metrics["spec_steps"] or (
        eng.metrics["spec_accepted"] / max(eng.metrics["spec_steps"], 1) > 2
    )


def test_sampled_and_greedy_coexist_per_slot():
    """A sampled request in the batch no longer suspends speculation:
    the greedy slot verifies while the sampled slot rides the EXACT
    chunked sampling path fused into the same dispatch — and sampled
    output stays seed-reproducible bit-for-bit."""
    eng = _engine(4)
    eng.start()
    try:
        sampled = _sp(temperature=0.8, top_p=0.9, max_tokens=10, seed=7)
        h1 = eng.submit(PLAIN, sampled)
        h2 = eng.submit(REPETITIVE, _sp(**GREEDY))
        t1, _ = h1.collect_tokens(timeout=120)
        t2, _ = h2.collect_tokens(timeout=120)
        assert len(t1) == 10 and len(t2) == 24
    finally:
        eng.stop()
    ref = _engine(0)
    t1_ref, _ = ref.generate(PLAIN, sampled)
    assert t1 == t1_ref, "sampled reproducibility broken by spec"
    t2_ref, _ = _engine(0).generate(REPETITIVE, _sp(**GREEDY))
    assert t2 == t2_ref, "greedy stream diverged beside a sampled slot"


def test_spec_respects_stop_tokens_and_budget():
    """A stop id inside an accepted run must end the stream AT the stop
    token — speculation can't overshoot the contract."""
    eng = _engine(4)
    toks_ref, fin_ref = _engine(0).generate(
        REPETITIVE, _sp(temperature=0.0, max_tokens=24, stop_token_ids=(6,)))
    toks, fin = eng.generate(
        REPETITIVE, _sp(temperature=0.0, max_tokens=24, stop_token_ids=(6,)))
    assert toks == toks_ref and fin.finish_reason == fin_ref.finish_reason


def test_spec_sessions_reuse_stays_correct():
    """Cross-turn prefix reuse on top of spec decode: turn 2 reuses
    rows written by verify steps, so its output must match a fresh
    engine's answer for the same conversation."""
    eng = _engine(4)
    h1 = eng.submit(REPETITIVE, _sp(**GREEDY), session_id="sess")
    eng_drive(eng, h1)
    t1, _ = h1.collect_tokens(timeout=1)
    follow = REPETITIVE + t1 + [9]
    h2 = eng.submit(follow, _sp(**GREEDY), session_id="sess")
    eng_drive(eng, h2)
    t2, _ = h2.collect_tokens(timeout=1)
    assert eng.metrics["prefix_reuse_tokens"] > 0
    ref = _engine(0)
    t2_ref, _ = ref.generate(follow, _sp(**GREEDY))
    assert t2 == t2_ref


def test_spec_with_int8_kv_bit_identical():
    """spec-on int8 greedy output == spec-off int8 (the verify window
    quantizes through the same _write_kv seam as every other write)."""
    ref, _ = _engine(0, kv_quant="int8").generate(
        REPETITIVE, _sp(temperature=0.0, max_tokens=32))
    eng = _engine(4, kv_quant="int8")
    toks, _ = eng.generate(REPETITIVE, _sp(temperature=0.0, max_tokens=32))
    assert toks == ref
    assert eng.metrics["spec_steps"] > 0


def test_spec_with_interleave_bit_identical():
    """The verify window rides the fused mixed dispatches: a greedy slot
    keeps speculating while a second prompt's pieces stream, and both
    outputs match the spec-off interleaved engine exactly."""
    outs = {}
    for tag, spec in (("off", 0), ("on", 4)):
        eng = _engine(spec, num_slots=2, prefill_chunk_tokens=8,
                      prefill_buckets=(16, 32))
        h1 = eng.submit(REPETITIVE, _sp(temperature=0.0, max_tokens=40))
        eng.step()
        eng.step()
        h2 = eng.submit(  # long prompt arrives while decode is live
            list(range(60, 90)), _sp(temperature=0.0, max_tokens=8))
        while eng.step():
            pass
        outs[tag] = (
            h1.collect_tokens(timeout=60)[0],
            h2.collect_tokens(timeout=60)[0],
        )
        if spec:
            assert eng.metrics["spec_steps"] > 0, "spec never engaged"
            assert eng.metrics["mixed_steps"] > 0, "interleave never engaged"
    assert outs["off"] == outs["on"]


def test_spec_coexists_with_grammar_slot():
    """A grammar-constrained greedy slot speculates: the acceptance
    oracle is the device-masked argmax, so constrained output is
    token-identical to the non-spec masked path, every emitted token is
    admissible under the host FSM walk (the post-hoc validator never
    fires), and the unconstrained slot is unaffected."""
    import json

    import jsonschema

    pytest.importorskip("jax")
    from omnia_tpu.engine.grammar import compile_json_schema
    from omnia_tpu.engine.tokenizer import ByteTokenizer

    tok = ByteTokenizer()
    schema = {"type": "object",
              "properties": {"a": {"type": "integer"},
                             "ok": {"type": "boolean"}},
              "required": ["a", "ok"]}
    g = compile_json_schema(schema, tok)
    over = dict(num_slots=2, grammar=True, grammar_max_states=512)
    sp_g = _sp(temperature=0.0, max_tokens=100, stop_token_ids=(0,))

    ref = _engine(0, **over)
    hg = ref.submit(tok.encode("make json"), sp_g, grammar=g)
    eng_drive(ref, hg)
    toks_ref, _ = hg.collect_tokens(timeout=1)

    eng = _engine(4, **over)
    hg = eng.submit(tok.encode("make json"), sp_g, grammar=g)
    hf = eng.submit(REPETITIVE, _sp(temperature=0.0, max_tokens=60))
    eng_drive(eng, hf)
    eng_drive(eng, hg)
    toks_f, _ = hf.collect_tokens(timeout=1)
    toks_g, fin_g = hg.collect_tokens(timeout=1)

    assert eng.metrics["spec_steps"] > 0, "grammar slot suspended spec"
    assert toks_g == toks_ref, "spec changed constrained greedy output"
    payload = [t for t in toks_g if t != 0]
    jsonschema.validate(json.loads(tok.decode(payload)), schema)
    view = g.view(eng.model_cfg.vocab_size, (0,))
    s = view.start
    for t in toks_g:
        assert view.allowed(s)[t], (s, t)
        s = view.advance(s, t)
    toks_f_ref, _ = _engine(0).generate(
        REPETITIVE, _sp(temperature=0.0, max_tokens=60))
    assert toks_f == toks_f_ref, "unconstrained slot diverged"


def test_mid_stream_deadline_and_cancel_keep_exact_ledgers():
    """A deadline or cancel landing between verify steps finishes the
    slot with its exact partial books: streamed tokens ==
    num_generated_tokens, and every submit reconciles to one finish."""
    eng = _engine(4)
    now = [1000.0]
    eng.clock = lambda: now[0]
    h = eng.submit(REPETITIVE, _sp(temperature=0.0, max_tokens=200),
                   deadline_s=50.0)
    for _ in range(6):
        eng.step()
    now[0] += 100.0  # deadline passes mid-generation
    eng_drive(eng, h)
    toks, fin = h.collect_tokens(timeout=1)
    assert fin.finish_reason.value == "deadline"
    assert fin.num_generated_tokens == len(toks) > 0
    assert eng.metrics["deadline_exceeded"] == 1

    h2 = eng.submit(REPETITIVE, _sp(temperature=0.0, max_tokens=200))
    for _ in range(6):
        eng.step()
    h2.cancel()
    eng_drive(eng, h2)
    toks2, fin2 = h2.collect_tokens(timeout=1)
    assert fin2.finish_reason.value == "cancelled"
    assert eng.metrics["requests_submitted"] == 2
    assert eng.metrics["requests_finished"] == 2
    assert eng.metrics["tokens_generated"] == len(toks) + len(toks2)


def test_spec_verify_flight_events():
    """Verify steps are flight-recorder-visible: spec_verify events
    carry per-step proposed/accepted counts and the dispatch-vs-sync
    wall split."""
    eng = _engine(4, flight_events=256)
    eng.generate(REPETITIVE, _sp(temperature=0.0, max_tokens=48))
    evs = eng._flight.events("spec_verify")
    assert len(evs) == eng.metrics["spec_steps"] > 0
    total_prop = sum(e.attrs["proposed"] for e in evs)
    total_acc = sum(e.attrs["accepted"] for e in evs)
    assert total_prop == eng.metrics["spec_proposed"]
    assert total_acc == eng.metrics["spec_accepted"]
    assert all(e.attrs["dispatch_s"] >= 0 and e.attrs["sync_s"] >= 0
               and e.attrs["slots"] >= 1 for e in evs)


def test_spec_verify_event_kind_is_registered():
    """The closed EVENTS vocabulary includes the new kind (jax-free)."""
    from omnia_tpu.engine.flight import EVENTS

    assert "spec_verify" in EVENTS


def test_spec_knobs_off_are_true_noop():
    """KNOB_GUARDS target: spec_decode=0 must keep a byte-identical
    lowered decode program and ZERO spec state regardless of the (dead)
    spec_decode_max / spec_gate_window values."""
    pytest.importorskip("jax")
    eng = _engine(0)
    eng2 = _engine(0, spec_decode_max=13, spec_gate_window=7)
    for e in (eng, eng2):
        assert e._verify_fn is None and e._verify_decode_fn is None
        assert e._mixed_spec_fns == {} and e._mixed_spec_sample_fns == {}
        assert e._spec_gate is None
        assert not e._spec_step()
        assert e.cfg.spec_window() == 0
        for key in ("spec_steps", "spec_proposed", "spec_accepted",
                    "spec_gate_state", "spec_index_bytes"):
            assert e.metrics[key] == 0, (key, e.metrics[key])
        assert e.metrics["spec_accept_ema"] == 0.0
        assert all(s.spec_index is None for s in e._slots)

    def lowered(e):
        return e._decode_fn_single.lower(
            e.params, e._ck, e._cv, e._tokens, e._positions, e._active,
            e._budget, e._stop_ids, e._key_data, e._temp, e._top_p,
            e._top_k,
        ).as_text()

    assert lowered(eng) == lowered(eng2)


def test_reprobe_cooldown_advances_once_per_step():
    """The up-to-two plan calls one scheduler step makes share a depths
    memo: a collapsed slot's re-probe cooldown must advance exactly
    once per step, never be burned by a discarded engage-probe plan."""
    eng = _engine(2, spec_decode_max=4)
    h = eng.submit(REPETITIVE, _sp(temperature=0.0, max_tokens=30))
    eng.step()  # placement: the slot is live with its first token out
    slot = next(s for s in eng._slots if s.active)
    slot.spec_k, slot.spec_cool = 0, 0
    depths: dict = {}
    eng._spec_plan(depths=depths)
    eng._spec_plan(depths=depths)
    assert slot.spec_cool == 1, "cooldown advanced per plan, not per step"
    # And the re-probe actually fires once the cadence elapses: the
    # probe depth (1) is granted and the cooldown resets — whether the
    # lookup then hits is the traffic's business, not the controller's.
    slot.spec_cool = sd._RETRY_STEPS - 1
    assert eng._slot_depth(slot) == 1
    assert slot.spec_cool == 0
    h.cancel()
    while eng.step():
        pass


def test_spec_gate_disable_is_observable_on_engine():
    """A configured gate surfaces its state in metrics; under an
    injected logical clock (lockstep) the gate is skipped entirely —
    speculation stays permitted and the state stays 0."""
    eng = _engine(4, spec_gate_window=4)
    eng.generate(REPETITIVE, _sp(temperature=0.0, max_tokens=60))
    assert eng.metrics["spec_gate_state"] in (0, 1, 2)
    assert eng._spec_gate is not None

    lk = _engine(4, spec_gate_window=4)
    lk.clock = lambda: 123.0  # injected clock: gate must never build
    lk.generate(REPETITIVE, _sp(temperature=0.0, max_tokens=30))
    assert lk._spec_gate is None
    assert lk.metrics["spec_gate_state"] == 0
    assert lk.metrics["spec_steps"] > 0


def test_spec_config_validation_on_engine():
    pytest.importorskip("jax")
    from omnia_tpu.engine import EngineConfig, InferenceEngine
    from omnia_tpu.models import get_config

    with pytest.raises(ValueError, match="spec"):
        InferenceEngine(
            get_config("test-tiny"),
            EngineConfig(num_slots=2, max_seq=64, prefill_buckets=(4,),
                         dtype="float32", spec_decode=8),
        )
