"""Image and inference roles are honest (VERDICT r3 #4): a declared
role now has a working path, enforced end-to-end — image generation
lands real PNGs in the media store with the storage_ref in the tool
reply, and inference.generate serves raw completions from the declared
inference-role provider (reference agentruntime_types.go:387-414,
internal/media/builder.go)."""

from __future__ import annotations

import base64
import json
import threading
import zlib
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from omnia_tpu.runtime.images import (
    HttpImageGen,
    ProceduralImageGen,
    decode_png_size,
    encode_png,
)

PACK = {"name": "img-agent", "version": "1.0.0",
        "prompts": {"system": "You are terse."},
        "sampling": {"temperature": 0.0, "max_tokens": 256}}


def _valid_png(png: bytes) -> tuple[int, int]:
    """Structural validity: signature, header dims, decompressable IDAT."""
    w, h = decode_png_size(png)
    idat_start = png.index(b"IDAT") + 4
    idat_len = int.from_bytes(png[idat_start - 8:idat_start - 4], "big")
    raw = zlib.decompress(png[idat_start:idat_start + idat_len])
    assert len(raw) == h * (1 + w * 3)  # filter byte + RGB rows
    return w, h


def test_procedural_generates_real_deterministic_pngs():
    gen = ProceduralImageGen()
    png1, ctype = gen.generate("a red fox", size=64)
    assert ctype == "image/png"
    assert _valid_png(png1) == (64, 64)
    # Deterministic per prompt; distinct across prompts.
    png1b, _ = ProceduralImageGen().generate("a red fox", size=64)
    png2, _ = gen.generate("a blue whale", size=64)
    assert png1 == png1b
    assert png1 != png2


def test_encode_png_roundtrip_shape():
    import numpy as np

    arr = np.arange(4 * 3 * 3, dtype=np.uint8).reshape(4, 3, 3)
    png = encode_png(arr)
    assert _valid_png(png) == (3, 4)


def test_openai_images_wire_shape():
    seen = []
    canned = base64.b64encode(b"png-bytes-here").decode()

    class Handler(BaseHTTPRequestHandler):
        def do_POST(self):
            body = self.rfile.read(int(self.headers.get("Content-Length") or 0))
            seen.append({"path": self.path,
                         "auth": self.headers.get("Authorization"),
                         "body": json.loads(body)})
            out = json.dumps({"data": [{"b64_json": canned}]}).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(out)))
            self.end_headers()
            self.wfile.write(out)

        def log_message(self, *a):
            pass

    httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        base = f"http://127.0.0.1:{httpd.server_address[1]}"
        gen = HttpImageGen({"base_url": base, "api_key": "ik",
                            "image_model": "gpt-image-1"})
        data, ctype = gen.generate("sunset", size=512)
        assert data == b"png-bytes-here" and ctype == "image/png"
        req = seen[-1]
        assert req["path"] == "/v1/images/generations"
        assert req["auth"] == "Bearer ik"
        assert req["body"] == {"model": "gpt-image-1", "prompt": "sunset",
                               "n": 1, "size": "512x512"}
    finally:
        httpd.shutdown()
        httpd.server_close()


def test_image_role_serves_generate_image_tool(tmp_path):
    """Declared image-role provider + media store ⇒ the model can call
    generate_image and the reply's storage_ref resolves to a real PNG."""
    from omnia_tpu.media import LocalMediaStore
    from omnia_tpu.runtime.packs import load_pack
    from omnia_tpu.runtime.providers import ProviderRegistry, ProviderSpec
    from omnia_tpu.runtime.server import RuntimeServer

    registry = ProviderRegistry()
    registry.register(ProviderSpec(
        name="main", type="mock",
        options={"scenarios": [
            # Once the tool result (carrying storage_ref) is in context,
            # the mock answers normally instead of re-calling the tool.
            {"pattern": "storage_ref", "reply": "done drawing"},
            {"pattern": "draw",
             "reply": '<tool_call>{"name": "generate_image", '
                      '"arguments": {"prompt": "a fox", "size": 32}}'
                      "</tool_call>"},
            {"pattern": ".", "reply": "ok"},
        ]}))
    registry.register(ProviderSpec(name="artist", type="procedural",
                                   role="image", options={"size": 32}))
    media = LocalMediaStore(str(tmp_path))
    runtime = RuntimeServer(pack=load_pack(PACK), providers=registry,
                            provider_name="main", media_store=media)
    port = runtime.serve("localhost:0")
    try:
        from omnia_tpu.runtime.client import RuntimeClient

        client = RuntimeClient(f"127.0.0.1:{port}")
        stream = client.open_stream("img-sess")
        tool_payloads = []
        final = None
        for msg in stream.turn("draw me a fox"):
            if msg.type == "tool_call":
                tool_payloads.append(msg)
            if msg.type in ("done", "error"):
                final = msg
                break
        stream.close()
        client.close()
        assert final is not None and final.type == "done", final
    finally:
        runtime.shutdown()
    # The generated ref resolves from the media store to a valid PNG.
    refs = [f for f in (tmp_path.rglob("*")) if f.is_file()]
    assert refs, "no media stored by generate_image"
    png = refs[0].read_bytes()
    assert _valid_png(png) == (32, 32)


def test_inference_role_serves_raw_generate():
    """inference.generate runs a raw completion on the inference-role
    provider — no pack templating — and errors honestly without one."""
    from omnia_tpu.runtime.packs import load_pack
    from omnia_tpu.runtime.providers import ProviderRegistry, ProviderSpec
    from omnia_tpu.runtime.server import RuntimeServer
    from omnia_tpu.runtime import contract as c

    registry = ProviderRegistry()
    registry.register(ProviderSpec(
        name="main", type="mock",
        options={"scenarios": [{"pattern": ".", "reply": "chat"}]}))
    registry.register(ProviderSpec(
        name="raw", type="mock", role="inference",
        options={"scenarios": [{"pattern": ".", "reply": "raw completion"}]}))
    runtime = RuntimeServer(pack=load_pack(PACK), providers=registry,
                            provider_name="main")
    resp = runtime.invoke(
        c.InvokeRequest(name="inference.generate",
                        input={"prompt": "2+2=", "max_tokens": 64}),
        None)
    assert resp.error_code is None or resp.error_code == "", resp
    assert resp.output["text"] == "raw completion"
    assert resp.usage.completion_tokens > 0
    # Input validation + honest absence.
    bad = runtime.invoke(
        c.InvokeRequest(name="inference.generate", input={}), None)
    assert bad.error_code == "bad_input"
    registry2 = ProviderRegistry()
    registry2.register(ProviderSpec(
        name="main", type="mock",
        options={"scenarios": [{"pattern": ".", "reply": "x"}]}))
    runtime2 = RuntimeServer(pack=load_pack(PACK), providers=registry2,
                             provider_name="main")
    none = runtime2.invoke(
        c.InvokeRequest(name="inference.generate",
                        input={"prompt": "p"}), None)
    assert none.error_code == "not_found"


def test_admission_accepts_working_image_inference_roles():
    """Role ⇒ type table: declared roles validate only with types that
    have a working backend; nonsense pairs are rejected."""
    from omnia_tpu.operator.resources import Resource
    from omnia_tpu.operator.validation import ValidationError, validate

    ok = Resource(kind="Provider", name="img",
                  spec={"type": "procedural", "role": "image"})
    validate(ok)
    ok2 = Resource(kind="Provider", name="inf",
                   spec={"type": "tpu", "role": "inference",
                         "model": "test-tiny"})
    validate(ok2)
    with pytest.raises(ValidationError, match="does not serve role"):
        validate(Resource(kind="Provider", name="bad",
                          spec={"type": "tone", "role": "image"}))
