"""Cold-start suite (ISSUE 13): tracker/manifest units, warmup-knob
guards, parallel-vs-serial warmup equivalence, staged readiness.

Module layout follows tests/test_spec_decode.py: everything importable
at module top is jax-free (ColdStartTracker, WarmupManifest, the mock
parity layer, the bench phase heartbeat, the flight init events), so
the CI analysis job runs that subset under its poisoned jax stub; the
engine-backed equivalence battery importorskips jax and runs in tier-1.
"""

from __future__ import annotations

import json
import os

import pytest

from omnia_tpu.engine.coldstart import (
    PHASE_CODES,
    PHASES,
    ColdStartTracker,
    WarmupManifest,
    manifest_bookkeeping,
    manifest_dir,
)

pytestmark = pytest.mark.coldstart

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# Tracker (jax-free)
# ---------------------------------------------------------------------------


class TestColdStartTracker:
    def test_phase_codes_cover_phases_in_order(self):
        assert list(PHASE_CODES) == list(PHASES)
        assert [PHASE_CODES[p] for p in PHASES] == list(range(len(PHASES)))

    def test_phase_spans_and_current_phase(self):
        t = [0.0]
        cs = ColdStartTracker(clock=lambda: t[0])
        assert cs.current_phase() == "idle"
        cs.begin_phase("backend_init")
        t[0] = 2.0
        assert cs.current_phase() == "backend_init"
        assert cs.end_phase("backend_init") == 2.0
        # Between phases: latest FINISHED phase, never back to idle.
        assert cs.current_phase() == "backend_init"
        cs.begin_phase("warmup_compile")
        t[0] = 5.0
        snap = cs.snapshot()
        assert snap["phase"] == "warmup_compile"
        assert snap["phases_s"] == {"backend_init": 2.0, "warmup_compile": 3.0}
        cs.end_phase("warmup_compile")
        cs.mark_ready()
        assert cs.current_phase() == "ready"
        assert cs.snapshot()["phase_code"] == PHASE_CODES["ready"]

    def test_overlapping_phases_report_latest_begun(self):
        """weights_load and warmup_compile legitimately overlap (the
        streaming/compile overlap is the whole point) — current phase is
        the most recently BEGUN unfinished one."""
        t = [0.0]
        cs = ColdStartTracker(clock=lambda: t[0])
        cs.begin_phase("weights_load")
        t[0] = 1.0
        cs.begin_phase("warmup_compile")
        assert cs.current_phase() == "warmup_compile"
        t[0] = 4.0
        cs.end_phase("warmup_compile")
        assert cs.current_phase() == "weights_load"
        assert cs.end_phase("weights_load") == 4.0

    def test_end_without_begin_is_zero(self):
        cs = ColdStartTracker()
        assert cs.end_phase("backend_init") == 0.0
        assert cs.current_phase() == "idle"

    def test_unknown_phase_rejected(self):
        with pytest.raises(ValueError):
            ColdStartTracker().begin_phase("nope")

    def test_weights_progress_is_monotone(self):
        cs = ColdStartTracker()
        cs.note_weights(100, 1000)
        cs.note_weights(50, 1000)  # a racing late callback can't regress
        snap = cs.snapshot()
        assert snap["weights_bytes_loaded"] == 100
        assert snap["weights_bytes_total"] == 1000

    def test_program_counter(self):
        cs = ColdStartTracker()
        cs.set_programs_total(3)
        assert cs.note_program() == 1
        assert cs.note_program(2) == 3
        snap = cs.snapshot()
        assert (snap["programs_done"], snap["programs_total"]) == (3, 3)

    def test_rewarmup_never_reports_done_over_total(self):
        """A second warmup on the same engine (sessions=False then a
        full warmup is a public sequence) re-declares its total, resets
        the done counter, and un-readies the phase — probes must never
        read 'programs 4/3' or a stale 'ready'."""
        cs = ColdStartTracker()
        cs.set_programs_total(2)
        cs.note_program(2)
        cs.mark_ready()
        cs.begin_phase("warmup_compile")
        assert cs.current_phase() == "warmup_compile"  # not stale "ready"
        cs.set_programs_total(3)
        assert cs.note_program() == 1
        snap = cs.snapshot()
        assert (snap["programs_done"], snap["programs_total"]) == (1, 3)


# ---------------------------------------------------------------------------
# Manifest (jax-free)
# ---------------------------------------------------------------------------


class TestWarmupManifest:
    def test_key_is_stable_and_content_sensitive(self):
        a = {"model": {"layers": 2}, "engine": {"max_seq": 128}}
        assert WarmupManifest.manifest_key(a) == WarmupManifest.manifest_key(
            {"engine": {"max_seq": 128}, "model": {"layers": 2}}
        )
        b = {"model": {"layers": 3}, "engine": {"max_seq": 128}}
        assert WarmupManifest.manifest_key(a) != WarmupManifest.manifest_key(b)

    def test_store_load_roundtrip_and_merge(self, tmp_path):
        d = str(tmp_path)
        assert WarmupManifest.load(d, "k") is None
        assert WarmupManifest.store(d, "k", ["decode:chunk8", "prefill:bucket64"])
        assert WarmupManifest.load(d, "k") == [
            "decode:chunk8", "prefill:bucket64",
        ]
        # sessions=False warmups must not erase a full warmup's families.
        assert WarmupManifest.store(d, "k", ["decode:chunk8", "session:rows64"])
        assert WarmupManifest.load(d, "k") == [
            "decode:chunk8", "prefill:bucket64", "session:rows64",
        ]

    def test_unwritable_dir_degrades_without_raising(self, tmp_path):
        # A regular file where the manifest dir should be: every write
        # attempt is an OSError (works even when the suite runs as root,
        # where a chmod-0o500 dir would still be writable).
        blocked = tmp_path / "not_a_dir"
        blocked.write_text("x")
        assert WarmupManifest.store(str(blocked), "k", ["a:b"]) is False

    def test_corrupt_manifest_reads_as_absent(self, tmp_path):
        path = WarmupManifest._path(str(tmp_path), "k")
        with open(path, "w") as f:
            f.write("{not json")
        assert WarmupManifest.load(str(tmp_path), "k") is None

    def test_bookkeeping_hits_and_misses(self, tmp_path):
        d = str(tmp_path)
        cs = ColdStartTracker()
        hits, misses = manifest_bookkeeping(d, "k", ["a:1", "b:2"], cs)
        assert (hits, misses) == (0, 2)
        cs2 = ColdStartTracker()
        hits, misses = manifest_bookkeeping(d, "k", ["a:1", "b:2", "c:3"], cs2)
        assert (hits, misses) == (2, 1)
        assert cs2.snapshot()["manifest_hits"] == 2
        # No directory: in-memory cold accounting, nothing persisted.
        hits, misses = manifest_bookkeeping(None, "k", ["a:1"], ColdStartTracker())
        assert (hits, misses) == (0, 1)

    def test_manifest_dir_env_override(self, tmp_path, monkeypatch):
        monkeypatch.setenv("OMNIA_WARMUP_MANIFEST_DIR", str(tmp_path))
        assert manifest_dir() == str(tmp_path)


# ---------------------------------------------------------------------------
# compile_cache fallback (jax-free satellite)
# ---------------------------------------------------------------------------


class TestCompileCacheDir:
    def test_env_override_wins(self, monkeypatch):
        from omnia_tpu.utils import compile_cache

        monkeypatch.setenv("OMNIA_JAX_CACHE_DIR", "/somewhere/persistent")
        assert compile_cache.default_cache_dir() == "/somewhere/persistent"

    def test_unwritable_default_falls_back_to_tmpdir(self, monkeypatch, caplog):
        """The dot-dir next to the package is unwritable in read-only
        container images — the cache must fall back to a tmpdir with a
        logged warning instead of failing enablement silently."""
        import logging

        from omnia_tpu.utils import compile_cache

        monkeypatch.delenv("OMNIA_JAX_CACHE_DIR", raising=False)
        monkeypatch.setattr(compile_cache, "_writable_dir", lambda p: False)
        with caplog.at_level(logging.WARNING, logger=compile_cache.__name__):
            d = compile_cache.default_cache_dir()
        assert d.startswith(__import__("tempfile").gettempdir())
        assert any("unwritable" in r.message for r in caplog.records)

    def test_writable_default_keeps_repo_dot_dir(self, monkeypatch):
        from omnia_tpu.utils import compile_cache

        monkeypatch.delenv("OMNIA_JAX_CACHE_DIR", raising=False)
        monkeypatch.setattr(compile_cache, "_writable_dir", lambda p: True)
        assert compile_cache.default_cache_dir().endswith(".jax_cache")


# ---------------------------------------------------------------------------
# Flight init-phase events (jax-free)
# ---------------------------------------------------------------------------


class TestInitPhaseFlightEvents:
    def test_init_events_are_in_the_closed_vocabulary(self):
        from omnia_tpu.engine.flight import EVENTS, INIT_EVENTS

        assert INIT_EVENTS <= EVENTS
        assert INIT_EVENTS == {
            "backend_init", "weights_load", "warmup_compile",
            "warmup_restore",
        }

    def test_note_init_phase_rejects_non_init_kinds(self):
        from omnia_tpu.engine.flight import FlightRecorder

        rec = FlightRecorder(16)
        with pytest.raises(AssertionError):
            rec.note_init_phase("decode_chunk", {})

    def test_chrome_export_renders_init_durations(self):
        """Init events carry `seconds`; the Chrome export must render
        them as duration rows on the engine-steps track AND keep every
        computed start non-negative (they are the longest durations in a
        cold-start dump, recorded at phase END)."""
        from omnia_tpu.engine.flight import FlightRecorder, to_chrome_trace

        rec = FlightRecorder(64)
        rec.note_init_phase("backend_init", {"backend": "cpu", "seconds": 1.5})
        rec.note_init_phase("weights_load", {"bytes": 123, "seconds": 2.0})
        rec.note_init_phase(
            "warmup_compile", {"programs": 7, "threads": 2, "seconds": 4.0}
        )
        rec.note_init_phase("warmup_restore", {"seconds": 0.25})
        doc = to_chrome_trace(rec.events())
        rows = {
            e["name"]: e for e in doc["traceEvents"] if e.get("ph") == "X"
        }
        assert set(rows) == {
            "backend_init", "weights_load", "warmup_compile",
            "warmup_restore",
        }
        assert rows["warmup_compile"]["dur"] == 4.0 * 1e6
        assert rows["warmup_compile"]["args"]["programs"] == 7
        for e in doc["traceEvents"]:
            if "ts" in e:
                assert e["ts"] >= 0.0, e


# ---------------------------------------------------------------------------
# Bench per-phase heartbeat (jax-free satellite; parent-side code only)
# ---------------------------------------------------------------------------


class TestBenchPhaseHeartbeat:
    def test_phase_marker_folding(self):
        import bench

        assert bench._phase_of("noise", "backend_init") == "backend_init"
        assert bench._phase_of(
            f"[bench +  1.0s] {bench._PHASE_MARKER} weights_load",
            "backend_init",
        ) == "weights_load"
        assert bench._phase_of(
            "[bench +  2.0s] backend up: tpu (v5e)", "backend_init"
        ) == "backend_up"
        # A malformed marker line keeps the previous phase.
        assert bench._phase_of(bench._PHASE_MARKER, "compile") == "compile"

    def test_child_emits_parseable_markers(self):
        """_mark_phase's output must fold back through _phase_of — the
        parent watchdog's stuck-phase attribution depends on it."""
        import bench

        with open(os.path.join(REPO, "bench.py")) as f:
            src = f.read()
        # The child marks every cold-start phase the runbook names.
        for phase in ("backend_init", "weights_load", "warmup_compile", "ready"):
            assert f'_mark_phase("{phase}")' in src, phase

    def test_bench_has_coldstart_scenario(self):
        import bench

        assert callable(bench._bench_coldstart)

    def test_kill_reason_names_stuck_phase(self):
        """The watchdog kill reasons interpolate the last seen phase —
        that string lands in aux.tpu_attempt_trace."""
        with open(os.path.join(REPO, "bench.py")) as f:
            src = f.read()
        assert src.count("stuck phase:") >= 2  # hard deadline + init stall


# ---------------------------------------------------------------------------
# Mock parity (jax-free)
# ---------------------------------------------------------------------------


class TestMockColdStartParity:
    def test_mock_warmup_books_ledger_and_manifest(self, tmp_path, monkeypatch):
        from omnia_tpu.engine.mock import MockEngine

        monkeypatch.setenv("OMNIA_WARMUP_MANIFEST_DIR", str(tmp_path))
        m = MockEngine()
        assert m.metrics["warmup_phase"] == 0
        m.warmup()
        assert m.metrics["warmup_phase"] == PHASE_CODES["ready"]
        assert m.metrics["warmup_programs_total"] == 1
        assert m.metrics["warmup_programs_done"] == 1
        assert m.metrics["warmup_manifest_misses"] == 1
        # Second mock, same knobs: the REAL manifest machinery reports
        # the restart as a hit.
        m2 = MockEngine()
        m2.warmup()
        assert m2.metrics["warmup_manifest_hits"] == 1
        assert m2.metrics["warmup_manifest_misses"] == 0
        # Different knobs → different key → cold. (prefill_chunk_tokens
        # keeps this constructible under the poisoned-jax CI stub.)
        m3 = MockEngine(prefill_chunk_tokens=7)
        m3.warmup()
        assert m3.metrics["warmup_manifest_hits"] == 0

    def test_mock_warmup_threads_zero_is_true_noop(self, tmp_path, monkeypatch):
        """warmup_threads on the mock is ledger-only: scripted output is
        EXACTLY unchanged across values, and 0 (default) leaves the same
        state as not passing the knob at all."""
        from omnia_tpu.engine.mock import MockEngine, Scenario
        from omnia_tpu.engine.types import SamplingParams

        monkeypatch.setenv("OMNIA_WARMUP_MANIFEST_DIR", str(tmp_path))
        sp = SamplingParams(max_tokens=32)
        outs = {}
        for threads in (None, 0, 3):
            kwargs = {} if threads is None else {"warmup_threads": threads}
            m = MockEngine([Scenario("hi", "hello-world")], **kwargs)
            m.warmup()
            toks, fin = m.generate(m.tokenizer.encode("hi"), sp)
            outs[threads] = (m.tokenizer.decode(toks), fin.finish_reason.value)
            assert m.warmup_threads == (threads or 0)
        assert outs[None] == outs[0] == outs[3] == ("hello-world", "stop")

    def test_mock_rejects_negative_threads(self):
        from omnia_tpu.engine.mock import MockEngine

        with pytest.raises(ValueError):
            MockEngine(warmup_threads=-1)


# ---------------------------------------------------------------------------
# Operator staged readiness (jax-free: pure helpers + a stubbed probe)
# ---------------------------------------------------------------------------


class TestOperatorStagedReadiness:
    def test_warmup_progress_message(self):
        controller = pytest.importorskip("omnia_tpu.operator.controller")

        msg = controller.warmup_progress_message({
            "phase": "warmup_compile", "programs_done": 12,
            "programs_total": 40, "weights_bytes_loaded": 1_200_000_000,
            "weights_bytes_total": 16_100_000_000,
        })
        assert msg == "phase=warmup_compile, programs 12/40, weights 1.2/16.1 GB"
        assert controller.warmup_progress_message({}) == (
            "phase=unknown (runtime reports no warmup progress)"
        )
        # Partial dicts (no checkpoint → no weight bytes) stay clean.
        assert controller.warmup_progress_message(
            {"phase": "warmup_compile", "programs_total": 0}
        ) == "phase=warmup_compile"

    def test_capability_gate_surfaces_initializing_progress(self, monkeypatch):
        """An initializing runtime must yield (not gated, warming msg) —
        capability absence during warmup is 'not ready', never
        'missing'; a ready runtime keeps the old gate semantics."""
        from types import SimpleNamespace

        controller = pytest.importorskip("omnia_tpu.operator.controller")
        client_mod = pytest.importorskip("omnia_tpu.runtime.client")
        from omnia_tpu.runtime.contract import HealthResponse

        responses = {}

        class FakeClient:
            def __init__(self, addr):
                pass

            def health(self, timeout=None):
                return responses["h"]

            def close(self):
                pass

        monkeypatch.setattr(client_mod, "RuntimeClient", FakeClient)
        fake_self = SimpleNamespace(capability_probe_timeout_s=1.0)
        dep = SimpleNamespace(
            pods=[SimpleNamespace(runtime_port=1)], candidate_pods=[],
            required_capabilities=["text", "streaming"], name="d",
        )
        gate = controller.ControllerManager._capability_gate

        responses["h"] = HealthResponse(
            status="initializing", capabilities=[],
            warmup={"phase": "warmup_compile", "programs_done": 3,
                    "programs_total": 9},
        )
        gated, missing, warming = gate(fake_self, dep)
        assert not gated and missing == []
        assert warming == "phase=warmup_compile, programs 3/9"

        responses["h"] = HealthResponse(status="ok", capabilities=["text"])
        gated, missing, warming = gate(fake_self, dep)
        assert gated and missing == ["streaming"] and warming is None

        responses["h"] = HealthResponse(
            status="ok", capabilities=["text", "streaming"]
        )
        assert gate(fake_self, dep) == (False, [], None)

    def test_health_response_wire_roundtrip_carries_warmup(self):
        from omnia_tpu.runtime.contract import HealthResponse

        h = HealthResponse(status="initializing",
                           warmup={"phase": "weights_load"})
        back = HealthResponse.from_bytes(h.to_bytes())
        assert back.warmup == {"phase": "weights_load"}
        # Legacy wire payloads (no warmup field) stay parseable.
        legacy = dict(json.loads(h.to_bytes()))
        legacy.pop("warmup")
        assert HealthResponse.from_bytes(
            json.dumps(legacy).encode()
        ).warmup == {}


# ---------------------------------------------------------------------------
# Engine-backed battery (skips without jax)
# ---------------------------------------------------------------------------


def _engine(monkeypatch=None, **over):
    jax = pytest.importorskip("jax")  # noqa: F841
    from omnia_tpu.engine import EngineConfig, InferenceEngine
    from omnia_tpu.models import get_config

    base = dict(num_slots=2, max_seq=128, prefill_buckets=(32, 64),
                dtype="float32", max_sessions=4)
    base.update(over)
    return InferenceEngine(get_config("test-tiny"), EngineConfig(**base), seed=3)


def _lowered_decode(eng):
    return eng._decode_fn_single.lower(
        eng.params, eng._ck, eng._cv, eng._tokens, eng._positions,
        eng._active, eng._budget, eng._stop_ids, eng._key_data,
        eng._temp, eng._top_p, eng._top_k,
    ).as_text()


def test_warmup_threads_zero_is_true_noop(tmp_path, monkeypatch):
    """warmup_threads is a host-side compile-concurrency knob: it is
    never read at trace time (byte-identical lowered programs across
    values), 0 builds zero parallel state (no executor, no scratch
    caches — the serial path), and post-warmup engine state is the
    restored pristine allocation either way."""
    pytest.importorskip("jax")
    from omnia_tpu.engine.types import EngineConfig

    monkeypatch.setenv("OMNIA_WARMUP_MANIFEST_DIR", str(tmp_path))
    assert EngineConfig().warmup_threads == 0  # the guarded default
    off = _engine()
    on = _engine(warmup_threads=3)
    assert _lowered_decode(off) == _lowered_decode(on)
    # Serial warmup allocates no scratch states: the only states list it
    # builds wraps the engine's OWN arrays (worker-0 semantics).
    tasks = off._warmup_tasks(sessions=True)
    states = off._run_warmup_serial(tasks[:1])
    assert len(states) == 1
    with pytest.raises(ValueError):
        _engine(warmup_threads=-1)


@pytest.mark.slow
def test_parallel_warmup_is_bit_identical_to_serial(tmp_path, monkeypatch):
    """Same compiled program set, same traced signatures, same restored
    state: a sampled (seeded) generation after parallel warmup matches
    serial warmup token for token, and the task inventories agree."""
    pytest.importorskip("jax")
    from omnia_tpu.engine.types import SamplingParams

    monkeypatch.setenv("OMNIA_WARMUP_MANIFEST_DIR", str(tmp_path))
    sp = SamplingParams(temperature=0.9, top_p=0.9, top_k=20,
                        max_tokens=12, seed=11)
    outs = {}
    inventories = {}
    for threads in (0, 3):
        eng = _engine(warmup_threads=threads, prefix_cache_slots=2,
                      prefill_chunk_tokens=32)
        inventories[threads] = [
            (fam, key) for fam, key, _fn in eng._warmup_tasks(sessions=True)
        ]
        eng.warmup()
        assert eng.metrics["warmup_programs_done"] == (
            eng.metrics["warmup_programs_total"]
        ) == len(inventories[threads])
        toks, fin = eng.generate(list(range(1, 40)), sp)
        outs[threads] = (toks, fin.finish_reason)
    assert inventories[0] == inventories[3]
    assert outs[0] == outs[3]


def test_manifest_keying_and_second_engine_hit(tmp_path, monkeypatch):
    """Second engine in-process with the same config: every program is a
    manifest hit (compiles should be persistent-cache restores on a pod
    restart). Changing model config / bucket set / kv_quant / kv_pages
    produces DISTINCT manifest keys; host-side knobs do not."""
    pytest.importorskip("jax")
    import dataclasses

    from omnia_tpu.engine import EngineConfig, InferenceEngine
    from omnia_tpu.models import get_config

    monkeypatch.setenv("OMNIA_WARMUP_MANIFEST_DIR", str(tmp_path))
    e1 = _engine()
    e1.warmup()
    total = e1.metrics["warmup_programs_total"]
    assert total > 0
    assert e1.metrics["warmup_manifest_misses"] == total

    e2 = _engine()
    assert e2._warmup_manifest_key() == e1._warmup_manifest_key()
    e2.warmup()
    assert e2.metrics["warmup_manifest_hits"] == total
    assert e2.metrics["warmup_manifest_misses"] == 0

    keys = {e1._warmup_manifest_key()}
    for over in (
        dict(prefill_buckets=(32,)),          # bucket set
        dict(kv_quant="int8"),                # KV representation
        dict(kv_pages=8, kv_page_tokens=32),  # paged layout
        dict(max_seq=64),                     # cache shape
    ):
        keys.add(_engine(**over)._warmup_manifest_key())
    assert len(keys) == 5, "every shape-relevant change must re-key"
    # Model config re-keys too.
    mc = dataclasses.replace(get_config("test-tiny"), num_layers=3)
    alt = InferenceEngine(
        mc, EngineConfig(num_slots=2, max_seq=128, prefill_buckets=(32, 64),
                         dtype="float32", max_sessions=4), seed=3,
    )
    assert alt._warmup_manifest_key() not in keys
    # Host-side knobs share the key (a restart that only tunes them
    # still reads its manifest).
    assert _engine(
        warmup_threads=3, flight_events=64, max_queue=8,
    )._warmup_manifest_key() == e1._warmup_manifest_key()


def test_warmup_progress_metrics_and_init_flight_events(tmp_path, monkeypatch):
    """After warmup: phase=ready, done==total, manifest books mirrored;
    the flight ring holds the init-phase events with their durations and
    they survive the Chrome export."""
    pytest.importorskip("jax")
    from omnia_tpu.engine.flight import to_chrome_trace

    monkeypatch.setenv("OMNIA_WARMUP_MANIFEST_DIR", str(tmp_path))
    eng = _engine(flight_events=128)
    eng.warmup()
    m = eng.metrics
    assert m["warmup_phase"] == PHASE_CODES["ready"]
    assert m["warmup_programs_total"] > 0
    assert m["warmup_programs_done"] == m["warmup_programs_total"]
    kinds = [e.kind for e in eng._flight.events()]
    assert kinds.count("backend_init") == 1
    assert kinds.count("warmup_compile") == 1
    assert kinds.count("warmup_restore") == 1
    compile_ev = eng._flight.events("warmup_compile")[0]
    assert compile_ev.attrs["programs"] == m["warmup_programs_total"]
    assert compile_ev.attrs["seconds"] > 0
    assert compile_ev.attrs["threads"] == 0
    doc = to_chrome_trace(eng._flight.events())
    names = {e["name"] for e in doc["traceEvents"] if e.get("ph") == "X"}
    assert {"warmup_compile", "warmup_restore"} <= names

    snap = eng._coldstart.snapshot()
    assert snap["phase"] == "ready"
    assert snap["phases_s"]["warmup_compile"] > 0


def test_checkpoint_loader_streams_with_progress_and_overlap(
    tmp_path, monkeypatch
):
    """The engine accepts a params LOADER: weights stream under the
    weights_load phase with per-tensor byte progress (metrics mirror +
    flight event), the param-free families compile on the overlap
    thread, and generation matches an engine built from the same
    checkpoint's preloaded params."""
    pytest.importorskip("jax")
    pytest.importorskip("safetensors")
    import jax.numpy as jnp

    from omnia_tpu.engine import EngineConfig, InferenceEngine, SamplingParams
    from omnia_tpu.models import checkpoint as ckpt_io
    from omnia_tpu.models import get_config, llama

    monkeypatch.setenv("OMNIA_WARMUP_MANIFEST_DIR", str(tmp_path / "man"))
    cfg = get_config("test-tiny")
    import jax

    params = llama.init_params(cfg, jax.random.key(0), dtype=jnp.float32)
    ckpt = str(tmp_path / "ckpt")
    ckpt_io.save_params(params, cfg, ckpt)

    calls = []

    def loader(progress_cb=None):
        def meter(loaded, total):
            calls.append((loaded, total))
            if progress_cb is not None:
                progress_cb(loaded, total)
        return ckpt_io.load_params(ckpt, cfg, dtype=jnp.float32,
                                   progress_cb=meter)

    ecfg = EngineConfig(num_slots=2, max_seq=128, prefill_buckets=(32, 64),
                        dtype="float32", max_sessions=4)
    eng = InferenceEngine(cfg, ecfg, params=loader, seed=3,)
    assert calls, "loader must stream with per-tensor progress"
    loaded, total = calls[-1]
    assert loaded == total == ckpt_io.expected_param_bytes(cfg, jnp.float32)
    assert eng.metrics["weights_bytes_loaded"] == total
    assert eng.metrics["weights_bytes_total"] == total
    snap = eng._coldstart.snapshot()
    assert "weights_load" in snap["phases_s"]

    ref = InferenceEngine(cfg, ecfg,
                          params=ckpt_io.load_params(ckpt, cfg,
                                                     dtype=jnp.float32),
                          seed=3)
    sp = SamplingParams(temperature=0.0, max_tokens=8)
    assert eng.generate([5, 6, 7], sp)[0] == ref.generate([5, 6, 7], sp)[0]


def test_runtime_forwards_warmup_threads(monkeypatch):
    """Providers forward the knob to tpu AND mock engines (the runtime
    options surface the operator's Provider CR exposes)."""
    pytest.importorskip("jax")
    from omnia_tpu.runtime.providers import ProviderSpec, build_engine

    mock = build_engine(ProviderSpec(
        name="m", type="mock", options={"warmup_threads": 2},
    ))
    assert mock.warmup_threads == 2
    tpu = build_engine(ProviderSpec(
        name="t", type="tpu", model="test-tiny",
        options={"num_slots": 2, "max_seq": 64, "prefill_buckets": [8],
                 "dtype": "float32", "warmup_threads": 3},
    ))
    assert tpu.cfg.warmup_threads == 3
