"""Ring attention (sequence/context parallelism) and MoE dispatch tests.

Ring attention is validated against the dense slot-contiguous GQA reference
on a virtual 8-device CPU mesh; MoE dispatch is validated against the exact
all-expert path at high capacity (where nothing drops).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from omnia_tpu.ops.attention import gqa_attention
from omnia_tpu.ops.moe import moe_dense, moe_dispatch
from omnia_tpu.parallel import make_mesh, ring_attention


def _dense_reference(q, k, v):
    """Full causal attention via the serving GQA kernel: positions 0..T-1."""
    B, T = q.shape[:2]
    q_pos = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (B, T))
    return gqa_attention(q, k, v, q_pos)


@pytest.mark.parametrize("sp,heads,kv_heads", [(4, 4, 2), (8, 4, 4), (2, 8, 2)])
def test_ring_attention_matches_dense(sp, heads, kv_heads):
    B, T, D = 2, 64, 16
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.standard_normal((B, T, heads, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, T, kv_heads, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, T, kv_heads, D)), jnp.float32)

    mesh = make_mesh(dp=1, tp=1, sp=sp)
    out = ring_attention(q, k, v, mesh)
    ref = _dense_reference(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4)


def test_ring_attention_dp_sp_mesh():
    """Ring attention with batch over dp and sequence over sp simultaneously."""
    B, T, H, D = 4, 32, 2, 8
    rng = np.random.default_rng(1)
    q = jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32)

    mesh = make_mesh(dp=2, tp=1, sp=4)
    out = ring_attention(q, k, v, mesh)
    ref = _dense_reference(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=2e-4, atol=2e-4)


def test_ring_attention_jits_and_grads():
    B, T, H, D = 1, 32, 2, 8
    rng = np.random.default_rng(2)
    q = jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, T, H, D)), jnp.float32)
    mesh = make_mesh(dp=1, tp=1, sp=4)

    def loss(q, k, v):
        return ring_attention(q, k, v, mesh).sum()

    g = jax.jit(jax.grad(loss))(q, k, v)
    assert g.shape == q.shape
    assert bool(jnp.all(jnp.isfinite(g)))


def _moe_params(key, d, f, E):
    ks = jax.random.split(key, 4)
    return {
        "router": jax.random.normal(ks[0], (d, E), jnp.float32) * 0.1,
        "wg": jax.random.normal(ks[1], (E, d, f), jnp.float32) * 0.05,
        "wu": jax.random.normal(ks[2], (E, d, f), jnp.float32) * 0.05,
        "wd": jax.random.normal(ks[3], (E, f, d), jnp.float32) * 0.05,
    }


def test_moe_dispatch_matches_dense_at_full_capacity():
    B, T, d, f, E, K = 2, 64, 16, 32, 4, 2
    p = _moe_params(jax.random.key(0), d, f, E)
    h = jax.random.normal(jax.random.key(1), (B, T, d), jnp.float32)
    # capacity_factor = E/K ⇒ capacity = N, nothing can drop ⇒ exact match
    out_d = moe_dispatch(h, p, K, capacity_factor=E / K)
    out_ref = moe_dense(h, p, K)
    np.testing.assert_allclose(np.asarray(out_d), np.asarray(out_ref), rtol=1e-4, atol=1e-4)


def test_moe_dispatch_drops_gracefully_at_low_capacity():
    B, T, d, f, E, K = 1, 32, 8, 16, 4, 2
    p = _moe_params(jax.random.key(2), d, f, E)
    h = jax.random.normal(jax.random.key(3), (B, T, d), jnp.float32)
    out = moe_dispatch(h, p, K, capacity_factor=0.5)
    assert out.shape == h.shape
    assert bool(jnp.all(jnp.isfinite(out)))


def test_moe_dispatch_sharded_over_tp():
    """Expert-parallel execution under jit with experts sharded over tp."""
    from jax.sharding import NamedSharding, PartitionSpec as P

    B, T, d, f, E, K = 2, 64, 16, 32, 8, 2
    mesh = make_mesh(dp=2, tp=4)
    p = _moe_params(jax.random.key(4), d, f, E)
    p_sharded = {
        "router": jax.device_put(p["router"], NamedSharding(mesh, P(None, None))),
        "wg": jax.device_put(p["wg"], NamedSharding(mesh, P("tp", None, None))),
        "wu": jax.device_put(p["wu"], NamedSharding(mesh, P("tp", None, None))),
        "wd": jax.device_put(p["wd"], NamedSharding(mesh, P("tp", None, None))),
    }
    h = jax.device_put(
        jax.random.normal(jax.random.key(5), (B, T, d), jnp.float32),
        NamedSharding(mesh, P("dp", None, None)),
    )
    out = jax.jit(lambda h, p: moe_dispatch(h, p, K))(h, p_sharded)
    ref = moe_dispatch(h, p, K)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-4, atol=1e-4)
