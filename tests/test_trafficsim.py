"""Traffic-simulator suite (ISSUE 14): seeded offered-trace determinism,
open-loop coordinated-omission guard, exact ledger reconciliation under
counted chaos, cancel/deadline partial-count exactness, flight-sourced
latency percentiles, the VU-pool backlog gate, SLO threshold gating, and
mock-vs-real report schema parity.

Module top is jax-free by design: everything except the real-engine
parity battery and the duplex driver runs under the CI analysis job's
poisoned jax stub (``pytest -m sim --noconftest``).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time

import pytest

from omnia_tpu.engine.coordinator import EngineCoordinator
from omnia_tpu.engine.faults import FaultPlan
from omnia_tpu.engine.mock import MockEngine, Scenario
from omnia_tpu.engine.types import FinishReason, SamplingParams
from omnia_tpu.evals.aggregator import Aggregator
from omnia_tpu.evals.defs import Threshold
from omnia_tpu.evals.trafficsim import (
    ArrivalSpec,
    ScenarioClass,
    SLOTarget,
    TrafficPlan,
    TrafficSimulator,
    arrival_times,
    default_classes,
    generate_offered,
    mock_scenarios,
    offered_digest,
)
from omnia_tpu.evals.trafficsim.arrivals import interval_counts
from omnia_tpu.evals.vu_pool import LoadProfile, VUPool

pytestmark = pytest.mark.sim

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

TOOL_SCHEMA = {
    "type": "object",
    "properties": {"tool": {"type": "string", "enum": ["search", "lookup"]},
                   "k": {"type": "integer"}},
    "required": ["tool", "k"],
}


def _test_classes(deadline: bool = True, cancel: bool = True,
                  grammar: bool = True, multiturn: bool = True):
    """A fast, controlled mix for hermetic runs: every special scenario
    shape (grammar turns, mid-stream cancels, deadline turns, session
    reuse) in a sub-second plan."""
    out = [ScenarioClass(
        name="chat_bursty",
        arrival=ArrivalSpec(profile="mmpp", rate_rps=18.0,
                            dwell_s=0.25, burst_dwell_s=0.1),
        prompt_tokens=(16, 32), max_tokens=24,
        slo=SLOTarget(ttft_ms=400.0),
    )]
    if grammar:
        out.append(ScenarioClass(
            name="grammar_tool",
            arrival=ArrivalSpec(profile="poisson", rate_rps=4.0),
            prompt_tokens=(20, 32), max_tokens=48,
            grammar_schema_json=json.dumps(TOOL_SCHEMA),
            stop_token_ids=(0,),
            slo=SLOTarget(ttft_ms=600.0),
        ))
    if cancel:
        out.append(ScenarioClass(
            name="cancel_midstream",
            arrival=ArrivalSpec(profile="poisson", rate_rps=4.0),
            prompt_tokens=(16, 24), max_tokens=96,
            cancel_after_tokens=4,
            slo=SLOTarget(ttft_ms=500.0),
        ))
    if deadline:
        # ttft sleep (80 ms) > TTL (40 ms): deterministic DEADLINE with
        # zero tokens at the worker, never a pre-route reap.
        out.append(ScenarioClass(
            name="deadline_short",
            arrival=ArrivalSpec(profile="poisson", rate_rps=4.0),
            prompt_tokens=(12, 20), max_tokens=16,
            deadline_s=0.04,
            slo=SLOTarget(ttft_ms=300.0, min_attainment=0.0),
        ))
    if multiturn:
        out.append(ScenarioClass(
            name="session_multiturn",
            arrival=ArrivalSpec(profile="poisson", rate_rps=6.0),
            prompt_tokens=(12, 20), max_tokens=16, turns=2,
            slo=SLOTarget(ttft_ms=700.0),
        ))
    return tuple(out)


def _test_mock_scenarios():
    return [
        Scenario(pattern=r"sim chat_bursty ", reply="b" * 24,
                 ttft_s=0.002, delay_per_token_s=0.0005),
        Scenario(pattern=r"sim grammar_tool ", reply="g" * 40,
                 ttft_s=0.002, delay_per_token_s=0.0005),
        Scenario(pattern=r"sim cancel_midstream ", reply="c" * 96,
                 ttft_s=0.002, delay_per_token_s=0.002),
        Scenario(pattern=r"sim deadline_short ", reply="d" * 16,
                 ttft_s=0.08, delay_per_token_s=0.0005),
        Scenario(pattern=r"sim session_multiturn ", reply="s" * 16,
                 ttft_s=0.002, delay_per_token_s=0.0005),
        Scenario(pattern=r".", reply="fallback", ttft_s=0.002),
    ]


def _fleet(n=2, fault_plan=None, flight_events=2048, max_queue=0,
           max_worker_queue=0):
    workers = [
        MockEngine(_test_mock_scenarios(), name=f"w{i}",
                   flight_events=flight_events, fault_plan=fault_plan,
                   max_queue=max_queue, prefill_chunk_tokens=16)
        for i in range(n)
    ]
    coord = EngineCoordinator(workers, max_worker_queue=max_worker_queue,
                              flight_events=512)
    return coord, workers


def _ident(report, name):
    for i in report["ledger"]["identities"]:
        if i["name"].startswith(name):
            return i
    raise AssertionError(
        f"identity {name!r} not in "
        f"{[i['name'] for i in report['ledger']['identities']]}"
    )


# ---------------------------------------------------------------------------
# Arrival processes.
# ---------------------------------------------------------------------------


class TestArrivals:
    def test_deterministic_per_seed(self):
        for profile in ("poisson", "mmpp", "ramp", "diurnal"):
            spec = ArrivalSpec(profile=profile, rate_rps=20.0)
            a = arrival_times(spec, 5.0, seed=42)
            b = arrival_times(spec, 5.0, seed=42)
            assert a == b
            assert a != arrival_times(spec, 5.0, seed=43)
            assert all(0 <= t < 5.0 for t in a)
            assert a == sorted(a)
            # Mean rate lands in the right ballpark over 5 s.
            assert 0.3 * 100 <= len(a) <= 2.0 * 100

    def test_mmpp_burstier_than_poisson(self):
        po = arrival_times(ArrivalSpec("poisson", rate_rps=20.0), 10.0, 7)
        mm = arrival_times(
            ArrivalSpec("mmpp", rate_rps=20.0, burst_factor=8.0), 10.0, 7
        )
        po_peak = max(interval_counts(po, 10.0))
        mm_peak = max(interval_counts(mm, 10.0))
        assert mm_peak > po_peak, (mm_peak, po_peak)

    def test_unknown_profile_rejected(self):
        with pytest.raises(ValueError, match="unknown arrival profile"):
            ArrivalSpec(profile="sawtooth")


# ---------------------------------------------------------------------------
# Offered trace: seeded determinism (the acceptance-criteria pin).
# ---------------------------------------------------------------------------


class TestOfferedTrace:
    def test_same_seed_identical_trace(self):
        plan = TrafficPlan(seed=5, duration_s=2.0)
        a, b = generate_offered(plan), generate_offered(plan)
        assert a == b
        assert offered_digest(a) == offered_digest(b)

    def test_seed_changes_trace(self):
        a = generate_offered(TrafficPlan(seed=5, duration_s=2.0))
        b = generate_offered(TrafficPlan(seed=6, duration_s=2.0))
        assert offered_digest(a) != offered_digest(b)

    def test_default_mix_covers_required_shapes(self):
        classes = default_classes()
        assert len(classes) >= 6
        by_name = {c.name: c for c in classes}
        assert by_name["grammar_tool"].grammar_schema_json is not None
        assert by_name["cancel_midstream"].cancel_after_tokens
        assert by_name["deadline_short"].deadline_s
        assert by_name["session_multiturn"].turns > 1
        assert by_name["duplex_voice"].duplex

    def test_max_prompt_tokens_really_bounds_prompts(self):
        # The clamp exists so real-engine runs fit the prefill buckets:
        # the drawn band must be a CEILING, not a suggestion — the head
        # truncates before the text may exceed it. The one floor is the
        # class marker the mock scripts key on, which never truncates.
        classes = default_classes(max_prompt_tokens=24,
                                  include_duplex=False)
        trace = generate_offered(
            TrafficPlan(seed=2, duration_s=2.0, classes=classes)
        )
        assert trace
        for req in trace:
            marker = f"sim {req.klass} "
            bound = max(24, len(marker) + 1)  # tokens = chars + BOS
            for turn in req.turns:
                assert len(turn.text) + 1 <= bound, \
                    (req.klass, len(turn.text) + 1, bound)
                assert turn.text.startswith(marker)

    def test_adding_a_class_never_perturbs_others(self):
        base = _test_classes(multiturn=False)
        more = base + (_test_classes()[-1],)
        a = generate_offered(TrafficPlan(seed=1, classes=base))
        b = generate_offered(TrafficPlan(seed=1, classes=more))
        keep = [r for r in b if r.klass != "session_multiturn"]
        assert [(r.klass, r.intended_at_s, r.turns) for r in a] == \
               [(r.klass, r.intended_at_s, r.turns) for r in keep]


# ---------------------------------------------------------------------------
# VU-pool backlog gate (satellite: queue-depth signal end to end).
# ---------------------------------------------------------------------------


class TestBacklogGate:
    def test_load_profile_backlog_rampdown(self):
        p = LoadProfile(8, backlog_limit=100)
        assert p.allowed(None, 0) == 8
        assert p.allowed(None, 50) == 4
        assert p.allowed(None, 100) == 1     # floor, never 0
        assert p.allowed(None, 10_000) == 1
        # Gate off: backlog ignored entirely.
        assert LoadProfile(8).allowed(None, 10_000) == 8
        # Pending ramp-down still composes on top.
        assert p.allowed(2, 50) == 2

    def test_pool_gates_on_backlog_signal(self):
        items = list(range(8))

        def run(backlog_fn):
            idx = [0]
            lock = threading.Lock()

            def source(_vu):
                with lock:
                    if idx[0] >= len(items):
                        return None
                    idx[0] += 1
                    return idx[0]

            def execute(_vu, _item):
                time.sleep(0.03)
                return "ok"

            pool = VUPool(
                concurrency=4, source=source, execute=execute,
                report=lambda i, r: None,
                profile=LoadProfile(4, backlog_limit=100),
                backlog=backlog_fn,
            )
            return pool.run(timeout_s=10.0)

        gated = run(lambda: 10_000)
        open_ = run(None)
        assert gated["max_active"] == 1
        assert gated["backlog_gated"] > 0
        assert gated["executed"] == 8
        assert open_["max_active"] > 1
        assert open_["backlog_gated"] == 0

    def test_simulator_wires_engine_backlog(self):
        # One deliberately slow worker + a token backlog limit below one
        # prompt: the pool's gate must visibly engage, and the ledger
        # still reconciles (gating delays offered load; it never drops
        # it).
        coord, _workers = _fleet(1)
        plan = TrafficPlan(
            seed=2, duration_s=0.4,
            classes=(ScenarioClass(
                name="cancel_midstream",
                arrival=ArrivalSpec(profile="poisson", rate_rps=20.0),
                prompt_tokens=(48, 64), max_tokens=96,
                cancel_after_tokens=12,
                slo=SLOTarget(ttft_ms=5000.0, min_attainment=0.0),
            ),),
        )
        sim = TrafficSimulator(coord, plan, concurrency=8,
                               backlog_limit_tokens=16)
        run = sim.run(timeout_s=30.0)
        report = run.report()
        assert report["ledger"]["ok"], report["ledger"]
        assert report["concurrency"]["pool"]["backlog_gated"] > 0
        assert report["ledger"]["offered_requests"] == len(run.trace)

    def test_coordinator_sums_worker_backlog(self):
        coord, workers = _fleet(2)
        assert coord.pending_prefill_tokens() == 0
        h1 = workers[0].submit(list(range(1, 40)),
                               SamplingParams(temperature=0.0, max_tokens=4))
        h2 = workers[1].submit(list(range(1, 30)),
                               SamplingParams(temperature=0.0, max_tokens=4))
        # Live playbacks mirror their prompt tokens; the coordinator
        # surface must sum them fleet-wide under the same method name.
        assert coord.pending_prefill_tokens() == \
            workers[0].pending_prefill_tokens() + \
            workers[1].pending_prefill_tokens()
        h1.collect_tokens(timeout=10)
        h2.collect_tokens(timeout=10)


# ---------------------------------------------------------------------------
# Direct mock-engine run: ledger + partial counts + flight sourcing.
# ---------------------------------------------------------------------------


class TestSimDirectMock:
    @pytest.fixture(scope="class")
    def run_and_report(self):
        eng = MockEngine(_test_mock_scenarios(), flight_events=4096,
                         prefill_chunk_tokens=16)
        plan = TrafficPlan(seed=11, duration_s=0.8,
                           classes=_test_classes())
        sim = TrafficSimulator(eng, plan, concurrency=16)
        run = sim.run(timeout_s=60.0)
        return run, run.report()

    def test_ledger_reconciles_exactly(self, run_and_report):
        run, report = run_and_report
        led = report["ledger"]
        assert led["ok"], led
        assert led["terminals_observed"] == led["engine_submits"]
        assert led["worker_submitted"] == led["worker_finished"]
        assert led["lost_streams"] == 0
        assert led["driver_errors"] == 0
        # Direct target: submits == finished + shed, no coordinator terms.
        assert led["engine_submits"] == \
            led["worker_finished"] + led["worker_shed"]
        assert led["flight"]["open_requests"] == 0
        assert led["flight"]["dropped"] == 0

    def test_every_class_played(self, run_and_report):
        _run, report = run_and_report
        for name in ("chat_bursty", "grammar_tool", "cancel_midstream",
                     "deadline_short", "session_multiturn"):
            assert report["classes"][name]["offered"] > 0, name

    def test_cancel_partial_counts_reconcile(self, run_and_report):
        run, report = run_and_report
        cell = report["classes"]["cancel_midstream"]
        assert cell["finish"]["cancelled"] == cell["turns_submitted"]
        assert cell["partial_mismatches"] == 0
        for out in run.outcomes:
            if out.klass == "cancel_midstream":
                assert out.cancelled_by_client
                assert out.tokens_streamed == out.num_generated
                assert out.tokens_streamed >= 4

    def test_deadline_partial_counts_reconcile(self, run_and_report):
        run, report = run_and_report
        cell = report["classes"]["deadline_short"]
        assert cell["finish"]["deadline"] == cell["turns_submitted"]
        assert cell["partial_mismatches"] == 0
        deadline_total = sum(b["deadline_exceeded"]
                             for b in run.worker_books)
        assert deadline_total == cell["finish"]["deadline"]

    def test_multiturn_sessions_submit_both_turns(self, run_and_report):
        _run, report = run_and_report
        cell = report["classes"]["session_multiturn"]
        assert cell["turns_offered"] == 2 * cell["offered"]
        assert cell["turns_submitted"] == cell["turns_offered"]
        assert cell["turns_skipped"] == 0

    def test_ttft_itl_sourced_from_flight_breakdowns(self, run_and_report):
        run, report = run_and_report
        chat = report["classes"]["chat_bursty"]
        assert chat["ttft_engine_ms"]["count"] > 0
        assert chat["itl_engine_ms"]["count"] > 0
        assert chat["queue_engine_ms"]["count"] > 0
        assert chat["breakdowns_missing"] == 0
        # The values really come from recorder terminals: every mapped
        # breakdown's ttft must match a recorder event, and the report's
        # p95 must be one of the observed samples.
        assert run.breakdowns
        samples = sorted(
            run.breakdowns[o.request_id]["breakdown"]["ttft_s"] * 1000.0
            for o in run.outcomes
            if o.klass == "chat_bursty" and o.request_id in run.breakdowns
            and o.tokens_streamed > 0
        )
        assert chat["ttft_engine_ms"]["p95"] in [
            pytest.approx(s, abs=1e-3) for s in samples
        ]

    def test_grammar_turns_complete_constrained(self, run_and_report):
        _run, report = run_and_report
        cell = report["classes"]["grammar_tool"]
        assert cell["finish"]["stop"] + cell["finish"]["length"] == \
            cell["turns_submitted"]
        assert cell["finish"]["error"] == 0

    def test_zero_offered_class_is_not_an_slo_failure(self):
        # A short run where a low-rate class produced no arrivals has no
        # evidence either way: attainment must be None (not 0.0) and the
        # cell must not report an SLO violation it never observed — and
        # the CLI table must render the empty cell without crashing.
        from omnia_tpu.evals.trafficsim.report import (
            _class_cell, summary_lines,
        )

        class _Plan:
            duration_s = 1.0

        class _Run:
            plan = _Plan()
            wall_s = 1.0
            breakdowns: dict = {}

        cell = _class_cell(_test_classes()[0], [], [], _Run())
        assert cell["offered"] == 0
        assert cell["slo"]["attainment"] is None
        assert cell["slo"]["passed"] is True
        assert cell["slo"]["failures"] == []
        report = {
            "seed": 0,
            "ledger": {"offered_requests": 0, "engine_submits": 0,
                       "ok": True, "identities": []},
            "slo": {"passed": True, "failures": []},
            "classes": {"empty": cell},
        }
        table = "\n".join(summary_lines(report))
        assert "empty" in table and "SLO FAIL" not in table

    def test_unsubmitted_offered_is_not_a_server_error(self):
        # A request the run never submitted (pool timeout truncated the
        # trace) is NOT met — the user got nothing — but it must not be
        # booked as a server error: max_error_rate judges the engine,
        # and the engine never saw the request.
        from omnia_tpu.evals.trafficsim.generator import (
            OfferedRequest, OfferedTurn,
        )
        from omnia_tpu.evals.trafficsim.report import _class_cell

        cls = _test_classes()[0]

        class _Plan:
            duration_s = 1.0

        class _Run:
            plan = _Plan()
            wall_s = 1.0
            breakdowns: dict = {}

        req = OfferedRequest(
            index=0, klass=cls.name, intended_at_s=0.0,
            turns=(OfferedTurn(text="sim chat_bursty never-sent",
                               max_tokens=8),),
        )
        cell = _class_cell(cls, [req], [], _Run())
        slo = cell["slo"]
        assert slo["unsubmitted"] == 1
        assert slo["errors"] == 0
        assert slo["error_rate"] == 0.0
        # Still counts against attainment: truncation must not flatter.
        assert slo["attainment"] == 0.0
        assert not any("error_rate" in f for f in slo["failures"])


# ---------------------------------------------------------------------------
# Coordinated-omission guard: a slow server must not shrink the offer.
# ---------------------------------------------------------------------------


class TestCoordinatedOmission:
    def test_slow_server_keeps_full_offered_trace(self):
        slow = [Scenario(pattern=r".", reply="z" * 30,
                         delay_per_token_s=0.01)]
        eng = MockEngine(slow, flight_events=1024)
        plan = TrafficPlan(
            seed=3, duration_s=0.4,
            classes=(ScenarioClass(
                name="chat_bursty",
                arrival=ArrivalSpec(profile="poisson", rate_rps=25.0),
                prompt_tokens=(12, 16), max_tokens=30,
                slo=SLOTarget(ttft_ms=100.0, min_attainment=0.0),
            ),),
        )
        expected = generate_offered(plan)
        sim = TrafficSimulator(eng, plan, concurrency=2)
        run = sim.run(timeout_s=60.0)
        report = run.report()
        # The offer never shrank: every generated request was submitted
        # and terminated, and the trace digest matches a fresh expansion.
        assert report["ledger"]["offered_requests"] == len(expected)
        assert report["ledger"]["engine_submits"] == len(expected)
        assert report["ledger"]["ok"], report["ledger"]
        assert run.offered_sha256 == offered_digest(expected)
        # The lateness is RECORDED, not hidden: with 2 VUs against
        # ~10 req over 0.4 s at ~0.3 s each, the tail submits late.
        cell = report["classes"]["chat_bursty"]
        assert cell["sched_delay_ms"]["p95"] > 50.0
        # And the intended-start TTFT view is correspondingly worse than
        # the submit-relative client view — the CO adjustment is visible.
        assert cell["ttft_from_intended_ms"]["p95"] > \
            cell["ttft_client_ms"]["p95"]


# ---------------------------------------------------------------------------
# Coordinator fleet + counted chaos: exact reconciliation.
# ---------------------------------------------------------------------------


class TestFleetJoin:
    def test_colliding_request_ids_never_cross_wire(self):
        # Two workers sharing one request-id namespace (real engines all
        # emit "req-N"; here two mocks with the SAME name) make the
        # flight-terminal join ambiguous: the overlap must be DROPPED
        # and counted, never attributed to the wrong class's books.
        workers = [
            MockEngine(_test_mock_scenarios(), name="mock",
                       flight_events=4096, prefill_chunk_tokens=16)
            for _ in range(2)
        ]
        coord = EngineCoordinator(workers, flight_events=512)
        plan = TrafficPlan(seed=9, duration_s=0.6,
                           classes=_test_classes(multiturn=False))
        sim = TrafficSimulator(coord, plan, concurrency=16)
        run = sim.run(timeout_s=60.0)
        report = run.report()
        assert report["ledger"]["ok"], report["ledger"]
        sim_rids = {o.request_id for o in run.outcomes}
        term_sets = [
            {ev.request_id for ev in w._flight.events("terminal")}
            for w in workers
        ]
        overlap = term_sets[0] & term_sets[1] & sim_rids
        # Both workers served traffic, so the hazard is real here.
        assert overlap, (len(term_sets[0]), len(term_sets[1]))
        assert run.breakdown_collisions == len(overlap)
        assert report["ledger"]["flight"]["id_collisions"] == len(overlap)
        assert not overlap & set(run.breakdowns)


class TestChaosLedger:
    def test_counted_faults_reconcile_exactly(self):
        plan_faults = FaultPlan(die_after_tokens=0, die_count=2,
                                flaky_submit=1)
        coord, workers = _fleet(2, flight_events=4096)
        plan = TrafficPlan(seed=13, duration_s=0.8,
                           classes=_test_classes(multiturn=False))
        sim = TrafficSimulator(coord, plan, concurrency=16,
                               chaos=plan_faults, chaos_at_s=0.1)
        run = sim.run(timeout_s=60.0)
        report = run.report()
        led = report["ledger"]
        assert led["ok"], led
        # The chaos plan actually fired, mid-run.
        assert run.chaos_fired["deaths"] == 2
        assert run.chaos_fired["submit_faults"] == 1
        # Exact attribution: every counted death is a transparent
        # resubmit, a surfaced worker-death ERROR, or a failed resubmit.
        ident = _ident(report, "FaultPlan deaths")
        assert ident["ok"] is True, ident
        assert led["coordinator"]["resubmits"] + \
            led["death_errors_observed"] + led["unrouted_resubmit"] == 2
        # Coordinator books close: every submit routed, shed, or failed
        # routing — and worker accepted == routed + resubmits.
        assert _ident(report, "submits == routed")["ok"] is True
        assert _ident(report, "worker_submitted == routed")["ok"] is True
        # Flaky submit surfaced as at least one failover.
        assert led["coordinator"]["failovers"] >= 1

    def test_clean_arm_has_no_chaos_artifacts(self):
        coord, _workers = _fleet(2, flight_events=4096)
        plan = TrafficPlan(seed=13, duration_s=0.6,
                           classes=_test_classes(multiturn=False))
        sim = TrafficSimulator(coord, plan, concurrency=16)
        report = sim.run(timeout_s=60.0).report()
        led = report["ledger"]
        assert led["ok"], led
        assert led["chaos_fired"] is None
        assert led["coordinator"]["resubmits"] == 0
        assert led["death_errors_observed"] == 0


# ---------------------------------------------------------------------------
# Coordinator grammar threading (satellite of the grammar seam).
# ---------------------------------------------------------------------------


class TestCoordinatorGrammar:
    def _grammar(self, eng):
        from omnia_tpu.engine.grammar.cache import compile_json_schema

        return compile_json_schema(TOOL_SCHEMA, eng.workers[0].tokenizer)

    def test_constrained_submit_through_coordinator(self):
        coord, workers = _fleet(2)
        g = self._grammar(coord)
        sp = SamplingParams(temperature=0.0, max_tokens=64,
                            stop_token_ids=(0,))
        tok = workers[0].tokenizer
        h = coord.submit(tok.encode("sim grammar_tool via coord"), sp,
                         grammar=g)
        toks, fin = h.collect_tokens(timeout=10)
        assert fin.finish_reason in (FinishReason.STOP, FinishReason.LENGTH)
        doc = json.loads(tok.decode([t for t in toks if t != 0]))
        assert doc["tool"] in ("search", "lookup")
        assert isinstance(doc["k"], int)

    def test_resubmit_keeps_grammar(self):
        fault = FaultPlan(die_after_tokens=0, die_count=1)
        coord, workers = _fleet(2, fault_plan=fault)
        g = self._grammar(coord)
        sp = SamplingParams(temperature=0.0, max_tokens=64,
                            stop_token_ids=(0,))
        tok = workers[0].tokenizer
        h = coord.submit(tok.encode("sim grammar_tool resubmit"), sp,
                         grammar=g)
        toks, fin = h.collect_tokens(timeout=10)
        assert fault.fired["deaths"] == 1
        assert coord.metrics["resubmits"] == 1
        # The replacement stream is still constrained — valid JSON out.
        assert fin.finish_reason in (FinishReason.STOP, FinishReason.LENGTH)
        doc = json.loads(tok.decode([t for t in toks if t != 0]))
        assert doc["tool"] in ("search", "lookup")

    def test_mock_name_prefixes_request_ids(self):
        default = MockEngine()
        named = MockEngine(name="w7")
        assert default.submit([1, 2], SamplingParams(max_tokens=1)) \
            .request_id.startswith("mock-")
        assert named.submit([1, 2], SamplingParams(max_tokens=1)) \
            .request_id.startswith("w7-")


# ---------------------------------------------------------------------------
# Aggregator fold + threshold gating (satellite).
# ---------------------------------------------------------------------------


class TestAggregatorSLO:
    def _report(self):
        coord, _ = _fleet(2, flight_events=4096)
        plan = TrafficPlan(seed=21, duration_s=0.5,
                           classes=_test_classes(deadline=False))
        return TrafficSimulator(coord, plan, concurrency=16) \
            .run(timeout_s=60.0).report()

    def test_fold_and_gate(self):
        report = self._report()
        agg = Aggregator()
        folded = agg.add_slo_cells(report, provider="mock-fleet")
        assert folded == len(report["classes"])
        cells = {c.scenario: c for c in agg.cells()}
        chat = cells["chat_bursty"]
        assert chat.slo_offered == report["classes"]["chat_bursty"]["offered"]
        assert chat.ttft_ms["p95"] == \
            report["classes"]["chat_bursty"]["ttft_engine_ms"]["p95"]
        d = chat.to_dict()
        assert d["slo_attainment"] is not None
        assert d["ttft_p95_ms"] == chat.ttft_ms["p95"]
        # Pure simulator cells are NOT judged by the classic check
        # gates: a DEFAULT threshold (min_pass_rate=1.0) must pass even
        # though these cells have zero check runs — the SLO gates below
        # are their verdict surface.
        verdict = agg.evaluate(Threshold(
            min_slo_attainment=0.0, max_p95_ttft_ms=60_000.0,
        ))
        assert verdict["passed"], verdict["failures"]
        # A failing gate names the class AND the percentile.
        verdict = agg.evaluate(Threshold(max_p95_ttft_ms=0.0001))
        assert not verdict["passed"]
        assert any("chat_bursty/mock-fleet: TTFT p95" in f
                   for f in verdict["failures"]), verdict["failures"]
        # Attainment gate likewise.
        verdict = agg.evaluate(Threshold(min_slo_attainment=1.01))
        assert any("SLO attainment" in f for f in verdict["failures"])

    def test_classic_jobs_unaffected(self):
        # Cells without folded SLO data never trip the new gates.
        from omnia_tpu.evals.defs import WorkResult

        agg = Aggregator()
        agg.add(WorkResult(work_id="w1", job="j", scenario="s",
                           provider="p", repeat=0))
        verdict = agg.evaluate(Threshold(
            min_slo_attainment=0.99, max_p95_ttft_ms=0.001,
            max_p95_itl_ms=0.001,
        ))
        assert verdict["passed"], verdict["failures"]
        assert verdict["cells"][0]["slo_attainment"] is None


# ---------------------------------------------------------------------------
# CLI: artifact round trip, seed reproduction, jax-free proof.
# ---------------------------------------------------------------------------


class TestCLI:
    def _run(self, *args, env=None):
        cmd = [sys.executable, "-m", "omnia_tpu.evals.trafficsim",
               "--duration", "0.5", "--no-duplex", *args]
        full_env = dict(os.environ)
        if env:
            full_env.update(env)
        return subprocess.run(cmd, cwd=REPO, env=full_env,
                              capture_output=True, text=True, timeout=120)

    def test_report_artifact_and_seed_reproduction(self, tmp_path):
        out_a = str(tmp_path / "a.json")
        out_b = str(tmp_path / "b.json")
        ra = self._run("--seed", "3", "--out", out_a)
        assert ra.returncode == 0, ra.stdout + ra.stderr
        rb = self._run("--seed", "3", "--out", out_b)
        assert rb.returncode == 0, rb.stdout + rb.stderr
        a = json.load(open(out_a))
        b = json.load(open(out_b))
        assert a["ledger"]["ok"] and b["ledger"]["ok"]
        assert a["offered_sha256"] == b["offered_sha256"]
        assert a["schema_version"] == 1
        rc = self._run("--seed", "4", "--out", str(tmp_path / "c.json"))
        assert rc.returncode == 0
        c = json.load(open(str(tmp_path / "c.json")))
        assert c["offered_sha256"] != a["offered_sha256"]

    def test_chaos_arm_reconciles(self, tmp_path):
        out = str(tmp_path / "chaos.json")
        r = self._run("--seed", "9", "--chaos", "--chaos-at", "0.05",
                      "--out", out)
        assert r.returncode == 0, r.stdout + r.stderr
        rep = json.load(open(out))
        assert rep["ledger"]["ok"], rep["ledger"]
        assert rep["ledger"]["chaos_fired"]["deaths"] >= 1

    def test_cli_is_jax_free(self, tmp_path):
        stub = os.path.join(REPO, "tests", "fixtures", "nojax_stub")
        r = self._run(
            "--seed", "1", "--out", str(tmp_path / "nj.json"),
            env={"PYTHONPATH": stub + os.pathsep
                 + os.environ.get("PYTHONPATH", "")},
        )
        assert r.returncode == 0, r.stdout + r.stderr
        assert json.load(open(str(tmp_path / "nj.json")))["ledger"]["ok"]


# ---------------------------------------------------------------------------
# Duplex/barge-in class (needs the runtime package → skips without jax).
# ---------------------------------------------------------------------------


class TestDuplex:
    def test_barge_in_sessions_reconcile(self):
        # exc_type: the CI poisoned-jax stub raises ImportError through
        # the runtime's provider-layer import — that's the skip signal.
        pytest.importorskip("omnia_tpu.runtime.conversation",
                            exc_type=ImportError)
        coord, _workers = _fleet(1, flight_events=2048)
        plan = TrafficPlan(
            seed=17, duration_s=0.5,
            classes=(ScenarioClass(
                name="duplex_voice",
                arrival=ArrivalSpec(profile="poisson", rate_rps=6.0),
                prompt_tokens=(12, 20), max_tokens=64,
                duplex=True, barge_in_after_chunks=2,
                slo=SLOTarget(ttft_ms=2000.0, min_attainment=0.0),
            ),),
        )
        sim = TrafficSimulator(coord, plan, concurrency=8)
        run = sim.run(timeout_s=60.0)
        report = run.report()
        led = report["ledger"]
        assert led["ok"], led
        cell = report["classes"]["duplex_voice"]
        assert cell["offered"] > 0
        # Every session was interrupted by the scripted barge-in, and
        # each one submitted exactly one engine request that terminated.
        assert cell["finish"]["interrupted"] == cell["turns_submitted"]
        assert led["engine_submits"] == cell["turns_submitted"]
        assert led["worker_finished"] == led["engine_submits"]
        assert run.duplex_skipped == 0


# ---------------------------------------------------------------------------
# Mock-vs-real-engine report schema parity (skips without jax).
# ---------------------------------------------------------------------------


def _key_paths(obj, prefix=""):
    """All dict key paths, recursing through dicts and list elements —
    the report-schema fingerprint both backends must share."""
    paths = set()
    if isinstance(obj, dict):
        for k, v in obj.items():
            p = f"{prefix}.{k}" if prefix else str(k)
            paths.add(p)
            paths |= _key_paths(v, p)
    elif isinstance(obj, list):
        for v in obj:
            paths |= _key_paths(v, prefix + "[]")
    return paths


class TestSchemaParityRealEngine:
    def test_mock_and_real_reports_share_schema(self):
        pytest.importorskip("jax", exc_type=ImportError)
        from omnia_tpu.engine import EngineConfig, InferenceEngine
        from omnia_tpu.models import get_config

        classes = _test_classes(multiturn=False)
        # Scale the offer down: a CPU test-tiny engine serves a few
        # requests, not a fleet's worth.
        import dataclasses as dc
        classes = tuple(
            dc.replace(
                c,
                arrival=dc.replace(c.arrival, rate_rps=3.0),
                prompt_tokens=(12, 24), max_tokens=8,
            )
            for c in classes
        )
        plan = TrafficPlan(seed=29, duration_s=0.6, classes=classes)

        mock = MockEngine(_test_mock_scenarios(), flight_events=2048)
        mock_report = TrafficSimulator(mock, plan, concurrency=8) \
            .run(timeout_s=60.0).report()

        ecfg = EngineConfig(
            num_slots=4, max_seq=128, prefill_buckets=(64,),
            dtype="float32", max_sessions=0, grammar=True,
            grammar_max_states=512, flight_events=2048, decode_chunk=2,
        )
        eng = InferenceEngine(get_config("test-tiny"), ecfg, seed=0)
        eng.warmup(sessions=False)
        eng.start()
        try:
            real_report = TrafficSimulator(eng, plan, concurrency=8,
                                           turn_timeout_s=120.0) \
                .run(timeout_s=300.0).report()
        finally:
            eng.stop()
        assert real_report["ledger"]["ok"], real_report["ledger"]
        assert mock_report["ledger"]["ok"], mock_report["ledger"]
        assert _key_paths(mock_report) == _key_paths(real_report)
        # Same flight-recorder sourcing on both backends.
        for rep in (mock_report, real_report):
            assert rep["classes"]["chat_bursty"]["ttft_engine_ms"]["count"] > 0
