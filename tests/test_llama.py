"""Model-level tests: shapes, prefill/decode equivalence, sharded equivalence."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from omnia_tpu.models import get_config
from omnia_tpu.models import llama
from omnia_tpu.parallel import make_mesh, shard_pytree


@pytest.fixture(scope="module")
def tiny():
    cfg = get_config("test-tiny")
    params = llama.init_params(cfg, jax.random.key(0), dtype=jnp.float32)
    return cfg, params


def test_forward_train_shapes(tiny):
    cfg, params = tiny
    tokens = jnp.zeros((2, 7), dtype=jnp.int32)
    logits = llama.forward_train(params, cfg, tokens)
    assert logits.shape == (2, 7, cfg.vocab_size)
    assert logits.dtype == jnp.float32


def test_forward_train_causal(tiny):
    """Changing a future token must not change past logits."""
    cfg, params = tiny
    rng = np.random.default_rng(0)
    toks = rng.integers(0, cfg.vocab_size, size=(1, 8))
    a = llama.forward_train(params, cfg, jnp.asarray(toks, dtype=jnp.int32))
    toks2 = toks.copy()
    toks2[0, 5] = (toks2[0, 5] + 1) % cfg.vocab_size
    b = llama.forward_train(params, cfg, jnp.asarray(toks2, dtype=jnp.int32))
    np.testing.assert_allclose(np.asarray(a[0, :5]), np.asarray(b[0, :5]), rtol=2e-4, atol=2e-4)
    assert not np.allclose(np.asarray(a[0, 5]), np.asarray(b[0, 5]))


def test_prefill_matches_forward_train(tiny):
    """Serving prefill (cache path) must produce the same logits as the
    no-cache training forward."""
    cfg, params = tiny
    B, T, S = 2, 6, 16
    rng = np.random.default_rng(1)
    tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, size=(B, T)), dtype=jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None, :], (B, T))
    ck, cv = llama.init_kv_cache(cfg, B, S, dtype=jnp.float32)
    logits_serve, _, _ = llama.forward(
        params, cfg, tokens, pos, ck, cv, jnp.zeros((B,), jnp.int32)
    )
    logits_train = llama.forward_train(params, cfg, tokens)
    np.testing.assert_allclose(
        np.asarray(logits_serve), np.asarray(logits_train), rtol=2e-4, atol=2e-4
    )


def test_decode_matches_prefill(tiny):
    """Incremental decode must reproduce full-prefill logits token by token.
    This is THE serving-correctness invariant."""
    cfg, params = tiny
    B, T, S = 1, 8, 16
    rng = np.random.default_rng(2)
    tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, size=(B, T)), dtype=jnp.int32)

    # Full prefill at once.
    pos = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None, :], (B, T))
    ck, cv = llama.init_kv_cache(cfg, B, S, dtype=jnp.float32)
    full_logits, _, _ = llama.forward(
        params, cfg, tokens, pos, ck, cv, jnp.zeros((B,), jnp.int32)
    )

    # Token-by-token decode.
    ck, cv = llama.init_kv_cache(cfg, B, S, dtype=jnp.float32)
    step_logits = []
    for t in range(T):
        tok = tokens[:, t : t + 1]
        p = jnp.full((B, 1), t, dtype=jnp.int32)
        start = jnp.full((B,), t, dtype=jnp.int32)
        lg, ck, cv = llama.forward(params, cfg, tok, p, ck, cv, start)
        step_logits.append(np.asarray(lg[:, 0]))

    np.testing.assert_allclose(
        np.stack(step_logits, axis=1), np.asarray(full_logits), rtol=2e-4, atol=2e-4
    )


def test_chunked_prefill_matches_full(tiny):
    """Multi-turn incremental prefill (write_start > 0) is exact."""
    cfg, params = tiny
    B, T, S = 1, 8, 16
    split = 5
    rng = np.random.default_rng(3)
    tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, size=(B, T)), dtype=jnp.int32)

    pos = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None, :], (B, T))
    ck, cv = llama.init_kv_cache(cfg, B, S, dtype=jnp.float32)
    full_logits, _, _ = llama.forward(
        params, cfg, tokens, pos, ck, cv, jnp.zeros((B,), jnp.int32)
    )

    ck, cv = llama.init_kv_cache(cfg, B, S, dtype=jnp.float32)
    _, ck, cv = llama.forward(
        params, cfg, tokens[:, :split], pos[:, :split], ck, cv, jnp.zeros((B,), jnp.int32)
    )
    second, _, _ = llama.forward(
        params, cfg, tokens[:, split:], pos[:, split:], ck, cv,
        jnp.full((B,), split, dtype=jnp.int32),
    )
    np.testing.assert_allclose(
        np.asarray(second), np.asarray(full_logits[:, split:]), rtol=2e-4, atol=2e-4
    )


def test_moe_forward(tiny):
    cfg = get_config("test-tiny-moe")
    params = llama.init_params(cfg, jax.random.key(1), dtype=jnp.float32)
    tokens = jnp.zeros((2, 5), dtype=jnp.int32)
    logits = llama.forward_train(params, cfg, tokens)
    assert logits.shape == (2, 5, cfg.vocab_size)
    assert bool(jnp.all(jnp.isfinite(logits)))


def test_param_count_estimate():
    cfg = get_config("llama3-8b")
    n = cfg.num_params()
    assert 7.5e9 < n < 8.5e9, n


def test_sharded_forward_matches_single_device(tiny, devices8):
    """TP+DP sharded execution must be numerically equivalent (f32) to
    single-device execution."""
    cfg, params = tiny
    mesh = make_mesh(dp=2, tp=2, devices=devices8)
    B, T, S = 2, 4, 8
    rng = np.random.default_rng(4)
    tokens = jnp.asarray(rng.integers(0, cfg.vocab_size, size=(B, T)), dtype=jnp.int32)
    pos = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32)[None, :], (B, T))
    start = jnp.zeros((B,), jnp.int32)

    ck, cv = llama.init_kv_cache(cfg, B, S, dtype=jnp.float32)
    ref_logits, ref_k, ref_v = llama.forward(params, cfg, tokens, pos, ck, cv, start)

    sh_params = shard_pytree(params, llama.param_specs(cfg), mesh)
    kspec, vspec = llama.kv_cache_specs()
    sh_ck = jax.device_put(ck, NamedSharding(mesh, kspec))
    sh_cv = jax.device_put(cv, NamedSharding(mesh, vspec))
    sh_tokens = jax.device_put(tokens, NamedSharding(mesh, P("dp", None)))

    fwd = jax.jit(lambda p, t, q, k, v, s: llama.forward(p, cfg, t, q, k, v, s))
    out_logits, out_k, out_v = fwd(sh_params, sh_tokens, pos, sh_ck, sh_cv, start)

    np.testing.assert_allclose(
        np.asarray(out_logits), np.asarray(ref_logits), rtol=1e-3, atol=1e-3
    )
    np.testing.assert_allclose(np.asarray(out_k), np.asarray(ref_k), rtol=1e-3, atol=1e-3)


def test_sharded_moe_matches_single_device(devices8):
    """Expert-parallel MoE over tp axis is numerically equivalent."""
    cfg = get_config("test-tiny-moe")
    params = llama.init_params(cfg, jax.random.key(2), dtype=jnp.float32)
    mesh = make_mesh(dp=2, tp=4, devices=devices8)
    tokens = jnp.asarray(
        np.random.default_rng(5).integers(0, cfg.vocab_size, size=(2, 4)), dtype=jnp.int32
    )
    ref = llama.forward_train(params, cfg, tokens)
    sh_params = shard_pytree(params, llama.param_specs(cfg), mesh)
    got = jax.jit(lambda p, t: llama.forward_train(p, cfg, t))(sh_params, tokens)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref), rtol=1e-3, atol=1e-3)


def test_train_step_runs_and_loss_decreases(devices8):
    import optax
    from omnia_tpu.parallel import make_mesh
    from omnia_tpu.train import make_train_step

    cfg = get_config("test-tiny")
    mesh = make_mesh(dp=2, tp=2, devices=devices8)
    init_fn, train_step = make_train_step(cfg, optax.adamw(1e-2), mesh=mesh)
    state = init_fn(jax.random.key(0))
    tokens = jnp.asarray(
        np.random.default_rng(0).integers(1, cfg.vocab_size, size=(4, 12)),
        dtype=jnp.int32,
    )
    state, loss0 = train_step(state, tokens)
    for _ in range(5):
        state, loss = train_step(state, tokens)
    assert float(loss) < float(loss0)
    assert int(state.step) == 6
