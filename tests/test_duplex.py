"""Duplex voice tests: negotiation, STT→turn→TTS flow, barge-in, and the
facade's binary-frame path end-to-end (mock speech providers)."""

from __future__ import annotations

import base64
import json
import time

import pytest

from omnia_tpu.runtime import contract as c
from omnia_tpu.runtime.client import RuntimeClient
from omnia_tpu.runtime.duplex import MockStt, MockTts, SpeechSupport
from omnia_tpu.runtime.packs import load_pack
from omnia_tpu.runtime.providers import ProviderRegistry, ProviderSpec
from omnia_tpu.runtime.server import RuntimeServer

PACK = {"name": "voice-agent", "version": "1.0.0",
        "prompts": {"system": "You speak."}, "sampling": {"max_tokens": 256}}

SCENARIOS = [
    {"pattern": "how do refunds work", "reply": "refunds take thirty days to process"},
    {"pattern": "slow story", "reply": "o n c e  u p o n  a  t i m e " * 20,
     "delay_per_token_s": 0.01},
    {"pattern": ".", "reply": "I heard you"},
]


def _server(speech=True):
    reg = ProviderRegistry()
    reg.register(ProviderSpec(name="m", type="mock", options={"scenarios": SCENARIOS}))
    return RuntimeServer(
        pack=load_pack(PACK), providers=reg, provider_name="m",
        speech=SpeechSupport(MockStt(), MockTts()) if speech else None,
    )


def _audio_msg(text: str, final: bool = True) -> c.ClientMessage:
    return c.ClientMessage(
        type="audio_input",
        audio_b64=base64.b64encode(text.encode()).decode(),
        final=final,
    )


class TestDuplexRuntime:
    def test_capability_gated(self):
        rt = _server(speech=False)
        port = rt.serve("localhost:0")
        try:
            client = RuntimeClient(f"localhost:{port}")
            assert "duplex_audio" not in client.health().capabilities
            stream = client.open_stream("s-nocap")
            stream.send(c.ClientMessage(type="duplex_start"))
            msgs = [next(iter(stream))]
            assert msgs[0].type == "error"
            assert msgs[0].error_code == "capability_unsupported"
            stream.close()
            client.close()
        finally:
            rt.shutdown()

    def test_voice_turn_flow(self):
        rt = _server()
        port = rt.serve("localhost:0")
        try:
            client = RuntimeClient(f"localhost:{port}")
            assert "duplex_audio" in client.health().capabilities
            stream = client.open_stream("s-voice")
            stream.send(c.ClientMessage(type="duplex_start",
                                        audio_format={"encoding": "pcm16"}))
            it = iter(stream)
            ready = next(it)
            assert ready.type == "duplex_ready"
            assert ready.audio_format["encoding"] == "pcm16"
            # two partial chunks then final
            stream.send(_audio_msg("how do refunds ", final=False))
            stream.send(_audio_msg("work", final=True))
            transcript_user = audio = transcript_assistant = done = None
            chunks = []
            while done is None:
                m = next(it)
                if m.type == "transcript" and m.role == "user":
                    transcript_user = m.text
                elif m.type == "media_chunk":
                    chunks.append((m.seq, base64.b64decode(m.audio_b64)))
                elif m.type == "transcript" and m.role == "assistant":
                    transcript_assistant = m.text
                elif m.type == "done":
                    done = m
            assert transcript_user == "how do refunds work"
            spoken = b"".join(audio for _seq, audio in sorted(chunks))
            assert spoken.decode() == "refunds take thirty days to process"
            assert [s for s, _ in chunks] == sorted(s for s, _ in chunks)
            assert transcript_assistant == "refunds take thirty days to process"
            assert done.usage.completion_tokens > 0
            stream.close()
            client.close()
        finally:
            rt.shutdown()

    def test_audio_before_start_rejected(self):
        rt = _server()
        port = rt.serve("localhost:0")
        try:
            client = RuntimeClient(f"localhost:{port}")
            stream = client.open_stream("s-early")
            stream.send(_audio_msg("hello"))
            m = next(iter(stream))
            assert m.type == "error" and m.error_code == "duplex_not_started"
            stream.close()
            client.close()
        finally:
            rt.shutdown()

    def test_unsupported_encoding_rejected(self):
        rt = _server()
        port = rt.serve("localhost:0")
        try:
            client = RuntimeClient(f"localhost:{port}")
            stream = client.open_stream("s-enc")
            stream.send(c.ClientMessage(type="duplex_start",
                                        audio_format={"encoding": "opus-48k"}))
            m = next(iter(stream))
            assert m.type == "error" and m.error_code == "unsupported_audio_format"
            stream.close()
            client.close()
        finally:
            rt.shutdown()

    def test_barge_in_interrupts_playback(self):
        rt = _server()
        port = rt.serve("localhost:0")
        try:
            client = RuntimeClient(f"localhost:{port}")
            stream = client.open_stream("s-barge")
            stream.send(c.ClientMessage(type="duplex_start"))
            it = iter(stream)
            assert next(it).type == "duplex_ready"
            stream.send(_audio_msg("tell me a slow story"))
            saw_interrupt = False
            deadline = time.monotonic() + 30
            sent_barge = False
            while time.monotonic() < deadline:
                m = next(it)
                if m.type == "media_chunk" and not sent_barge:
                    # caller starts talking while the agent is speaking
                    stream.send(_audio_msg("wait stop", final=False))
                    sent_barge = True
                elif m.type == "interruption":
                    saw_interrupt = True
                    break
                elif m.type == "done":
                    break
            assert saw_interrupt, "barge-in never interrupted playback"
            stream.close()
            client.close()
        finally:
            rt.shutdown()


class TestDuplexFacade:
    def test_binary_frames_end_to_end(self):
        from websockets.sync.client import connect

        from omnia_tpu.facade.server import FacadeServer

        rt = _server()
        rport = rt.serve("localhost:0")
        facade = FacadeServer(runtime_target=f"localhost:{rport}", agent_name="voice-agent")
        fport = facade.serve()
        try:
            with connect(f"ws://localhost:{fport}/ws") as ws:
                connected = json.loads(ws.recv(timeout=10))
                assert "duplex_audio" in connected["capabilities"]
                ws.send(json.dumps({"type": "duplex_start",
                                    "format": {"encoding": "pcm16"}}))
                ready = json.loads(ws.recv(timeout=10))
                assert ready["type"] == "duplex_ready"
                ws.send(b"how do refunds work")  # binary audio
                ws.send(b"")  # empty frame = end of utterance
                audio = bytearray()
                transcripts = []
                deadline = time.monotonic() + 30
                while time.monotonic() < deadline:
                    frame = ws.recv(timeout=deadline - time.monotonic())
                    if isinstance(frame, bytes):
                        audio.extend(frame)
                        continue
                    doc = json.loads(frame)
                    if doc["type"] == "transcript":
                        transcripts.append((doc["role"], doc["text"]))
                    elif doc["type"] == "done":
                        break
                assert audio.decode() == "refunds take thirty days to process"
                assert ("user", "how do refunds work") in transcripts
                ws.send(json.dumps({"type": "hangup"}))
        finally:
            facade.shutdown()
            rt.shutdown()

    def test_binary_frame_without_duplex_rejected(self):
        from websockets.sync.client import connect

        from omnia_tpu.facade.server import FacadeServer

        rt = _server()
        rport = rt.serve("localhost:0")
        facade = FacadeServer(runtime_target=f"localhost:{rport}", agent_name="voice-agent")
        fport = facade.serve()
        try:
            with connect(f"ws://localhost:{fport}/ws") as ws:
                json.loads(ws.recv(timeout=10))  # connected
                ws.send(b"raw audio out of nowhere")
                err = json.loads(ws.recv(timeout=10))
                assert err["type"] == "error"
                assert err["code"] == "duplex_not_started"
        finally:
            facade.shutdown()
            rt.shutdown()


class TestProviderResolvedSpeech:
    """Speech resolves from declared tts/stt-role providers (reference
    provider_types.go:40-63 — duplex speech comes from Provider CRDs, not
    hardwired mocks; VERDICT r2 #6), and the `tone` type round-trips REAL
    pcm16 audio through the facade binary-frame path."""

    def _server_with_speech_providers(self, speech_type="tone"):
        reg = ProviderRegistry()
        reg.register(ProviderSpec(name="m", type="mock",
                                  options={"scenarios": SCENARIOS}))
        reg.register(ProviderSpec(name="ears", type=speech_type, role="stt"))
        reg.register(ProviderSpec(name="voice", type=speech_type, role="tts"))
        # No explicit speech= : the runtime must resolve it from roles.
        return RuntimeServer(pack=load_pack(PACK), providers=reg,
                             provider_name="m")

    def test_tone_codec_roundtrip_is_real_pcm16(self):
        import numpy as np

        from omnia_tpu.runtime.duplex import TonePcmStt, TonePcmTts

        fmt = {"encoding": "pcm16", "sample_rate_hz": 16000, "channels": 1}
        audio = b"".join(TonePcmTts().synthesize("how do refunds work?", fmt))
        samples = np.frombuffer(audio, dtype="<i2")
        assert len(samples) > 1000  # genuine sample data, not text bytes
        assert int(np.abs(samples).max()) > 5000
        assert TonePcmStt().transcribe(audio, fmt) == "how do refunds work?"

    def test_speech_resolved_from_provider_roles(self):
        rt = self._server_with_speech_providers()
        assert "duplex_audio" in rt.capabilities
        # Without speech-role providers: no duplex capability.
        reg = ProviderRegistry()
        reg.register(ProviderSpec(name="m", type="mock",
                                  options={"scenarios": SCENARIOS}))
        bare = RuntimeServer(pack=load_pack(PACK), providers=reg,
                             provider_name="m")
        assert "duplex_audio" not in bare.capabilities

    def test_pcm16_roundtrip_through_facade_binary_frames(self):
        import numpy as np
        from websockets.sync.client import connect

        from omnia_tpu.facade.server import FacadeServer
        from omnia_tpu.runtime.duplex import TonePcmStt, TonePcmTts

        fmt = {"encoding": "pcm16", "sample_rate_hz": 16000, "channels": 1}
        rt = self._server_with_speech_providers()
        rport = rt.serve("localhost:0")
        facade = FacadeServer(runtime_target=f"localhost:{rport}",
                              agent_name="voice-agent")
        fport = facade.serve()
        try:
            with connect(f"ws://localhost:{fport}/ws") as ws:
                connected = json.loads(ws.recv(timeout=10))
                assert "duplex_audio" in connected["capabilities"]
                ws.send(json.dumps({"type": "duplex_start", "format": fmt}))
                assert json.loads(ws.recv(timeout=10))["type"] == "duplex_ready"
                # The caller actually SPEAKS pcm16 (tone-encoded utterance).
                utterance = b"".join(
                    TonePcmTts().synthesize("how do refunds work", fmt)
                )
                for i in range(0, len(utterance), 4096):
                    ws.send(utterance[i : i + 4096])
                ws.send(b"")  # end of utterance
                audio = bytearray()
                transcripts = []
                deadline = time.monotonic() + 30
                while time.monotonic() < deadline:
                    frame = ws.recv(timeout=deadline - time.monotonic())
                    if isinstance(frame, bytes):
                        audio.extend(frame)
                        continue
                    doc = json.loads(frame)
                    if doc["type"] == "transcript":
                        transcripts.append((doc["role"], doc["text"]))
                    elif doc["type"] == "done":
                        break
                assert ("user", "how do refunds work") in transcripts
                # The reply audio is real pcm16 that decodes to the reply.
                samples = np.frombuffer(bytes(audio), dtype="<i2")
                assert int(np.abs(samples).max()) > 5000
                assert (
                    TonePcmStt().transcribe(bytes(audio), fmt)
                    == "refunds take thirty days to process"
                )
        finally:
            facade.shutdown()
            rt.shutdown()
