"""Deterministic chaos harness for the request-lifecycle robustness layer.

Every scenario injects ONE counted fault (engine/faults.FaultPlan) and
asserts the system degrades to exactly one terminal event per request
with the correct FinishReason, and that the coordinator/engine metrics
reconcile EXACTLY with the observed terminal events. No randomness: the
plans are counted, the backoff jitter is seeded, deadline tests inject
the engine's logical clock, and the suite runs hermetically on
JAX_PLATFORMS=cpu (mock workers everywhere; the two scenarios that need
the real scheduler/watchdog use the test-tiny engine).

Fault matrix (ISSUE 7 acceptance): worker death pre-token, worker death
mid-stream, hang-on-dispatch, full queue, deadline in queue, deadline
mid-decode — plus flaky-submit failover, graceful drain, and the
all-faults reconciliation battery.
"""

from __future__ import annotations

import queue as queue_mod
import time

import pytest

from omnia_tpu.engine import (
    EngineConfig,
    FinishReason,
    InferenceEngine,
    MockEngine,
    SamplingParams,
)
from omnia_tpu.engine.coordinator import EngineCoordinator
from omnia_tpu.engine.faults import FaultPlan
from omnia_tpu.engine.mock import Scenario
from omnia_tpu.engine.tokenizer import ByteTokenizer
from omnia_tpu.models import get_config

pytestmark = pytest.mark.chaos

TOK = ByteTokenizer()
SP = SamplingParams(max_tokens=64)
GREEDY = SamplingParams(temperature=0.0, max_tokens=8)


def _drain_events(handle, timeout=10.0):
    """Collect every event on a handle up to (and including) its first
    terminal, then assert NO second terminal ever arrives — the
    exactly-one-terminal invariant every fault must preserve."""
    tokens, finals = [], []
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        try:
            ev = handle._queue.get(timeout=0.1)
        except queue_mod.Empty:
            if finals:
                break
            continue
        if ev.token_id is not None:
            tokens.append(ev.token_id)
        if ev.is_final:
            finals.append(ev)
            # Grace window: a buggy double-finish would land right after.
            deadline = min(deadline, time.monotonic() + 0.2)
    assert len(finals) == 1, f"expected exactly one terminal, got {finals}"
    return tokens, finals[0]


def _tiny_engine(**kw):
    kw.setdefault("num_slots", 2)
    kw.setdefault("max_seq", 64)
    kw.setdefault("prefill_buckets", (8,))
    kw.setdefault("dtype", "float32")
    return InferenceEngine(get_config("test-tiny"), EngineConfig(**kw), seed=0)


def _mock_pair(plan0=None, reply="hello chaos"):
    """Two scripted workers; worker 0 (the deterministic first routing
    choice — least-loaded ties break by index) carries the fault."""
    w0 = MockEngine([Scenario(".", reply)], fault_plan=plan0)
    w1 = MockEngine([Scenario(".", reply)])
    return w0, w1


class TestWorkerDeath:
    def test_pre_token_death_resubmits_transparently(self):
        """Zero tokens emitted → the coordinator may resubmit without
        any observable duplication: the caller sees one clean STOP."""
        plan = FaultPlan(die_after_tokens=0, die_count=1)
        w0, w1 = _mock_pair(plan)
        coord = EngineCoordinator([w0, w1])
        h = coord.submit(TOK.encode("hi"), SP)
        tokens, fin = _drain_events(h)
        assert fin.finish_reason == FinishReason.STOP
        assert TOK.decode(tokens) == "hello chaos"
        assert plan.fired["deaths"] == 1
        # Reconciliation: one routed request, one resubmit, no shed.
        assert coord.metrics["routed"] == 1
        assert coord.metrics["resubmits"] == 1 == plan.fired["deaths"]
        assert coord.metrics["shed"] == 0

    def test_mid_stream_death_surfaces_partial_error(self):
        """≥1 token delivered → resubmitting would silently duplicate
        the prefix: the ERROR surfaces with the exact partial count."""
        plan = FaultPlan(die_after_tokens=3, die_count=1)
        w0, w1 = _mock_pair(plan)
        coord = EngineCoordinator([w0, w1])
        h = coord.submit(TOK.encode("hi"), SP)
        tokens, fin = _drain_events(h)
        assert fin.finish_reason == FinishReason.ERROR
        assert len(tokens) == 3 == fin.num_generated_tokens
        assert coord.metrics["resubmits"] == 0
        assert coord.metrics["routed"] == 1

    def test_validation_error_never_resubmits_or_downs_a_worker(self):
        """A deterministic request rejection (zero-token ERROR with no
        accepted-prompt marker) must surface as-is: resubmitting would
        recur identically on every worker, and a malformed-request
        stream must never smear healthy workers' reputations."""
        w0, w1 = _mock_pair()
        coord = EngineCoordinator([w0, w1])
        tokens, fin = _drain_events(coord.submit([], SP))  # empty prompt
        assert fin.finish_reason == FinishReason.ERROR
        assert "empty prompt" in fin.error
        assert tokens == []
        assert coord.metrics["resubmits"] == 0
        assert coord._healthy_indices() == [0, 1]

    def test_resubmit_budget_is_bounded(self):
        """Every worker dying pre-token exhausts the resubmit budget
        and ends in ONE honest ERROR, not an infinite relocation loop."""
        w0 = MockEngine([Scenario(".", "x")],
                        fault_plan=FaultPlan(die_after_tokens=0, die_count=10))
        w1 = MockEngine([Scenario(".", "x")],
                        fault_plan=FaultPlan(die_after_tokens=0, die_count=10))
        coord = EngineCoordinator([w0, w1], resubmit_retries=1)
        h = coord.submit(TOK.encode("hi"), SP)
        tokens, fin = _drain_events(h)
        assert fin.finish_reason == FinishReason.ERROR
        assert tokens == []
        assert coord.metrics["resubmits"] == 1


class TestFlakySubmit:
    def test_submit_exception_fails_over_with_backoff(self):
        plan = FaultPlan(flaky_submit=1)
        w0, w1 = _mock_pair(plan)
        coord = EngineCoordinator([w0, w1])
        h = coord.submit(TOK.encode("hi"), SP)
        tokens, fin = _drain_events(h)
        assert fin.finish_reason == FinishReason.STOP
        assert TOK.decode(tokens) == "hello chaos"
        assert plan.fired["submit_faults"] == 1
        assert coord.metrics["failovers"] == 1
        assert coord.metrics["routed"] == 1

    def test_flaky_worker_reinstates_after_cooldown(self):
        """Hysteresis round-trip: the submit failure downs the worker,
        the cooldown holds it out, then it reinstates and serves."""
        plan = FaultPlan(flaky_submit=1)
        w0, w1 = _mock_pair(plan)
        coord = EngineCoordinator(
            [w0, w1], probe_interval_s=0.0, health_cooldown_s=0.05
        )
        h = coord.submit(TOK.encode("hi"), SP)
        # The failover happened synchronously inside submit: w0 is down
        # the moment the call returns, before any cooldown can elapse.
        assert coord._healthy_indices() == [1]
        _drain_events(h)
        deadline = time.monotonic() + 5
        while coord._healthy_indices() != [0, 1]:
            assert time.monotonic() < deadline, "worker never reinstated"
            time.sleep(0.01)

    def test_every_submit_failing_is_honest_error(self):
        w0 = MockEngine(fault_plan=FaultPlan(flaky_submit=100))
        coord = EngineCoordinator([w0], submit_retries=2)
        tokens, fin = _drain_events(coord.submit(TOK.encode("hi"), SP))
        # The failures mark the only worker down → honest no-workers
        # terminal (not a raise, not silence).
        assert fin.finish_reason == FinishReason.ERROR
        assert tokens == []


class TestHangOnDispatch:
    def test_engine_watchdog_trips_fails_handles_and_recovers(self):
        """The real scheduler path: a hung chunk sync trips the
        watchdog at the bound, in-flight handles fail, recovery
        reallocates device state, and the engine serves again."""
        eng = _tiny_engine(watchdog_s=0.15, decode_chunk=2)
        eng._fault_plan = FaultPlan(hang_dispatch_s=1.0, hang_count=1)
        eng.start()
        try:
            h = eng.submit([1, 2, 3], SamplingParams(temperature=0.0,
                                                     max_tokens=30))
            tokens, fin = _drain_events(h, timeout=20)
            assert fin.finish_reason == FinishReason.ERROR
            assert eng.metrics["watchdog_trips"] == 1
            assert eng.metrics["recoveries"] >= 1
            deadline = time.monotonic() + 5
            while not eng.healthy() and time.monotonic() < deadline:
                time.sleep(0.02)
            assert eng.healthy(), "engine did not recover after the trip"
            toks, fin = eng.submit(
                [1, 2, 3], SamplingParams(temperature=0.0, max_tokens=4)
            ).collect_tokens(timeout=30)
            assert fin.finish_reason == FinishReason.LENGTH and len(toks) == 4
            # Books balance across the incident: every accepted submit
            # reached exactly one finish (incl. the watchdog ERROR).
            assert (eng.metrics["requests_finished"]
                    == eng.metrics["requests_submitted"])
        finally:
            eng.stop()

    def test_mock_watchdog_parity_and_coordinator_resubmit(self):
        """A hung worker dispatch fails pre-token at the watchdog bound
        and the coordinator re-places the request elsewhere — client
        latency is bounded by watchdog_s + one resubmit, not the hang."""
        plan = FaultPlan(hang_dispatch_s=5.0, hang_count=1)
        w0 = MockEngine([Scenario(".", "ok")], fault_plan=plan,
                        watchdog_s=0.1)
        w1 = MockEngine([Scenario(".", "ok")])
        coord = EngineCoordinator([w0, w1])
        t0 = time.monotonic()
        tokens, fin = _drain_events(coord.submit(TOK.encode("hi"), SP))
        assert fin.finish_reason == FinishReason.STOP
        assert TOK.decode(tokens) == "ok"
        assert time.monotonic() - t0 < 3.0, "hang leaked into the client"
        assert w0.metrics["watchdog_trips"] == 1
        assert coord.metrics["resubmits"] == 1


class TestFullQueue:
    def test_engine_sheds_overloaded_beyond_max_queue(self):
        eng = _tiny_engine(max_queue=2)
        handles = [eng.submit([1, 2], GREEDY) for _ in range(4)]
        shed = [h for h in handles
                if not h._queue.empty()
                and h._queue.queue[0].finish_reason == FinishReason.OVERLOADED]
        assert len(shed) == 2
        assert eng.metrics["requests_shed"] == 2
        while eng.step():
            pass
        finals = [_drain_events(h)[1] for h in handles]
        reasons = sorted(f.finish_reason.value for f in finals)
        assert reasons == ["length", "length", "overloaded", "overloaded"]
        # Reconciliation: submitted == finished, shed is its own ledger.
        assert eng.metrics["requests_submitted"] == 2
        assert eng.metrics["requests_finished"] == 2

    def test_coordinator_sheds_before_routing_when_saturated(self):
        """Every healthy worker at the queue bound → OVERLOADED before
        any routing/affinity work happens."""
        w0 = MockEngine([Scenario(".", "slow reply here",
                                  delay_per_token_s=0.05)], max_queue=1)
        w1 = MockEngine([Scenario(".", "slow reply here",
                                  delay_per_token_s=0.05)], max_queue=1)
        coord = EngineCoordinator([w0, w1], max_worker_queue=1)
        h_a = coord.submit(TOK.encode("a"), SP)
        h_b = coord.submit(TOK.encode("b"), SP)
        deadline = time.monotonic() + 2
        while (w0.queue_depth() + w1.queue_depth()) < 2:
            assert time.monotonic() < deadline
            time.sleep(0.005)
        tokens, fin = _drain_events(coord.submit(TOK.encode("c"), SP))
        assert fin.finish_reason == FinishReason.OVERLOADED
        assert tokens == []
        assert coord.metrics["shed"] == 1
        assert coord.metrics["routed"] == 2
        for h in (h_a, h_b):
            _, fin = _drain_events(h)
            assert fin.finish_reason == FinishReason.STOP


class TestDeadlines:
    def test_deadline_in_queue_sheds_deterministically(self):
        """Injected logical clock: the queued request's TTL expires
        between steps → DEADLINE shed, zero tokens, books balanced."""
        eng = _tiny_engine(num_slots=1)
        clock = [0.0]
        eng.clock = lambda: clock[0]
        # Occupy the only slot so the deadlined request stays queued.
        h_busy = eng.submit([1, 2], SamplingParams(temperature=0.0,
                                                   max_tokens=40))
        h_late = eng.submit([3, 4], GREEDY, deadline_s=5.0)
        eng.step()  # places h_busy; h_late waits
        clock[0] = 10.0  # TTL expires while queued
        while eng.step():
            pass
        tokens, fin = _drain_events(h_late)
        assert fin.finish_reason == FinishReason.DEADLINE
        assert tokens == []
        _, fin_busy = _drain_events(h_busy)
        assert fin_busy.finish_reason == FinishReason.LENGTH
        assert eng.metrics["deadline_exceeded"] == 1
        assert (eng.metrics["requests_finished"]
                == eng.metrics["requests_submitted"] == 2)

    def test_deadline_mid_decode_finishes_early_with_partial(self):
        eng = _tiny_engine()
        clock = [0.0]
        eng.clock = lambda: clock[0]
        h = eng.submit([1, 2, 3], SamplingParams(temperature=0.0,
                                                 max_tokens=1000),
                       deadline_s=5.0)
        eng.step()  # prefill + first token
        eng.step()
        clock[0] = 10.0  # boundary passes mid-decode
        while eng.step():
            pass
        tokens, fin = _drain_events(h)
        assert fin.finish_reason == FinishReason.DEADLINE
        assert 1 <= len(tokens) < 1000
        assert fin.num_generated_tokens == len(tokens)
        assert eng.metrics["deadline_exceeded"] == 1

    def test_mock_deadline_mid_stream(self):
        w = MockEngine([Scenario(".", "0123456789" * 4,
                                 delay_per_token_s=0.02)])
        h = w.submit(TOK.encode("x"), SP, deadline_s=0.1)
        tokens, fin = _drain_events(h)
        assert fin.finish_reason == FinishReason.DEADLINE
        assert 0 < len(tokens) < 40
        assert fin.num_generated_tokens == len(tokens)
        assert w.metrics["deadline_exceeded"] == 1

    def test_coordinator_threads_deadline_to_worker(self):
        w = MockEngine([Scenario(".", "0123456789" * 4,
                                 delay_per_token_s=0.02)])
        coord = EngineCoordinator([w])
        tokens, fin = _drain_events(
            coord.submit(TOK.encode("x"), SP, deadline_s=0.1)
        )
        assert fin.finish_reason == FinishReason.DEADLINE
        assert w.metrics["deadline_exceeded"] == 1


class TestGracefulDrain:
    def test_drain_finishes_active_sheds_new_offloads_sessions(self):
        eng = _tiny_engine()
        eng.start()
        h = eng.submit([1, 2, 3], SamplingParams(temperature=0.0,
                                                 max_tokens=6),
                       session_id="drain-s")
        eng.stop(drain=True)
        tokens, fin = _drain_events(h)
        assert fin.finish_reason == FinishReason.LENGTH
        assert len(tokens) == 6
        # Admission is closed...
        _, fin2 = _drain_events(eng.submit([1, 2], GREEDY))
        assert fin2.finish_reason == FinishReason.OVERLOADED
        assert eng.metrics["requests_shed"] == 1
        # ...and the idle session's rows were paged to host.
        assert eng.metrics["session_offloads"] == 1
        assert eng._sessions["drain-s"].host_k is not None

    def test_drain_timeout_still_delivers_terminals(self):
        """A drain window that elapses with work outstanding must not
        strand clients: queued requests shed (OVERLOADED), the active
        slot fails with its partial count, books balance."""
        eng = _tiny_engine(num_slots=1, decode_chunk=1)
        eng.start()
        sp_long = SamplingParams(temperature=0.0, max_tokens=100_000)
        h_active = eng.submit(list(range(1, 9)), sp_long)
        h_queued = eng.submit(list(range(1, 9)), sp_long)
        deadline = time.monotonic() + 10
        while h_active.first_token_at is None:
            assert time.monotonic() < deadline, "request never started"
            time.sleep(0.01)
        eng.stop(drain=True, drain_timeout_s=0.05)
        toks_a, fin_a = _drain_events(h_active, timeout=20)
        assert fin_a.finish_reason == FinishReason.ERROR
        assert fin_a.num_generated_tokens == len(toks_a) >= 1
        toks_q, fin_q = _drain_events(h_queued, timeout=20)
        assert fin_q.finish_reason == FinishReason.OVERLOADED
        assert toks_q == []
        assert (eng.metrics["requests_finished"]
                == eng.metrics["requests_submitted"] == 2)

    def test_drain_wait_covers_mid_placement_under_lock(self):
        """ISSUE 9 lock-discipline regression: the drain wait reads the
        ``_placing`` claim in the SAME critical section as the queue
        (lifecycle._drain_work_left) — the pre-fix unlocked read could
        end the drain while a request sat mid-placement in neither
        ledger. Simulate a stuck placement claim and assert the drain
        genuinely waits for it, then closes admission."""
        import threading

        eng = _tiny_engine()
        with eng._lock:
            eng._placing += 1
        released_at = []

        def releaser():
            time.sleep(0.15)
            with eng._lock:
                eng._placing -= 1
            released_at.append(time.monotonic())

        threading.Thread(target=releaser, daemon=True).start()
        t0 = time.monotonic()
        eng.stop(drain=True, drain_timeout_s=5.0)
        assert released_at, "drain returned before the claim released"
        assert time.monotonic() - t0 >= 0.14
        # Draining flag was flipped under the lock; admission is closed.
        _, fin = _drain_events(eng.submit([1, 2], GREEDY))
        assert fin.finish_reason == FinishReason.OVERLOADED

    def test_restart_after_drain_reopens_admission(self):
        eng = _tiny_engine()
        eng.start()
        eng.stop(drain=True)
        eng.start()
        try:
            toks, fin = eng.submit([1, 2], GREEDY).collect_tokens(timeout=30)
            assert fin.finish_reason == FinishReason.LENGTH
        finally:
            eng.stop()


class TestLockstepReplication:
    def test_submit_event_carries_deadline_and_applies_it(self):
        """Deadline decisions replicate as events (like register_prefix):
        the TTL rides the submit event frame, and applying the event
        threads it into the engine's submit — so every rank anchors the
        same deadline to the same broadcast logical clock."""
        import json

        from omnia_tpu.engine.multihost import LockstepEngine

        inner = MockEngine([Scenario(".", "0123456789" * 4,
                                     delay_per_token_s=0.02)])
        lock = LockstepEngine(inner)
        h = lock.submit(TOK.encode("x"), SP, deadline_s=0.1)
        raws = lock._drain_pending()
        ev = json.loads(raws[0])
        assert ev["op"] == "submit" and ev["deadline_s"] == 0.1
        # Apply the event the way every rank's tick loop would; the
        # leader wrapper binds and the TTL reaps mid-stream.
        lock._apply(ev)
        tokens, fin = _drain_events(h)
        assert fin.finish_reason == FinishReason.DEADLINE
        assert inner.metrics["deadline_exceeded"] == 1
        assert fin.num_generated_tokens == len(tokens)


class TestReconciliation:
    def test_fault_battery_books_balance_exactly(self):
        """A battery across every mock-expressible fault: N submits in,
        N terminal events out, and the coordinator's routed/shed/
        resubmit/failover ledger explains every one of them."""
        plan = FaultPlan(die_after_tokens=0, die_count=2, flaky_submit=1)
        w0 = MockEngine([Scenario(".", "abc")], fault_plan=plan, max_queue=64)
        w1 = MockEngine([Scenario(".", "abc")], max_queue=64)
        coord = EngineCoordinator([w0, w1], max_worker_queue=64)
        finals = []
        for i in range(12):
            h = coord.submit(TOK.encode(f"r{i}"), SP,
                             session_id=f"sess-{i % 3}")
            finals.append(_drain_events(h)[1])
        assert len(finals) == 12  # exactly one terminal each
        clean = sum(f.finish_reason in (FinishReason.STOP,
                                        FinishReason.LENGTH) for f in finals)
        assert clean == 12  # every fault was absorbed: death resubmitted,
        # flaky submit failed over — the caller never saw one
        assert coord.metrics["routed"] == 12
        assert coord.metrics["shed"] == 0
        assert coord.metrics["resubmits"] == plan.fired["deaths"] == 2
        assert coord.metrics["failovers"] >= plan.fired["submit_faults"] == 1
        # Worker-side books also balance: every accepted submit finished.
        for w in (w0, w1):
            assert (w.metrics["requests_finished"]
                    == w.metrics["requests_submitted"])
