"""Tracing tests: span lifecycle, propagation, sampling, log correlation,
and the conversation/llm/tool span vocabulary on a real turn."""

from __future__ import annotations

import json
import logging

from omnia_tpu.utils import tracing as tr


class TestTracer:
    def test_span_nesting_and_context(self):
        t = tr.Tracer("svc")
        with t.start_span("parent") as parent:
            assert tr.current_span() is parent
            with t.start_span("child") as child:
                assert child.trace_id == parent.trace_id
                assert child.parent_id == parent.span_id
        assert tr.current_span() is None
        assert [s.name for s in t.spans()] == ["child", "parent"]

    def test_traceparent_roundtrip(self):
        t = tr.Tracer("a")
        span = t.start_span("root")
        header = span.traceparent()
        parsed = tr.parse_traceparent(header)
        assert parsed == (span.trace_id, span.span_id, True)
        t2 = tr.Tracer("b")
        remote = t2.start_span("remote-child", traceparent=header)
        assert remote.trace_id == span.trace_id
        assert remote.parent_id == span.span_id
        assert tr.parse_traceparent("garbage") is None

    def test_sampling_zero_exports_nothing(self):
        t = tr.Tracer("svc", sample_rate=0.0)
        with t.start_span("root"):
            pass
        assert t.spans() == []

    def test_children_follow_root_decision(self):
        t = tr.Tracer("svc", sample_rate=1.0)
        with t.start_span("root") as root:
            t.sample_rate = 0.0  # must not affect children of a sampled root
            with t.start_span("child") as child:
                assert child.trace_id == root.trace_id
        assert len(t.spans()) == 2

    def test_error_recording(self):
        t = tr.Tracer("svc")
        try:
            with t.start_span("boom"):
                raise ValueError("bad")
        except ValueError:
            pass
        s = t.spans("boom")[0]
        assert s.status == "error"
        assert s.attrs["error.message"] == "bad"

    def test_ntp_step_cannot_corrupt_duration(self, monkeypatch):
        """start_ns/end_ns come from the wall clock for cross-process
        timestamp correlation, but the DURATION must come from the
        monotonic clock: a backwards NTP step between start and end used
        to yield a negative span duration (end_ns < start_ns)."""
        import time as _time

        t = tr.Tracer("svc")
        span = t.start_span("stepped")
        # Simulate an NTP step: wall clock jumps 10 s into the past
        # while ~2 ms of real (monotonic) time elapses.
        real_time_ns = _time.time_ns
        monkeypatch.setattr(
            _time, "time_ns", lambda: real_time_ns() - 10_000_000_000
        )
        _time.sleep(0.002)
        span.end()
        assert span.end_ns >= span.start_ns
        dur = span.end_ns - span.start_ns
        assert 1_000_000 <= dur < 5_000_000_000  # ~2ms real, never -10s
        assert span.duration_ns() == dur

    def test_forward_wall_jump_does_not_inflate_duration(self, monkeypatch):
        import time as _time

        t = tr.Tracer("svc")
        span = t.start_span("jumped")
        real_time_ns = _time.time_ns
        monkeypatch.setattr(
            _time, "time_ns", lambda: real_time_ns() + 3_600_000_000_000
        )
        span.end()
        # A +1h wall jump must not become a 1h span.
        assert span.end_ns - span.start_ns < 1_000_000_000

    def test_jsonl_export(self, tmp_path):
        path = str(tmp_path / "spans.jsonl")
        t = tr.Tracer("svc", export_path=path)
        with t.start_span("exported", attrs={"k": "v"}):
            pass
        rows = [json.loads(l) for l in open(path)]
        assert rows[0]["name"] == "exported"
        assert rows[0]["attributes"]["k"] == "v"
        assert rows[0]["end_ns"] >= rows[0]["start_ns"]

    def test_log_correlation_filter(self, caplog):
        t = tr.Tracer("svc")
        logger = logging.getLogger("corr-test")
        logger.addFilter(tr.TraceContextFilter())
        try:
            with t.start_span("op") as span:
                with caplog.at_level(logging.INFO, logger="corr-test"):
                    logger.info("inside")
            assert caplog.records[0].trace_id == span.trace_id
            assert caplog.records[0].span_id == span.span_id
        finally:
            logger.filters.clear()


class TestTurnSpans:
    def test_conversation_llm_tool_spans_on_turn(self):
        from omnia_tpu.engine import MockEngine
        from omnia_tpu.engine.mock import Scenario
        from omnia_tpu.engine.tokenizer import ByteTokenizer
        from omnia_tpu.runtime import contract as c
        from omnia_tpu.runtime.context_store import InMemoryContextStore
        from omnia_tpu.runtime.conversation import Conversation
        from omnia_tpu.runtime.packs import load_pack
        from omnia_tpu.tools import ToolExecutor, ToolHandler

        tracer = tr.Tracer("runtime-test")
        tok = ByteTokenizer()
        scenarios = [
            Scenario(pattern=r"\[TOOL\]echoed", reply="tool done"),
            Scenario(pattern="use the tool",
                     reply='<tool_call>{"name": "echo", "arguments": {}}</tool_call>'),
        ]
        conv = Conversation(
            session_id="traced",
            pack=load_pack({"name": "t", "version": "1.0.0",
                            "prompts": {"system": "s"},
                            "tools": [{"name": "echo"}],
                            "sampling": {"max_tokens": 256}}),
            engine=MockEngine(scenarios, tokenizer=tok),
            tokenizer=tok,
            store=InMemoryContextStore(),
            tool_executor=ToolExecutor([ToolHandler(name="echo", fn=lambda a: "echoed")]),
            tracer=tracer,
        )
        # remote parent from the facade
        root = tr.Tracer("facade").start_span("ws-turn")
        conv.traceparent = root.traceparent()
        msgs = list(conv.stream(c.ClientMessage(content="use the tool please")))
        assert msgs[-1].type == "done"

        conv_spans = tracer.spans(tr.SPAN_CONVERSATION)
        llm_spans = tracer.spans(tr.SPAN_LLM)
        tool_spans = tracer.spans(tr.SPAN_TOOL)
        assert len(conv_spans) == 1
        assert len(llm_spans) == 2  # tool round + final round
        assert len(tool_spans) == 1
        # whole turn parents under the facade's trace
        assert conv_spans[0].trace_id == root.trace_id
        assert all(s.trace_id == root.trace_id for s in llm_spans + tool_spans)
        # llm spans carry TTFT + token metrics; tool span carries outcome
        assert llm_spans[0].attrs["llm.ttft_s"] >= 0
        assert llm_spans[0].attrs["llm.completion_tokens"] > 0
        assert tool_spans[0].attrs == {
            **tool_spans[0].attrs, "tool.name": "echo", "tool.is_error": False}
        # turn-level rollup on the conversation span
        assert conv_spans[0].attrs["llm.finish_reason"] == "stop"
        assert conv_spans[0].attrs["turn.index"] == 1


class TestSamplingPropagation:
    def test_children_of_unsampled_root_are_dropped(self):
        t = tr.Tracer("svc", sample_rate=0.0)
        with t.start_span("root"):
            with t.start_span("child"):
                with t.start_span("grandchild"):
                    pass
        assert t.spans() == []  # nothing leaks under the zero trace id

    def test_unsampled_remote_parent_honored(self):
        t = tr.Tracer("svc", sample_rate=1.0)
        root = t.start_span("root")
        unsampled = root.traceparent()[:-2] + "00"  # flags 00
        with t.start_span("remote-child", traceparent=unsampled):
            pass
        assert not t.spans("remote-child")
        root.end()


class TestOTLPExport:
    """OTLP/HTTP exporter (reference internal/tracing OTLP→Tempo): spans
    arrive at a collector in ExportTraceServiceRequest shape; a dead
    collector drops batches without stalling serving."""

    def _collector(self):
        import http.server
        import threading

        received = []

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_POST(self):
                n = int(self.headers.get("Content-Length") or 0)
                received.append((self.path, json.loads(self.rfile.read(n))))
                self.send_response(200)
                self.send_header("Content-Length", "2")
                self.end_headers()
                self.wfile.write(b"{}")

            def log_message(self, *a):
                pass

        httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        return httpd, received

    def test_spans_reach_collector_in_otlp_shape(self):
        from omnia_tpu.utils.tracing import OTLPExporter, Tracer

        httpd, received = self._collector()
        try:
            exporter = OTLPExporter(
                f"http://127.0.0.1:{httpd.server_address[1]}",
                flush_interval_s=60,  # flush manually
            )
            tracer = Tracer("runtime", otlp=exporter)
            with tracer.start_span("conversation", attrs={"turn": 3}) as parent:
                parent.add_llm_metrics(10, 5, ttft_s=0.1, cost_usd=0.01)
                with tracer.start_span("llm") as child:
                    child.add_event("first_token")
            exporter.flush()
            assert received, "no OTLP request arrived"
            path, doc = received[0]
            assert path == "/v1/traces"
            rs = doc["resourceSpans"][0]
            svc = rs["resource"]["attributes"][0]
            assert svc["key"] == "service.name"
            assert svc["value"]["stringValue"] == "runtime"
            spans = rs["scopeSpans"][0]["spans"]
            by_name = {s["name"]: s for s in spans}
            assert set(by_name) == {"conversation", "llm"}
            conv, llm = by_name["conversation"], by_name["llm"]
            assert llm["traceId"] == conv["traceId"]
            assert llm["parentSpanId"] == conv["spanId"]
            assert int(conv["endTimeUnixNano"]) >= int(conv["startTimeUnixNano"])
            attrs = {a["key"]: a["value"] for a in conv["attributes"]}
            assert attrs["llm.prompt_tokens"] == {"intValue": "10"}
            assert attrs["llm.cost_usd"] == {"doubleValue": 0.01}
            assert llm["events"][0]["name"] == "first_token"
            assert exporter.exported == 2
        finally:
            exporter.shutdown()
            httpd.shutdown()

    def test_dead_collector_drops_not_blocks(self):
        import time as _time

        from omnia_tpu.utils.tracing import OTLPExporter, Tracer

        exporter = OTLPExporter("http://127.0.0.1:1", flush_interval_s=60,
                                timeout_s=0.3)
        tracer = Tracer("runtime", otlp=exporter)
        t0 = _time.monotonic()
        for _ in range(20):
            with tracer.start_span("s"):
                pass
        assert _time.monotonic() - t0 < 1.0  # span path never blocks
        exporter.flush()
        assert exporter.dropped == 20
        assert exporter.exported == 0
        exporter.shutdown()
