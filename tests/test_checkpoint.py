"""Checkpoint loading: HF-layout safetensors → stacked serving pytree.

The gold tests build a *real* HuggingFace llama/mixtral (transformers,
torch CPU), save it with save_pretrained, load it through the production
loader, and require the forward passes to agree to float32 round-off —
proving the name mapping, transposes, RoPE convention, norm placement, and
MoE routing all match the ecosystem format the platform claims to serve.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from omnia_tpu.models import checkpoint as ck
from omnia_tpu.models import get_config, llama


def _tiny_hf_llama(tmp_path, tie=False):
    import torch
    from transformers import LlamaConfig, LlamaForCausalLM

    cfg = LlamaConfig(
        vocab_size=256, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        rope_theta=10000.0, rms_norm_eps=1e-5, tie_word_embeddings=tie,
        max_position_embeddings=128,
    )
    torch.manual_seed(0)
    model = LlamaForCausalLM(cfg).eval()
    model.save_pretrained(str(tmp_path), safe_serialization=True)
    return model


class TestHFEquivalence:
    def test_llama_logits_match_transformers(self, tmp_path):
        import torch

        model = _tiny_hf_llama(tmp_path)
        mcfg = ck.read_config(str(tmp_path))
        assert (mcfg.num_heads, mcfg.num_kv_heads, mcfg.head_dim) == (4, 2, 16)
        params = ck.load_params(str(tmp_path), mcfg, dtype=jnp.float32)
        toks = np.random.default_rng(0).integers(0, 256, (2, 12))
        with torch.no_grad():
            ref = model(torch.tensor(toks)).logits.numpy()
        mine = np.asarray(llama.forward_train(params, mcfg, jnp.asarray(toks)))
        np.testing.assert_allclose(mine, ref, atol=1e-5, rtol=1e-5)

    def test_llama31_rope_scaling_matches_transformers(self, tmp_path):
        """Llama 3.1/3.2 checkpoints ship rope_scaling rope_type='llama3';
        the frequency remap must match transformers exactly or long-context
        generations silently degrade."""
        import torch
        from transformers import LlamaConfig, LlamaForCausalLM

        cfg = LlamaConfig(
            vocab_size=256, hidden_size=64, intermediate_size=128,
            num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
            rope_theta=10000.0, rms_norm_eps=1e-5, tie_word_embeddings=False,
            max_position_embeddings=256,
            rope_scaling={
                "rope_type": "llama3", "factor": 8.0,
                "low_freq_factor": 1.0, "high_freq_factor": 4.0,
                "original_max_position_embeddings": 64,
            },
        )
        torch.manual_seed(0)
        model = LlamaForCausalLM(cfg).eval()
        model.save_pretrained(str(tmp_path), safe_serialization=True)
        mcfg = ck.read_config(str(tmp_path))
        assert mcfg.rope_scaling == (8.0, 1.0, 4.0, 64.0)
        params = ck.load_params(str(tmp_path), mcfg, dtype=jnp.float32)
        # Long positions (past original_max) are where the remap matters.
        toks = np.random.default_rng(1).integers(0, 256, (1, 96))
        with torch.no_grad():
            ref = model(torch.tensor(toks)).logits.numpy()
        mine = np.asarray(llama.forward_train(params, mcfg, jnp.asarray(toks)))
        np.testing.assert_allclose(mine, ref, atol=1e-4, rtol=1e-4)

    def test_unsupported_rope_scaling_raises(self):
        with pytest.raises(ck.CheckpointError, match="rope_scaling"):
            ck.hf_config_to_model({
                "num_attention_heads": 4, "hidden_size": 64, "vocab_size": 256,
                "num_hidden_layers": 2, "intermediate_size": 128,
                "rope_scaling": {"rope_type": "yarn", "factor": 4.0},
            })

    def test_unsupported_model_type_raises(self):
        with pytest.raises(ck.CheckpointError, match="model_type"):
            ck.hf_config_to_model({"model_type": "qwen2"})

    def test_mixtral_logits_match_transformers(self, tmp_path):
        import torch
        from transformers import MixtralConfig, MixtralForCausalLM

        cfg = MixtralConfig(
            vocab_size=256, hidden_size=64, intermediate_size=128,
            num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
            num_local_experts=4, num_experts_per_tok=2,
            rope_theta=10000.0, rms_norm_eps=1e-5, tie_word_embeddings=False,
            max_position_embeddings=128,
        )
        torch.manual_seed(0)
        model = MixtralForCausalLM(cfg).eval()
        model.save_pretrained(str(tmp_path), safe_serialization=True)

        mcfg = ck.read_config(str(tmp_path))
        assert mcfg.is_moe and mcfg.num_experts == 4
        params = ck.load_params(str(tmp_path), mcfg, dtype=jnp.float32)
        toks = np.random.default_rng(0).integers(0, 256, (2, 12))
        with torch.no_grad():
            ref = model(torch.tensor(toks)).logits.numpy()
        mine = np.asarray(llama.forward_train(params, mcfg, jnp.asarray(toks)))
        np.testing.assert_allclose(mine, ref, atol=1e-5, rtol=1e-5)


class TestRoundTrip:
    def _assert_trees_equal(self, a, b):
        flat_a = jax.tree_util.tree_leaves_with_path(a)
        flat_b = {jax.tree_util.keystr(k): v for k, v in jax.tree_util.tree_leaves_with_path(b)}
        for k, va in flat_a:
            key = jax.tree_util.keystr(k)
            vb = flat_b[key]
            np.testing.assert_array_equal(np.asarray(va), np.asarray(vb), err_msg=key)

    def test_dense_roundtrip(self, tmp_path):
        cfg = get_config("test-tiny")
        params = llama.init_params(cfg, jax.random.key(7), dtype=jnp.float32)
        ck.save_params(params, cfg, str(tmp_path))
        assert os.path.exists(tmp_path / "model.safetensors")
        loaded = ck.load_params(str(tmp_path), dtype=jnp.float32)
        self._assert_trees_equal(params, loaded)

    def test_moe_roundtrip_sharded_files(self, tmp_path):
        cfg = get_config("test-tiny-moe")
        params = llama.init_params(cfg, jax.random.key(3), dtype=jnp.float32)
        # Tiny shard budget → many files + index, exercising the index path.
        ck.save_params(params, cfg, str(tmp_path), max_shard_bytes=64 * 1024)
        assert os.path.exists(tmp_path / "model.safetensors.index.json")
        loaded = ck.load_params(str(tmp_path), dtype=jnp.float32)
        self._assert_trees_equal(params, loaded)
        # config round-trips too
        rcfg = ck.read_config(str(tmp_path))
        assert rcfg.num_experts == cfg.num_experts
        assert rcfg.ffn_hidden_size == cfg.ffn_hidden_size

    def test_bf16_load_dtype(self, tmp_path):
        cfg = get_config("test-tiny")
        params = llama.init_params(cfg, jax.random.key(7), dtype=jnp.float32)
        ck.save_params(params, cfg, str(tmp_path))
        loaded = ck.load_params(str(tmp_path), dtype=jnp.bfloat16)
        assert loaded["embed"].dtype == jnp.bfloat16


class TestShardedLoad:
    def test_mesh_load_matches_unsharded(self, tmp_path):
        from omnia_tpu.parallel import make_mesh

        cfg = get_config("test-tiny")
        params = llama.init_params(cfg, jax.random.key(5), dtype=jnp.float32)
        ck.save_params(params, cfg, str(tmp_path))
        mesh = make_mesh(dp=2, tp=2, devices=jax.devices()[:4])
        sharded = ck.load_params(str(tmp_path), dtype=jnp.float32, mesh=mesh)
        # Placement carries the param_specs sharding…
        assert sharded["embed"].sharding.mesh == mesh
        # …and gathered values equal the unsharded load.
        plain = ck.load_params(str(tmp_path), dtype=jnp.float32)
        np.testing.assert_array_equal(
            np.asarray(sharded["layers"]["attn"]["wq"]),
            np.asarray(plain["layers"]["attn"]["wq"]),
        )
        toks = np.random.default_rng(0).integers(0, 256, (2, 8))
        a = np.asarray(llama.forward_train(sharded, cfg, jnp.asarray(toks)))
        b = np.asarray(llama.forward_train(plain, cfg, jnp.asarray(toks)))
        np.testing.assert_allclose(a, b, atol=2e-5, rtol=2e-5)


class TestErrors:
    def test_missing_dir(self, tmp_path):
        with pytest.raises(ck.CheckpointError, match="config.json"):
            ck.read_config(str(tmp_path / "nope"))

    def test_missing_tensor(self, tmp_path):
        cfg = get_config("test-tiny")
        params = llama.init_params(cfg, jax.random.key(0), dtype=jnp.float32)
        ck.save_params(params, cfg, str(tmp_path))
        # Claim one more layer than the checkpoint has.
        import dataclasses

        bigger = dataclasses.replace(cfg, num_layers=3)
        with pytest.raises(ck.CheckpointError, match="not in checkpoint"):
            ck.load_params(str(tmp_path), bigger, dtype=jnp.float32)

    def test_shape_mismatch(self, tmp_path):
        cfg = get_config("test-tiny")
        params = llama.init_params(cfg, jax.random.key(0), dtype=jnp.float32)
        ck.save_params(params, cfg, str(tmp_path))
        import dataclasses

        wider = dataclasses.replace(cfg, hidden_size=128)
        with pytest.raises(ck.CheckpointError, match="shape"):
            ck.load_params(str(tmp_path), wider, dtype=jnp.float32)

    def test_config_missing_field(self):
        with pytest.raises(ck.CheckpointError, match="missing required field"):
            ck.hf_config_to_model({"hidden_size": 64})

    def test_lm_head_fallback_ties_to_embed(self, tmp_path):
        """Checkpoints that omit lm_head (implicit tying) still load."""
        cfg = get_config("test-tiny")
        params = llama.init_params(cfg, jax.random.key(0), dtype=jnp.float32)
        ck.save_params(params, cfg, str(tmp_path))
        # Rewrite without lm_head.
        from safetensors import safe_open
        from safetensors.numpy import save_file

        f = str(tmp_path / "model.safetensors")
        with safe_open(f, framework="np") as h:
            tensors = {k: h.get_tensor(k) for k in h.keys() if k != "lm_head.weight"}
        save_file(tensors, f)
        loaded = ck.load_params(str(tmp_path), cfg, dtype=jnp.float32)
        np.testing.assert_array_equal(
            np.asarray(loaded["lm_head"]), np.asarray(loaded["embed"]).T
        )


class TestProviderWiring:
    def test_build_engine_from_checkpoint(self, tmp_path):
        from omnia_tpu.engine import SamplingParams
        from omnia_tpu.runtime.providers import ProviderSpec, build_engine

        cfg = get_config("test-tiny")
        params = llama.init_params(cfg, jax.random.key(11), dtype=jnp.float32)
        ck.save_params(params, cfg, str(tmp_path))
        spec = ProviderSpec(
            name="real", type="tpu", model="tiny-ckpt",
            options={
                "checkpoint_path": str(tmp_path),
                "num_slots": 2, "max_seq": 64, "prefill_buckets": [32],
                "dtype": "float32",
            },
        )
        engine = build_engine(spec)
        np.testing.assert_array_equal(
            np.asarray(engine.params["embed"]), np.asarray(params["embed"])
        )
        engine.warmup()
        engine.start()
        try:
            toks, reason = engine.generate(
                [1, 2, 3], SamplingParams(temperature=0.0, max_tokens=4)
            )
            assert len(toks) >= 1
        finally:
            engine.stop()

    def test_tokenizer_from_checkpoint_dir(self, tmp_path):
        from tokenizers import Tokenizer
        from tokenizers.models import WordLevel
        from tokenizers.pre_tokenizers import Whitespace

        from omnia_tpu.runtime.providers import ProviderSpec, build_tokenizer

        vocab = {"[UNK]": 0, "<s>": 1, "</s>": 2, "hello": 3, "world": 4}
        t = Tokenizer(WordLevel(vocab, unk_token="[UNK]"))
        t.pre_tokenizer = Whitespace()
        t.save(str(tmp_path / "tokenizer.json"))
        with open(tmp_path / "tokenizer_config.json", "w") as f:
            json.dump(
                {"tokenizer_class": "PreTrainedTokenizerFast",
                 "bos_token": "<s>", "eos_token": "</s>", "unk_token": "[UNK]"},
                f,
            )
        spec = ProviderSpec(
            name="p", type="tpu", options={"checkpoint_path": str(tmp_path)}
        )
        tok = build_tokenizer(spec)
        assert tok.encode("hello world", add_bos=False) == [3, 4]
        assert tok.bos_id == 1 and tok.eos_id == 2

    def test_byte_tokenizer_when_no_files(self, tmp_path):
        from omnia_tpu.engine.tokenizer import ByteTokenizer
        from omnia_tpu.runtime.providers import ProviderSpec, build_tokenizer

        spec = ProviderSpec(
            name="p", type="tpu", options={"checkpoint_path": str(tmp_path)}
        )
        assert isinstance(build_tokenizer(spec), ByteTokenizer)
