"""Test bootstrap: force an 8-device virtual CPU platform.

Two subtleties of this environment:

- The axon TPU plugin registers itself from sitecustomize at interpreter
  start, so jax may already be imported before this file runs. Backend
  *creation* is lazy though, so ``jax.config.update("jax_platforms", ...)``
  still wins as long as no backend has been touched yet — env vars alone
  are NOT sufficient here.
- ``xla_force_host_platform_device_count`` is read from XLA_FLAGS when the
  CPU client is created, which is also lazy — setting it here works.

Mirrors the reference's clusterless testing stance (SURVEY.md §4: the
reference tests distributed topology without a cluster via a file-backed
fake); multi-chip sharding is tested without TPUs via virtual host devices.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session", autouse=True)
def _assert_cpu_platform():
    devs = jax.devices()
    assert devs[0].platform == "cpu", f"tests must run on CPU, got {devs[0]}"
    yield


@pytest.fixture(scope="session")
def devices8():
    devs = jax.devices()
    assert len(devs) >= 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs
