"""Device-resident decode loop suite (ISSUE 17): the chunk drainer,
the ring self-gate, the deadline-step conversion, the mock's ring
mirror, and the ring-on-vs-off equivalence battery.

Module top is jax-free by design: the validate/drainer/gate/state
units and the MockEngine ring-mirror battery all run under the CI
analysis job's poisoned jax stub (``pytest -m devloop --noconftest``);
the engine-backed equivalence battery importorskips jax.
"""

from __future__ import annotations

import threading
import time
from types import SimpleNamespace

import pytest

try:  # the CI analysis job runs the jax-free subset on a bare venv
    import numpy as np
except ImportError:  # pragma: no cover - CI analysis job only
    np = None

from omnia_tpu.engine.devloop import (
    ChunkDrainer,
    DevLoopState,
    RingGate,
    _InflightChunk,
    validate_decode_ring,
)
from omnia_tpu.engine.mock import MockEngine, Scenario
from omnia_tpu.engine.types import FinishReason, SamplingParams

pytestmark = pytest.mark.devloop


# ---------------------------------------------------------------------------
# validate_decode_ring (jax-free)
# ---------------------------------------------------------------------------


class TestValidate:
    @pytest.mark.parametrize("ring", [0, 2, 3, 8])
    def test_servable_values_pass(self, ring):
        validate_decode_ring(SimpleNamespace(decode_ring=ring))

    def test_negative_rejected(self):
        with pytest.raises(ValueError, match="must be >= 0"):
            validate_decode_ring(SimpleNamespace(decode_ring=-1))

    def test_one_deep_ring_rejected(self):
        """ring=1 can never overlap a drain with the next dispatch —
        a misconfiguration, not a degraded mode."""
        with pytest.raises(ValueError, match="one-deep ring"):
            validate_decode_ring(SimpleNamespace(decode_ring=1))

    def test_knobless_config_is_off(self):
        validate_decode_ring(SimpleNamespace())  # duck-typed: absent = 0


# ---------------------------------------------------------------------------
# ChunkDrainer (jax-free)
# ---------------------------------------------------------------------------


class _Boom:
    """An array-like whose readback dies (a donated buffer freed by
    recovery while the drainer was still reading)."""

    def __array__(self, dtype=None, copy=None):
        raise RuntimeError("buffer deleted")


class TestChunkDrainer:
    @pytest.fixture(autouse=True)
    def _needs_numpy(self):
        # The drain IS the numpy readback; on the bare CI venv these
        # skip while the gate/state/mock units still run.
        pytest.importorskip("numpy")

    def test_drain_returns_host_array_fifo(self):
        d = ChunkDrainer()
        try:
            entries = [d.submit([i, i + 1]) for i in range(3)]
            outs = [d.wait(e, timeout=5) for e in entries]
            for i, out in enumerate(outs):
                assert isinstance(out, np.ndarray)
                assert out.tolist() == [i, i + 1]
            drains, drain_s = d.stats()
            assert drains == 3 and drain_s >= 0.0
            assert not d.poisoned
        finally:
            d.stop()
        assert not d._thread.is_alive()

    def test_readback_exception_parked_and_reraised(self):
        d = ChunkDrainer()
        try:
            bad = d.submit(_Boom())
            with pytest.raises(RuntimeError, match="buffer deleted"):
                d.wait(bad, timeout=5)
            # The drainer itself survives a dead buffer: next entry drains.
            good = d.wait(d.submit([7]), timeout=5)
            assert good.tolist() == [7]
        finally:
            d.stop()

    def test_timeout_poisons(self):
        d = ChunkDrainer()
        entry = d.submit([1], pre_sleep_s=0.5)
        assert d.wait(entry, timeout=0.01) is None
        assert d.poisoned
        # stop() must not block on the wedged thread.
        t0 = time.monotonic()
        d.stop()
        assert time.monotonic() - t0 < 0.4

    def test_on_drained_runs_on_drainer_thread(self):
        d = ChunkDrainer()
        seen = {}
        fired = threading.Event()

        def cb(arr, took):
            seen["arr"] = arr
            seen["took"] = took
            seen["thread"] = threading.current_thread().name
            fired.set()

        try:
            d.wait(d.submit([1, 2], on_drained=cb), timeout=5)
            assert fired.wait(5)
            assert seen["arr"].tolist() == [1, 2]
            assert seen["took"] >= 0.0
            assert seen["thread"] == "omnia-chunk-drainer"
        finally:
            d.stop()

    def test_callback_exception_does_not_kill_drainer(self):
        d = ChunkDrainer()
        try:
            d.wait(d.submit([1], on_drained=lambda a, t: 1 / 0), timeout=5)
            assert d.wait(d.submit([2]), timeout=5).tolist() == [2]
        finally:
            d.stop()

    def test_fault_pre_sleep_is_timed(self):
        """Injected hang rides the drain wall (watchdog/chaos parity)."""
        d = ChunkDrainer()
        try:
            d.wait(d.submit([1], pre_sleep_s=0.05), timeout=5)
            _, drain_s = d.stats()
            assert drain_s >= 0.05
        finally:
            d.stop()


# ---------------------------------------------------------------------------
# RingGate (jax-free) — the spec-decode _SpecGate state machine
# ---------------------------------------------------------------------------


class TestRingGate:
    def test_probe_cycle_keeps_faster_async(self):
        g = RingGate(window=2, hold_factor=2)
        assert g.state == RingGate.PROBE_ASYNC and g.allows_async()
        # Async probe: 100 tok/s realized.
        g.tick(0.0, 0)
        g.tick(1.0, 100)
        assert g.state == RingGate.PROBE_SYNC and not g.allows_async()
        # Sync probe: 10 tok/s — async wins, hold on.
        g.tick(2.0, 110)
        g.tick(3.0, 120)
        assert g.state == RingGate.HOLD_ON and g.allows_async()
        assert g.state_code() == 1
        assert g.decisions == 1 and g.disables == 0
        rep = g.report()
        assert rep["state"] == "on"
        assert rep["rate_async_tok_s"] == 100.0
        assert rep["rate_sync_tok_s"] == 10.0

    def test_slower_async_is_disabled(self):
        g = RingGate(window=2, hold_factor=2)
        g.tick(0.0, 0)
        g.tick(1.0, 10)     # async: 10 tok/s
        g.tick(2.0, 60)
        g.tick(3.0, 160)    # sync: 100 tok/s — ring does not pay
        assert g.state == RingGate.HOLD_OFF and not g.allows_async()
        assert g.state_code() == 2
        assert g.disables == 1
        assert g.report()["state"] == "off"

    def test_hold_expiry_reprobes(self):
        g = RingGate(window=1, hold_factor=2)
        g.tick(0.0, 0)      # async probe ends (rate 0 over zero time)
        g.tick(1.0, 0)      # sync probe: rate 0 — tie keeps async on
        assert g.state == RingGate.HOLD_ON
        g.tick(2.0, 50)
        g.tick(3.0, 100)    # hold (window*factor=2 ticks) expires
        assert g.state == RingGate.PROBE_ASYNC
        assert g.rate_async == 50.0  # hold refreshed the async rate

    def test_window_zero_always_allows(self):
        g = RingGate(window=0)
        for i in range(10):
            assert g.tick(float(i), i * 5)
        assert g.state_code() == 0


# ---------------------------------------------------------------------------
# DevLoopState + _InflightChunk (jax-free)
# ---------------------------------------------------------------------------


class TestDevLoopState:
    def test_ring_off_builds_nothing(self):
        st = DevLoopState(0)
        assert st.capacity == 0 and st.gate is None
        assert not st.async_engaged(wall_clock=True)
        assert not st.async_engaged(wall_clock=False)
        assert st.drainer_if_live() is None
        st.stop()  # no drainer ever built — a no-op

    def test_ring_on_capacity_and_gate(self):
        st = DevLoopState(3)
        assert st.capacity == 3 and isinstance(st.gate, RingGate)
        assert st.async_engaged(wall_clock=True)
        # Lockstep engines (injected logical clock) keep async drain
        # unconditionally — the gate's wall-clock decision never binds.
        st.gate.state = RingGate.HOLD_OFF
        assert not st.async_engaged(wall_clock=True)
        assert st.async_engaged(wall_clock=False)
        st.stop()

    def test_gateless_ring(self):
        st = DevLoopState(2, gate=False)
        assert st.gate is None and st.async_engaged(wall_clock=True)
        st.stop()

    def test_drainer_lazy_and_poison_replacement(self):
        st = DevLoopState(2)
        assert st.drainer_if_live() is None  # lazy: nothing until first use
        d1 = st.get_drainer()
        assert st.get_drainer() is d1
        d1.poisoned = True
        assert st.drainer_if_live() is None
        d2 = st.get_drainer()  # recovery lane: fresh thread
        assert d2 is not d1 and not d2.poisoned
        st.stop()
        assert st._drainer is None

    def test_step_ema(self):
        st = DevLoopState(2)
        before = st.step_ema_s
        for _ in range(50):
            st.observe_step_time(1.0)
        assert abs(st.step_ema_s - 1.0) < 1e-3 and st.step_ema_s != before
        st.stop()

    def test_inflight_chunk_fields(self):
        ch = _InflightChunk("toks", [(0, "r0")], 0.25)
        assert ch.dl_steps is None and ch.entry is None
        assert ch.toks == "toks" and ch.dispatch_s == 0.25
        assert not hasattr(ch, "__dict__")  # __slots__: pipeline entry


# ---------------------------------------------------------------------------
# MockEngine ring mirror (jax-free)
# ---------------------------------------------------------------------------


REPLY = "devloop-reply!"  # 14 tokens under the byte tokenizer


class TestMockRingMirror:
    def test_mock_rejects_one_deep_ring(self):
        with pytest.raises(ValueError, match="one-deep ring"):
            MockEngine(decode_ring=1)

    def test_mock_ring_ledger(self):
        m = MockEngine([Scenario(".", REPLY)], decode_ring=4)
        toks, fin = m.generate(m.tokenizer.encode("hi"))
        assert m.tokenizer.decode(toks) == REPLY
        assert fin.finish_reason is FinishReason.STOP
        assert m.metrics["decode_ring_enabled"] == 1
        # ceil(14 / 4) chunk-strides drained, gate engaged, no stalls.
        assert m.metrics["ring_drains"] == 4
        assert m.metrics["decode_ring_gate_state"] == 1
        assert m.metrics["ring_full_stalls"] == 0
        assert m.metrics["early_exit_steps"] == 0

    def test_mock_decode_ring_off_is_true_noop(self):
        """KNOB_GUARDS target (MockEngine.decode_ring): the default books
        zero ring state and playback is byte-identical to a ring mock."""
        off = MockEngine([Scenario(".", REPLY)])
        on = MockEngine([Scenario(".", REPLY)], decode_ring=2)
        prompt = off.tokenizer.encode("hi")
        t_off, _ = off.generate(prompt)
        t_on, _ = on.generate(prompt)
        assert t_off == t_on
        assert off.decode_ring == 0
        for key in ("decode_ring_enabled", "ring_drains",
                    "ring_full_stalls", "early_exit_steps",
                    "decode_ring_gate_state"):
            assert off.metrics[key] == 0, (key, off.metrics[key])


# ---------------------------------------------------------------------------
# Aggregator devloop gate (jax-free) — bench aux.devloop → ArenaJob verdict
# ---------------------------------------------------------------------------


class TestAggregatorDevloopGate:
    def _agg(self):
        from omnia_tpu.evals.aggregator import Aggregator

        return Aggregator()

    def test_silent_regression_fails_the_bound(self):
        from omnia_tpu.evals.defs import Threshold

        agg = self._agg()
        assert not agg.add_devloop({"error": "boom"})  # errored phase folds nothing
        assert agg.add_devloop({
            "ratio_on_vs_off": 0.9, "gate": {"state": "on"},
            "paying": False, "regression": True,
        })
        verdict = agg.evaluate(Threshold(min_devloop_ratio=0.95))
        assert not verdict["passed"]
        assert "devloop/bench" in verdict["failures"][0]
        assert "0.900" in verdict["failures"][0]
        assert verdict["devloop"][0]["regression"] is True

    def test_reported_gate_disable_clears_the_bound(self):
        from omnia_tpu.evals.defs import Threshold

        agg = self._agg()
        assert agg.add_devloop({
            "ratio_on_vs_off": 0.7, "gate": {"state": "off"},
            "paying": True, "regression": False,
        })
        verdict = agg.evaluate(Threshold(min_devloop_ratio=0.95))
        assert verdict["passed"] and verdict["devloop"][0]["gate_disabled"]

    def test_unset_bound_and_unfolded_jobs_never_engage(self):
        from omnia_tpu.evals.defs import Threshold

        agg = self._agg()
        agg.add_devloop({"ratio_on_vs_off": 0.5, "gate": None})
        assert agg.evaluate(Threshold())["passed"]  # no bound set
        clean = self._agg().evaluate(Threshold(min_devloop_ratio=0.95))
        assert clean["passed"] and "devloop" not in clean  # nothing folded

    def test_threshold_schema_row(self):
        from omnia_tpu.evals.defs import ArenaJobSpec

        spec = ArenaJobSpec.from_dict({
            "name": "perf", "providers": ["p"],
            "threshold": {"min_devloop_ratio": 0.97},
        })
        assert spec.threshold.min_devloop_ratio == 0.97


# ---------------------------------------------------------------------------
# Engine-backed equivalence battery (skips without jax)
# ---------------------------------------------------------------------------


def _engine(**kw):
    pytest.importorskip("jax")
    from omnia_tpu.engine.engine import InferenceEngine
    from omnia_tpu.engine.types import EngineConfig
    from omnia_tpu.models import get_config

    seed = kw.pop("seed", 0)
    base = dict(num_slots=2, max_seq=64, prefill_buckets=(8,),
                dtype="float32", max_sessions=0)
    base.update(kw)
    return InferenceEngine(get_config("test-tiny"), EngineConfig(**base),
                           seed=seed)


GREEDY = SamplingParams(temperature=0.0, max_tokens=12)


def _drive(eng, *handles, timeout=60):
    deadline = time.monotonic() + timeout
    out = []
    while eng.step():
        assert time.monotonic() < deadline
    for h in handles:
        out.append(h.collect_tokens(timeout=timeout))
    return out


def test_decode_ring_off_is_true_noop():
    """KNOB_GUARDS target (EngineConfig.decode_ring): decode_ring=0
    allocates ZERO ring state — no devloop container, no drainer
    thread, no per-slot grammar-EOS array — and the compiled decode
    program carries the exact pre-ring operands (the 12-argument
    signature lowers; byte-identical whether or not the host-side
    watchdog, which shares the drainer implementation, is on)."""
    off = _engine()
    wd = _engine(watchdog_s=30.0)
    assert off._devloop is None and off._geos is None
    assert off.cfg.decode_ring == 0

    def lowered(eng):
        return eng._decode_fn_single.lower(
            eng.params, eng._ck, eng._cv, eng._tokens, eng._positions,
            eng._active, eng._budget, eng._stop_ids, eng._key_data,
            eng._temp, eng._top_p, eng._top_k,
        ).as_text()

    # The watchdog engine owns devloop state (its drainer) but traces
    # the identical ring-free program.
    assert wd._devloop is not None and wd._devloop.ring == 0
    assert lowered(off) == lowered(wd)

    toks, fin = off.generate([1, 2, 3], GREEDY)
    assert toks and fin.finish_reason is not None
    for key in ("ring_drains", "ring_full_stalls", "early_exit_steps",
                "decode_ring_gate_state", "decode_ring_enabled"):
        assert off.metrics[key] == 0, (key, off.metrics[key])
    wd.stop()


def test_ring_one_rejected_at_construction():
    with pytest.raises(ValueError, match="one-deep ring"):
        _engine(decode_ring=1)


def test_ring_greedy_equivalence_and_resident_kv():
    """Ring on vs off: bit-identical greedy streams AND bit-identical
    valid resident KV rows for a sessionful turn (the ring early-out
    may skip frozen-slot garbage writes, so only rows below the
    session's valid frontier are comparable — exactly the rows any
    later turn can read)."""
    prompt = [1, 2, 3, 4]
    results = []
    for ring in (0, 2):
        eng = _engine(decode_ring=ring, max_sessions=4)
        h = eng.submit(prompt, GREEDY, session_id="s")
        (res,) = _drive(eng, h)
        rows = len(eng._sessions["s"].token_ids)
        assert rows > 0
        ck = np.asarray(eng._ck)[:, 0, :rows]
        cv = np.asarray(eng._cv)[:, 0, :rows]
        results.append((res, rows, ck, cv))
        if ring:
            assert eng.metrics["decode_ring_enabled"] == 1
            assert eng.metrics["ring_drains"] > 0
            eng.stop()
    (t0, r0, ck0, cv0), (t1, r1, ck1, cv1) = results
    assert t0 == t1 and r0 == r1
    np.testing.assert_array_equal(ck0, ck1)
    np.testing.assert_array_equal(cv0, cv1)


@pytest.mark.parametrize("extra", [
    pytest.param({"kv_quant": "int8"}, id="int8-kv"),
    pytest.param({"kv_pages": 9, "kv_page_tokens": 8}, id="paged"),
    pytest.param({"spec_decode": 2}, id="spec"),
    pytest.param({"prefill_chunk_tokens": 4}, id="interleave"),
])
def test_ring_equivalence_with_cotenant(extra):
    """Ring on vs off under each major engine feature, with TWO live
    requests so chunks carry multi-slot snapshots (spec-decode and
    mixed interleave steps must ride the same ring unchanged)."""
    pa, pb = [1, 2, 3], [9, 8, 7, 6]
    streams = []
    for ring in (0, 2):
        eng = _engine(decode_ring=ring, **extra)
        ha = eng.submit(pa, GREEDY)
        hb = eng.submit(pb, GREEDY)
        streams.append([t for t, _ in _drive(eng, ha, hb)])
        eng.stop()
    assert streams[0] == streams[1]


def test_ring_grammar_equivalence_and_inscan_eos():
    """Grammar-constrained ring decode: identical constrained streams,
    and the ring engine carries the per-slot grammar-EOS ids so the
    scan can freeze a completed grammar slot in-scan."""
    pytest.importorskip("jax")
    from omnia_tpu.engine.grammar import compile_json_schema
    from omnia_tpu.engine.tokenizer import ByteTokenizer
    from omnia_tpu.models import get_config

    schema = {"type": "object",
              "properties": {"a": {"type": "integer"}},
              "required": ["a"]}
    g = compile_json_schema(schema, ByteTokenizer())
    sp = SamplingParams(temperature=0.0, max_tokens=40, stop_token_ids=(0,))
    streams = []
    for ring in (0, 2):
        eng = _engine(decode_ring=ring, num_slots=4, max_seq=128,
                      prefill_buckets=(8, 16, 32), grammar=True,
                      grammar_max_states=512)
        if ring:
            assert eng._geos is not None
        else:
            assert eng._geos is None
        h = eng.submit(list(b"make json"), sp, grammar=g)
        streams.append(_drive(eng, h)[0][0])
        eng.stop()
    assert streams[0] == streams[1]
    v = g.view(get_config("test-tiny").vocab_size, (0,))
    s = v.start
    for t in streams[0]:
        assert v.allowed(s)[t]
        s = v.advance(s, t)


def test_mid_scan_deadline_exact_partial_counts():
    """The in-scan deadline-step budget: a slot whose wall budget
    converts to 1 step emits exactly one in-chunk token and finishes
    DEADLINE at the same step the device masked it — streamed tokens
    == num_generated, and the chunk's remaining steps are booked as
    early-exit savings."""
    eng = _engine(decode_ring=2)
    # Force the deadline→steps conversion to 1 step without the
    # boundary reap ever firing: a far-future wall deadline against a
    # huge per-step EMA.
    eng._devloop.step_ema_s = 1e4
    h = eng.submit([1, 2, 3], SamplingParams(temperature=0.0, max_tokens=32),
                   deadline_s=60.0)
    ((toks, fin),) = _drive(eng, h)
    assert fin.finish_reason is FinishReason.DEADLINE
    assert len(toks) == fin.num_generated_tokens
    # Prefill's first token + exactly one in-scan step before the mask.
    assert fin.num_generated_tokens == 2
    assert eng.metrics["deadline_exceeded"] == 1
    assert eng.metrics["early_exit_steps"] > 0


def test_cancel_mid_ring_exact_partial_counts():
    """A cancel landing while ring chunks are in flight: the terminal
    carries exactly the streamed token count (no token from a stale
    drained chunk leaks past the terminal)."""
    eng = _engine(decode_ring=2)
    h = eng.submit([1, 2, 3], SamplingParams(temperature=0.0, max_tokens=48))
    for _ in range(3):
        eng.step()
    h.cancel()
    while eng.step():
        pass
    toks, fin = h.collect_tokens(timeout=30)
    assert fin.finish_reason is FinishReason.CANCELLED
    assert len(toks) == fin.num_generated_tokens


def test_ring_watchdog_trip_poisons_drainer_and_recovers():
    """An injected hang on the drainer thread trips the watchdog at
    the bound, poisons the drainer, and recovery rebuilds device state
    plus a FRESH drainer lane — the engine serves again."""
    from omnia_tpu.engine.faults import FaultPlan

    plan = FaultPlan(hang_dispatch_s=30.0, hang_count=1)
    eng = _engine(decode_ring=2, watchdog_s=0.2)
    eng._fault_plan = plan
    h = eng.submit([1, 2, 3], GREEDY)
    from omnia_tpu.engine.faults import WatchdogTimeout

    with pytest.raises(WatchdogTimeout):
        while eng.step():
            pass
    assert eng.metrics["watchdog_trips"] == 1
    poisoned = eng._devloop._drainer
    assert poisoned is not None and poisoned.poisoned
    eng._recover("watchdog tripped")
    assert eng.healthy() and eng.metrics["recoveries"] == 1
    _toks, fin = h.collect_tokens(timeout=30)
    assert fin.finish_reason is FinishReason.ERROR
    # Post-recovery service on a fresh drainer lane.
    toks2, fin2 = eng.generate([4, 5, 6], GREEDY)
    assert toks2 and fin2.finish_reason is not None
    assert eng._devloop._drainer is not poisoned
    eng.stop()


def test_ring_drain_stop_with_inflight_chunks():
    """stop(drain=True) with a half-drained ring: every in-flight
    chunk's tokens are surfaced (the stream terminal arrives), and the
    drainer thread is joined."""
    eng = _engine(decode_ring=2)
    h = eng.submit([1, 2, 3], SamplingParams(temperature=0.0, max_tokens=48))
    for _ in range(4):
        eng.step()
    assert eng._inflight  # chunks genuinely in flight mid-drain
    eng.stop(drain=True)
    d = eng._devloop._drainer
    assert d is None  # stop() joined and cleared the drainer
    toks, fin = h.collect_tokens(timeout=5)
    assert fin.finish_reason is not None
    assert len(toks) == fin.num_generated_tokens


def test_ring_full_stall_books_and_preserves_stream():
    """A pipeline held past the ring's undrained-chunk capacity books
    ring_full_stalls and processes the oldest chunk first — tokens
    still arrive exactly once, in order."""
    eng = _engine(decode_ring=2, decode_pipeline=4)
    off = _engine(decode_pipeline=4)
    sp = SamplingParams(temperature=0.0, max_tokens=24)
    (t_on,) = _drive(eng, eng.submit([5, 6, 7], sp))
    (t_off,) = _drive(off, off.submit([5, 6, 7], sp))
    assert t_on[0] == t_off[0]
    # decode_pipeline=4 wants 4 undrained chunks; capacity 2 stalls it.
    assert eng.metrics["ring_full_stalls"] > 0
    eng.stop()
