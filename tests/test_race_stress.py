"""Concurrency stress harness — the Python analog of the reference's
`go test -race` CI discipline (SURVEY §5.2): hammer the hot shared
structures from many threads and assert invariants hold. CPython won't
flag data races by itself, so these tests are written to DETECT their
symptoms: lost updates, double-finishes, cross-session leaks, deadlocks
(every wait is bounded)."""

from __future__ import annotations

import concurrent.futures
import json
import threading

from omnia_tpu.engine import EngineConfig, InferenceEngine, SamplingParams
from omnia_tpu.models import get_config

STRESS_THREADS = 12


def test_engine_concurrent_submit_cancel_release():
    """Many threads submitting, cancelling, and releasing sessions against
    one running engine: every request must reach exactly one terminal
    event, and the engine must stay healthy."""
    eng = InferenceEngine(
        get_config("test-tiny"),
        EngineConfig(num_slots=4, max_seq=64, prefill_buckets=(8,),
                     dtype="float32", decode_chunk=4, max_sessions=8),
        seed=0,
    )
    eng.warmup()
    eng.start()
    errors: list[str] = []

    def worker(i: int):
        try:
            for j in range(6):
                sp = SamplingParams(temperature=0.0, max_tokens=4 + (j % 3))
                h = eng.submit([1 + i, 2 + j, 3], sp,
                               session_id=f"s-{i % 5}" if j % 2 else None)
                if j % 3 == 2:
                    h.cancel()
                toks, fin = h.collect_tokens(timeout=60)
                if fin.finish_reason is None:
                    errors.append(f"w{i}: no terminal event")
                if j % 4 == 3:
                    eng.release_session(f"s-{i % 5}")
        except Exception as e:  # noqa: BLE001 - collected for the assert
            errors.append(f"w{i}: {e!r}")

    with concurrent.futures.ThreadPoolExecutor(STRESS_THREADS) as ex:
        list(ex.map(worker, range(STRESS_THREADS)))
    # Stop FIRST: the terminal event is pushed before the finished
    # counter increments, so the books are only guaranteed balanced once
    # the engine thread has joined.
    eng.stop()
    assert not errors, errors[:5]
    assert eng.healthy()
    # Every submit reached exactly one finish (no double-finish, no loss).
    assert eng.metrics["requests_finished"] == eng.metrics["requests_submitted"]


def test_session_api_concurrent_appends_and_reads():
    """Concurrent appends/reads/deletes across sessions: per-session
    message counts must be exact (lost updates are the race symptom)."""
    from omnia_tpu.session.api import SessionAPI

    api = SessionAPI(rate_limit_rps=1e9)  # stress the store, not the limiter
    per_thread = 20
    errors: list[str] = []

    def writer(i: int):
        try:
            sid = f"race-{i % 4}"
            for j in range(per_thread):
                code, _ = api.handle("POST", "/api/v1/messages", {
                    "session_id": sid, "role": "user",
                    "content": f"m-{i}-{j}",
                })
                assert code == 200
                api.handle("GET", f"/api/v1/sessions/{sid}/messages", None)
        except Exception as e:  # noqa: BLE001
            errors.append(repr(e))

    with concurrent.futures.ThreadPoolExecutor(STRESS_THREADS) as ex:
        list(ex.map(writer, range(STRESS_THREADS)))
    assert not errors, errors[:5]
    total = 0
    for k in range(4):
        code, doc = api.handle("GET", f"/api/v1/sessions/race-{k}/messages", None)
        assert code == 200
        total += len(doc["messages"])
    assert total == STRESS_THREADS * per_thread


def test_facade_concurrent_ws_sessions():
    """Concurrent WS clients through facade→runtime: each gets ITS OWN
    streamed reply (cross-connection chunk leakage is the race symptom)."""
    from websockets.sync.client import connect

    from omnia_tpu.facade.server import FacadeServer
    from omnia_tpu.runtime.packs import load_pack
    from omnia_tpu.runtime.providers import ProviderRegistry, ProviderSpec
    from omnia_tpu.runtime.server import RuntimeServer

    reg = ProviderRegistry()
    reg.register(ProviderSpec(name="m", type="mock", options={"scenarios": [
        {"pattern": f"who am i {i} ", "reply": f"you are client {i}"}
        for i in range(10)
    ] + [{"pattern": ".", "reply": "generic"}]}))
    rt = RuntimeServer(
        pack=load_pack({"name": "p", "version": "1.0.0",
                        "prompts": {"system": "s"},
                        "sampling": {"max_tokens": 32}}),
        providers=reg, provider_name="m")
    rport = rt.serve("localhost:0")
    facade = FacadeServer(runtime_target=f"localhost:{rport}", agent_name="a",
                          messages_per_minute=100000)
    fport = facade.serve()
    errors: list[str] = []

    def client(i: int):
        try:
            with connect(f"ws://localhost:{fport}/ws?user=u{i}") as ws:
                json.loads(ws.recv(timeout=15))
                for _turn in range(3):
                    ws.send(json.dumps(
                        {"type": "message", "content": f"who am i {i} ?"}))
                    text = ""
                    while True:
                        m = json.loads(ws.recv(timeout=30))
                        if m["type"] == "chunk":
                            text += m["text"]
                        elif m["type"] in ("done", "error"):
                            break
                    if text != f"you are client {i}":
                        errors.append(f"client {i} got {text!r}")
        except Exception as e:  # noqa: BLE001
            errors.append(f"client {i}: {e!r}")

    threads = [threading.Thread(target=client, args=(i,)) for i in range(10)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    try:
        assert not errors, errors[:5]
        assert not any(t.is_alive() for t in threads), "stuck client threads"
    finally:
        facade.shutdown()
        rt.shutdown()


def test_coordinator_concurrent_routing_and_failover():
    """Routing + failover under concurrency: affinity map must stay
    consistent while one worker flaps health."""
    from omnia_tpu.engine.coordinator import EngineCoordinator
    from omnia_tpu.engine.mock import MockEngine, Scenario

    workers = [MockEngine([Scenario(".", "w")]) for _ in range(3)]
    # MockEngine has no healthy(); give every worker one the coordinator
    # reads (workers 1-2 stay healthy so requests ALWAYS have a home and
    # must finish cleanly; only worker 0 flaps).
    for w in workers:
        w._healthy = True
        w.healthy = (lambda w=w: w._healthy)  # type: ignore[assignment]
        w.start()
    # probe_interval_s=0 restores this test's original per-request
    # health reads: a 2 ms flap must be OBSERVED by routing, which the
    # production-default probe cache would legitimately smooth over.
    coord = EngineCoordinator(workers, probe_interval_s=0.0)
    stop = threading.Event()

    def flapper():
        import time as _t

        while not stop.is_set():
            workers[0]._healthy = not workers[0]._healthy
            _t.sleep(0.002)

    flap = threading.Thread(target=flapper)
    flap.start()
    errors: list[str] = []

    def submitter(i: int):
        try:
            for j in range(30):
                h = coord.submit([1, 2], SamplingParams(max_tokens=2),
                                 session_id=f"cs-{i % 6}")
                toks, fin = h.collect_tokens(timeout=30)
                # Two workers are always healthy: every request must end
                # in a CLEAN finish, never an error or silence.
                if fin.finish_reason is None or fin.finish_reason.value not in (
                    "length", "stop",
                ):
                    errors.append(f"bad finish: {fin.finish_reason}")
        except Exception as e:  # noqa: BLE001
            errors.append(repr(e))

    with concurrent.futures.ThreadPoolExecutor(8) as ex:
        list(ex.map(submitter, range(8)))
    stop.set()
    flap.join(timeout=5)
    for w in workers:
        w.stop()
    assert not errors, errors[:5]
    assert coord.metrics["routed"] == 8 * 30
    # Affinity entries only point at known workers, and the always-
    # healthy workers actually carried load (routing isn't stuck on 0).
    with coord._lock:
        assert all(0 <= idx < 3 for idx in coord._affinity.values())
        assert set(coord._affinity.values()) - {0}, coord._affinity


def test_coordinator_submit_failover_metrics_reconcile_exactly():
    """16-thread submit against a fleet where one worker flaps health
    AND kills a bounded number of requests pre-token: every submit
    reaches exactly ONE clean terminal, and the coordinator's ledger
    (routed / resubmits / shed) reconciles EXACTLY with the terminal
    events and the fault plan's fired counts (ISSUE 7 satellite)."""
    from omnia_tpu.engine.coordinator import EngineCoordinator
    from omnia_tpu.engine.faults import FaultPlan
    from omnia_tpu.engine.mock import MockEngine, Scenario

    THREADS, PER = 16, 8
    # Worker 0 kills its first 20 requests before the first token —
    # every one is coordinator-resubmittable, and the counted plan lets
    # the reconciliation below be exact instead of statistical.
    plan = FaultPlan(die_after_tokens=0, die_count=20)
    workers = [
        MockEngine([Scenario(".", "w")],
                   fault_plan=plan if i == 0 else None)
        for i in range(3)
    ]
    for w in workers:
        w.start()
    # probe_interval_s=0: every routing decision sees live health, so
    # the flapping worker actually takes traffic whenever it is up
    # (cached probes could otherwise park it down for the whole storm).
    coord = EngineCoordinator(workers, resubmit_retries=2,
                              probe_interval_s=0.0)
    # Deterministic teeth BEFORE the flap starts: worker 0 is healthy
    # and least-loaded ties route to the lowest index, so these all hit
    # the fault, die pre-token, and resubmit — the ledger below can
    # never trivially pass on a fault that no request ever reached.
    for k in range(4):
        toks, fin = coord.submit([9, k], SamplingParams(max_tokens=2)
                                 ).collect_tokens(timeout=30)
        assert fin.finish_reason.value in ("length", "stop"), fin
    assert plan.fired["deaths"] == 4
    assert coord.metrics["resubmits"] == 4
    stop = threading.Event()

    def flapper():
        import time as _t

        while not stop.is_set():
            workers[0]._healthy = not workers[0]._healthy
            _t.sleep(0.002)

    flap = threading.Thread(target=flapper)
    flap.start()
    errors: list[str] = []
    finals: list = []
    finals_lock = threading.Lock()

    def submitter(i: int):
        try:
            for j in range(PER):
                h = coord.submit([1 + i, 2 + j], SamplingParams(max_tokens=2),
                                 session_id=f"rx-{(i + j) % 6}")
                toks, fin = h.collect_tokens(timeout=30)
                with finals_lock:
                    finals.append(fin)
                # Two workers never fault: with resubmit, every request
                # must end CLEAN — an ERROR/None means a death leaked
                # through or a terminal was lost.
                if fin.finish_reason is None or fin.finish_reason.value not in (
                    "length", "stop",
                ):
                    errors.append(f"bad finish: {fin.finish_reason}")
        except Exception as e:  # noqa: BLE001 - collected for the assert
            errors.append(repr(e))

    with concurrent.futures.ThreadPoolExecutor(THREADS) as ex:
        list(ex.map(submitter, range(THREADS)))
    stop.set()
    flap.join(timeout=5)
    for w in workers:
        w.stop()
    assert not errors, errors[:5]
    total = THREADS * PER + 4  # storm + the deterministic warmup
    # Exactly one terminal per submit, all clean.
    assert len(finals) == THREADS * PER
    # Exact ledger reconciliation: every submit routed once (nothing
    # shed — no queue bounds configured), and every injected pre-token
    # death was resubmitted exactly once.
    assert coord.metrics["routed"] == total
    assert coord.metrics["shed"] == 0
    assert coord.metrics["resubmits"] == plan.fired["deaths"] >= 4
    # Worker-side books balance too: accepted == finished on every
    # worker (the deaths are ERROR terminals, counted as finished).
    for w in workers:
        assert w.metrics["requests_finished"] == w.metrics["requests_submitted"]
    # And the per-request token streams stayed clean: total clean
    # finishes == routed submits (deaths were absorbed, not surfaced).
    clean = sum(f.finish_reason.value in ("length", "stop") for f in finals)
    assert clean == THREADS * PER


# ---------------------------------------------------------------------------
# Seeded interleaving fault injection (raceharness.py): deterministic
# schedule exploration over the hot shared structures — the systematic
# layer the plain stress loops above can't provide (SURVEY §5.2).
# ---------------------------------------------------------------------------

from raceharness import run_interleaved  # noqa: E402


def test_interleaved_circuit_breaker_consistency():
    """CircuitBreaker.allow/record from interleaved threads: failure
    count stays within [0, threshold] and the breaker never wedges
    closed-forever after successes."""
    from omnia_tpu.tools.executor import CircuitBreaker

    def scenario():
        br = CircuitBreaker(threshold=5, cooldown_s=0.01)
        opened = []

        def hammer():
            for i in range(60):
                if br.allow():
                    # Failure-heavy (1 success in 8): the threshold IS
                    # crossed under every schedule, so the open/half-open
                    # path gets exercised, not just the counter.
                    br.record(i % 8 == 7)
                elif not opened:
                    opened.append(True)

        def check():
            import time as _t

            assert opened, "breaker never opened — scenario lost its teeth"
            with br._lock:
                # Failed half-open trials keep counting past the
                # threshold (benign); the REAL invariants: the counter
                # never goes negative, and crossing the threshold always
                # leaves the breaker open.
                assert br._failures >= 0, br._failures
                if br._failures >= br.threshold:
                    assert br._opened_at is not None
            # After cooldown + sustained success it must admit again
            # (a breaker wedged open forever is the failure mode).
            deadline = _t.monotonic() + 5
            while _t.monotonic() < deadline:
                if br.allow():
                    br.record(True)
                    if br.allow():
                        return
                _t.sleep(0.005)
            raise AssertionError("breaker never recovered after cooldown")

        return [hammer] * 4, check

    assert not run_interleaved(scenario), "breaker raced"


def test_interleaved_stream_claims_exactly_once():
    """XREADGROUP '>' under interleaved consumers: every entry is
    delivered to EXACTLY one consumer (double-delivery or loss is the
    race symptom in the PEL bookkeeping)."""
    from omnia_tpu.redis.client import RedisClient
    from omnia_tpu.redis.server import RedisServer

    def scenario():
        srv = RedisServer().start()
        seed_client = RedisClient(*srv.address)
        n = 30
        for i in range(n):
            seed_client.execute("XADD", "q", "*", "i", str(i))
        seed_client.execute("XGROUP", "CREATE", "q", "g", "0")
        got: list[list[str]] = [[], [], []]

        def consumer(k: int):
            def body():
                c = RedisClient(*srv.address)
                while True:
                    r = c.execute("XREADGROUP", "GROUP", "g", f"c{k}",
                                  "COUNT", "2", "STREAMS", "q", ">")
                    if not r:
                        break
                    for _key, entries in r:
                        for eid, fields in entries:
                            got[k].append(fields[1].decode())
                            c.execute("XACK", "q", "g", eid)
                c.close()
            return body

        def check():
            try:
                all_items = sorted(x for g in got for x in g)
                assert all_items == sorted(str(i) for i in range(n)), (
                    f"delivered {len(all_items)}/{n}: dupes or losses")
                assert seed_client.execute("XPENDING", "q", "g")[0] == 0
            finally:
                seed_client.close()
                srv.stop()

        return [consumer(k) for k in range(3)], check

    assert not run_interleaved(scenario, seeds=range(4), timeout_s=90)


def test_interleaved_lockstep_drain_counter():
    """LockstepEngine submit vs _drain_pending: the pending-submit
    counter must equal the queue's actual submit count under any
    schedule (drift would corrupt queue_depth autoscaling signals)."""
    from omnia_tpu.engine.mock import MockEngine, Scenario
    from omnia_tpu.engine.multihost import LockstepEngine

    def scenario():
        lock = LockstepEngine(MockEngine([Scenario(".", "x")]))

        def submitter():
            for _ in range(25):
                lock.submit([1, 2], SamplingParams(max_tokens=1))

        def drainer():
            for _ in range(40):
                lock._drain_pending()

        def check():
            # Drain whatever remains, then the books must balance.
            drained = True
            while drained:
                drained = bool(lock._drain_pending())
            with lock._lock:
                assert lock._pending_submits == 0, lock._pending_submits
                assert not lock._pending

        return [submitter, submitter, drainer, drainer], check

    assert not run_interleaved(scenario, seeds=range(5))


def test_interleaved_mock_drain_vs_submit_ledger():
    """ISSUE 9 lock-discipline regression (seeded schedules): MockEngine
    ``stop(drain=True)`` racing ``submit``. The pre-fix unlocked
    ``_draining`` write could interleave with submit's check-and-reserve
    so a playback was admitted after the drain decided the engine was
    idle. Under every forced schedule: each submit reaches exactly one
    terminal, and the ledger reconciles exactly —
    attempts == submitted + shed and submitted == finished."""
    from omnia_tpu.engine.mock import MockEngine, Scenario

    def scenario():
        eng = MockEngine([Scenario(".", "abcdef")])
        eng.start()
        results: list = []

        def submitter(k: int):
            def body():
                for j in range(5):
                    h = eng.submit([k, j, 1], SamplingParams(max_tokens=3))
                    _toks, fin = h.collect_tokens(timeout=20)
                    results.append(fin)
            return body

        def drainer():
            h = eng.submit([9, 9], SamplingParams(max_tokens=3))
            _toks, fin = h.collect_tokens(timeout=20)
            results.append(fin)
            eng.stop(drain=True, drain_timeout_s=20)

        def check():
            import time as _t

            attempts = 3 * 5 + 1
            finals = list(results)
            assert len(finals) == attempts, len(finals)
            assert all(f.finish_reason is not None for f in finals)
            # requests_finished increments AFTER the terminal push; give
            # the playback threads a bounded moment to balance the books.
            deadline = _t.monotonic() + 5
            while _t.monotonic() < deadline:
                m = eng.metrics
                with eng._lock:
                    submitted, finished, shed = (
                        m["requests_submitted"], m["requests_finished"],
                        m["requests_shed"],
                    )
                if submitted == finished and submitted + shed == attempts:
                    return
                _t.sleep(0.005)
            raise AssertionError(
                f"ledger never reconciled: submitted={submitted} "
                f"finished={finished} shed={shed} attempts={attempts}"
            )

        return [submitter(0), submitter(1), submitter(2), drainer], check

    assert not run_interleaved(scenario, seeds=range(5), timeout_s=90)


def test_interleaved_coordinator_drain_failover_ledger():
    """ISSUE 9 satellite: coordinator failover + drain under forced
    interleavings — ``stop(drain=True)`` racing ``submit`` and
    ``release_session``, with worker 0 killing a counted number of
    requests pre-token. The PR 5 ledger must reconcile EXACTLY under
    every schedule: one terminal per submit, routed == accepted submits,
    resubmits == injected zero-token deaths, and worker books
    (submitted + shed vs routed + resubmits) balance fleet-wide."""
    from omnia_tpu.engine.coordinator import EngineCoordinator
    from omnia_tpu.engine.faults import FaultPlan
    from omnia_tpu.engine.mock import MockEngine, Scenario

    def scenario():
        plan = FaultPlan(die_after_tokens=0, die_count=3)
        workers = [
            MockEngine([Scenario(".", "w")],
                       fault_plan=plan if i == 0 else None)
            for i in range(3)
        ]
        for w in workers:
            w.start()
        coord = EngineCoordinator(workers, resubmit_retries=2,
                                  probe_interval_s=0.0)
        finals: list = []

        def submitter(k: int):
            def body():
                for j in range(4):
                    h = coord.submit([1 + k, 2 + j],
                                     SamplingParams(max_tokens=2),
                                     session_id=f"dr-{(k + j) % 3}")
                    _toks, fin = h.collect_tokens(timeout=30)
                    finals.append(fin)
            return body

        def releaser():
            for sid in ("dr-0", "dr-1", "dr-2", "dr-0"):
                coord.release_session(sid)

        def drainer():
            coord.stop(drain=True)

        def check():
            import time as _t

            total = 2 * 4
            assert len(finals) == total
            assert all(f.finish_reason is not None for f in finals)
            # Worker books balance once playback threads finish their
            # post-terminal increments (bounded wait).
            deadline = _t.monotonic() + 5
            while _t.monotonic() < deadline:
                snap = []
                for w in workers:
                    with w._lock:
                        snap.append((
                            w.metrics["requests_submitted"],
                            w.metrics["requests_finished"],
                            w.metrics["requests_shed"],
                        ))
                if all(s == f for s, f, _ in snap):
                    break
                _t.sleep(0.005)
            assert all(s == f for s, f, _ in snap), snap
            with coord._metrics_lock:
                routed = coord.metrics["routed"]
                resubmits = coord.metrics["resubmits"]
                shed = coord.metrics["shed"]
            # Every submit found a worker (all stay healthy; drain sheds
            # AT the worker, not before routing) and every injected
            # zero-token death was transparently resubmitted.
            assert routed == total and shed == 0
            assert resubmits == plan.fired["deaths"]
            # Fleet-wide attempt conservation: each routed submit +
            # each resubmit landed on exactly one worker, where it was
            # either accepted or shed by the drain.
            accepted = sum(s for s, _f, _sh in snap)
            worker_shed = sum(sh for _s, _f, sh in snap)
            assert accepted + worker_shed == routed + resubmits, (
                snap, routed, resubmits
            )
            # Affinity hygiene under release/drain races: surviving pins
            # only name real workers.
            with coord._lock:
                assert all(0 <= i < 3 for i in coord._affinity.values())

        return [submitter(0), submitter(1), releaser, drainer], check

    assert not run_interleaved(scenario, seeds=range(5), timeout_s=120)


def test_interleaved_prober_hard_and_soft_evidence():
    """ISSUE 9 lock-discipline regression: ``_note_probe`` now reads the
    per-worker health record inside ``_health_lock`` (the read raced
    probe writers before). Hammer mixed hard/soft evidence under forced
    schedules and assert the cached state can never wedge: counters stay
    non-negative and the worker still transitions down on consecutive
    failures and back up on recovery."""
    from omnia_tpu.engine.coordinator import EngineCoordinator
    from omnia_tpu.engine.mock import MockEngine

    def scenario():
        coord = EngineCoordinator(
            [MockEngine()], health_fail_threshold=3, health_cooldown_s=0.0,
        )

        def noter(hard: bool):
            def body():
                for i in range(40):
                    coord._note_probe(0, i % 3 != 0, hard=hard and i % 7 == 0)
            return body

        def check():
            with coord._health_lock:
                st = coord._health[0]
                assert st.fails >= 0
            # Post-contention the state machine must still move: three
            # consecutive failures down the worker, one success (zero
            # cooldown) reinstates it.
            for _ in range(3):
                coord._note_probe(0, False)
            with coord._health_lock:
                assert not coord._health[0].up
            coord._note_probe(0, True)
            with coord._health_lock:
                assert coord._health[0].up

        return [noter(True), noter(False), noter(False)], check

    assert not run_interleaved(scenario, seeds=range(5))


def test_interleaved_media_grant_lifecycle():
    """MediaStore negotiate/put/resolve across threads: every granted
    upload resolves to exactly the bytes its thread wrote (cross-ref
    bleed is the race symptom)."""
    import tempfile

    from omnia_tpu.media import LocalMediaStore

    def scenario():
        store = LocalMediaStore(tempfile.mkdtemp(prefix="race-media-"))
        results: dict[int, tuple[str, bytes]] = {}

        def uploader(k: int):
            def body():
                for j in range(8):
                    grant = store.negotiate_upload("ws")
                    payload = f"{k}:{j}".encode() * 10
                    store.put(grant.storage_ref, grant.token, payload)
                    results[(k, j)] = (grant.storage_ref, payload)
            return body

        def check():
            assert len(results) == 3 * 8
            for (k, j), (ref, payload) in results.items():
                assert store.resolve(ref) == payload, (k, j)

        return [uploader(k) for k in range(3)], check

    assert not run_interleaved(scenario, seeds=range(4))
