"""Concurrency stress harness — the Python analog of the reference's
`go test -race` CI discipline (SURVEY §5.2): hammer the hot shared
structures from many threads and assert invariants hold. CPython won't
flag data races by itself, so these tests are written to DETECT their
symptoms: lost updates, double-finishes, cross-session leaks, deadlocks
(every wait is bounded)."""

from __future__ import annotations

import concurrent.futures
import json
import threading

from omnia_tpu.engine import EngineConfig, InferenceEngine, SamplingParams
from omnia_tpu.models import get_config

STRESS_THREADS = 12


def test_engine_concurrent_submit_cancel_release():
    """Many threads submitting, cancelling, and releasing sessions against
    one running engine: every request must reach exactly one terminal
    event, and the engine must stay healthy."""
    eng = InferenceEngine(
        get_config("test-tiny"),
        EngineConfig(num_slots=4, max_seq=64, prefill_buckets=(8,),
                     dtype="float32", decode_chunk=4, max_sessions=8),
        seed=0,
    )
    eng.warmup()
    eng.start()
    errors: list[str] = []

    def worker(i: int):
        try:
            for j in range(6):
                sp = SamplingParams(temperature=0.0, max_tokens=4 + (j % 3))
                h = eng.submit([1 + i, 2 + j, 3], sp,
                               session_id=f"s-{i % 5}" if j % 2 else None)
                if j % 3 == 2:
                    h.cancel()
                toks, fin = h.collect_tokens(timeout=60)
                if fin.finish_reason is None:
                    errors.append(f"w{i}: no terminal event")
                if j % 4 == 3:
                    eng.release_session(f"s-{i % 5}")
        except Exception as e:  # noqa: BLE001 - collected for the assert
            errors.append(f"w{i}: {e!r}")

    with concurrent.futures.ThreadPoolExecutor(STRESS_THREADS) as ex:
        list(ex.map(worker, range(STRESS_THREADS)))
    try:
        assert not errors, errors[:5]
        assert eng.healthy()
        # Every submit reached exactly one finish (no double-finish, no loss).
        assert eng.metrics["requests_finished"] == eng.metrics["requests_submitted"]
    finally:
        eng.stop()


def test_session_api_concurrent_appends_and_reads():
    """Concurrent appends/reads/deletes across sessions: per-session
    message counts must be exact (lost updates are the race symptom)."""
    from omnia_tpu.session.api import SessionAPI

    api = SessionAPI(rate_limit_rps=1e9)  # stress the store, not the limiter
    per_thread = 20
    errors: list[str] = []

    def writer(i: int):
        try:
            sid = f"race-{i % 4}"
            for j in range(per_thread):
                code, _ = api.handle("POST", "/api/v1/messages", {
                    "session_id": sid, "role": "user",
                    "content": f"m-{i}-{j}",
                })
                assert code == 200
                api.handle("GET", f"/api/v1/sessions/{sid}/messages", None)
        except Exception as e:  # noqa: BLE001
            errors.append(repr(e))

    with concurrent.futures.ThreadPoolExecutor(STRESS_THREADS) as ex:
        list(ex.map(writer, range(STRESS_THREADS)))
    assert not errors, errors[:5]
    total = 0
    for k in range(4):
        code, doc = api.handle("GET", f"/api/v1/sessions/race-{k}/messages", None)
        assert code == 200
        total += len(doc["messages"])
    assert total == STRESS_THREADS * per_thread


def test_facade_concurrent_ws_sessions():
    """Concurrent WS clients through facade→runtime: each gets ITS OWN
    streamed reply (cross-connection chunk leakage is the race symptom)."""
    from websockets.sync.client import connect

    from omnia_tpu.facade.server import FacadeServer
    from omnia_tpu.runtime.packs import load_pack
    from omnia_tpu.runtime.providers import ProviderRegistry, ProviderSpec
    from omnia_tpu.runtime.server import RuntimeServer

    reg = ProviderRegistry()
    reg.register(ProviderSpec(name="m", type="mock", options={"scenarios": [
        {"pattern": f"who am i {i} ", "reply": f"you are client {i}"}
        for i in range(10)
    ] + [{"pattern": ".", "reply": "generic"}]}))
    rt = RuntimeServer(
        pack=load_pack({"name": "p", "version": "1.0.0",
                        "prompts": {"system": "s"},
                        "sampling": {"max_tokens": 32}}),
        providers=reg, provider_name="m")
    rport = rt.serve("localhost:0")
    facade = FacadeServer(runtime_target=f"localhost:{rport}", agent_name="a",
                          messages_per_minute=100000)
    fport = facade.serve()
    errors: list[str] = []

    def client(i: int):
        try:
            with connect(f"ws://localhost:{fport}/ws?user=u{i}") as ws:
                json.loads(ws.recv(timeout=15))
                for _turn in range(3):
                    ws.send(json.dumps(
                        {"type": "message", "content": f"who am i {i} ?"}))
                    text = ""
                    while True:
                        m = json.loads(ws.recv(timeout=30))
                        if m["type"] == "chunk":
                            text += m["text"]
                        elif m["type"] in ("done", "error"):
                            break
                    if text != f"you are client {i}":
                        errors.append(f"client {i} got {text!r}")
        except Exception as e:  # noqa: BLE001
            errors.append(f"client {i}: {e!r}")

    threads = [threading.Thread(target=client, args=(i,)) for i in range(10)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    try:
        assert not errors, errors[:5]
        assert not any(t.is_alive() for t in threads), "stuck client threads"
    finally:
        facade.shutdown()
        rt.shutdown()


def test_coordinator_concurrent_routing_and_failover():
    """Routing + failover under concurrency: affinity map must stay
    consistent while one worker flaps health."""
    from omnia_tpu.engine.coordinator import EngineCoordinator
    from omnia_tpu.engine.mock import MockEngine, Scenario

    workers = [MockEngine([Scenario(".", "w")]) for _ in range(3)]
    for w in workers:
        w.start()
    coord = EngineCoordinator(workers)
    stop = threading.Event()

    def flapper():
        import time as _t

        while not stop.is_set():
            workers[0]._healthy = not getattr(workers[0], "_healthy", True)
            _t.sleep(0.002)

    # MockEngine has no _healthy attr by default; give it one the
    # coordinator reads through healthy().
    workers[0]._healthy = True
    workers[0].healthy = lambda: workers[0]._healthy  # type: ignore[assignment]
    flap = threading.Thread(target=flapper)
    flap.start()
    errors: list[str] = []

    def submitter(i: int):
        try:
            for j in range(30):
                h = coord.submit([1, 2], SamplingParams(max_tokens=2),
                                 session_id=f"cs-{i % 6}")
                _toks, fin = h.collect_tokens(timeout=30)
                if fin.finish_reason is None:
                    errors.append("no terminal")
        except Exception as e:  # noqa: BLE001
            errors.append(repr(e))

    with concurrent.futures.ThreadPoolExecutor(8) as ex:
        list(ex.map(submitter, range(8)))
    stop.set()
    flap.join(timeout=5)
    for w in workers:
        w.stop()
    assert not errors, errors[:5]
    # Affinity entries only point at known workers.
    with coord._lock:
        assert all(0 <= idx < 3 for idx in coord._affinity.values())
