"""Facade e2e: real WebSocket client → FacadeServer → runtime gRPC →
mock engine, all in one process over localhost (reference integration
pattern)."""

import http.server
import json
import threading
import time

import pytest
from websockets.exceptions import ConnectionClosed
from websockets.sync.client import connect

from omnia_tpu.facade.auth import AuthChain, ClientKeyValidator, HmacValidator
from omnia_tpu.facade.recording import RecordingInterceptor
from omnia_tpu.facade.server import FacadeServer
from omnia_tpu.runtime.packs import load_pack
from omnia_tpu.runtime.providers import ProviderRegistry, ProviderSpec
from omnia_tpu.runtime.server import RuntimeServer
from omnia_tpu.tools import ToolExecutor, ToolHandler

PACK = {
    "name": "ws-agent",
    "version": "1.0.0",
    "prompts": {"system": "You are an assistant."},
    "tools": [
        {"name": "echo"},
        {"name": "lookup", "client_side": True},
    ],
    "sampling": {"temperature": 0.0, "max_tokens": 256},
}

SCENARIOS = [
    {"pattern": r"\[TOOL\]client data", "reply": "got your data"},
    {
        "pattern": "clienttool",
        "reply": '<tool_call>{"name": "lookup", "arguments": {"k": "v"}}</tool_call>',
    },
    {"pattern": "hello", "reply": "hi there"},
    {"pattern": "slow", "reply": "s l o w", "delay_per_token_s": 0.02},
]


@pytest.fixture(scope="module")
def record_sink():
    records = []

    class Handler(http.server.BaseHTTPRequestHandler):
        def do_POST(self):
            body = self.rfile.read(int(self.headers.get("Content-Length", 0)))
            records.append((self.path, json.loads(body)))
            self.send_response(204)
            self.end_headers()

        def log_message(self, *args):
            pass

    server = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    yield server.server_address[1], records
    server.shutdown()


@pytest.fixture(scope="module")
def stack(record_sink):
    sink_port, _ = record_sink
    registry = ProviderRegistry()
    registry.register(ProviderSpec(name="main", type="mock", options={"scenarios": SCENARIOS}))
    runtime = RuntimeServer(
        pack=load_pack(PACK),
        providers=registry,
        provider_name="main",
        tool_executor=ToolExecutor(
            [
                ToolHandler(name="echo", fn=lambda a: "echoed"),
                ToolHandler(name="lookup", type="client"),
            ]
        ),
    )
    rport = runtime.serve("localhost:0")
    facade = FacadeServer(
        runtime_target=f"localhost:{rport}",
        agent_name="ws-agent",
        auth_chain=AuthChain(
            [ClientKeyValidator({"key1": "secret-abc"}), HmacValidator(b"mgmt-secret")]
        ),
        recording=RecordingInterceptor(f"http://127.0.0.1:{sink_port}"),
        messages_per_minute=600,
    )
    fport = facade.serve()
    yield facade, fport
    facade.shutdown()
    runtime.shutdown()


def _url(port, **params):
    q = "&".join(f"{k}={v}" for k, v in params.items())
    return f"ws://localhost:{port}/ws" + (f"?{q}" if q else "")


def _recv_until(ws, types, timeout=15):
    got = []
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        msg = json.loads(ws.recv(timeout=deadline - time.monotonic()))
        got.append(msg)
        if msg["type"] in types:
            return got
    raise TimeoutError(f"never saw {types}, got {got}")


class TestFacade:
    def test_unauthorized_rejected(self, stack):
        _, port = stack
        with pytest.raises(ConnectionClosed) as exc:
            ws = connect(_url(port, token="wrong"))
            ws.recv(timeout=5)
        assert exc.value.rcvd.code == 4401

    def test_turn_streams(self, stack):
        _, port = stack
        with connect(_url(port, token="secret-abc")) as ws:
            connected = json.loads(ws.recv(timeout=10))
            assert connected["type"] == "connected"
            assert connected["agent"] == "ws-agent"
            assert not connected["resumed"]
            assert "streaming" in connected["capabilities"]

            ws.send(json.dumps({"type": "message", "content": "hello facade"}))
            msgs = _recv_until(ws, {"done", "error"})
            text = "".join(m["text"] for m in msgs if m["type"] == "chunk")
            assert text == "hi there"
            assert msgs[-1]["type"] == "done"
            assert msgs[-1]["usage"]["completion_tokens"] > 0

    def test_mgmt_jwt_auth(self, stack):
        _, port = stack
        token = HmacValidator.mint(b"mgmt-secret", subject="dashboard")
        with connect(_url(port, token=token)) as ws:
            assert json.loads(ws.recv(timeout=10))["type"] == "connected"

    def test_resume_same_session(self, stack):
        _, port = stack
        with connect(_url(port, token="secret-abc", session="ws-resume-1")) as ws:
            connected = json.loads(ws.recv(timeout=10))
            assert not connected["resumed"]
            # Authenticated sessions are namespaced per user; the server
            # returns the canonical id and resumes by it or by the raw id.
            canonical = connected["session_id"]
            assert canonical.endswith("ws-resume-1")
            ws.send(json.dumps({"type": "message", "content": "hello"}))
            _recv_until(ws, {"done", "error"})
            ws.send(json.dumps({"type": "hangup"}))
        for handle in ("ws-resume-1", canonical):
            with connect(_url(port, token="secret-abc", session=handle)) as ws:
                connected = json.loads(ws.recv(timeout=10))
                assert connected["resumed"]
                assert connected["session_id"] == canonical

    def test_foreign_session_rejected(self, stack):
        """One principal must not resume (or hijack) another's session."""
        _, port = stack
        with connect(_url(port, token="secret-abc", session="private-1")) as ws:
            canonical = json.loads(ws.recv(timeout=10))["session_id"]
            ws.send(json.dumps({"type": "hangup"}))
        other = HmacValidator.mint(b"mgmt-secret", subject="dashboard")
        with pytest.raises(ConnectionClosed) as exc:
            with connect(_url(port, token=other, session=canonical)) as ws:
                ws.recv(timeout=10)
        assert exc.value.rcvd.code == 4403

    def test_user_param_cannot_override_principal(self, stack, record_sink):
        """?user= is an impersonation vector when auth is on — must be ignored."""
        _, port = stack
        _, records = record_sink
        before = len(records)
        with connect(_url(port, token="secret-abc", user="victim")) as ws:
            ws.recv(timeout=10)
            ws.send(json.dumps({"type": "message", "content": "hi"}))
            _recv_until(ws, {"done", "error"})
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and len(records) < before + 2:
            time.sleep(0.05)
        new = [r for _, r in records[before:]]
        ids = [r["user_id"] for r in new if "user_id" in r]
        assert ids, f"no recorded identity at all: {new}"
        assert all(i == "key1" for i in ids)

    def test_client_tool_roundtrip(self, stack):
        _, port = stack
        with connect(_url(port, token="secret-abc")) as ws:
            ws.recv(timeout=10)
            ws.send(json.dumps({"type": "message", "content": "clienttool now"}))
            msgs = _recv_until(ws, {"tool_call"})
            tc = msgs[-1]
            assert tc["name"] == "lookup"
            ws.send(
                json.dumps(
                    {
                        "type": "tool_result",
                        "tool_call_id": tc["id"],
                        "content": "client data",
                    }
                )
            )
            msgs = _recv_until(ws, {"done", "error"})
            text = "".join(m["text"] for m in msgs if m["type"] == "chunk")
            assert text == "got your data"

    def test_bad_json_reported(self, stack):
        _, port = stack
        with connect(_url(port, token="secret-abc")) as ws:
            ws.recv(timeout=10)
            ws.send("{{{nope")
            msg = json.loads(ws.recv(timeout=10))
            assert msg["type"] == "error"
            assert msg["code"] == "bad_json"

    def test_unexpected_tool_result(self, stack):
        _, port = stack
        with connect(_url(port, token="secret-abc")) as ws:
            ws.recv(timeout=10)
            ws.send(json.dumps({"type": "tool_result", "tool_call_id": "x", "content": "y"}))
            msg = json.loads(ws.recv(timeout=10))
            assert msg["code"] == "unexpected_tool_result"

    def test_recording_captures_both_sides(self, stack, record_sink):
        _, port = stack
        _, records = record_sink
        before = len(records)
        with connect(_url(port, token="secret-abc", user="u-rec")) as ws:
            ws.recv(timeout=10)
            ws.send(json.dumps({"type": "message", "content": "hello recorder"}))
            _recv_until(ws, {"done", "error"})
        # Wait for both *message* records (session-ensure records also
        # land in the sink, so a raw count races the assistant delivery).
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline:
            msgs = [r for _, r in records[before:] if r.get("kind") == "message"]
            if len(msgs) >= 2:
                break
            time.sleep(0.05)
        new = [r for _, r in records[before:]]
        roles = [r["role"] for r in new if r.get("kind") == "message"]
        assert "user" in roles and "assistant" in roles
        assistant = next(r for r in new if r.get("role") == "assistant")
        assert assistant["usage"]["completion_tokens"] > 0

    def test_health_and_metrics_endpoints(self, stack):
        facade, _ = stack
        import urllib.request

        base = f"http://localhost:{facade.health_port}"
        assert urllib.request.urlopen(base + "/healthz").status == 200
        assert urllib.request.urlopen(base + "/readyz").status == 200
        body = urllib.request.urlopen(base + "/metrics").read().decode()
        assert "omnia_facade_connections_active" in body
        assert "omnia_facade_turn_seconds_bucket" in body

    def test_rate_limit_closes(self, record_sink):
        sink_port, _ = record_sink
        registry = ProviderRegistry()
        registry.register(
            ProviderSpec(name="main", type="mock", options={"scenarios": SCENARIOS})
        )
        runtime = RuntimeServer(
            pack=load_pack(PACK), providers=registry, provider_name="main"
        )
        rport = runtime.serve("localhost:0")
        facade = FacadeServer(
            runtime_target=f"localhost:{rport}", messages_per_minute=0.0001
        )
        port = facade.serve()
        try:
            with pytest.raises(ConnectionClosed) as exc:
                ws = connect(_url(port))
                ws.recv(timeout=10)
                for i in range(15):  # burst allows 10
                    ws.send(json.dumps({"type": "message", "content": "hello"}))
                    while True:
                        m = json.loads(ws.recv(timeout=10))
                        if m["type"] in ("done", "error"):
                            break
            assert exc.value.rcvd.code == 4429
        finally:
            facade.shutdown()
            runtime.shutdown()

    def test_drain_rejects_new_and_reports_unready(self, record_sink):
        registry = ProviderRegistry()
        registry.register(
            ProviderSpec(name="main", type="mock", options={"scenarios": SCENARIOS})
        )
        runtime = RuntimeServer(
            pack=load_pack(PACK), providers=registry, provider_name="main"
        )
        rport = runtime.serve("localhost:0")
        facade = FacadeServer(runtime_target=f"localhost:{rport}", drain_timeout_s=0.5)
        port = facade.serve()
        try:
            import urllib.request

            threading.Thread(target=facade.drain, daemon=True).start()
            time.sleep(0.1)
            resp = urllib.request.urlopen(
                f"http://localhost:{facade.health_port}/readyz"
            )
        except urllib.error.HTTPError as e:
            assert e.code == 503
        else:
            pytest.fail(f"readyz should 503 while draining, got {resp.status}")
        finally:
            with pytest.raises(ConnectionClosed):
                ws = connect(_url(port))
                ws.recv(timeout=5)
            facade.shutdown()
            runtime.shutdown()
