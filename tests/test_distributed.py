"""Multi-host runtime smoke: two real OS processes join one JAX
distributed runtime through the OMNIA_* env contract and run ONE sharded
model forward spanning both (SURVEY §5.8's DCN path, exercised over
localhost Gloo the way the virtual CPU mesh exercises ICI)."""

from __future__ import annotations

import os
import socket
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CHILD = r"""
import os
from omnia_tpu.parallel.distributed import maybe_initialize_distributed

info = maybe_initialize_distributed()
assert info is not None and info["num_processes"] == 2

import jax
import jax.numpy as jnp
import numpy as np

assert jax.process_count() == 2
assert jax.device_count() == 2  # one CPU device per process, global view

from omnia_tpu.models import get_config, llama
from omnia_tpu.parallel import make_mesh, shard_pytree
from omnia_tpu.parallel.sharding import named_sharding_tree

cfg = get_config("test-tiny", num_heads=2, num_kv_heads=2)
mesh = make_mesh(dp=1, tp=2)  # the GLOBAL mesh: tp axis spans processes
params = shard_pytree(
    llama.init_params(cfg, jax.random.key(0)), llama.param_specs(cfg), mesh
)
B, S = 2, 16
ck, cv = llama.init_kv_cache(cfg, B, S)
tree = named_sharding_tree(llama.kv_cache_specs(), mesh)
ck = jax.device_put(ck, tree[0])
cv = jax.device_put(cv, tree[1])
toks = jnp.zeros((B,), jnp.int32)
pos = jnp.zeros((B,), jnp.int32)

@jax.jit
def decode(params, ck, cv, tokens, positions):
    logits, ck, cv = llama.forward(
        params, cfg, tokens[:, None], positions[:, None], ck, cv, positions
    )
    return jnp.argmax(logits[:, 0], axis=-1)

out = decode(params, ck, cv, toks, pos)
from jax.experimental import multihost_utils
gathered = multihost_utils.process_allgather(out, tiled=True)
assert np.isfinite(np.asarray(gathered)).all()
print(f"RANK-OK {jax.process_index()} out={np.asarray(out).tolist()}", flush=True)
"""



def _rank_env(coord_port: int, extra: dict | None = None, n: int = 2) -> dict:
    """Shared n-process env contract (the PALLAS/XLA scrubs must stay in
    ONE place — drift here means ranks init different backends)."""
    env = {
        **os.environ,
        "JAX_PLATFORMS": "cpu",
        "PYTHONPATH": REPO,
        "OMNIA_COORDINATOR_ADDR": f"127.0.0.1:{coord_port}",
        "OMNIA_NUM_PROCESSES": str(n),
        **(extra or {}),
    }
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("XLA_FLAGS", None)  # one device per process, not a forced 8
    return env


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]

def test_two_process_engine_forward():
    port = _free_port()
    env_base = _rank_env(port)
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", CHILD],
            env={**env_base, "OMNIA_PROCESS_ID": str(rank)},
            cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        )
        for rank in range(2)
    ]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=300)
        outs.append(out.decode())
    assert all(p.returncode == 0 for p in procs), outs
    assert all("RANK-OK" in o for o in outs), outs


def test_hostname_ordinal_inference():
    from omnia_tpu.parallel import distributed as D

    assert D._infer_process_id({"HOSTNAME": "agent-70b-3"}) == 3
    assert D._infer_process_id({"OMNIA_PROCESS_ID": "5"}) == 5
    assert D._infer_process_id({"HOSTNAME": "nodigit"}) is None
    # no coordinator → no-op, no jax import side effects
    assert D.maybe_initialize_distributed({}) is None


LOCKSTEP_CHILD = r"""
import os
from omnia_tpu.parallel.distributed import maybe_initialize_distributed

info = maybe_initialize_distributed()
import jax
import numpy as np
from omnia_tpu.engine import EngineConfig, InferenceEngine, SamplingParams
from omnia_tpu.engine.multihost import LockstepEngine
from omnia_tpu.models import get_config

N = int(os.environ["OMNIA_NUM_PROCESSES"])  # tp spans all ranks
cfg = get_config("test-tiny", num_heads=max(2, N), num_kv_heads=max(2, N))
eng = InferenceEngine(
    cfg,
    EngineConfig(num_slots=2, max_seq=64, prefill_buckets=(8,),
                 dtype="float32", tp=N, decode_chunk=4, max_sessions=4),
    seed=3,
)
lock = LockstepEngine(eng)
lock.warmup()

if lock.is_leader:
    lock.start()
    sp = SamplingParams(temperature=0.0, max_tokens=6)
    h1 = lock.submit([1, 2, 3], sp, session_id="ms")
    t1, f1 = h1.collect_tokens(timeout=120)
    assert f1.finish_reason.value == "length", f1
    # second turn reuses the session across BOTH processes' replicas
    h2 = lock.submit([1, 2, 3] + t1 + [9], sp, session_id="ms")
    t2, f2 = h2.collect_tokens(timeout=120)
    assert eng.metrics["prefix_reuse_tokens"] > 0
    lock.release_session("ms")
    import time as _t
    _t.sleep(0.3)  # let the release tick replicate
    lock.stop()
    print(f"LEADER-OK t1={t1} gen={eng.metrics['tokens_generated']}", flush=True)
else:
    lock.run_follower()
    print(f"FOLLOWER-OK gen={eng.metrics['tokens_generated']} "
          f"reuse={eng.metrics['prefix_reuse_tokens']} "
          f"sessions={len(eng._sessions)}", flush=True)
"""


def test_lockstep_engine_two_processes():
    """The multi-host serving design end-to-end: a tp=2 engine whose mesh
    SPANS two OS processes, leader-submitted turns (with cross-turn
    session reuse and release) replicated to the follower — identical
    host bookkeeping on both ranks proves the step streams stayed in
    lockstep (divergence would deadlock the collectives and time out)."""
    port = _free_port()
    env_base = _rank_env(port)
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", LOCKSTEP_CHILD],
            env={**env_base, "OMNIA_PROCESS_ID": str(rank)},
            cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        )
        for rank in range(2)
    ]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=600)
        outs.append(out.decode())
    assert all(p.returncode == 0 for p in procs), outs
    leader = next(o for o in outs if "LEADER-OK" in o)
    follower = next(o for o in outs if "FOLLOWER-OK" in o)
    # Identical replica bookkeeping: same tokens generated, same reuse,
    # and the released session is gone on the follower too.
    import re as _re

    gen_l = int(_re.search(r"gen=(\d+)", leader).group(1))
    gen_f = int(_re.search(r"gen=(\d+)", follower).group(1))
    assert gen_l == gen_f > 0, (leader, follower)
    assert int(_re.search(r"reuse=(\d+)", follower).group(1)) > 0
    assert int(_re.search(r"sessions=(\d+)", follower).group(1)) == 0


def test_lockstep_engine_four_processes():
    """4-rank lockstep (VERDICT r3 #6): the same replicated-engine design
    at tp=4 across four OS processes — the broadcast fan-out and the
    deterministic step stream must hold beyond the pairwise case."""
    port = _free_port()
    env_base = _rank_env(port, n=4)
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", LOCKSTEP_CHILD],
            env={**env_base, "OMNIA_PROCESS_ID": str(rank)},
            cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        )
        for rank in range(4)
    ]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=600)
        outs.append(out.decode())
    assert all(p.returncode == 0 for p in procs), outs
    import re as _re

    leader = next(o for o in outs if "LEADER-OK" in o)
    followers = [o for o in outs if "FOLLOWER-OK" in o]
    assert len(followers) == 3, outs
    gen_l = int(_re.search(r"gen=(\d+)", leader).group(1))
    assert gen_l > 0
    for f in followers:
        assert int(_re.search(r"gen=(\d+)", f).group(1)) == gen_l, (leader, f)
        assert int(_re.search(r"reuse=(\d+)", f).group(1)) > 0
        assert int(_re.search(r"sessions=(\d+)", f).group(1)) == 0


DEATH_LEADER = r"""
import os, sys, time, threading
from omnia_tpu.parallel.distributed import maybe_initialize_distributed
maybe_initialize_distributed()
from omnia_tpu.engine import EngineConfig, InferenceEngine, SamplingParams
from omnia_tpu.engine.multihost import LockstepEngine
from omnia_tpu.models import get_config

marker = os.environ["OMNIA_TEST_MARKER"]
cfg = get_config("test-tiny", num_heads=2, num_kv_heads=2)
eng = InferenceEngine(
    cfg,
    EngineConfig(num_slots=2, max_seq=128, prefill_buckets=(8,),
                 dtype="float32", tp=2, decode_chunk=2, max_sessions=0),
    seed=3,
)
lock = LockstepEngine(eng, tick_timeout_s=8.0)
lock.warmup()
lock.start()
# A long turn; the follower dies once the first token streams.
h = lock.submit([1, 2, 3], SamplingParams(temperature=0.0, max_tokens=120))
t_start = time.monotonic()
final = None
tokens = 0
for ev in h.events(timeout=120):
    if ev.token_id is not None:
        tokens += 1
        if tokens == 1:
            open(marker, "w").write("turn-started")
    if ev.is_final:
        final = ev
        break
elapsed = time.monotonic() - t_start
assert final is not None, "no final event within 120s (leader hung)"
assert final.finish_reason.value == "error", final
assert elapsed < 60, f"error took {elapsed:.0f}s — not bounded"
# Readiness flips within the bound too.
deadline = time.monotonic() + 30
while lock.healthy() and time.monotonic() < deadline:
    time.sleep(0.5)
assert not lock.healthy(), "engine still healthy after peer loss"
# New work fails fast instead of queueing into the void.
h2 = lock.submit([4, 5], SamplingParams(max_tokens=4))
toks2, fin2 = h2.collect_tokens(timeout=15)
assert fin2.finish_reason.value == "error", fin2
print(f"DEATH-OK tokens={tokens} elapsed={elapsed:.1f}s", flush=True)
os._exit(0)  # loop thread is wedged in the dead collective by design
"""

DEATH_FOLLOWER = r"""
import os, threading, time
from omnia_tpu.parallel.distributed import maybe_initialize_distributed
maybe_initialize_distributed()
from omnia_tpu.engine import EngineConfig, InferenceEngine
from omnia_tpu.engine.multihost import LockstepEngine
from omnia_tpu.models import get_config

marker = os.environ["OMNIA_TEST_MARKER"]

def die_on_marker():
    while not os.path.exists(marker):
        time.sleep(0.05)
    os._exit(9)  # SIGKILL-equivalent: no shutdown handshake, mid-turn

threading.Thread(target=die_on_marker, daemon=True).start()
cfg = get_config("test-tiny", num_heads=2, num_kv_heads=2)
eng = InferenceEngine(
    cfg,
    EngineConfig(num_slots=2, max_seq=128, prefill_buckets=(8,),
                 dtype="float32", tp=2, decode_chunk=2, max_sessions=0),
    seed=3,
)
lock = LockstepEngine(eng, tick_timeout_s=8.0)
lock.warmup()
lock.run_follower()
"""


def test_lockstep_follower_death_bounded(tmp_path):
    """Failure detection (VERDICT r3 #6): kill the follower mid-turn and
    require the leader to surface an ERROR on the live handle, flip
    healthy() to False, and fail new submits — all within the tick
    watchdog's bound instead of hanging in the dead collective."""
    port = _free_port()
    marker = str(tmp_path / "turn-started")
    env_base = _rank_env(port, {"OMNIA_TEST_MARKER": marker})
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", code],
            env={**env_base, "OMNIA_PROCESS_ID": str(rank)},
            cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        )
        for rank, code in ((0, DEATH_LEADER), (1, DEATH_FOLLOWER))
    ]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=300)
        outs.append(out.decode())
    assert procs[0].returncode == 0, outs
    assert "DEATH-OK" in outs[0], outs
    assert procs[1].returncode == 9, outs  # follower really died mid-turn


def test_multihost_runtime_binaries_serve_grpc(tmp_path):
    """THE multi-host serving e2e: two real `omnia-runtime` binaries with
    a `type: tpu` provider whose tp=2 mesh spans both processes — the
    follower replicates, the leader serves gRPC, and a Converse turn
    streams real engine tokens through the public contract."""
    import json as _json
    import time as _time

    (tmp_path / "pack.json").write_text(_json.dumps({
        "name": "mh", "version": "1.0.0",
        "prompts": {"system": "s"}, "sampling": {"temperature": 0.0,
                                                 "max_tokens": 8}}))
    (tmp_path / "providers.json").write_text(_json.dumps([{
        "name": "t", "type": "tpu", "model": "test-tiny",
        "options": {"tp": 2, "num_slots": 2, "max_seq": 64,
                    "prefill_buckets": [8], "dtype": "float32"},
    }]))
    coord_port = _free_port()
    grpc_port = _free_port()
    env_base = _rank_env(coord_port, {
        "OMNIA_PACK_PATH": str(tmp_path / "pack.json"),
        "OMNIA_PROVIDERS_PATH": str(tmp_path / "providers.json"),
        "OMNIA_GRPC_PORT": str(grpc_port),
    })
    # stderr → files: a PIPE nobody drains can block a chatty rank mid-
    # collective and stall the whole lockstep run; files never backpressure.
    logs = [open(tmp_path / f"rank{r}.log", "wb") for r in range(2)]
    procs = [
        subprocess.Popen(
            [sys.executable, "-c",
             "from omnia_tpu.cli import runtime_main; runtime_main()"],
            env={**env_base, "OMNIA_PROCESS_ID": str(rank)},
            cwd=REPO, stdout=subprocess.DEVNULL, stderr=logs[rank],
        )
        for rank in range(2)
    ]

    def rank_log(r):
        logs[r].flush()
        return (tmp_path / f"rank{r}.log").read_bytes().decode()[-2000:]

    try:
        from omnia_tpu.runtime.client import RuntimeClient

        deadline = _time.monotonic() + 240
        client = None
        while _time.monotonic() < deadline:
            for r, p in enumerate(procs):
                if p.poll() is not None:
                    raise AssertionError(f"rank {r} died: {rank_log(r)}")
            try:
                client = RuntimeClient(f"127.0.0.1:{grpc_port}")
                if client.health().status == "ok":
                    break
                client.close()
                client = None
            except Exception:
                if client is not None:
                    client.close()
                    client = None
            _time.sleep(1.0)
        assert client is not None, (
            "leader gRPC never became healthy; "
            f"rank0: {rank_log(0)} rank1: {rank_log(1)}")
        stream = client.open_stream("mh-sess")
        chunks = []
        final = None
        for msg in stream.turn("hello multihost"):
            if msg.type == "chunk":
                chunks.append(msg.text)
            if msg.type in ("done", "error"):
                final = msg
                break
        stream.close()
        client.close()
        assert final is not None and final.type == "done", final
        assert chunks, "no tokens streamed from the multi-host engine"
    finally:
        import signal as _signal

        for p in procs:
            if p.poll() is None:
                p.send_signal(_signal.SIGTERM)
        for p in procs:
            try:
                p.wait(timeout=15)
            except subprocess.TimeoutExpired:
                p.kill()
        for f in logs:
            f.close()
