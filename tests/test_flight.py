"""Engine flight recorder (engine/flight.py): event-ledger exactness,
ring bounds, latency-breakdown arithmetic, Chrome-trace export schema,
traceparent continuity across a counted pre-token worker death, mock
vocabulary parity, and seeded-interleaving concurrency.

Module-level imports are deliberately jax-free: the recorder, its export
CLI, the mock engine, and the coordinator run with no device stack (the
CI analysis job runs this file with no jax installed — engine-backed
tests importorskip jax and simply skip there; tier-1 runs everything).
"""

from __future__ import annotations

import json
import threading

import pytest

from omnia_tpu.engine.coordinator import EngineCoordinator
from omnia_tpu.engine.faults import FaultPlan
from omnia_tpu.engine.flight import (
    EVENTS,
    FlightRecorder,
    load_jsonl,
    main as flight_main,
    to_chrome_trace,
)
from omnia_tpu.engine.mock import MockEngine, Scenario
from omnia_tpu.engine.types import FinishReason, SamplingParams
from omnia_tpu.utils import tracing as tr

pytestmark = pytest.mark.flight

GREEDY = SamplingParams(temperature=0.0, max_tokens=8)


def _scripted_run(rec: FlightRecorder, clock: list, rid: str = "r1",
                  tokens: int = 3) -> None:
    """One full request lifecycle against an injected clock. The emit
    hot path never calls the recorder — the first-token stamp (taken by
    the handle) rides the terminal, exactly like the engine seams."""
    rec.note_submit(rid, 5)
    clock[0] += 1.0
    rec.note_claim(rid)
    clock[0] += 2.0
    rec.note_placement(rid, 0, 5, reuse=1, seeded=2, prefill_s=1.5)
    first_token_at = clock[0]  # first token lands AT placement
    clock[0] += float(tokens)  # decode: 1.0 per further token + finish
    rec.note_terminal(rid, "stop", tokens=tokens,
                      first_token_at=first_token_at)


class TestRecorderUnit:
    def _clocked(self, capacity: int = 64):
        clock = [0.0]
        return FlightRecorder(capacity, clock=lambda: clock[0]), clock

    def test_capacity_zero_refused(self):
        with pytest.raises(ValueError):
            FlightRecorder(0)

    def test_breakdown_stage_arithmetic(self):
        """The LatencyBreakdown fields against a scripted clock: the
        stages must tile the wall exactly (queue + placement + decode ==
        terminal - submit) and per-token decode is the mean gap."""
        rec, clock = self._clocked()
        _scripted_run(rec, clock, tokens=3)
        term = rec.events("terminal")[0]
        bd = term.attrs["breakdown"]
        assert bd["queue_s"] == 1.0
        assert bd["placement_s"] == 2.0
        assert bd["prefill_s"] == 1.5
        assert bd["ttft_s"] == 3.0          # submit → first token
        assert bd["decode_s"] == 3.0        # first token → terminal
        assert bd["decode_s_per_token"] == 1.5
        assert bd["tokens"] == 3
        wall = 6.0  # terminal mono - submit mono under the scripted clock
        assert bd["queue_s"] + bd["placement_s"] + bd["decode_s"] == wall
        # Histograms observed once per request (inter_token = the mean
        # gap at the terminal — never a per-token observe on the hot path).
        assert rec.hist["ttft"].count == 1
        assert rec.hist["queue_wait"].count == 1
        assert rec.hist["inter_token"].count == 1
        # Open books closed at the terminal: no leak on a long-lived engine.
        assert rec.stats()["open_requests"] == 0

    def test_ring_overwrite_bounds(self):
        rec, clock = self._clocked(capacity=8)
        for i in range(10):
            _scripted_run(rec, clock, rid=f"r{i}", tokens=2)
        evs = rec.events()
        stats = rec.stats()
        assert len(evs) == 8 == stats["retained"]
        assert stats["recorded"] == 40  # 4 ring events per request
        assert stats["dropped"] == 32
        # The retained window is the contiguous TAIL of the seq stream.
        seqs = [e.seq for e in evs]
        assert seqs == list(range(32, 40))
        assert stats["open_requests"] == 0

    def test_vocabulary_is_closed(self):
        rec, _clock = self._clocked()
        with pytest.raises(AssertionError):
            rec._record("not-a-kind", "", {})
        for e in rec.events():
            assert e.kind in EVENTS

    def test_stall_attribution_windows_per_request(self):
        """stall_steps counts engine stalls observed during THIS
        request's lifetime, not all-time."""
        rec, clock = self._clocked()
        rec.note_stall(3)                    # before r1 exists
        rec.note_submit("r1", 4)
        rec.note_stall(2)                    # during r1
        rec.note_terminal("r1", "stop")
        rec.note_submit("r2", 4)
        rec.note_terminal("r2", "stop")      # no stalls during r2
        bds = [e.attrs["breakdown"] for e in rec.events("terminal")]
        assert bds[0]["stall_steps"] == 2
        assert bds[1]["stall_steps"] == 0

    def test_queue_reaped_terminal_attributes_wait_to_queue(self):
        """A request reaped from the queue (deadline/cancel/drain) was
        never claimed — its whole lifetime IS queue wait, and the
        breakdown must say so (an all-zero breakdown would blind the
        queue-pressure diagnosis the runbook leans on)."""
        rec, clock = self._clocked()
        rec.note_submit("q1", 4)
        clock[0] += 2.5
        rec.note_terminal("q1", "deadline")
        bd = rec.events("terminal")[0].attrs["breakdown"]
        assert bd["queue_s"] == 2.5
        assert bd["placement_s"] == 0.0 and bd["ttft_s"] == 0.0

    def test_chrome_trace_head_duration_event_stays_nonnegative(self):
        """Ring-overwrite head case: when the earliest retained event is
        a duration event (decode_chunk recorded at its END), its computed
        start must not land at a negative ts."""
        rec, clock = self._clocked()
        rec.note_decode_chunk(4, 0.010, 0.005, 2)  # recorded at end
        clock[0] += 1.0
        rec.note_submit("r", 4)
        rec.note_terminal("r", "stop")
        doc = to_chrome_trace(rec.events())
        for e in doc["traceEvents"]:
            if e["ph"] != "M":
                assert e["ts"] >= 0, e
        chunk = next(e for e in doc["traceEvents"]
                     if e["name"] == "decode_chunk")
        assert chunk["ts"] == 0.0  # the dump's origin is its true start

    def test_terminal_without_submit_is_tolerated(self):
        """A terminal for a request the recorder never saw (ring
        recycled mid-incident) records an empty breakdown, not a crash."""
        rec, _clock = self._clocked()
        rec.note_terminal("ghost", "error", error="boom")
        term = rec.events("terminal")[0]
        assert term.attrs["reason"] == "error"
        assert term.attrs["breakdown"]["tokens"] == 0

    def test_jsonl_dump_and_cli_chrome_export(self, tmp_path, capsys):
        rec, clock = self._clocked()
        _scripted_run(rec, clock)
        dump = str(tmp_path / "flight.jsonl")
        n = rec.dump_jsonl(dump)
        assert n == len(load_jsonl(dump)) == 4
        out = str(tmp_path / "trace.json")
        assert flight_main([dump, "-o", out]) == 0
        assert "1 terminals" in capsys.readouterr().out
        doc = json.load(open(out))
        self._check_chrome_schema(doc)

    def _check_chrome_schema(self, doc: dict) -> None:
        evs = doc["traceEvents"]
        assert isinstance(evs, list) and evs
        for e in evs:
            assert e["ph"] in ("M", "X", "i")
            assert e["pid"] == 1
            if e["ph"] != "M":
                assert isinstance(e["ts"], (int, float)) and e["ts"] >= 0
            if e["ph"] == "X":
                assert e["dur"] >= 0
        names = {e["name"] for e in evs}
        # The per-request phase rows and the terminal marker.
        assert {"queue", "placement", "decode"} <= names
        assert any(n.startswith("finish:") for n in names)
        # Request rows are named via thread_name metadata.
        assert any(
            e["ph"] == "M" and e["name"] == "thread_name"
            and e["args"]["name"] == "r1" for e in evs
        )

    def test_chrome_trace_engine_step_row(self):
        rec, _clock = self._clocked()
        rec.note_decode_chunk(4, 0.001, 0.002, 2)
        rec.note_mixed_step("r", 8, 8, 0.003)
        rec.note_prefill_piece("r", 8, 8, 0.004)
        rec.note_offload("s", 16)
        rec.note_restore("s", 1)
        doc = to_chrome_trace(rec.events())
        by_name = {}
        for e in doc["traceEvents"]:
            by_name.setdefault(e["name"], e)
        assert by_name["decode_chunk"]["ph"] == "X"
        assert by_name["decode_chunk"]["tid"] == 0
        assert by_name["decode_chunk"]["dur"] == pytest.approx(3000, abs=1)
        assert by_name["offload"]["ph"] == "i"
        # Per-chunk dispatch/sync histograms observed in µs.
        assert rec.hist["dispatch_us"].count == 1
        assert rec.hist["sync_us"].count == 1


class TestMockParity:
    def test_mock_records_engine_vocabulary(self):
        """The mock emits the IDENTICAL event vocabulary on a playback:
        hermetic tests see the same timeline shape the real engine
        records, and the terminal ledger reconciles exactly."""
        m = MockEngine([Scenario("hi", "hello")], flight_events=64)
        toks, fin = m.generate(m.tokenizer.encode("hi"), GREEDY)
        assert fin.finish_reason is FinishReason.STOP
        kinds = [e.kind for e in m._flight.events()]
        assert set(kinds) <= EVENTS
        assert kinds == ["submit", "claim", "placement", "terminal"]
        assert m.metrics["flight_enabled"] == 1
        term = m._flight.events("terminal")[0]
        bd = term.attrs["breakdown"]
        assert bd["tokens"] == len(toks) == 5
        assert bd["ttft_s"] >= 0 and bd["queue_s"] >= 0
        assert m._flight.hist["ttft"].count == 1
        # Ledger exactness: one terminal per accepted submit.
        assert len(m._flight.events("terminal")) == m.metrics["requests_finished"]
        assert len(m._flight.events("submit")) == m.metrics["requests_submitted"]

    def test_metrics_rebind_replaces_dead_engine(self):
        """Rebinding a registry to a replacement engine must repoint the
        collector — a first-wins register would keep exposing the dead
        engine's frozen counters while still passing the freshness stamp."""
        from omnia_tpu.utils.metrics import Registry, bind_engine_metrics

        old = MockEngine([Scenario(".*", "abc")], flight_events=16)
        old.generate(old.tokenizer.encode("x"), GREEDY)
        reg = Registry(prefix="omnia_facade")
        bind_engine_metrics(reg, old)
        assert "omnia_engine_requests_finished 1.0" in reg.expose()
        new = MockEngine([Scenario(".*", "abc")], flight_events=16)
        bind_engine_metrics(reg, new)  # provider reload: engine replaced
        assert "omnia_engine_requests_finished 0.0" in reg.expose()
        new.generate(new.tokenizer.encode("x"), GREEDY)
        body = reg.expose()
        assert "omnia_engine_requests_finished 1.0" in body
        # The replacement recorder's histograms took over too.
        assert "omnia_engine_ttft_seconds_count 1" in body
        # Rebinding to a recorder-LESS engine sweeps the old flight
        # histograms — frozen series from the dead engine must not
        # survive behind a passing freshness stamp.
        bind_engine_metrics(reg, MockEngine([], flight_events=0))
        swept = reg.expose()
        assert "omnia_engine_ttft_seconds" not in swept
        assert "omnia_engine_flight_enabled 0.0" in swept

    def test_doctor_presence_ignores_freshness_stamp(self):
        """The collector's own scrape_unixtime stamp must not satisfy
        the engine-family presence check: a collector bound to an empty
        source (mis-wired engine) exposes ONLY the stamp, and that is a
        FAIL, not '1 live engine series'."""
        from omnia_tpu.doctor import Doctor
        from omnia_tpu.utils.metrics import DictCollector, Registry

        reg = Registry(prefix="omnia_facade")
        reg.register(DictCollector("omnia_engine", lambda: {}))
        d = Doctor()
        d.add_engine_metrics_check(reg.expose)
        check = d.run()["checks"][0]
        assert check["status"] == "fail", check
        assert "no omnia_engine_* series" in check["detail"]

    def test_mock_shed_records_no_submit(self):
        """Rejected requests (validation/overload) never enter the
        flight books — submit events mirror requests_submitted, never
        requests_shed."""
        m = MockEngine([], flight_events=64, max_queue=0)
        h = m.submit([], GREEDY)  # validation reject: empty prompt
        _toks, fin = h.collect_tokens(timeout=5)
        assert fin.finish_reason is FinishReason.ERROR
        assert m._flight.events() == []


class TestTraceContinuity:
    def _fleet(self, fault_worker0: FaultPlan):
        w0 = MockEngine([Scenario(".*", "abcde")], flight_events=64,
                        fault_plan=fault_worker0)
        w1 = MockEngine([Scenario(".*", "abcde")], flight_events=64)
        w0.tracer = tr.Tracer("worker-0")
        w1.tracer = tr.Tracer("worker-1")
        coord = EngineCoordinator([w0, w1], flight_events=64,
                                  probe_timeout_s=None)
        return coord, w0, w1

    def test_traceparent_survives_pretoken_worker_death(self):
        """ISSUE 10 acceptance: one injected pre-token worker death —
        the request transparently resubmits, the coordinator records the
        failure as flight events, and BOTH workers' engine spans carry
        the SAME trace id as the caller's span (new events, not a new
        trace)."""
        plan = FaultPlan(die_after_tokens=0, die_count=1)
        coord, w0, w1 = self._fleet(plan)
        root = tr.Tracer("runtime").start_span("llm-turn")
        # Ties route to worker 0 (least-loaded min by (load, idx)), so
        # the counted death fires on the first placement.
        h = coord.submit(w0.tokenizer.encode("go"), GREEDY,
                         trace_ctx=root.traceparent())
        toks, fin = h.collect_tokens(timeout=30)
        assert fin.finish_reason is FinishReason.STOP
        assert w0.tokenizer.decode(toks) == "abcde"
        assert plan.fired["deaths"] == 1
        assert coord.metrics["resubmits"] == 1
        # The coordinator's flight trail shows the re-placement.
        coord_kinds = [e.kind for e in coord._flight.events()]
        assert "resubmit" in coord_kinds
        # Both workers opened engine-request spans under ONE trace id.
        s0 = w0.tracer.spans(tr.SPAN_ENGINE)
        s1 = w1.tracer.spans(tr.SPAN_ENGINE)
        assert len(s0) == 1 and len(s1) == 1
        assert s0[0].trace_id == s1[0].trace_id == root.trace_id
        # The dead worker's span closed with the error; the replacement
        # carries the real finish.
        assert s0[0].attrs["llm.finish_reason"] == "error"
        assert s1[0].attrs["llm.finish_reason"] == "stop"
        assert s1[0].attrs["engine.tokens"] == 5
        root.end()

    def test_submit_failover_reuses_trace_ctx(self):
        """A worker whose submit() raises is failed over — the
        replacement still receives the caller's trace context and the
        coordinator records the failover event."""
        plan = FaultPlan(flaky_submit=1)
        coord, w0, w1 = self._fleet(plan)
        root = tr.Tracer("runtime").start_span("llm-turn")
        h = coord.submit(w0.tokenizer.encode("go"), GREEDY,
                         trace_ctx=root.traceparent())
        _toks, fin = h.collect_tokens(timeout=30)
        assert fin.finish_reason is FinishReason.STOP
        assert [e.kind for e in coord._flight.events()].count("failover") == 1
        spans = w1.tracer.spans(tr.SPAN_ENGINE)
        assert len(spans) == 1 and spans[0].trace_id == root.trace_id
        root.end()

    def test_unsampled_parent_opens_no_engine_span(self):
        """Parent-based sampling holds end-to-end: an unsampled llm span
        (flags 00 — what a _NoopSpan propagates) must not resurrect as
        an engine span."""
        m = MockEngine([Scenario(".*", "hi")], flight_events=64)
        m.tracer = tr.Tracer("w")
        unsampled = tr.Tracer("up", sample_rate=0.0)
        noop = unsampled.start_span("llm")
        h = m.submit(m.tokenizer.encode("x"), GREEDY,
                     trace_ctx=noop.traceparent())
        h.collect_tokens(timeout=10)
        assert m.tracer.spans(tr.SPAN_ENGINE) == []
        # The flight books still record the lifecycle (tracing and
        # recording are independent planes).
        assert len(m._flight.events("terminal")) == 1


class TestConcurrentRecorders:
    def test_seeded_interleavings_keep_books_exact(self):
        """raceharness satellite: N threads drive full request
        lifecycles into ONE recorder under forced interleavings — the
        seq stream stays strictly contiguous, the ledger reconciles
        exactly (recorded == dropped + retained), every terminal closes
        its books, and the histograms count every request."""
        from raceharness import run_interleaved

        threads, per_thread = 4, 6

        def scenario():
            rec = FlightRecorder(32)

            def body_for(t):
                def body():
                    import time as _t

                    for i in range(per_thread):
                        rid = f"t{t}-r{i}"
                        rec.note_submit(rid, 4)
                        rec.note_claim(rid)
                        rec.note_placement(rid, 0, 4)
                        rec.note_terminal(rid, "stop", tokens=2,
                                          first_token_at=_t.monotonic())
                return body

            def check():
                stats = rec.stats()
                total = threads * per_thread * 4  # 4 ring events/request
                assert stats["recorded"] == total, stats
                assert stats["retained"] + stats["dropped"] == total
                assert stats["open_requests"] == 0
                seqs = [e.seq for e in rec.events()]
                assert seqs == sorted(seqs)
                assert seqs == list(range(seqs[0], seqs[0] + len(seqs)))
                assert rec.hist["ttft"].count == threads * per_thread
                n_term = threads * per_thread
                assert rec.hist["queue_wait"].count == n_term

            return [body_for(t) for t in range(threads)], check

        failures = run_interleaved(scenario, seeds=range(6))
        assert not failures, failures

    def test_concurrent_submit_vs_terminal_no_deadlock(self):
        """Submit path (caller thread) racing terminal path (engine
        thread) through the recorder must never deadlock — the regression
        shape of the nested-lock bug found during development."""
        rec = FlightRecorder(64)
        stop = threading.Event()

        def submits():
            i = 0
            while not stop.is_set():
                rec.note_submit(f"s{i}", 1)
                rec.note_terminal(f"s{i}", "stop")
                i += 1

        ts = [threading.Thread(target=submits, daemon=True) for _ in range(3)]
        for t in ts:
            t.start()
        import time as _time

        _time.sleep(0.2)
        stop.set()
        for t in ts:
            t.join(timeout=5)
        assert not any(t.is_alive() for t in ts)
        assert rec.stats()["open_requests"] == 0


# ---------------------------------------------------------------------------
# Real-engine suite (skips cleanly where jax is absent — the CI analysis
# job; tier-1 runs it on the CPU backend).
# ---------------------------------------------------------------------------


def _tiny_engine(**over):
    pytest.importorskip("jax")
    from omnia_tpu.engine import EngineConfig, InferenceEngine
    from omnia_tpu.models import get_config

    base = dict(num_slots=2, max_seq=64, prefill_buckets=(8,),
                dtype="float32", max_sessions=4, flight_events=512)
    base.update(over)
    return InferenceEngine(get_config("test-tiny"), EngineConfig(**base), seed=3)


class TestEngineLedger:
    def test_end_to_end_timeline_and_trace_continuity(self):
        """ISSUE 10 acceptance: one request traced end-to-end — the
        caller's span and the engine's `omnia.engine.request` span share
        a trace id, and the flight dump reconstructs a complete
        queue→placement→prefill→decode→finish timeline whose summed
        stages equal the request's wall time within 5%."""
        eng = _tiny_engine()
        tracer = tr.Tracer("engine-under-test")
        eng.tracer = tracer
        root = tr.Tracer("runtime").start_span("llm")
        h = eng.submit([1, 2, 3], GREEDY, trace_ctx=root.traceparent())
        while eng.step():
            pass
        toks, fin = h.collect_tokens(timeout=60)
        assert fin.finish_reason is FinishReason.LENGTH and len(toks) == 8
        evs = eng._flight.events()
        kinds = [e.kind for e in evs]
        # Complete lifecycle, in order.
        for a, b in zip(["submit", "claim", "placement", "terminal"],
                        ["claim", "placement", "terminal", None]):
            if b is not None:
                assert kinds.index(a) < kinds.index(b), kinds
        assert "prefill_piece" in kinds and "decode_chunk" in kinds
        assert set(kinds) <= EVENTS
        # Stage sum == wall within 5% (plus a tiny absolute epsilon for
        # scheduler bookkeeping between the stage boundaries).
        sub = next(e for e in evs if e.kind == "submit")
        term = next(e for e in evs if e.kind == "terminal")
        bd = term.attrs["breakdown"]
        wall = term.mono - sub.mono
        staged = bd["queue_s"] + bd["placement_s"] + bd["decode_s"]
        assert abs(staged - wall) <= 0.05 * wall + 0.02, (staged, wall, bd)
        assert bd["tokens"] == 8
        assert 0 < bd["ttft_s"] <= wall
        # Trace continuity: engine span under the caller's trace id,
        # breakdown stamped on the span.
        spans = tracer.spans(tr.SPAN_ENGINE)
        assert len(spans) == 1
        assert spans[0].trace_id == root.trace_id
        assert spans[0].parent_id == root.span_id
        assert spans[0].attrs["llm.finish_reason"] == "length"
        assert spans[0].attrs["engine.tokens"] == 8
        assert spans[0].end_ns >= spans[0].start_ns
        # Chrome export of the real run keeps the schema.
        doc = to_chrome_trace(evs)
        names = {e["name"] for e in doc["traceEvents"]}
        assert {"queue", "placement", "decode", "decode_chunk"} <= names
        root.end()

    def test_ledger_reconciles_with_terminal_counters(self):
        """Event-ledger exactness: submit events == requests_submitted,
        terminal events == requests_finished — across normal finishes
        AND a queue-cancelled request."""
        eng = _tiny_engine()
        handles = [eng.submit([1, 2, 3], GREEDY) for _ in range(3)]
        handles[2].cancel()  # reaped from the queue, still a terminal
        while eng.step():
            pass
        for h in handles:
            h.collect_tokens(timeout=60)
        assert len(eng._flight.events("submit")) == (
            eng.metrics["requests_submitted"]) == 3
        assert len(eng._flight.events("terminal")) == (
            eng.metrics["requests_finished"]) == 3
        reasons = sorted(
            e.attrs["reason"] for e in eng._flight.events("terminal")
        )
        assert reasons == ["cancelled", "length", "length"]
        assert eng._flight.stats()["open_requests"] == 0
        # Per-chunk dispatch/sync observations landed.
        assert eng._flight.hist["dispatch_us"].count > 0
        assert eng._flight.hist["sync_us"].count > 0

    def test_prometheus_bridge_and_doctor_freshness(self):
        """bind_engine_metrics exposes the live omnia_engine_* family +
        the recorder histograms through a Registry, and the doctor's
        engine-metrics check passes against it (present AND non-stale)."""
        from omnia_tpu.doctor import Doctor
        from omnia_tpu.utils.metrics import Registry, bind_engine_metrics

        eng = _tiny_engine()
        eng.generate([1, 2, 3], GREEDY)
        reg = Registry(prefix="omnia_facade")
        bind_engine_metrics(reg, eng)
        body = reg.expose()
        assert "omnia_engine_requests_finished 1.0" in body
        assert "omnia_engine_flight_enabled 1.0" in body
        assert "omnia_engine_ttft_seconds_count 1" in body
        assert "omnia_engine_dispatch_us_bucket" in body
        doctor = Doctor()
        doctor.add_engine_metrics_check(reg.expose)
        report = doctor.run()
        assert report["status"] == "pass", report
        # And the check has teeth: a frozen snapshot FAILS freshness.
        frozen = body
        stale = Doctor()
        stale.add_engine_metrics_check(lambda: frozen)
        assert stale.run()["checks"][0]["status"] == "fail"
        # An exposition with no engine family FAILS presence.
        empty = Doctor()
        empty.add_engine_metrics_check(lambda: "omnia_facade_x 1\n")
        assert empty.run()["checks"][0]["status"] == "fail"


def test_flight_off_is_true_noop():
    """KNOB_GUARDS row for EngineConfig.flight_events: 0 (default) must
    allocate ZERO recorder state, trace zero new operands (byte-identical
    lowered decode programs vs a flight-on engine — the layer is
    host-side by design), emit identical greedy tokens, and never open a
    span even when trace_ctx arrives."""
    pytest.importorskip("jax")
    off = _tiny_engine(flight_events=0, max_sessions=0)
    on = _tiny_engine(max_sessions=0)
    assert off._flight is None
    assert off.metrics["flight_enabled"] == 0
    assert on.metrics["flight_enabled"] == 1

    def lowered(eng):
        return eng._decode_fn_single.lower(
            eng.params, eng._ck, eng._cv, eng._tokens, eng._positions,
            eng._active, eng._budget, eng._stop_ids, eng._key_data,
            eng._temp, eng._top_p, eng._top_k,
        ).as_text()

    assert lowered(off) == lowered(on)
    # trace_ctx on a flight-off engine: accepted, ignored, no span.
    tracer = tr.Tracer("off-engine")
    off.tracer = tracer
    root = tr.Tracer("up").start_span("llm")
    t_off, _ = off.generate([4, 5, 6], GREEDY)
    h = off.submit([4, 5, 6], GREEDY, trace_ctx=root.traceparent())
    while off.step():
        pass
    t_ctx, _ = h.collect_tokens(timeout=60)
    t_on, _ = on.generate([4, 5, 6], GREEDY)
    assert t_off == t_on == t_ctx
    assert tracer.spans(tr.SPAN_ENGINE) == []
    root.end()


class TestConversationContinuity:
    def test_runtime_llm_span_and_engine_span_share_trace(self):
        """The full runtime path: Conversation's llm span rides submit()
        as trace_ctx, so the llm span and the engine's request span land
        in one trace — with the turn's conversation span as the root."""
        from omnia_tpu.runtime import contract as c
        from omnia_tpu.runtime.context_store import InMemoryContextStore
        from omnia_tpu.runtime.conversation import Conversation
        from omnia_tpu.runtime.packs import load_pack

        tracer = tr.Tracer("runtime-test")
        engine = MockEngine([Scenario(".*", "hello there")],
                            flight_events=64)
        engine.tracer = tracer
        conv = Conversation(
            session_id="flight-e2e",
            pack=load_pack({"name": "t", "version": "1.0.0",
                            "prompts": {"system": "s"},
                            "sampling": {"max_tokens": 64}}),
            engine=engine,
            tokenizer=engine.tokenizer,
            store=InMemoryContextStore(),
            tracer=tracer,
        )
        msgs = list(conv.stream(c.ClientMessage(content="hi")))
        assert msgs[-1].type == "done"
        conv_spans = tracer.spans(tr.SPAN_CONVERSATION)
        llm_spans = tracer.spans(tr.SPAN_LLM)
        eng_spans = tracer.spans(tr.SPAN_ENGINE)
        assert len(conv_spans) == 1 and len(llm_spans) == 1
        assert len(eng_spans) == 1
        assert eng_spans[0].trace_id == llm_spans[0].trace_id == (
            conv_spans[0].trace_id)
        assert eng_spans[0].parent_id == llm_spans[0].span_id
        # The flight terminal matched the turn's streamed tokens.
        bd = engine._flight.events("terminal")[0].attrs["breakdown"]
        assert bd["tokens"] == len("hello there")

    def test_legacy_engine_without_trace_ctx_still_serves(self):
        """Engines predating the trace_ctx kwarg are supported duck
        types: the conversation retries without it."""
        from omnia_tpu.runtime import contract as c
        from omnia_tpu.runtime.context_store import InMemoryContextStore
        from omnia_tpu.runtime.conversation import Conversation
        from omnia_tpu.runtime.packs import load_pack

        class LegacyEngine(MockEngine):
            def submit(self, prompt_tokens, params=SamplingParams(),
                       session_id=None, grammar=None, deadline_s=None):
                return super().submit(prompt_tokens, params,
                                      session_id=session_id)

        tracer = tr.Tracer("runtime-test")
        engine = LegacyEngine([Scenario(".*", "ok")])
        conv = Conversation(
            session_id="legacy",
            pack=load_pack({"name": "t", "version": "1.0.0",
                            "prompts": {"system": "s"},
                            "sampling": {"max_tokens": 16}}),
            engine=engine,
            tokenizer=engine.tokenizer,
            store=InMemoryContextStore(),
            tracer=tracer,
        )
        msgs = list(conv.stream(c.ClientMessage(content="hi")))
        assert msgs[-1].type == "done"
        assert tracer.spans(tr.SPAN_LLM)  # the llm span still exists
