"""Seeded interleaving fault injection — the systematic half of the race
discipline (SURVEY §5.2; VERDICT r3 weak #7).

CPython has no `-race` detector, so the honest equivalent is to FORCE
diverse thread interleavings deterministically and assert invariants
under each: every participating thread runs under a per-thread
`sys.settrace` hook that, with a seeded per-line probability, yields or
micro-sleeps — exploring schedules a plain stress loop would almost
never hit — while the global switch interval is dropped so the OS
scheduler cooperates. Each seed reproduces its schedule family, so a
failure prints the seed that found it.

Usage:
    def scenario():
        state = make_fresh_state()
        def body(): ...mutate state...
        def check(): ...assert invariants over state...
        return [body, body, body], check

    failures = run_interleaved(scenario, seeds=range(8))
    assert not failures, failures
"""

from __future__ import annotations

import random
import sys
import threading
import time
from typing import Callable, Iterable, Optional, Sequence


class InterleaveRun:
    """One seeded schedule family over a set of thread bodies."""

    def __init__(self, seed: int, jitter_prob: float = 0.04,
                 sleeps=(0.0, 1e-5, 1e-4)):
        self.seed = seed
        self.jitter_prob = jitter_prob
        self.sleeps = sleeps

    def _wrap(self, index: int, body: Callable[[], None],
              errors: list, barrier: threading.Barrier):
        rng = random.Random((self.seed << 16) ^ index)

        def trace(frame, event, arg):
            if event == "line" and rng.random() < self.jitter_prob:
                time.sleep(rng.choice(self.sleeps))
            return trace

        def runner():
            try:
                barrier.wait(timeout=30)  # maximal contention at the start
                sys.settrace(trace)
                try:
                    body()
                finally:
                    sys.settrace(None)
            except Exception as e:  # noqa: BLE001 - collected for asserts
                errors.append(f"seed={self.seed} thread={index}: {e!r}")

        # daemon: a genuinely-deadlocked schedule must FAIL the test, not
        # hang interpreter shutdown joining the stuck thread.
        return threading.Thread(target=runner, name=f"race-{index}", daemon=True)

    def run(self, bodies: Sequence[Callable[[], None]],
            timeout_s: float = 60.0) -> list[str]:
        errors: list[str] = []
        barrier = threading.Barrier(len(bodies))
        old_interval = sys.getswitchinterval()
        sys.setswitchinterval(1e-5)
        try:
            threads = [self._wrap(i, b, errors, barrier)
                       for i, b in enumerate(bodies)]
            for t in threads:
                t.start()
            deadline = time.monotonic() + timeout_s
            for t in threads:
                t.join(timeout=max(0.0, deadline - time.monotonic()))
            stuck = [t.name for t in threads if t.is_alive()]
            if stuck:
                errors.append(f"seed={self.seed} DEADLOCK: {stuck} still alive")
        finally:
            sys.setswitchinterval(old_interval)
        return errors


def run_interleaved(
    scenario: Callable[[], tuple[Sequence[Callable[[], None]],
                                 Optional[Callable[[], None]]]],
    seeds: Iterable[int] = range(6),
    timeout_s: float = 60.0,
) -> list[str]:
    """Run a scenario under each seed's schedule family.

    scenario: () -> (bodies, check) — FRESH state per seed so one seed's
    corruption cannot mask another's; `check` (may be None) asserts the
    seed's post-run invariants against that state and raises on
    violation. Returns all failures across seeds (empty == clean).
    """
    failures: list[str] = []
    for seed in seeds:
        bodies, check = scenario()
        run_failures = InterleaveRun(seed).run(bodies, timeout_s=timeout_s)
        failures += run_failures
        if any("DEADLOCK" in f for f in run_failures):
            # Stuck threads are still mutating the state — running the
            # invariant check now would only bury the real diagnosis
            # under spurious failures.
            continue
        if check is not None:
            try:
                check()
            except Exception as e:  # noqa: BLE001
                failures.append(f"seed={seed} invariant: {e!r}")
    return failures
