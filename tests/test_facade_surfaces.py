"""REST / MCP / A2A facade surface tests against a live runtime."""

from __future__ import annotations

import json
import urllib.error
import urllib.request

import pytest

from omnia_tpu.facade import A2aFacade, McpFacade, RestFacade
from omnia_tpu.facade.auth import AuthChain, ClientKeyValidator
from omnia_tpu.runtime.packs import load_pack
from omnia_tpu.runtime.providers import ProviderRegistry, ProviderSpec
from omnia_tpu.runtime.server import RuntimeServer

PACK = {
    "name": "fn-agent",
    "version": "1.0.0",
    "prompts": {"system": "You classify text."},
    "sampling": {"temperature": 0.0, "max_tokens": 256},
    "functions": [
        {
            "name": "classify",
            "description": "Classify sentiment",
            "input_schema": {"type": "object", "required": ["text"]},
            "output_schema": {"type": "object", "required": ["label"]},
            "prompt": "Classify: {{input}}",
        }
    ],
}

SCENARIOS = [
    {"pattern": "Classify.*terrible", "reply": "not json at all"},
    {"pattern": "Classify", "reply": '{"label": "positive"}'},
    {"pattern": "hello", "reply": "hi from rest"},
]


@pytest.fixture(scope="module")
def runtime():
    reg = ProviderRegistry()
    reg.register(ProviderSpec(name="m", type="mock", options={"scenarios": SCENARIOS}))
    rt = RuntimeServer(pack=load_pack(PACK), providers=reg, provider_name="m")
    port = rt.serve("localhost:0")
    yield f"localhost:{port}"
    rt.shutdown()


def _post(url, body, token=None, expect_error=False):
    headers = {"Content-Type": "application/json"}
    if token:
        headers["Authorization"] = f"Bearer {token}"
    req = urllib.request.Request(url, data=json.dumps(body).encode(), headers=headers)
    try:
        with urllib.request.urlopen(req) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as e:
        if not expect_error:
            raise
        return e.code, json.loads(e.read() or b"{}")


class TestRestFacade:
    def test_function_invoke_and_status_mapping(self, runtime):
        facade = RestFacade(runtime_target=runtime, agent_name="fn-agent")
        port = facade.serve()
        base = f"http://localhost:{port}"
        try:
            status, out = _post(base + "/functions/classify", {"text": "great stuff"})
            assert status == 200 and out["output"] == {"label": "positive"}
            assert out["usage"]["completion_tokens"] > 0
            # caller's bad input → 400
            status, out = _post(base + "/functions/classify", {"nope": 1}, expect_error=True)
            assert status == 400 and out["error"] == "bad_input"
            # model's bad output → 502 (runtime's fault)
            status, out = _post(base + "/functions/classify", {"text": "terrible"},
                                expect_error=True)
            assert status == 502 and out["error"] == "bad_output"
            # unknown function → 404
            status, _ = _post(base + "/functions/ghost", {}, expect_error=True)
            assert status == 404
            # function listing
            with urllib.request.urlopen(base + "/v1/functions") as resp:
                fns = json.loads(resp.read())["functions"]
            assert fns[0]["name"] == "classify"
        finally:
            facade.shutdown()

    def test_rest_chat_and_auth(self, runtime):
        facade = RestFacade(
            runtime_target=runtime, agent_name="fn-agent",
            auth_chain=AuthChain([ClientKeyValidator({"kid": "sekret"})]),
        )
        port = facade.serve()
        base = f"http://localhost:{port}"
        try:
            status, _ = _post(base + "/v1/chat", {"content": "hello"}, expect_error=True)
            assert status == 401
            status, out = _post(base + "/v1/chat", {"content": "hello"}, token="sekret")
            assert status == 200 and out["content"] == "hi from rest"
            assert out["finish_reason"] == "stop"
        finally:
            facade.shutdown()

    def test_drain_rejects_new_work(self, runtime):
        facade = RestFacade(runtime_target=runtime)
        port = facade.serve()
        base = f"http://localhost:{port}"
        try:
            facade.drain()
            status, _ = _post(base + "/v1/chat", {"content": "hello"}, expect_error=True)
            assert status == 503
            with urllib.request.urlopen(base + "/healthz") as resp:
                assert resp.status == 200  # liveness unaffected
        finally:
            facade.shutdown()


class TestMcpFacade:
    @pytest.fixture()
    def mcp(self, runtime):
        facade = McpFacade(runtime_target=runtime, agent_name="fn-agent")
        port = facade.serve()
        yield f"http://localhost:{port}/mcp"
        facade.shutdown()

    def _rpc(self, url, method, params=None, rpc_id=1):
        body = {"jsonrpc": "2.0", "id": rpc_id, "method": method}
        if params is not None:
            body["params"] = params
        return _post(url, body)

    def test_initialize_and_list(self, mcp):
        status, out = self._rpc(mcp, "initialize", {})
        assert status == 200
        assert out["result"]["serverInfo"]["name"] == "fn-agent"
        _, out = self._rpc(mcp, "tools/list")
        tools = out["result"]["tools"]
        assert tools[0]["name"] == "classify"
        assert tools[0]["inputSchema"]["type"] == "object"

    def test_tools_call_success_and_error(self, mcp):
        _, out = self._rpc(mcp, "tools/call",
                           {"name": "classify", "arguments": {"text": "nice"}})
        content = out["result"]["content"][0]["text"]
        assert json.loads(content) == {"label": "positive"}
        assert out["result"]["isError"] is False
        # execution error → isError result, not protocol error
        _, out = self._rpc(mcp, "tools/call",
                           {"name": "classify", "arguments": {"text": "terrible"}})
        assert out["result"]["isError"] is True
        # unknown tool → invalid params protocol error
        _, out = self._rpc(mcp, "tools/call", {"name": "ghost", "arguments": {}})
        assert out["error"]["code"] == -32602

    def test_unknown_method_and_notification(self, mcp):
        _, out = self._rpc(mcp, "resources/list")
        assert out["error"]["code"] == -32601
        status, _ = _post(mcp, {"jsonrpc": "2.0", "method": "notifications/initialized"})
        assert status == 202


class TestA2aFacade:
    @pytest.fixture()
    def a2a(self, runtime):
        facade = A2aFacade(runtime_target=runtime, agent_name="fn-agent",
                           description="classifies text")
        port = facade.serve()
        yield facade, f"http://localhost:{port}"
        facade.shutdown()

    def test_agent_card(self, a2a):
        _, base = a2a
        with urllib.request.urlopen(base + "/.well-known/agent.json") as resp:
            card = json.loads(resp.read())
        assert card["name"] == "fn-agent"
        assert card["protocolVersion"]
        assert card["url"].startswith("http://")

    def test_message_send_and_task_roundtrip(self, a2a):
        _, base = a2a
        _, out = _post(base + "/", {
            "jsonrpc": "2.0", "id": 1, "method": "message/send",
            "params": {"message": {
                "role": "user", "kind": "message", "messageId": "m1",
                "parts": [{"kind": "text", "text": "hello"}]}},
        })
        task = out["result"]
        assert task["status"]["state"] == "completed"
        reply = task["artifacts"][0]["parts"][0]["text"]
        assert reply == "hi from rest"
        # tasks/get returns the stored task
        _, out2 = _post(base + "/", {"jsonrpc": "2.0", "id": 2, "method": "tasks/get",
                                     "params": {"id": task["id"]}})
        assert out2["result"]["id"] == task["id"]
        # cancel on a terminal task is idempotent
        _, out3 = _post(base + "/", {"jsonrpc": "2.0", "id": 3, "method": "tasks/cancel",
                                     "params": {"id": task["id"]}})
        assert out3["result"]["status"]["state"] == "completed"

    def test_same_context_resumes_conversation(self, a2a, runtime):
        facade, base = a2a

        def send(text, ctx=None):
            msg = {"role": "user", "kind": "message", "messageId": "m",
                   "parts": [{"kind": "text", "text": text}]}
            if ctx:
                msg["contextId"] = ctx
            _, out = _post(base + "/", {"jsonrpc": "2.0", "id": 1,
                                        "method": "message/send",
                                        "params": {"message": msg}})
            return out["result"]

        t1 = send("hello")
        ctx = t1["contextId"]
        t2 = send("hello", ctx=ctx)
        assert t2["contextId"] == ctx
        assert t2["id"] != t1["id"]  # new task, same conversation

    def test_bad_params_is_invalid_params(self, a2a):
        _, base = a2a
        _, out = _post(base + "/", {"jsonrpc": "2.0", "id": 1, "method": "message/send",
                                    "params": {"message": {"parts": []}}})
        assert out["error"]["code"] == -32602


CLIENT_TOOL_PACK = {
    "name": "ct-agent",
    "version": "1.0.0",
    "prompts": {"system": "s"},
    "tools": [{"name": "lookup", "client_side": True}],
    "sampling": {"temperature": 0.0, "max_tokens": 256},
}

CLIENT_TOOL_SCENARIOS = [
    {"pattern": "needs the client",
     "reply": '<tool_call>{"name": "lookup", "arguments": {}}</tool_call>'},
    {"pattern": ".", "reply": "plain"},
]


@pytest.fixture(scope="module")
def ct_runtime():
    from omnia_tpu.tools import ToolExecutor, ToolHandler

    reg = ProviderRegistry()
    reg.register(ProviderSpec(name="m", type="mock",
                              options={"scenarios": CLIENT_TOOL_SCENARIOS}))
    rt = RuntimeServer(pack=load_pack(CLIENT_TOOL_PACK), providers=reg,
                       provider_name="m",
                       tool_executor=ToolExecutor([ToolHandler(name="lookup", type="client")]))
    port = rt.serve("localhost:0")
    yield f"localhost:{port}"
    rt.shutdown()


class TestClientToolCancellation:
    def test_rest_chat_cancels_turn_not_blocks_session(self, ct_runtime):
        import time

        facade = RestFacade(runtime_target=ct_runtime, agent_name="ct-agent")
        port = facade.serve()
        base = f"http://localhost:{port}"
        try:
            t0 = time.monotonic()
            status, _ = _post(base + "/v1/chat", {"content": "this needs the client tool"},
                              expect_error=True)
            assert status == 501
            assert time.monotonic() - t0 < 10  # no 60s client-tool wait
            # same session must NOT be blocked behind a held turn lock
            t0 = time.monotonic()
            status, out = _post(base + "/v1/chat", {"content": "say something plain"})
            assert status == 200 and out["content"] == "plain"
            assert time.monotonic() - t0 < 10
        finally:
            facade.shutdown()

    def test_a2a_client_tool_fails_fast(self, ct_runtime):
        import time

        facade = A2aFacade(runtime_target=ct_runtime, agent_name="ct-agent")
        port = facade.serve()
        try:
            t0 = time.monotonic()
            _, out = _post(f"http://localhost:{port}/", {
                "jsonrpc": "2.0", "id": 1, "method": "message/send",
                "params": {"message": {"role": "user", "kind": "message", "messageId": "m",
                                       "parts": [{"kind": "text", "text": "this needs the client tool"}]}},
            })
            task = out["result"]
            assert task["status"]["state"] == "failed"
            assert "client tools" in task["status"]["message"]["parts"][0]["text"]
            assert time.monotonic() - t0 < 10
        finally:
            facade.shutdown()


class TestA2aDurableTasks:
    def test_task_survives_facade_restart(self, runtime):
        """VERDICT r4 #8: tasks live in Redis with a TTL (reference
        redis_task_store.go) — a client can poll tasks/get after the
        facade pod that ran the turn is gone."""
        from omnia_tpu.facade.a2a import RedisTaskStore
        from omnia_tpu.redis import RedisClient, RedisServer

        rsrv = RedisServer().start()
        try:
            def make_facade():
                f = A2aFacade(
                    runtime_target=runtime, agent_name="durable-agent",
                    task_store=RedisTaskStore(
                        RedisClient(*rsrv.address), ttl_s=60.0
                    ),
                )
                return f, f"http://localhost:{f.serve()}"

            facade1, base1 = make_facade()
            _, out = _post(base1 + "/", {
                "jsonrpc": "2.0", "id": 1, "method": "message/send",
                "params": {"message": {
                    "role": "user", "kind": "message", "messageId": "m1",
                    "parts": [{"kind": "text", "text": "hello"}]}},
            })
            task = out["result"]
            assert task["status"]["state"] == "completed"
            facade1.shutdown()  # pod dies

            facade2, base2 = make_facade()  # replacement pod, same Redis
            try:
                _, out2 = _post(base2 + "/", {
                    "jsonrpc": "2.0", "id": 2, "method": "tasks/get",
                    "params": {"id": task["id"]}})
                got = out2["result"]
                assert got["id"] == task["id"]
                assert got["status"]["state"] == "completed"
                assert got["artifacts"] == task["artifacts"]
                # cancel on the resumed terminal task stays idempotent
                _, out3 = _post(base2 + "/", {
                    "jsonrpc": "2.0", "id": 3, "method": "tasks/cancel",
                    "params": {"id": task["id"]}})
                assert out3["result"]["status"]["state"] == "completed"
            finally:
                facade2.shutdown()
        finally:
            rsrv.stop()

    def test_inmemory_store_enforces_max_tasks_cap(self):
        """Regression: the size cap must survive refactors — without it a
        client minting tasks faster than TTL expiry OOMs the facade."""
        from omnia_tpu.facade.a2a import TaskStore

        store = TaskStore(ttl_s=3600.0, max_tasks=3)
        for i in range(10):
            store.put({"id": f"t{i}", "status": {"state": "completed"},
                       "artifacts": []})
        assert len(store._tasks) <= 3
        assert store.get("t9") is not None  # newest survives

    def test_redis_store_ttl_and_transition_guard(self):
        from omnia_tpu.facade.a2a import RedisTaskStore
        from omnia_tpu.redis import RedisClient, RedisServer

        rsrv = RedisServer().start()
        try:
            store = RedisTaskStore(RedisClient(*rsrv.address), ttl_s=60.0)
            store.put({"id": "t1", "status": {"state": "working"},
                       "artifacts": []})
            assert store.get("t1")["status"]["state"] == "working"
            # unless_state guard: a cancelled task is not overwritten
            store.transition("t1", {"state": "canceled"})
            after = store.transition(
                "t1", {"state": "completed"}, unless_state=("canceled",)
            )
            assert after["status"]["state"] == "canceled"
            assert store.get("missing") is None
        finally:
            rsrv.stop()


class TestA2aIsolation:
    def test_tasks_scoped_to_principal(self, runtime):
        from omnia_tpu.facade.auth import AuthChain, ClientKeyValidator

        facade = A2aFacade(
            runtime_target=runtime, agent_name="fn-agent",
            auth_chain=AuthChain([ClientKeyValidator({"alice": "key-a", "bob": "key-b"})]),
        )
        port = facade.serve()
        base = f"http://localhost:{port}"
        try:
            _, out = _post(base + "/", {
                "jsonrpc": "2.0", "id": 1, "method": "message/send",
                "params": {"message": {"role": "user", "kind": "message", "messageId": "m",
                                       "parts": [{"kind": "text", "text": "hello"}]}},
            }, token="key-a")
            task = out["result"]
            assert "_owner" not in task  # internals never on the wire
            # bob cannot read alice's task...
            _, out = _post(base + "/", {"jsonrpc": "2.0", "id": 2, "method": "tasks/get",
                                        "params": {"id": task["id"]}}, token="key-b")
            assert out["error"]["code"] == -32602
            # ...nor hijack its id via message/send
            _, out = _post(base + "/", {
                "jsonrpc": "2.0", "id": 3, "method": "message/send",
                "params": {"message": {"role": "user", "kind": "message", "messageId": "m",
                                       "taskId": task["id"],
                                       "parts": [{"kind": "text", "text": "steal"}]}},
            }, token="key-b")
            assert out["error"]["code"] == -32602
            # alice still sees her own
            _, out = _post(base + "/", {"jsonrpc": "2.0", "id": 4, "method": "tasks/get",
                                        "params": {"id": task["id"]}}, token="key-a")
            assert out["result"]["status"]["state"] == "completed"
        finally:
            facade.shutdown()
