"""BASELINE config 4 shape, end to end: an operator-deployed
tool-calling agent whose ToolRegistry mixes gRPC (omnia.tools.v1
ToolService) and MCP (stdio) handlers — the conversation loop executes
BOTH remote transports mid-turn, driven over the live WebSocket facade.

This is the staged benchmark config VERDICT r4 said the missing
grpc/mcp dispatch blocked; with the transports landed, the whole chain
is a test: CRDs → controller → in-process pod → WS turn → tool_call
events → gRPC/MCP backends → final answer.
"""

import json
import os
import sys
import time

import pytest
from websockets.sync.client import connect

from omnia_tpu.operator.controller import ControllerManager
from omnia_tpu.operator.resources import Resource
from omnia_tpu.operator.store import MemoryResourceStore
from omnia_tpu.tools.grpc_transport import GrpcToolServer

MCP_FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures",
                           "mcp_stdio_server.py")

PACK = {
    "name": "support-pack",
    "version": "1.0.0",
    "prompts": {"system": "You are a billing support agent."},
    "sampling": {"temperature": 0.0, "max_tokens": 128},
}


@pytest.fixture()
def grpc_billing():
    srv = GrpcToolServer({
        "quote": (lambda a: {"refund_usd": round(a["amount"] * 0.9, 2)},
                  "quotes a refund", None),
    }).start()
    yield srv
    srv.stop()


def _scenarios():
    """Mock LLM that chains BOTH tools: gRPC quote, then MCP lookup,
    then answers from their results. The mock is first-match-wins over
    the ACCUMULATED turn view (earlier tool results stay visible), so
    the terminal pattern comes first and each pattern keys on the
    NEWEST marker the previous round introduced."""
    return [
        {"pattern": r"T-7",                # after the MCP result: answer
         "reply": "your 90.0 refund is attached to ticket T-7"},
        {"pattern": r"refund_usd.*90\.0",  # after the gRPC result
         "reply": '<tool_call>{"name": "ticket_lookup", '
                  '"arguments": {"id": "T-7"}}</tool_call>'},
        {"pattern": ".",                   # first round: call the gRPC tool
         "reply": '<tool_call>{"name": "refund_quote", '
                  '"arguments": {"amount": 100}}</tool_call>'},
    ]


def test_config4_grpc_and_mcp_tools_through_operator(grpc_billing):
    store = MemoryResourceStore()
    cm = ControllerManager(store)
    try:
        store.apply(Resource(kind="Provider", name="mock-llm", spec={
            "type": "mock", "role": "llm",
            "options": {"scenarios": _scenarios()},
        }))
        store.apply(Resource(kind="PromptPack", name="support-pack",
                             spec={"content": PACK}))
        store.apply(Resource(kind="ToolRegistry", name="support-tools", spec={
            "probe": {"enabled": False},
            "tools": [
                {"name": "refund_quote",
                 "description": "quote a refund via the billing ToolService",
                 "handler": {"type": "grpc", "remoteName": "quote",
                             "grpcConfig": {"endpoint": grpc_billing.endpoint},
                             "timeoutSeconds": 10}},
                {"name": "ticket_lookup",
                 "description": "fetch a ticket from the MCP server",
                 "handler": {"type": "mcp", "remoteName": "echo",
                             "mcpConfig": {"transport": "stdio",
                                           "command": sys.executable,
                                           "args": [MCP_FIXTURE]},
                             "timeoutSeconds": 15}},
            ],
        }))
        store.apply(Resource(kind="AgentRuntime", name="support-agent", spec={
            "mode": "agent",
            "promptPackRef": {"name": "support-pack"},
            "toolRegistryRef": {"name": "support-tools"},
            "providers": [{"name": "main",
                           "providerRef": {"name": "mock-llm"}}],
            "facades": [{"type": "websocket"}],
            "replicas": 1,
        }))
        cm.drain_queue()
        res = store.get("default", "AgentRuntime", "support-agent")
        assert res.status["phase"] == "Running", res.status
        url = res.status["endpoints"][0]["url"]

        tool_calls, chunks, done = [], [], None
        with connect(url, open_timeout=15) as ws:
            json.loads(ws.recv(timeout=15))  # connected
            ws.send(json.dumps({"type": "message",
                                "content": "I want a refund on my $100 order"}))
            deadline = time.time() + 60
            while time.time() < deadline:
                msg = json.loads(ws.recv(timeout=60))
                if msg["type"] == "tool_call":
                    tool_calls.append(msg["tool_call"]["name"])
                elif msg["type"] == "chunk":
                    chunks.append(msg["text"])
                elif msg["type"] in ("done", "error"):
                    done = msg
                    break
        assert done is not None and done["type"] == "done", done
        text = "".join(chunks)
        assert "90.0" in text and "T-7" in text, text
        # server-side tools execute server-side: they surface as events,
        # never as client-suspension tool_calls
        assert tool_calls == []
    finally:
        cm.shutdown()
