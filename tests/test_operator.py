"""Operator plane tests: resources, admission, store watch, deployment
builder, capability gate, autoscaling, rollout.

Mirrors the reference's controller/envtest coverage (reconcile → pod,
capability gate scale-to-zero, KEDA trigger, rollout promote/rollback)
with the in-process pod backend standing in for kubelet."""

import json
import time

import pytest

from omnia_tpu.operator import (
    AgentDeployment,
    Autoscaler,
    AutoscalingPolicy,
    ControllerManager,
    FileResourceStore,
    K8sManifestBackend,
    MemoryResourceStore,
    Resource,
    ValidationError,
)
from omnia_tpu.operator.rollout import RolloutPhase

PACK_CONTENT = {
    "name": "op-agent",
    "version": "1.0.0",
    "prompts": {"system": "You are an operator-managed assistant."},
    "sampling": {"temperature": 0.0, "max_tokens": 64},
}


def _resources(agent_extra=None, ns="default"):
    provider = Resource(
        kind="Provider",
        name="mock-llm",
        namespace=ns,
        spec={
            "type": "mock",
            "role": "llm",
            "options": {"scenarios": [{"pattern": "hello", "reply": "hi from pod"}]},
        },
    )
    pack = Resource(
        kind="PromptPack", name="op-pack", namespace=ns, spec={"content": PACK_CONTENT}
    )
    agent_spec = {
        "mode": "agent",
        "promptPackRef": {"name": "op-pack"},
        "providers": [{"name": "main", "providerRef": {"name": "mock-llm"}}],
        "facades": [{"type": "websocket"}],
        "replicas": 1,
    }
    agent_spec.update(agent_extra or {})
    agent = Resource(kind="AgentRuntime", name="op-agent", namespace=ns, spec=agent_spec)
    return provider, pack, agent


# -- resources & validation -------------------------------------------


def test_manifest_round_trip():
    r = Resource(kind="Provider", name="p", spec={"type": "mock"}, labels={"a": "b"})
    m = r.to_manifest()
    r2 = Resource.from_manifest(m)
    assert (r2.kind, r2.name, r2.spec, r2.labels) == (r.kind, r.name, r.spec, r.labels)


@pytest.mark.parametrize(
    "kind,spec,needle",
    [
        ("AgentRuntime", {"mode": "bogus", "promptPackRef": {"name": "x"}, "providers": [{"name": "a", "providerRef": {"name": "p"}}]}, "mode"),
        ("AgentRuntime", {"promptPackRef": {"name": "x"}, "providers": []}, "providers"),
        ("AgentRuntime", {"mode": "agent", "promptPackRef": {"name": "x"}, "providers": [{"name": "a", "providerRef": {"name": "p"}}], "facades": [{"type": "mcp"}]}, "mcp facade requires"),
        ("Provider", {"type": "openai"}, "type"),
        ("Provider", {"type": "tpu"}, "model"),
        ("PromptPack", {"content": {"name": "x"}}, "version"),
        ("ToolRegistry", {"tools": [{"name": "t", "handler": {"type": "carrier-pigeon"}}]}, "handler.type"),
        ("SessionRetentionPolicy", {"hotIdleSeconds": 100, "warmWindowSeconds": 10}, "windows"),
        ("AgentPolicy", {"allowTools": ["a"], "denyTools": ["a"]}, "both"),
    ],
)
def test_admission_rejects(kind, spec, needle):
    with pytest.raises(ValidationError) as ei:
        MemoryResourceStore().apply(Resource(kind=kind, name="x", spec=spec))
    assert needle in str(ei.value)


def test_unknown_kind_fails_closed():
    with pytest.raises(ValidationError):
        MemoryResourceStore().apply(Resource(kind="Gadget", name="x"))


# -- store -------------------------------------------------------------


def test_store_watch_and_generation():
    store = MemoryResourceStore()
    events = []
    store.watch(lambda ev, r: events.append((ev, r.name, r.generation)))
    p, _, _ = _resources()
    store.apply(p)
    p2 = Resource(kind="Provider", name="mock-llm", spec=dict(p.spec))
    store.apply(p2)
    store.delete("default", "Provider", "mock-llm")
    assert [e[0] for e in events] == ["ADDED", "MODIFIED", "DELETED"]
    assert events[1][2] == 2  # generation bumped


def test_status_subresource_does_not_bump_generation():
    store = MemoryResourceStore()
    p, _, _ = _resources()
    store.apply(p)
    store.update_status(p, {"phase": "Ready"})
    got = store.get("default", "Provider", "mock-llm")
    assert got.status["phase"] == "Ready" and got.generation == 1


def test_file_store_persistence_and_external_sync(tmp_path):
    root = str(tmp_path / "devroot")
    store = FileResourceStore(root)
    p, pack, _ = _resources()
    store.apply(p)
    store.apply(pack)
    # A fresh store instance reads back the same resources.
    store2 = FileResourceStore(root)
    assert store2.get("default", "Provider", "mock-llm") is not None
    assert store2.get("default", "PromptPack", "op-pack").spec["content"]["name"] == "op-agent"
    # kubectl-apply-equivalent: drop a YAML into the tree, then sync.
    import yaml

    doc = Resource(
        kind="Workspace", name="team-a", spec={"environment": "dev"}
    ).to_manifest()
    (tmp_path / "devroot" / "extra.yaml").write_text(yaml.safe_dump(doc))
    store2.sync()
    assert store2.get("default", "Workspace", "team-a") is not None


# -- manifest rendering ------------------------------------------------


def test_k8s_manifest_renders_tpu_placement():
    _, _, agent = _resources(
        agent_extra={
            "podOverrides": {
                "nodeSelector": {"cloud.google.com/gke-tpu-accelerator": "tpu-v5-lite-podslice"},
                "tolerations": [{"key": "google.com/tpu", "operator": "Exists"}],
            },
            "tpuChips": 8,
        }
    )
    dep = AgentDeployment(
        resource=agent,
        pack_doc=PACK_CONTENT,
        provider_specs=[{"name": "main", "type": "mock"}],
        default_provider="main",
    )
    out = K8sManifestBackend().render(dep)
    podspec = out["deployment"]["spec"]["template"]["spec"]
    assert podspec["nodeSelector"]["cloud.google.com/gke-tpu-accelerator"]
    names = [c["name"] for c in podspec["containers"]]
    assert names == ["facade", "runtime"]
    assert podspec["containers"][1]["resources"]["limits"]["google.com/tpu"] == 8
    assert out["deployment"]["metadata"]["annotations"]["omnia/config-hash"]
    # Config change changes the hash (restart trigger).
    dep.pack_doc = {**PACK_CONTENT, "version": "1.0.1"}
    assert K8sManifestBackend().render(dep)["deployment"]["metadata"]["annotations"][
        "omnia/config-hash"
    ] != out["deployment"]["metadata"]["annotations"]["omnia/config-hash"]


# -- autoscaler --------------------------------------------------------


def test_autoscaler_scales_on_queue_depth():
    s = Autoscaler(AutoscalingPolicy(min_replicas=0, max_replicas=4, target_queue_depth=8))
    now = 1000.0
    assert s.desired_replicas(1, total_queue_depth=30, active_connections=5, now=now) == 4
    assert s.desired_replicas(1, total_queue_depth=9, active_connections=1, now=now) == 2
    # Busy but empty queue: hold current.
    assert s.desired_replicas(2, 0, 3, now=now) == 2


def test_autoscaler_scale_to_zero_needs_idle_window():
    p = AutoscalingPolicy(min_replicas=0, max_replicas=4, scale_to_zero_after_idle_s=300, stabilization_s=0)
    s = Autoscaler(p)
    now = 1000.0
    s.desired_replicas(1, 5, 1, now=now)  # busy
    assert s.desired_replicas(1, 0, 0, now=now + 100) == 1  # not idle long enough
    assert s.desired_replicas(1, 0, 0, now=now + 400) == 0  # idle window passed
    # KEDA activation: any load from zero wakes one replica.
    assert s.desired_replicas(0, 1, 0, now=now + 500) == 1


def test_autoscaler_stabilization_blocks_flapping():
    p = AutoscalingPolicy(min_replicas=1, max_replicas=8, target_queue_depth=8, stabilization_s=60)
    s = Autoscaler(p)
    now = 1000.0
    assert s.desired_replicas(1, 64, 0, now=now) == 8
    assert s.desired_replicas(8, 8, 0, now=now + 1) == 8  # down blocked
    assert s.desired_replicas(8, 8, 0, now=now + 61) == 1  # allowed after window


# -- controller end-to-end --------------------------------------------


@pytest.fixture(params=["memory", "kube"])
def manager(request):
    """Controller tests run UNMODIFIED over both the in-process store and
    the KubeResourceStore backed by the in-tree apiserver shim — the
    same-suite-through-every-backend discipline the reference gets from
    envtest (ISSUE 1 acceptance criterion)."""
    if request.param == "memory":
        store = MemoryResourceStore()
        cm = ControllerManager(store)
        yield store, cm
        cm.shutdown()
    else:
        from omnia_tpu.kube.apiserver import ApiServerShim
        from omnia_tpu.kube.client import KubeClient
        from omnia_tpu.kube.store import KubeResourceStore

        shim = ApiServerShim(register_omnia_crds=True).start()
        store = KubeResourceStore(
            client=KubeClient(shim.local_config()),
            backoff_base_s=0.02, backoff_cap_s=0.2,
        )
        cm = ControllerManager(store)
        yield store, cm
        cm.shutdown()
        store.close()
        shim.stop()


def test_reconcile_brings_up_agent_and_serves_ws(manager):
    store, cm = manager
    provider, pack, agent = _resources()
    store.apply(provider)
    store.apply(pack)
    store.apply(agent)
    cm.drain_queue()

    res = store.get("default", "AgentRuntime", "op-agent")
    assert res.status["phase"] == "Running"
    assert res.status["replicas"] == 1
    eps = res.status["endpoints"]
    assert len(eps) == 1 and eps[0]["weight"] == 100.0

    # Drive a real WS chat turn through the operator-built pod.
    from websockets.sync.client import connect

    with connect(eps[0]["url"], open_timeout=10) as ws:
        ws.recv()  # connected frame
        ws.send(json.dumps({"type": "message", "content": "hello"}))
        chunks, done = [], None
        deadline = time.time() + 10
        while time.time() < deadline:
            doc = json.loads(ws.recv(timeout=10))
            if doc["type"] == "chunk":
                chunks.append(doc["text"])
            elif doc["type"] == "done":
                done = doc
                break
        assert "".join(chunks) == "hi from pod"
        assert done is not None

    # Provider/pack get status phases too.
    assert store.get("default", "Provider", "mock-llm").status["phase"] == "Ready"
    assert store.get("default", "PromptPack", "op-pack").status["phase"] == "Ready"


def test_missing_ref_sets_pending(manager):
    store, cm = manager
    _, pack, agent = _resources()
    store.apply(pack)
    store.apply(agent)  # provider ref missing
    cm.drain_queue()
    res = store.get("default", "AgentRuntime", "op-agent")
    assert res.status["phase"] == "Pending"
    cond = res.status["conditions"][0]
    assert cond["type"] == "ReferencesResolved" and "providerRef" in cond["message"]
    # Applying the provider requeues and unblocks (watch fan-in).
    provider, _, _ = _resources()
    store.apply(provider)
    cm.drain_queue()
    assert store.get("default", "AgentRuntime", "op-agent").status["phase"] == "Running"


def test_capability_gate_blocks_and_scales_to_zero(manager, monkeypatch):
    store, cm = manager
    provider, pack, agent = _resources()
    store.apply(provider)
    store.apply(pack)
    store.apply(agent)
    cm.drain_queue()
    dep = cm.deployments["default/AgentRuntime/op-agent"]
    assert len(dep.pods) == 1

    # Spec now requires a capability the runtime does not advertise.
    dep.required_capabilities = dep.required_capabilities + ["duplex_audio"]
    gated, missing, warming = cm._capability_gate(dep)
    assert gated and missing == ["duplex_audio"] and warming is None
    monkeypatch.setattr(
        cm, "_required_capabilities", lambda res, tools: ["duplex_audio"]
    )
    cm.reconcile_agent_runtime(store.get("default", "AgentRuntime", "op-agent"))
    res = store.get("default", "AgentRuntime", "op-agent")
    assert res.status["phase"] == "Blocked"
    assert res.status["replicas"] == 0 and not dep.pods


def test_delete_tears_down_pods(manager):
    store, cm = manager
    provider, pack, agent = _resources()
    store.apply(provider)
    store.apply(pack)
    store.apply(agent)
    cm.drain_queue()
    dep = cm.deployments["default/AgentRuntime/op-agent"]
    pod = dep.pods[0]
    store.delete("default", "AgentRuntime", "op-agent")
    cm.drain_queue()
    assert "default/AgentRuntime/op-agent" not in cm.deployments
    # Pod's runtime socket is gone.
    from omnia_tpu.runtime.client import RuntimeClient

    with pytest.raises(Exception):
        client = RuntimeClient(f"localhost:{pod.runtime_port}")
        try:
            client.health(timeout=1.0)
        finally:
            client.close()


# -- rollout -----------------------------------------------------------


def test_rollout_steps_and_promotion(manager):
    store, cm = manager
    provider, pack, agent = _resources(
        agent_extra={"rollout": {"steps": [{"weight": 10}, {"weight": 50}]}}
    )
    store.apply(provider)
    store.apply(pack)
    store.apply(agent)
    cm.drain_queue()
    dep = cm.deployments["default/AgentRuntime/op-agent"]
    stable_before = dep.stable_hash

    # Pack content change → new config hash → candidate at step 0.
    pack2 = Resource(
        kind="PromptPack",
        name="op-pack",
        spec={"content": {**PACK_CONTENT, "version": "1.1.0"}},
    )
    store.apply(pack2)
    cm.drain_queue()
    st = cm.rollouts.state(dep)
    assert st.phase == RolloutPhase.PROGRESSING
    assert dep.candidate_weight == 10
    weights = dict(dep.endpoints())
    assert pytest.approx(sum(weights.values())) == 100

    cm.rollouts.tick(dep)  # step 1
    assert dep.candidate_weight == 50
    cm.rollouts.tick(dep)  # promote
    st = cm.rollouts.state(dep)
    assert st.phase == RolloutPhase.PROMOTED
    assert dep.stable_hash != stable_before
    assert not dep.candidate_pods and len(dep.pods) == 1
    res = store.get("default", "AgentRuntime", "op-agent")
    cm.reconcile_agent_runtime(res)
    assert store.get("default", "AgentRuntime", "op-agent").status["rollout"]["phase"] == "Promoted"


def test_rollout_rollback_on_failed_analysis(manager):
    store, cm = manager
    provider, pack, agent = _resources(
        agent_extra={"rollout": {"steps": [{"weight": 20}]}}
    )
    store.apply(provider)
    store.apply(pack)
    store.apply(agent)
    cm.drain_queue()
    dep = cm.deployments["default/AgentRuntime/op-agent"]
    stable_before = dep.stable_hash

    store.apply(
        Resource(
            kind="PromptPack",
            name="op-pack",
            spec={"content": {**PACK_CONTENT, "version": "2.0.0"}},
        )
    )
    cm.drain_queue()
    assert cm.rollouts.state(dep).phase == RolloutPhase.PROGRESSING

    cm.rollouts.analyzer = lambda d: False  # candidate unhealthy
    cm.rollouts.tick(dep)
    st = cm.rollouts.state(dep)
    assert st.phase == RolloutPhase.ROLLED_BACK
    assert dep.stable_hash == stable_before
    assert not dep.candidate_pods and dep.candidate_weight == 0

    # The failed hash is latched: further resyncs must NOT respawn a
    # candidate for the same (still-failing) config ...
    cm.rollouts.tick(dep)
    cm.rollouts.tick(dep)
    assert cm.rollouts.state(dep).phase == RolloutPhase.ROLLED_BACK
    assert not dep.candidate_pods

    # ... but a NEW config does restart a rollout.
    cm.rollouts.analyzer = lambda d: True
    store.apply(
        Resource(
            kind="PromptPack",
            name="op-pack",
            spec={"content": {**PACK_CONTENT, "version": "3.0.0"}},
        )
    )
    cm.drain_queue()
    assert cm.rollouts.state(dep).phase == RolloutPhase.PROGRESSING


def test_capability_gate_latches_without_flapping(manager, monkeypatch):
    """Once gated, resyncs must NOT restart pods until the config changes."""
    store, cm = manager
    provider, pack, agent = _resources()
    store.apply(provider)
    store.apply(pack)
    store.apply(agent)
    cm.drain_queue()
    monkeypatch.setattr(cm, "_required_capabilities", lambda r, t: ["duplex_audio"])
    res = store.get("default", "AgentRuntime", "op-agent")
    cm.reconcile_agent_runtime(res)
    dep = cm.deployments["default/AgentRuntime/op-agent"]
    assert not dep.pods and dep.gate_blocked_hash
    starts_before = cm.backend._counter
    for _ in range(3):  # resyncs while latched
        cm.reconcile_agent_runtime(store.get("default", "AgentRuntime", "op-agent"))
    assert cm.backend._counter == starts_before, "latched gate must not start pods"
    assert store.get("default", "AgentRuntime", "op-agent").status["phase"] == "Blocked"
    # Requirements change back to satisfiable -> re-admitted.
    monkeypatch.undo()
    cm.reconcile_agent_runtime(store.get("default", "AgentRuntime", "op-agent"))
    assert store.get("default", "AgentRuntime", "op-agent").status["phase"] == "Running"
    assert len(dep.pods) == 1


def test_replica_edit_does_not_restart_pods(manager):
    store, cm = manager
    provider, pack, agent = _resources()
    store.apply(provider)
    store.apply(pack)
    store.apply(agent)
    cm.drain_queue()
    dep = cm.deployments["default/AgentRuntime/op-agent"]
    pod_before = dep.pods[0]
    hash_before = dep.stable_hash
    agent2 = Resource(
        kind="AgentRuntime", name="op-agent", spec={**agent.spec, "replicas": 2}
    )
    store.apply(agent2)
    cm.drain_queue()
    assert dep.config_hash() == hash_before, "replicas must not change config hash"
    assert dep.pods[0] is pod_before, "existing pod must survive a replica edit"
    assert len(dep.pods) == 2


# -- source-sync CRDs ---------------------------------------------------


class TestSourceCRDs:
    """PromptPackSource / ArenaSource / ArenaTemplateSource /
    ArenaDevSession (reference ee promptpacksource_controller.go +
    arena source controllers): synced content projects into resources,
    and a source version move drives the pack's version-triggered
    rollout."""

    def _pack_files(self, version):
        return {"pack.json": json.dumps({
            **PACK_CONTENT, "version": version,
        }).encode()}

    def test_pack_source_syncs_and_triggers_rollout(self, manager, monkeypatch, tmp_path):
        import omnia_tpu.oci as oci

        monkeypatch.setenv("OMNIA_SYNC_ROOT", str(tmp_path))
        store, cm = manager
        reg = oci.OCIRegistry().start()
        try:
            oci.push_artifact(reg, "packs/op", "stable", self._pack_files("1.0.0"))
            provider, _pack, agent = _resources(agent_extra={
                "rollout": {"steps": [{"weight": 50}]},
            })
            store.apply(provider)
            store.apply(Resource(kind="PromptPackSource", name="op-src", spec={
                "source": {"type": "oci", "ref": f"{reg.endpoint}/packs/op:stable"},
                "packName": "op-pack",
                "interval_s": 0.0,
            }))
            cm.drain_queue()
            src = store.get("default", "PromptPackSource", "op-src")
            assert src.status["phase"] == "Ready", src.status
            assert src.status["packVersion"] == "1.0.0"
            pack = store.get("default", "PromptPack", "op-pack")
            assert pack is not None
            assert pack.spec["content"]["version"] == "1.0.0"
            store.apply(agent)
            cm.drain_queue()
            dep = cm.deployments["default/AgentRuntime/op-agent"]

            # Source push (tag move) → pack update → candidate rollout.
            oci.push_artifact(reg, "packs/op", "stable", self._pack_files("2.0.0"))
            cm.resync()     # interval elapsed → re-sync picks up new digest
            cm.drain_queue()
            assert store.get("default", "PromptPack", "op-pack") \
                .spec["content"]["version"] == "2.0.0"
            st = cm.rollouts.state(dep)
            assert st.phase == RolloutPhase.PROGRESSING
            assert dep.candidate_pods, "pack-source push must spawn a candidate"
        finally:
            reg.stop()

    def test_arena_source_feeds_job_scenarios(self, manager, monkeypatch, tmp_path):
        monkeypatch.setenv("OMNIA_SYNC_ROOT", str(tmp_path))
        store, cm = manager
        provider, pack, agent = _resources()
        store.apply(provider)
        store.apply(pack)
        store.apply(Resource(kind="ArenaSource", name="scn", spec={
            "source": {"type": "configmap", "data": {
                "scenarios.json": json.dumps([
                    {"name": "greet", "turns": [{"user": "hello", "checks": [
                        {"kind": "contains", "value": "hi"}]}]},
                ]),
            }},
        }))
        cm.drain_queue()
        assert store.get("default", "ArenaSource", "scn").status["phase"] == "Ready"
        store.apply(Resource(kind="ArenaJob", name="aj", spec={
            "scenariosFrom": {"name": "scn"},
            "providers": ["mock-llm"],
            "mode": "direct",
        }))
        cm.drain_queue()
        aj = store.get("default", "ArenaJob", "aj")
        # The job partitioned the SYNCED scenarios (none declared inline).
        assert aj.status.get("phase") == "Running", aj.status
        assert aj.status.get("total") == 1
        # Drive a direct worker to the verdict (same harness as the EE
        # arena test — workers are separate processes in production).
        from omnia_tpu.evals.worker import ArenaWorker, DirectRunner
        from omnia_tpu.runtime.packs import load_pack
        from omnia_tpu.runtime.providers import ProviderRegistry, ProviderSpec

        reg = ProviderRegistry()
        reg.register(ProviderSpec(name="mock-llm", type="mock", options={
            "scenarios": [{"pattern": "hello", "reply": "hi there"}]}))
        wpack = load_pack({"name": "p", "version": "1.0.0",
                           "prompts": {"system": "s"},
                           "sampling": {"temperature": 0.0, "max_tokens": 32}})
        ArenaWorker(cm.arena.queue, DirectRunner(wpack, reg)).run_until_empty()
        cm.resync()
        aj = store.get("default", "ArenaJob", "aj")
        assert aj.status.get("phase") == "Succeeded", aj.status

    def test_arena_template_source_and_dev_session(self, manager, monkeypatch, tmp_path):
        monkeypatch.setenv("OMNIA_SYNC_ROOT", str(tmp_path))
        store, cm = manager
        store.apply(Resource(kind="ArenaTemplateSource", name="tmpl", spec={
            "source": {"type": "configmap",
                       "data": {"base.json": "{}"}},
        }))
        provider, pack, agent = _resources()
        store.apply(provider)
        store.apply(pack)
        store.apply(agent)
        cm.drain_queue()
        assert store.get("default", "ArenaTemplateSource", "tmpl") \
            .status["phase"] == "Ready"
        store.apply(Resource(kind="ArenaDevSession", name="dev1", spec={
            "agentRef": {"name": "op-agent"}, "ttl_s": 0.05,
        }))
        cm.drain_queue()
        ads = store.get("default", "ArenaDevSession", "dev1")
        assert ads.status["phase"] == "Ready"
        assert ads.status["expiresAt"] > time.time()
        time.sleep(0.1)
        cm.resync()
        assert store.get("default", "ArenaDevSession", "dev1") \
            .status["phase"] == "Expired"

    def test_bad_source_fails_closed(self, manager):
        store, cm = manager
        with pytest.raises(ValidationError):
            store.apply(Resource(kind="PromptPackSource", name="bad", spec={
                "source": {"type": "git"},  # missing repo
            }))
        store.apply(Resource(kind="PromptPackSource", name="dangling", spec={
            "source": {"type": "oci", "ref": "localhost:1/none:x"},
        }))
        cm.drain_queue()
        assert store.get("default", "PromptPackSource", "dangling") \
            .status["phase"] == "Error"


class TestSkillSources:
    """SkillSource reconcile + pack skills merge (reference
    skillsource_controller.go + promptpack_skills.go): synced skill
    markdown lands in the deployed pack's system prompt, and a skill
    update re-resolves the agents that use it."""

    def test_skill_merges_into_served_pack(self, manager, monkeypatch, tmp_path):
        monkeypatch.setenv("OMNIA_SYNC_ROOT", str(tmp_path))
        store, cm = manager
        store.apply(Resource(kind="SkillSource", name="refund-playbook", spec={
            "source": {"type": "configmap", "data": {
                "SKILL.md": "Always quote the thirty day refund window.",
            }},
        }))
        provider = Resource(kind="Provider", name="mock-llm", spec={
            "type": "mock", "role": "llm", "options": {"scenarios": [
                # Mock matching runs over system + current turn: hitting
                # this pattern PROVES the skill text reached the prompt.
                {"pattern": "thirty day refund window",
                 "reply": "skill applied"},
                {"pattern": ".", "reply": "no skill"},
            ]}})
        store.apply(provider)
        store.apply(Resource(kind="PromptPack", name="op-pack", spec={
            "content": {**PACK_CONTENT, "skills": ["refund-playbook"]}}))
        agent_spec = {
            "mode": "agent",
            "promptPackRef": {"name": "op-pack"},
            "providers": [{"name": "main", "providerRef": {"name": "mock-llm"}}],
            "facades": [{"type": "websocket"}],
        }
        store.apply(Resource(kind="AgentRuntime", name="op-agent",
                             spec=agent_spec))
        cm.drain_queue()
        src = store.get("default", "SkillSource", "refund-playbook")
        assert src.status["phase"] == "Ready"
        dep = cm.deployments["default/AgentRuntime/op-agent"]

        from websockets.sync.client import connect

        with connect(dep.pods[0].endpoint) as ws:
            json.loads(ws.recv(timeout=10))
            ws.send(json.dumps({"type": "message", "content": "hello"}))
            text = ""
            while True:
                m = json.loads(ws.recv(timeout=30))
                if m["type"] == "chunk":
                    text += m["text"]
                elif m["type"] in ("done", "error"):
                    break
        assert text == "skill applied"

    def test_missing_skill_fails_ref_resolution(self, manager, monkeypatch, tmp_path):
        monkeypatch.setenv("OMNIA_SYNC_ROOT", str(tmp_path))
        store, cm = manager
        provider, _pack, agent = _resources()
        store.apply(provider)
        store.apply(Resource(kind="PromptPack", name="op-pack", spec={
            "content": {**PACK_CONTENT, "skills": ["ghost-skill"]}}))
        store.apply(agent)
        cm.drain_queue()
        res = store.get("default", "AgentRuntime", "op-agent")
        # Unresolvable skills park the agent at Pending with the ref
        # condition naming the skill (same stance as a missing pack).
        assert res.status["phase"] == "Pending"
        cond = res.status["conditions"][0]
        assert cond["status"] == "False" and "ghost-skill" in cond["message"]


class TestHTTPRouteObservation:
    """Gateway-API HTTPRoute endpoint observation (VERDICT r3 #9;
    reference internal/controller/facade_endpoints.go + facade_route.go):
    routes targeting an agent's Service surface public URLs in
    status.facade.endpoints, live-updating on route changes."""

    def test_route_urls_surface_in_facade_status(self):
        store = MemoryResourceStore()
        mgr = ControllerManager(store)
        try:
            for r in _resources():
                store.apply(r)
            mgr.drain_queue()
            res = store.get("default", "AgentRuntime", "op-agent")
            # No route yet: facade endpoints fall back to pod endpoints.
            assert res.status["facade"]["endpoints"] == res.status["endpoints"]
            # A route appears → its hostnames become the public endpoints.
            store.apply(Resource(kind="HTTPRoute", name="chat-route", spec={
                "hostnames": ["chat.example.com", "www.chat.example.com"],
                "rules": [{
                    "matches": [{"path": {"type": "PathPrefix",
                                          "value": "/ws"}}],
                    "backendRefs": [{"name": "agent-op-agent", "port": 8080}],
                }],
            }))
            mgr.drain_queue()  # route event requeued the agent
            res = store.get("default", "AgentRuntime", "op-agent")
            eps = res.status["facade"]["endpoints"]
            assert [e["url"] for e in eps] == [
                "https://chat.example.com/ws",
                "https://www.chat.example.com/ws",
            ], eps
            assert all(e["source"] == "httproute" and e["route"] == "chat-route"
                       for e in eps)
            # Routes for OTHER services don't leak in.
            store.apply(Resource(kind="HTTPRoute", name="other", spec={
                "hostnames": ["other.example.com"],
                "rules": [{"backendRefs": [{"name": "agent-someone-else"}]}],
            }))
            mgr.drain_queue()
            res = store.get("default", "AgentRuntime", "op-agent")
            assert all("other.example.com" not in e["url"]
                       for e in res.status["facade"]["endpoints"])
            # Route deletion falls back to pod endpoints on next resync.
            store.delete("default", "HTTPRoute", "chat-route")
            mgr.resync()
            res = store.get("default", "AgentRuntime", "op-agent")
            assert res.status["facade"]["endpoints"] == res.status["endpoints"]
        finally:
            mgr.shutdown()

    def test_devroot_route_yaml_populates_status(self, tmp_path):
        """The devroot path: an HTTPRoute YAML dropped into the config
        tree (kubectl-apply equivalent) surfaces its hostname in the
        agent's status.facade.endpoints on the next resync."""
        import yaml as _yaml

        root = str(tmp_path / "devroot")
        store = FileResourceStore(root)
        mgr = ControllerManager(store)
        try:
            for r in _resources():
                store.apply(r)
            mgr.drain_queue()
            doc = Resource(kind="HTTPRoute", name="public", spec={
                "hostnames": ["agents.corp.example"],
                "rules": [{"backendRefs": [{"name": "agent-op-agent"}]}],
            }).to_manifest()
            (tmp_path / "devroot" / "route.yaml").write_text(
                _yaml.safe_dump(doc))
            mgr.resync()  # devroot sync + requeue
            mgr.drain_queue()
            res = store.get("default", "AgentRuntime", "op-agent")
            assert [e["url"] for e in res.status["facade"]["endpoints"]] == [
                "https://agents.corp.example"
            ]
        finally:
            mgr.shutdown()

    def test_httproute_admission(self):
        store = MemoryResourceStore()
        with pytest.raises(ValidationError, match="backendRefs"):
            store.apply(Resource(kind="HTTPRoute", name="bad", spec={
                "rules": [{"backendRefs": [{"port": 8080}]}]}))
        with pytest.raises(ValidationError, match="hostnames"):
            store.apply(Resource(kind="HTTPRoute", name="bad2", spec={
                "hostnames": "chat.example.com"}))

    def test_route_multi_path_and_hostile_shapes(self):
        """Every match path yields an endpoint; admitted-but-odd shapes
        (string path, non-list backendRefs) never crash reconcile."""
        store = MemoryResourceStore()
        mgr = ControllerManager(store)
        try:
            for r in _resources():
                store.apply(r)
            mgr.drain_queue()
            store.apply(Resource(kind="HTTPRoute", name="multi", spec={
                "hostnames": ["h.example"],
                "rules": [{
                    "matches": [{"path": {"value": "/api"}},
                                {"path": {"value": "/ws"}},
                                {"path": "bare-string"}],  # skipped, not fatal
                    "backendRefs": [{"name": "agent-op-agent"}],
                }],
            }))
            mgr.drain_queue()
            res = store.get("default", "AgentRuntime", "op-agent")
            urls = [e["url"] for e in res.status["facade"]["endpoints"]]
            assert urls == ["https://h.example/api", "https://h.example/ws"]
            with pytest.raises(ValidationError, match="must be a list"):
                store.apply(Resource(kind="HTTPRoute", name="bad3", spec={
                    "rules": [{"backendRefs": 5}]}))
        finally:
            mgr.shutdown()
