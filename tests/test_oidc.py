"""OIDC / JWKS / edge-trust auth tests (reference pkg/facade/auth/
{oidc,jwks,edge_trust}.go parity): RS256 validation against a local JWKS
fixture, discovery, rotation-by-refetch, downgrade-attack rejection, and
the validators working through the real facade WebSocket handshake."""

import http.server
import json
import threading
import time

import pytest

from omnia_tpu.facade.auth import AuthChain, Principal, _b64url_encode
from omnia_tpu.facade.oidc import (
    EdgeTrustValidator,
    HTTPJWKS,
    OIDCValidator,
    StaticJWKS,
    discover_jwks_uri,
)


# ---------------------------------------------------------------------------
# RS256 fixture key + minting helpers
# ---------------------------------------------------------------------------

from cryptography.hazmat.primitives import hashes
from cryptography.hazmat.primitives.asymmetric import padding, rsa


@pytest.fixture(scope="module")
def keypair():
    priv = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    return priv


def _jwk(priv, kid="k1"):
    pub = priv.public_key().public_numbers()
    return {
        "kty": "RSA",
        "kid": kid,
        "use": "sig",
        "alg": "RS256",
        "n": _b64url_encode(pub.n.to_bytes((pub.n.bit_length() + 7) // 8, "big")),
        "e": _b64url_encode(pub.e.to_bytes((pub.e.bit_length() + 7) // 8, "big")),
    }


def mint(priv, kid="k1", alg="RS256", **claims):
    claims.setdefault("sub", "user-1")
    claims.setdefault("iss", "https://idp.test")
    claims.setdefault("aud", "omnia")
    claims.setdefault("exp", int(time.time()) + 300)
    header = _b64url_encode(json.dumps({"alg": alg, "kid": kid}).encode())
    payload = _b64url_encode(json.dumps(claims).encode())
    sig = priv.sign(
        f"{header}.{payload}".encode(), padding.PKCS1v15(), hashes.SHA256()
    )
    return f"{header}.{payload}.{_b64url_encode(sig)}"


@pytest.fixture(scope="module")
def validator(keypair):
    return OIDCValidator(
        StaticJWKS({"keys": [_jwk(keypair)]}),
        issuer="https://idp.test",
        audience="omnia",
    )


class TestOIDCValidation:
    def test_valid_token(self, keypair, validator):
        p = validator.validate(mint(keypair))
        assert p is not None and p.method == "oidc"
        assert p.subject == "user-1"
        assert p.claims["aud"] == "omnia"

    def test_wrong_signature_rejected(self, validator):
        other = rsa.generate_private_key(public_exponent=65537, key_size=2048)
        assert validator.validate(mint(other)) is None

    def test_expired_rejected(self, keypair, validator):
        tok = mint(keypair, exp=int(time.time()) - 120)
        assert validator.validate(tok) is None

    def test_not_yet_valid_rejected(self, keypair, validator):
        tok = mint(keypair, nbf=int(time.time()) + 300)
        assert validator.validate(tok) is None

    def test_wrong_issuer_rejected(self, keypair, validator):
        assert validator.validate(mint(keypair, iss="https://evil.test")) is None

    def test_wrong_audience_rejected(self, keypair, validator):
        assert validator.validate(mint(keypair, aud="other")) is None

    def test_audience_list_accepted(self, keypair, validator):
        p = validator.validate(mint(keypair, aud=["other", "omnia"]))
        assert p is not None

    def test_unknown_kid_rejected(self, keypair, validator):
        assert validator.validate(mint(keypair, kid="k-unknown")) is None

    def test_alg_none_downgrade_rejected(self, keypair, validator):
        header = _b64url_encode(json.dumps({"alg": "none", "kid": "k1"}).encode())
        payload = _b64url_encode(
            json.dumps({"sub": "evil", "iss": "https://idp.test",
                        "aud": "omnia", "exp": int(time.time()) + 300}).encode()
        )
        assert validator.validate(f"{header}.{payload}.") is None

    def test_hs256_confusion_rejected(self, keypair, validator):
        # Token HMAC-signed with the PUBLIC key bytes, alg=HS256 — the
        # classic key-confusion attack; must not validate.
        import hashlib
        import hmac as hmac_mod

        header = _b64url_encode(json.dumps({"alg": "HS256", "kid": "k1"}).encode())
        payload = _b64url_encode(
            json.dumps({"sub": "evil", "iss": "https://idp.test",
                        "aud": "omnia", "exp": int(time.time()) + 300}).encode()
        )
        fake_key = json.dumps(_jwk(keypair)).encode()
        sig = hmac_mod.new(fake_key, f"{header}.{payload}".encode(), hashlib.sha256).digest()
        assert validator.validate(f"{header}.{payload}.{_b64url_encode(sig)}") is None

    def test_garbage_rejected(self, validator):
        assert validator.validate("") is None
        assert validator.validate("a.b") is None
        assert validator.validate("not-a-jwt-at-all") is None

    def test_missing_subject_rejected(self, keypair):
        v = OIDCValidator(StaticJWKS({"keys": [_jwk(keypair)]}))
        header = mint(keypair)
        # mint always sets sub; craft one without it
        tok = mint(keypair, sub="")
        assert v.validate(tok) is None


# ---------------------------------------------------------------------------
# JWKS over HTTP: discovery, caching, rotation
# ---------------------------------------------------------------------------


class _IdpServer:
    """Local IdP fixture: serves openid-configuration + a mutable JWKS."""

    def __init__(self):
        self.jwks = {"keys": []}
        self.hits = {"jwks": 0, "discovery": 0}
        idp = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):
                if self.path == "/.well-known/openid-configuration":
                    idp.hits["discovery"] += 1
                    body = json.dumps(
                        {"issuer": idp.issuer, "jwks_uri": idp.issuer + "/jwks"}
                    ).encode()
                elif self.path == "/jwks":
                    idp.hits["jwks"] += 1
                    body = json.dumps(idp.jwks).encode()
                else:
                    self.send_response(404)
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        self.httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.issuer = f"http://127.0.0.1:{self.httpd.server_address[1]}"
        threading.Thread(target=self.httpd.serve_forever, daemon=True).start()

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()


@pytest.fixture()
def idp():
    s = _IdpServer()
    yield s
    s.stop()


class TestJWKSOverHTTP:
    def test_discovery_and_validation(self, keypair, idp):
        idp.jwks = {"keys": [_jwk(keypair)]}
        uri = discover_jwks_uri(idp.issuer)
        v = OIDCValidator(HTTPJWKS(uri), issuer="https://idp.test", audience="omnia")
        assert v.validate(mint(keypair)) is not None
        assert idp.hits["discovery"] == 1

    def test_cache_avoids_refetch(self, keypair, idp):
        idp.jwks = {"keys": [_jwk(keypair)]}
        v = OIDCValidator(HTTPJWKS(idp.issuer + "/jwks"), issuer="https://idp.test",
                          audience="omnia")
        for _ in range(5):
            assert v.validate(mint(keypair)) is not None
        assert idp.hits["jwks"] == 1

    def test_rotation_refetches_on_unknown_kid(self, keypair, idp):
        idp.jwks = {"keys": [_jwk(keypair, kid="old")]}
        jwks = HTTPJWKS(idp.issuer + "/jwks", min_refresh_s=0.0)
        v = OIDCValidator(jwks, issuer="https://idp.test", audience="omnia")
        assert v.validate(mint(keypair, kid="old")) is not None
        # IdP rotates: new kid published
        new_priv = rsa.generate_private_key(public_exponent=65537, key_size=2048)
        idp.jwks = {"keys": [_jwk(new_priv, kid="new")]}
        assert v.validate(mint(new_priv, kid="new")) is not None
        assert idp.hits["jwks"] == 2

    def test_idp_down_denies_not_crashes(self, keypair, idp):
        url = idp.issuer + "/jwks"
        idp.stop()
        v = OIDCValidator(HTTPJWKS(url), issuer="https://idp.test", audience="omnia")
        assert v.validate(mint(keypair)) is None


# ---------------------------------------------------------------------------
# edge trust
# ---------------------------------------------------------------------------


class TestEdgeTrust:
    def test_trusts_identity_only_with_edge_secret(self):
        v = EdgeTrustValidator("edge-s3cret")
        headers = {"X-Forwarded-User": "alice", "X-Edge-Auth": "edge-s3cret"}
        p = v.validate_request("", headers)
        assert p is not None and p.subject == "alice" and p.method == "edge_trust"

    def test_no_secret_no_trust(self):
        v = EdgeTrustValidator("edge-s3cret")
        assert v.validate_request("", {"X-Forwarded-User": "mallory"}) is None
        assert v.validate_request("", {"X-Forwarded-User": "m",
                                       "X-Edge-Auth": "wrong"}) is None
        assert v.validate_request("", None) is None
        assert v.validate("") is None

    def test_secret_without_identity_denied(self):
        v = EdgeTrustValidator("edge-s3cret")
        assert v.validate_request("", {"X-Edge-Auth": "edge-s3cret"}) is None

    def test_chain_integration(self, keypair):
        chain = AuthChain([
            OIDCValidator(StaticJWKS({"keys": [_jwk(keypair)]}),
                          issuer="https://idp.test", audience="omnia"),
            EdgeTrustValidator("edge-s3cret"),
        ])
        # OIDC path
        p = chain.authenticate(mint(keypair), headers={})
        assert p is not None and p.method == "oidc"
        # edge path
        p = chain.authenticate(
            "", headers={"x-forwarded-user": "bob", "x-edge-auth": "edge-s3cret"}
        )
        assert p is not None and p.subject == "bob"
        # neither
        assert chain.authenticate("", headers={}) is None


# ---------------------------------------------------------------------------
# through the real facade WS handshake
# ---------------------------------------------------------------------------


class TestFacadeIntegration:
    @pytest.fixture()
    def facade(self, keypair):
        from websockets.sync.client import connect  # noqa: F401 (env check)

        from omnia_tpu.engine.mock import MockEngine, Scenario
        from omnia_tpu.facade.server import FacadeServer
        from omnia_tpu.runtime.packs import load_pack
        from omnia_tpu.runtime.providers import ProviderRegistry, ProviderSpec
        from omnia_tpu.runtime.server import RuntimeServer

        pack = {
            "name": "oidc-agent", "version": "1.0.0",
            "prompts": {"system": "sys", "greeting": "hi"},
            "sampling": {"temperature": 0.0, "max_tokens": 32},
        }
        reg = ProviderRegistry()
        reg.register(ProviderSpec(name="m", type="mock", options={
            "scenarios": [{"pattern": ".", "reply": "ok"}]}))
        rt = RuntimeServer(pack=load_pack(pack), providers=reg, provider_name="m")
        rt_port = rt.serve("localhost:0")
        chain = AuthChain([
            OIDCValidator(StaticJWKS({"keys": [_jwk(keypair)]}),
                          issuer="https://idp.test", audience="omnia"),
            EdgeTrustValidator("edge-s3cret"),
        ])
        f = FacadeServer(
            runtime_target=f"localhost:{rt_port}", agent_name="oidc-agent",
            auth_chain=chain,
        )
        port = f.serve()
        yield port
        f.shutdown()
        rt.shutdown()

    def test_oidc_bearer_ws_handshake(self, keypair, facade):
        import json as j

        from websockets.sync.client import connect

        tok = mint(keypair, sub="ws-user")
        with connect(
            f"ws://localhost:{facade}/ws",
            additional_headers={"Authorization": f"Bearer {tok}"},
        ) as ws:
            hello = j.loads(ws.recv(timeout=10))
            assert hello["type"] == "connected"

    def test_bad_token_closes_4401(self, facade):
        from websockets.sync.client import connect
        from websockets.exceptions import ConnectionClosed

        with pytest.raises(Exception) as ei:
            with connect(
                f"ws://localhost:{facade}/ws",
                additional_headers={"Authorization": "Bearer nope"},
            ) as ws:
                ws.recv(timeout=10)
        assert "4401" in str(ei.value) or isinstance(ei.value, ConnectionClosed)

    def test_edge_headers_ws_handshake(self, facade):
        import json as j

        from websockets.sync.client import connect

        with connect(
            f"ws://localhost:{facade}/ws",
            additional_headers={
                "X-Forwarded-User": "edge-user",
                "X-Edge-Auth": "edge-s3cret",
            },
        ) as ws:
            hello = j.loads(ws.recv(timeout=10))
            assert hello["type"] == "connected"
