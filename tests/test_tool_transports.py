"""gRPC / MCP / OpenAPI tool transports (VERDICT r4 #2).

Covers the three handler types the executor previously rejected, each
against an in-process fixture server, plus the executor integration so
all five CRD handler types dispatch end-to-end.
"""

import http.server
import json
import os
import sys
import threading

import pytest

from omnia_tpu.tools.executor import ToolExecutor, ToolHandler
from omnia_tpu.tools.grpc_transport import GrpcToolClient, GrpcToolServer
from omnia_tpu.tools.mcp_client import (
    MCPClient, MCPProtocolError, MCPTransportError, StdioTransport,
    StreamableHttpTransport,
)
from omnia_tpu.tools.openapi import OpenAPIAdapter

FIXTURE = os.path.join(os.path.dirname(__file__), "fixtures", "mcp_stdio_server.py")


# ---------------------------------------------------------------------------
# gRPC


@pytest.fixture()
def grpc_server():
    srv = GrpcToolServer({
        "add": (lambda a: {"sum": a["x"] + a["y"]}, "adds", {
            "type": "object",
            "properties": {"x": {"type": "number"}, "y": {"type": "number"}},
        }),
        "boom": lambda a: (_ for _ in ()).throw(RuntimeError("kaboom")),
    }).start()
    yield srv
    srv.stop()


def test_grpc_roundtrip(grpc_server):
    client = GrpcToolClient(grpc_server.endpoint)
    resp = client.execute("add", {"x": 2, "y": 3})
    assert not resp.is_error
    assert json.loads(resp.result_json) == {"sum": 5}
    client.close()


def test_grpc_tool_error_is_application_level(grpc_server):
    client = GrpcToolClient(grpc_server.endpoint)
    resp = client.execute("boom", {})
    assert resp.is_error and "kaboom" in resp.error_message
    resp = client.execute("nosuch", {})
    assert resp.is_error and "unknown tool" in resp.error_message
    client.close()


def test_grpc_list_tools(grpc_server):
    client = GrpcToolClient(grpc_server.endpoint)
    tools = client.list_tools()
    assert [t["name"] for t in tools] == ["add", "boom"]
    assert tools[0]["input_schema"]["properties"]["x"]["type"] == "number"
    client.close()


def test_grpc_auth_enforced():
    srv = GrpcToolServer({"echo": lambda a: a}, require_token="sekrit").start()
    try:
        import grpc

        bad = GrpcToolClient(srv.endpoint)
        with pytest.raises(grpc.RpcError) as ei:
            bad.execute("echo", {})
        assert ei.value.code() == grpc.StatusCode.UNAUTHENTICATED
        bad.close()
        good = GrpcToolClient(srv.endpoint, auth_token="sekrit")
        assert not good.execute("echo", {"a": 1}).is_error
        good.close()
    finally:
        srv.stop()


def test_executor_grpc_dispatch(grpc_server):
    ex = ToolExecutor([ToolHandler(
        name="adder", type="grpc", endpoint=grpc_server.endpoint,
        remote_name="add", timeout_s=5.0,
    )])
    out = ex.execute("adder", {"x": 10, "y": 5})
    assert not out.is_error and json.loads(out.content) == {"sum": 15}
    # application-level tool error: no retry, flows to the model
    ex2 = ToolExecutor([ToolHandler(
        name="boom", type="grpc", endpoint=grpc_server.endpoint, timeout_s=5.0,
    )])
    out = ex2.execute("boom", {})
    assert out.is_error and "kaboom" in out.content
    ex.close()
    ex2.close()


def test_executor_grpc_unreachable_retries_then_errors():
    ex = ToolExecutor([ToolHandler(
        name="dead", type="grpc", endpoint="127.0.0.1:1", timeout_s=0.5,
    )], max_retries=1)
    out = ex.execute("dead", {})
    assert out.is_error and "after 2 attempts" in out.content
    ex.close()


# ---------------------------------------------------------------------------
# MCP stdio


def _stdio_cfg(**extra):
    cfg = {"transport": "stdio", "command": sys.executable, "args": [FIXTURE]}
    cfg.update(extra)
    return cfg


def test_mcp_stdio_handshake_and_call():
    client = MCPClient.from_config(_stdio_cfg(), timeout_s=10.0)
    try:
        tools = client.list_tools()
        assert {t["name"] for t in tools} >= {"echo", "fail"}
        assert client.server_info["name"] == "fixture-mcp"
        content, is_error = client.call_tool("echo", {"text": "hi"})
        assert not is_error and json.loads(content) == {"text": "hi"}
        content, is_error = client.call_tool("fail", {})
        assert is_error and "deliberate failure" in content
    finally:
        client.close()


def test_mcp_stdio_unknown_tool_is_protocol_error():
    client = MCPClient.from_config(_stdio_cfg(), timeout_s=10.0)
    try:
        with pytest.raises(MCPProtocolError):
            client.call_tool("nosuch", {})
    finally:
        client.close()


def test_mcp_tool_filter():
    client = MCPClient.from_config(
        _stdio_cfg(toolFilter={"blocklist": ["hidden"]}), timeout_s=10.0
    )
    try:
        assert "hidden" not in {t["name"] for t in client.list_tools()}
        content, is_error = client.call_tool("hidden", {})
        assert is_error and "blocked" in content
    finally:
        client.close()


def test_mcp_crash_is_transport_error():
    client = MCPClient.from_config(_stdio_cfg(), timeout_s=10.0)
    try:
        with pytest.raises(MCPTransportError):
            client.call_tool("crash", {})
    finally:
        client.close()


def test_executor_mcp_dispatch_and_redial_after_crash():
    ex = ToolExecutor([
        ToolHandler(name="echo", type="mcp", mcp=_stdio_cfg(), timeout_s=10.0),
        ToolHandler(name="crash", type="mcp", mcp=_stdio_cfg(), timeout_s=10.0),
    ])
    try:
        out = ex.execute("echo", {"text": "one"})
        assert not out.is_error
        # crash kills the shared stdio session; the executor must evict
        # the dead client and re-dial, so a following echo still works.
        out = ex.execute("crash", {})
        assert out.is_error
        out = ex.execute("echo", {"text": "two"})
        assert not out.is_error and json.loads(out.content) == {"text": "two"}
    finally:
        ex.close()


# ---------------------------------------------------------------------------
# MCP streamable http


@pytest.fixture()
def mcp_http_server():
    """POST JSON-RPC endpoint; answers initialize with an Mcp-Session-Id
    and serves tools/call for `echo`. Asserts the session id comes back.
    Responds in SSE framing when the request metadata asks for it."""
    seen = {"session_ids": [], "sse": False}

    class Handler(http.server.BaseHTTPRequestHandler):
        def do_POST(self):
            body = json.loads(self.rfile.read(int(self.headers["Content-Length"])))
            sid = self.headers.get("Mcp-Session-Id")
            if sid:
                seen["session_ids"].append(sid)
            rid = body.get("id")
            if rid is None:
                self.send_response(202)
                self.end_headers()
                return
            method = body["method"]
            if method == "initialize":
                result = {
                    "protocolVersion": body["params"]["protocolVersion"],
                    "capabilities": {"tools": {}},
                    "serverInfo": {"name": "fixture-http-mcp", "version": "1"},
                }
            elif method == "tools/list":
                result = {"tools": [{"name": "echo", "inputSchema": {"type": "object"}}]}
            elif method == "tools/call":
                result = {
                    "content": [{
                        "type": "text",
                        "text": json.dumps(body["params"].get("arguments", {})),
                    }],
                    "isError": False,
                }
            else:
                result = {}
            payload = {"jsonrpc": "2.0", "id": rid, "result": result}
            if method == "tools/call":
                seen["sse"] = True
                raw = ("event: message\ndata: " + json.dumps(payload) + "\n\n").encode()
                ctype = "text/event-stream"
            else:
                raw = json.dumps(payload).encode()
                ctype = "application/json"
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            if method == "initialize":
                self.send_header("Mcp-Session-Id", "sess-42")
            self.send_header("Content-Length", str(len(raw)))
            self.end_headers()
            self.wfile.write(raw)

        def log_message(self, *args):
            pass

    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    yield f"http://127.0.0.1:{srv.server_port}/mcp", seen
    srv.shutdown()


def test_mcp_streamable_http_with_session_and_sse(mcp_http_server):
    endpoint, seen = mcp_http_server
    client = MCPClient(StreamableHttpTransport(endpoint, timeout_s=5.0))
    tools = client.list_tools()
    assert tools[0]["name"] == "echo"
    content, is_error = client.call_tool("echo", {"q": "sse"})
    assert not is_error and json.loads(content) == {"q": "sse"}
    # session id minted on initialize must ride every later request
    assert "sess-42" in seen["session_ids"] and seen["sse"]


def test_executor_mcp_http_dispatch(mcp_http_server):
    endpoint, _ = mcp_http_server
    ex = ToolExecutor([ToolHandler(
        name="echo", type="mcp",
        mcp={"transport": "streamable-http", "endpoint": endpoint},
        timeout_s=5.0,
    )])
    out = ex.execute("echo", {"n": 7})
    assert not out.is_error and json.loads(out.content) == {"n": 7}
    ex.close()


# ---------------------------------------------------------------------------
# OpenAPI


PETSTORE = {
    "openapi": "3.0.0",
    "info": {"title": "petstore", "version": "1"},
    "servers": [{"url": "https://unused.example"}],
    "paths": {
        "/pets/{petId}": {
            "get": {
                "operationId": "getPet",
                "summary": "fetch one pet",
                "parameters": [
                    {"name": "petId", "in": "path", "required": True,
                     "schema": {"type": "integer"}},
                    {"name": "verbose", "in": "query",
                     "schema": {"type": "boolean"}},
                    {"name": "X-Trace", "in": "header",
                     "schema": {"type": "string"}},
                ],
            },
        },
        "/pets": {
            "post": {
                "operationId": "createPet",
                "requestBody": {
                    "required": True,
                    "content": {"application/json": {"schema": {
                        "$ref": "#/components/schemas/NewPet"
                    }}},
                },
            },
        },
    },
    "components": {"schemas": {"NewPet": {
        "type": "object",
        "properties": {"name": {"type": "string"}, "tag": {"type": "string"}},
        "required": ["name"],
    }}},
}


@pytest.fixture()
def api_backend():
    """Records the request the adapter builds and answers JSON."""
    seen = {}

    class Handler(http.server.BaseHTTPRequestHandler):
        def _handle(self):
            seen["method"] = self.command
            seen["path"] = self.path
            seen["headers"] = dict(self.headers)
            length = int(self.headers.get("Content-Length") or 0)
            seen["body"] = self.rfile.read(length).decode() if length else ""
            raw = json.dumps({"ok": True}).encode()
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.send_header("Content-Length", str(len(raw)))
            self.end_headers()
            self.wfile.write(raw)

        do_GET = do_POST = do_PUT = do_DELETE = _handle

        def log_message(self, *args):
            pass

    srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    threading.Thread(target=srv.serve_forever, daemon=True).start()
    yield f"http://127.0.0.1:{srv.server_port}", seen
    srv.shutdown()


def test_openapi_parse_and_schemas():
    adapter = OpenAPIAdapter(PETSTORE)
    assert set(adapter.ops) == {"getPet", "createPet"}
    get_schema = adapter.ops["getPet"].input_schema()
    assert get_schema["properties"]["petId"]["type"] == "integer"
    assert get_schema["required"] == ["petId"]
    # requestBody object properties are flattened through the $ref
    post_schema = adapter.ops["createPet"].input_schema()
    assert post_schema["properties"]["name"]["type"] == "string"
    assert "name" in post_schema["required"]
    tools = adapter.list_tools()
    assert {t["name"] for t in tools} == {"getPet", "createPet"}


def test_openapi_get_request_mapping(api_backend):
    base, seen = api_backend
    adapter = OpenAPIAdapter(PETSTORE, base_url=base)
    out = adapter.call("getPet", {"petId": 7, "verbose": True, "X-Trace": "t1"})
    assert json.loads(out) == {"ok": True}
    assert seen["method"] == "GET"
    assert seen["path"] == "/pets/7?verbose=True"
    assert seen["headers"]["X-Trace"] == "t1"


def test_openapi_post_body_mapping(api_backend):
    base, seen = api_backend
    adapter = OpenAPIAdapter(PETSTORE, base_url=base)
    adapter.call("createPet", {"name": "rex", "tag": "dog"})
    assert seen["method"] == "POST" and seen["path"] == "/pets"
    assert json.loads(seen["body"]) == {"name": "rex", "tag": "dog"}


def test_openapi_missing_path_param_is_error():
    adapter = OpenAPIAdapter(PETSTORE, base_url="http://x")
    with pytest.raises(ValueError):
        adapter.build_request("getPet", {})


def test_openapi_yaml_and_operation_filter():
    import yaml

    text = yaml.safe_dump(PETSTORE)
    adapter = OpenAPIAdapter(
        OpenAPIAdapter.parse_text(text), operation_filter=["getPet"]
    )
    assert set(adapter.ops) == {"getPet"}


def test_executor_openapi_dispatch(api_backend):
    base, seen = api_backend
    ex = ToolExecutor([ToolHandler(
        name="getPet", type="openapi", spec=PETSTORE, base_url=base,
        timeout_s=5.0,
    )])
    out = ex.execute("getPet", {"petId": 3})
    assert not out.is_error and seen["path"] == "/pets/3"
    # missing required path param: fatal, not retried
    out = ex.execute("getPet", {})
    assert out.is_error and "petId" in out.content
    ex.close()


# ---------------------------------------------------------------------------
# all five types through one executor


def test_executor_dispatches_all_five_types(grpc_server, api_backend):
    base, _ = api_backend
    ex = ToolExecutor([
        ToolHandler(name="py", type="python", fn=lambda a: {"py": True}),
        ToolHandler(name="web", type="http", url=base + "/hook"),
        ToolHandler(name="grpc_add", type="grpc", endpoint=grpc_server.endpoint,
                    remote_name="add", timeout_s=5.0),
        ToolHandler(name="mcp_echo", type="mcp", mcp=_stdio_cfg(),
                    remote_name="echo", timeout_s=10.0),
        ToolHandler(name="getPet", type="openapi", spec=PETSTORE,
                    base_url=base, timeout_s=5.0),
        ToolHandler(name="browser", type="client"),
    ])
    try:
        assert json.loads(ex.execute("py", {}).content) == {"py": True}
        assert not ex.execute("web", {"k": 1}).is_error
        assert json.loads(ex.execute("grpc_add", {"x": 1, "y": 1}).content) == {"sum": 2}
        assert not ex.execute("mcp_echo", {"text": "all5"}).is_error
        assert not ex.execute("getPet", {"petId": 9}).is_error
        assert ex.is_client_side("browser")
    finally:
        ex.close()
