"""Deployment-artifact tests: CRD YAML in sync with the generator, the
install bundle linting clean (the repo's kubectl-dry-run gate), agent-pod
manifests passing the same gate, and the CLI entry points assembling
services from OMNIA_* env (reference wiring-test discipline,
hack/check-wiring-tests.sh)."""

import json
import os
import urllib.request

import pytest
import yaml

from omnia_tpu.operator.crds import KINDS, render_crd, render_crds
from omnia_tpu.operator.install import DEFAULT_VALUES, render_install, to_yaml
from omnia_tpu.operator.manifest_lint import lint

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


class TestCRDs:
    def test_kind_count_and_lint(self):
        assert len(KINDS) == 17
        crds = render_crds()
        assert lint(crds) == []

    def test_committed_yaml_in_sync(self):
        """deploy/crds/*.yaml is generated output (controller-gen
        discipline): regenerating must reproduce the committed files."""
        for kind, (plural, _fn, _s) in KINDS.items():
            path = os.path.join(REPO, "deploy", "crds", f"{plural}.yaml")
            assert os.path.exists(path), f"missing committed CRD {plural}.yaml"
            with open(path) as f:
                committed = yaml.safe_load(f)
            assert committed == render_crd(kind), (
                f"{plural}.yaml out of sync — regenerate deploy/crds"
            )

    def test_enums_match_validation_vocabulary(self):
        """The cluster-enforced enums and the in-process admission enums
        are the same objects — drift is impossible, but prove the wiring."""
        ar = render_crd("AgentRuntime")
        spec = ar["spec"]["versions"][0]["schema"]["openAPIV3Schema"]
        facade_enum = (
            spec["properties"]["spec"]["properties"]["facades"]["items"]
            ["properties"]["type"]["enum"]
        )
        from omnia_tpu.operator.resources import FACADE_TYPES

        assert facade_enum == list(FACADE_TYPES)


class TestInstallBundle:
    def test_default_render_lints_clean(self):
        assert lint(render_install()) == []

    def test_committed_install_yaml_in_sync(self):
        path = os.path.join(REPO, "deploy", "install.yaml")
        with open(os.path.join(REPO, "deploy", "values.yaml")) as f:
            values = yaml.safe_load(f)
        with open(path) as f:
            committed = list(yaml.safe_load_all(f))
        assert committed == render_install(values), (
            "deploy/install.yaml out of sync — regenerate via "
            "python -m omnia_tpu.operator.install deploy/values.yaml"
        )

    def test_encryption_values_stamp_env_via_secret(self):
        """values.encryption stamps OMNIA_ENCRYPTION + a secretKeyRef KEK
        on session-api and memory-api; the key never appears inline."""
        out = render_install({"encryption": {"enabled": True,
                                             "secretName": "my-kek"}})
        assert lint(out) == []
        for name in ("omnia-session-api", "omnia-memory-api"):
            dep = next(m for m in out if m["kind"] == "Deployment"
                       and m["metadata"]["name"] == name)
            env = {e["name"]: e for e
                   in dep["spec"]["template"]["spec"]["containers"][0]["env"]}
            assert env["OMNIA_ENCRYPTION"]["value"] == "local"
            ref = env["OMNIA_KEK_B64"]["valueFrom"]["secretKeyRef"]
            assert ref == {"name": "my-kek", "key": "kek"}
            assert "value" not in env["OMNIA_KEK_B64"]
        # default render stays off
        bare = next(m for m in render_install() if m["kind"] == "Deployment"
                    and m["metadata"]["name"] == "omnia-session-api")
        names = [e["name"] for e
                 in bare["spec"]["template"]["spec"]["containers"][0]["env"]]
        assert "OMNIA_ENCRYPTION" not in names

    def test_values_override_merge(self):
        out = render_install({
            "namespace": "custom-ns",
            "redis": {"enabled": False},
            "images": {"operator": "registry.example/op:v2"},
        })
        assert lint(out) == []
        kinds = [(m["kind"], m["metadata"]["name"]) for m in out]
        assert ("Deployment", "omnia-redis") not in kinds
        op = next(m for m in out if m["metadata"]["name"] == "omnia-operator"
                  and m["kind"] == "Deployment")
        assert op["metadata"]["namespace"] == "custom-ns"
        assert op["spec"]["template"]["spec"]["containers"][0]["image"] == \
            "registry.example/op:v2"
        # Unspecified images keep defaults (deep merge, not replace).
        sess = next(m for m in out if m["metadata"]["name"] == "omnia-session-api"
                    and m["kind"] == "Deployment")
        assert sess["spec"]["template"]["spec"]["containers"][0]["image"] == \
            DEFAULT_VALUES["images"]["sessionApi"]

    def test_observability_bundle(self):
        """Observability section renders Prometheus + Grafana + podmonitors
        and stays lint-clean (reference charts/omnia/templates/
        observability); disabled by default."""
        out = render_install({"observability": {"enabled": True}})
        assert lint(out) == []
        kinds = [(m["kind"], m["metadata"]["name"]) for m in out]
        for expected in (
            ("Deployment", "omnia-prometheus"),
            ("Service", "omnia-prometheus"),
            ("ConfigMap", "omnia-prometheus-config"),
            ("Deployment", "omnia-grafana"),
            ("ConfigMap", "omnia-grafana-dashboards"),
            ("PodMonitor", "omnia-agents"),
            ("PodMonitor", "omnia-services"),
        ):
            assert expected in kinds, expected
        # Prometheus scrapes by port name `metrics` (reference podmonitor
        # discovery) and the Grafana dashboard carries the serving panels.
        prom_cm = next(m for m in out
                       if m["metadata"]["name"] == "omnia-prometheus-config")
        assert "metrics" in prom_cm["data"]["prometheus.yml"]
        graf_cm = next(m for m in out
                       if m["metadata"]["name"] == "omnia-grafana-dashboards")
        dash = json.loads(graf_cm["data"]["omnia-serving.json"])
        exprs = [t["expr"] for p in dash["panels"] for t in p["targets"]]
        assert any("omnia_engine_queue_depth" in e for e in exprs)
        # Off by default: no observability objects in a bare render.
        bare = [(m["kind"], m["metadata"]["name"]) for m in render_install()]
        assert ("Deployment", "omnia-prometheus") not in bare

    def test_observability_logs_traces_bundle(self):
        """Loki + Tempo + Alloy collector render with the bundle
        (VERDICT r3 #8): OTLP wired to Tempo on every service, Grafana
        provisioned with all three datasources, collector config tails
        omnia pods into Loki."""
        out = render_install({"observability": {"enabled": True}})
        assert lint(out) == []
        kinds = [(m["kind"], m["metadata"]["name"]) for m in out]
        for expected in (
            ("Deployment", "omnia-loki"),
            ("Service", "omnia-loki"),
            ("ConfigMap", "omnia-loki-config"),
            ("Deployment", "omnia-tempo"),
            ("Service", "omnia-tempo"),
            ("ConfigMap", "omnia-tempo-config"),
            ("ConfigMap", "omnia-collector-config"),
            ("DaemonSet", "omnia-collector"),
            ("ConfigMap", "omnia-grafana-datasources"),
        ):
            assert expected in kinds, expected
        # Every core service exports OTLP at the bundled Tempo.
        for name in ("omnia-operator", "omnia-session-api", "omnia-memory-api"):
            dep = next(m for m in out if m["kind"] == "Deployment"
                       and m["metadata"]["name"] == name)
            env = {e["name"]: e.get("value")
                   for e in dep["spec"]["template"]["spec"]["containers"][0]["env"]}
            assert env["OMNIA_OTLP_ENDPOINT"].endswith(":4318"), (name, env)
        # Tempo receives OTLP on both protocols; Loki honors retention.
        tempo_cm = next(m for m in out
                        if m["metadata"]["name"] == "omnia-tempo-config")
        assert "4317" in tempo_cm["data"]["tempo.yaml"]
        assert "4318" in tempo_cm["data"]["tempo.yaml"]
        loki_cm = next(m for m in out
                       if m["metadata"]["name"] == "omnia-loki-config")
        assert "retention_period: 168h" in loki_cm["data"]["loki.yaml"]
        # The collector tails omnia pods into Loki and relays to Tempo.
        alloy = next(m for m in out
                     if m["metadata"]["name"] == "omnia-collector-config")
        cfg = alloy["data"]["config.alloy"]
        assert "loki.source.kubernetes" in cfg and "omnia-loki" in cfg
        assert "otelcol.exporter.otlphttp" in cfg and "omnia-tempo" in cfg
        # Grafana sees metrics, logs, and traces.
        ds = next(m for m in out
                  if m["metadata"]["name"] == "omnia-grafana-datasources")
        assert all(t in ds["data"]["datasources.yaml"]
                   for t in ("prometheus", "loki", "tempo"))
        # Collector correctness: the DaemonSet runs under its OWN minimal
        # ServiceAccount (NOT the operator's — the cluster-wide pods/log
        # grant must not attach to the operator), node-scoped discovery
        # (no N× log duplication), stable relay Service, and the
        # collector ClusterRole really grants pod/log access.
        out_sa = render_install({"serviceAccount": "my-sa",
                                 "observability": {"enabled": True}})
        ds = next(m for m in out_sa if m["kind"] == "DaemonSet")
        pod = ds["spec"]["template"]["spec"]
        assert pod["serviceAccountName"] == "omnia-collector"
        collector_sas = [m for m in out_sa if m["kind"] == "ServiceAccount"
                         and m["metadata"]["name"] == "omnia-collector"]
        assert len(collector_sas) == 1
        crb = next(m for m in out_sa if m["kind"] == "ClusterRoleBinding"
                   and m["metadata"]["name"] == "omnia-collector")
        assert crb["subjects"][0]["name"] == "omnia-collector"
        env = pod["containers"][0]["env"][0]
        assert env["name"] == "NODE_NAME"
        assert env["valueFrom"]["fieldRef"]["fieldPath"] == "spec.nodeName"
        assert 'field = "spec.nodeName=" + sys.env("NODE_NAME")' in cfg
        assert ("Service", "omnia-collector") in kinds
        role = next(m for m in out if m["kind"] == "ClusterRole"
                    and m["metadata"]["name"] == "omnia-collector")
        flat = [(g, res, v) for r in role["rules"] for g in r["apiGroups"]
                for res in r["resources"] for v in r["verbs"]]
        assert ("", "pods", "list") in flat and ("", "pods/log", "get") in flat
        # ...and the operator's role does NOT carry the log grant.
        op_role = next(m for m in out if m["kind"] == "ClusterRole"
                       and m["metadata"]["name"] == "omnia-operator")
        op_flat = [res for r in op_role["rules"] for res in r["resources"]]
        assert "pods/log" not in op_flat
        # Tempo expires blocks instead of filling the emptyDir (ADVICE r4).
        assert "block_retention: 168h" in tempo_cm["data"]["tempo.yaml"]
        # Loki actually ENFORCES retention (compactor, Loki 3.x).
        assert "retention_enabled: true" in loki_cm["data"]["loki.yaml"]
        # No observability env leaks into a bare render.
        bare_dep = next(m for m in render_install() if m["kind"] == "Deployment"
                        and m["metadata"]["name"] == "omnia-operator")
        bare_env = [e["name"] for e
                    in bare_dep["spec"]["template"]["spec"]["containers"][0]["env"]]
        assert "OMNIA_OTLP_ENDPOINT" not in bare_env

    def test_values_schema_rejects_typos(self):
        """values.schema.json discipline (reference charts/omnia):
        unknown keys and wrong types fail at render, not at apply."""
        from omnia_tpu.operator.install import ValuesError, VALUES_SCHEMA

        with pytest.raises(ValuesError, match="observabilty"):
            render_install({"observabilty": {"enabled": True}})
        with pytest.raises(ValuesError, match="replicas"):
            render_install({"operator": {"replicas": "three"}})
        with pytest.raises(ValuesError, match="loki"):
            render_install({"observability": {"loki": {"imge": "x"}}})
        # The committed schema file matches the in-code schema.
        with open(os.path.join(REPO, "deploy", "values.schema.json")) as f:
            assert json.load(f) == VALUES_SCHEMA
        # The committed values pass their own schema.
        with open(os.path.join(REPO, "deploy", "values.yaml")) as f:
            render_install(yaml.safe_load(f))

    def test_yaml_round_trips(self):
        manifests = render_install()
        assert list(yaml.safe_load_all(to_yaml(manifests))) == manifests

    def test_rbac_covers_crd_group(self):
        from omnia_tpu.operator.crds import GROUP

        out = render_install()
        role = next(m for m in out if m["kind"] == "ClusterRole")
        assert any(GROUP in r["apiGroups"] for r in role["rules"])


class TestAgentPodManifests:
    def test_agent_deployment_passes_lint(self):
        from omnia_tpu.operator.deployment import AgentDeployment, K8sManifestBackend
        from omnia_tpu.operator.resources import Resource

        res = Resource(
            kind="AgentRuntime", name="support-bot", namespace="team-a",
            spec={
                "promptPackRef": {"name": "pack"},
                "providers": [{"providerRef": {"name": "tpu-llm"}}],
                "tpuChips": 8,
                "podOverrides": {
                    "nodeSelector": {
                        "cloud.google.com/gke-tpu-accelerator": "tpu-v5-lite-podslice",
                        "cloud.google.com/gke-tpu-topology": "2x4",
                    },
                    "tolerations": [{
                        "key": "google.com/tpu", "operator": "Exists",
                        "effect": "NoSchedule",
                    }],
                },
            },
        )
        dep = AgentDeployment(
            res, pack_doc={"name": "pack", "version": "1.0.0"},
            provider_specs=[{"name": "tpu-llm", "type": "tpu"}],
            default_provider="tpu-llm",
        )
        rendered = K8sManifestBackend().render(dep)
        manifests = [rendered["deployment"], rendered["service"]]
        errs = lint(manifests)
        assert errs == [], errs
        dep_m = next(m for m in manifests if m["kind"] == "Deployment")
        pod = dep_m["spec"]["template"]["spec"]
        assert pod["nodeSelector"]["cloud.google.com/gke-tpu-accelerator"]
        runtime = next(c for c in pod["containers"] if c["name"] == "runtime")
        assert runtime["resources"]["limits"]["google.com/tpu"] == 8


class TestMultiHostManifests:
    def test_tpu_hosts_renders_statefulset_with_coordinator(self):
        """spec.tpuHosts > 1 → StatefulSet with stable ordinals (= jax
        process ids), headless coordinator service, and the distributed
        env contract on the runtime container (SURVEY §5.8 DCN path)."""
        from omnia_tpu.operator.deployment import AgentDeployment, K8sManifestBackend
        from omnia_tpu.operator.resources import Resource

        res = Resource(
            kind="AgentRuntime", name="llama70b", namespace="prod",
            spec={
                "promptPackRef": {"name": "pack"},
                "providers": [{"providerRef": {"name": "tpu-llm"}}],
                "tpuChips": 4, "tpuHosts": 4,
            },
        )
        dep = AgentDeployment(
            res, pack_doc={"name": "pack", "version": "1.0.0"},
            provider_specs=[{"name": "tpu-llm", "type": "tpu"}],
            default_provider="tpu-llm",
        )
        rendered = K8sManifestBackend().render(dep)
        sts = rendered["deployment"]
        assert sts["kind"] == "StatefulSet"
        assert sts["spec"]["replicas"] == 4
        assert sts["spec"]["serviceName"] == "agent-llama70b-hosts"
        runtime = next(c for c in sts["spec"]["template"]["spec"]["containers"]
                       if c["name"] == "runtime")
        env = {e["name"]: e.get("value") for e in runtime["env"]}
        assert env["OMNIA_NUM_PROCESSES"] == "4"
        assert env["OMNIA_COORDINATOR_ADDR"] == (
            "agent-llama70b-0.agent-llama70b-hosts.prod.svc:8476")
        headless = rendered["headless_service"]
        assert headless["spec"]["clusterIP"] == "None"
        # Clients route to the LEADER pod only; followers have no facade.
        assert rendered["service"]["spec"]["selector"] == {
            "statefulset.kubernetes.io/pod-name": "agent-llama70b-0"}
        # autoscaling must not target a multi-host set
        assert "autoscaling" not in rendered

    def test_multi_host_rejects_replicas_and_autoscaling(self):
        from omnia_tpu.operator.resources import Resource
        from omnia_tpu.operator.validation import ValidationError, validate

        base = {
            "promptPackRef": {"name": "p"},
            "providers": [{"providerRef": {"name": "m"}}],
            "tpuHosts": 4,
        }
        with pytest.raises(ValidationError, match="replicas"):
            validate(Resource(kind="AgentRuntime", name="a",
                              spec={**base, "replicas": 3}))
        with pytest.raises(ValidationError, match="autoscaled"):
            validate(Resource(kind="AgentRuntime", name="a",
                              spec={**base, "autoscaling": {"maxReplicas": 4}}))


class TestDockerfiles:
    SERVICES = ("runtime", "facade", "session-api", "memory-api", "operator",
                "redisd")

    def test_dockerfiles_exist_with_entrypoints(self):
        for svc in self.SERVICES:
            path = os.path.join(REPO, "deploy", "docker", f"Dockerfile.{svc}")
            assert os.path.exists(path), f"missing Dockerfile.{svc}"
            content = open(path).read()
            assert "ENTRYPOINT" in content
            assert "omnia_tpu" in content

    def test_entrypoints_are_declared_scripts(self):
        """Every ENTRYPOINT [\"omnia-*\"] must be a console script in
        pyproject — an image that can't exec its entrypoint is dead on
        arrival."""
        import re
        import tomllib

        with open(os.path.join(REPO, "pyproject.toml"), "rb") as f:
            scripts = tomllib.load(f)["project"]["scripts"]
        for svc in self.SERVICES:
            content = open(
                os.path.join(REPO, "deploy", "docker", f"Dockerfile.{svc}")
            ).read()
            for m in re.findall(r'ENTRYPOINT \["(omnia-[a-z-]+)"', content):
                assert m in scripts, f"{m} not in pyproject scripts"

    def test_script_targets_import_and_are_callable(self):
        import importlib
        import tomllib

        with open(os.path.join(REPO, "pyproject.toml"), "rb") as f:
            scripts = tomllib.load(f)["project"]["scripts"]
        for name, target in scripts.items():
            mod_name, fn_name = target.split(":")
            fn = getattr(importlib.import_module(mod_name), fn_name)
            assert callable(fn), name


class TestManifestLintBites:
    """The gate is only a gate if it fails bad input."""

    def test_selector_mismatch_caught(self):
        bad = {
            "apiVersion": "apps/v1", "kind": "Deployment",
            "metadata": {"name": "x", "namespace": "d"},
            "spec": {
                "selector": {"matchLabels": {"app": "x"}},
                "template": {
                    "metadata": {"labels": {"app": "WRONG"}},
                    "spec": {"containers": [{"name": "c", "image": "i"}]},
                },
            },
        }
        assert any("selector" in e for e in lint([bad]))

    def test_duplicate_pod_port_names_caught(self):
        bad = {
            "apiVersion": "apps/v1", "kind": "Deployment",
            "metadata": {"name": "x", "namespace": "d"},
            "spec": {
                "selector": {"matchLabels": {"a": "b"}},
                "template": {
                    "metadata": {"labels": {"a": "b"}},
                    "spec": {"containers": [
                        {"name": "c1", "image": "i",
                         "ports": [{"name": "metrics", "containerPort": 1}]},
                        {"name": "c2", "image": "i",
                         "ports": [{"name": "metrics", "containerPort": 2}]},
                    ]},
                },
            },
        }
        assert any("duplicate port name" in e for e in lint([bad]))

    def test_crd_name_rule_caught(self):
        crd = render_crd("Provider")
        crd["metadata"]["name"] = "wrong.example.com"
        assert any("plural" in e or "<plural>" in e for e in lint([crd]))

    def test_untyped_schema_caught(self):
        crd = render_crd("Provider")
        schema = crd["spec"]["versions"][0]["schema"]["openAPIV3Schema"]
        schema["properties"]["spec"]["properties"]["mystery"] = {}
        assert any("missing type" in e for e in lint([crd]))


class TestCLIWiring:
    def test_session_api_from_env(self, tmp_path, monkeypatch):
        """omnia-session-api assembles redis hot tier + warm sqlite + cold
        archive purely from env, serves HTTP, and records a session."""
        import threading

        from omnia_tpu.redis import RedisServer
        from omnia_tpu.session.api import SessionAPI  # noqa: F401

        srv = RedisServer().start()
        monkeypatch.setenv("OMNIA_REDIS_ADDR", "127.0.0.1:%d" % srv.address[1])
        monkeypatch.setenv("OMNIA_WARM_DB", str(tmp_path / "warm.db"))
        monkeypatch.setenv("OMNIA_COLD_DIR", str(tmp_path / "cold"))
        monkeypatch.setenv("OMNIA_HTTP_PORT", "0")

        # Drive the same assembly code the entry point runs, without the
        # signal wait: replicate session_api_main's wiring through its
        # helpers.
        from omnia_tpu import cli

        rc = cli._redis_client()
        assert rc is not None
        from omnia_tpu.session.redis_hot import RedisHotStore
        from omnia_tpu.session.cold import ColdArchive, LocalBlobStore
        from omnia_tpu.session.tiers import TieredStore
        from omnia_tpu.session.warm import WarmStore

        store = TieredStore(
            hot=RedisHotStore(rc),
            warm=WarmStore(os.environ["OMNIA_WARM_DB"]),
            cold=ColdArchive(LocalBlobStore(os.environ["OMNIA_COLD_DIR"])),
        )
        api = SessionAPI(store=store)
        port = api.serve(host="127.0.0.1", port=0)
        try:
            body = json.dumps({"session_id": "cli-smoke"}).encode()
            req = urllib.request.Request(
                f"http://127.0.0.1:{port}/api/v1/sessions", data=body,
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=10) as resp:
                assert resp.status in (200, 201)
            assert store.get_session("cli-smoke") is not None
        finally:
            api.shutdown()
            srv.stop()


class TestExamples:
    """The shipped examples must actually load and reconcile (an example
    that drifts from the schema is worse than none)."""

    def test_example_devroots_reconcile(self):
        from omnia_tpu.operator.controller import ControllerManager
        from omnia_tpu.operator.resources import Resource
        from omnia_tpu.operator.store import MemoryResourceStore

        for example, agent_kinds in (
            ("examples/custom-runtime/devroot/agent.yaml", "agent"),
            ("examples/echo-function/function.yaml", "function"),
            ("examples/voice-agent/agent.yaml", "agent"),
            ("examples/tool-agent/agent.yaml", "agent"),
        ):
            store = MemoryResourceStore()
            mgr = ControllerManager(store)  # before apply: watch fires
            try:
                with open(os.path.join(REPO, example)) as f:
                    for doc in yaml.safe_load_all(f):
                        store.apply(Resource.from_manifest(doc))  # admission
                mgr.drain_queue()
                ar = store.list(kind="AgentRuntime")[0]
                assert ar.status.get("phase") == "Running", (example, ar.status)
                assert ar.spec["mode"] == agent_kinds
            finally:
                mgr.shutdown()

    def test_voice_agent_example_speaks_pcm16(self):
        """The voice-agent example makes a REAL voice call against its
        declared tone speech providers: pcm16 in, pcm16 out (VERDICT r2
        #6 'voice-agent example runs against declared providers')."""
        import json as _json
        import time as _time

        import numpy as np
        from websockets.sync.client import connect

        from omnia_tpu.operator.controller import ControllerManager
        from omnia_tpu.operator.resources import Resource
        from omnia_tpu.operator.store import MemoryResourceStore
        from omnia_tpu.runtime.duplex import TonePcmStt, TonePcmTts

        from omnia_tpu.runtime.speechd import SpeechDevServer

        store = MemoryResourceStore()
        mgr = ControllerManager(store)
        fmt = {"encoding": "pcm16", "sample_rate_hz": 16000, "channels": 1}
        # The example declares REAL vendor-type (cartesia) speech
        # providers pointed at the dev speech server; the test runs one
        # on an ephemeral port and rewrites only base_url.
        speechd = SpeechDevServer(api_key="dev")
        sport = speechd.serve()
        try:
            with open(os.path.join(REPO, "examples/voice-agent/agent.yaml")) as f:
                for doc in yaml.safe_load_all(f):
                    opts = (doc.get("spec") or {}).get("options") or {}
                    if "base_url" in opts:
                        opts["base_url"] = f"http://127.0.0.1:{sport}"
                    store.apply(Resource.from_manifest(doc))
            mgr.drain_queue()
            dep = next(iter(mgr.deployments.values()))
            endpoint = dep.pods[0].endpoint
            with connect(endpoint) as ws:
                connected = _json.loads(ws.recv(timeout=10))
                assert "duplex_audio" in connected["capabilities"]
                ws.send(_json.dumps({"type": "duplex_start", "format": fmt}))
                assert _json.loads(ws.recv(timeout=10))["type"] == "duplex_ready"
                ws.send(b"".join(TonePcmTts().synthesize("about refunds", fmt)))
                ws.send(b"")
                audio = bytearray()
                deadline = _time.monotonic() + 30
                while _time.monotonic() < deadline:
                    frame = ws.recv(timeout=deadline - _time.monotonic())
                    if isinstance(frame, bytes):
                        audio.extend(frame)
                    elif _json.loads(frame)["type"] == "done":
                        break
                samples = np.frombuffer(bytes(audio), dtype="<i2")
                assert int(np.abs(samples).max()) > 5000
                assert (
                    TonePcmStt().transcribe(bytes(audio), fmt)
                    == "refunds take thirty days to process"
                )
            # The vendor path really was exercised: the dev server saw
            # authenticated cartesia-shaped STT + TTS calls.
            paths = {r["path"] for r in speechd.requests}
            assert paths == {"/stt", "/tts/bytes"}, paths
        finally:
            mgr.shutdown()
            speechd.shutdown()


class TestEntryPointWiring:
    """Systematic per-entry-point wiring (reference
    hack/check-wiring-tests.sh discipline: every binary's main must be
    asserted to actually connect its flags/env/servers): each long-running
    main boots in a child process from OMNIA_* env alone, answers its
    health/serving port, and dies cleanly on SIGTERM."""

    @staticmethod
    def _free_port():
        import socket

        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        p = s.getsockname()[1]
        s.close()
        return p

    def _boot(self, main_name, env, probe, timeout=60):
        import signal
        import subprocess
        import sys
        import time as _t

        child_env = {**os.environ, **env,
                     "JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO}
        child_env.pop("PALLAS_AXON_POOL_IPS", None)
        proc = subprocess.Popen(
            [sys.executable, "-c",
             f"from omnia_tpu.cli import {main_name}; raise SystemExit({main_name}())"],
            env=child_env, cwd=REPO,
            stdout=subprocess.DEVNULL, stderr=subprocess.PIPE,
        )
        try:
            deadline = _t.monotonic() + timeout
            last = None
            while _t.monotonic() < deadline:
                if proc.poll() is not None:
                    raise AssertionError(
                        f"{main_name} exited early rc={proc.returncode}: "
                        f"{proc.stderr.read().decode()[-2000:]}"
                    )
                try:
                    probe()
                    break
                except Exception as e:  # noqa: BLE001 - poll until ready
                    last = e
                    _t.sleep(0.25)
            else:
                raise AssertionError(f"{main_name} never became ready: {last}")
            proc.send_signal(signal.SIGTERM)
            rc = proc.wait(timeout=20)
            assert rc in (0, -signal.SIGTERM), f"{main_name} dirty exit {rc}"
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=10)

    @staticmethod
    def _http_ok(url):
        def probe():
            with urllib.request.urlopen(url, timeout=2) as r:
                assert r.status == 200
        return probe

    def test_redisd_main(self):
        port = self._free_port()

        def probe():
            from omnia_tpu.redis import RedisClient

            assert RedisClient("127.0.0.1", port).ping()

        self._boot("redisd_main", {"OMNIA_REDIS_PORT": str(port)}, probe)

    def test_session_api_main(self, tmp_path):
        port = self._free_port()
        self._boot(
            "session_api_main",
            {"OMNIA_HTTP_PORT": str(port),
             "OMNIA_WARM_DB": str(tmp_path / "warm.db")},
            self._http_ok(f"http://127.0.0.1:{port}/healthz"),
        )

    def test_memory_api_main(self, tmp_path):
        port = self._free_port()
        self._boot(
            "memory_api_main",
            {"OMNIA_HTTP_PORT": str(port),
             "OMNIA_MEMORY_DB": str(tmp_path / "mem.jsonl"),
             "OMNIA_EMBED_DIM": "16"},
            self._http_ok(f"http://127.0.0.1:{port}/healthz"),
        )

    def test_runtime_and_facade_mains(self, tmp_path):
        """runtime main serves the gRPC contract from pack+provider files;
        facade main bridges it to WS — the agent pod pair, booted exactly
        as the Dockerfiles do."""
        import json as _json

        rt_port = self._free_port()
        ws_port = self._free_port()
        health_port = self._free_port()
        (tmp_path / "pack.json").write_text(_json.dumps({
            "name": "wire", "version": "1.0.0",
            "prompts": {"system": "s"}, "sampling": {"max_tokens": 16}}))
        (tmp_path / "providers.json").write_text(_json.dumps([
            {"name": "m", "type": "mock",
             "options": {"scenarios": [{"pattern": ".", "reply": "wired"}]}}]))

        def rt_probe():
            from omnia_tpu.runtime.client import RuntimeClient

            c = RuntimeClient(f"127.0.0.1:{rt_port}")
            try:
                assert c.health().status == "ok"
            finally:
                c.close()

        import signal
        import subprocess
        import sys
        import time as _t

        env = {**os.environ, "JAX_PLATFORMS": "cpu", "PYTHONPATH": REPO,
               "OMNIA_PACK_PATH": str(tmp_path / "pack.json"),
               "OMNIA_PROVIDERS_PATH": str(tmp_path / "providers.json"),
               "OMNIA_GRPC_PORT": str(rt_port)}
        env.pop("PALLAS_AXON_POOL_IPS", None)
        rt = subprocess.Popen(
            [sys.executable, "-c",
             "from omnia_tpu.cli import runtime_main; raise SystemExit(runtime_main())"],
            env=env, cwd=REPO, stdout=subprocess.DEVNULL, stderr=subprocess.PIPE)
        try:
            deadline = _t.monotonic() + 90
            while _t.monotonic() < deadline:
                if rt.poll() is not None:
                    raise AssertionError(
                        f"runtime died: {rt.stderr.read().decode()[-2000:]}")
                try:
                    rt_probe()
                    break
                except Exception:
                    _t.sleep(0.25)
            else:
                raise AssertionError("runtime never ready")
            self._boot(
                "facade_main",
                {"OMNIA_RUNTIME_TARGET": f"127.0.0.1:{rt_port}",
                 "OMNIA_WS_PORT": str(ws_port),
                 "OMNIA_HEALTH_PORT": str(health_port)},
                self._http_ok(f"http://127.0.0.1:{health_port}/healthz"),
            )
        finally:
            rt.send_signal(signal.SIGTERM)
            try:
                rt.wait(timeout=20)
            except subprocess.TimeoutExpired:
                rt.kill()

    def test_operator_main(self, tmp_path):
        import yaml as _yaml

        http_port = self._free_port()
        api_port = self._free_port()
        devroot = tmp_path / "devroot"
        devroot.mkdir()
        (devroot / "provider.yaml").write_text(_yaml.safe_dump({
            "apiVersion": "omnia.tpu/v1alpha1", "kind": "Provider",
            "metadata": {"name": "m"},
            "spec": {"type": "mock", "role": "llm", "options": {}}}))
        self._boot(
            "operator_main",
            {"OMNIA_CONFIG_DIR": str(devroot),
             "OMNIA_HTTP_PORT": str(http_port),
             "OMNIA_API_PORT": str(api_port),
             "OMNIA_DASHBOARD": "1"},
            self._http_ok(f"http://127.0.0.1:{http_port}/healthz"),
            timeout=90,
        )

    def test_compaction_and_doctor_mains_one_shot(self, tmp_path, monkeypatch):
        """The CronJob-style binaries run one pass and exit 0."""
        from omnia_tpu import cli

        monkeypatch.setenv("OMNIA_WARM_DB", str(tmp_path / "warm.db"))
        monkeypatch.setenv("OMNIA_COLD_DIR", str(tmp_path / "cold"))
        monkeypatch.delenv("OMNIA_REDIS_ADDR", raising=False)
        monkeypatch.delenv("OMNIA_PG_DSN", raising=False)
        assert cli.compaction_main() == 0
        monkeypatch.delenv("OMNIA_RUNTIME_TARGET", raising=False)
        monkeypatch.delenv("OMNIA_SESSION_API_URL", raising=False)
        assert cli.doctor_main() in (0, 1)  # no checks configured → report

    def test_conformance_main_one_shot(self):
        """omnia-conformance (conformance_main) runs the suite against a
        live runtime target and exits by verdict."""
        import sys
        from unittest import mock

        from omnia_tpu import cli
        from omnia_tpu.runtime.packs import load_pack
        from omnia_tpu.runtime.providers import ProviderRegistry, ProviderSpec
        from omnia_tpu.runtime.server import RuntimeServer

        reg = ProviderRegistry()
        reg.register(ProviderSpec(name="m", type="mock", options={
            "scenarios": [{"pattern": ".", "reply": "conformant"}]}))
        rt = RuntimeServer(
            pack=load_pack({"name": "p", "version": "1.0.0",
                            "prompts": {"system": "s"},
                            "sampling": {"max_tokens": 16}}),
            providers=reg, provider_name="m")
        port = rt.serve("localhost:0")
        try:
            with mock.patch.object(sys, "argv",
                                   ["omnia-conformance", f"localhost:{port}"]):
                assert cli.conformance_main() == 0
        finally:
            rt.shutdown()

    def test_lsp_main_stdio_wiring(self, tmp_path, monkeypatch):
        """omnia-pack-lsp (lsp_main) speaks LSP over stdio: initialize →
        respond → exit cleanly."""
        import io
        import sys

        from omnia_tpu import lsp as lsp_mod

        body = b""
        for doc in (
            {"jsonrpc": "2.0", "id": 1, "method": "initialize", "params": {}},
            {"jsonrpc": "2.0", "method": "exit"},
        ):
            payload = json.dumps(doc).encode()
            body += b"Content-Length: %d\r\n\r\n%s" % (len(payload), payload)

        stdin = io.BytesIO(body)
        stdout = io.BytesIO()
        monkeypatch.setattr(lsp_mod.sys, "stdin",
                            type("S", (), {"buffer": stdin})())
        monkeypatch.setattr(lsp_mod.sys, "stdout",
                            type("S", (), {"buffer": stdout})())
        assert lsp_mod.lsp_main() == 0
        out = stdout.getvalue()
        assert b"capabilities" in out


class TestExampleScripts:
    """Shipped example/demo scripts must actually run (an example that
    drifts from the API is worse than none)."""

    def test_custom_facade_example(self):
        import importlib.util
        import urllib.request as _ur

        from omnia_tpu.runtime.packs import load_pack
        from omnia_tpu.runtime.providers import ProviderRegistry, ProviderSpec
        from omnia_tpu.runtime.server import RuntimeServer

        reg = ProviderRegistry()
        reg.register(ProviderSpec(name="m", type="mock", options={
            "scenarios": [{"pattern": ".", "reply": "from custom facade"}]}))
        rt = RuntimeServer(
            pack=load_pack({"name": "p", "version": "1.0.0",
                            "prompts": {"system": "s"},
                            "sampling": {"max_tokens": 64}}),
            providers=reg, provider_name="m")
        port = rt.serve("localhost:0")
        spec = importlib.util.spec_from_file_location(
            "slackish", os.path.join(REPO, "examples/custom-facade/slackish.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        httpd = mod.serve(f"localhost:{port}", port=0)
        import threading as _th

        _th.Thread(target=httpd.serve_forever, daemon=True).start()
        try:
            hport = httpd.server_address[1]
            req = _ur.Request(
                f"http://127.0.0.1:{hport}/command",
                data=json.dumps({"user": "ada", "text": "hi"}).encode())
            with _ur.urlopen(req, timeout=15) as resp:
                assert json.loads(resp.read())["reply"] == "from custom facade"
        finally:
            httpd.shutdown()
            rt.shutdown()

    def test_memory_seeder_demo(self, monkeypatch):
        import importlib.util

        from omnia_tpu.memory import HashingEmbedder, MemoryAPI

        api = MemoryAPI(embedder=HashingEmbedder(dim=16))
        port = api.serve(host="127.0.0.1", port=0)
        try:
            monkeypatch.setenv("OMNIA_MEMORY_API_URL", f"http://127.0.0.1:{port}")
            spec = importlib.util.spec_from_file_location(
                "seed", os.path.join(REPO, "demos/memory-seeder/seed.py"))
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)
            mod.main()
            api.reembed.drain()
            code, resp = api.handle(
                "POST", "/api/v1/memories/retrieve",
                {"workspace_id": "demo", "query": "refund", "limit": 3})
            assert code == 200
            assert any("thirty days" in m["content"] for m in resp["memories"])
        finally:
            api.close()


class TestPDB:
    def test_multi_replica_agents_get_disruption_floor(self):
        from omnia_tpu.operator.deployment import AgentDeployment, K8sManifestBackend
        from omnia_tpu.operator.resources import Resource

        def render(extra, replicas=1):
            res = Resource(kind="AgentRuntime", name="a", spec={
                "promptPackRef": {"name": "p"},
                "providers": [{"providerRef": {"name": "m"}}], **extra})
            return K8sManifestBackend().render(AgentDeployment(
                res, pack_doc={"name": "p", "version": "1.0.0"},
                provider_specs=[{"name": "m", "type": "mock"}],
                default_provider="m", replicas=replicas))

        out = render({}, replicas=3)
        pdb = out["pdb"]
        assert pdb["spec"]["minAvailable"] == 1
        # track-scoped: a lone canary pod must not satisfy the floor.
        assert pdb["spec"]["selector"]["matchLabels"] == {
            "omnia/agent": "a", "omnia/track": "stable"}
        # Single replica: a PDB would block every drain — none rendered.
        assert "pdb" not in render({}, replicas=1)
        # ...unless autoscaling can fan it out past one pod.
        scaled = render({"autoscaling": {"minReplicas": 1, "maxReplicas": 5}},
                        replicas=1)
        assert scaled["pdb"]["spec"]["minAvailable"] == 1
        # Multi-host: evicting any host breaks lockstep — none rendered.
        assert "pdb" not in render({"tpuHosts": 2})


class TestCanaryManifests:
    def test_render_candidate_with_traffic_split(self):
        """Cluster-side rollout artifacts (reference rollout_candidate.go
        + rollout_istio.go): candidate Deployment on its own track label,
        track-scoped Services, Istio VirtualService splitting by step
        weight — selectors must NOT leak candidate pods into stable."""
        from omnia_tpu.operator.deployment import AgentDeployment, K8sManifestBackend
        from omnia_tpu.operator.resources import Resource

        res = Resource(kind="AgentRuntime", name="a", spec={
            "promptPackRef": {"name": "p"},
            "providers": [{"providerRef": {"name": "m"}}]})
        dep = AgentDeployment(
            res, pack_doc={"name": "p", "version": "1.0.0"},
            provider_specs=[{"name": "m", "type": "mock"}],
            default_provider="m")
        out = K8sManifestBackend().render_candidate(dep, "hash-v2", 25)
        cand = out["candidate_deployment"]
        assert cand["metadata"]["name"] == "agent-a-canary"
        assert cand["spec"]["selector"]["matchLabels"]["omnia/track"] == "candidate"
        assert cand["spec"]["template"]["metadata"]["labels"]["omnia/track"] == "candidate"
        assert cand["metadata"]["annotations"]["omnia/config-hash"] == "hash-v2"
        assert cand["spec"]["replicas"] == 1
        assert lint([cand, out["stable_service"], out["candidate_service"]]) == []
        routes = out["virtual_service"]["spec"]["http"][0]["route"]
        assert [(r["destination"]["host"], r["weight"]) for r in routes] == [
            ("agent-a-stable", 75), ("agent-a-canary", 25)]
        # Candidate service selects ONLY candidate pods; stable selects all
        # agent pods minus... k8s can't negate, so stable keeps the agent
        # selector and the VS weights do the split (reference approach).
        assert out["candidate_service"]["spec"]["selector"]["omnia/track"] == "candidate"
