"""Realtime park/resume + route table tests (reference
internal/facade/realtime_registry.go:27-118, route_store.go /
route_store_redis.go parity): a WS blip mid-duplex parks the live call;
reconnecting with the same session resumes it with nothing lost."""

import json
import threading
import time

import pytest
from websockets.sync.client import connect

from omnia_tpu.facade.realtime import (
    InMemoryRouteStore,
    RealtimeRegistry,
    RedisRouteStore,
)
from omnia_tpu.facade.server import FacadeServer
from omnia_tpu.redis import RedisClient, RedisServer
from omnia_tpu.runtime.duplex import MockStt, MockTts, SpeechSupport
from omnia_tpu.runtime.packs import load_pack
from omnia_tpu.runtime.providers import ProviderRegistry, ProviderSpec
from omnia_tpu.runtime.server import RuntimeServer

PACK = {
    "name": "voice", "version": "1.0.0",
    "prompts": {"system": "You speak."}, "sampling": {"max_tokens": 256},
}
SCENARIOS = [
    {"pattern": "how do refunds work", "reply": "refunds take thirty days"},
    {"pattern": "story", "reply": "o n c e  u p o n  a  t i m e " * 4,
     "delay_per_token_s": 0.01},
    {"pattern": ".", "reply": "I heard you"},
]


@pytest.fixture()
def stack():
    reg = ProviderRegistry()
    reg.register(ProviderSpec(name="m", type="mock", options={"scenarios": SCENARIOS}))
    rt = RuntimeServer(
        pack=load_pack(PACK), providers=reg, provider_name="m",
        speech=SpeechSupport(MockStt(), MockTts()),
    )
    rport = rt.serve("localhost:0")
    registry = RealtimeRegistry(park_ttl_s=10.0)
    routes = InMemoryRouteStore()
    facade = FacadeServer(
        runtime_target=f"localhost:{rport}", agent_name="voice-agent",
        realtime=registry, route_store=routes, advertise_address="pod-1:443",
    )
    fport = facade.serve()
    yield facade, fport, registry, routes
    registry.shutdown()
    facade.shutdown()
    rt.shutdown()


def _drain_call(ws, want_text: str, deadline_s: float = 30.0):
    """Collect binary audio + transcripts until `done`."""
    audio = bytearray()
    transcripts = []
    deadline = time.monotonic() + deadline_s
    while time.monotonic() < deadline:
        frame = ws.recv(timeout=deadline - time.monotonic())
        if isinstance(frame, bytes):
            audio.extend(frame)
            continue
        doc = json.loads(frame)
        if doc["type"] == "transcript":
            transcripts.append((doc["role"], doc["text"]))
        elif doc["type"] == "done":
            break
    return bytes(audio), transcripts


class TestParkResume:
    def test_ws_blip_parks_then_resume_preserves_call(self, stack):
        facade, fport, registry, routes = stack
        url = f"ws://localhost:{fport}/ws?session=call-1&user=alice"

        # Start the call, provoke a long reply, kill the socket mid-stream.
        ws = connect(url)
        connected = json.loads(ws.recv(timeout=10))
        session_id = connected["session_id"]
        ws.send(json.dumps({"type": "duplex_start", "format": {"encoding": "pcm16"}}))
        assert json.loads(ws.recv(timeout=10))["type"] == "duplex_ready"
        ws.send(b"story")
        ws.send(b"")
        # Read a couple of frames to know the reply is flowing, then blip.
        got_first = ws.recv(timeout=15)
        ws.socket.close()  # abrupt — no close handshake, no hangup

        deadline = time.monotonic() + 5
        while registry.parked_count() == 0 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert registry.parked_count() == 1
        assert routes.get(session_id) == "pod-1:443"

        # Reconnect with the same session: the call resumes; buffered
        # audio generated during the blip is replayed.
        time.sleep(0.3)  # let some output accumulate while parked
        ws2 = connect(url)
        connected2 = json.loads(ws2.recv(timeout=10))
        assert connected2["resumed"] is True
        assert connected2.get("mode") == "duplex"
        audio, transcripts = _drain_call(ws2, "once")
        full = (got_first if isinstance(got_first, bytes) else b"") + audio
        assert b"o n c e" in full or b"u p o n" in full
        assert registry.parked_count() == 0
        # Second utterance on the resumed call proves the stream is live.
        ws2.send(b"how do refunds work")
        ws2.send(b"")
        audio2, tr2 = _drain_call(ws2, "refunds")
        assert b"refunds take thirty days" in audio2
        ws2.send(json.dumps({"type": "hangup"}))
        ws2.close()

    def test_transcripts_recorded_through_blip(self, stack):
        facade, fport, registry, routes = stack
        url = f"ws://localhost:{fport}/ws?session=call-rec&user=alice"
        ws = connect(url)
        sid = json.loads(ws.recv(timeout=10))["session_id"]
        ws.send(json.dumps({"type": "duplex_start", "format": {}}))
        assert json.loads(ws.recv(timeout=10))["type"] == "duplex_ready"
        ws.send(b"story")
        ws.send(b"")
        ws.recv(timeout=15)  # first frame flowing
        ws.socket.close()
        deadline = time.monotonic() + 5
        while registry.parked_count() == 0 and time.monotonic() < deadline:
            time.sleep(0.05)
        # The turn completes while nobody is attached; its frames buffer
        # and its transcripts are recorded at emit time. Attach a fake
        # sink and the whole parked backlog (incl. done) replays.
        parked = registry.take(sid, "alice")
        assert parked is not None
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline and not any(
            m.type == "done" for m in list(parked._buffer)
        ):
            time.sleep(0.1)

        class FakeWS:
            frames = []

            def send(self, data):
                FakeWS.frames.append(data)

        replayed = parked.attach(FakeWS())
        assert replayed > 0
        jsons = [json.loads(f) for f in FakeWS.frames if isinstance(f, str)]
        assert any(d["type"] == "done" for d in jsons)
        assert any(
            d["type"] == "transcript" and d["role"] == "assistant" for d in jsons
        )
        parked.close()

    def test_hangup_is_not_parked(self, stack):
        facade, fport, registry, routes = stack
        url = f"ws://localhost:{fport}/ws?session=call-2&user=bob"
        with connect(url) as ws:
            sid = json.loads(ws.recv(timeout=10))["session_id"]
            ws.send(json.dumps({"type": "duplex_start", "format": {}}))
            assert json.loads(ws.recv(timeout=10))["type"] == "duplex_ready"
            ws.send(b"how do refunds work")
            ws.send(b"")
            _drain_call(ws, "refunds")
            ws.send(json.dumps({"type": "hangup"}))
        time.sleep(0.3)
        assert registry.parked_count() == 0
        assert routes.get(sid) is None

    def test_other_user_cannot_take_parked_call(self, stack):
        facade, fport, registry, routes = stack
        ws = connect(f"ws://localhost:{fport}/ws?session=call-3&user=alice")
        sid = json.loads(ws.recv(timeout=10))["session_id"]
        ws.send(json.dumps({"type": "duplex_start", "format": {}}))
        assert json.loads(ws.recv(timeout=10))["type"] == "duplex_ready"
        ws.send(b"story")
        ws.send(b"")
        ws.recv(timeout=15)
        ws.socket.close()
        deadline = time.monotonic() + 5
        while registry.parked_count() == 0 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert registry.take(sid, "mallory") is None
        assert registry.parked_count() == 1  # still parked for alice
        took = registry.take(sid, "alice")
        assert took is not None
        took.close()


class TestRegistry:
    def test_reaper_expires_unclaimed(self):
        registry = RealtimeRegistry(park_ttl_s=0.2)

        class FakeStream:
            closed = False

            def __iter__(self):
                return iter(())

            def close(self):
                FakeStream.closed = True

        from omnia_tpu.facade.realtime import DuplexSession

        s = DuplexSession(FakeStream(), "sid-x", "u", forward=lambda ws, m: None)
        registry.park(s)
        deadline = time.monotonic() + 5
        while registry.parked_count() and time.monotonic() < deadline:
            time.sleep(0.05)
        assert registry.parked_count() == 0
        assert FakeStream.closed
        registry.shutdown()


@pytest.fixture(params=["memory", "redis"])
def route_store(request):
    if request.param == "memory":
        yield InMemoryRouteStore()
    else:
        srv = RedisServer().start()
        c = RedisClient(*srv.address)
        yield RedisRouteStore(c)
        c.close()
        srv.stop()


class TestRouteStoreConformance:
    def test_put_get_delete(self, route_store):
        route_store.put("s1", "10.0.0.5:8443")
        assert route_store.get("s1") == "10.0.0.5:8443"
        route_store.put("s1", "10.0.0.6:8443")  # move
        assert route_store.get("s1") == "10.0.0.6:8443"
        route_store.delete("s1")
        assert route_store.get("s1") is None

    def test_ttl_expires(self, route_store):
        route_store.put("s2", "pod:1", ttl_s=0.05)
        assert route_store.get("s2") == "pod:1"
        time.sleep(0.12)
        assert route_store.get("s2") is None

    def test_missing_is_none(self, route_store):
        assert route_store.get("never") is None
        route_store.delete("never")  # no raise
