"""DOM-level console tests (VERDICT r3 #2): parse the SPA and assert its
view wiring against the live JSON APIs, and prove the console WS path is
authenticated end-to-end — dashboard login → server-minted mgmt JWT →
facade HmacValidator accepts it (and rejects its absence).

Reference analogs: dashboard/src/app route families (view coverage),
dashboard/server.js:1-40 (server-side mgmt-JWT mint for the WS path)."""

from __future__ import annotations

import json
import os
import re
import urllib.parse
import urllib.request
from html.parser import HTMLParser

import pytest

from omnia_tpu.dashboard import DashboardServer
from omnia_tpu.operator.resources import Resource
from omnia_tpu.operator.store import MemoryResourceStore

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SPA = os.path.join(REPO, "omnia_tpu", "dashboard", "static", "index.html")

MGMT_SECRET = b"console-mgmt-secret"
DASH_TOKEN = "dash-write-token"


class _Dom(HTMLParser):
    """Minimal DOM index: ids, nav buttons (data-view), forms."""

    def __init__(self):
        super().__init__()
        self.ids: set[str] = set()
        self.views: list[str] = []

    def handle_starttag(self, tag, attrs):
        a = dict(attrs)
        if "id" in a:
            self.ids.add(a["id"])
        if tag == "button" and "data-view" in a:
            self.views.append(a["data-view"])


@pytest.fixture(scope="module")
def dom():
    html = open(SPA).read()
    p = _Dom()
    p.feed(html)
    return html, p


@pytest.fixture(scope="module")
def dash():
    store = MemoryResourceStore()
    store.apply(Resource(kind="PromptPack", name="p1", spec={"content": {
        "name": "p1", "version": "2.0.0",
        "prompts": {"system": "s"},
        "skills": ["sk1"],
        "functions": [{
            "name": "get_weather", "description": "weather lookup",
            "parameters": {"type": "object",
                           "properties": {"city": {"type": "string"}},
                           "required": ["city"]},
        }],
    }}))
    store.apply(Resource(kind="SkillSource", name="sk1", spec={
        "source": {"type": "configmap", "name": "cm"},
    }))
    store.apply(Resource(kind="MemoryPolicy", name="mp", spec={}))
    srv = DashboardServer(
        store, write_token=DASH_TOKEN, mgmt_secret=MGMT_SECRET,
    )
    port = srv.serve(host="127.0.0.1", port=0)
    yield srv, port
    srv.shutdown()


def _req(port, path, method="GET", body=None, headers=None):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", method=method,
        data=body, headers=headers or {},
    )
    try:
        with urllib.request.urlopen(req, timeout=10) as r:
            return r.status, dict(r.headers), json.loads(r.read() or b"{}")
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), json.loads(e.read() or b"{}")


# ---------------------------------------------------------------------------
# DOM wiring
# ---------------------------------------------------------------------------


def test_every_nav_view_has_section_and_loader(dom):
    """Nav button → view section → registered run() loader, for every
    route family the reference console ships."""
    html, p = dom
    loaders = set(re.findall(r'run\("([\w-]+)"', html))
    for view in p.views:
        assert f"view-{view}" in p.ids, f"nav {view!r} has no section"
        assert view in loaders, f"nav {view!r} has no loader"
    # Route-family parity floor (reference dashboard/src/app).
    required = {"agents", "console", "sessions", "costs", "quality", "arena",
                "providers", "packs", "tools", "skills", "functions",
                "workspaces", "memories", "memory-analytics", "topology",
                "settings"}
    assert required <= set(p.views), sorted(required - set(p.views))


def test_every_spa_api_path_is_served(dom, dash):
    """Every /api path the page's JS fetches must resolve on the server
    (proxied families may 503 without a backing service, never 404)."""
    html, _p = dom
    _srv, port = dash
    auth = {"Authorization": f"Bearer {DASH_TOKEN}"}
    paths = set(re.findall(r'api\(["`](/api/[\w./-]+)', html))
    paths |= set(re.findall(r'fetch\("(/api/[\w./-]+)"', html))
    assert len(paths) >= 15, sorted(paths)
    for path in sorted(paths):
        status, _h, _doc = _req(port, path, headers=auth)
        assert status != 404, f"{path} is referenced by the SPA but 404s"


def test_console_ws_requires_server_minted_token(dom):
    """The chat path must fetch /api/console-token and put it on the WS
    URL — no bare `new WebSocket(url)` without the token branch."""
    html, p = dom
    assert "consoleToken" in html
    connect_fn = html.split("async function connectChat")[1].split("\n}")[0]
    assert "consoleToken()" in connect_fn
    assert "token=" in connect_fn
    assert html.count("new WebSocket(") == 1  # only the console, tokened
    # Login affordances exist (reference auth routes).
    assert "login-form" in p.ids and "login-overlay" in p.ids


# ---------------------------------------------------------------------------
# Auth flow (login → cookie → console token → facade accepts)
# ---------------------------------------------------------------------------


def test_login_flow_and_console_token(dash):
    _srv, port = dash
    # Unauthenticated: /api/me says login required, token endpoint 401s.
    status, _h, me = _req(port, "/api/me")
    assert status == 200 and me["loginRequired"] and not me["authenticated"]
    status, _h, doc = _req(port, "/api/console-token")
    assert status == 401
    # Wrong credentials rejected.
    status, _h, _doc = _req(
        port, "/api/login", method="POST",
        body=json.dumps({"token": "nope"}).encode())
    assert status == 401
    # Right credentials → HttpOnly session cookie.
    status, headers, _doc = _req(
        port, "/api/login", method="POST",
        body=json.dumps({"token": DASH_TOKEN}).encode())
    assert status == 200
    cookie = headers.get("Set-Cookie", "")
    assert cookie.startswith("omnia_console=") and "HttpOnly" in cookie
    session = cookie.split(";")[0]
    # Cookie authenticates /api/me and the token mint.
    status, _h, me = _req(port, "/api/me", headers={"Cookie": session})
    assert status == 200 and me["authenticated"]
    status, _h, doc = _req(
        port, "/api/console-token", headers={"Cookie": session})
    assert status == 200 and doc["token"].count(".") == 2
    # The minted token is a real mgmt-plane credential: the facade's own
    # validator accepts it (audience "mgmt"), same as any in-cluster JWT.
    from omnia_tpu.facade.auth import HmacValidator

    principal = HmacValidator(MGMT_SECRET).validate(doc["token"])
    assert principal is not None and principal.subject == "console-user"
    assert principal.claims["aud"] == "mgmt"
    # Wrong-secret facade rejects it; expiry is short.
    assert HmacValidator(b"other").validate(doc["token"]) is None
    assert doc["expires_in_s"] <= 600


def test_cookie_secure_flag_opt_in():
    """OMNIA_COOKIE_SECURE=1 (TLS-terminating ingress) marks the session
    cookie Secure so it never rides a plaintext path; default posture
    (in-cluster plain HTTP) leaves it off."""
    store = MemoryResourceStore()
    srv = DashboardServer(store, write_token=DASH_TOKEN,
                          cookie_secure=True)
    port = srv.serve(host="127.0.0.1", port=0)
    try:
        _status, headers, _doc = _req(
            port, "/api/login", method="POST",
            body=json.dumps({"token": DASH_TOKEN}).encode())
        assert "Secure" in headers.get("Set-Cookie", "")
    finally:
        srv.shutdown()
    assert DashboardServer(store, write_token=DASH_TOKEN).cookie_secure is False


def test_data_routes_gated_when_login_required(dash):
    """'Login required' is server-enforced: every data route 401s without
    a credential, not just the token mint."""
    _srv, port = dash
    for path in ("/api/agents", "/api/settings", "/api/resources",
                 "/api/skills", "/api/sessions"):
        status, _h, doc = _req(port, path)
        assert status == 401, (path, status, doc)
    # /api/me and the SPA itself stay reachable (login page must load).
    assert _req(port, "/api/me")[0] == 200


def test_session_cookie_is_not_a_facade_token(dash):
    """The 12 h console cookie must be useless at a facade: it is signed
    with a DERIVED key (not raw mgmt_secret) and carries aud=console —
    either alone defeats replaying it as a WS ?token=."""
    from omnia_tpu.facade.auth import HmacValidator

    _srv, port = dash
    _s, headers, _d = _req(port, "/api/login", method="POST",
                           body=json.dumps({"token": DASH_TOKEN}).encode())
    cookie_jwt = headers["Set-Cookie"].split(";")[0].split("=", 1)[1]
    # Raw-secret validator (worst-case facade config): signature fails.
    assert HmacValidator(MGMT_SECRET).validate(cookie_jwt) is None
    # Audience-pinned validator (cli.py facade assembly): also fails.
    assert HmacValidator(MGMT_SECRET, audience="mgmt").validate(cookie_jwt) is None


def test_logout_expires_cookie_server_side(dash):
    _srv, port = dash
    _s, headers, _d = _req(port, "/api/login", method="POST",
                           body=json.dumps({"token": DASH_TOKEN}).encode())
    session = headers["Set-Cookie"].split(";")[0]
    status, headers, doc = _req(port, "/api/logout", method="POST",
                                headers={"Cookie": session})
    assert status == 200 and not doc["authenticated"]
    assert "Max-Age=0" in headers.get("Set-Cookie", "")


def test_login_handler_rejects_malformed_bodies(dash):
    _srv, port = dash
    for body in (b'"abc"', b'{"token": 5}', b"{bad json",
                 '{"token": "päss"}'.encode()):
        status, _h, _doc = _req(port, "/api/login", method="POST", body=body)
        assert status in (400, 401), (body, status)


def test_mgmt_secret_without_dashboard_token_stays_locked():
    """A mgmt secret alone must not leave the mint (or anything) open:
    auth is required but no credential can satisfy it."""
    srv = DashboardServer(MemoryResourceStore(), write_token=None,
                          mgmt_secret=b"only-mgmt")
    port = srv.serve(host="127.0.0.1", port=0)
    try:
        status, _h, me = _req(port, "/api/me")
        assert status == 200 and me["loginRequired"]
        assert _req(port, "/api/console-token")[0] == 401
        assert _req(port, "/api/agents")[0] == 401
        status, _h, doc = _req(port, "/api/login", method="POST",
                               body=json.dumps({"token": "x"}).encode())
        assert status == 403 and "OMNIA_DASHBOARD_TOKEN" in doc["error"]
    finally:
        srv.shutdown()


def test_console_token_disabled_without_mgmt_secret():
    srv = DashboardServer(MemoryResourceStore(), write_token=None,
                          mgmt_secret=None)
    port = srv.serve(host="127.0.0.1", port=0)
    try:
        status, _h, me = _req(port, "/api/me")
        assert status == 200 and not me["loginRequired"]  # dev mode: open
        status, _h, doc = _req(port, "/api/console-token")
        assert status == 503  # honest: minting unconfigured, never a fake
        assert "OMNIA_MGMT_SECRET" in doc["error"]
    finally:
        srv.shutdown()


def test_console_token_endpoint_gets_no_cors_grant(dash):
    """The minted WS credential must not be readable cross-origin."""
    _srv, port = dash
    status, headers, _doc = _req(port, "/api/login", method="POST",
                                 body=json.dumps({"token": DASH_TOKEN}).encode())
    session = headers["Set-Cookie"].split(";")[0]
    status, headers, _doc = _req(
        port, "/api/console-token", headers={"Cookie": session})
    assert status == 200
    assert "Access-Control-Allow-Origin" not in headers
    status, headers, _doc = _req(port, "/api/agents",
                                 headers={"Cookie": session})
    assert headers.get("Access-Control-Allow-Origin") == "*"


def test_authenticated_ws_end_to_end(dash):
    """Full path: dashboard-minted token → live facade WS with an HMAC
    auth chain → accepted; the same connect without a token closes 4401.
    This is the 'no unauthenticated WS path from the console' proof."""
    websockets = pytest.importorskip("websockets.sync.client")
    from omnia_tpu.facade.auth import AuthChain, HmacValidator
    from omnia_tpu.facade.server import FacadeServer
    from omnia_tpu.runtime.packs import load_pack
    from omnia_tpu.runtime.providers import ProviderRegistry, ProviderSpec
    from omnia_tpu.runtime.server import RuntimeServer

    _srv, port = dash
    status, headers, _doc = _req(port, "/api/login", method="POST",
                                 body=json.dumps({"token": DASH_TOKEN}).encode())
    session = headers["Set-Cookie"].split(";")[0]
    _s, _h, doc = _req(port, "/api/console-token",
                       headers={"Cookie": session})
    token = doc["token"]

    registry = ProviderRegistry()
    registry.register(ProviderSpec(
        name="main", type="mock",
        options={"scenarios": [{"pattern": ".", "reply": "hi"}]},
    ))
    runtime = RuntimeServer(
        pack=load_pack({"name": "a", "version": "1.0.0",
                        "prompts": {"system": "s"},
                        "sampling": {"max_tokens": 16}}),
        providers=registry, provider_name="main",
    )
    rport = runtime.serve("localhost:0")
    facade = FacadeServer(
        runtime_target=f"localhost:{rport}", agent_name="console-e2e",
        auth_chain=AuthChain([HmacValidator(MGMT_SECRET)]),
    )
    fport = facade.serve()
    try:
        with websockets.connect(
            f"ws://localhost:{fport}/ws?token={token}", open_timeout=10,
        ) as ws:
            first = json.loads(ws.recv(timeout=10))
            assert first["type"] == "connected"
        with pytest.raises(Exception) as exc:
            with websockets.connect(
                f"ws://localhost:{fport}/ws", open_timeout=10,
            ) as ws:
                ws.recv(timeout=10)
        assert "4401" in str(exc.value)
    finally:
        facade.shutdown()
        runtime.shutdown()


# ---------------------------------------------------------------------------
# New route families' content
# ---------------------------------------------------------------------------


def test_skills_functions_settings_payloads(dash):
    srv, port = dash
    auth = {"Authorization": f"Bearer {DASH_TOKEN}"}
    _s, _h, doc = _req(port, "/api/skills", headers=auth)
    [skill] = doc["skills"]
    assert skill["name"] == "sk1" and skill["consumers"] == ["p1"]
    _s, _h, doc = _req(port, "/api/functions", headers=auth)
    [fn] = doc["functions"]
    assert fn["name"] == "get_weather" and fn["pack"] == "p1"
    assert fn["parameters"] == ["city"] and fn["required"] == ["city"]
    _s, _h, doc = _req(port, "/api/settings", headers=auth)
    assert doc["auth"] == {"loginRequired": True, "writesEnabled": True,
                           "consoleTokenMinting": True}
    assert {"name": "mp", "namespace": "default", "phase": ""} in (
        doc["policies"]["MemoryPolicy"])
    _s, _h, doc = _req(port, "/api/memory-analytics?workspace=w1",
                       headers=auth)
    assert doc["workspace"] == "w1" and doc["available"] is False


def test_pod_facades_validate_console_tokens(monkeypatch):
    """The cluster side of 'no unauthenticated WS path': with a mgmt
    secret configured, controller-started pods build an audience-pinned
    HMAC chain (in-process backend) and the rendered K8s manifest stamps
    OMNIA_MGMT_SECRET (secretKeyRef) and the OTLP endpoint onto both
    containers."""
    from omnia_tpu.operator.deployment import (
        InProcessPodBackend,
        K8sManifestBackend,
    )

    monkeypatch.setenv("OMNIA_MGMT_SECRET", "pod-secret")
    monkeypatch.setenv("OMNIA_OTLP_ENDPOINT", "http://tempo:4318")
    backend = InProcessPodBackend()
    chain = backend._auth_chain()
    assert chain is not None
    from omnia_tpu.facade.auth import HmacValidator

    good = HmacValidator.mint(b"pod-secret", "console-user", audience="mgmt")
    bad_aud = HmacValidator.mint(b"pod-secret", "console-user",
                                 audience="console")
    assert chain.authenticate(good) is not None
    assert chain.authenticate(bad_aud) is None  # cookie-shaped JWT refused
    monkeypatch.delenv("OMNIA_MGMT_SECRET")
    assert InProcessPodBackend()._auth_chain() is None  # dev: open as before
    monkeypatch.setenv("OMNIA_MGMT_SECRET", "pod-secret")

    class _Dep:
        name = "a"
        namespace = "default"
        default_provider = "main"
        session_api_url = ""
        stable_hash = "h"
        replicas = 1

        class resource:
            spec = {}

        def config_hash(self):
            return "h"

    manifest = K8sManifestBackend().render(_Dep())["deployment"]
    for c in manifest["spec"]["template"]["spec"]["containers"]:
        refs = [e for e in c["env"] if e["name"] == "OMNIA_MGMT_SECRET"]
        assert refs and refs[0]["valueFrom"]["secretKeyRef"]["name"] == "omnia-mgmt"
        # Trace export propagates operator env -> agent pods.
        otlp = [e for e in c["env"] if e["name"] == "OMNIA_OTLP_ENDPOINT"]
        assert otlp and otlp[0]["value"] == "http://tempo:4318"


def test_console_ws_proxy_end_to_end(tmp_path):
    """Reference dashboard/server.js parity: chat frames flow browser →
    dashboard WS proxy → facade; the cookie rides the upgrade, the mgmt
    JWT is minted server-side and NEVER reaches the client; unknown
    targets and missing sessions are refused."""
    import websockets.sync.client as wsc

    from omnia_tpu.facade.auth import AuthChain, HmacValidator
    from omnia_tpu.facade.server import FacadeServer
    from omnia_tpu.operator.store import MemoryResourceStore
    from omnia_tpu.runtime.packs import load_pack
    from omnia_tpu.runtime.providers import ProviderRegistry, ProviderSpec
    from omnia_tpu.runtime.server import RuntimeServer

    registry = ProviderRegistry()
    registry.register(ProviderSpec(
        name="main", type="mock",
        options={"scenarios": [{"pattern": ".", "reply": "proxied hi"}]},
    ))
    runtime = RuntimeServer(
        pack=load_pack({"name": "a", "version": "1.0.0",
                        "prompts": {"system": "s"},
                        "sampling": {"max_tokens": 16}}),
        providers=registry, provider_name="main",
    )
    rport = runtime.serve("localhost:0")
    facade = FacadeServer(
        runtime_target=f"localhost:{rport}", agent_name="proxy-e2e",
        auth_chain=AuthChain([HmacValidator(MGMT_SECRET, audience="mgmt")]),
    )
    fport = facade.serve()
    endpoint = f"ws://localhost:{fport}/ws"

    store = MemoryResourceStore()
    agent = store.apply(Resource(kind="AgentRuntime", name="proxy-agent", spec={
        "mode": "agent", "promptPackRef": {"name": "p"},
        "providers": [{"name": "m", "providerRef": {"name": "x"}}],
    }))
    store.update_status(agent, {"endpoints": [{"url": endpoint}]})
    srv = DashboardServer(store, write_token=DASH_TOKEN,
                          mgmt_secret=MGMT_SECRET)
    port = srv.serve(host="127.0.0.1", port=0)
    try:
        assert srv.ws_proxy_port
        proxy = (f"ws://127.0.0.1:{srv.ws_proxy_port}/proxy?url="
                 + json.dumps(endpoint)[1:-1])
        # 1. No cookie → 4401 at the proxy; the facade is never dialed.
        with pytest.raises(Exception) as exc:
            with wsc.connect(proxy, open_timeout=10) as ws:
                ws.recv(timeout=5)
        assert "4401" in str(exc.value)
        # 2. Login, then chat THROUGH the proxy with only the cookie.
        _s, headers, _d = _req(port, "/api/login", method="POST",
                               body=json.dumps({"token": DASH_TOKEN}).encode())
        cookie = headers["Set-Cookie"].split(";")[0]
        with wsc.connect(proxy, open_timeout=15,
                         additional_headers={"Cookie": cookie}) as ws:
            first = json.loads(ws.recv(timeout=15))
            assert first["type"] == "connected"
            ws.send(json.dumps({"type": "message", "content": "hello"}))
            text, done = "", None
            while done is None:
                m = json.loads(ws.recv(timeout=30))
                if m["type"] == "chunk":
                    text += m["text"]
                if m["type"] in ("done", "error"):
                    done = m
            assert done["type"] == "done" and text == "proxied hi"
        # 3. A client-smuggled query string on the target is STRIPPED:
        # `?token=garbage` must not ride ahead of the server-minted
        # token (pre-fix it did, and the facade read the garbage one).
        smuggle = (f"ws://127.0.0.1:{srv.ws_proxy_port}/proxy?url="
                   + urllib.parse.quote(endpoint + "?token=garbage", safe=""))
        with wsc.connect(smuggle, open_timeout=15,
                         additional_headers={"Cookie": cookie}) as ws:
            first = json.loads(ws.recv(timeout=15))
            assert first["type"] == "connected"
        # 4. Unknown target → 4403 (the proxy is not an open relay).
        bad = (f"ws://127.0.0.1:{srv.ws_proxy_port}/proxy?url="
               "ws%3A%2F%2Fevil.example%2Fws")
        with pytest.raises(Exception) as exc:
            with wsc.connect(bad, open_timeout=10,
                             additional_headers={"Cookie": cookie}) as ws:
                ws.recv(timeout=5)
        assert "4403" in str(exc.value)
    finally:
        srv.shutdown()
        facade.shutdown()
        runtime.shutdown()
