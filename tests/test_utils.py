"""Unit tests: auth chain, rate limiter, metrics registry."""

import time

from omnia_tpu.facade.auth import (
    AllowAll,
    AuthChain,
    ClientKeyValidator,
    HmacValidator,
    SharedTokenValidator,
)
from omnia_tpu.utils.metrics import Registry
from omnia_tpu.utils.ratelimit import KeyedLimiter


class TestAuth:
    def test_client_key(self):
        v = ClientKeyValidator({"web": "s3cret"})
        assert v.validate("s3cret").subject == "web"
        assert v.validate("wrong") is None
        assert v.validate("") is None

    def test_shared_token(self):
        v = SharedTokenValidator("tok", subject="doctor")
        assert v.validate("tok").subject == "doctor"
        assert v.validate("nope") is None

    def test_hmac_jwt_roundtrip(self):
        secret = b"k"
        tok = HmacValidator.mint(secret, "dash", audience="mgmt", ttl_s=60)
        v = HmacValidator(secret, audience="mgmt")
        p = v.validate(tok)
        assert p.subject == "dash" and p.method == "hmac_jwt"

    def test_hmac_jwt_wrong_audience(self):
        tok = HmacValidator.mint(b"k", "dash", audience="other")
        assert HmacValidator(b"k", audience="mgmt").validate(tok) is None

    def test_hmac_jwt_expired(self):
        tok = HmacValidator.mint(b"k", "dash", ttl_s=-10)
        assert HmacValidator(b"k").validate(tok) is None

    def test_hmac_jwt_tampered(self):
        tok = HmacValidator.mint(b"k", "dash")
        head, payload, sig = tok.split(".")
        assert HmacValidator(b"k").validate(f"{head}.{payload}x.{sig}") is None
        assert HmacValidator(b"other").validate(tok) is None

    def test_chain_order_and_fail_closed(self):
        chain = AuthChain([ClientKeyValidator({"a": "ka"})])
        assert chain.authenticate("ka").method == "client_key"
        assert chain.authenticate("nope") is None
        assert AuthChain([]).authenticate("anything") is None
        assert AuthChain([AllowAll()]).authenticate("").method == "anonymous"


class TestRateLimit:
    def test_burst_then_block(self):
        lim = KeyedLimiter(rate=0.0001, burst=3)
        assert all(lim.allow("k") for _ in range(3))
        assert not lim.allow("k")
        assert lim.allow("other")  # independent key

    def test_refill(self):
        lim = KeyedLimiter(rate=50, burst=1)
        assert lim.allow("k")
        assert not lim.allow("k")
        time.sleep(0.05)
        assert lim.allow("k")


class TestMetrics:
    def test_counter_labels(self):
        r = Registry("t")
        c = r.counter("reqs")
        c.inc()
        c.inc(2, code="500")
        out = r.expose()
        assert "t_reqs 1.0" in out
        assert 't_reqs{code="500"} 2.0' in out

    def test_gauge_fn(self):
        r = Registry("t")
        r.gauge("depth", fn=lambda: 7)
        assert "t_depth 7" in r.expose()

    def test_histogram_buckets_and_quantile(self):
        r = Registry("t")
        h = r.histogram("lat", buckets=(0.1, 1.0, 10.0))
        for v in (0.05, 0.05, 0.5, 2.0):
            h.observe(v)
        assert h.count == 4
        assert h.quantile(0.5) == 0.1
        out = r.expose()
        assert 't_lat_bucket{le="+Inf"} 4' in out
        assert "t_lat_count 4" in out

    def test_same_metric_returned(self):
        r = Registry("t")
        assert r.counter("x") is r.counter("x")
