"""Postgres tier tests: wire protocol, and warm-store conformance.

The conformance class runs the SAME assertions against the SQLite
WarmStore and the PG-backed PgWarmStore — the latter through the real
wire protocol against the in-tree PG server (reference analog:
testcontainers-postgres in provider_test.go). Set OMNIA_TEST_PG_DSN
(host:port/user/db[/password]) to additionally run against a real
Postgres."""

import os
import threading
import time

import pytest

from omnia_tpu.pg import PGClient, PGError, PGServer
from omnia_tpu.pg.client import PGUnavailable, bind, quote_literal
from omnia_tpu.session.pg_warm import PgWarmStore
from omnia_tpu.session.records import (
    EvalResultRecord,
    MessageRecord,
    ProviderCallRecord,
    SessionRecord,
)
from omnia_tpu.session.tiers import TieredStore, demote_bundle
from omnia_tpu.session.warm import WarmStore


@pytest.fixture(scope="module")
def server():
    srv = PGServer().start()
    yield srv
    srv.stop()


# ---------------------------------------------------------------------------
# protocol / client
# ---------------------------------------------------------------------------


class TestProtocol:
    def test_quote_literal_escaping(self):
        assert quote_literal(None) == "NULL"
        assert quote_literal(True) == "TRUE"
        assert quote_literal(7) == "7"
        assert quote_literal(1.5) == "1.5"
        assert quote_literal("it's") == "E'it''s'"
        assert quote_literal("a\\b") == "E'a\\\\b'"
        assert quote_literal({"k": 1}) == "E'{\"k\": 1}'"
        with pytest.raises(PGError):
            quote_literal("bad\x00byte")

    def test_bind_positional_no_shadowing(self):
        sql = bind("SELECT $1, $2, $10", ["a"] * 10)
        assert "$" not in sql

    def test_injection_via_param_is_inert(self, server):
        c = PGClient(*server.address)
        c.execute("CREATE TABLE IF NOT EXISTS inj (id TEXT)")
        evil = "x'; DROP TABLE inj; --"
        c.execute("INSERT INTO inj VALUES ($1)", [evil])
        rows = c.query("SELECT id FROM inj WHERE id=$1", [evil])
        assert rows == [{"id": evil}]
        assert c.query("SELECT COUNT(*) AS n FROM inj")[0]["n"] == "1"
        c.close()

    def test_error_then_connection_still_usable(self, server):
        c = PGClient(*server.address)
        with pytest.raises(PGError):
            c.query("SELECT FROM FROM")
        assert c.ping()
        c.close()

    def test_unreachable_maps_to_unavailable(self):
        c = PGClient("127.0.0.1", 1, timeout_s=0.2)
        with pytest.raises(PGUnavailable):
            c.query("SELECT 1")

    def test_concurrent_clients(self, server):
        boot = PGClient(*server.address)
        boot.execute("CREATE TABLE IF NOT EXISTS ctr (k TEXT PRIMARY KEY, n BIGINT)")
        boot.execute("INSERT INTO ctr VALUES ('c', 0)"
                     " ON CONFLICT(k) DO UPDATE SET n=0")
        errs = []

        def worker():
            try:
                c = PGClient(*server.address)
                for _ in range(25):
                    c.execute("UPDATE ctr SET n = n + 1 WHERE k='c'")
                c.close()
            except Exception as e:  # pragma: no cover
                errs.append(e)

        threads = [threading.Thread(target=worker) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errs
        assert boot.query("SELECT n FROM ctr")[0]["n"] == "100"
        boot.close()


# ---------------------------------------------------------------------------
# warm-store conformance: sqlite AND postgres run the same suite
# ---------------------------------------------------------------------------


def _pg_params():
    out = [("pg-double", None)]
    dsn = os.environ.get("OMNIA_TEST_PG_DSN")
    if dsn:
        out.append(("pg-real", dsn))
    return out


@pytest.fixture(params=["sqlite"] + [p[0] for p in _pg_params()])
def make_warm(request, server):
    if request.param == "sqlite":
        yield lambda: WarmStore()
        return
    if request.param == "pg-double":
        counter = [0]

        def make():
            # Fresh tables per store: separate schema via table prefix is
            # overkill for the double — wipe instead.
            c = PGClient(*server.address)
            for t in ("sessions", "records", "provider_usage"):
                c.execute(f"DROP TABLE IF EXISTS {t}")
            return PgWarmStore(c)

        yield make
        return
    # real postgres: host:port/user/db[/password]
    dsn = os.environ["OMNIA_TEST_PG_DSN"]
    hostport, user, db, *pw = dsn.split("/")
    host, _, port = hostport.partition(":")

    def make_real():
        c = PGClient(host, int(port or 5432), user=user, database=db,
                     password=pw[0] if pw else None)
        for t in ("sessions", "records", "provider_usage"):
            c.execute(f"DROP TABLE IF EXISTS {t}")
        return PgWarmStore(c)

    yield make_real


class TestWarmConformance:
    def test_session_round_trip(self, make_warm):
        warm = make_warm()
        rec = SessionRecord(session_id="w1", workspace="acme", agent="bot",
                            attrs={"k": "v", "n": 3})
        warm.ensure_session(rec)
        got = warm.get_session("w1")
        assert got.workspace == "acme" and got.attrs == {"k": "v", "n": 3}
        assert got.tier == "warm"
        assert warm.get_session("nope") is None
        assert [s.session_id for s in warm.list_sessions(workspace="acme")] == ["w1"]
        assert warm.delete_session("w1")
        assert not warm.delete_session("w1")

    def test_ensure_is_upsert(self, make_warm):
        warm = make_warm()
        warm.ensure_session(SessionRecord(session_id="u1", updated_at=100.0))
        warm.ensure_session(SessionRecord(session_id="u1", updated_at=200.0))
        assert warm.get_session("u1").updated_at == 200.0
        assert len(warm.list_sessions()) == 1

    def test_records_round_trip_ordered(self, make_warm):
        warm = make_warm()
        warm.ensure_session(SessionRecord(session_id="r1"))
        for i in range(3):
            warm.append_message(MessageRecord(
                session_id="r1", role="user", content=f"m{i}",
                created_at=1000.0 + i))
        warm.append_eval_result(EvalResultRecord(
            session_id="r1", eval_name="q", score=0.5, passed=True))
        msgs = warm.messages("r1")
        assert [m.content for m in msgs] == ["m0", "m1", "m2"]
        assert warm.eval_results("r1")[0].eval_name == "q"
        allr = warm.all_records("r1")
        assert len(allr["message"]) == 3 and len(allr["eval_result"]) == 1

    def test_usage_aggregates_and_dedupes(self, make_warm):
        warm = make_warm()
        warm.ensure_session(SessionRecord(session_id="s-u", workspace="w1"))
        pc = ProviderCallRecord(
            session_id="s-u", provider="tpu", model="llama",
            input_tokens=100, output_tokens=50, cost_usd=0.25)
        warm.append_provider_call(pc)
        warm.append_provider_call(pc)  # at-least-once redelivery
        u = warm.usage("w1")
        assert u["input_tokens"] == 100 and u["output_tokens"] == 50
        assert u["calls"] == 1 and abs(u["cost_usd"] - 0.25) < 1e-9
        assert warm.usage("other")["calls"] == 0

    def test_sessions_older_than(self, make_warm):
        warm = make_warm()
        warm.ensure_session(SessionRecord(session_id="old", updated_at=100.0))
        warm.ensure_session(SessionRecord(session_id="new", updated_at=5e9))
        olds = warm.sessions_older_than(1000.0)
        assert [s.session_id for s in olds] == ["old"]

    def test_tiered_demotion_and_readthrough(self, make_warm):
        warm = make_warm()
        ts = TieredStore(warm=warm)
        ts.ensure_session(SessionRecord(session_id="tier-1"))
        ts.append_message(MessageRecord(session_id="tier-1", role="user",
                                        content="hot msg"))
        bundles = ts.hot.pop_idle(idle_s=0, now=time.time() + 60)
        demote_bundle(warm, bundles[0])
        assert [m.content for m in ts.messages("tier-1")] == ["hot msg"]
        assert ts.get_session("tier-1") is not None


class TestBindRegression:
    def test_param_value_containing_placeholder_stays_inert(self, server):
        """A parameter VALUE containing '$1' must never be re-expanded
        inside another parameter's quotes (injection regression)."""
        c = PGClient(*server.address)
        c.execute("DROP TABLE IF EXISTS bindreg")
        c.execute("CREATE TABLE bindreg (a TEXT, b TEXT)")
        sneaky = "user text mentioning $1 and $2 here"
        c.execute("INSERT INTO bindreg VALUES ($1, $2)", ["rid-1", sneaky])
        rows = c.query("SELECT a, b FROM bindreg")
        assert rows == [{"a": "rid-1", "b": sneaky}]
        with pytest.raises(PGError, match="no parameter"):
            bind("SELECT $1, $2", ["only-one"])
        c.close()
