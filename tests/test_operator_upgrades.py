"""Operator upgrades: metric-gated rollout analysis, HPA/KEDA object
rendering, and per-service-group workspace data planes (reference
rollout_analysis.go, autoscaling.go:74/:204, workspace_services.go)."""

import json
import time
import urllib.request

import pytest

from omnia_tpu.operator.analysis import AnalysisRunner
from omnia_tpu.operator.controller import ControllerManager
from omnia_tpu.operator.deployment import AgentDeployment, K8sManifestBackend
from omnia_tpu.operator.manifest_lint import lint
from omnia_tpu.operator.resources import Resource
from omnia_tpu.operator.rollout import RolloutPhase
from omnia_tpu.operator.store import MemoryResourceStore
from omnia_tpu.operator.workspace import render_workspace_manifests

PACK = {
    "name": "up-agent", "version": "1.0.0",
    "prompts": {"system": "s"},
    "sampling": {"temperature": 0.0, "max_tokens": 32},
}


def _apply_agent(store, rollout=None, scenarios=None):
    store.apply(Resource(kind="Provider", name="p", spec={
        "type": "mock", "role": "llm",
        "options": {"scenarios": scenarios or [{"pattern": ".", "reply": "ok"}]}}))
    store.apply(Resource(kind="PromptPack", name="pk", spec={"content": PACK}))
    spec = {
        "mode": "agent",
        "promptPackRef": {"name": "pk"},
        "providers": [{"name": "main", "providerRef": {"name": "p"}}],
        "replicas": 1,
    }
    if rollout:
        spec["rollout"] = rollout
    store.apply(Resource(kind="AgentRuntime", name="up-agent", spec=spec))


class TestRolloutAnalysis:
    def _chat(self, endpoint, text):
        from websockets.sync.client import connect

        with connect(endpoint) as ws:
            json.loads(ws.recv(timeout=10))
            ws.send(json.dumps({"type": "message", "content": text}))
            while True:
                m = json.loads(ws.recv(timeout=30))
                if m["type"] in ("done", "error"):
                    return m

    def test_unhealthy_metrics_roll_back(self):
        """Candidate whose turns error past maxErrorRate must roll back,
        not promote — evaluated from the candidate pods' real metrics."""
        store = MemoryResourceStore()
        mgr = ControllerManager(store)
        try:
            store.apply(Resource(kind="RolloutAnalysis", name="ra", spec={
                "minSamples": 1,
                "metrics": [{"name": "error-rate", "maxErrorRate": 0.2}],
            }))
            _apply_agent(store, rollout={
                "steps": [{"weight": 50, "pause_s": 0.05}],
                "analysis": {"name": "ra"},
            }, scenarios=[
                {"pattern": "boom", "error": "simulated provider failure"},
                {"pattern": ".", "reply": "ok"},
            ])
            mgr.drain_queue()
            dep = next(iter(mgr.deployments.values()))

            # Trigger a canary: config change spawns a candidate track.
            res = store.get("default", "AgentRuntime", "up-agent")
            res.spec["context"] = {"ttl_s": 123}
            store.apply(res)
            mgr.drain_queue()
            st = mgr.rollouts.state(dep)
            assert st.phase == RolloutPhase.PROGRESSING
            # Drive ERROR turns through the candidate (the mock provider's
            # error scenario streams an error final).
            cand = dep.candidate_pods[0]
            for _ in range(3):
                out = self._chat(cand.endpoint, "boom")
                assert out["type"] == "error", out
            time.sleep(0.1)  # step pause elapses
            mgr.resync()
            st = mgr.rollouts.state(dep)
            assert st.phase == RolloutPhase.ROLLED_BACK, st.to_status()
            results = mgr.analysis.last_results[dep.resource.key]
            er = next(r for r in results if r["name"] == "error-rate")
            assert er["passed"] is False and er["observed"] == 1.0
        finally:
            mgr.shutdown()

    def test_healthy_metrics_promote(self):
        store = MemoryResourceStore()
        mgr = ControllerManager(store)
        try:
            store.apply(Resource(kind="RolloutAnalysis", name="ra", spec={
                "minSamples": 1,
                "metrics": [{"name": "error-rate", "maxErrorRate": 0.2},
                            {"name": "p95-latency", "maxP95LatencyS": 30.0}],
            }))
            _apply_agent(store, rollout={
                "steps": [{"weight": 50, "pause_s": 0.05}],
                "analysis": {"name": "ra"},
            })
            mgr.drain_queue()
            dep = next(iter(mgr.deployments.values()))
            res = store.get("default", "AgentRuntime", "up-agent")
            res.spec["context"] = {"ttl_s": 456}
            store.apply(res)
            mgr.drain_queue()
            cand = dep.candidate_pods[0]
            assert self._chat(cand.endpoint, "hello")["type"] == "done"
            time.sleep(0.1)
            mgr.resync()
            assert mgr.rollouts.state(dep).phase == RolloutPhase.PROMOTED
        finally:
            mgr.shutdown()

    def test_missing_analysis_ref_fails_closed(self):
        store = MemoryResourceStore()
        mgr = ControllerManager(store)
        try:
            _apply_agent(store, rollout={
                "steps": [{"weight": 50, "pause_s": 0.05}],
                "analysis": {"name": "ghost"},
            })
            mgr.drain_queue()
            dep = next(iter(mgr.deployments.values()))
            res = store.get("default", "AgentRuntime", "up-agent")
            res.spec["context"] = {"ttl_s": 9}
            store.apply(res)
            mgr.drain_queue()
            time.sleep(0.1)
            mgr.resync()
            assert mgr.rollouts.state(dep).phase == RolloutPhase.ROLLED_BACK
        finally:
            mgr.shutdown()


class TestAutoscalingManifests:
    def _dep(self, autoscaling):
        res = Resource(kind="AgentRuntime", name="scaler", spec={
            "promptPackRef": {"name": "pk"},
            "providers": [{"providerRef": {"name": "p"}}],
            "autoscaling": autoscaling,
        })
        return AgentDeployment(
            res, pack_doc=PACK, provider_specs=[{"name": "p", "type": "mock"}],
            default_provider="p")

    def test_scale_to_zero_renders_keda(self):
        out = K8sManifestBackend().render(self._dep({
            "minReplicas": 0, "maxReplicas": 8, "scaleToZero": True,
            "queueDepthTarget": 4}))
        so = out["autoscaling"]
        assert so["kind"] == "ScaledObject"
        assert so["spec"]["minReplicaCount"] == 0
        trig = so["spec"]["triggers"][0]
        assert trig["type"] == "prometheus"
        assert "queue_depth" in trig["metadata"]["query"]
        assert trig["metadata"]["threshold"] == "4"
        assert lint([out["deployment"], out["service"], so]) == []

    def test_plain_hpa_otherwise(self):
        out = K8sManifestBackend().render(self._dep({
            "minReplicas": 2, "maxReplicas": 6}))
        hpa = out["autoscaling"]
        assert hpa["kind"] == "HorizontalPodAutoscaler"
        assert hpa["spec"]["minReplicas"] == 2
        assert hpa["spec"]["metrics"][0]["pods"]["metric"]["name"] == \
            "omnia_runtime_queue_depth"

    def test_no_autoscaling_no_object(self):
        out = K8sManifestBackend().render(self._dep(None))
        assert "autoscaling" not in out


class TestWorkspaceServiceGroups:
    def test_in_process_groups_serve_real_apis(self):
        store = MemoryResourceStore()
        mgr = ControllerManager(store)
        try:
            store.apply(Resource(kind="Workspace", name="team-a", spec={
                "environment": "dev",
                "services": [
                    {"name": "core", "sessionApi": True, "memoryApi": True},
                    {"name": "batch", "sessionApi": True},
                ],
            }))
            mgr.drain_queue()
            res = store.get("default", "Workspace", "team-a")
            assert res.status["phase"] == "Ready"
            groups = {g["group"]: g for g in res.status["serviceGroups"]}
            assert set(groups) == {"core", "batch"}
            assert "memoryApi" in groups["core"] and "memoryApi" not in groups["batch"]
            # The endpoints are LIVE services.
            body = json.dumps({"session_id": "ws-grp"}).encode()
            req = urllib.request.Request(
                groups["core"]["sessionApi"] + "/api/v1/sessions", data=body,
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=10) as r:
                assert r.status == 200
            # Group isolation: the other group has no such session.
            with urllib.request.urlopen(
                groups["batch"]["sessionApi"] + "/api/v1/sessions", timeout=10
            ) as r:
                assert json.loads(r.read())["sessions"] == []
            # Removing a group converges: its service stops.
            res.spec["services"] = [{"name": "core", "sessionApi": True,
                                     "memoryApi": True}]
            store.apply(res)
            mgr.drain_queue()
            res = store.get("default", "Workspace", "team-a")
            assert [g["group"] for g in res.status["serviceGroups"]] == ["core"]
        finally:
            mgr.shutdown()

    def test_rendered_manifests_lint_clean(self):
        res = Resource(kind="Workspace", name="team-b", spec={
            "environment": "prod",
            "roleBindings": [{"role": "admin", "users": ["alice"]}],
            "services": [{"name": "core", "sessionApi": True, "memoryApi": True}],
        })
        manifests = render_workspace_manifests(res)
        assert lint(manifests) == [], lint(manifests)
        kinds = [m["kind"] for m in manifests]
        assert kinds.count("Deployment") == 2 and kinds.count("Service") == 2
        assert "NetworkPolicy" in kinds and "RoleBinding" in kinds
        netpol = next(m for m in manifests if m["kind"] == "NetworkPolicy")
        assert netpol["spec"]["policyTypes"] == ["Ingress"]
