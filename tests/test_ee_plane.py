"""EE plane tests: license activation/gating, store-resident EE kinds
reconciled by the operator (ArenaJob end-to-end with a worker, ToolPolicy
→ shared evaluator, SessionPrivacyPolicy/RolloutAnalysis), operator REST
(tool-test, content CRUD, authz, mgmt tokens, license endpoints), and the
mgmt-plane token fetcher."""

import http.server
import json
import threading
import time
import urllib.request

import pytest
from cryptography.hazmat.primitives.asymmetric import rsa
from cryptography.hazmat.primitives import serialization

from omnia_tpu.license import (
    CommunityLicenseManager,
    EE_FEATURES,
    LicenseError,
    LicenseManager,
    sign_license,
)
from omnia_tpu.operator.api import ContentStore, OperatorAPI
from omnia_tpu.operator.controller import ControllerManager
from omnia_tpu.operator.resources import Resource
from omnia_tpu.operator.store import MemoryResourceStore
from omnia_tpu.operator.validation import ValidationError


@pytest.fixture(scope="module")
def vendor_key():
    priv = rsa.generate_private_key(public_exponent=65537, key_size=2048)
    pub_pem = priv.public_key().public_bytes(
        serialization.Encoding.PEM, serialization.PublicFormat.SubjectPublicKeyInfo
    )
    return priv, pub_pem


class TestLicense:
    def test_activate_and_gate(self, vendor_key):
        priv, pub = vendor_key
        mgr = LicenseManager(pub)
        assert not mgr.licensed("arena")
        with pytest.raises(LicenseError):
            mgr.require("arena")
        key = sign_license(priv, customer="acme", features=["arena"])
        lic = mgr.activate(key)
        assert lic.customer == "acme"
        assert mgr.licensed("arena")
        assert not mgr.licensed("privacy-api")  # only licensed features
        hb = mgr.heartbeat()
        assert hb["active"] and hb["customer"] == "acme"

    def test_forged_key_rejected(self, vendor_key):
        _priv, pub = vendor_key
        other = rsa.generate_private_key(public_exponent=65537, key_size=2048)
        mgr = LicenseManager(pub)
        with pytest.raises(LicenseError, match="signature"):
            mgr.activate(sign_license(other))
        with pytest.raises(LicenseError, match="malformed"):
            mgr.activate("not-a-key")

    def test_tampered_payload_rejected(self, vendor_key):
        priv, pub = vendor_key
        key = sign_license(priv, features=["arena"])
        payload, sig = key.split(".")
        import base64

        doc = json.loads(base64.urlsafe_b64decode(payload + "=="))
        doc["features"] = sorted(EE_FEATURES)  # self-upgrade attempt
        forged = base64.urlsafe_b64encode(
            json.dumps(doc, sort_keys=True).encode()
        ).decode().rstrip("=") + "." + sig
        mgr = LicenseManager(pub)
        with pytest.raises(LicenseError, match="signature"):
            mgr.activate(forged)

    def test_expiry_and_grace(self, vendor_key):
        priv, pub = vendor_key
        mgr = LicenseManager(pub, grace_s=3600)
        key = sign_license(priv, features=["arena"],
                           expires_at=time.time() - 60)  # expired, in grace
        mgr.activate(key)
        assert mgr.licensed("arena")
        hb = mgr.heartbeat()
        assert hb["in_grace"] and hb["active"]
        # Beyond grace: activation refuses outright.
        dead = sign_license(priv, features=["arena"],
                            expires_at=time.time() - 7200)
        with pytest.raises(LicenseError, match="expired"):
            LicenseManager(pub, grace_s=3600).activate(dead)


# ---------------------------------------------------------------------------
# EE kinds through the operator
# ---------------------------------------------------------------------------

SCENARIO = {
    "name": "refund-check",
    "turns": [{
        "user": "how do refunds work?",
        "checks": [{"kind": "contains", "value": "refund"}],
    }],
}


class TestEEKindsReconcile:
    def test_arena_job_end_to_end(self):
        """ArenaJob resource → controller submits to the arena queue → a
        worker drains it → status converges to a verdict."""
        from omnia_tpu.evals.arena import ArenaJobController
        from omnia_tpu.evals.queue import ArenaQueue
        from omnia_tpu.evals.worker import ArenaWorker, DirectRunner
        from omnia_tpu.runtime.packs import load_pack
        from omnia_tpu.runtime.providers import ProviderRegistry, ProviderSpec

        store = MemoryResourceStore()
        arena = ArenaJobController(ArenaQueue())
        mgr = ControllerManager(store, arena=arena)
        store.apply(Resource(kind="ArenaJob", name="job-a", spec={
            "scenarios": [SCENARIO],
            "providers": ["good"],
            "threshold": {"min_pass_rate": 1.0},
        }))
        mgr.drain_queue()
        res = store.get("default", "ArenaJob", "job-a")
        assert res.status["phase"] == "Running"
        assert res.status["total"] == 1

        reg = ProviderRegistry()
        reg.register(ProviderSpec(name="good", type="mock", options={
            "scenarios": [{"pattern": "refund",
                           "reply": "a refund lands within 30 days"}]}))
        pack = load_pack({"name": "p", "version": "1.0.0",
                          "prompts": {"system": "s"},
                          "sampling": {"temperature": 0.0, "max_tokens": 64}})
        ArenaWorker(arena.queue, DirectRunner(pack, reg)).run_until_empty()
        mgr.resync()
        res = store.get("default", "ArenaJob", "job-a")
        assert res.status["phase"] == "Succeeded", res.status
        assert res.status["verdict"]["passed"] is True
        mgr.shutdown()

    def test_arena_job_blocked_without_license(self, vendor_key):
        _priv, pub = vendor_key
        store = MemoryResourceStore()
        mgr = ControllerManager(store, license_manager=LicenseManager(pub))
        store.apply(Resource(kind="ArenaJob", name="job-b", spec={
            "scenarios": [SCENARIO], "providers": ["p"]}))
        mgr.drain_queue()
        res = store.get("default", "ArenaJob", "job-b")
        assert res.status["phase"] == "Blocked"
        assert "license" in res.status["message"]
        mgr.shutdown()

    def test_tool_policy_builds_shared_evaluator(self):
        store = MemoryResourceStore()
        mgr = ControllerManager(store)
        store.apply(Resource(kind="ToolPolicy", name="deny-destructive", spec={
            "tools": ["db_*"],
            "rules": [{"action": "deny", "when": 'args.mode == "write"',
                       "reason": "writes forbidden"}],
            "default_action": "allow",
        }))
        mgr.drain_queue()
        res = store.get("default", "ToolPolicy", "deny-destructive")
        assert res.status["phase"] == "Ready"
        assert res.status["policiesLoaded"] == 1
        d = mgr.policy_evaluator.decide({
            "tool": "db_query", "agent": "a", "args": {"mode": "write"}})
        assert d.allow is False and "writes forbidden" in d.reason
        d = mgr.policy_evaluator.decide({
            "tool": "db_query", "agent": "a", "args": {"mode": "read"}})
        assert d.allow is True
        mgr.shutdown()

    def test_admission_rejects_bad_ee_specs(self):
        store = MemoryResourceStore()
        with pytest.raises(ValidationError, match="scenarios"):
            store.apply(Resource(kind="ArenaJob", name="x",
                                 spec={"providers": ["p"]}))
        with pytest.raises(ValidationError, match="action"):
            store.apply(Resource(kind="ToolPolicy", name="x",
                                 spec={"rules": [{"action": "maybe"}]}))
        with pytest.raises(ValidationError, match="metrics"):
            store.apply(Resource(kind="RolloutAnalysis", name="x", spec={}))

    def test_passive_ee_kinds_ready(self):
        store = MemoryResourceStore()
        mgr = ControllerManager(store)
        store.apply(Resource(kind="SessionPrivacyPolicy", name="spp", spec={
            "recording": True, "redactFields": ["ssn"]}))
        store.apply(Resource(kind="RolloutAnalysis", name="ra", spec={
            "metrics": [{"name": "error-rate", "maxErrorRate": 0.05}]}))
        mgr.drain_queue()
        assert store.get("default", "SessionPrivacyPolicy", "spp").status["phase"] == "Ready"
        assert store.get("default", "RolloutAnalysis", "ra").status["phase"] == "Ready"
        mgr.shutdown()


# ---------------------------------------------------------------------------
# operator REST
# ---------------------------------------------------------------------------


@pytest.fixture()
def op_api():
    store = MemoryResourceStore()
    store.apply(Resource(kind="Workspace", name="team-a", spec={
        "environment": "dev",
        "roleBindings": [
            {"role": "admin", "users": ["alice"]},
            {"role": "viewer", "users": ["bob"]},
        ],
    }))
    api = OperatorAPI(store, mgmt_secret=b"mgmt-secret",
                      service_token="svc-tok")
    port = api.serve(host="127.0.0.1", port=0)
    yield api, port
    api.shutdown()


def _call(port, method, path, body=None, token="svc-tok"):
    data = json.dumps(body).encode() if body is not None else None
    headers = {"Content-Type": "application/json"}
    if token and path != "/api/v1/mgmt-token":
        headers["Authorization"] = f"Bearer {token}"
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=data, method=method,
        headers=headers,
    )
    try:
        with urllib.request.urlopen(req, timeout=10) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


class TestOperatorAPI:
    def test_tooltest_executes_http_handler(self, op_api):
        _api, port = op_api

        class Echo(http.server.BaseHTTPRequestHandler):
            def do_POST(self):
                n = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(n)
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):
                pass

        httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Echo)
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        try:
            status, doc = _call(port, "POST", "/api/v1/tooltest", {
                "handler": {"name": "echo-tool", "type": "http",
                            "url": f"http://127.0.0.1:{httpd.server_address[1]}/"},
                "arguments": {"q": "refunds"},
            })
            assert status == 200 and doc["ok"], doc
            assert "refunds" in doc["result"]
            assert doc["latency_ms"] >= 0
        finally:
            httpd.shutdown()

    def test_tooltest_requires_service_token(self, op_api):
        """An mcp/python handler config is code execution on the operator
        host — the route must not be callable unauthenticated."""
        _api, port = op_api
        status, _ = _call(port, "POST", "/api/v1/tooltest", {
            "handler": {"name": "x", "type": "http", "url": "http://h/"},
        }, token=None)
        assert status in (401, 403)

    def test_tooltest_rejects_stdio_mcp(self, op_api):
        """Even authenticated, a stdio MCP config names a binary to spawn
        on the operator host; tooltest refuses it (defense in depth)."""
        _api, port = op_api
        status, doc = _call(port, "POST", "/api/v1/tooltest", {
            "handler": {"name": "evil", "type": "mcp",
                        "mcp": {"transport": "stdio", "command": "bash",
                                "args": ["-c", "true"]}},
        })
        assert status == 400 and "stdio" in doc["error"]

    def test_tooltest_reports_unreachable_backend(self, op_api):
        _api, port = op_api
        status, doc = _call(port, "POST", "/api/v1/tooltest", {
            "handler": {"name": "dead", "type": "http",
                        "url": "http://127.0.0.1:1/", "timeout_s": 0.3},
        })
        assert status == 200 and doc["ok"] is False

    def test_content_crud_versions(self, op_api):
        _api, port = op_api
        s, v1 = _call(port, "PUT", "/api/v1/content/team-a/packs/main.json",
                      {"content": '{"v": 1}', "author": "alice"})
        assert s == 200 and v1["version"] == 1
        _call(port, "PUT", "/api/v1/content/team-a/packs/main.json",
              {"content": '{"v": 2}'})
        s, latest = _call(port, "GET", "/api/v1/content/team-a/packs/main.json")
        assert latest["version"] == 2 and latest["content"] == '{"v": 2}'
        s, old = _call(port, "GET",
                       "/api/v1/content/team-a/packs/main.json?version=1")
        assert old["content"] == '{"v": 1}'
        s, listing = _call(port, "GET", "/api/v1/content/team-a/")
        assert listing["items"][0]["path"] == "packs/main.json"
        s, d = _call(port, "DELETE", "/api/v1/content/team-a/packs/main.json")
        assert d["deleted"]
        s, _ = _call(port, "GET", "/api/v1/content/team-a/packs/main.json")
        assert s == 404

    def test_authz_roles(self, op_api):
        _api, port = op_api
        s, doc = _call(port, "POST", "/api/v1/authz/check",
                       {"workspace": "team-a", "user": "alice", "verb": "delete"})
        assert doc == {"allowed": True, "role": "admin"}
        s, doc = _call(port, "POST", "/api/v1/authz/check",
                       {"workspace": "team-a", "user": "bob", "verb": "delete"})
        assert doc["allowed"] is False
        s, doc = _call(port, "POST", "/api/v1/authz/check",
                       {"workspace": "team-a", "user": "bob", "verb": "get"})
        assert doc["allowed"] is True
        s, doc = _call(port, "POST", "/api/v1/authz/check",
                       {"workspace": "nope", "user": "alice", "verb": "get"})
        assert doc["allowed"] is False

    def test_mgmt_token_minting_and_fetcher(self, op_api):
        from omnia_tpu.facade.auth import HmacValidator
        from omnia_tpu.utils.mgmtplane import MgmtTokenFetcher

        _api, port = op_api
        fetcher = MgmtTokenFetcher(f"http://127.0.0.1:{port}", subject="doctor",
                                   service_token="svc-tok")
        tok = fetcher.token()
        principal = HmacValidator(b"mgmt-secret", audience="mgmt").validate(tok)
        assert principal is not None and principal.subject == "doctor"
        # Cached until near expiry: same token returned.
        assert fetcher.token() == tok
        assert fetcher.auth_header()["Authorization"].startswith("Bearer ")
        # Without the service token, minting is denied — an open minting
        # endpoint would let any caller escalate to a mgmt principal.
        s, doc = _call(port, "POST", "/api/v1/mgmt-token", {"subject": "evil"})
        assert s == 401
        # And with NO service token configured at all, minting is disabled.
        api2 = OperatorAPI(MemoryResourceStore(), mgmt_secret=b"x")
        port2 = api2.serve(host="127.0.0.1", port=0)
        try:
            s, doc = _call(port2, "POST", "/api/v1/mgmt-token", {"subject": "u"})
            assert s == 401
        finally:
            api2.shutdown()

    def test_license_endpoints(self, op_api, vendor_key):
        priv, pub = vendor_key
        store = MemoryResourceStore()
        api = OperatorAPI(store, license_manager=LicenseManager(pub))
        port = api.serve(host="127.0.0.1", port=0)
        try:
            s, hb = _call(port, "GET", "/api/v1/license")
            assert hb["active"] is False
            s, doc = _call(port, "POST", "/api/v1/license/activate",
                           {"key": sign_license(priv, features=["arena"])})
            assert s == 200 and doc["activated"]
            s, hb = _call(port, "GET", "/api/v1/license")
            assert hb["active"] and hb["features"] == ["arena"]
            s, doc = _call(port, "POST", "/api/v1/license/activate",
                           {"key": "garbage"})
            assert s == 402
        finally:
            api.shutdown()

    def test_deploy_intent_applies_resources(self, op_api):
        api, port = op_api
        s, doc = _call(port, "POST", "/api/v1/deploy", {
            "version": "v1",
            "name": "intent-bot",
            "pack": {"name": "intent-pack", "version": "1.0.0",
                     "prompts": {"system": "s"},
                     "sampling": {"temperature": 0.0, "max_tokens": 32}},
            "providers": [{"name": "m", "providerRef": {"name": "mock-llm"}}],
        })
        assert s == 200, doc
        assert api.store.get("default", "AgentRuntime", "intent-bot") is not None
