"""Grammar-constrained decoding: compiler, engine masking, runtime path.

Covers the PR's acceptance contract:
- every sampled sequence under a grammar decodes to output that parses
  under the source schema/regex (property tests, worst-case sampling),
- the post-hoc response_format validator can never fire with a grammar
  attached (cross-check over random schemas),
- the mock engine enforces identical masks to the compiled path,
- grammar=off is a guarded true no-op,
- the compile cache key is content-addressed and process-stable.
"""

from __future__ import annotations

import hashlib
import json
import os
import random
import re
import subprocess
import sys
import threading

import jsonschema
import numpy as np
import pytest

from omnia_tpu.engine.grammar import (
    GrammarTooLarge,
    GrammarUnsupported,
    TokenGrammar,
    clear_cache,
    compile_json_schema,
    compile_regex,
    compile_turn_grammar,
    force_complete,
    grammar_cache_key,
    stats,
    walk_text,
)
from omnia_tpu.engine.grammar.fsm import NfaBuilder, determinize
from omnia_tpu.engine.tokenizer import ByteTokenizer, IncrementalDetokenizer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOK = ByteTokenizer()


def _complete(view, toks, s):
    """Deterministic completion: each completion token strictly reduces
    distance-to-accept, so this terminates in <= num_states steps."""
    while not view.is_accepting(s):
        t = view.completion_token(s)
        assert t >= 0, f"state {s} cannot complete"
        toks.append(t)
        s = view.advance(s, t)
    return toks, s


def _rand_walk(view, rng, max_tokens=400):
    """Random phase over admissible BYTE tokens, then forced completion —
    worst-case in the sense that the random phase explores arbitrary
    grammar corners before finishing."""
    toks, s = [], view.start
    for _ in range(rng.randint(3, max_tokens)):
        allowed = np.flatnonzero(view.allowed(s)[:256])
        if allowed.size == 0:
            break
        t = int(rng.choice(allowed))
        toks.append(t)
        s = view.advance(s, t)
    toks, _s = _complete(view, toks, s)
    return TOK.decode(toks)


def _garbage_walk(view, rng, n=48):
    """Worst-case proposal stream (the mock's semantics): mostly-masked
    garbage bytes, each masked proposal replaced by the completion move —
    what a maximally misbehaving model would force the sampler into."""
    toks, s = [], view.start
    for _ in range(n):
        t = rng.randrange(256)
        if not view.allowed(s)[t]:
            t = view.completion_token(s)
            if t < 0:
                break
        toks.append(t)
        s = view.advance(s, t)
    toks, _s = _complete(view, toks, s)
    return TOK.decode(toks)


# ---------------------------------------------------------------------------
# Regex compiler
# ---------------------------------------------------------------------------


class TestRegexCompile:
    PATTERNS = [
        r"[a-c]{2,4}(x|yz)?\d+",
        r"(foo|bar)+",
        r"v\d+\.\d+\.\d+(-rc\d)?",
        r"[A-F0-9]{8}",
        r"yes|no|maybe",
        r"a*b+c?",
        r"\w{1,6}@\w{1,6}\.(com|org)",
        r"^anchored$",
        r"[^x]{1,3}",
        r"wild.{0,3}card",
    ]

    def test_walks_fullmatch_python_re(self):
        rng = random.Random(7)
        for pat in self.PATTERNS:
            g = compile_regex(pat, TOK)
            v = g.view()
            for _ in range(8):
                text = _rand_walk(v, rng)
                assert re.fullmatch(pat, text, re.ASCII), (pat, text)

    def test_rejects_matching_strings_only(self):
        g = compile_regex(r"ab+c", TOK)
        v = g.view()
        ok = TOK.encode("abbc", add_bos=False)
        bad = TOK.encode("abd", add_bos=False)
        assert walk_text(v, ok)
        assert not walk_text(v, bad)

    def test_eos_only_when_complete(self):
        g = compile_regex(r"ab", TOK)
        v = g.view()
        s = v.start
        assert not v.allowed(s)[TOK.eos_id]
        s = v.advance(s, ord("a"))
        assert not v.allowed(s)[TOK.eos_id]
        s = v.advance(s, ord("b"))
        assert v.is_accepting(s)
        assert v.allowed(s)[TOK.eos_id]

    def test_unsupported_constructs_refuse(self):
        for pat in [r"(?=look)x", r"a\1", r"mid^anchor", r"\bword"]:
            with pytest.raises(GrammarUnsupported):
                compile_regex(pat, TOK)

    def test_runaway_repeat_bounds(self):
        with pytest.raises(GrammarTooLarge):
            compile_regex(r"a{1,99999}", TOK)


# ---------------------------------------------------------------------------
# JSON-Schema compiler (property tests, worst-case sampling)
# ---------------------------------------------------------------------------


def _rand_schema(rng: random.Random, depth: int) -> dict:
    kinds = ["string", "integer", "number", "boolean", "null", "enum"]
    if depth > 0:
        kinds += ["object", "array", "anyOf"]
    kind = rng.choice(kinds)
    if kind == "string":
        s: dict = {"type": "string"}
        if rng.random() < 0.5:
            lo = rng.randint(0, 3)
            s["minLength"] = lo
            s["maxLength"] = lo + rng.randint(0, 6)
        return s
    if kind == "integer":
        s = {"type": "integer"}
        if rng.random() < 0.4:
            s["minimum"] = 0
        return s
    if kind == "number":
        return {"type": "number"}
    if kind == "boolean":
        return {"type": "boolean"}
    if kind == "null":
        return {"type": "null"}
    if kind == "enum":
        pool = ["red", "green", 1, 2.5, True, None, "héllo"]
        return {"enum": rng.sample(pool, rng.randint(1, 3))}
    if kind == "anyOf":
        return {"anyOf": [_rand_schema(rng, depth - 1)
                          for _ in range(rng.randint(1, 2))]}
    if kind == "array":
        lo = rng.randint(0, 2)
        return {
            "type": "array",
            "items": _rand_schema(rng, depth - 1),
            "minItems": lo,
            "maxItems": lo + rng.randint(0, 2),
        }
    props = {
        f"k{i}": _rand_schema(rng, depth - 1)
        for i in range(rng.randint(1, 3))
    }
    names = list(props)
    return {
        "type": "object",
        "properties": props,
        "required": rng.sample(names, rng.randint(0, len(names))),
    }


class TestJsonSchemaProperty:
    def test_fifty_random_schemas_worst_case_sampling(self):
        """Acceptance property: with a grammar attached, every admitted
        output parses AND validates — so the post-hoc validator
        (`_check_response_format`) can never fire. ~50 random schemas,
        worst-case (garbage-proposal) and random-walk sampling."""
        from omnia_tpu.runtime.conversation import Conversation

        check = Conversation._check_response_format
        rng = random.Random(11)
        for i in range(50):
            schema = _rand_schema(rng, depth=2)
            g = compile_json_schema(schema, TOK)
            v = g.view()
            for walker in (_rand_walk, _garbage_walk):
                text = walker(v, rng)
                doc = json.loads(text)
                jsonschema.validate(doc, schema)
                rf = {"type": "json_schema", "schema": schema}
                err = check(None, text, rf)
                assert err is None, (schema, text, err)

    def test_generic_json_mode(self):
        g = compile_json_schema(None, TOK)
        rng = random.Random(3)
        v = g.view()
        for _ in range(5):
            json.loads(_rand_walk(v, rng, max_tokens=2000))

    def test_unenforceable_keywords_refuse(self):
        bad = [
            {"type": "integer", "minimum": 5},
            {"type": "number", "maximum": 10},
            {"oneOf": [{"type": "integer"}, {"type": "number"}]},
            {"type": "array", "items": {"type": "integer"},
             "uniqueItems": True},
            {"type": "object", "properties": {"a": {"type": "string"}},
             "required": ["a", "missing"]},
            {"type": "string", "pattern": 'quo"te'},
        ]
        for schema in bad:
            with pytest.raises(GrammarUnsupported):
                compile_json_schema(schema, TOK)

    def test_string_pattern_enforced(self):
        schema = {"type": "string", "pattern": "^[a-z]{2,5}$"}
        g = compile_json_schema(schema, TOK)
        rng = random.Random(5)
        for _ in range(5):
            text = _rand_walk(g.view(), rng)
            doc = json.loads(text)
            jsonschema.validate(doc, schema)

    def test_pattern_json_unsafe_bytes_restricted_or_refused(self):
        """`.` and negated classes can MATCH a raw quote/backslash/
        control byte even when the pattern source never spells one.
        The compiler intersects every class with the JSON-string-safe
        alphabet (emitted ⊂ pattern language — still re.search-valid),
        and refuses outright when a LITERAL requires a forbidden byte."""
        rng = random.Random(6)
        for pat in ["a.c", "[^x]+", "^[a-z]{1,4}(-[0-9]{1,3})?$"]:
            schema = {"type": "string", "pattern": pat}
            g = compile_json_schema(schema, TOK)
            for _ in range(6):
                text = _garbage_walk(g.view(), rng)
                doc = json.loads(text)  # raw quote/control would break this
                jsonschema.validate(doc, schema)
        with pytest.raises(GrammarUnsupported):
            compile_json_schema(
                {"type": "string", "pattern": "a\\tb"}, TOK)

    def test_enum_filtered_by_sibling_type(self):
        schema = {"type": "integer", "enum": [1, "x", 2, True]}
        g = compile_json_schema(schema, TOK)
        rng = random.Random(8)
        for _ in range(6):
            doc = json.loads(_rand_walk(g.view(), rng))
            jsonschema.validate(doc, schema)  # only 1 / 2 are emittable
        with pytest.raises(GrammarUnsupported):
            compile_json_schema({"type": "integer", "enum": ["x"]}, TOK)
        with pytest.raises(GrammarUnsupported):
            compile_json_schema({"type": "integer", "const": True}, TOK)

    def test_non_serializable_spec_refuses_not_crashes(self):
        with pytest.raises(GrammarUnsupported):
            compile_turn_grammar(None, [{
                "name": "bad",
                "input_schema": {"type": "object",
                                 "properties": {"s": {"enum": {1, 2}}}},
            }], TOK)


# ---------------------------------------------------------------------------
# Tool-call turn grammar
# ---------------------------------------------------------------------------

TOOLS = [
    {"name": "add", "input_schema": {
        "type": "object",
        "properties": {"a": {"type": "integer"}, "b": {"type": "integer"}},
        "required": ["a", "b"]}},
    {"name": "get_weather", "input_schema": {
        "type": "object",
        "properties": {"city": {"type": "string", "maxLength": 12}},
        "required": ["city"]}},
]


class TestToolCallGrammar:
    def test_marker_forces_valid_tool_json(self):
        g = compile_turn_grammar(None, TOOLS, TOK)
        v = g.view()
        rng = random.Random(2)
        script = iter(TOK.encode("so, <tool_call>garbage", add_bos=False))

        def propose(_s, allowed):
            t = next(script, None)
            if t is None:
                return rng.choice(np.flatnonzero(allowed).tolist())
            return t

        toks, done = force_complete(v, propose, 600)
        assert done
        text = TOK.decode(toks)
        m = re.search(r"<tool_call>(.*?)</tool_call>", text, re.S)
        assert m, text
        call = json.loads(m.group(1))
        schema = {t["name"]: t["input_schema"] for t in TOOLS}[call["name"]]
        jsonschema.validate(call["arguments"], schema)

    def test_name_commit_hot_swaps_argument_schema(self):
        """Once the emitted name commits to one tool, only that tool's
        argument schema remains admissible — `add` args cannot carry
        get_weather's `city`."""
        g = compile_turn_grammar(None, TOOLS, TOK)
        v = g.view()
        good = TOK.encode('<tool_call>{"name":"add","arguments":{"a":1', add_bos=False)
        assert walk_text(v, good)
        crossed = TOK.encode(
            '<tool_call>{"name":"add","arguments":{"city"', add_bos=False)
        assert not walk_text(v, crossed)

    def test_free_text_allows_partial_marker(self):
        g = compile_turn_grammar(None, TOOLS, TOK)
        v = g.view()
        assert walk_text(v, TOK.encode("a < b and <tool", add_bos=False))
        # ... and text states accept (the model may stop mid-almost-marker)
        s = v.start
        for t in TOK.encode("half <tool", add_bos=False):
            s = v.advance(s, t)
        assert v.is_accepting(s)

    def test_response_format_plus_tools_union(self):
        rf = {"type": "json_schema",
              "schema": {"type": "object",
                         "properties": {"x": {"type": "integer"}},
                         "required": ["x"]}}
        g = compile_turn_grammar(rf, TOOLS, TOK)
        v = g.view()
        rng = random.Random(9)
        for _ in range(6):
            text = _rand_walk(v, rng)
            if text.startswith("<tool_call>"):
                call = json.loads(
                    text[len("<tool_call>"):text.index("</tool_call>")])
                assert call["name"] in {"add", "get_weather"}
            else:
                jsonschema.validate(json.loads(text), rf["schema"])


# ---------------------------------------------------------------------------
# Token-level compilation for multi-byte-token vocabularies
# ---------------------------------------------------------------------------


class _FakeBpeTokenizer:
    """Tiny stand-in for an HF vocabulary: multi-byte tokens exercise the
    longest-match transition path (a token is admitted only when its
    WHOLE byte string stays on live DFA paths)."""

    def __init__(self):
        self.pieces = [None, "a", "b", "ab", "abc", "xyz", '"', "{", "}"]
        self.vocab_size = len(self.pieces) + 1  # + eos
        self.bos_id = 0
        self.eos_id = len(self.pieces)

    def token_bytes(self):
        return [p.encode() if p else None for p in self.pieces] + [None]

    def encode(self, text, add_bos=True):  # pragma: no cover - unused
        raise NotImplementedError

    def decode(self, ids):
        return "".join(self.pieces[i] for i in ids
                       if i < len(self.pieces) and self.pieces[i])


class TestMultiByteTokens:
    def test_longest_match_token_transitions(self):
        tok = _FakeBpeTokenizer()
        b = NfaBuilder()
        from omnia_tpu.engine.grammar.regex import regex_fragment

        frag = regex_fragment(b, "abab|abc")
        dfa = determinize(b, frag.start, {frag.end})
        g = TokenGrammar(dfa, tok)
        v = g.view()
        s = v.start
        allowed = v.allowed(s)
        # "ab" and "abc" walk whole-token; "b"/"xyz" die on byte 1.
        assert allowed[tok.pieces.index("a")]
        assert allowed[tok.pieces.index("ab")]
        assert allowed[tok.pieces.index("abc")]
        assert not allowed[tok.pieces.index("b")]
        assert not allowed[tok.pieces.index("xyz")]
        s2 = v.advance(s, tok.pieces.index("ab"))
        assert v.allowed(s2)[tok.pieces.index("ab")]
        s3 = v.advance(s2, tok.pieces.index("ab"))
        assert v.is_accepting(s3)
        assert v.allowed(s3)[tok.eos_id]
        assert not v.allowed(s2)[tok.eos_id]

    def test_gpt2_byte_level_and_byte_fallback_pieces(self):
        """Byte-level BPE pieces decode through the GPT-2 byte alphabet
        (NOT utf-8 re-encoding — 'Ã©' is the two bytes C3 A9, one é) and
        sentencepiece `<0xNN>` byte-fallback pieces are single bytes."""
        from omnia_tpu.engine.grammar.fsm import tokenizer_token_bytes

        class Inner:
            def convert_ids_to_tokens(self, i):
                return ["Ġhi", "Ã©", "<0x0A>", "▁sp", None][i]

        class Wrapper:
            vocab_size = 5
            bos_id = 3
            eos_id = 4
            _tok = Inner()

            def decode(self, ids):  # pragma: no cover - unused
                return ""

        tb = tokenizer_token_bytes(Wrapper())
        assert tb[0] == b" hi"
        assert tb[1] == "é".encode("utf-8")  # C3 A9, not C3 83 C2 A9
        assert tb[2] == b"\n"
        # byte-level alphabet detected ⇒ '▁' is outside the byte
        # decoder's domain: masked (None), never wrong bytes
        assert tb[3] is None
        assert tb[4] is None


# ---------------------------------------------------------------------------
# Compile cache
# ---------------------------------------------------------------------------


class TestCompileCache:
    def test_key_is_content_addressed_and_order_stable(self):
        s1 = {"type": "object", "properties": {"a": {"type": "integer"},
                                               "b": {"type": "boolean"}}}
        s2 = json.loads(json.dumps(s1))  # fresh dicts
        s2["properties"] = dict(reversed(list(s2["properties"].items())))
        assert grammar_cache_key("turn", s1, TOK) == \
            grammar_cache_key("turn", s2, TOK)

    def test_key_stable_across_processes(self):
        """The key must be a pure function of (spec, tokenizer
        fingerprint) — re-derive the canonical payload independently and
        match the sha256. A process-local id() or dict-order dependence
        would break this."""
        spec = {"schema": {"type": "integer"}}
        payload = {
            "v": 1, "kind": "regex", "spec": spec,
            "tokenizer": {"class": "ByteTokenizer", "vocab_size": 259,
                          "bos_id": 256, "eos_id": 257},
        }
        blob = json.dumps(payload, sort_keys=True, separators=(",", ":"),
                          ensure_ascii=True)
        expected = hashlib.sha256(blob.encode()).hexdigest()
        assert grammar_cache_key("regex", spec, TOK) == expected

    def test_hit_miss_counters(self):
        clear_cache()
        compile_regex(r"x\d+", TOK)
        assert stats == {"hits": 0, "misses": 1}
        compile_regex(r"x\d+", TOK)
        assert stats == {"hits": 1, "misses": 1}
        compile_regex(r"y\d+", TOK)
        assert stats == {"hits": 1, "misses": 2}


# ---------------------------------------------------------------------------
# Windowed incremental detokenizer (satellite)
# ---------------------------------------------------------------------------


class TestDetokenizerWindow:
    def test_multibyte_utf8_split_across_tokens_equivalence(self):
        # CJK + emoji + combining chars, byte tokens split mid-rune, and
        # long enough to force many window folds.
        text = ("héllo wörld 漢字テスト🙂🦙" * 20) + " tail"
        ids = TOK.encode(text, add_bos=False)
        detok = IncrementalDetokenizer(TOK)
        streamed = "".join(detok.push(i) for i in ids) + detok.flush()
        assert streamed == TOK.decode(ids)

    def test_window_actually_bounds_state(self):
        detok = IncrementalDetokenizer(TOK)
        for i in TOK.encode("abcdefgh" * 50, add_bos=False):
            detok.push(i)
        assert len(detok._ids) <= IncrementalDetokenizer.WINDOW

    def test_fold_defers_on_split_sensitive_tokenizer(self):
        """Sentencepiece-style decode (leading-space marker stripped at
        SEQUENCE start only) makes decode(left)+decode(right) differ
        from decode(whole) at every cut — the fold must defer (window
        bound yields) and the stream must still equal the full-sequence
        decode exactly."""

        class SpStyleTok:
            vocab_size = 300
            bos_id = 256
            eos_id = 257

            def decode(self, ids):
                # every piece carries a leading-space marker; the very
                # first marker of a sequence is stripped.
                return " ".join(str(i) for i in ids)

        tok = SpStyleTok()
        detok = IncrementalDetokenizer(tok)
        ids = list(range(2, 102))
        streamed = "".join(detok.push(i) for i in ids) + detok.flush()
        assert streamed == tok.decode(ids)
        # correctness won over the window bound: no fold point was legal
        detok2 = IncrementalDetokenizer(tok)
        for i in ids:
            detok2.push(i)
        assert len(detok2._ids) == len(ids)

    def test_trailing_partial_rune_held_back(self):
        detok = IncrementalDetokenizer(TOK)
        ids = "🙂".encode("utf-8")
        assert detok.push(ids[0]) == ""
        assert detok.push(ids[1]) == ""
        assert detok.push(ids[2]) == ""
        assert detok.push(ids[3]) == "🙂"


# ---------------------------------------------------------------------------
# Engine integration (compiled path) + no-op guard
# ---------------------------------------------------------------------------


def _drain(engine, handle):
    toks = []
    fin = None
    while fin is None:
        engine.step()
        try:
            while True:
                ev = handle._queue.get_nowait()
                if ev.token_id is not None:
                    toks.append(ev.token_id)
                if ev.is_final:
                    fin = ev
                    break
        except Exception:  # noqa: BLE001 - queue.Empty
            pass
    return toks, fin


@pytest.fixture(scope="module")
def grammar_engine():
    from omnia_tpu.engine import EngineConfig, InferenceEngine
    from omnia_tpu.models import get_config

    ecfg = EngineConfig(num_slots=4, max_seq=128, prefill_buckets=(64,),
                        dtype="float32", max_sessions=4, grammar=True,
                        grammar_max_states=512)
    return InferenceEngine(get_config("test-tiny"), ecfg, seed=0)


SCHEMA = {"type": "object",
          "properties": {"a": {"type": "integer"},
                         "ok": {"type": "boolean"}},
          "required": ["a", "ok"]}


class TestEngineGrammar:
    def test_constrained_sampled_generation_validates(self, grammar_engine):
        from omnia_tpu.engine import FinishReason, SamplingParams

        eng = grammar_engine
        g = compile_json_schema(SCHEMA, TOK)
        # Stop id 0: byte 0 is never admissible inside the grammar, so
        # it plays EOS for the 256-vocab test model.
        sp = SamplingParams(temperature=1.0, max_tokens=120,
                            stop_token_ids=(0,))
        prompt = TOK.encode("make json")
        handles = [eng.submit(prompt, sp, grammar=g) for _ in range(3)]
        for h in handles:
            toks, fin = _drain(eng, h)
            assert fin.finish_reason == FinishReason.STOP
            text = TOK.decode([t for t in toks if t != 0])
            jsonschema.validate(json.loads(text), SCHEMA)
        assert eng.metrics["grammar_rejections_avoided"] >= 3
        assert 0.0 < eng.metrics["masked_logit_fraction"] <= 1.0

    def test_mixed_batch_unconstrained_slot_unaffected(self, grammar_engine):
        from omnia_tpu.engine import SamplingParams

        eng = grammar_engine
        g = compile_json_schema(SCHEMA, TOK)
        sp_g = SamplingParams(temperature=1.0, max_tokens=100,
                              stop_token_ids=(0,))
        sp_free = SamplingParams(temperature=1.0, max_tokens=12, seed=5)
        prompt = TOK.encode("mix")
        hg = eng.submit(prompt, sp_g, grammar=g)
        hf = eng.submit(prompt, sp_free)
        toks_f, fin_f = _drain(eng, hf)
        toks_g, fin_g = _drain(eng, hg)
        assert len(toks_f) == 12  # free slot ran to its budget unmasked
        jsonschema.validate(
            json.loads(TOK.decode([t for t in toks_g if t != 0])), SCHEMA)

    def test_sampled_tokens_follow_host_mirror(self, grammar_engine):
        """Device-advanced FSM state and the host mirror agree: every
        emitted token is admissible from the mirror's running state —
        the compiled path enforces exactly the TokenGrammar tables."""
        from omnia_tpu.engine import SamplingParams

        eng = grammar_engine
        g = compile_json_schema(SCHEMA, TOK)
        sp = SamplingParams(temperature=1.0, max_tokens=100,
                            stop_token_ids=(0,))
        h = eng.submit(TOK.encode("mirror"), sp, grammar=g)
        toks, _fin = _drain(eng, h)
        v = g.view(eng.model_cfg.vocab_size, (0,))
        s = v.start
        for t in toks:
            assert v.allowed(s)[t], (s, t)
            s = v.advance(s, t)

    def test_session_turns_with_grammar(self, grammar_engine):
        from omnia_tpu.engine import SamplingParams

        eng = grammar_engine
        g = compile_json_schema(SCHEMA, TOK)
        sp = SamplingParams(temperature=1.0, max_tokens=100,
                            stop_token_ids=(0,))
        prompt = TOK.encode("turn one")
        h = eng.submit(prompt, sp, session_id="gs", grammar=g)
        toks, _ = _drain(eng, h)
        prompt2 = prompt + toks[:-1] + TOK.encode(" turn two", add_bos=False)
        h2 = eng.submit(prompt2, sp, session_id="gs", grammar=g)
        toks2, _ = _drain(eng, h2)
        jsonschema.validate(
            json.loads(TOK.decode([t for t in toks2 if t != 0])), SCHEMA)

    def test_too_large_grammar_rejected_at_submit(self, grammar_engine):
        from omnia_tpu.engine import FinishReason, SamplingParams

        eng = grammar_engine
        big = compile_json_schema(None, TOK)  # generic JSON > 512 states
        h = eng.submit(TOK.encode("x"), SamplingParams(), grammar=big)
        ev = h.get_event(timeout=5)
        assert ev.finish_reason == FinishReason.ERROR
        assert "grammar" in ev.error

    def test_compile_cache_metrics_mirrored(self, grammar_engine):
        from omnia_tpu.engine import SamplingParams

        clear_cache()
        g = compile_json_schema(SCHEMA, TOK)
        compile_json_schema(SCHEMA, TOK)
        eng = grammar_engine
        h = eng.submit(TOK.encode("m"), SamplingParams(
            temperature=0.0, max_tokens=4, stop_token_ids=(0,)), grammar=g)
        _drain(eng, h)
        assert eng.metrics["grammar_compile_misses"] == 1
        assert eng.metrics["grammar_compile_hits"] == 1


class TestGrammarOffNoop:
    """CI/tooling satellite: grammar=off allocates nothing and traces no
    grammar operands; the grammar package itself is jax-free."""

    def test_engine_grammar_import_is_jax_free(self):
        """Importing omnia_tpu.engine.grammar must not initialize jax —
        no device arrays can exist if jax is never imported."""
        code = (
            "import sys; import omnia_tpu.engine.grammar; "
            "assert 'jax' not in sys.modules, 'jax imported'; "
            "import omnia_tpu.engine.grammar.fsm, "
            "omnia_tpu.engine.grammar.jsonfsm, "
            "omnia_tpu.engine.grammar.regex, omnia_tpu.engine.grammar.cache; "
            "assert 'jax' not in sys.modules, 'jax imported by submodule'"
        )
        subprocess.run([sys.executable, "-c", code], check=True, cwd=REPO)

    def test_grammar_off_engine_allocates_no_grammar_state(self):
        from omnia_tpu.engine import (
            EngineConfig, FinishReason, InferenceEngine, SamplingParams,
        )
        from omnia_tpu.models import get_config

        ecfg = EngineConfig(num_slots=2, max_seq=64, prefill_buckets=(32,),
                            dtype="float32", max_sessions=0)
        eng = InferenceEngine(get_config("test-tiny"), ecfg, seed=0)
        assert not eng.supports_grammar()
        assert eng._gstate is None
        assert eng._gtable is None
        assert eng._gactive is None
        assert eng._gbias_zero is None
        # A grammar request is refused with a real error, not silently
        # served unconstrained.
        g = compile_regex(r"\d+", TOK)
        h = eng.submit(TOK.encode("x"), SamplingParams(), grammar=g)
        ev = h.get_event(timeout=5)
        assert ev.finish_reason == FinishReason.ERROR
        assert "grammar=off" in ev.error
        # ... and ungrammared serving works with untouched grammar metrics.
        h2 = eng.submit(TOK.encode("y"), SamplingParams(max_tokens=4))
        _drain(eng, h2)
        assert eng.metrics["grammar_compile_misses"] == 0
        assert eng.metrics["masked_logit_fraction"] == 0.0
        assert eng.metrics["grammar_rejections_avoided"] == 0

    def test_grammar_package_sources_never_import_jax(self):
        gdir = os.path.join(REPO, "omnia_tpu", "engine", "grammar")
        for fn in os.listdir(gdir):
            if not fn.endswith(".py"):
                continue
            with open(os.path.join(gdir, fn)) as f:
                src = f.read()
            assert not re.search(r"^\s*(import jax|from jax)", src, re.M), (
                f"omnia_tpu/engine/grammar/{fn} imports jax — the package "
                "must stay host-side"
            )


# ---------------------------------------------------------------------------
# Mock-engine parity
# ---------------------------------------------------------------------------


class TestMockGrammarParity:
    def test_mock_enforces_identical_masks(self):
        """The mock walks the SAME table the compiled path uploads:
        every emitted token is admissible step-by-step, and the device
        table rows equal the mock view's rows."""
        from omnia_tpu.engine import MockEngine, SamplingParams

        g = compile_json_schema(SCHEMA, TOK)
        eng = MockEngine([], tokenizer=TOK)
        h = eng.submit(TOK.encode("anything"),
                       SamplingParams(max_tokens=200), grammar=g)
        toks, fin = h.collect_tokens(timeout=30)
        v = g.view(TOK.vocab_size)
        s = v.start
        for t in toks:
            assert v.allowed(s)[t]
            s = v.advance(s, t)
        assert v.is_accepting(s)
        jsonschema.validate(json.loads(TOK.decode(toks)), SCHEMA)
        # Device-table prefix == mock view table (same arrays, padded).
        dev = g.device_table(512, 259, (0,))
        np.testing.assert_array_equal(
            dev[:g.num_states, :TOK.vocab_size],
            g.view(259, (0,)).table[:, :TOK.vocab_size],
        )
        assert eng.metrics["grammar_rejections_avoided"] == 1
        assert eng.metrics["masked_logit_fraction"] > 0

    def test_scripted_garbage_becomes_valid(self):
        from omnia_tpu.engine import MockEngine, SamplingParams
        from omnia_tpu.engine.mock import Scenario

        g = compile_json_schema(SCHEMA, TOK)
        eng = MockEngine([Scenario(pattern=".", reply="not json at all")],
                         tokenizer=TOK)
        toks, _fin = eng.submit(
            TOK.encode("x"), SamplingParams(max_tokens=200), grammar=g
        ).collect_tokens(timeout=30)
        jsonschema.validate(json.loads(TOK.decode(toks)), SCHEMA)

    def test_conforming_script_plays_back_verbatim(self):
        """Stop-id parity with the compiled path: a scripted reply that
        already satisfies the grammar must stream byte-identical —
        including when the request carries custom stop ids (the mock's
        view must unmask them in accepting states, like placement
        does)."""
        from omnia_tpu.engine import MockEngine, SamplingParams
        from omnia_tpu.engine.mock import Scenario

        g = compile_json_schema(SCHEMA, TOK)
        reply = '{"a":7,"ok":true}'
        eng = MockEngine([Scenario(pattern=".", reply=reply)], tokenizer=TOK)
        toks, _fin = eng.submit(
            TOK.encode("x"),
            SamplingParams(max_tokens=200, stop_token_ids=(0,)), grammar=g,
        ).collect_tokens(timeout=30)
        assert TOK.decode(toks) == reply


# ---------------------------------------------------------------------------
# Runtime path: conversation + response_format / tool args (cross-check)
# ---------------------------------------------------------------------------


def _conv(scenarios, pack_extra=None, handlers=None, session="g1"):
    from omnia_tpu.engine import MockEngine
    from omnia_tpu.engine.mock import Scenario
    from omnia_tpu.runtime.context_store import InMemoryContextStore
    from omnia_tpu.runtime.conversation import Conversation
    from omnia_tpu.runtime.packs import load_pack
    from omnia_tpu.tools import ToolExecutor

    doc = {
        "name": "g", "version": "1.0.0",
        "prompts": {"system": "You are terse."},
        "sampling": {"temperature": 0.0, "max_tokens": 400},
    }
    doc.update(pack_extra or {})
    tok = ByteTokenizer()
    eng = MockEngine([Scenario(**s) for s in scenarios], tokenizer=tok)
    return Conversation(
        session_id=session, pack=load_pack(doc), engine=eng, tokenizer=tok,
        store=InMemoryContextStore(),
        tool_executor=ToolExecutor(handlers or []),
    )


class TestRuntimeGrammar:
    def test_posthoc_validator_never_fires_with_grammar(self):
        """Cross-check satellite: random schemas, scripted-garbage
        replies (the mock's worst-case proposal stream) — the turn must
        finish `done`, never `bad_response_format`."""
        import omnia_tpu.runtime.contract as c

        rng = random.Random(23)
        for i in range(8):
            schema = _rand_schema(rng, depth=1)
            conv = _conv([{"pattern": ".", "reply": "complete garbage !!"}],
                         session=f"pg{i}")
            msgs = list(conv.stream(c.ClientMessage(
                content=f"go {i}",
                response_format={"type": "json_schema", "schema": schema},
            )))
            assert msgs[-1].type == "done", (schema, vars(msgs[-1]))
            text = "".join(m.text for m in msgs if m.type == "chunk")
            jsonschema.validate(json.loads(text), schema)

    def test_plain_json_mode_stays_posthoc(self):
        """`{"type": "json"}` (no schema) keeps the pre-grammar
        behavior: invalid output surfaces bad_response_format."""
        import omnia_tpu.runtime.contract as c

        conv = _conv([{"pattern": ".", "reply": "not json at all"}])
        msgs = list(conv.stream(c.ClientMessage(
            content="x", response_format={"type": "json"})))
        assert msgs[-1].type == "error"
        assert msgs[-1].error_code == "bad_response_format"

    def test_tool_arguments_valid_by_construction(self):
        import omnia_tpu.runtime.contract as c
        from omnia_tpu.tools import ToolHandler

        calls = []
        handlers = [ToolHandler(name="add", type="python",
                                fn=lambda a: calls.append(a) or "5")]
        conv = _conv(
            [
                {"pattern": r"\[TOOL\]", "reply": "the sum is 5"},
                # Scripted args are WRONG (strings): the grammar coerces
                # them into schema-valid integers before dispatch.
                {"pattern": "calc", "reply":
                    '<tool_call>{"name": "add", "arguments": '
                    '{"a": "two", "b": "three"}}</tool_call>'},
            ],
            pack_extra={"tools": [dict(TOOLS[0], description="adds")]},
            handlers=handlers,
        )
        msgs = list(conv.stream(c.ClientMessage(content="calc")))
        assert msgs[-1].type == "done"
        assert len(calls) == 1
        jsonschema.validate(calls[0], TOOLS[0]["input_schema"])

    def test_schema_less_tool_disables_constraint(self):
        conv = _conv([], pack_extra={"tools": [
            {"name": "a", "input_schema": {"type": "object"}},
            {"name": "b"},  # no schema anywhere
        ]})
        assert conv._grammar_tools(None) is None

    def test_plain_json_with_tools_attaches_nothing(self):
        """A tools-only grammar under {"type": "json"} would admit free
        text the format forbids — no partial enforcement: attach
        nothing, keep both post-hoc paths."""
        import omnia_tpu.runtime.contract as c

        conv = _conv([], pack_extra={"tools": [dict(TOOLS[0])]})
        msg = c.ClientMessage(content="x", response_format={"type": "json"})
        assert conv._turn_grammar(msg, None) is None
        # ... while without a response_format the tool grammar attaches.
        assert conv._turn_grammar(c.ClientMessage(content="x"), None) \
            is not None

    def test_unsupported_schema_falls_back_posthoc(self):
        import omnia_tpu.runtime.contract as c

        schema = {"type": "integer", "minimum": 5}  # not FSM-enforceable
        conv = _conv([{"pattern": ".", "reply": "7"}])
        msgs = list(conv.stream(c.ClientMessage(
            content="x",
            response_format={"type": "json_schema", "schema": schema})))
        # grammar refused → post-hoc validated the scripted reply (7 ≥ 5)
        assert msgs[-1].type == "done"


class TestUnterminatedToolCall:
    def test_truncated_stream_surfaces_partial(self):
        import omnia_tpu.runtime.contract as c

        conv = _conv([{"pattern": ".", "reply":
                       '<tool_call>{"name": "echo", "argu'}])
        msgs = list(conv.stream(c.ClientMessage(content="x")))
        assert msgs[-1].type == "error"
        assert msgs[-1].error_code == "truncated_tool_call"
        # The buffered partial call is named, not silently dropped.
        assert '{"name": "echo"' in msgs[-1].error_message

    def test_cancel_inside_tool_call_distinct_finish(self):
        import omnia_tpu.runtime.contract as c

        conv = _conv([{"pattern": ".", "reply":
                       '<tool_call>{"name": "echo", "arguments": {"text": '
                       '"' + "x" * 200 + '"}}</tool_call>',
                       "delay_per_token_s": 0.01}])
        timer = threading.Timer(0.4, conv.cancel_turn)
        timer.start()
        try:
            msgs = list(conv.stream(c.ClientMessage(content="x")))
        finally:
            timer.cancel()
        assert msgs[-1].type == "done"
        assert msgs[-1].finish_reason == "cancelled_in_tool_call"

    def test_parser_partial_property(self):
        from omnia_tpu.runtime.conversation import ToolCallStreamParser

        p = ToolCallStreamParser()
        p.feed('before <tool_call>{"na')
        assert p.in_tool
        assert p.partial == '{"na'
        p2 = ToolCallStreamParser()
        p2.feed("plain text")
        assert p2.partial == ""


class TestBenchGrammarScenario:
    def test_bench_has_grammar_scenario(self):
        import bench

        assert callable(bench._bench_grammar)

    def test_bench_wires_grammar_aux(self):
        with open(os.path.join(REPO, "bench.py")) as f:
            src = f.read()
        assert '"grammar": grammar_bench' in src
        assert 'result["aux"]["grammar"] = grammar_bench' in src


class TestReviewHardening:
    """Contracts pinned by the second review pass."""

    def test_bare_object_schema_admits_arbitrary_members(self):
        """{"type": "object"} means ANY object (additionalProperties
        defaults true) — constraining it to the literal "{}" would make
        a permissively-schema'd tool strictly less usable than one with
        no schema at all."""
        g = compile_json_schema({"type": "object"}, TOK)
        v = g.view(TOK.vocab_size, (0,))
        good = '{"anything": [1, "x"], "more": true}'
        assert walk_text(v, TOK.encode(good, add_bos=False))
        assert walk_text(v, TOK.encode("{}", add_bos=False))
        assert not walk_text(v, TOK.encode("[1]", add_bos=False))

    def test_stop_id_masked_outside_accepting_states(self):
        """A stop id that is also a grammar token must not be sampleable
        mid-grammar (the engine would terminate on it and emit truncated,
        schema-invalid output)."""
        from omnia_tpu.engine.grammar.fsm import GrammarError

        schema = {"type": "object",
                  "properties": {"s": {"type": "string", "maxLength": 4}},
                  "required": ["s"]}
        v = compile_json_schema(schema, TOK).view(TOK.vocab_size, (120,))
        v.check_live()  # strings can route around 'x'
        s = v.start
        for t in TOK.encode('{"s": "a', add_bos=False):
            s = v.advance(s, t)
        assert v.advance(s, 120) < 0  # 'x' masked inside the string
        # When masking starves a state (the '}' byte as a stop id), the
        # request refuses up front instead of silently truncating.
        g2 = compile_json_schema(
            {"type": "object", "properties": {"a": {"type": "integer"}},
             "required": ["a"]}, TOK)
        with pytest.raises(GrammarError):
            g2.view(TOK.vocab_size, (125,)).check_live()

    def test_view_and_table_memos_bounded(self):
        schema = {"type": "object",
                  "properties": {"x": {"type": "integer"}},
                  "required": ["x"]}
        g = compile_json_schema(schema, TOK)
        for i in range(3 * TokenGrammar._MEMO_CAP):
            g.view(TOK.vocab_size, (i,))
        assert len(g._views) <= TokenGrammar._MEMO_CAP
        assert g.nbytes() > 0

    def test_turn_grammar_respects_engine_state_budget(self):
        """A compiled grammar larger than THIS engine's device budget
        falls back to post-hoc validation (the compile cache is shared
        across engines), never a hard submit error."""
        from types import SimpleNamespace

        from omnia_tpu.runtime.context_store import InMemoryContextStore
        from omnia_tpu.runtime.conversation import Conversation
        from omnia_tpu.runtime.packs import load_pack
        from omnia_tpu.tools import ToolExecutor
        import omnia_tpu.runtime.contract as c

        class StubEngine:
            cfg = SimpleNamespace(grammar_max_states=4)

            def supports_grammar(self):
                return True

        conv = Conversation(
            session_id="budget", engine=StubEngine(), tokenizer=TOK,
            pack=load_pack({"name": "v", "version": "1.0.0",
                            "prompts": {"system": "t"},
                            "sampling": {"temperature": 0.0,
                                         "max_tokens": 10}}),
            store=InMemoryContextStore(), tool_executor=ToolExecutor([]))
        schema = {"type": "object",
                  "properties": {"x": {"type": "integer"}},
                  "required": ["x"]}
        msg = c.ClientMessage(
            content="q",
            response_format={"type": "json_schema", "schema": schema})
        assert conv._turn_grammar(msg, None) is None
        StubEngine.cfg = SimpleNamespace(grammar_max_states=4096)
        assert conv._turn_grammar(msg, None) is not None

    def test_rf_with_partially_schemad_tools_attaches_nothing(self):
        """rf-only enforcement with tools declared would mask off every
        tool's `<tool_call>` marker bytes — the no-partial-enforcement
        rule applies turn-wide."""
        from types import SimpleNamespace

        from omnia_tpu.runtime.context_store import InMemoryContextStore
        from omnia_tpu.runtime.conversation import Conversation
        from omnia_tpu.runtime.packs import load_pack
        from omnia_tpu.tools import ToolExecutor
        import omnia_tpu.runtime.contract as c

        class StubEngine:
            cfg = SimpleNamespace(grammar_max_states=4096)

            def supports_grammar(self):
                return True

        conv = Conversation(
            session_id="partial", engine=StubEngine(), tokenizer=TOK,
            pack=load_pack({"name": "v", "version": "1.0.0",
                            "prompts": {"system": "t"},
                            "sampling": {"temperature": 0.0,
                                         "max_tokens": 10},
                            "tools": [{"name": "a",
                                       "input_schema": {"type": "object"}},
                                      {"name": "b"}]}),
            store=InMemoryContextStore(), tool_executor=ToolExecutor([]))
        msg = c.ClientMessage(
            content="q",
            response_format={"type": "json_schema",
                             "schema": {"type": "object",
                                        "properties": {},
                                        "maxProperties": 0}})
        assert conv._turn_grammar(msg, None) is None


    def test_lone_surrogate_escapes_unrepresentable(self):
        """String grammars must refuse surrogate escapes outright: a
        lone \\uD800-\\uDFFF passes json.loads AND jsonschema, but the
        decoded value crashes any downstream .encode('utf-8') — so the
        automaton may not admit them (pairs included; astral chars stay
        expressible as raw UTF-8)."""
        schema = {"type": "object",
                  "properties": {"s": {"type": "string"}},
                  "required": ["s"]}
        v = compile_json_schema(schema, TOK).view(TOK.vocab_size, (0,))

        def admits(text):
            return walk_text(v, TOK.encode(text, add_bos=False))

        assert admits('{"s":"\\u0041"}')          # ordinary escape fine
        assert admits('{"s":"\\uD7FF"}')          # just below the range
        assert admits('{"s":"🚀"}')               # astral as raw UTF-8
        for esc in ("\\uD800", "\\uDBFF", "\\uDC00", "\\uDFFF"):
            assert not admits('{"s":"%s"}' % esc), esc
        # Pairs are refused too (their high half is already inadmissible).
        assert not admits('{"s":"\\uD83D\\uDE00"}')
        # minLength path uses the same character class.
        v2 = compile_json_schema(
            {"type": "object",
             "properties": {"s": {"type": "string", "minLength": 2}},
             "required": ["s"]}, TOK).view(TOK.vocab_size, (0,))
        assert not walk_text(v2, TOK.encode('{"s":"\\uDC00\\uDC00"}',
                                            add_bos=False))

    def test_mock_refuses_starved_grammar_like_engine(self):
        """Submit-time parity: a grammar starved by its stop id refuses
        on the mock exactly as on the real engine — instead of playing
        back a truncated walk that force_complete mislabels 'completed'."""
        from omnia_tpu.engine import FinishReason, MockEngine, SamplingParams
        from omnia_tpu.engine.mock import Scenario

        g = compile_json_schema(
            {"type": "object", "properties": {"a": {"type": "integer"}},
             "required": ["a"]}, TOK)
        eng = MockEngine([Scenario(pattern=".", reply="x")], tokenizer=TOK)
        # '}' (125) as stop id starves the post-'}' states.
        h = eng.submit(TOK.encode("x"),
                       SamplingParams(max_tokens=50, stop_token_ids=(125,)),
                       grammar=g)
        ev = h.get_event(timeout=5)
        assert ev.finish_reason == FinishReason.ERROR
        assert "grammar rejected" in ev.error

    def test_force_complete_reports_starved_state_honestly(self):
        """force_complete must not conflate 'accepting' with 'dead end':
        a walk stuck in a non-accepting state with no completion move
        returns completed=False."""
        from omnia_tpu.engine.grammar.fsm import SamplerView

        # Two states: start --(1)--> trap; trap is non-accepting and has
        # no outgoing admissible token at all.
        table = np.full((2, 3), -1, np.int32)
        table[0, 1] = 1
        view = SamplerView(table, np.array([False, False]), 0)
        toks, done = force_complete(view, lambda s, allowed: 1, 10)
        assert toks == [1]
        assert done is False


    def test_grammar_eos_finishes_without_explicit_stop_id(self):
        """A grammar request whose SamplingParams omit the tokenizer's
        eos from stop_token_ids must still finish STOP at the terminal
        accepting state: placement folds grammar.eos_id into the slot's
        stop set, so the view's only-unmasked-token there actually
        terminates instead of streaming raw EOS until the budget."""
        import dataclasses

        from omnia_tpu.engine import (EngineConfig, FinishReason,
                                      InferenceEngine, SamplingParams)
        from omnia_tpu.models import get_config

        # test-tiny's vocab (256) excludes ByteTokenizer's eos (257);
        # widen it so the accepting-state EOS unmask is in-vocab.
        mcfg = dataclasses.replace(get_config("test-tiny"),
                                   name="test-tiny-eos", vocab_size=260)
        ecfg = EngineConfig(num_slots=2, max_seq=128, prefill_buckets=(64,),
                            dtype="float32", max_sessions=2, grammar=True,
                            grammar_max_states=256)
        eng = InferenceEngine(mcfg, ecfg, seed=1)
        g = compile_regex("(ab|cd)", TOK)
        h = eng.submit(TOK.encode("x"),
                       SamplingParams(temperature=1.0, max_tokens=40), grammar=g)
        toks, fin = _drain(eng, h)
        assert fin.finish_reason == FinishReason.STOP
        assert TOK.decode([t for t in toks if t < 256]) in ("ab", "cd")

    def test_truncated_hex_escapes_refused(self):
        """Pattern-final '\\x4' / '\\u12' must refuse like Python re
        does (incomplete escape), not compile a mask admitting chr(0x4)
        that the post-hoc validator then crashes on."""
        for pat in (r"id-\x4", r"id-\u12", r"[\x4]"):
            with pytest.raises(GrammarUnsupported):
                compile_regex(pat, TOK)

    def test_possessive_quantifiers_refused(self):
        """Possessive quantifiers change the language (a*+a matches
        nothing) — dropping one would admit strings re.fullmatch
        rejects, so they refuse; lazy modifiers (preference-only) still
        compile."""
        for pat in (r"a*+a", r"a++", r"ab?+", r"a{1,3}+b"):
            with pytest.raises(GrammarUnsupported):
                compile_regex(pat, TOK)
        g = compile_regex(r"a+?b", TOK)  # lazy: same language as a+b
        v = g.view(TOK.vocab_size, (0,))
        assert walk_text(v, TOK.encode("aab", add_bos=False))
