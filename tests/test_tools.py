"""Tool executor tests: dispatch, retry classification, circuit breaker,
policy gate — against a real local HTTP server."""

import http.server
import json
import threading

import pytest

from omnia_tpu.tools import CircuitBreaker, ToolExecutor, ToolHandler


@pytest.fixture(scope="module")
def http_backend():
    """Local HTTP tool backend with scriptable failure modes."""
    state = {"fail_next": 0, "calls": 0}

    class Handler(http.server.BaseHTTPRequestHandler):
        def do_POST(self):
            state["calls"] += 1
            body = self.rfile.read(int(self.headers.get("Content-Length", 0)))
            if self.path == "/flaky" and state["fail_next"] > 0:
                state["fail_next"] -= 1
                self.send_response(503)
                self.end_headers()
                return
            if self.path == "/badreq":
                self.send_response(400, "nope")
                self.end_headers()
                return
            self.send_response(200)
            self.send_header("Content-Type", "application/json")
            self.end_headers()
            self.wfile.write(json.dumps({"echo": json.loads(body or b"{}")}).encode())

        def log_message(self, *args):
            pass

    server = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Handler)
    threading.Thread(target=server.serve_forever, daemon=True).start()
    yield server.server_address[1], state
    server.shutdown()


def test_python_tool():
    ex = ToolExecutor([ToolHandler(name="add", fn=lambda a: a["x"] + a["y"])])
    out = ex.execute("add", {"x": 2, "y": 3})
    assert not out.is_error
    assert out.content == "5"


def test_unknown_tool_is_error():
    ex = ToolExecutor()
    out = ex.execute("nope", {})
    assert out.is_error
    assert "unknown tool" in out.content


def test_http_tool_roundtrip(http_backend):
    port, _ = http_backend
    ex = ToolExecutor(
        [ToolHandler(name="web", type="http", url=f"http://127.0.0.1:{port}/ok")]
    )
    out = ex.execute("web", {"q": "hi"})
    assert not out.is_error
    assert json.loads(out.content) == {"echo": {"q": "hi"}}


def test_http_5xx_retried_then_succeeds(http_backend):
    port, state = http_backend
    state["fail_next"] = 2
    ex = ToolExecutor(
        [ToolHandler(name="flaky", type="http", url=f"http://127.0.0.1:{port}/flaky")]
    )
    out = ex.execute("flaky", {})
    assert not out.is_error  # 2 failures < default 2 retries + first attempt


def test_http_4xx_not_retried(http_backend):
    port, state = http_backend
    before = state["calls"]
    ex = ToolExecutor(
        [ToolHandler(name="bad", type="http", url=f"http://127.0.0.1:{port}/badreq")]
    )
    out = ex.execute("bad", {})
    assert out.is_error
    assert "400" in out.content
    assert state["calls"] == before + 1  # exactly one attempt


def test_transport_error_exhausts_retries():
    ex = ToolExecutor(
        [ToolHandler(name="gone", type="http", url="http://127.0.0.1:1/none", timeout_s=0.2)],
        max_retries=1,
    )
    out = ex.execute("gone", {})
    assert out.is_error
    assert "after 2 attempts" in out.content


def test_circuit_breaker_opens_and_half_opens():
    cb = CircuitBreaker(threshold=2, cooldown_s=0.05)
    assert cb.allow()
    cb.record(False)
    cb.record(False)
    assert not cb.allow()
    import time

    time.sleep(0.08)
    assert cb.allow()  # half-open trial
    cb.record(True)
    assert cb.allow()


def test_breaker_blocks_dispatch():
    calls = []

    def boom(a):
        calls.append(1)
        raise RuntimeError("down")

    ex = ToolExecutor([ToolHandler(name="b", fn=boom)], max_retries=0)
    for _ in range(5):
        ex.execute("b", {})
    out = ex.execute("b", {})
    assert out.is_error
    assert "circuit open" in out.content


def test_policy_gate_fail_closed():
    ex = ToolExecutor(
        [ToolHandler(name="t", fn=lambda a: "ok")],
        policy_check=lambda name, args, ctx: False,
    )
    out = ex.execute("t", {})
    assert out.is_error and "denied" in out.content

    def broken_policy(name, args, ctx):
        raise RuntimeError("policy svc down")

    ex2 = ToolExecutor([ToolHandler(name="t", fn=lambda a: "ok")], policy_check=broken_policy)
    out2 = ex2.execute("t", {})
    assert out2.is_error and "deny" in out2.content


def test_client_side_marker():
    ex = ToolExecutor([ToolHandler(name="ui", type="client")])
    assert ex.is_client_side("ui")
    out = ex.execute("ui", {})
    assert out.is_error
