"""Runtime-plane tests: packs, context store, conversation loop, and the
facade-less gRPC integration (both ends in one process over localhost, the
reference's integration-test pattern)."""

import json
import threading
import time

import pytest

from omnia_tpu.engine import MockEngine, SamplingParams
from omnia_tpu.engine.mock import Scenario
from omnia_tpu.engine.tokenizer import ByteTokenizer
from omnia_tpu.runtime import contract as c
from omnia_tpu.runtime.client import RuntimeClient
from omnia_tpu.runtime.context_store import (
    BrokenContextStore,
    ConversationState,
    FileContextStore,
    InMemoryContextStore,
    Turn,
)
from omnia_tpu.runtime.conversation import Conversation, ToolCallStreamParser
from omnia_tpu.runtime.packs import PackValidationError, load_pack, validate_pack
from omnia_tpu.runtime.providers import ProviderRegistry, ProviderSpec
from omnia_tpu.runtime.server import RuntimeServer
from omnia_tpu.tools import ToolExecutor, ToolHandler

PACK = {
    "name": "test-agent",
    "version": "1.0.0",
    "prompts": {"system": "You are {{persona}}.", "greeting": "hello!"},
    "params": {"persona": {"type": "string", "default": "helpful"}},
    "tools": [
        {"name": "echo", "description": "echo back"},
        {"name": "browser", "description": "client side", "client_side": True},
    ],
    "sampling": {"temperature": 0.0, "max_tokens": 128},
    "functions": [
        {
            "name": "classify",
            "input_schema": {"type": "object", "required": ["text"]},
            "output_schema": {"type": "object", "required": ["label"]},
            "prompt": "Classify: {{input}}",
        }
    ],
}


class TestPacks:
    def test_valid_pack_loads(self):
        pack = load_pack(PACK)
        assert pack.name == "test-agent"
        assert pack.render_system() == "You are helpful."
        assert pack.render_system({"persona": "terse"}) == "You are terse."

    def test_missing_system_rejected(self):
        doc = {"name": "x", "version": "1.0.0", "prompts": {}}
        errs = validate_pack(doc)
        assert any("system" in e for e in errs)

    def test_bad_version_rejected(self):
        doc = {"name": "x", "version": "not-semver", "prompts": {"system": "s"}}
        assert validate_pack(doc)

    def test_undeclared_template_param_rejected(self):
        doc = {
            "name": "x",
            "version": "1.0.0",
            "prompts": {"system": "hello {{nope}}"},
        }
        errs = validate_pack(doc)
        assert any("undeclared" in e for e in errs)

    def test_unknown_top_level_key_rejected(self):
        doc = dict(PACK, extra_field=1)
        assert validate_pack(doc)

    def test_required_param_enforced_at_render(self):
        doc = {
            "name": "x",
            "version": "1.0.0",
            "prompts": {"system": "agent {{who}}"},
            "params": {"who": {"type": "string", "required": True}},
        }
        pack = load_pack(doc)
        with pytest.raises(PackValidationError, match="missing required"):
            pack.render_system()


class TestContextStore:
    def test_in_memory_roundtrip(self):
        store = InMemoryContextStore()
        st = ConversationState(session_id="s1", turns=[Turn("user", "hi")])
        store.put(st)
        got = store.get("s1")
        assert got.turns[0].content == "hi"
        assert store.exists("s1")
        store.delete("s1")
        assert not store.exists("s1")

    def test_ttl_eviction(self):
        store = InMemoryContextStore(ttl_s=0.05)
        store.put(ConversationState(session_id="s1"))
        time.sleep(0.1)
        assert store.get("s1") is None

    def test_file_store_roundtrip(self, tmp_path):
        store = FileContextStore(str(tmp_path))
        st = ConversationState(session_id="a/b", turns=[Turn("user", "x")])
        store.put(st)
        assert store.exists("a/b")
        assert store.get("a/b").turns[0].content == "x"
        # second store instance sees it (multi-process topology)
        store2 = FileContextStore(str(tmp_path))
        assert store2.exists("a/b")


class TestToolCallStreamParser:
    def test_plain_text_passthrough(self):
        p = ToolCallStreamParser()
        out = p.feed("hello world")
        assert out == [("text", "hello world")]

    def test_tool_call_split_across_chunks(self):
        p = ToolCallStreamParser()
        events = []
        for chunk in ["before <tool", '_call>{"name":', '"echo"}</tool_call> after']:
            events.extend(p.feed(chunk))
        kinds = [k for k, _ in events]
        assert ("tool", '{"name":"echo"}') in events
        assert "".join(v for k, v in events if k == "text") == "before  after"
        assert kinds.index("tool") > 0

    def test_partial_marker_held_back(self):
        p = ToolCallStreamParser()
        out = p.feed("text <tool")
        assert out == [("text", "text ")]
        assert p.flush() == "<tool"


def _make_conversation(scenarios, store=None, handlers=None, session="s1"):
    tok = ByteTokenizer()
    engine = MockEngine(scenarios, tokenizer=tok)
    executor = ToolExecutor(
        handlers
        or [
            ToolHandler(name="echo", type="python", fn=lambda args: f"echo:{args.get('text', '')}"),
            ToolHandler(name="browser", type="client"),
        ]
    )
    return Conversation(
        session_id=session,
        pack=load_pack(PACK),
        engine=engine,
        tokenizer=tok,
        store=store if store is not None else InMemoryContextStore(),
        provider_spec=ProviderSpec(
            name="mock", type="mock", input_cost_per_mtok=1.0, output_cost_per_mtok=2.0
        ),
        tool_executor=executor,
    )


class TestConversation:
    def test_simple_turn_streams_and_persists(self):
        store = InMemoryContextStore()
        conv = _make_conversation(
            [Scenario(pattern="weather", reply="it is sunny")], store=store
        )
        msgs = list(conv.stream(c.ClientMessage(content="weather?")))
        text = "".join(m.text for m in msgs if m.type == "chunk")
        assert text == "it is sunny"
        done = msgs[-1]
        assert done.type == "done"
        assert done.usage.completion_tokens > 0
        assert done.usage.cost_usd > 0
        state = store.get("s1")
        assert [t.role for t in state.turns] == ["user", "assistant"]

    def test_multi_turn_history_in_prompt(self):
        # Second turn's prompt must contain the first exchange.
        seen_prompts = []

        class SpyEngine(MockEngine):
            def submit(self, prompt_tokens, params=SamplingParams(), session_id=None):
                seen_prompts.append(ByteTokenizer().decode(prompt_tokens))
                return super().submit(prompt_tokens, params)

        tok = ByteTokenizer()
        conv = _make_conversation([Scenario(pattern=".", reply="ok")])
        conv.engine = SpyEngine([Scenario(pattern=".", reply="ok")], tokenizer=tok)
        list(conv.stream(c.ClientMessage(content="first question")))
        list(conv.stream(c.ClientMessage(content="second question")))
        assert "first question" in seen_prompts[1]
        assert "[ASSIST]ok[/ASSIST]" in seen_prompts[1]

    def test_server_side_tool_round(self):
        scenarios = [
            Scenario(pattern=r"\[TOOL\]echo:ping", reply="tool said ping"),
            Scenario(
                pattern="use the tool",
                reply='<tool_call>{"name": "echo", "arguments": {"text": "ping"}}</tool_call>',
            ),
        ]
        conv = _make_conversation(scenarios)
        msgs = list(conv.stream(c.ClientMessage(content="use the tool")))
        text = "".join(m.text for m in msgs if m.type == "chunk")
        assert text == "tool said ping"
        assert msgs[-1].type == "done"

    def test_client_side_tool_suspends_and_resumes(self):
        scenarios = [
            Scenario(pattern=r"\[TOOL\]page content", reply="summarized"),
            Scenario(
                pattern="summarize",
                reply='<tool_call>{"name": "browser", "arguments": {"url": "x"}}</tool_call>',
            ),
        ]
        conv = _make_conversation(scenarios)
        out = []

        def run():
            out.extend(conv.stream(c.ClientMessage(content="summarize this")))

        t = threading.Thread(target=run)
        t.start()
        # wait for the tool_call announcement
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if any(m.type == "tool_call" for m in out):
                break
            time.sleep(0.01)
        tc = next(m for m in out if m.type == "tool_call")
        assert tc.tool_call.client_side
        assert tc.tool_call.name == "browser"
        conv.provide_tool_results(
            [c.ToolResult(tool_call_id=tc.tool_call.tool_call_id, content="page content")]
        )
        t.join(timeout=10)
        text = "".join(m.text for m in out if m.type == "chunk")
        assert text == "summarized"

    def test_input_closed_ends_client_tool_wait(self):
        """Stream teardown (input_closed) must end a client-tool wait
        promptly — the protocol cancel frame can be lost in teardown."""
        scenarios = [
            Scenario(
                pattern="summarize",
                reply='<tool_call>{"name": "browser", "arguments": {}}</tool_call>',
            ),
        ]
        conv = _make_conversation(scenarios)
        closed = threading.Event()
        out = []
        t0 = time.monotonic()

        def run():
            out.extend(
                conv.stream(c.ClientMessage(content="summarize this"), input_closed=closed)
            )

        t = threading.Thread(target=run)
        t.start()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if any(m.type == "tool_call" for m in out):
                break
            time.sleep(0.01)
        closed.set()  # client went away without results
        t.join(timeout=10)
        assert not t.is_alive()
        assert time.monotonic() - t0 < 10  # not the 60s client-tool timeout
        assert out[-1].type == "done" and out[-1].finish_reason == "cancelled"

    def test_results_queued_before_close_still_consumed(self):
        """Send-then-half-close is legal: results queued before input_closed
        fires must be consumed, not discarded as a cancel."""
        scenarios = [
            # tool-result scenario first: list order decides when both match
            Scenario(pattern=r"\[TOOL\]page content", reply="summarized"),
            Scenario(
                pattern="summarize",
                reply='<tool_call>{"name": "browser", "arguments": {}}</tool_call>',
            ),
        ]
        conv = _make_conversation(scenarios)
        closed = threading.Event()
        out = []

        def run():
            out.extend(
                conv.stream(c.ClientMessage(content="summarize this"), input_closed=closed)
            )

        t = threading.Thread(target=run)
        t.start()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if any(m.type == "tool_call" for m in out):
                break
            time.sleep(0.01)
        tc = next(m for m in out if m.type == "tool_call")
        # reader delivers results, THEN the stream half-closes
        conv.provide_tool_results(
            [c.ToolResult(tool_call_id=tc.tool_call.tool_call_id, content="page content")]
        )
        closed.set()
        t.join(timeout=10)
        text = "".join(m.text for m in out if m.type == "chunk")
        assert text == "summarized"
        assert out[-1].type == "done" and out[-1].finish_reason == "stop"

    def test_six_round_tool_chain_completes(self):
        """The loop is time-budgeted (reference conversation.go:36 uses a
        120 s execution budget, not a small round cap): a legitimate
        6-step chain inside the budget must complete."""
        calls = {"n": 0}

        def step(_args):
            calls["n"] += 1
            return f"STEP{calls['n']}"

        scenarios = [
            Scenario(pattern="STEP6", reply="chain finished"),
            Scenario(
                pattern=".",
                reply='<tool_call>{"name": "step", "arguments": {}}</tool_call>',
            ),
        ]
        conv = _make_conversation(scenarios, handlers=[ToolHandler(
            name="step", type="python", fn=step,
        )])
        msgs = list(conv.stream(c.ClientMessage(content="run the chain")))
        assert calls["n"] == 6
        assert msgs[-1].type == "done" and msgs[-1].finish_reason == "stop"
        assert "chain finished" in "".join(
            m.text for m in msgs if m.type == "chunk"
        )

    def test_tool_loop_limit(self):
        scenarios = [
            Scenario(
                pattern=".",
                reply='<tool_call>{"name": "echo", "arguments": {}}</tool_call>',
            )
        ]
        conv = _make_conversation(scenarios)
        msgs = list(conv.stream(c.ClientMessage(content="loop forever")))
        assert msgs[-1].type == "error"
        assert msgs[-1].error_code == "tool_loop_limit"

    def test_store_outage_reported(self):
        conv = _make_conversation(
            [Scenario(pattern=".", reply="x")], store=BrokenContextStore()
        )
        msgs = list(conv.stream(c.ClientMessage(content="hi")))
        assert msgs[-1].type == "error"
        assert msgs[-1].error_code == "store_unavailable"

    def test_malformed_tool_call_is_error(self):
        scenarios = [
            Scenario(pattern=".", reply="<tool_call>not json</tool_call>")
        ]
        conv = _make_conversation(scenarios)
        msgs = list(conv.stream(c.ClientMessage(content="x")))
        assert msgs[-1].type == "error"
        assert msgs[-1].error_code == "tool_error"

    def test_response_format_json_enforced(self):
        conv = _make_conversation([Scenario(pattern=".", reply="not json at all")])
        msgs = list(
            conv.stream(
                c.ClientMessage(content="x", response_format={"type": "json"})
            )
        )
        assert msgs[-1].type == "error"
        assert msgs[-1].error_code == "bad_response_format"


@pytest.fixture(scope="module")
def grpc_pair():
    """Runtime server + client over real localhost gRPC, mock engine."""
    registry = ProviderRegistry()
    registry.register(
        ProviderSpec(
            name="main",
            type="mock",
            options={
                "scenarios": [
                    {"pattern": r"\[TOOL\]echo:hi", "reply": "tool done"},
                    {
                        "pattern": "tooltime",
                        "reply": '<tool_call>{"name": "echo", "arguments": {"text": "hi"}}</tool_call>',
                    },
                    {"pattern": "hello", "reply": "world"},
                    {"pattern": "Classify", "reply": '{"label": "positive"}'},
                    {"pattern": "badout", "reply": "oops not json"},
                ]
            },
        )
    )
    executor = ToolExecutor(
        [ToolHandler(name="echo", type="python", fn=lambda a: f"echo:{a.get('text','')}")]
    )
    pack = dict(PACK)
    pack["functions"] = PACK["functions"] + [
        {
            "name": "badfn",
            "output_schema": {"type": "object"},
            "prompt": "badout {{input}}",
        }
    ]
    server = RuntimeServer(
        pack=load_pack(pack),
        providers=registry,
        provider_name="main",
        tool_executor=executor,
    )
    port = server.serve("localhost:0")
    client = RuntimeClient(f"localhost:{port}")
    yield server, client
    client.close()
    server.shutdown()


class TestGrpcIntegration:
    def test_hello_and_turn(self, grpc_pair):
        _, client = grpc_pair
        stream = client.open_stream("sess-int-1", user_id="u1")
        msgs = list(stream.turn("hello there"))
        assert stream.hello is not None
        assert stream.hello.contract_version == c.CONTRACT_VERSION
        assert c.Capability.STREAMING.value in stream.hello.capabilities
        text = "".join(m.text for m in msgs if m.type == "chunk")
        assert text == "world"
        assert msgs[-1].type == "done"
        stream.close()

    def test_tool_round_over_grpc(self, grpc_pair):
        _, client = grpc_pair
        stream = client.open_stream("sess-int-2")
        msgs = list(stream.turn("tooltime please"))
        text = "".join(m.text for m in msgs if m.type == "chunk")
        assert text == "tool done"
        stream.close()

    def test_health_capabilities(self, grpc_pair):
        _, client = grpc_pair
        h = client.health()
        assert h.status == "ok"
        assert h.model == "llama3-8b"
        assert c.Capability.TOOLS.value in h.capabilities

    def test_has_conversation_tristate(self, grpc_pair):
        server, client = grpc_pair
        assert client.has_conversation("nope") == c.ResumeState.NOT_FOUND
        stream = client.open_stream("sess-int-3")
        list(stream.turn("hello"))
        stream.close()
        assert client.has_conversation("sess-int-3") == c.ResumeState.ACTIVE
        old_store = server.store
        server.store = BrokenContextStore()
        try:
            assert client.has_conversation("sess-int-3") == c.ResumeState.UNAVAILABLE
        finally:
            server.store = old_store

    def test_invoke_function_mode(self, grpc_pair):
        _, client = grpc_pair
        resp = client.invoke("classify", {"text": "great stuff"})
        assert resp.error_code == ""
        assert resp.output == {"label": "positive"}
        assert resp.usage.completion_tokens > 0

    def test_invoke_bad_input_schema(self, grpc_pair):
        _, client = grpc_pair
        resp = client.invoke("classify", {"wrong": 1})
        assert resp.error_code == "bad_input"

    def test_invoke_unknown_function(self, grpc_pair):
        _, client = grpc_pair
        resp = client.invoke("nope", {})
        assert resp.error_code == "not_found"

    def test_invoke_bad_output_is_runtime_fault(self, grpc_pair):
        _, client = grpc_pair
        resp = client.invoke("badfn", {"x": 1})
        assert resp.error_code == "bad_output"

    def test_resume_same_session_has_history(self, grpc_pair):
        _, client = grpc_pair
        s1 = client.open_stream("sess-resume")
        list(s1.turn("hello"))
        s1.close()
        # new stream, same session id: history must persist via context store
        s2 = client.open_stream("sess-resume")
        msgs = list(s2.turn("hello again"))
        assert msgs[-1].type == "done"
        s2.close()


class TestReviewRegressions:
    def test_contract_ignores_unknown_fields(self):
        raw = json.dumps({"type": "message", "content": "x", "trace_id": "new-field"}).encode()
        m = c.ClientMessage.from_bytes(raw)
        assert m.content == "x"
        raw2 = json.dumps({"type": "chunk", "text": "y", "future": 1}).encode()
        assert c.ServerMessage.from_bytes(raw2).text == "y"
        raw3 = json.dumps({"status": "ok", "shiny": True}).encode()
        assert c.HealthResponse.from_bytes(raw3).status == "ok"

    def test_truncated_tool_call_not_leaked(self):
        from omnia_tpu.engine.mock import Scenario

        conv = _make_conversation(
            [Scenario(pattern=".", reply='text then <tool_call>{"name": "ec')]
        )
        msgs = list(conv.stream(c.ClientMessage(content="x")))
        text = "".join(m.text for m in msgs if m.type == "chunk")
        assert "{" not in text and "tool_call" not in text
        assert msgs[-1].type == "error"
        assert msgs[-1].error_code == "truncated_tool_call"

    def test_stale_client_results_discarded(self):
        from omnia_tpu.engine.mock import Scenario

        scenarios = [
            Scenario(pattern=r"\[TOOL\]fresh data", reply="used fresh"),
            Scenario(
                pattern="go",
                reply='<tool_call>{"name": "browser", "arguments": {}}</tool_call>',
            ),
        ]
        conv = _make_conversation(scenarios)
        # stale result sitting in the queue from a previous timed-out turn
        conv.provide_tool_results(
            [c.ToolResult(tool_call_id="old-call", content="stale data")]
        )
        out = []

        def run():
            out.extend(conv.stream(c.ClientMessage(content="go")))

        t = threading.Thread(target=run)
        t.start()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and not any(m.type == "tool_call" for m in out):
            time.sleep(0.01)
        tc = next(m for m in out if m.type == "tool_call")
        # a mismatched batch arriving mid-wait must also be discarded
        conv.provide_tool_results(
            [c.ToolResult(tool_call_id="also-wrong", content="stale data")]
        )
        conv.provide_tool_results(
            [c.ToolResult(tool_call_id=tc.tool_call.tool_call_id, content="fresh data")]
        )
        t.join(timeout=10)
        text = "".join(m.text for m in out if m.type == "chunk")
        assert text == "used fresh"

    def test_cancel_interrupts_turn_over_grpc(self, grpc_pair):
        _, client = grpc_pair
        # slow scenario: reuse 'hello' but with a huge reply via new session;
        # simplest: cancel immediately after sending — the turn should finish
        # with finish_reason=cancelled or complete normally (race), never hang.
        stream = client.open_stream("sess-cancel")
        stream.send_text("hello")
        stream.send(c.ClientMessage(type="cancel"))
        final = None
        for m in stream:
            if m.type in ("done", "error"):
                final = m
                break
        assert final is not None
        stream.close()

    def test_runtime_server_with_real_tpu_engine_serves(self):
        """The flagship path: a type=tpu provider (tiny model) must actually
        serve a Converse turn — engine warmup + loop thread started by serve()."""
        registry = ProviderRegistry()
        registry.register(
            ProviderSpec(
                name="tpu-main",
                type="tpu",
                model="test-tiny",
                options={
                    "num_slots": 2,
                    "max_seq": 128,
                    "prefill_buckets": [64],
                    "dtype": "float32",
                },
            )
        )
        server = RuntimeServer(
            pack=load_pack(
                {
                    "name": "tpu-agent",
                    "version": "1.0.0",
                    "prompts": {"system": "sys"},
                    "sampling": {"temperature": 0.0, "max_tokens": 8},
                }
            ),
            providers=registry,
            provider_name="tpu-main",
        )
        port = server.serve("localhost:0")
        try:
            client = RuntimeClient(f"localhost:{port}")
            h = client.health()
            assert h.status == "ok"  # ready implies warmed + started
            stream = client.open_stream("tpu-sess")
            msgs = list(stream.turn("hi"))
            assert msgs[-1].type == "done"
            n_chunk_msgs = sum(1 for m in msgs if m.type == "chunk")
            assert n_chunk_msgs > 0
            stream.close()
            client.close()
        finally:
            server.shutdown()


# ---------------------------------------------------------------------------
# Memory capability in the turn loop
# ---------------------------------------------------------------------------


class TestMemoryCapability:
    def _memory(self, ambient_limit=4):
        from omnia_tpu.memory import HashingEmbedder, InProcessMemory, MemoryAPI
        from omnia_tpu.runtime.memory_capability import MemoryCapability

        mem = InProcessMemory(MemoryAPI(embedder=HashingEmbedder(dim=64)))
        return mem, MemoryCapability(mem, workspace_id="ws", agent_id="agent1")

    def _conv_with_memory(self, scenarios, capability, user_id="u1"):
        conv = _make_conversation(scenarios)
        conv.memory = capability
        conv.user_id = user_id
        return conv

    def test_ambient_memory_injected_into_prompt(self):
        mem, cap = self._memory()
        mem.remember("ws", "the user is allergic to peanuts",
                     virtual_user_id="u1", agent_id="agent1")
        mem.api.reembed.drain()
        seen_prompts = []

        class SpyEngine(MockEngine):
            def submit(self, prompt_tokens, params=SamplingParams(), session_id=None):
                seen_prompts.append(ByteTokenizer().decode(prompt_tokens))
                return super().submit(prompt_tokens, params)

        conv = self._conv_with_memory([Scenario(pattern=".", reply="ok")], cap)
        conv.engine = SpyEngine([Scenario(pattern=".", reply="ok")], tokenizer=ByteTokenizer())
        list(conv.stream(c.ClientMessage(content="what snacks are safe? peanuts allergic?")))
        assert "[MEMORY]" in seen_prompts[0]
        assert "allergic to peanuts" in seen_prompts[0]
        # memory tools advertised in the system block
        assert "memory__remember" in seen_prompts[0]

    def test_memory_remember_tool_scoped_to_identity(self):
        mem, cap = self._memory()
        scenarios = [
            Scenario(pattern=r"\[TOOL\]remembered", reply="noted!"),
            Scenario(
                pattern=r"likes tabs",
                reply='<tool_call>{"name": "memory__remember", "arguments": {"content": "user likes tabs", "category": "preference"}}</tool_call>',
            ),
        ]
        conv = self._conv_with_memory(scenarios, cap, user_id="u7")
        msgs = list(conv.stream(c.ClientMessage(content="I want you to know I likes tabs")))
        assert msgs[-1].type == "done"
        mem.api.reembed.drain()
        saved = mem.api.store.scan("ws")
        assert len(saved) == 1
        # scope comes from authenticated identity, not the model
        assert saved[0].virtual_user_id == "u7"
        assert saved[0].agent_id == "agent1"
        assert saved[0].tier == "user_for_agent"

    def test_memory_recall_tool_round(self):
        mem, cap = self._memory()
        mem.remember("ws", "deploy window is friday", virtual_user_id="u1",
                     agent_id="agent1", category="ops")
        mem.api.reembed.drain()
        scenarios = [
            Scenario(pattern=r"\[TOOL\].*deploy window is friday", reply="it is friday"),
            Scenario(
                pattern=r"when can we deploy",
                reply='<tool_call>{"name": "memory__recall", "arguments": {"query": "deploy window"}}</tool_call>',
            ),
        ]
        conv = self._conv_with_memory(scenarios, cap)
        msgs = list(conv.stream(c.ClientMessage(content="when can we deploy?")))
        text = "".join(m.text for m in msgs if m.type == "chunk")
        assert "it is friday" in text

    def test_memory_failure_degrades_not_dies(self):
        from omnia_tpu.runtime.memory_capability import MemoryCapability

        class BrokenClient:
            def recall(self, *a, **k):
                raise RuntimeError("memory-api down")

            def remember(self, *a, **k):
                raise RuntimeError("memory-api down")

        cap = MemoryCapability(BrokenClient(), workspace_id="ws")
        conv = self._conv_with_memory([Scenario(pattern=".", reply="fine")], cap)
        msgs = list(conv.stream(c.ClientMessage(content="hello")))
        assert msgs[-1].type == "done"  # ambient failure → turn continues
        # explicit tool failure is reported as a tool error, not a crash
        content, is_error = cap.execute("memory__remember", {"content": "x"}, "u1")
        assert is_error and "failed" in content

    def test_server_advertises_memory_capability(self):
        from omnia_tpu.memory import HashingEmbedder, InProcessMemory, MemoryAPI
        from omnia_tpu.runtime.memory_capability import MemoryCapability

        mem = InProcessMemory(MemoryAPI(embedder=HashingEmbedder(dim=32)))
        cap = MemoryCapability(mem, workspace_id="ws")
        registry = ProviderRegistry()
        registry.register(
            ProviderSpec(name="mock", type="mock",
                         options={"scenarios": [{"pattern": ".", "reply": "ok"}]})
        )
        server = RuntimeServer(
            pack=load_pack(PACK), providers=registry, provider_name="mock", memory=cap
        )
        assert c.Capability.MEMORY.value in server.capabilities
        plain = RuntimeServer(pack=load_pack(PACK), providers=registry, provider_name="mock")
        assert c.Capability.MEMORY.value not in plain.capabilities

    def test_session_identity_pinned_across_streams(self):
        from omnia_tpu.memory import HashingEmbedder, InProcessMemory, MemoryAPI
        from omnia_tpu.runtime.memory_capability import MemoryCapability

        mem = InProcessMemory(MemoryAPI(embedder=HashingEmbedder(dim=32)))
        registry = ProviderRegistry()
        registry.register(
            ProviderSpec(name="mock", type="mock",
                         options={"scenarios": [{"pattern": ".", "reply": "ok"}]})
        )
        server = RuntimeServer(
            pack=load_pack(PACK), providers=registry, provider_name="mock",
            memory=MemoryCapability(mem, workspace_id="ws"),
        )
        port = server.serve("localhost:0")
        try:
            client = RuntimeClient(f"localhost:{port}")
            s1 = client.open_stream("pinned-sess", user_id="alice")
            assert list(s1.turn("hi"))[-1].type == "done"
            s1.close()
            # same session, different identity → rejected, not inherited
            s2 = client.open_stream("pinned-sess", user_id="mallory")
            msgs = list(s2.turn("hi"))
            assert msgs[-1].type == "error"
            assert msgs[-1].error_code == "session_identity_mismatch"
            s2.close()
            # missing identity is a mismatch too
            s3 = client.open_stream("pinned-sess")
            msgs = list(s3.turn("hi"))
            assert msgs[-1].error_code == "session_identity_mismatch"
            s3.close()
            client.close()
        finally:
            server.shutdown()

    def test_anonymous_remember_refused_not_escalated(self):
        mem, cap = self._memory()
        content, is_error = cap.execute(
            "memory__remember", {"content": "private fact"}, user_id=""
        )
        assert is_error and "identity" in content
        assert mem.api.store.scan("ws") == []  # nothing written at any tier

    def test_shared_capabilities_list_not_mutated(self):
        from omnia_tpu.memory import HashingEmbedder, InProcessMemory, MemoryAPI
        from omnia_tpu.runtime.memory_capability import MemoryCapability
        from omnia_tpu.runtime.server import DEFAULT_CAPABILITIES

        shared = ["text", "streaming"]
        mem = InProcessMemory(MemoryAPI(embedder=HashingEmbedder(dim=32)))
        registry = ProviderRegistry()
        registry.register(
            ProviderSpec(name="mock", type="mock",
                         options={"scenarios": [{"pattern": ".", "reply": "ok"}]})
        )
        RuntimeServer(pack=load_pack(PACK), providers=registry, provider_name="mock",
                      memory=MemoryCapability(mem, workspace_id="ws"),
                      capabilities=shared)
        assert shared == ["text", "streaming"]
        assert "memory" not in DEFAULT_CAPABILITIES
