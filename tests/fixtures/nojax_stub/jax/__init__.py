"""Poisoned jax stub (tests/test_analysis.py): the analysis CLI must run
in containers with no accelerator stack, so importing jax from anywhere
under ``python -m omnia_tpu.analysis`` is a hard failure."""

raise ImportError(
    "omnia_tpu.analysis must not import jax (poisoned stub — see "
    "tests/test_analysis.py::test_cli_module_runs_clean_without_jax)"
)
