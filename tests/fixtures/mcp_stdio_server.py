"""Minimal MCP server over stdio for transport tests.

Speaks newline-delimited JSON-RPC 2.0: initialize handshake, tools/list,
tools/call. Tools: echo (returns args as text), fail (isError result),
crash (exits the process mid-call to exercise transport-error retry).
"""

import json
import sys

TOOLS = [
    {
        "name": "echo",
        "description": "echo arguments back",
        "inputSchema": {"type": "object", "properties": {"text": {"type": "string"}}},
    },
    {"name": "fail", "description": "always errors", "inputSchema": {"type": "object"}},
    {"name": "crash", "description": "kills the server", "inputSchema": {"type": "object"}},
    {"name": "hidden", "description": "filtered out by tests", "inputSchema": {"type": "object"}},
]


def reply(rid, result):
    sys.stdout.write(json.dumps({"jsonrpc": "2.0", "id": rid, "result": result}) + "\n")
    sys.stdout.flush()


def main():
    for line in sys.stdin:
        line = line.strip()
        if not line:
            continue
        msg = json.loads(line)
        method, rid = msg.get("method"), msg.get("id")
        if method == "initialize":
            reply(rid, {
                "protocolVersion": msg["params"].get("protocolVersion"),
                "capabilities": {"tools": {}},
                "serverInfo": {"name": "fixture-mcp", "version": "1.0"},
            })
        elif method == "notifications/initialized":
            continue
        elif method == "tools/list":
            reply(rid, {"tools": TOOLS})
        elif method == "tools/call":
            name = msg["params"]["name"]
            args = msg["params"].get("arguments", {})
            if name == "crash":
                sys.exit(1)
            if name == "fail":
                reply(rid, {
                    "content": [{"type": "text", "text": "deliberate failure"}],
                    "isError": True,
                })
            elif name in ("echo", "hidden"):
                reply(rid, {
                    "content": [{"type": "text", "text": json.dumps(args)}],
                    "isError": False,
                })
            else:
                sys.stdout.write(json.dumps({
                    "jsonrpc": "2.0", "id": rid,
                    "error": {"code": -32602, "message": f"unknown tool {name}"},
                }) + "\n")
                sys.stdout.flush()
        elif rid is not None:
            sys.stdout.write(json.dumps({
                "jsonrpc": "2.0", "id": rid,
                "error": {"code": -32601, "message": f"unknown method {method}"},
            }) + "\n")
            sys.stdout.flush()


if __name__ == "__main__":
    main()
